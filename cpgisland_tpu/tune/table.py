"""graftune — the versioned knob-winner table (``TUNING.json``).

The autotuner's persistence half, the COSTS.json/MEMORY.json workflow
verbatim: a committed lockfile with per-platform sections, re-baselined
by ``tools/graftune.py --update-tune`` after a verified sweep, stale
entries reported like stale waivers (``python -m cpgisland_tpu.analysis
--tune``).

**What a winner is.**  One swept knob decision — a lane length, a time
tile, a flat-decode block size, a per-path ``fused``/``stacked`` boolean,
an engine choice — keyed by (task, platform, pow2 geometry bucket, S,
stacked M) and stamped with the **kernel-structure fingerprint** of the
COSTS.json entries the sweep timed through.  That stamp is the whole
point: the "re-sweep tile knobs after kernel-structure changes;
swept-once conclusions rot" lesson has bitten three times (r3->r4 lanes,
the r9 fused kernel, the seq2d caps), so a kernel reshape that drifts
COSTS.json automatically flips every dependent winner to STALE — the
routers fall back to the hard-coded defaults bit-for-bit and the next
``graftune --all`` re-earns the knobs, instead of a human remembering to.

**Applied vs recorded.**  Every winner row carries ``applied``: routers
honor only applied rows.  A sweep on the capturing TPU applies its
winners; a CPU sweep records rates as *projections* (``projection:
true``) and applies only values equal to the legacy default — a serial
machine's timings must never flip a chip knob (the BASELINE.md decision
rule, now enforced in code instead of prose).

No jax at module level (routers consult this at runtime from ops/);
platform detection imports jax lazily.
"""

from __future__ import annotations

import hashlib
import json
import os
from dataclasses import dataclass, field
from typing import Optional

TUNING_VERSION = 1
LOCKFILE_NAME = "TUNING.json"
# Test/process-isolation hook: point the whole consultation machinery at a
# different table (or at a nonexistent path for the legacy-defaults arm).
ENV_PATH = "CPGISLAND_TUNING_FILE"

# Relative throughput advantage a measured winner needs before a flip is
# applied over the legacy default — ties and noise keep the shipped knob
# (re-measure before trusting a regression; CLAUDE.md relay notes).
FLIP_MARGIN = 0.03


def _repo_root() -> str:
    pkg = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    return os.path.dirname(pkg)


def default_table_path() -> str:
    env = os.environ.get(ENV_PATH)
    if env:
        return env
    return os.path.join(_repo_root(), LOCKFILE_NAME)


def default_costs_path() -> str:
    from cpgisland_tpu.analysis import cost_contracts

    return cost_contracts.default_lockfile_path()


# -- the in-process cache + generation counter --------------------------------
#
# The table is consulted on hot routing paths (pick_lane_T runs per placed
# shard), so loads are cached by (path, mtime).  The GENERATION bumps on
# every cache refresh — including an in-process --update-tune write — and
# pick_lane_T's lru-cached feasibility filter keys on it, so a sweep that
# lands mid-session invalidates every cached pre-sweep lane choice instead
# of serving them for the rest of the process.

_override_path: Optional[str] = None
_cache: dict = {"path": None, "mtime": None, "data": None, "gen": 0}


def set_table_path(path: Optional[str]) -> None:
    """Process-local override of the table location (tests; None resets)."""
    global _override_path
    _override_path = path


def _table_path(path: Optional[str] = None) -> str:
    if path is not None:
        return path
    if _override_path is not None:
        return _override_path
    return default_table_path()


def _mtime(path: str) -> int:
    try:
        return os.stat(path).st_mtime_ns
    except OSError:
        return -1


def load_table(path: Optional[str] = None) -> Optional[dict]:
    """The cached table dict, or None when the file does not exist."""
    p = _table_path(path)
    m = _mtime(p)
    if _cache["path"] != p or _cache["mtime"] != m:
        data = None
        if m >= 0:
            try:
                with open(p, "r", encoding="utf-8") as fh:
                    data = json.load(fh)
            except (OSError, ValueError):
                data = None
        _cache.update(path=p, mtime=m, data=data, gen=_cache["gen"] + 1)
    return _cache["data"]


def generation() -> int:
    """Monotone counter that moves whenever the consulted table changes
    (path switch, on-disk edit, in-process write) — the cache key the
    routing-side lru caches fold in."""
    load_table()
    return _cache["gen"]


# -- the kernel-structure fingerprint -----------------------------------------


_fp_cache: dict = {}


def costs_fingerprint(
    entry_names, costs_path: Optional[str] = None
) -> str:
    """Stable digest of the named COSTS.json entries — the staleness key.

    The cpu section is the canonical structure (the CPU XLA twins are
    arithmetic-identical to the chip kernels and always captured); a
    missing entry digests as ``missing`` so removing or renaming a cost
    entry stales its dependents exactly like reshaping it would."""
    cp = costs_path or default_costs_path()
    names = tuple(entry_names)
    key = (cp, _mtime(cp), names)
    hit = _fp_cache.get(key)
    if hit is not None:
        return hit
    try:
        with open(cp, "r", encoding="utf-8") as fh:
            lock = json.load(fh)
    except (OSError, ValueError):
        lock = {}
    platforms = lock.get("platforms", {})
    section = platforms.get("cpu")
    if section is None and platforms:
        section = platforms[sorted(platforms)[0]]
    entries = (section or {}).get("entries", {})
    h = hashlib.sha256()
    for name in names:
        e = entries.get(name)
        canon = "missing" if e is None else json.dumps(e, sort_keys=True)
        h.update(name.encode())
        h.update(b"\0")
        h.update(canon.encode())
        h.update(b"\1")
    fp = "sha256:" + h.hexdigest()[:16]
    if len(_fp_cache) > 256:
        _fp_cache.clear()
    _fp_cache[key] = fp
    return fp


# -- keys and entries ---------------------------------------------------------


def pow2_bucket(n: int) -> int:
    """The geometry bucket of an ``n``-symbol input — the same pow2 class
    the ``lane_geometry`` obs event dedupes on."""
    return 1 << max(int(n) - 1, 0).bit_length()


def entry_key(
    task: str,
    n_pow2: Optional[int] = None,
    S: Optional[int] = None,
    M: int = 1,
) -> str:
    """Canonical winner key.  ``None`` fields are wildcards: a boolean
    fused/stacked verdict applies across geometries, a lane winner binds
    to its swept pow2 bucket."""
    return (
        f"{task}|n={n_pow2 if n_pow2 else '*'}"
        f"|S={S if S else '*'}|M={M}"
    )


def make_entry(
    task: str,
    value,
    *,
    legacy,
    costs_entries,
    applied: bool,
    projection: bool,
    rate_msym_s: Optional[float] = None,
    baseline_msym_s: Optional[float] = None,
    ratio: Optional[float] = None,
    parity: Optional[dict] = None,
    verdict: Optional[dict] = None,
    swept: Optional[list] = None,
    pruned: Optional[list] = None,
    costs_path: Optional[str] = None,
) -> dict:
    """One winner row, fingerprint-stamped against the CURRENT COSTS.json."""
    return {
        "task": task,
        "value": value,
        "legacy": legacy,
        "applied": bool(applied),
        "projection": bool(projection),
        "rate_msym_s": rate_msym_s,
        "baseline_msym_s": baseline_msym_s,
        "ratio": ratio,
        "parity": parity,
        "verdict": verdict,
        "swept": swept or [],
        "pruned": pruned or [],
        "costs_entries": sorted(costs_entries),
        "costs_fingerprint": costs_fingerprint(
            sorted(costs_entries), costs_path
        ),
    }


def write_entries(
    entries: dict,
    platform: Optional[str] = None,
    path: Optional[str] = None,
) -> str:
    """Merge winner rows into the platform section (atomic, the lockfile
    write shape of cost_contracts/mem_contracts) and bump the generation."""
    if platform is None:
        import jax

        platform = jax.default_backend()
    p = _table_path(path)
    data = load_table(p) or {
        "version": TUNING_VERSION,
        "flip_margin": FLIP_MARGIN,
        "platforms": {},
    }
    section = data["platforms"].setdefault(platform, {"entries": {}})
    try:
        import jax

        section["jax"] = jax.__version__
    except Exception:  # pragma: no cover - jax is always importable here
        pass
    section.setdefault("entries", {}).update(entries)
    tmp = p + ".tmp"
    with open(tmp, "w", encoding="utf-8") as fh:
        json.dump(data, fh, indent=1, sort_keys=True)
        fh.write("\n")
    os.replace(tmp, p)
    load_table(p)  # refresh the cache (and bump the generation) now
    return p


# -- lookup -------------------------------------------------------------------


@dataclass
class TuneDecision:
    """One consultation's verdict: ``fresh`` (applied winner, fingerprint
    current), ``stale`` (winner exists but its kernel structure drifted,
    it is unapplied, or its value is out of domain), or ``absent``."""

    status: str                # "fresh" | "stale" | "absent"
    value: object = None
    key: str = ""
    reason: str = ""
    entry: Optional[dict] = field(default=None, repr=False)

    @property
    def fresh(self) -> bool:
        return self.status == "fresh"


def _platform(platform: Optional[str]) -> str:
    if platform is not None:
        return platform
    import jax

    return jax.default_backend()


def _check_entry(
    entry: dict, key: str, costs_path: Optional[str]
) -> TuneDecision:
    fp_now = costs_fingerprint(
        entry.get("costs_entries", []), costs_path
    )
    if entry.get("costs_fingerprint") != fp_now:
        return TuneDecision(
            status="stale", key=key, entry=entry,
            reason=(
                f"kernel-structure fingerprint drifted "
                f"({entry.get('costs_fingerprint')} -> {fp_now}; "
                f"dependent cost entries: "
                f"{entry.get('costs_entries', [])}) — re-sweep with "
                "tools/graftune.py"
            ),
        )
    if not entry.get("applied", False):
        return TuneDecision(
            status="stale", key=key, entry=entry,
            reason="recorded but not applied (projection sweep — the "
            "winner waits for a capture-platform run)",
        )
    return TuneDecision(
        status="fresh", key=key, entry=entry, value=entry.get("value"),
    )


def lookup(
    task: str,
    *,
    platform: Optional[str] = None,
    n: Optional[int] = None,
    S: Optional[int] = None,
    M: int = 1,
    path: Optional[str] = None,
    costs_path: Optional[str] = None,
) -> TuneDecision:
    """Find the winner for a routing site.  Tries the exact pow2 bucket of
    ``n`` first, then the wildcard-geometry key; absent/stale results
    carry the reason the caller's obs event reports."""
    data = load_table(path)
    if data is None:
        return TuneDecision(status="absent", reason="no tuning table")
    section = data.get("platforms", {}).get(_platform(platform))
    if section is None:
        return TuneDecision(
            status="absent", reason="no section for this platform"
        )
    entries = section.get("entries", {})
    keys = []
    if n is not None:
        keys.append(entry_key(task, pow2_bucket(n), S, M))
    keys.append(entry_key(task, None, S, M))
    stale: Optional[TuneDecision] = None
    for key in keys:
        e = entries.get(key)
        if e is None:
            continue
        d = _check_entry(e, key, costs_path)
        if d.fresh:
            return d
        stale = stale or d
    if stale is not None:
        return stale
    return TuneDecision(status="absent", reason="no matching winner")


# -- reporting (analysis --tune / bench extras) -------------------------------


def table_report(
    platform: Optional[str] = None,
    path: Optional[str] = None,
    costs_path: Optional[str] = None,
) -> dict:
    """Fresh/stale census of one platform section — the ``--tune`` diff
    and bench --extended's ``tuning_table_fresh`` extra.  Stale rows are
    named with their drift reason, the stale-waiver UX."""
    data = load_table(path)
    plat = _platform(platform)
    out: dict = {
        "platform": plat, "fresh": 0, "stale": 0, "entries": 0,
        "stale_entries": [], "path": _table_path(path),
    }
    if data is None:
        out["note"] = (
            f"no {LOCKFILE_NAME} — routers run the hard-coded defaults; "
            "baseline with tools/graftune.py --update-tune"
        )
        return out
    section = data.get("platforms", {}).get(plat)
    if section is None:
        out["note"] = (
            f"no '{plat}' section (captured: "
            f"{sorted(data.get('platforms', {}))}) — routers run the "
            "hard-coded defaults on this platform"
        )
        return out
    for key in sorted(section.get("entries", {})):
        e = section["entries"][key]
        out["entries"] += 1
        d = _check_entry(e, key, costs_path)
        if d.fresh:
            out["fresh"] += 1
        else:
            out["stale"] += 1
            out["stale_entries"].append({"key": key, "reason": d.reason})
    return out
