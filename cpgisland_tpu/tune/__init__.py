"""graftune — the fingerprint-keyed knob autotuner (ROADMAP item 1).

Three layers:

- :mod:`~cpgisland_tpu.tune.table` — the versioned winner table
  (``TUNING.json``): per-platform sections, winners keyed by (task,
  platform, pow2 geometry bucket, S, stacked M) and stamped with the
  COSTS.json kernel-structure fingerprint of the entries they were swept
  through.  A kernel reshape drifts the fingerprint and every dependent
  winner goes STALE automatically.
- :mod:`~cpgisland_tpu.tune.sweep` + :mod:`~cpgisland_tpu.tune.tasks` —
  the sweep driver (``tools/graftune.py``): enumerate knob tuples per
  kernel family, prune through ``memmodel.feasible`` BEFORE any compile
  (ledger-asserted), parity-gate every survivor against the current
  default arm, time with the full bench discipline, persist winners.
- this module — **router consultation**.  Every helper here takes the
  routing site's LEGACY default and returns it bit-for-bit unless a
  fresh, applied, in-domain winner matches; explicit caller kwargs never
  reach these helpers at all (explicit always wins).  Fresh hits emit
  ``tune_pick``; matching-but-stale entries emit ``tune_stale`` with the
  drift reason; absent stays silent (the hot-path default).

Consulting sites: ``fb_pallas.pick_lane_T`` (lane_T, + the
generation-keyed feasibility-filter cache), the per-path ``fused``
defaults (train backends, parallel posterior), the per-path ``one_pass``
defaults (posterior_sharded, Seq/Seq2D backends — the matrix-carried
true-one-pass arm, shipped False), the per-path ``stacked`` defaults
(family.compare, serve broker, FamilyEStep), SeqBackend's ``t_tile``,
``decode_batch_flat``'s block_size, and ``resolve_fb_engine``'s auto
branch.
"""

from __future__ import annotations

import functools
from typing import Optional

from cpgisland_tpu.tune import table
from cpgisland_tpu.tune.table import (  # noqa: F401  (re-exported API)
    TuneDecision,
    costs_fingerprint,
    default_table_path,
    entry_key,
    generation,
    load_table,
    lookup,
    pow2_bucket,
    set_table_path,
    table_report,
    write_entries,
)


def _emit(decision: table.TuneDecision, task: str, **fields) -> None:
    from cpgisland_tpu import obs

    if decision.fresh:
        obs.event(
            "tune_pick", _dedupe=True, task=task, key=decision.key,
            value=decision.value, **fields,
        )
    elif decision.status == "stale":
        obs.event(
            "tune_stale", _dedupe=True, task=task, key=decision.key,
            reason=decision.reason, **fields,
        )


@functools.lru_cache(maxsize=256)
def _sweepable_cached(task: str, value, _table_gen: int) -> bool:
    try:
        from cpgisland_tpu.tune import sweep

        sweep.validate_entry(task, value)
        return True
    except Exception:
        return False


def _sweepable(task: str, value) -> bool:
    """Is ``value`` something the sweep could have legitimately written
    for ``task``?  Membership in the task's candidate domain + the
    graftmem feasibility oracle — the same gate ``--apply`` runs
    (sweep.validate_entry), reused router-side so a hand-corrupted table
    row can never route.  lru-cached per table generation: consultation
    sits on per-record routing paths (decode_batch_flat's default), and
    rebuilding the task registry + footprint model per call is the exact
    per-call cost the pick_lane_T cache exists to avoid."""
    try:
        hash(value)
    except TypeError:
        return False
    return _sweepable_cached(task, value, table.generation())


def _consult(
    task: str, legacy, *, domain=None, validator=None, n=None, S=None, M=1
):
    """The one fallback rule: fresh + in-domain -> winner, else legacy."""
    d = table.lookup(task, n=n, S=S, M=M)
    if d.fresh and (
        (domain is not None and d.value not in domain)
        or (validator is not None and not validator(d.value))
    ):
        # A winner outside the router's legal domain (a planted lane_T=8,
        # a corrupt block size, an engine the model is not eligible for)
        # must never route — the sweep's parity gate rejects these at
        # apply time, and the router refuses them defensively too.
        d = table.TuneDecision(
            status="stale", key=d.key, entry=d.entry,
            reason=f"winner {d.value!r} outside the router domain",
        )
    _emit(d, task)
    if d.fresh:
        return d.value
    return legacy


def tuned_lane_T(
    n: int, onehot: bool, long_lanes: bool, candidates
) -> Optional[int]:
    """Winner lane length for this input's pow2 bucket, or None for the
    legacy rate-table minimization.  ``candidates`` is the feasible rate
    table — a winner outside it (absurd, or newly infeasible after a
    memmodel recalibration) is refused."""
    task = "lane." + ("onehot" if onehot else "dense") + (
        ".long" if long_lanes else ""
    )
    got = _consult(task, None, domain=set(candidates), n=n)
    return got


def default_fused(path: str, legacy: bool = True) -> bool:
    """Per-path r9 pass-fusion default: ``posterior`` | ``em_seq`` |
    ``em_chunked`` | ``em_family``."""
    return bool(_consult(f"fused.{path}", legacy, domain=(True, False)))


def default_one_pass(path: str, legacy: bool = False) -> bool:
    """Per-path true-one-pass default (matrix-carried reduced FB, the
    products pass folded into the co-scheduled launch): ``posterior`` |
    ``em_seq``.  Shipped legacy is False — the one-pass trade (4 carry
    rows, wider VMEM) is only decidable on silicon; the chip sweep flips
    the winner past the 3% margin like every other task."""
    return bool(_consult(f"one_pass.{path}", legacy, domain=(True, False)))


def default_stacked(site: str, legacy: bool = True) -> bool:
    """Per-site multi-model stacking default: ``compare`` |
    ``serve_decode`` | ``em_family`` | ``posterior``."""
    return bool(_consult(f"stacked.{site}", legacy, domain=(True, False)))


def default_block_size(
    scores: bool = False, stacked_m: int = 1, legacy: int = 4096
) -> int:
    """Flat-decode step-block default (decode_batch_flat's bk).

    The sweep writes ONE winner per variant at M=1 (the single-model flat
    stream is the swept geometry), so stacked launches adopt that same
    winner — ``viterbi_onehot._stacked_block_for`` then clamps it to the
    M-member VMEM cap on TPU exactly as it clamps the hard-coded default
    (``stacked_m`` stays a parameter for the obs trail and future
    M-keyed sweeps)."""
    del stacked_m
    task = "flat.block" + (".scores" if scores else "")
    return int(_consult(
        task, legacy, validator=lambda v: _sweepable(task, v),
    ))


def default_t_tile(path: str, legacy: int) -> int:
    """Per-path lane-kernel time tile (the fb grid's t_tile knob)."""
    task = f"t_tile.{path}"
    return int(_consult(
        task, legacy, validator=lambda v: _sweepable(task, v),
    ))


def default_engine(path: str, legacy: str, eligible) -> str:
    """Tuned engine choice for an ``auto`` resolution, constrained to the
    currently-eligible ladder (a winner the model cannot run is refused)."""
    return str(_consult(f"engine.{path}", legacy, domain=set(eligible)))
