"""graftune sweep driver — prune, parity-gate, time, persist.

The pipeline per task (:mod:`~cpgisland_tpu.tune.tasks`):

1. **Prune.**  Every candidate knob tuple runs through the graftmem
   static VMEM model (``memmodel.feasible`` — the PR-13 oracle) before
   anything compiles; rejected tuples are recorded in the
   :class:`SweepLedger` with the model's reason and MUST never reach a
   compile (``ledger.check_compile`` raises — the acceptance assertion,
   not a convention).
2. **Parity gate.**  Every survivor's output is compared against the
   CURRENT DEFAULT arm on the same input before any timing: a knob that
   changes answers beyond the path's pinned tolerance is rejected as
   ``parity_failed`` and can never become a winner — the gate that keeps
   an absurd planted value (lane_T=8) out of the table.
3. **Time.**  The bench.py relay discipline: chained data-dependent reps
   inside one ``lax.scan``, a distinct seed folded into every rep,
   every rep fetching a small output, sub-100us walls retried as relay
   phantoms, and the ``obs.watchdog`` per-path plausibility ceilings
   armed on TPU.
4. **Verdict + persist.**  The winner is the fastest parity-clean
   candidate; a flip away from the legacy default is APPLIED only on the
   capturing platform (TPU) with a >=``FLIP_MARGIN`` measured advantage
   — CPU sweeps record rates as projections and keep the legacy value
   applied, the BASELINE.md decision rule in code.  ``--update-tune`` /
   ``--apply`` (tools/graftune.py) write the rows into TUNING.json.
"""

from __future__ import annotations

import dataclasses
import time
from typing import Optional

from cpgisland_tpu.tune import table as tune_table
from cpgisland_tpu.tune import tasks as tune_tasks
from cpgisland_tpu.tune.table import FLIP_MARGIN


class PrunedTupleCompiled(AssertionError):
    """A memmodel-rejected knob tuple reached the compile/time stage."""


class SweepLedger:
    """The prune/compile audit the acceptance criteria assert on: every
    candidate is either pruned (with the feasibility reason) or timed,
    and the two sets must stay disjoint."""

    def __init__(self):
        self.pruned: dict = {}
        self.timed: list = []

    def prune(self, task: str, value, reason: str) -> None:
        self.pruned[(task, repr(value))] = reason
        from cpgisland_tpu import obs

        obs.event(
            "tune_prune", _dedupe=True, task=task, value=repr(value),
            reason=reason[:200],
        )

    def check_compile(self, task: str, value) -> None:
        if (task, repr(value)) in self.pruned:
            raise PrunedTupleCompiled(
                f"{task}: pruned candidate {value!r} reached the "
                "compile/time stage — the feasibility prune must gate "
                "every compile"
            )
        self.timed.append((task, repr(value)))

    @property
    def clean(self) -> bool:
        return not (set(self.pruned) & set(self.timed))

    def as_dict(self) -> dict:
        return {
            "pruned": [
                {"task": t, "value": v, "reason": r}
                for (t, v), r in sorted(self.pruned.items())
            ],
            "timed": [
                {"task": t, "value": v} for t, v in self.timed
            ],
            "clean": self.clean,
        }


def _best_wall(fn, reps: int) -> float:
    """Min wall over reps with DISTINCT seeds; sub-100us walls are relay
    phantoms and retried (the bench.py defense)."""
    seed, done, phantoms, best = 1, 0, 0, float("inf")
    while done < reps:
        t0 = time.perf_counter()
        fn(seed)
        dt = time.perf_counter() - t0
        seed += 1
        if dt < 1e-4:
            phantoms += 1
            if phantoms > 3 * reps:
                raise RuntimeError(
                    "persistent ~0 ms results: relay phantom"
                )
            continue
        best = min(best, dt)
        done += 1
    return best


def _ceilings() -> dict:
    import jax

    if jax.default_backend() != "tpu":
        return {}
    from cpgisland_tpu.obs import watchdog

    return watchdog.path_ceilings()


def _check_ceiling(tput: float, ceiling: float, what: str) -> None:
    if tput > ceiling:
        raise RuntimeError(
            f"{what}: {tput / 1e6:.0f} Msym/s exceeds the "
            f"{ceiling / 1e6:.0f} Msym/s plausibility ceiling "
            "(relay phantom?)"
        )


@dataclasses.dataclass
class TaskReport:
    task: str
    key: str
    legacy: object
    winner: object            # fastest parity-clean candidate (measured)
    applied_value: object     # what the persisted row routes (flip rule)
    decision: str             # "keep" | "flip"
    projection: bool
    rows: list                # per-candidate {value, status, rate, err}
    parity: dict
    entry: dict               # the TUNING.json row make_entry produced

    def as_dict(self) -> dict:
        return {
            "task": self.task, "key": self.key, "legacy": self.legacy,
            "winner": self.winner, "applied_value": self.applied_value,
            "decision": self.decision, "projection": self.projection,
            "rows": self.rows, "parity": self.parity,
        }


def validate_entry(task_name: str, value, cfg=None) -> None:
    """The apply-time gate a winner row must pass before it is written —
    and the gate a PLANTED row hits when someone tries to apply it.

    Checks, in order: the value is in the task's candidate domain (an
    absurd lane_T=8 dies here — it was never sweepable), and the
    graftmem feasibility oracle admits it (a value that stopped fitting
    after a memmodel recalibration dies here).  The numeric parity gate
    itself runs during the sweep — values that fail it never become
    winners — so a row that skipped the sweep entirely is exactly what
    this function refuses."""
    cfg = cfg or tune_tasks.SweepConfig(smoke=True)
    matches = tune_tasks.tasks_by_name([task_name])
    t = matches[0]
    domain = t.candidates(cfg)
    if value not in domain:
        raise ValueError(
            f"parity gate: {task_name} winner {value!r} is outside the "
            f"sweepable candidate domain {domain} — refusing to apply an "
            "unswept value"
        )
    f = t.feasibility(value, cfg)
    if f is not None and not f.ok:
        raise ValueError(
            f"parity gate: {task_name} winner {value!r} fails the "
            f"graftmem feasibility model — {f.reason}"
        )


def run_task(
    t: tune_tasks.Task,
    cfg: tune_tasks.SweepConfig,
    ledger: SweepLedger,
    log=None,
) -> TaskReport:
    import jax

    def say(msg):
        if log:
            log(msg)

    projection = jax.default_backend() != "tpu"
    legacy = t.legacy(cfg)
    cands = t.candidates(cfg)
    survivors = []
    pruned_rows = []
    for c in cands:
        f = t.feasibility(c, cfg)
        if f is not None and not f.ok:
            ledger.prune(t.name, c, f.reason)
            pruned_rows.append({"value": c, "reason": f.reason})
            say(f"{t.name}: pruned {c!r} ({f.reason[:80]}...)")
            continue
        survivors.append(c)
    if legacy not in survivors:
        raise RuntimeError(
            f"{t.name}: the legacy default {legacy!r} was pruned by the "
            "feasibility model — recalibrate memmodel before sweeping"
        )

    env = t.build(cfg)
    ledger.check_compile(t.name, legacy)
    ref = jax.block_until_ready(t.run_once(env, legacy))
    ceiling = _ceilings().get(t.ceiling_key, float("inf"))

    rows = []
    parity = {"tol": t.parity_tol, "max_err": 0.0}
    best = None
    for c in survivors:
        if c != legacy:
            ledger.check_compile(t.name, c)
            err = t.parity_err(ref, jax.block_until_ready(
                t.run_once(env, c)
            ))
        else:
            err = 0.0
        parity["max_err"] = max(parity["max_err"], err)
        if err > t.parity_tol:
            rows.append(
                {"value": c, "status": "parity_failed", "err": err}
            )
            say(f"{t.name}: {c!r} REJECTED by parity gate (err {err:.2e})")
            continue
        fn = t.make_chained(env, c, cfg)
        fn(0)  # warm (seed 0 — every timed rep folds a distinct seed)
        wall = _best_wall(fn, cfg.reps) / cfg.chain
        n_sym = env.get("n", cfg.n)
        tput = n_sym / wall
        _check_ceiling(tput, ceiling, t.name)
        rows.append({
            "value": c, "status": "timed", "err": err,
            "msym_per_s": round(tput / 1e6, 1),
            "wall_ms": round(wall * 1e3, 3),
        })
        say(f"{t.name}: {c!r} -> {tput / 1e6:8.1f} Msym/s")
        if best is None or tput > best[1]:
            best = (c, tput)

    timed = {r["value"]: r["msym_per_s"] for r in rows
             if r["status"] == "timed"}
    base_rate = timed.get(legacy)
    winner, win_rate = best if best is not None else (legacy, None)
    ratio = (
        round(win_rate / (base_rate * 1e6), 3)
        if (win_rate is not None and base_rate) else None
    )
    # The flip rule (BASELINE.md's "flip the per-path default on a
    # measured loss", automated): adopt a non-legacy winner only on the
    # capturing platform and only past the margin — projections and
    # noise-level wins keep the shipped default.
    flip = (
        winner != legacy
        and not projection
        and ratio is not None
        and ratio >= 1.0 + FLIP_MARGIN
    )
    applied_value = winner if flip else legacy
    decision = "flip" if flip else "keep"

    key = tune_table.entry_key(
        t.name,
        n_pow2=tune_table.pow2_bucket(cfg.n) if t.bucketed else None,
        S=t.n_states,
    )
    entry = tune_table.make_entry(
        t.name, applied_value, legacy=legacy,
        costs_entries=t.costs_entries,
        # CPU rows stay recorded-not-applied for geometry knobs so the
        # routing never moves on projection timings; boolean verdicts
        # whose applied value IS the legacy default are safe to apply
        # anywhere (fresh-and-consulted, value unchanged).
        applied=(not projection) or (applied_value == legacy),
        projection=projection,
        rate_msym_s=timed.get(applied_value),
        baseline_msym_s=base_rate,
        ratio=ratio,
        parity=parity,
        verdict={
            "decision": decision, "winner_measured": winner,
            "ratio_vs_legacy": ratio,
            # The shipped default measured a LOSS past the margin (a
            # non-legacy arm beat it) — the signal the BASELINE.md flip
            # rule keys on.  On a capture platform this coincides with a
            # flip; on a projection it is recorded but NOT applied.
            "measured_loss": bool(
                winner != legacy
                and ratio is not None
                and ratio >= 1.0 + FLIP_MARGIN
            ),
        },
        swept=rows,
        pruned=pruned_rows,
    )
    return TaskReport(
        task=t.name, key=key, legacy=legacy, winner=winner,
        applied_value=applied_value, decision=decision,
        projection=projection, rows=rows, parity=parity, entry=entry,
    )


def run_sweep(
    names=None,
    prefix: Optional[str] = None,
    cfg: Optional[tune_tasks.SweepConfig] = None,
    smoke: bool = False,
    log=None,
) -> dict:
    """Run the selected tasks; returns the report dict tools/graftune.py
    prints as its one JSON line (winners NOT yet persisted — that is the
    --update-tune / --apply step, gated per row by validate_entry)."""
    import jax

    if cfg is None:
        cfg = tune_tasks.SweepConfig(
            n=(256 << 10) if smoke else (2 << 20),
            chain=2, reps=1 if smoke else 2, smoke=smoke,
        )
    if names is None and prefix is None and smoke:
        names = list(tune_tasks.SMOKE_TASKS)
    ledger = SweepLedger()
    reports = []
    for t in tune_tasks.tasks_by_name(names, prefix):
        reports.append(run_task(t, cfg, ledger, log=log))
    if not ledger.clean:  # pragma: no cover - check_compile raises first
        raise PrunedTupleCompiled("pruned/timed candidate sets overlap")
    return {
        "bench": "graftune",
        "backend": jax.default_backend(),
        "projection": jax.default_backend() != "tpu",
        "n_symbols": cfg.n,
        "chain": cfg.chain,
        "tasks": [r.as_dict() for r in reports],
        "ledger": ledger.as_dict(),
        "_reports": reports,   # stripped before printing (persist handle)
    }


def persist(
    report: dict,
    update_tune: bool = False,
    apply_verdicts: bool = False,
    path: Optional[str] = None,
    platform: Optional[str] = None,
) -> Optional[str]:
    """Write sweep winners into TUNING.json.

    ``update_tune`` writes the geometry-knob rows (lane/t_tile/block/
    engine); ``apply_verdicts`` writes the fused/stacked verdict rows
    (the satellite rule: the verdict block is applied by flag, never by
    hand-editing defaults).  Every row re-runs :func:`validate_entry`
    first — the same gate a planted absurd winner fails."""
    entries = {}
    for r in report["_reports"]:
        is_verdict = r.task.startswith(("fused.", "stacked."))
        if is_verdict and not apply_verdicts:
            continue
        if not is_verdict and not update_tune:
            continue
        validate_entry(r.task, r.applied_value)
        entries[r.key] = r.entry
    if not entries:
        return None
    return tune_table.write_entries(entries, platform=platform, path=path)
