"""graftune tasks — one sweep definition per kernel-family knob.

Each :class:`Task` names the knob, its legal candidate domain, the
``memmodel`` feasibility check that prunes candidates BEFORE any compile,
the parity gate that compares every survivor against the current default
arm BEFORE any timing, and the chained-timing program (the bench.py relay
discipline: R data-dependent reps inside one ``lax.scan``, a distinct
seed folded into every rep's params/input, every rep fetching a small
output).

The task set subsumes the hand-driven chip-window harnesses: the
``fused.*`` booleans are tools/bench_passfusion.py's A/B decisions, the
``stacked.*`` booleans are tools/bench_multimodel.py's, and the lane /
t_tile / block_size sweeps are the "re-sweep tile knobs after kernel
reshapes" obligation — one ``tools/graftune.py --all`` run per TPU
window instead of three harnesses plus hand-edited defaults.

Everything imports jax lazily: task construction is metadata-only (the
CLI lists tasks without a backend).
"""

from __future__ import annotations

import dataclasses
from typing import Callable, Optional

# Parity tolerances per output class (the test-suite's own gates).
CONF_TOL = 2e-5          # posterior confidence tracks
STATS_REL_TOL = 1e-4     # EM sufficient statistics, relative
SCORE_REL_TOL = 1e-4     # per-record Viterbi scores, relative
PATH_MISMATCH_MAX = 1e-3  # path positions allowed to differ (tie class)


@dataclasses.dataclass
class SweepConfig:
    """One sweep invocation's geometry/discipline knobs."""

    n: int = 2 << 20          # symbols per timed input
    chain: int = 2            # data-dependent reps inside one lax.scan
    reps: int = 2             # wall repetitions (min taken)
    members: int = 3          # stacked-arm member count
    smoke: bool = False


@dataclasses.dataclass
class Task:
    """One sweep task.  ``candidates`` includes the legacy value; the
    driver prunes via ``feasibility``, parity-gates survivors against the
    ``legacy`` arm's output, times them, and derives the verdict."""

    name: str
    family: str                       # "fb.reduced" | "decode.flat" | ...
    costs_entries: tuple              # COSTS.json staleness dependencies
    legacy: Callable                  # cfg -> legacy value
    candidates: Callable              # cfg -> [value, ...]
    feasibility: Callable             # (value, cfg) -> Feasibility | None
    build: Callable                   # cfg -> env dict (params, inputs)
    run_once: Callable                # (env, value) -> comparable output
    parity_err: Callable              # (ref_out, out) -> float
    parity_tol: float
    make_chained: Callable            # (env, value, cfg) -> fn(seed)->float
    ceiling_key: str                  # obs.watchdog path ceiling name
    bucketed: bool = False            # key on the pow2 geometry bucket
    n_states: Optional[int] = None    # S key field (None = wildcard)


def _params():
    from cpgisland_tpu.models import presets

    return presets.durbin_cpg8()


def _member_params(m: int):
    """M reduced-eligible members over one alphabet: the flagship preset
    with per-member prior perturbations (emission structure — the
    routing key — is untouched)."""
    import dataclasses as dc

    import jax.numpy as jnp

    base = _params()
    return tuple(
        dc.replace(base, log_pi=base.log_pi - jnp.float32(i) * 1e-4)
        for i in range(m)
    )


def _jitter(p, s):
    """Params-side distinct-seed fold (full seed, no modulus — a wrapped
    jitter hands the relay a byte-identical repeat; bench_passfusion)."""
    import dataclasses as dc

    import jax.numpy as jnp

    return dc.replace(p, log_pi=p.log_pi - s.astype(jnp.float32) * 1e-7)


def _obs_stream(n: int, seed: int = 1):
    import numpy as np

    import jax.numpy as jnp

    rng = np.random.default_rng(seed)
    return jnp.asarray(
        rng.integers(0, 4, size=n, dtype=np.int32).astype(np.uint8)
    )


def _island_mask8():
    import numpy as np

    import jax.numpy as jnp

    return jnp.asarray(np.r_[np.ones(4), np.zeros(4)].astype(np.float32))


def _stats_rel_err(a, b) -> float:
    import jax.numpy as jnp

    return float(
        jnp.max(
            jnp.abs(a.trans - b.trans)
            / jnp.maximum(jnp.abs(a.trans), 1e-3)
        )
    )


# -- lane_T (reduced FB family) ----------------------------------------------


def _lane_task() -> Task:
    def legacy(cfg):
        from cpgisland_tpu.ops import fb_pallas

        return fb_pallas.legacy_lane_T(cfg.n, onehot=True, long_lanes=True)

    def candidates(cfg):
        from cpgisland_tpu.ops import fb_pallas

        return [k for k in sorted(fb_pallas._LANE_RATE_ONEHOT)]

    def feas(value, cfg):
        from cpgisland_tpu.analysis import memmodel
        from cpgisland_tpu.ops.fb_onehot import TUNE_KERNELS

        k = memmodel.Knobs(lane_tile=256, lane_T=int(value))
        return memmodel.feasible(TUNE_KERNELS["em_seq"], k)

    def build(cfg):
        return {
            "params": _params(),
            "obs": _obs_stream(cfg.n),
            "mask": _island_mask8(),
        }

    def run_once(env, value):
        from cpgisland_tpu.ops import fb_pallas

        conf, _ = fb_pallas.seq_posterior_pallas(
            env["params"], env["obs"], env["obs"].shape[0], env["mask"],
            lane_T=int(value), onehot=True,
        )
        return conf

    def parity_err(ref, out):
        import jax.numpy as jnp

        return float(jnp.max(jnp.abs(ref - out)))

    def make_chained(env, value, cfg):
        import jax
        import jax.numpy as jnp

        from cpgisland_tpu.ops import fb_pallas

        n = env["obs"].shape[0]

        @jax.jit
        def chained(p, obs, s):
            p = _jitter(p, s)

            def body(c, _):
                conf, _ = fb_pallas.seq_posterior_pallas(
                    p, obs, n, env["mask"] + c * 0.0,
                    lane_T=int(value), onehot=True,
                )
                return jnp.sum(conf[:8]) * 1e-9, None

            c, _ = jax.lax.scan(
                body, jnp.float32(0), None, length=cfg.chain
            )
            return c

        return lambda s: float(
            jax.device_get(chained(env["params"], env["obs"], jnp.int32(s)))
        )

    return Task(
        name="lane.onehot.long", family="fb.reduced",
        costs_entries=("posterior.onehot", "em.seq.onehot"),
        legacy=legacy, candidates=candidates, feasibility=feas,
        build=build, run_once=run_once, parity_err=parity_err,
        parity_tol=CONF_TOL, make_chained=make_chained,
        ceiling_key="posterior", bucketed=True,
    )


# -- t_tile (reduced FB exact-seq family) ------------------------------------


def _t_tile_seq_task() -> Task:
    def legacy(cfg):
        from cpgisland_tpu.ops import fb_pallas

        return fb_pallas.DEFAULT_T_TILE

    def candidates(cfg):
        # 4096 exists to be PRUNED: the seq-stats alphas2/betas2 stream
        # blocks alone outgrow the VMEM model there (the ledger's proof
        # that rejected tuples never reach compile).
        return [256, 512, 1024, 4096]

    def feas(value, cfg):
        from cpgisland_tpu.analysis import memmodel
        from cpgisland_tpu.ops.fb_onehot import TUNE_KERNELS

        k = memmodel.Knobs(lane_tile=256, t_tile=int(value))
        return memmodel.feasible(TUNE_KERNELS["em_seq"], k)

    def _lane(cfg):
        from cpgisland_tpu.ops import fb_pallas

        return fb_pallas.legacy_lane_T(cfg.n, onehot=True, long_lanes=True)

    def build(cfg):
        return {
            "params": _params(),
            "obs": _obs_stream(cfg.n, seed=2),
            "lane_T": _lane(cfg),
        }

    def run_once(env, value):
        from cpgisland_tpu.ops import fb_pallas

        return fb_pallas.seq_stats_pallas(
            env["params"], env["obs"], env["obs"].shape[0],
            lane_T=env["lane_T"], t_tile=int(value), onehot=True,
        )

    def make_chained(env, value, cfg):
        import jax
        import jax.numpy as jnp

        from cpgisland_tpu.ops import fb_pallas

        n = env["obs"].shape[0]

        @jax.jit
        def chained(p, obs, s):
            p = _jitter(p, s)

            def body(c, _):
                st = fb_pallas.seq_stats_pallas(
                    p, obs, n, lane_T=env["lane_T"], t_tile=int(value),
                    onehot=True,
                )
                return c + st.loglik * 1e-9, None

            c, _ = jax.lax.scan(
                body, jnp.float32(0), None, length=cfg.chain
            )
            return c

        return lambda s: float(
            jax.device_get(chained(env["params"], env["obs"], jnp.int32(s)))
        )

    return Task(
        name="t_tile.em_seq", family="fb.reduced",
        costs_entries=("em.seq.onehot",),
        legacy=legacy, candidates=candidates, feasibility=feas,
        build=build, run_once=run_once, parity_err=_stats_rel_err,
        parity_tol=STATS_REL_TOL, make_chained=make_chained,
        ceiling_key="em-seq",
    )


# -- flat-decode block size ---------------------------------------------------


def _flat_geometry(cfg):
    import numpy as np

    import jax.numpy as jnp

    T = 4096 if cfg.smoke else 16384
    N = max(4, cfg.n // T)
    rng = np.random.default_rng(4)
    chunks = jnp.asarray(
        rng.integers(0, 4, size=(N, T), dtype=np.int32).astype(np.uint8)
    )
    lengths = jnp.full(N, T, jnp.int32)
    return chunks, lengths


def _flat_block_task(scores: bool) -> Task:
    def legacy(cfg):
        return 4096

    def candidates(cfg):
        # 16384 exists to be pruned: the score rows (dmax) and the
        # backtrace path_out both outgrow the VMEM model there, while the
        # flat route's own modeled cap sits at 8192 — one notch above the
        # vmap route's measured bk>=8192 failure (test_graftmem pins the
        # distinction).
        return [1024, 2048, 4096, 8192, 16384]

    def feas(value, cfg):
        from cpgisland_tpu.analysis import memmodel

        return memmodel.flat_block_feasibility(int(value), scores=scores)

    def build(cfg):
        chunks, lengths = _flat_geometry(cfg)
        return {"params": _params(), "chunks": chunks, "lengths": lengths}

    def run_once(env, value):
        from cpgisland_tpu.ops import viterbi_onehot as OH

        return OH.decode_batch_flat(
            env["params"], env["chunks"], env["lengths"],
            block_size=int(value), return_score=scores,
        )

    def parity_err(ref, out):
        import numpy as np

        if scores:
            p_ref, s_ref = ref
            p_out, s_out = out
            rel = float(
                np.max(
                    np.abs(np.asarray(s_ref) - np.asarray(s_out))
                    / np.maximum(np.abs(np.asarray(s_ref)), 1.0)
                )
            )
        else:
            p_ref, p_out, rel = ref, out, 0.0
        mism = float(
            np.mean(np.asarray(p_ref) != np.asarray(p_out))
        )
        # Path positions may move only on exact max-plus ties (the flat
        # decoder's pinned rounding-tie contract); scores must agree.
        # Both gates normalize to the task's shared tolerance: the result
        # crosses parity_tol iff either crosses its own bound.
        tol = min(SCORE_REL_TOL, PATH_MISMATCH_MAX)
        return max(rel / SCORE_REL_TOL, mism / PATH_MISMATCH_MAX) * tol

    def make_chained(env, value, cfg):
        import jax
        import jax.numpy as jnp

        from cpgisland_tpu.ops import viterbi_onehot as OH

        chunks, lengths = env["chunks"], env["lengths"]
        T = chunks.shape[1]
        P = min(8191, T - 2)

        @jax.jit
        def chained(ch, s):
            pos = 1 + (s * 7) % P
            ch = ch.at[0, pos].set(
                ((ch[0, pos].astype(jnp.int32) + 1 + s // P) % 4)
                .astype(ch.dtype)
            )

            def body(c, _):
                got = OH.decode_batch_flat(
                    env["params"], ch, lengths,
                    block_size=int(value), return_score=scores,
                )
                paths = got[0] if scores else got
                return c + jnp.sum(paths[:, :8]).astype(jnp.float32) * 1e-9, None

            c, _ = jax.lax.scan(
                body, jnp.float32(0), None, length=cfg.chain
            )
            return c

        return lambda s: float(jax.device_get(chained(chunks, jnp.int32(s))))

    return Task(
        name="flat.block" + (".scores" if scores else ""),
        family="decode.flat",
        costs_entries=(
            ("decode.batch_flat.scores.onehot",) if scores
            else ("decode.batch_flat.onehot",)
        ),
        legacy=legacy, candidates=candidates, feasibility=feas,
        build=build, run_once=run_once, parity_err=parity_err,
        parity_tol=min(SCORE_REL_TOL, PATH_MISMATCH_MAX),
        make_chained=make_chained, ceiling_key="decode",
    )


# -- per-path fused booleans (the bench_passfusion decisions) ----------------


def _fused_task(path: str) -> Task:
    costs = {
        "posterior": ("posterior.onehot",),
        "em_seq": ("em.seq.onehot",),
        "em_chunked": ("em.chunked.onehot",),
    }[path]
    ceiling = {"posterior": "posterior", "em_seq": "em-seq",
               "em_chunked": "em"}[path]

    def build(cfg):
        from cpgisland_tpu.ops import fb_pallas

        env = {"params": _params()}
        if path == "em_chunked":
            import numpy as np

            import jax.numpy as jnp

            chunk = (1 << 14) if cfg.smoke else (1 << 16)
            n_chunks = max(1, cfg.n // chunk)
            rng = np.random.default_rng(3)
            env["chunks"] = jnp.asarray(
                rng.integers(
                    0, 4, size=(n_chunks, chunk), dtype=np.int32
                ).astype(np.uint8)
            )
            env["lengths"] = jnp.full(n_chunks, chunk, jnp.int32)
            env["n"] = n_chunks * chunk
        else:
            env["obs"] = _obs_stream(cfg.n, seed=5)
            env["n"] = cfg.n
            env["lane_T"] = fb_pallas.legacy_lane_T(
                cfg.n, onehot=True, long_lanes=True
            )
            env["mask"] = _island_mask8()
        return env

    def run_once(env, value):
        from cpgisland_tpu.ops import fb_pallas

        if path == "posterior":
            conf, _ = fb_pallas.seq_posterior_pallas(
                env["params"], env["obs"], env["n"], env["mask"],
                lane_T=env["lane_T"], onehot=True, fused=bool(value),
            )
            return conf
        if path == "em_seq":
            return fb_pallas.seq_stats_pallas(
                env["params"], env["obs"], env["n"],
                lane_T=env["lane_T"], onehot=True, fused=bool(value),
            )
        return fb_pallas.batch_stats_pallas(
            env["params"], env["chunks"], env["lengths"], onehot=True,
            fused=bool(value),
        )

    def parity_err(ref, out):
        if path == "posterior":
            import jax.numpy as jnp

            return float(jnp.max(jnp.abs(ref - out)))
        return _stats_rel_err(ref, out)

    def make_chained(env, value, cfg):
        import jax
        import jax.numpy as jnp

        # The symbol stream rides as an ARGUMENT, never a closed-over
        # constant: remote compile ships program bytes over HTTP and a
        # baked 64+ MiB array is an HTTP 413 on the relay (CLAUDE.md).
        data_key = "chunks" if path == "em_chunked" else "obs"

        @jax.jit
        def chained(p, data, s):
            p = _jitter(p, s)

            def body(c, _):
                got = run_once({**env, "params": p, data_key: data}, value)
                small = got[:8] if path == "posterior" else got.loglik
                return c + jnp.sum(small) * 1e-9, None

            c, _ = jax.lax.scan(
                body, jnp.float32(0), None, length=cfg.chain
            )
            return c

        return lambda s: float(
            jax.device_get(chained(env["params"], env[data_key], jnp.int32(s)))
        )

    return Task(
        name=f"fused.{path}", family="fb.reduced", costs_entries=costs,
        legacy=lambda cfg: True,
        candidates=lambda cfg: [True, False],
        feasibility=lambda value, cfg: None,
        build=build, run_once=run_once, parity_err=parity_err,
        parity_tol=CONF_TOL if path == "posterior" else STATS_REL_TOL,
        make_chained=make_chained, ceiling_key=ceiling,
    )


def _one_pass_task(path: str) -> Task:
    """The true-one-pass A/B (ISSUE 17): False = the shipped fused 2-pass
    arm, True = the matrix-carried kernel with the products pass folded
    in.  Same harness shape as ``fused.*``; the True arm's knob point is
    pruned through the matrix kernel's graftmem row before any compile."""
    costs = {
        "posterior": ("posterior.onehot.onepass",),
        "em_seq": ("em.seq.onehot.onepass",),
    }[path]
    ceiling = {"posterior": "posterior", "em_seq": "em-seq"}[path]

    def build(cfg):
        from cpgisland_tpu.ops import fb_pallas

        env = {"params": _params()}
        env["obs"] = _obs_stream(cfg.n, seed=9)
        env["n"] = cfg.n
        env["lane_T"] = fb_pallas.legacy_lane_T(
            cfg.n, onehot=True, long_lanes=True
        )
        env["mask"] = _island_mask8()
        return env

    def run_once(env, value):
        from cpgisland_tpu.ops import fb_pallas

        if path == "posterior":
            conf, _ = fb_pallas.seq_posterior_pallas(
                env["params"], env["obs"], env["n"], env["mask"],
                lane_T=env["lane_T"], onehot=True, one_pass=bool(value),
            )
            return conf
        return fb_pallas.seq_stats_pallas(
            env["params"], env["obs"], env["n"],
            lane_T=env["lane_T"], onehot=True, one_pass=bool(value),
        )

    def feas(value, cfg):
        if not value:
            return None
        from cpgisland_tpu.analysis import memmodel

        # The matrix kernel streams DOUBLED [t_tile, 4, lane_tile] blocks
        # both ways — prune its production 256-lane point statically.
        return memmodel.feasible(
            "fb.fwdbwdmat.onehot", memmodel.Knobs(lane_tile=256)
        )

    def parity_err(ref, out):
        if path == "posterior":
            import jax.numpy as jnp

            return float(jnp.max(jnp.abs(ref - out)))
        return _stats_rel_err(ref, out)

    def make_chained(env, value, cfg):
        import jax
        import jax.numpy as jnp

        # Params-side seed fold (relay anti-phantom); the symbol stream
        # rides as an argument, never a baked constant (HTTP 413).
        @jax.jit
        def chained(p, data, s):
            p = _jitter(p, s)

            def body(c, _):
                got = run_once({**env, "params": p, "obs": data}, value)
                small = got[:8] if path == "posterior" else got.loglik
                return c + jnp.sum(small) * 1e-9, None

            c, _ = jax.lax.scan(
                body, jnp.float32(0), None, length=cfg.chain
            )
            return c

        return lambda s: float(
            jax.device_get(chained(env["params"], env["obs"], jnp.int32(s)))
        )

    return Task(
        name=f"one_pass.{path}", family="fb.reduced", costs_entries=costs,
        legacy=lambda cfg: False,
        candidates=lambda cfg: [False, True],
        feasibility=feas,
        build=build, run_once=run_once, parity_err=parity_err,
        parity_tol=CONF_TOL if path == "posterior" else STATS_REL_TOL,
        make_chained=make_chained, ceiling_key=ceiling,
    )


# -- per-site stacked booleans (the bench_multimodel decisions) --------------


def _stacked_task(site: str) -> Task:
    costs = {
        "em_family": ("em.chunked.onehot.stacked3",),
        # The compare site's stacked unit IS the stacked posterior pass
        # (family.stacked groups compare members into
        # posterior_sharded_stacked units) — the task times that unit and
        # the winner routes compare_record's ``stacked`` default.
        "compare": ("posterior.onehot.stacked3",),
        "serve_decode": ("decode.batch_flat.onehot.stacked3",),
    }[site]
    ceiling = {"em_family": "em", "compare": "posterior",
               "serve_decode": "decode"}[site]

    def build(cfg):
        from cpgisland_tpu.ops import fb_pallas

        env = {"members": _member_params(cfg.members)}
        if site == "em_family":
            import numpy as np

            import jax.numpy as jnp

            chunk = (1 << 14) if cfg.smoke else (1 << 16)
            n_chunks = max(1, cfg.n // chunk)
            rng = np.random.default_rng(6)
            env["chunks"] = jnp.asarray(
                rng.integers(
                    0, 4, size=(n_chunks, chunk), dtype=np.int32
                ).astype(np.uint8)
            )
            env["lengths"] = jnp.full(n_chunks, chunk, jnp.int32)
            env["n"] = n_chunks * chunk
        elif site == "compare":
            env["obs"] = _obs_stream(cfg.n, seed=7)
            env["n"] = cfg.n
            env["lane_T"] = fb_pallas.legacy_lane_T(
                cfg.n, onehot=True, long_lanes=True
            )
            env["masks"] = tuple(_island_mask8() for _ in env["members"])
        else:
            from cpgisland_tpu.analysis import memmodel

            chunks, lengths = _flat_geometry(cfg)
            env["chunks"], env["lengths"] = chunks, lengths
            env["n"] = int(chunks.shape[0] * chunks.shape[1])
            # ONE explicit block for BOTH arms, already inside the
            # stacked M-member VMEM cap so the on-TPU clamp never fires
            # and the A/B compares identical geometries.
            env["block"] = min(
                4096, memmodel.stacked_block_cap(cfg.members, scores=False)
            )
        return env

    def run_once(env, value):
        from cpgisland_tpu.ops import fb_pallas
        from cpgisland_tpu.ops import viterbi_onehot as OH

        members = env["members"]
        if site == "em_family":
            if value:
                return fb_pallas.batch_stats_pallas_stacked(
                    members, env["chunks"], env["lengths"]
                )
            return tuple(
                fb_pallas.batch_stats_pallas(
                    p, env["chunks"], env["lengths"], onehot=True
                )
                for p in members
            )
        if site == "compare":
            if value:
                conf, _ = fb_pallas.seq_posterior_pallas_stacked(
                    members, env["obs"], env["n"], env["masks"],
                    lane_T=env["lane_T"],
                )
                return conf
            import jax.numpy as jnp

            return jnp.stack([
                fb_pallas.seq_posterior_pallas(
                    p, env["obs"], env["n"], m,
                    lane_T=env["lane_T"], onehot=True,
                )[0]
                for p, m in zip(members, env["masks"])
            ])
        # Both arms at ONE explicit block (env["block"], stacked-feasible
        # so the TPU clamp never fires): block_size=None would consult
        # the tuning table per arm (different M keys -> potentially
        # different blocks, and a trace-time lookup inside the chained
        # jit), contaminating the A/B with mismatched geometries.
        if value:
            return OH.decode_batch_flat_stacked(
                members, env["chunks"], env["lengths"],
                block_size=env["block"],
            )
        import jax.numpy as jnp

        return jnp.stack([
            OH.decode_batch_flat(
                p, env["chunks"], env["lengths"], block_size=env["block"]
            )
            for p in members
        ])

    def parity_err(ref, out):
        import numpy as np

        if site == "em_family":
            return max(
                _stats_rel_err(a, b) for a, b in zip(ref, out)
            )
        if site == "compare":
            import jax.numpy as jnp

            return float(jnp.max(jnp.abs(ref - out)))
        # Stacked decode is bit-identical per member off-TPU (same block)
        # and tie-class on chip: the err is the path-mismatch fraction.
        return float(np.mean(np.asarray(ref) != np.asarray(out)))

    def make_chained(env, value, cfg):
        import jax
        import jax.numpy as jnp

        if site == "serve_decode":
            chunks = env["chunks"]
            T = chunks.shape[1]
            P = min(8191, T - 2)

            @jax.jit
            def chained(ch, s):
                pos = 1 + (s * 7) % P
                ch = ch.at[0, pos].set(
                    ((ch[0, pos].astype(jnp.int32) + 1 + s // P) % 4)
                    .astype(ch.dtype)
                )

                def body(c, _):
                    got = run_once({**env, "chunks": ch}, value)
                    return (
                        c + jnp.sum(got[0][:, :8]).astype(jnp.float32) * 1e-9,
                        None,
                    )

                c, _ = jax.lax.scan(
                    body, jnp.float32(0), None, length=cfg.chain
                )
                return c

            return lambda s: float(
                jax.device_get(chained(chunks, jnp.int32(s)))
            )

        # Stream-as-argument, same HTTP-413 rule as the fused tasks.
        data_key = "chunks" if site == "em_family" else "obs"

        @jax.jit
        def chained(p0, data, s):
            p0 = _jitter(p0, s)

            def body(c, _):
                members = (p0,) + tuple(env["members"][1:])
                got = run_once(
                    {**env, "members": members, data_key: data}, value
                )
                if site == "em_family":
                    small = sum(st.loglik for st in got)
                else:
                    small = jnp.sum(got[0][:8])
                return c + small * 1e-9, None

            c, _ = jax.lax.scan(
                body, jnp.float32(0), None, length=cfg.chain
            )
            return c

        return lambda s: float(
            jax.device_get(
                chained(env["members"][0], env[data_key], jnp.int32(s))
            )
        )

    def feas(value, cfg):
        if not value:
            return None
        from cpgisland_tpu.analysis import memmodel
        from cpgisland_tpu.ops.fb_onehot import TUNE_KERNELS

        kernel = {
            "em_family": TUNE_KERNELS["em_chunked"],
            "compare": TUNE_KERNELS["posterior"],
            "serve_decode": "decode.backpointers.onehot",
        }[site]
        return memmodel.feasible(
            kernel,
            memmodel.Knobs(
                lane_tile=256 if site != "serve_decode" else 128,
                stacked_m=cfg.members,
            ),
        )

    return Task(
        name=f"stacked.{site}", family="stacked", costs_entries=costs,
        legacy=lambda cfg: True,
        candidates=lambda cfg: [True, False],
        feasibility=feas,
        build=build, run_once=run_once, parity_err=parity_err,
        parity_tol=(
            STATS_REL_TOL if site == "em_family"
            else CONF_TOL if site == "compare" else PATH_MISMATCH_MAX
        ),
        make_chained=make_chained, ceiling_key=ceiling,
    )


# -- engine choice (auto's dense-vs-reduced pick) ----------------------------


def _engine_task() -> Task:
    def legacy(cfg):
        import jax

        return "onehot" if jax.default_backend() == "tpu" else "xla"

    def build(cfg):
        import numpy as np

        import jax.numpy as jnp

        chunk = (1 << 14) if cfg.smoke else (1 << 16)
        n_chunks = max(1, cfg.n // chunk)
        rng = np.random.default_rng(8)
        return {
            "params": _params(),
            "chunks": jnp.asarray(
                rng.integers(
                    0, 4, size=(n_chunks, chunk), dtype=np.int32
                ).astype(np.uint8)
            ),
            "lengths": jnp.full(n_chunks, chunk, jnp.int32),
            "n": n_chunks * chunk,
        }

    def run_once(env, value):
        from cpgisland_tpu.ops import fb_pallas
        from cpgisland_tpu.ops.forward_backward import batch_stats

        if value == "onehot":
            return fb_pallas.batch_stats_pallas(
                env["params"], env["chunks"], env["lengths"], onehot=True
            )
        return batch_stats(
            env["params"], env["chunks"], env["lengths"], mode="rescaled"
        )

    def make_chained(env, value, cfg):
        import jax
        import jax.numpy as jnp

        # Stream-as-argument, same HTTP-413 rule as the fused tasks.
        @jax.jit
        def chained(p, chunks, s):
            p = _jitter(p, s)

            def body(c, _):
                st = run_once({**env, "params": p, "chunks": chunks}, value)
                return c + st.loglik * 1e-9, None

            c, _ = jax.lax.scan(
                body, jnp.float32(0), None, length=cfg.chain
            )
            return c

        return lambda s: float(
            jax.device_get(chained(env["params"], env["chunks"], jnp.int32(s)))
        )

    return Task(
        name="engine.fb_chunked", family="fb.reduced",
        costs_entries=("em.chunked.onehot", "em.chunked.xla"),
        legacy=legacy,
        candidates=lambda cfg: ["onehot", "xla"],
        feasibility=lambda value, cfg: None,
        build=build, run_once=run_once, parity_err=_stats_rel_err,
        parity_tol=STATS_REL_TOL, make_chained=make_chained,
        ceiling_key="em",
    )


# -- the registry -------------------------------------------------------------


def all_tasks() -> list:
    return [
        _lane_task(),
        _t_tile_seq_task(),
        _flat_block_task(scores=False),
        _flat_block_task(scores=True),
        _fused_task("posterior"),
        _fused_task("em_seq"),
        _fused_task("em_chunked"),
        _one_pass_task("posterior"),
        _one_pass_task("em_seq"),
        _stacked_task("em_family"),
        _stacked_task("compare"),
        _stacked_task("serve_decode"),
        _engine_task(),
    ]


# The --smoke slice: one kernel family per engine — reduced FB (lane sweep
# + a fused verdict), stacked, and flat decode — each completing the full
# prune -> parity-gate -> time -> persist cycle on CPU.
SMOKE_TASKS = (
    "lane.onehot.long",
    "t_tile.em_seq",
    "flat.block.scores",
    "fused.em_chunked",
    "one_pass.posterior",
    "stacked.em_family",
)


def tasks_by_name(names=None, prefix: Optional[str] = None) -> list:
    tasks = all_tasks()
    if names is not None:
        want = set(names)
        missing = want - {t.name for t in tasks}
        if missing:
            raise KeyError(
                f"unknown tune task(s) {sorted(missing)} "
                f"(have: {sorted(t.name for t in tasks)})"
            )
        tasks = [t for t in tasks if t.name in want]
    if prefix:
        tasks = [t for t in tasks if t.name.startswith(prefix)]
    return tasks
