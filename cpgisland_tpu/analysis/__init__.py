"""graftcheck: static analysis enforcing this codebase's TPU invariants.

Two layers (LINT.md is the rule catalogue):

- **AST lint** (:mod:`~cpgisland_tpu.analysis.core` + the ``rules_*``
  modules) — pure-``ast`` checkers for the project rules that otherwise
  fail only at runtime, on real TPU, or at genome scale: jit closures over
  array constants, Mosaic sublane alignment, hot-path host syncs, max-plus
  normalization, stats-in-backward-chain, retrace hazards, plus two
  hygiene rules.  No tracing, no devices (the analysis modules import no
  jax of their own; the parent package import is the only cost) — the
  whole package lints in well under a second.
- **jaxpr contracts** (:mod:`~cpgisland_tpu.analysis.contracts`) — traces
  the registered decode/posterior/EM entry points on abstract inputs (CPU,
  no TPU needed) and asserts graph-level contracts: no f64 on device
  paths, no callbacks in hot graphs, reduced/pallas engines stay
  pallas-free off-TPU (the interpreter pathology), and dispatch-surface
  stability via ``obs.no_new_compiles``.
- **cost contracts** (:mod:`~cpgisland_tpu.analysis.costmodel` +
  :mod:`~cpgisland_tpu.analysis.cost_contracts`, "graftcost") — the same
  traces measured: per-primitive FLOP/byte/serial-depth fingerprints at
  two geometries, decomposed per-symbol vs fixed, locked in the committed
  ``COSTS.json`` and diffed in CI (``--costs`` / ``--update-costs``),
  plus quantitative contracts (no dense-pair ops on reduced paths,
  bounded fused-EM fixed share, documented pass structure, lane-scaled
  serial depth).

CLI: ``python -m cpgisland_tpu.analysis [paths...]`` (or
``tools/graftcheck.py``); exits non-zero on violations.  Inline waivers:
``# graftcheck: allow(<rule>) -- <reason>``.
"""

from cpgisland_tpu.analysis.core import (  # noqa: F401  (public re-exports)
    FileContext,
    Finding,
    LintResult,
    all_rules,
    lint_file,
    run_lint,
)
