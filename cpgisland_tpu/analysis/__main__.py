import sys

from cpgisland_tpu.analysis.cli import main

sys.exit(main())
