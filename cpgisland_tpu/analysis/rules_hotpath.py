"""R3 ``hot-path-host-sync``: banned blocking fetches in registered hot paths.

Every blocking device->host sync on this setup pays a 50-100 ms relay
round trip (CLAUDE.md), so the decode/posterior/EM driver loops must
either avoid host syncs or route the ones they genuinely need through
``obs.note_fetch`` — which both documents the sync as intentional and
makes the dispatch ledger count it (PR 1).  Inside a registered hot path
(see :mod:`cpgisland_tpu.analysis.config` and the ``# graftcheck:
hot-path`` marker) this rule flags:

- ``x.item()``
- ``float(x)`` / ``int(x)`` on a non-literal (implicit scalar fetch)
- ``np.asarray(x)`` (the canonical fetch spelling)
- ``jax.block_until_ready`` / ``jax.device_get``

unless the call sits inside an ``obs.note_fetch(...)`` /
``obs.note_upload(...)`` wrapper expression.  Intentional unrouted syncs
carry an inline waiver naming why the round trip is unavoidable.

Precision carve-outs (a linter nobody trusts is worse than none):

- ``np.asarray(x)`` where ``x`` is rooted at a parameter of the hot
  function is host-input coercion at the API boundary, not a device
  fetch, and passes; so does ``np.asarray`` of a name assigned from a
  list/tuple literal or comprehension (already-host data);
- ``float()``/``int()`` flag only when the argument *itself computes on
  device* — it contains a ``jnp.*``/``jax.*`` call or a method call like
  ``x.min()`` — because ``float(already_fetched_scalar)`` is free and
  pervasive after a routed fetch.
"""

from __future__ import annotations

import ast
from typing import Iterator

from cpgisland_tpu.analysis import astutil
from cpgisland_tpu.analysis.core import FileContext, Finding, register

BANNED_CALLS = frozenset({
    "np.asarray", "numpy.asarray",
    "jax.block_until_ready", "jax.device_get",
})
NOTE_WRAPPERS = ("note_fetch", "note_upload")
SCALAR_CASTS = frozenset({"float", "int"})


def _routed_through_note(node: ast.AST) -> bool:
    for p in astutil.parents(node):
        if isinstance(p, ast.Call):
            fn = p.func
            name = fn.attr if isinstance(fn, ast.Attribute) else (
                fn.id if isinstance(fn, ast.Name) else None
            )
            if name in NOTE_WRAPPERS:
                return True
        elif isinstance(p, (ast.stmt,)) and not isinstance(p, ast.Expr):
            # Stop at the enclosing statement boundary (assignments etc.
            # still count as the same expression tree, so only break once
            # we leave expression context entirely).
            break
    return False


def _root_name(node: ast.AST):
    """The root Name of an expression like ``obs[0]``, ``params.log_B``,
    or ``conf.sum(...)`` (method calls unwrap to their receiver)."""
    while True:
        if isinstance(node, (ast.Subscript, ast.Attribute, ast.Starred)):
            node = node.value
        elif isinstance(node, ast.Call) and isinstance(node.func, ast.Attribute):
            node = node.func.value
        else:
            break
    return node.id if isinstance(node, ast.Name) else None


def _host_rooted(ctx: FileContext, use_site: ast.AST, arg: ast.AST) -> bool:
    """Arg is rooted at a parameter of an enclosing function (input coercion
    at an API/helper boundary — a device value crossing that boundary had
    its sync counted at its producer) or at a name assigned from a
    list/tuple/dict literal or comprehension (already-host data)."""
    root = _root_name(arg)
    if root is None:
        return False
    for fn in astutil.enclosing_functions(use_site):
        if root in {p.arg for p in astutil.func_params(fn)}:
            return True
        v = astutil.single_assignments(fn).get(root)
        if isinstance(
            v, (ast.List, ast.Tuple, ast.Dict, ast.ListComp, ast.DictComp,
                ast.GeneratorExp)
        ):
            return True
        if root in astutil.bound_names(fn):
            return False  # bound here to something non-literal: judged live
    return False


def _computes_on_device(ctx: FileContext, arg: ast.AST) -> bool:
    """Does the cast argument itself do device work — a jnp./jax. call or a
    method call (``x.min()``) anywhere inside it?"""
    for node in ast.walk(arg):
        if not isinstance(node, ast.Call):
            continue
        name = ctx.call_name(node) or ""
        if name.startswith(("jnp.", "jax.", "jax.numpy.")):
            return True
        if isinstance(node.func, ast.Attribute) and node.func.attr in (
            "min", "max", "sum", "mean", "prod", "argmax", "argmin", "all",
            "any", "item",
        ):
            return True
    return False


def _arg_already_fetched(ctx: FileContext, arg: ast.AST) -> bool:
    """float()/int() on a value that is ALREADY a host fetch result is free;
    the inner fetch call is what gets judged (or flagged) on its own."""
    if not isinstance(arg, ast.Call):
        return False
    fn = arg.func
    name = fn.attr if isinstance(fn, ast.Attribute) else (
        fn.id if isinstance(fn, ast.Name) else None
    )
    if name in NOTE_WRAPPERS:
        return True
    canonical = ctx.call_name(arg)
    return canonical is not None and astutil.matches(canonical, BANNED_CALLS)


def _hot_function_nodes(ctx: FileContext):
    for node in ast.walk(ctx.tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                and node.name in ctx.hot_functions:
            yield node


@register(
    "hot-path-host-sync",
    "no .item()/float()/np.asarray/block_until_ready/device_get inside "
    "registered hot paths unless routed through obs.note_fetch",
    origin="CLAUDE.md: each blocking dispatch pays ~50-100 ms relay RTT; "
    "obs.note_fetch documents + ledger-counts the intentional ones",
)
def check_hot_path_host_sync(ctx: FileContext) -> Iterator[Finding]:
    seen: set[int] = set()
    for hot in _hot_function_nodes(ctx):
        for node in ast.walk(hot):
            if not isinstance(node, ast.Call) or id(node) in seen:
                continue
            msg = None
            if isinstance(node.func, ast.Attribute) and node.func.attr == "item" \
                    and not node.args:
                msg = ".item() blocks on a device->host scalar fetch"
            else:
                name = ctx.call_name(node)
                if name is not None and astutil.matches(name, BANNED_CALLS):
                    short = name.rsplit(".", 1)[-1]
                    if not (short == "asarray" and node.args
                            and _host_rooted(ctx, node, node.args[0])):
                        msg = f"{short}() is a blocking host sync"
                elif isinstance(node.func, ast.Name) \
                        and node.func.id in SCALAR_CASTS and node.args \
                        and not isinstance(node.args[0], ast.Constant) \
                        and not _arg_already_fetched(ctx, node.args[0]) \
                        and not _host_rooted(ctx, node, node.args[0]) \
                        and _computes_on_device(ctx, node.args[0]):
                    msg = (
                        f"{node.func.id}() on a device-computed value is an "
                        "implicit blocking scalar fetch"
                    )
            if msg is None or _routed_through_note(node):
                continue
            seen.add(id(node))
            yield ctx.finding(
                "hot-path-host-sync",
                node,
                f"hot path {hot.name!r}: {msg}; route it through "
                "obs.note_fetch(...) or waive with the reason the round "
                "trip is unavoidable",
            )
