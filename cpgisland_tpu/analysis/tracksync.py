"""graftsync runtime tracker: a mini-TSan for the serve paths.

The static rules (:mod:`rules_sync`) prove lock discipline on the AST; this
module validates the same model under a REAL concurrent load.  An installed
:class:`LockTracker` patches the ``threading.Lock`` / ``RLock`` /
``Condition`` factories so every lock created inside the install window is
wrapped with bookkeeping (locks created before install are untouched):

- **lock-order recording** — each acquire of B while holding A records an
  ``A -> B`` edge with the acquiring site; :meth:`LockTracker.cycles`
  reports cycles in the observed order graph (the dynamic twin of
  ``synccheck``'s static graph — an inversion that only manifests under a
  particular interleaving still shows up here, because BOTH orders were
  observed even if they never overlapped in time).
- **guarded-access recording** — :meth:`LockTracker.watch_attrs` installs
  checking descriptors for chosen attributes of a watched instance: every
  get/set on a watched object asserts the guarding lock is held by the
  current thread and records a violation otherwise (reads and writes that
  the static rule waived or missed surface here).

Opt-in only: nothing is patched at import.  Tests install around the code
under test (the serve-mux stress test), or set ``CPGISLAND_TRACKSYNC=1``
to have ``tests/conftest.py`` install a session-wide tracker.  Like the
rest of the analysis package, this module imports no jax.
"""

from __future__ import annotations

import dataclasses
import os
import sys
import threading
import weakref
from typing import Optional

# Real primitives captured BEFORE any patching: the tracker's own state is
# guarded by an unwrapped lock (a tracked internal lock would recurse).
_REAL_LOCK = threading.Lock
_REAL_RLOCK = threading.RLock
_REAL_CONDITION = threading.Condition

_TRACKER_FILES = (os.path.abspath(__file__), threading.__file__)


def _call_site() -> str:
    """file:line of the nearest frame outside this module and threading."""
    f = sys._getframe(1)
    while f is not None:
        fn = f.f_code.co_filename
        if os.path.abspath(fn) not in _TRACKER_FILES and "threading" not in fn:
            return f"{os.path.basename(fn)}:{f.f_lineno}"
        f = f.f_back
    return "<unknown>"


@dataclasses.dataclass
class Violation:
    kind: str  # "lock-order-cycle" | "guarded-access"
    message: str


class _Tracked:
    """Shared bookkeeping half of the wrappers."""

    def __init__(self, tracker: "LockTracker", kind: str):
        self.tracker = tracker
        self.kind = kind
        self.name = f"{kind}@{_call_site()}"
        tracker._register(self)

    # identity used in held lists / edges: the wrapper object itself.


class TrackedLock(_Tracked):
    def __init__(self, tracker, kind="Lock", inner=None):
        super().__init__(tracker, kind)
        self._inner = inner if inner is not None else (
            _REAL_RLOCK() if kind == "RLock" else _REAL_LOCK()
        )

    def acquire(self, blocking=True, timeout=-1):
        got = self._inner.acquire(blocking, timeout)
        if got:
            self.tracker._note_acquire(self)
        return got

    def release(self):
        self.tracker._note_release(self)
        self._inner.release()

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc):
        self.release()
        return False

    def locked(self):
        return self._inner.locked()

    # RLock protocol bits some library code touches (real Condition over a
    # tracked RLock); delegate so semantics stay exact.
    def _is_owned(self):
        inner = self._inner
        if hasattr(inner, "_is_owned"):
            return inner._is_owned()
        if inner.acquire(False):
            inner.release()
            return False
        return True

    def _release_save(self):
        self.tracker._note_release(self)
        if hasattr(self._inner, "_release_save"):
            return self._inner._release_save()
        self._inner.release()
        return None

    def _acquire_restore(self, state):
        if hasattr(self._inner, "_acquire_restore"):
            self._inner._acquire_restore(state)
        else:
            self._inner.acquire()
        self.tracker._note_acquire(self)


class TrackedCondition(_Tracked):
    """Condition wrapper.  Built over a :class:`TrackedLock`, the condition
    IS that lock for ordering purposes (one mutex); built bare, it owns a
    fresh tracked RLock — exactly threading.Condition's semantics."""

    def __init__(self, tracker, lock=None):
        if isinstance(lock, TrackedLock):
            self._lockid = lock
            inner_lock = lock._inner
        elif lock is not None:  # an untracked caller-supplied lock
            self._lockid = None
            inner_lock = lock
        else:
            self._lockid = TrackedLock(tracker, "RLock")
            inner_lock = self._lockid._inner
        super().__init__(tracker, "Condition")
        if self._lockid is not None:
            # Ordering identity is the underlying mutex, not the cv object.
            self.name = self._lockid.name
        self._inner = _REAL_CONDITION(inner_lock)

    def _ident(self):
        return self._lockid if self._lockid is not None else self

    def acquire(self, *a, **k):
        got = self._inner.acquire(*a, **k)
        if got:
            self.tracker._note_acquire(self._ident())
        return got

    def release(self):
        self.tracker._note_release(self._ident())
        self._inner.release()

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc):
        self.release()
        return False

    def wait(self, timeout=None):
        # wait releases the mutex and re-acquires before returning: mirror
        # that in the held bookkeeping (a re-acquire while holding OTHER
        # locks is a real ordering event and is recorded as such).
        self.tracker._note_release(self._ident())
        try:
            return self._inner.wait(timeout)
        finally:
            self.tracker._note_acquire(self._ident())

    def wait_for(self, predicate, timeout=None):
        self.tracker._note_release(self._ident())
        try:
            return self._inner.wait_for(predicate, timeout)
        finally:
            self.tracker._note_acquire(self._ident())

    def notify(self, n=1):
        self._inner.notify(n)

    def notify_all(self):
        self._inner.notify_all()


# Sentinel distinguishing "class had no attribute" from a genuine None
# class-level default (both must round-trip through uninstall correctly).
_MISSING = object()


class _GuardedDescriptor:
    """Class-level data descriptor checking lock ownership on watched
    instances; unwatched instances of the same class pass through.
    Installed by :meth:`LockTracker.watch_attrs` and REMOVED (prior class
    attribute restored) by the tracker's uninstall."""

    def __init__(self, attr: str, prior):
        self.attr = attr
        self.prior = prior  # _MISSING or the shadowed class attribute

    def __get__(self, obj, objtype=None):
        if obj is None:
            return self
        reg = _WATCHED.get(id(obj))
        if reg is not None:
            reg.check(obj, self.attr, "read")
        try:
            return obj.__dict__[self.attr]
        except KeyError:
            if self.prior is not _MISSING:  # pre-existing class-level default
                return self.prior
            raise AttributeError(self.attr) from None

    def __set__(self, obj, value):
        reg = _WATCHED.get(id(obj))
        if reg is not None:
            reg.check(obj, self.attr, "write")
        obj.__dict__[self.attr] = value

    def __delete__(self, obj):
        reg = _WATCHED.get(id(obj))
        if reg is not None:
            reg.check(obj, self.attr, "write")
        try:
            del obj.__dict__[self.attr]
        except KeyError:
            raise AttributeError(self.attr) from None


# id(instance) -> _WatchEntry; module-level so descriptors can reach it
# without holding a reference cycle through the tracker.
_WATCHED: dict[int, "_WatchEntry"] = {}


class _WatchEntry:
    def __init__(self, tracker: "LockTracker", lock, label: str):
        self.tracker = tracker
        self.lock = lock
        self.label = label

    def check(self, obj, attr: str, op: str) -> None:
        self.tracker._check_guarded(self, obj, attr, op)


class LockTracker:
    """See module docstring.  One instance per install window."""

    def __init__(self):
        self._mu = _REAL_LOCK()
        self._tls = threading.local()
        self.locks: list = []
        # (src name, dst name) -> first site observed
        self.edges: dict[tuple[str, str], str] = {}
        self.acquires = 0
        self.guarded_checks = 0
        self._violations: list[Violation] = []
        self._watch_refs: list = []
        # (cls, attr) of every descriptor THIS tracker installed, so
        # uninstall can restore the shadowed class attributes — a leaked
        # descriptor would keep routing every later instance of the class
        # through a dead tracker's checks for the rest of the process.
        self._installed_descriptors: list = []

    # -- lock bookkeeping ----------------------------------------------------

    def _register(self, lk) -> None:
        with self._mu:
            self.locks.append(weakref.ref(lk))

    def _held(self) -> list:
        held = getattr(self._tls, "held", None)
        if held is None:
            held = self._tls.held = []
        return held

    def _note_acquire(self, lk) -> None:
        held = self._held()
        site = _call_site()
        if lk not in held:
            with self._mu:
                self.acquires += 1
                for h in held:
                    if h is not lk:
                        self.edges.setdefault((h.name, lk.name), site)
        held.append(lk)

    def _note_release(self, lk) -> None:
        held = self._held()
        for i in range(len(held) - 1, -1, -1):
            if held[i] is lk:
                del held[i]
                return

    def held_by_me(self, lk) -> bool:
        if isinstance(lk, TrackedCondition):
            lk = lk._ident()
        return lk in self._held()

    # -- guarded access ------------------------------------------------------

    def watch_attrs(self, obj, lock, attrs, label: Optional[str] = None):
        """Install guarded-access checking for ``attrs`` of ``obj`` (which
        must be guarded by ``lock`` — a tracked Lock/Condition created
        inside the install window)."""
        if isinstance(lock, TrackedCondition):
            lock = lock._ident()
        if not isinstance(lock, TrackedLock):
            raise TypeError(
                "watch_attrs needs a tracked lock (create the watched "
                "object while the tracker is installed)"
            )
        cls = type(obj)
        for attr in attrs:
            cur = cls.__dict__.get(attr, _MISSING)
            if not isinstance(cur, _GuardedDescriptor):
                setattr(cls, attr, _GuardedDescriptor(attr, cur))
                self._installed_descriptors.append((cls, attr))
        entry = _WatchEntry(self, lock, label or cls.__name__)
        _WATCHED[id(obj)] = entry
        self._watch_refs.append((weakref.ref(obj, self._unwatch(id(obj))),
                                 cls, tuple(attrs)))
        return entry

    @staticmethod
    def _unwatch(key: int):
        def cb(_ref):
            _WATCHED.pop(key, None)

        return cb

    def unwatch_all(self) -> None:
        """Remove every guarded-access descriptor this tracker installed,
        restoring the shadowed class attributes (called by uninstall)."""
        for cls, attr in self._installed_descriptors:
            desc = cls.__dict__.get(attr)
            if not isinstance(desc, _GuardedDescriptor):
                continue  # someone else already replaced it
            if desc.prior is _MISSING:
                delattr(cls, attr)
            else:
                setattr(cls, attr, desc.prior)
        self._installed_descriptors.clear()
        for ref, _cls, _attrs in self._watch_refs:
            obj = ref()
            if obj is not None:
                _WATCHED.pop(id(obj), None)
        self._watch_refs.clear()

    def _check_guarded(self, entry: _WatchEntry, obj, attr, op) -> None:
        with self._mu:
            self.guarded_checks += 1
        if not self.held_by_me(entry.lock):
            site = _call_site()
            with self._mu:
                self._violations.append(Violation(
                    "guarded-access",
                    f"{op} of {entry.label}.{attr} at {site} on thread "
                    f"{threading.current_thread().name!r} without holding "
                    f"{entry.lock.name}",
                ))

    # -- reporting -----------------------------------------------------------

    def cycles(self) -> list[list[str]]:
        with self._mu:
            edges = dict(self.edges)
        adj: dict[str, list[str]] = {}
        for (src, dst) in edges:
            adj.setdefault(src, []).append(dst)
        seen: set = set()
        out: list[list[str]] = []

        def dfs(start, cur, path, on_path):
            for nxt in adj.get(cur, ()):
                if nxt == start:
                    key = frozenset(path + [nxt])
                    if key not in seen:
                        seen.add(key)
                        out.append(path + [nxt, start])
                elif nxt not in on_path:
                    dfs(start, nxt, path + [nxt], on_path | {nxt})

        for node in adj:
            dfs(node, node, [node], {node})
        return out

    def violations(self) -> list[Violation]:
        with self._mu:
            out = list(self._violations)
        for cyc in self.cycles():
            sites = {
                f"{a}->{b}: {self.edges.get((a, b), '?')}"
                for a, b in zip(cyc, cyc[1:])
            }
            out.append(Violation(
                "lock-order-cycle",
                "observed lock-order cycle " + " -> ".join(cyc)
                + " (" + "; ".join(sorted(sites)) + ")",
            ))
        return out

    def assert_clean(self) -> None:
        bad = self.violations()
        if bad:
            raise AssertionError(
                "graftsync runtime tracker found violations:\n"
                + "\n".join(f"  [{v.kind}] {v.message}" for v in bad)
            )

    def summary(self) -> dict:
        n_cycles = len(self.cycles())  # takes _mu itself: compute first
        with self._mu:
            return {
                "locks": sum(1 for r in self.locks if r() is not None),
                "acquires": self.acquires,
                "edges": sorted(f"{a} -> {b}" for (a, b) in self.edges),
                "guarded_checks": self.guarded_checks,
                "violations": len(self._violations) + n_cycles,
            }


_INSTALLED: Optional[LockTracker] = None


def current() -> Optional[LockTracker]:
    return _INSTALLED


def install(tracker: Optional[LockTracker] = None):
    """Patch the threading lock factories to produce tracked locks feeding
    ``tracker``; returns ``(tracker, uninstall)``.  One install at a time."""
    global _INSTALLED
    if _INSTALLED is not None:
        raise RuntimeError("a LockTracker is already installed")
    tracker = tracker if tracker is not None else LockTracker()

    def make_lock():
        return TrackedLock(tracker, "Lock")

    def make_rlock():
        return TrackedLock(tracker, "RLock")

    def make_condition(lock=None):
        return TrackedCondition(tracker, lock)

    threading.Lock = make_lock
    threading.RLock = make_rlock
    threading.Condition = make_condition
    _INSTALLED = tracker

    def uninstall() -> None:
        global _INSTALLED
        threading.Lock = _REAL_LOCK
        threading.RLock = _REAL_RLOCK
        threading.Condition = _REAL_CONDITION
        tracker.unwatch_all()
        _INSTALLED = None

    return tracker, uninstall


def ensure_installed():
    """The active tracker (env/fixture mode) or a fresh install.  Returns
    ``(tracker, uninstall)`` where ``uninstall`` is a no-op when reusing an
    already-installed tracker (its owner uninstalls)."""
    if _INSTALLED is not None:
        return _INSTALLED, lambda: None
    return install()
