"""graftcheck Layer 6 — the scale-invariance dataflow model (graftscale).

The r9/r17 pass collapse rests on one invariant no earlier layer can see:
the co-scheduled backward SELF-NORMALIZES (it divides by its own previous
sum, not the forward's cs), so fused/one-pass betas are per-position
*directions* and every consumer downstream must be scale-free — the znorm
stats kernel, the conf ratio, the MPM argmax.  The one known violation
class (pairing the cs-scaled chunked stats kernel with self-normalized
betas) lived only as a CLAUDE.md comment ("that pairing is a bug").  This
module turns the comment into dataflow: an abstract interpretation over
jaxprs that assigns every intermediate a *scale type* with respect to a
tagged input and certifies the declared signature of each consumer.

The abstract domain (positive homogeneity degrees):

- ``Deg(k)`` — positively homogeneous of degree ``k``: scaling the tagged
  input by ``c > 0`` scales this value by ``c**k``.  ``Deg(0)`` is
  scale-FREE (constants, and anything whose tagged scale collapsed
  through a ratio / normalize / argmax).
- ``ANY`` — degree-polymorphic: exact zeros and tiny guard literals
  (``jnp.maximum(z, 1e-30)``); joins with every ``Deg(k)`` as that
  ``Deg(k)``.  Without this element every guarded normalizer would
  poison to MIXED.
- ``MIXED`` — not positively homogeneous (e.g. ``x + 1`` of a tagged
  ``x``, ``log`` of a degree-1 value, a scan carry with no fixed-point
  degree).  Carries the provenance of the equation that broke it.

Propagation is the closed primitive set the FB/decode graphs actually
use: mul/div/dot add/subtract degrees, sums and maxima preserve them,
same-degree add/select joins, exp/log admit only degree 0, comparisons
and argmax of uniform-degree operands collapse to degree 0, and loop
carries (scan/while) must reach a degree FIXED POINT — a carry whose
degree grows per iteration is reported MIXED with the loop named.

Two rule modes share the engine:

- ``mode="linear"`` — probability space.  The tag is a multiplicative
  scaling of the tagged tensor (the reduced beta streams).
- ``mode="maxplus"`` — log space for the decode chains.  The tag is an
  additive OFFSET (a shift of ``log_pi``); ``add``/``sub`` take the
  mul/div roles (degree add/subtract), ``max``/argmax take the
  join/collapse roles, and true-score returns certify degree 1 (scores
  shift by exactly the offset) while paths certify degree 0.

No jax at module level: :func:`analyze` imports it lazily, so the lint
layer and ``--list-rules`` never pay a backend init.
"""

from __future__ import annotations

import dataclasses
from fractions import Fraction
from typing import Optional

# Literals with magnitude at or below this are numerical guards
# (LOG_ZERO-adjacent epsilons, the 1e-30 normalizer floors), classified
# degree-polymorphic rather than degree-0 so ``maximum(z, eps)`` keeps
# z's degree instead of poisoning to MIXED.
GUARD_EPS = 1e-20

_ANY = "any"
_DEG = "deg"
_MIXED = "mixed"


@dataclasses.dataclass(frozen=True)
class Scale:
    """Abstract scale of one value w.r.t. the tagged input."""

    kind: str                      # "any" | "deg" | "mixed"
    deg: Optional[Fraction] = None  # set iff kind == "deg"
    why: Optional[str] = None       # provenance iff kind == "mixed"

    def describe(self) -> str:
        if self.kind == _ANY:
            return "any"
        if self.kind == _MIXED:
            return "mixed"
        if self.deg == 0:
            return "free"
        d = self.deg
        return f"deg:{d.numerator}" if d.denominator == 1 else f"deg:{d}"

    @property
    def is_free(self) -> bool:
        """Scale-free: invariant under tagged-input scaling."""
        return self.kind == _ANY or (self.kind == _DEG and self.deg == 0)

    @property
    def tagged(self) -> bool:
        """Carries a nonzero tagged degree (or worse)."""
        return not self.is_free


ANY = Scale(_ANY)
FREE = Scale(_DEG, Fraction(0))


def DEG(k) -> Scale:
    k = Fraction(k)
    return FREE if k == 0 else Scale(_DEG, k)


def MIXED(why: str) -> Scale:
    return Scale(_MIXED, why=why)


def join(a: Scale, b: Scale, why: str = "join of differing degrees") -> Scale:
    """Least upper bound: the scale of a value that may be either input
    (select branches, concatenated operands, add of same-degree terms)."""
    if a.kind == _MIXED:
        return a
    if b.kind == _MIXED:
        return b
    if a.kind == _ANY:
        return b
    if b.kind == _ANY:
        return a
    if a.deg == b.deg:
        return a
    return MIXED(why)


def join_all(scales, why: str = "join of differing degrees") -> Scale:
    out = ANY
    for s in scales:
        out = join(out, s, why)
    return out


# ---------------------------------------------------------------------------
# Equation provenance (the costmodel convention: file:function of the
# user-frame that emitted the primitive).


def _user_frame(eqn) -> str:
    """'file:line:function' of the user frame that emitted this equation
    (the costmodel attribution convention, plus the line)."""
    try:
        from jax._src import source_info_util

        frame = source_info_util.user_frame(eqn.source_info)
        if frame is None:
            return "<jax>"
        fname = frame.file_name.rsplit("/", 1)[-1]
        return f"{fname}:{frame.start_line}:{frame.function_name}"
    except Exception:
        return "<unknown>"


# ---------------------------------------------------------------------------
# The rule table.  Handlers get (state, eqn, in_scales) and return a list of
# output scales.  A missing entry falls back to the soundness default:
# untagged inputs -> FREE outputs for ANY primitive (a computation that
# never touches the tagged value is constant under the tag), tagged inputs
# through an unmodeled primitive -> MIXED naming it.


class _State:
    def __init__(self, mode: str):
        self.mode = mode
        self.findings: list = []


def _why(eqn, reason: str) -> str:
    return f"{reason} in '{eqn.primitive.name}' @ {_user_frame(eqn)}"


def _inherit_mixed(ins):
    for s in ins:
        if s.kind == _MIXED:
            return s
    return None


def _r_degree_add(st, eqn, ins):
    """mul / dot_general (linear), add / sub-as-add (maxplus): degrees add."""
    m = _inherit_mixed(ins)
    if m:
        return [m]
    if any(s.kind == _ANY for s in ins):
        return [ANY]
    return [DEG(sum((s.deg for s in ins), Fraction(0)))]


def _r_degree_sub(st, eqn, ins):
    """div (linear), sub (maxplus): degree difference.  The ratio collapse:
    Deg(1)/Deg(1) -> FREE is how normalizers erase the tagged scale."""
    m = _inherit_mixed(ins)
    if m:
        return [m]
    a, b = ins
    if a.kind == _ANY:
        return [ANY]
    if b.kind == _ANY:
        # Dividing BY an exact zero/guard literal: the guard is a stand-in
        # for a same-degree quantity only when it appears under max(); a
        # bare guarded denominator is degree-0 in practice (eps literal).
        return [a]
    return [DEG(a.deg - b.deg)]


def _r_linear(st, eqn, ins):
    """Degree-preserving joins: add/sub/max/min (linear), reduce_sum/max,
    cumsum/cummax, concatenate, pad, clamp — same degree in, same out."""
    m = _inherit_mixed(ins)
    if m:
        return [m]
    return [join_all(ins, _why(eqn, "operands of differing degree"))]


def _r_collapse(st, eqn, ins):
    """argmax/argmin/sign/is_finite and comparisons: uniform-degree inputs
    collapse to FREE (the decision is invariant under c > 0 scaling)."""
    m = _inherit_mixed(ins)
    if m:
        return [m]
    j = join_all(ins, "")
    if j.kind == _MIXED:
        return [MIXED(_why(eqn, "comparison across differing degrees"))]
    return [FREE]


def _r_select(st, eqn, ins):
    """select_n(pred, *cases): pred must be scale-safe; result joins cases."""
    pred, cases = ins[0], ins[1:]
    if pred.kind == _MIXED:
        return [pred]
    m = _inherit_mixed(cases)
    if m:
        return [m]
    return [join_all(cases, _why(eqn, "select branches of differing degree"))]


def _r_exp_like(st, eqn, ins):
    """exp/log/tanh/...: transcendental — only degree-0 passes through."""
    m = _inherit_mixed(ins)
    if m:
        return [m]
    if all(s.is_free for s in ins):
        return [FREE]
    return [MIXED(_why(eqn, "transcendental of a tagged value"))]


def _r_preserve(st, eqn, ins):
    """Shape/layout ops: the (single data) operand's scale passes through."""
    return [ins[0]]


def _r_free(st, eqn, ins):
    m = _inherit_mixed(ins)
    if m:
        return [m]
    return [FREE]


def _r_neg(st, eqn, ins):
    if st.mode == "maxplus":
        s = ins[0]
        if s.kind == _DEG:
            return [DEG(-s.deg)]
        return [s]
    return [ins[0]]


def _r_integer_pow(st, eqn, ins):
    s = ins[0]
    if s.kind != _DEG:
        return [s]
    y = eqn.params.get("y", 1)
    if st.mode == "maxplus" and s.deg != 0:
        return [MIXED(_why(eqn, "power of a tagged log-space value"))]
    return [DEG(s.deg * y)]


def _r_sqrt(st, eqn, ins):
    s = ins[0]
    if s.kind != _DEG:
        return [s]
    if st.mode == "maxplus" and s.deg != 0:
        return [MIXED(_why(eqn, "sqrt of a tagged log-space value"))]
    return [DEG(s.deg / 2)]


def _r_rsqrt(st, eqn, ins):
    s = ins[0]
    if s.kind != _DEG:
        return [s]
    if st.mode == "maxplus" and s.deg != 0:
        return [MIXED(_why(eqn, "rsqrt of a tagged log-space value"))]
    return [DEG(-s.deg / 2)]


def _r_convert(st, eqn, ins):
    s = ins[0]
    try:
        import numpy as np

        to_float = np.issubdtype(eqn.params["new_dtype"], np.floating)
    except Exception:
        to_float = True
    if to_float:
        return [s]
    # float -> int/bool truncation is only scale-safe for untagged values.
    if s.is_free or s.kind == _ANY:
        return [FREE]
    if s.kind == _MIXED:
        return [s]
    return [MIXED(_why(eqn, "integer cast of a tagged value"))]


def _r_round_like(st, eqn, ins):
    m = _inherit_mixed(ins)
    if m:
        return [m]
    if all(s.is_free for s in ins):
        return [FREE]
    return [MIXED(_why(eqn, "rounding/remainder of a tagged value"))]


def _r_gather(st, eqn, ins):
    operand, idx = ins[0], ins[1:]
    if any(s.tagged for s in idx):
        m = _inherit_mixed(idx)
        return [m if m else MIXED(_why(eqn, "tagged value used as gather index"))]
    return [operand]


def _r_scatter(st, eqn, ins):
    # scatter(operand, indices, updates): join operand/updates degrees.
    operand, idx, upd = ins[0], ins[1], ins[2]
    if idx.tagged:
        m = _inherit_mixed([idx])
        return [m if m else MIXED(_why(eqn, "tagged value used as scatter index"))]
    m = _inherit_mixed([operand, upd])
    if m:
        return [m]
    return [join(operand, upd, _why(eqn, "scatter operand/updates degree mismatch"))]


def _r_sort(st, eqn, ins):
    m = _inherit_mixed(ins)
    if m:
        return [m for _ in ins]
    return list(ins)


def _r_dus(st, eqn, ins):
    # dynamic_update_slice(operand, update, *starts)
    operand, upd, starts = ins[0], ins[1], ins[2:]
    if any(s.tagged for s in starts):
        return [MIXED(_why(eqn, "tagged value used as slice index"))]
    m = _inherit_mixed([operand, upd])
    if m:
        return [m]
    return [join(operand, upd, _why(eqn, "update slice of differing degree"))]


def _r_ds(st, eqn, ins):
    operand, starts = ins[0], ins[1:]
    if any(s.tagged for s in starts):
        return [MIXED(_why(eqn, "tagged value used as slice index"))]
    return [operand]


_LINEAR_JOIN = (
    "add", "sub", "max", "min", "reduce_sum", "reduce_max", "reduce_min",
    "cumsum", "cummax", "cummin", "concatenate", "pad", "clamp",
    "add_any",
)
_COLLAPSE = (
    "argmax", "argmin", "sign", "is_finite", "eq", "ne", "lt", "le", "gt",
    "ge", "reduce_and", "reduce_or",
)
_EXP_LIKE = (
    "exp", "exp2", "log", "log2", "log1p", "expm1", "tanh", "logistic",
    "erf", "erfc", "erf_inv", "sin", "cos", "atan2", "pow", "cbrt",
    "reduce_prod", "cumprod", "cumlogsumexp", "digamma", "lgamma",
)
_PRESERVE = (
    "broadcast_in_dim", "reshape", "transpose", "squeeze", "rev", "slice",
    "copy", "reduce_precision", "stop_gradient", "device_put", "real",
    "expand_dims", "split", "optimization_barrier",
)

_RULES_LINEAR = {}
_RULES_MAXPLUS = {}

for _n in ("mul", "dot_general"):
    _RULES_LINEAR[_n] = _r_degree_add
_RULES_LINEAR["div"] = _r_degree_sub
for _n in _LINEAR_JOIN:
    _RULES_LINEAR[_n] = _r_linear
for _n in _COLLAPSE:
    _RULES_LINEAR[_n] = _r_collapse
for _n in _EXP_LIKE:
    _RULES_LINEAR[_n] = _r_exp_like
for _n in _PRESERVE:
    _RULES_LINEAR[_n] = _r_preserve
_RULES_LINEAR.update({
    "select_n": _r_select, "neg": _r_neg, "abs": _r_preserve,
    "integer_pow": _r_integer_pow, "sqrt": _r_sqrt, "rsqrt": _r_rsqrt,
    "convert_element_type": _r_convert, "iota": _r_free,
    "floor": _r_round_like, "ceil": _r_round_like, "round": _r_round_like,
    "rem": _r_round_like, "nextafter": _r_round_like,
    "gather": _r_gather, "scatter": _r_scatter, "scatter-add": _r_scatter,
    "scatter_add": _r_scatter, "sort": _r_sort,
    "dynamic_update_slice": _r_dus, "dynamic_slice": _r_ds,
    "and": _r_collapse, "or": _r_collapse, "xor": _r_collapse,
    "not": _r_collapse,
})


def _r_square(st, eqn, ins):
    s = ins[0]
    if s.kind != _DEG:
        return [s]
    if st.mode == "maxplus" and s.deg != 0:
        return [MIXED(_why(eqn, "square of a tagged log-space value"))]
    return [DEG(s.deg * 2)]


_RULES_LINEAR["square"] = _r_square

def _r_mul_maxplus(st, eqn, ins):
    """max-plus mul/div: an offset-tagged value times a constant scales
    the OFFSET — not homogeneous — except multiplication by an exact zero
    (the ``v * 0.0`` shape-broadcast idiom), which erases the value."""
    m = _inherit_mixed(ins)
    if m:
        return [m]
    if any(s.kind == _ANY for s in ins):
        return [ANY]
    if all(s.is_free for s in ins):
        return [FREE]
    return [MIXED(_why(eqn, "product of a tagged log-space value"))]


# max-plus: add/sub take the mul/div roles; mul/dot of tagged values are
# no longer homogeneous (c * x scales the OFFSET, which only a constant
# could absorb); exp/log stay transcendental barriers.
_RULES_MAXPLUS = dict(_RULES_LINEAR)
_RULES_MAXPLUS.update({
    "add": _r_degree_add, "add_any": _r_degree_add,
    "sub": _r_degree_sub,
    "mul": _r_mul_maxplus, "dot_general": _r_mul_maxplus,
    "div": _r_mul_maxplus,
    "square": _r_exp_like, "integer_pow": _r_exp_like,
    "sqrt": _r_exp_like, "rsqrt": _r_exp_like,
    "reduce_sum": _r_exp_like, "cumsum": _r_exp_like,
    "exp": _r_exp_like, "log": _r_exp_like,
})
# max/min joins and comparisons keep their linear behavior (inherited).

_SCAN_MAX_ITERS = 8


# ---------------------------------------------------------------------------
# The interpreter.


class ScaleReport:
    """Result of one :func:`analyze` run."""

    def __init__(self, out_scales, mode):
        self.out_scales: list[Scale] = out_scales
        self.mode = mode

    def signature(self) -> list[str]:
        return [s.describe() for s in self.out_scales]


def _classify_const(val) -> Scale:
    import numpy as np

    try:
        arr = np.asarray(val)
    except Exception:
        return FREE
    if arr.dtype == object:
        return FREE
    if arr.size == 0:
        return ANY
    if np.issubdtype(arr.dtype, np.floating) or np.issubdtype(
            arr.dtype, np.complexfloating):
        a = np.abs(arr)
        if bool((a <= GUARD_EPS).all()):
            return ANY
    elif bool((arr == 0).all()):
        return ANY
    return FREE


def _sub_closed(params, key):
    j = params.get(key)
    return j


def _analyze_jaxpr(jaxpr, in_scales, const_scales, st: _State) -> list[Scale]:
    """Propagate scales through one (open) jaxpr; returns outvar scales."""
    import jax

    env: dict[int, Scale] = {}

    def read(atom) -> Scale:
        if isinstance(atom, jax.core.Literal):
            return _classify_const(atom.val)
        return env.get(id(atom), FREE)

    def write(var, s: Scale) -> None:
        env[id(var)] = s

    for v, s in zip(jaxpr.constvars, const_scales):
        write(v, s)
    for v, s in zip(jaxpr.invars, in_scales):
        write(v, s)

    rules = _RULES_MAXPLUS if st.mode == "maxplus" else _RULES_LINEAR

    for eqn in jaxpr.eqns:
        ins = [read(a) for a in eqn.invars]
        name = eqn.primitive.name
        outs: Optional[list[Scale]] = None

        if name in ("pjit", "closed_call", "core_call", "remat_call",
                    "custom_jvp_call", "custom_vjp_call", "checkpoint",
                    "remat", "custom_vjp_call_jaxpr", "xla_call"):
            sub = (_sub_closed(eqn.params, "jaxpr")
                   or _sub_closed(eqn.params, "call_jaxpr")
                   or _sub_closed(eqn.params, "fun_jaxpr"))
            if sub is not None:
                inner = getattr(sub, "jaxpr", sub)
                consts = [_classify_const(c)
                          for c in getattr(sub, "consts", [])]
                n_in = len(inner.invars)
                # custom_* calls may pass extra leading residuals; align
                # from the END (the data operands are trailing).
                use = ins[-n_in:] if len(ins) >= n_in else (
                    [FREE] * (n_in - len(ins)) + ins)
                outs = _analyze_jaxpr(inner, use, consts, st)
        elif name == "scan":
            outs = _analyze_scan(eqn, ins, st)
        elif name == "while":
            outs = _analyze_while(eqn, ins, st)
        elif name == "cond":
            outs = _analyze_cond(eqn, ins, st)
        elif name in rules:
            handler = rules[name]
            outs = handler(st, eqn, ins)
        if outs is None:
            # Soundness default: a primitive that never sees a tagged value
            # is constant under the tag; a tagged value through an
            # unmodeled primitive is MIXED, naming the primitive.
            m = _inherit_mixed(ins)
            if m is not None:
                outs = [m] * len(eqn.outvars)
            elif all(s.is_free for s in ins):
                outs = [FREE] * len(eqn.outvars)
            else:
                outs = [MIXED(_why(eqn, "unmodeled primitive"))] * len(
                    eqn.outvars)
        if len(outs) < len(eqn.outvars):
            outs = list(outs) + [outs[-1]] * (len(eqn.outvars) - len(outs))
        for v, s in zip(eqn.outvars, outs):
            write(v, s)

    return [read(v) for v in jaxpr.outvars]


def _loop_sub(params, key):
    sub = params[key]
    inner = getattr(sub, "jaxpr", sub)
    consts = [_classify_const(c) for c in getattr(sub, "consts", [])]
    return inner, consts


def _analyze_scan(eqn, ins, st: _State) -> list[Scale]:
    inner, consts = _loop_sub(eqn.params, "jaxpr")
    n_consts = eqn.params["num_consts"]
    n_carry = eqn.params["num_carry"]
    body_consts = ins[:n_consts]
    carry = list(ins[n_consts:n_consts + n_carry])
    xs = ins[n_consts + n_carry:]
    ys_out: list[Scale] = []
    for _ in range(_SCAN_MAX_ITERS):
        outs = _analyze_jaxpr(inner, body_consts + carry + xs, consts, st)
        new_carry = [join(c, o, "scan carry degree not a fixed point")
                     for c, o in zip(carry, outs[:n_carry])]
        ys_out = outs[n_carry:]
        if new_carry == carry:
            break
        carry = new_carry
    else:
        carry = [MIXED(_why(eqn, "scan carry degree not a fixed point"))
                 for _ in carry]
        outs = _analyze_jaxpr(inner, body_consts + carry + xs, consts, st)
        ys_out = outs[n_carry:]
    return carry + ys_out


def _analyze_while(eqn, ins, st: _State) -> list[Scale]:
    body, body_consts_s = _loop_sub(eqn.params, "body_jaxpr")
    cond, cond_consts_s = _loop_sub(eqn.params, "cond_jaxpr")
    cn = eqn.params["cond_nconsts"]
    bn = eqn.params["body_nconsts"]
    cond_consts = ins[:cn]
    body_consts = ins[cn:cn + bn]
    carry = list(ins[cn + bn:])
    for _ in range(_SCAN_MAX_ITERS):
        outs = _analyze_jaxpr(body, body_consts + carry, body_consts_s, st)
        new_carry = [join(c, o, "while carry degree not a fixed point")
                     for c, o in zip(carry, outs)]
        if new_carry == carry:
            break
        carry = new_carry
    else:
        carry = [MIXED(_why(eqn, "while carry degree not a fixed point"))
                 for _ in carry]
    # The cond must be scale-safe too: a tagged predicate changes the trip
    # count under scaling.
    pred = _analyze_jaxpr(cond, cond_consts + carry, cond_consts_s, st)
    if pred and pred[0].tagged:
        why = (pred[0].why if pred[0].kind == _MIXED
               else _why(eqn, "while predicate depends on tagged scale"))
        return [MIXED(why) for _ in carry]
    return carry


def _analyze_cond(eqn, ins, st: _State) -> list[Scale]:
    branches = eqn.params["branches"]
    idx, ops = ins[0], ins[1:]
    if idx.tagged:
        return [MIXED(_why(eqn, "cond index depends on tagged scale"))]
    branch_outs = []
    for br in branches:
        inner = getattr(br, "jaxpr", br)
        consts = [_classify_const(c) for c in getattr(br, "consts", [])]
        branch_outs.append(_analyze_jaxpr(inner, ops, consts, st))
    n_out = max(len(b) for b in branch_outs)
    out = []
    for i in range(n_out):
        out.append(join_all(
            (b[i] for b in branch_outs if i < len(b)),
            _why(eqn, "cond branches of differing degree")))
    return out


# ---------------------------------------------------------------------------
# Public API.


def analyze(closed, tagged, mode: str = "linear") -> ScaleReport:
    """Run the scale dataflow over a ClosedJaxpr.

    ``tagged``: iterable of flat invar indices carrying degree 1 (the beta
    stream in linear mode; the log-space offset in maxplus mode).  Returns
    a :class:`ScaleReport` whose ``out_scales`` align with the jaxpr's
    outvars.
    """
    st = _State(mode)
    jaxpr = closed.jaxpr
    tagged = frozenset(tagged)
    in_scales = [DEG(1) if i in tagged else FREE
                 for i in range(len(jaxpr.invars))]
    const_scales = [_classify_const(c) for c in closed.consts]
    outs = _analyze_jaxpr(jaxpr, in_scales, const_scales, st)
    return ScaleReport(outs, mode)


def trace_scales(fn, args, tagged_argnums, mode: str = "linear"):
    """Trace ``fn(*args)`` and analyze; returns (ScaleReport, ClosedJaxpr).

    ``tagged_argnums`` are POSITIONAL argument indices; arguments must be
    single arrays (the consumer-level entries pass flat streams, so the
    flat invar index equals the arg index).
    """
    import jax

    closed = jax.make_jaxpr(fn)(*args)
    n_args = len(args)
    flat_per_arg = []
    offset = 0
    for a in args:
        leaves = len(jax.tree_util.tree_leaves(a))
        flat_per_arg.append(range(offset, offset + leaves))
        offset += leaves
    if offset != len(closed.jaxpr.invars):
        raise ValueError(
            f"flat invar mismatch: {offset} leaves vs "
            f"{len(closed.jaxpr.invars)} invars")
    tagged = set()
    for i in tagged_argnums:
        if i >= n_args:
            raise ValueError(f"tagged argnum {i} out of range")
        tagged.update(flat_per_arg[i])
    return analyze(closed, tagged, mode=mode), closed


def out_provenance(closed) -> list[str]:
    """Per-outvar 'file:line:function' of the defining top-level equation
    (the finding's provenance anchor when a declared-free output derives a
    nonzero degree)."""
    import jax

    defined = {}
    for eqn in closed.jaxpr.eqns:
        frame = _user_frame(eqn)
        for v in eqn.outvars:
            defined[id(v)] = f"{eqn.primitive.name} @ {frame}"
    out = []
    for v in closed.jaxpr.outvars:
        if isinstance(v, jax.core.Literal):
            out.append("<literal>")
        else:
            out.append(defined.get(id(v), "<input>"))
    return out


def const_bytes(closed) -> int:
    """Total baked-constant bytes of a ClosedJaxpr (the HTTP 413 axis:
    remote compile ships constvars inside the program bytes)."""
    import numpy as np

    total = 0
    for c in getattr(closed, "consts", []):
        try:
            total += int(np.asarray(c).nbytes)
        except Exception:
            pass
    return total
