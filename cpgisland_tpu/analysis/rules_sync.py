"""Layer 4 — concurrency contracts (``graftsync``): the per-file AST rules.

PR 8 made the repo genuinely multi-threaded (broker cv, transport writer
threads, worker loop, prefetcher); these rules machine-check the lock
discipline the serve subsystem now depends on.  Three per-file rules plus
the per-file half of the lock-order check (the cross-module graph runs in
:mod:`synccheck` via ``--sync``):

- ``sync-guarded-by`` — guarded-by inference: an instance attribute (or a
  module global) ever WRITTEN under a lock is guarded by that lock, so every
  other access must hold it.  Intentionally unguarded state is registered
  centrally (``config.SYNC_UNGUARDED``, reason required) or waived inline.
- ``sync-lock-order`` — intra-file lock-order cycles and non-reentrant
  self-acquisition (the static-deadlock check; cross-module via ``--sync``).
- ``sync-blocking-under-lock`` — no supervised dispatch, device
  fetch/``block_until_ready``, ``queue.Queue.put/get``, socket I/O,
  ``Thread.join``, sleeps, or subprocesses while holding a lock.  A thread
  wedged under a lock stalls every other thread that needs it — and on this
  project it compounds the never-kill-mid-TPU-execution rule: a dispatch
  stranded behind a held lock cannot be safely killed (CLAUDE.md).
- ``sync-thread-lifecycle`` — every ``threading.Thread`` is daemonized or
  owns a stop ``Event`` and a deterministic ``join``; thread targets that
  drain iterators (``next(...)``) need a generator-close path (the PR 5
  prefetcher shutdown lessons: an abandoned producer leaks the wrapped
  FASTA handle until GC).
"""

from __future__ import annotations

import ast
from typing import Iterator

from cpgisland_tpu.analysis import astutil, synccheck
from cpgisland_tpu.analysis.config import (
    sync_blocking_ok_for,
    sync_unguarded_for,
)
from cpgisland_tpu.analysis.core import FileContext, Finding, register


def _model(ctx: FileContext) -> synccheck.FileSyncModel:
    # One model per FileContext (the four rules share the lock discovery).
    cached = getattr(ctx, "_sync_model", None)
    if cached is None:
        cached = synccheck.FileSyncModel(ctx)
        ctx._sync_model = cached  # type: ignore[attr-defined]
    return cached


# ---------------------------------------------------------------------------
# sync-guarded-by


@register(
    "sync-guarded-by",
    "state written under a lock must be read/written under that lock "
    "everywhere (guarded-by inference; register intentional exceptions in "
    "config.SYNC_UNGUARDED with a reason)",
    origin="PR 8 serve subsystem: broker/tenant counters are mutated by the "
    "transport thread (submit) AND the worker loop (flush); a half-guarded "
    "field is a lost-update bug that only shows under concurrent load",
)
def check_guarded_by(ctx: FileContext) -> Iterator[Finding]:
    model = _model(ctx)
    registered = sync_unguarded_for(ctx.relpath)
    yield from _class_guarded(ctx, model, registered)
    yield from _module_guarded(ctx, model, registered)


def _class_guarded(ctx, model, registered) -> Iterator[Finding]:
    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.ClassDef):
            continue
        locks = model.class_locks.get(node.name)
        if not locks:
            continue
        groups = set(locks.values())
        lock_attrs = set(locks)
        accesses = []  # (method_name, attr, write?, node, held)
        for m in node.body:
            if not isinstance(m, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            locals_map = model.local_locks(m, f"{node.name}.{m.name}")
            resolve = model.resolver(node.name, locals_map)
            base = synccheck.base_held_for(m.name, groups)
            for n, held in synccheck.walk_held(m, resolve, base):
                if (isinstance(n, ast.Attribute)
                        and isinstance(n.value, ast.Name)
                        and n.value.id == "self"
                        and n.attr not in lock_attrs):
                    accesses.append(
                        (m.name, n.attr, synccheck.attr_write_p(n), n, held)
                    )
        guards: dict[str, set] = {}
        for method, attr, write, _n, held in accesses:
            if write and held and method != "__init__":
                guards.setdefault(attr, set()).update(held)
        for method, attr, write, n, held in accesses:
            if method == "__init__" or attr not in guards:
                continue
            if held & guards[attr]:
                continue
            reason = registered.get(f"{node.name}.{attr}") or registered.get(attr)
            if reason is not None:
                continue
            lock_names = ", ".join(
                sorted(lk.label for lk in guards[attr])
            )
            yield ctx.finding(
                "sync-guarded-by", n,
                f"{'write to' if write else 'read of'} 'self.{attr}' outside "
                f"its guarding lock ({lock_names}): the attribute is written "
                f"under that lock elsewhere in {node.name}; hold the lock "
                "here, or register the field in config.SYNC_UNGUARDED with "
                "a reason",
            )


def _module_guarded(ctx, model, registered) -> Iterator[Finding]:
    if not model.module_locks:
        return
    mod_groups = set(model.module_locks.values())
    lock_names = set(model.module_locks)
    accesses = []  # (fn_name, name, write?, node, held)
    for class_name, fn, qual in synccheck.iter_functions(model):
        locals_map = model.local_locks(fn, qual)
        resolve = model.resolver(class_name, locals_map)
        base = synccheck.base_held_for(fn.name, mod_groups)
        bound = astutil.bound_names(fn)
        globals_here = synccheck.declared_globals(fn)
        for n, held in synccheck.walk_held(fn, resolve, base):
            if (isinstance(n, ast.Name) and n.id not in lock_names
                    and (n.id in globals_here or n.id not in bound)):
                accesses.append(
                    (fn.name, n.id,
                     synccheck.name_write_p(n, globals_here), n, held)
                )
    guards: dict[str, set] = {}
    for _fn, name, write, _n, held in accesses:
        if write and held:
            guards.setdefault(name, set()).update(held & mod_groups)
    guards = {k: v for k, v in guards.items() if v}
    for _fn, name, write, n, held in accesses:
        if name not in guards or held & guards[name]:
            continue
        if registered.get(name) is not None:
            continue
        lock_label = ", ".join(sorted(lk.label for lk in guards[name]))
        yield ctx.finding(
            "sync-guarded-by", n,
            f"{'write to' if write else 'read of'} module global {name!r} "
            f"outside its guarding lock ({lock_label}); hold the lock here, "
            "or register it in config.SYNC_UNGUARDED with a reason",
        )


# ---------------------------------------------------------------------------
# sync-lock-order (per-file half; cross-module graph = synccheck.run_sync)


@register(
    "sync-lock-order",
    "lock acquisition order must be acyclic (static deadlock detection; "
    "this per-file rule catches intra-file cycles — the cross-module graph "
    "runs via `--sync`)",
    origin="PR 8: broker cv -> session lock -> breaker lock -> prepared "
    "cache now nest across modules; one inverted pair under load is a "
    "daemon-freezing deadlock that also strands in-flight TPU dispatches "
    "(the never-kill-mid-execution rule makes that unrecoverable)",
)
def check_lock_order(ctx: FileContext) -> Iterator[Finding]:
    model = _model(ctx)
    if not model.module_locks and not model.class_locks:
        return
    graph = synccheck.LockGraph([model])
    yield from synccheck.graph_findings(graph)


# ---------------------------------------------------------------------------
# sync-blocking-under-lock

_BLOCKING_CANONICAL = {
    "jax.block_until_ready": "a blocking device fetch",
    "jax.device_get": "a blocking device fetch",
    "jax.device_put": "a blocking device upload",
    "time.sleep": "a sleep",
    "subprocess.run": "a subprocess",
    "subprocess.check_call": "a subprocess",
    "subprocess.check_output": "a subprocess",
}
_BLOCKING_METHODS = {"block_until_ready": "a blocking device fetch"}
_SOCKET_METHODS = {"accept", "recv", "recvfrom", "sendall", "connect"}


def _blocking_reason(ctx, model, class_name, call: ast.Call):
    """Why this call blocks, or None.  Receiver-sensitive cases (queue
    put/get, Thread.join) only fire on attributes the model KNOWS are
    queues/threads, so dict.get / str.join never false-positive."""
    canon = ctx.imports.canonical(call.func)
    if canon in _BLOCKING_CANONICAL:
        return f"{_BLOCKING_CANONICAL[canon]} ({canon})"
    func = call.func
    if not isinstance(func, ast.Attribute):
        return None
    if func.attr in _BLOCKING_METHODS:
        return f"{_BLOCKING_METHODS[func.attr]} (.{func.attr}())"
    if func.attr in _SOCKET_METHODS:
        return f"socket I/O (.{func.attr}())"
    if func.attr == "run" and isinstance(func.value, ast.Attribute) \
            and func.value.attr == "supervisor":
        return "a supervised dispatch (supervisor.run)"
    if func.attr == "supervise":
        return "a supervised dispatch (.supervise)"
    recv = func.value
    if (isinstance(recv, ast.Attribute) and isinstance(recv.value, ast.Name)
            and recv.value.id == "self" and class_name):
        if func.attr in ("put", "get") and recv.attr in \
                model.queue_attrs.get(class_name, ()):
            return f"a blocking queue op (self.{recv.attr}.{func.attr})"
        if func.attr == "join" and recv.attr in \
                model.thread_attrs.get(class_name, ()):
            return f"a thread join (self.{recv.attr}.join)"
    return None


def _direct_blocking_in(ctx, model, class_name, fn: ast.AST):
    """(call, reason) for blocking calls anywhere in ``fn``'s own scope —
    the depth-1 callee expansion of the rule."""
    out = []
    for n in astutil.walk_scope(fn):
        if isinstance(n, ast.Call):
            reason = _blocking_reason(ctx, model, class_name, n)
            if reason is not None:
                out.append((n, reason))
    return out


@register(
    "sync-blocking-under-lock",
    "no supervised dispatch, device fetch, queue put/get, socket I/O, "
    "thread join, sleep, or subprocess while holding a lock",
    origin="CLAUDE.md never-kill-mid-TPU-execution + the 50-100 ms relay "
    "RTT: a thread blocked under a lock stalls every submitter AND can "
    "strand an in-flight dispatch behind it; blocking work happens outside "
    "the critical section (see prepared._cached: build outside, insert "
    "under lock)",
)
def check_blocking_under_lock(ctx: FileContext) -> Iterator[Finding]:
    model = _model(ctx)
    if not model.module_locks and not model.class_locks:
        return
    exempt = sync_blocking_ok_for(ctx.relpath)
    tops = {name: fn for _c, fn, name in synccheck.iter_functions(model)
            if "." not in name}
    for class_name, fn, qual in synccheck.iter_functions(model):
        if fn.name in exempt or qual in exempt:
            continue
        locals_map = model.local_locks(fn, qual)
        resolve = model.resolver(class_name, locals_map)
        groups = (
            set(model.class_locks.get(class_name or "", {}).values())
            | set(model.module_locks.values())
        )
        base = synccheck.base_held_for(fn.name, groups)
        for n, held in synccheck.walk_held(fn, resolve, base):
            if not held or not isinstance(n, ast.Call):
                continue
            locks = ", ".join(sorted(lk.label for lk in held))
            reason = _blocking_reason(ctx, model, class_name, n)
            if reason is not None:
                yield ctx.finding(
                    "sync-blocking-under-lock", n,
                    f"{reason} while holding {locks}: move the blocking "
                    "work outside the critical section",
                )
                continue
            # Depth-1 callee expansion: a same-file helper that blocks.
            callee = None
            if isinstance(n.func, ast.Name) and n.func.id in tops:
                callee = (n.func.id, None, tops[n.func.id])
            elif (isinstance(n.func, ast.Attribute)
                    and isinstance(n.func.value, ast.Name)
                    and n.func.value.id == "self" and class_name):
                key = f"{class_name}.{n.func.attr}"
                for cn, cfn, cq in synccheck.iter_functions(model):
                    if cq == key:
                        callee = (n.func.attr, cn, cfn)
                        break
            if callee is None:
                continue
            cname, ccls, cfn = callee
            inner = _direct_blocking_in(ctx, model, ccls, cfn)
            if inner:
                _c, why = inner[0]
                yield ctx.finding(
                    "sync-blocking-under-lock", n,
                    f"call to {cname}() which performs {why} while holding "
                    f"{locks}: move the blocking work outside the critical "
                    "section (or register the gate in "
                    "config.SYNC_BLOCKING_OK with a reason)",
                )


# ---------------------------------------------------------------------------
# sync-thread-lifecycle


@register(
    "sync-thread-lifecycle",
    "threads must be daemonized or joined with an owned stop Event; thread "
    "targets draining iterators need a generator-close path",
    origin="PR 5 prefetcher shutdown: a non-daemon producer with no stop "
    "Event hangs pytest/process exit, and an abandoned producer leaks the "
    "wrapped FASTA generator's file handle until GC (prefetch._finish / "
    "_join_then_close are the reference pattern)",
)
def check_thread_lifecycle(ctx: FileContext) -> Iterator[Finding]:
    model = _model(ctx)
    has_event = False
    has_join = False
    close_calls: set[str] = set()
    thread_calls: list[ast.Call] = []
    for n in ast.walk(ctx.tree):
        if isinstance(n, ast.Call):
            canon = ctx.imports.canonical(n.func)
            if canon == "threading.Thread":
                thread_calls.append(n)
            elif canon == "threading.Event":
                has_event = True
            if isinstance(n.func, ast.Attribute):
                if n.func.attr == "join":
                    has_join = True
                if n.func.attr == "close":
                    dn = astutil.dotted_name(n.func.value)
                    if dn:
                        close_calls.add(dn.rsplit(".", 1)[-1])
            # helper-mediated close (prefetch._close_iter(self._it) pattern)
            if isinstance(n.func, ast.Name) and "close" in n.func.id:
                for a in n.args:
                    dn = astutil.dotted_name(a)
                    if dn:
                        close_calls.add(dn.rsplit(".", 1)[-1])
    if not thread_calls:
        return
    defs = {name: fn for _c, fn, name in synccheck.iter_functions(model)}
    for call in thread_calls:
        daemon = any(
            kw.arg == "daemon" and isinstance(kw.value, ast.Constant)
            and kw.value.value is True
            for kw in call.keywords
        )
        if not daemon and not (has_event and has_join):
            yield ctx.finding(
                "sync-thread-lifecycle", call,
                "threading.Thread is neither daemonized nor deterministically "
                "joined: pass daemon=True, or own a stop threading.Event and "
                "join() the thread on shutdown (prefetch/worker pattern)",
            )
        # Generator-close half: a target that drains an iterator must have
        # a close path for it somewhere in this file.
        target = next(
            (kw.value for kw in call.keywords if kw.arg == "target"), None
        )
        tname = None
        if isinstance(target, ast.Name):
            tname = target.id
        elif isinstance(target, ast.Attribute):
            tname = target.attr
        tfn = (
            defs.get(tname)
            or next((fn for q, fn in defs.items()
                     if q.endswith(f".{tname}")), None)
        )
        if tfn is None:
            continue
        drains = [
            n for n in astutil.walk_scope(tfn)
            if isinstance(n, ast.Call) and isinstance(n.func, ast.Name)
            and n.func.id == "next" and n.args
        ]
        if drains and not close_calls:
            yield ctx.finding(
                "sync-thread-lifecycle", call,
                f"thread target {tname!r} drains an iterator (next(...)) "
                "but this file never closes one: an abandoned producer "
                "leaks the wrapped generator's resources — close it on "
                "shutdown (see utils.prefetch._close_iter)",
            )
