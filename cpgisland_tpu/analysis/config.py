"""Central graftcheck configuration: the hot-path registry.

``hot-path-host-sync`` (R3) only fires inside functions *registered* as hot
paths — the decode/posterior/EM inner loops whose per-iteration host syncs
each cost a 50-100 ms relay round trip (CLAUDE.md).  Registration is either
central (here, keyed by module path suffix) or inline via a
``# graftcheck: hot-path`` comment on/above the ``def``.

The central list is deliberately the *driver loops*, not the jitted bodies:
a host sync inside a jitted function is a trace error jax reports itself;
the silent latency bugs live in the Python loops that orchestrate spans,
records, and EM iterations.
"""

from __future__ import annotations

# module-path suffix (posix-style) -> function names whose whole body
# (including nested defs) is a hot path.
HOT_PATHS: dict[str, frozenset[str]] = {
    "parallel/decode.py": frozenset({
        "viterbi_sharded",
        "viterbi_sharded_spans",
    }),
    "parallel/posterior.py": frozenset({
        "posterior_sharded",
        "transfer_total_sharded",
    }),
    "parallel/mesh.py": frozenset({"fetch_sharded_prefix"}),
    "train/baum_welch.py": frozenset({"_fit_fused", "fit"}),
    "ops/islands_device.py": frozenset({
        "call_islands_device",
        "call_islands_device_obs",
        "call_islands_device_async",
        "call_islands_device_obs_async",
        "_cols_to_host",
    }),
    "pipeline.py": frozenset({
        "_batched_device_calls",
        "_device_calls_retry",
        "_device_calls_deferred",
        "_decode_small_batch",
        "_posterior_record_unit",
        "posterior_file",
        "decode_file",
    }),
    # The dispatch supervisor wraps every supervised serving fetch: a host
    # sync written INSIDE it would silently multiply under retries, so any
    # future sync there must route through obs.note_fetch (no unledgered
    # retries) or carry a waiver.
    "resilience/policy.py": frozenset({"run", "supervise"}),
    "resilience/sentinel.py": frozenset({"verify", "_canary_value"}),
    # The serving daemon's flush drivers: every request in a flush pays any
    # stray sync here, multiplied by the flush rate — the single hottest
    # host loop in a long-lived process.
    "serve/broker.py": frozenset({
        "flush_once",
        "take_flush",
        "run_batch",
        "finish_flush",
        "_run_flush",
        "_decode_record",
        "_posterior_record",
        "_host_calls",
        "_device_calls",
    }),
    "serve/worker.py": frozenset({"_run"}),
    # The fleet's per-device flush workers: N copies of the worker loop's
    # cadence, each one a flush-rate-multiplied host loop like the broker's
    # drivers above.
    "serve/fleet.py": frozenset({"_run", "_execute"}),
}


def hot_functions_for(relpath: str) -> frozenset[str]:
    rel = relpath.replace("\\", "/")
    for suffix, names in HOT_PATHS.items():
        if rel.endswith(suffix):
            return names
    return frozenset()


# -- Layer 4 (graftsync) registries ------------------------------------------
#
# ``sync-guarded-by`` infers guarded state from writes under a lock; fields
# that are INTENTIONALLY accessed outside it are registered here with a
# reason (the hot-path-registry pattern: central, reviewed, justified — a
# reasonless exemption is not expressible).  Keys are module-path suffixes;
# values map "Class.attr" (or a bare attr / module-global name) to the
# justification.

SYNC_UNGUARDED: dict[str, dict[str, str]] = {
    "utils/native.py": {
        "_lib": "double-checked fast path: the unlocked read is benign — a "
        "stale None retries under _lock, a non-None CDLL is immutable once "
        "published and never reassigned back to None",
        "_tried": "same double-checked fast path as _lib (worst case two "
        "threads both enter the locked slow path, which re-checks)",
    },
    "resilience/faultplan.py": {
        "_ACTIVE": "the graftfault disarmed fast path: check()/wall_pad() "
        "run on EVERY supervised dispatch and must cost one module-global "
        "read when no plan is armed; arm/disarm serialize under _LOCK, and "
        "a stale read merely shifts one injection boundary — plans are "
        "armed before their workload starts",
    },
    "obs/scope.py": {
        "_ACTIVE": "the graftscope telemetry-off fast path: hop()/record()/"
        "complete() sit on every serve hot path and must cost one "
        "module-global read when no scope is installed; install/uninstall "
        "serialize under _HANDLE_LOCK, and a stale read degrades to one "
        "dropped telemetry hop — never a wrong serve result",
    },
}


def sync_unguarded_for(relpath: str) -> dict[str, str]:
    rel = relpath.replace("\\", "/")
    for suffix, entries in SYNC_UNGUARDED.items():
        if rel.endswith(suffix):
            return entries
    return {}


# ``sync-blocking-under-lock`` exemptions: functions whose blocking work
# under a lock IS the design (serialization gates), keyed module-path
# suffix -> {function name: reason}.  Anything else blocking under a lock
# needs the code restructured (build outside, insert under lock) or an
# inline waiver.

SYNC_BLOCKING_OK: dict[str, dict[str, str]] = {
    "utils/native.py": {
        "load": "one-time native build gate: concurrent loaders MUST wait "
        "for the single make/dlopen (running two builds of the same .so "
        "would race the artifact); _lock is a leaf — no other lock is ever "
        "taken under it, so the wait cannot deadlock",
    },
}


def sync_blocking_ok_for(relpath: str) -> dict[str, str]:
    rel = relpath.replace("\\", "/")
    for suffix, entries in SYNC_BLOCKING_OK.items():
        if rel.endswith(suffix):
            return entries
    return {}
