"""Central graftcheck configuration: the hot-path registry.

``hot-path-host-sync`` (R3) only fires inside functions *registered* as hot
paths — the decode/posterior/EM inner loops whose per-iteration host syncs
each cost a 50-100 ms relay round trip (CLAUDE.md).  Registration is either
central (here, keyed by module path suffix) or inline via a
``# graftcheck: hot-path`` comment on/above the ``def``.

The central list is deliberately the *driver loops*, not the jitted bodies:
a host sync inside a jitted function is a trace error jax reports itself;
the silent latency bugs live in the Python loops that orchestrate spans,
records, and EM iterations.
"""

from __future__ import annotations

# module-path suffix (posix-style) -> function names whose whole body
# (including nested defs) is a hot path.
HOT_PATHS: dict[str, frozenset[str]] = {
    "parallel/decode.py": frozenset({
        "viterbi_sharded",
        "viterbi_sharded_spans",
    }),
    "parallel/posterior.py": frozenset({
        "posterior_sharded",
        "transfer_total_sharded",
    }),
    "parallel/mesh.py": frozenset({"fetch_sharded_prefix"}),
    "train/baum_welch.py": frozenset({"_fit_fused", "fit"}),
    "ops/islands_device.py": frozenset({
        "call_islands_device",
        "call_islands_device_obs",
        "call_islands_device_async",
        "call_islands_device_obs_async",
        "_cols_to_host",
    }),
    "pipeline.py": frozenset({
        "_batched_device_calls",
        "_device_calls_retry",
        "_device_calls_deferred",
        "_decode_small_batch",
        "_posterior_record_unit",
        "posterior_file",
        "decode_file",
    }),
    # The dispatch supervisor wraps every supervised serving fetch: a host
    # sync written INSIDE it would silently multiply under retries, so any
    # future sync there must route through obs.note_fetch (no unledgered
    # retries) or carry a waiver.
    "resilience/policy.py": frozenset({"run", "supervise"}),
    "resilience/sentinel.py": frozenset({"verify", "_canary_value"}),
    # The serving daemon's flush drivers: every request in a flush pays any
    # stray sync here, multiplied by the flush rate — the single hottest
    # host loop in a long-lived process.
    "serve/broker.py": frozenset({
        "flush_once",
        "_run_flush",
        "_decode_record",
        "_posterior_record",
        "_host_calls",
        "_device_calls",
    }),
    "serve/worker.py": frozenset({"_run"}),
}


def hot_functions_for(relpath: str) -> frozenset[str]:
    rel = relpath.replace("\\", "/")
    for suffix, names in HOT_PATHS.items():
        if rel.endswith(suffix):
            return names
    return frozenset()
