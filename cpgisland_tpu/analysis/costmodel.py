"""graftcheck Layer 3 — the quantitative jaxpr cost model (graftcost).

Layer 2 checks what a traced graph *contains* (booleans: no f64, no
callbacks, pallas routing); this layer measures what it *costs*.  Every
registered contract entry (:mod:`~cpgisland_tpu.analysis.contracts`) is
traced at >=2 abstract geometries and each metric is linearly decomposed
into a **per-symbol** slope and a **fixed** intercept — the static twin of
BASELINE.md's measured size curve (the ~8-11 ms of fixed per-iteration
in-graph cost that bounds em-seq2d).  The decomposition is what lets a CI
diff say *which equations grew* when a regression lands, on CPU, in
seconds, before any TPU run.

Metrics per trace (deterministic functions of the jaxpr — fingerprints,
not a profiler; the model is deliberately approximate but stable):

- **flops** — per-primitive floating-op estimate (elementwise = out
  elements, ``dot_general`` = 2·M·N·K, reductions = in elements, ``scan``
  = trip count x body, ``cum*`` = 2n with log-depth, data movement = 0).
- **bytes** — operand + result footprint per equation (HBM-traffic proxy;
  ``scan`` bodies scale by trip count).
- **serial_depth** — critical-path length through the dependency graph,
  where a ``scan`` contributes trips x its body's critical path: the
  static stand-in for "sequential chain latency", the measured bound on
  every reduced path (BASELINE.md roofline).
- **n_eqns / prims** — equation count and per-primitive histogram (the
  names a drift report can print).
- **passes** — number of T-scaling sequential loops (scan equations whose
  total cost grows with the symbol count): the pass-sum structure
  BASELINE.md documents (3-pass posterior, 3-pass decode).

``while`` bodies are costed ONCE (trip counts are value-dependent); the
fused-EM contract reads the body cost directly (`while_body_costs`), which
is exactly the per-iteration cost the size curve measures.

No TPU, no execution: everything here is ``jax.make_jaxpr`` on abstract
inputs, so tracing a 16 Mi-symbol geometry costs the same as 16 Ki.
"""

from __future__ import annotations

import dataclasses
import math
from typing import Iterable, Optional

# Primitives that are pure data movement / metadata: zero flops, bytes only.
_MOVEMENT_PRIMS = frozenset({
    "broadcast_in_dim", "reshape", "transpose", "convert_element_type",
    "slice", "dynamic_slice", "dynamic_update_slice", "concatenate", "pad",
    "squeeze", "rev", "gather", "scatter", "copy", "iota", "split",
    "device_put", "stop_gradient", "select_and_scatter_add",
})

# Reductions: flops = input elements (one combine per element).
_REDUCE_PRIMS = frozenset({
    "reduce_sum", "reduce_max", "reduce_min", "reduce_prod", "reduce_and",
    "reduce_or", "argmax", "argmin", "reduce_precision",
})

# Cumulative ops: associative-scan lowering — ~2n work, log2(n) depth.
_CUM_PRIMS = frozenset({"cummax", "cummin", "cumsum", "cumprod",
                        "cumlogsumexp"})

# Sub-jaxpr carrying primitives and how many times their body runs.
_LOOP_PRIMS = frozenset({"scan", "while"})


@dataclasses.dataclass
class EqnCost:
    """One equation's cost, multiplicity-scaled (loop bodies count trips)."""

    prim: str
    group: str       # "file:function" from source_info — the attribution key
    flops: int
    bytes: int       # operand + result footprint
    out_elems: int   # result elements PER APPLICATION (x mult = total)
    depth: int       # serial-depth contribution if on the critical path
    path: str = ""   # nesting, e.g. "scan/scan" (loop bodies)
    mult: int = 1    # applications (loop trip products folded in)

    def as_dict(self) -> dict:
        return dataclasses.asdict(self)


@dataclasses.dataclass
class CostMetrics:
    """Aggregate fingerprint of one traced graph."""

    flops: int
    bytes: int
    serial_depth: int
    n_eqns: int
    prims: dict          # primitive -> structural count
    prim_flops: dict     # primitive -> multiplicity-scaled flops total
    n_scan_eqns: int     # structural scan count (pass detection pairs these)

    def as_dict(self) -> dict:
        return {
            "flops": self.flops, "bytes": self.bytes,
            "serial_depth": self.serial_depth, "n_eqns": self.n_eqns,
            "prims": dict(sorted(self.prims.items())),
            "prim_flops": dict(sorted(self.prim_flops.items())),
            "n_scan_eqns": self.n_scan_eqns,
        }


def _aval_elems(aval) -> int:
    shape = getattr(aval, "shape", None)
    if not shape:
        return 1
    n = 1
    for d in shape:
        n *= int(d)
    return n


def _aval_bytes(aval) -> int:
    dt = getattr(aval, "dtype", None)
    itemsize = getattr(dt, "itemsize", 4)
    return _aval_elems(aval) * int(itemsize)


def _eqn_group(eqn) -> str:
    """'file:function' of the user frame that emitted this equation."""
    try:
        from jax._src import source_info_util

        frame = source_info_util.user_frame(eqn.source_info)
        if frame is None:
            return "<jax>"
        fname = frame.file_name.rsplit("/", 1)[-1]
        return f"{fname}:{frame.function_name}"
    except Exception:
        return "<unknown>"


def _dot_general_flops(eqn) -> int:
    (contract, batch) = eqn.params["dimension_numbers"]
    lhs = eqn.invars[0].aval
    rhs = eqn.invars[1].aval
    lhs_shape = lhs.shape
    k = 1
    for d in contract[0]:
        k *= int(lhs_shape[d])
    b = 1
    for d in batch[0]:
        b *= int(lhs_shape[d])
    m = _aval_elems(lhs) // max(k * b, 1)
    n = _aval_elems(rhs) // max(k * b, 1)
    return 2 * b * m * n * k


def _closed_of(value):
    """Yield ClosedJaxpr/Jaxpr objects inside an eqn param value."""
    import jax

    if isinstance(value, jax.core.ClosedJaxpr):
        yield value.jaxpr
    elif isinstance(value, jax.core.Jaxpr):
        yield value
    elif isinstance(value, (list, tuple)):
        for v in value:
            yield from _closed_of(v)


def _io_bytes(eqn) -> int:
    import jax

    total = 0
    for v in eqn.invars:
        if not isinstance(v, jax.core.Literal):
            total += _aval_bytes(v.aval)
    for v in eqn.outvars:
        total += _aval_bytes(v.aval)
    return total


def _base_flops(eqn) -> int:
    """Flops of one application of a LEAF primitive (no sub-jaxprs)."""
    name = eqn.primitive.name
    if name in _MOVEMENT_PRIMS:
        return 0
    if name == "dot_general":
        return _dot_general_flops(eqn)
    if name in _REDUCE_PRIMS:
        return sum(
            _aval_elems(v.aval) for v in eqn.invars if hasattr(v, "aval")
        )
    if name in _CUM_PRIMS:
        return 2 * sum(_aval_elems(v.aval) for v in eqn.outvars)
    if name == "sort":
        n = sum(_aval_elems(v.aval) for v in eqn.outvars)
        return n * max(1, int(math.log2(max(n, 2))))
    # Default: elementwise — one op per output element.
    return sum(_aval_elems(v.aval) for v in eqn.outvars)


def _leaf_depth(eqn) -> int:
    name = eqn.primitive.name
    if name in _CUM_PRIMS or name == "sort":
        n = max((_aval_elems(v.aval) for v in eqn.outvars), default=1)
        return max(1, int(math.ceil(math.log2(max(n, 2)))))
    return 1


def _scan_trips(eqn) -> int:
    return int(eqn.params.get("length", 1))


def eqn_costs(closed, _mult: int = 1, _path: str = "") -> list:
    """Flattened, multiplicity-scaled per-equation costs for a (Closed)Jaxpr.

    Loop bodies are inlined with their trip count folded into every
    contained equation (``while`` bodies count as ONE trip — the
    per-iteration cost).  Deterministic order: jaxpr equation order,
    depth-first into sub-jaxprs.
    """
    jaxpr = getattr(closed, "jaxpr", closed)
    out: list[EqnCost] = []
    for eqn in jaxpr.eqns:
        name = eqn.primitive.name
        subs = [s for v in eqn.params.values() for s in _closed_of(v)]
        if name == "scan":
            trips = _scan_trips(eqn)
            for sub in _closed_of(eqn.params["jaxpr"]):
                out.extend(
                    eqn_costs(sub, _mult * trips, _path + name + "/")
                )
            continue
        if name == "while":
            # Trip counts are value-dependent: cost ONE iteration of the
            # body (+ one cond evaluation) — the per-iteration cost the
            # size-curve methodology measures.
            for key in ("cond_jaxpr", "body_jaxpr"):
                for sub in _closed_of(eqn.params[key]):
                    out.extend(eqn_costs(sub, _mult, _path + name + "/"))
            continue
        if name == "cond":
            # Upper bound: the most expensive branch.
            branch_costs = [
                eqn_costs(s, _mult, _path + name + "/")
                for s in _closed_of(eqn.params["branches"])
            ]
            if branch_costs:
                out.extend(
                    max(branch_costs, key=lambda cs: sum(c.flops for c in cs))
                )
            continue
        if subs and name not in ("pallas_call",):
            # pjit / closed_call / custom_jvp / remat ... — transparent.
            for sub in subs:
                out.extend(eqn_costs(sub, _mult, _path))
            continue
        out.append(
            EqnCost(
                prim=name,
                group=_eqn_group(eqn),
                flops=_base_flops(eqn) * _mult,
                bytes=_io_bytes(eqn) * _mult,
                out_elems=sum(_aval_elems(v.aval) for v in eqn.outvars),
                depth=_leaf_depth(eqn) * _mult,
                path=_path,
                mult=_mult,
            )
        )
    return out


def _jaxpr_depth(closed) -> int:
    """Critical-path length (in leaf-equation applications) of a jaxpr.

    scan contributes trips x body critical path; while contributes ONE
    body critical path (per-iteration depth); transparent call prims
    contribute their body's critical path."""
    import jax

    jaxpr = getattr(closed, "jaxpr", closed)
    depth: dict[int, int] = {}

    def var_depth(v) -> int:
        if isinstance(v, jax.core.Literal):
            return 0
        return depth.get(id(v), 0)

    for eqn in jaxpr.eqns:
        name = eqn.primitive.name
        base = max((var_depth(v) for v in eqn.invars), default=0)
        if name == "scan":
            body = max(
                (_jaxpr_depth(s) for s in _closed_of(eqn.params["jaxpr"])),
                default=1,
            )
            d = base + _scan_trips(eqn) * body
        elif name == "while":
            body = max(
                (_jaxpr_depth(s) for s in _closed_of(eqn.params["body_jaxpr"])),
                default=1,
            )
            d = base + body
        elif name == "cond":
            body = max(
                (_jaxpr_depth(s) for s in _closed_of(eqn.params["branches"])),
                default=1,
            )
            d = base + body
        else:
            subs = [s for v in eqn.params.values() for s in _closed_of(v)]
            if subs and name != "pallas_call":
                d = base + max(_jaxpr_depth(s) for s in subs)
            else:
                d = base + _leaf_depth(eqn)
        for v in eqn.outvars:
            depth[id(v)] = d
    return max(
        (var_depth(v) for v in jaxpr.outvars), default=0
    )


def cost_jaxpr(closed) -> CostMetrics:
    """Aggregate CostMetrics for a ClosedJaxpr."""
    costs = eqn_costs(closed)
    prims: dict[str, int] = {}
    prim_flops: dict[str, int] = {}
    for c in costs:
        prims[c.prim] = prims.get(c.prim, 0) + 1
        prim_flops[c.prim] = prim_flops.get(c.prim, 0) + c.flops
    n_scans = _count_scans(closed)
    return CostMetrics(
        flops=sum(c.flops for c in costs),
        bytes=sum(c.bytes for c in costs),
        serial_depth=_jaxpr_depth(closed),
        n_eqns=len(costs),
        prims=prims,
        prim_flops=prim_flops,
        n_scan_eqns=n_scans,
    )


def _scan_eqns(closed) -> list:
    """All scan equations (recursively, deterministic order)."""
    jaxpr = getattr(closed, "jaxpr", closed)
    out = []
    for eqn in jaxpr.eqns:
        if eqn.primitive.name == "scan":
            out.append(eqn)
        for v in eqn.params.values():
            for sub in _closed_of(v):
                out.extend(_scan_eqns(sub))
    return out


def scan_costs(closed) -> list:
    """[(group, trips, total body flops x trips)] per scan equation, in
    deterministic order — the pass-structure detector pairs these across
    geometries."""
    out = []
    for eqn in _scan_eqns(closed):
        trips = _scan_trips(eqn)
        body_flops = 0
        for sub in _closed_of(eqn.params["jaxpr"]):
            body_flops += sum(c.flops for c in eqn_costs(sub))
        out.append((_eqn_group(eqn), trips, trips * body_flops))
    return out


def _count_scans(closed) -> int:
    return len(_scan_eqns(closed))


def while_body_costs(closed) -> list:
    """[(while-eqn index, list[EqnCost] of its body)] — the fused-EM
    fixed-share contract reads per-iteration body cost directly."""
    import itertools

    jaxpr = getattr(closed, "jaxpr", closed)
    out = []
    counter = itertools.count()

    def walk(j):
        for eqn in j.eqns:
            if eqn.primitive.name == "while":
                idx = next(counter)
                body = []
                for sub in _closed_of(eqn.params["body_jaxpr"]):
                    body.extend(eqn_costs(sub))
                out.append((idx, body))
            for v in eqn.params.values():
                for sub in _closed_of(v):
                    walk(sub)

    walk(jaxpr)
    return out


# -- linear decomposition over geometries ------------------------------------


@dataclasses.dataclass
class LinearFit:
    """cost(T) ~= per_symbol * T + fixed, from the two extreme geometries."""

    per_symbol: float
    fixed: float

    def at(self, n_symbols: float) -> float:
        return self.per_symbol * n_symbols + self.fixed

    def as_dict(self) -> dict:
        return {"per_symbol": self.per_symbol, "fixed": self.fixed}


def fit_linear(points: Iterable[tuple]) -> LinearFit:
    """Fit (n_symbols, value) points; uses the extreme pair (the middle
    points, when present, are linearity witnesses the caller can check)."""
    pts = sorted(points)
    (n1, v1), (n2, v2) = pts[0], pts[-1]
    if n2 == n1:
        return LinearFit(per_symbol=0.0, fixed=float(v1))
    slope = (v2 - v1) / (n2 - n1)
    return LinearFit(per_symbol=slope, fixed=float(v1) - slope * n1)


@dataclasses.dataclass
class EntryCosts:
    """A contract entry traced at each geometry + the per-metric fits."""

    name: str
    geometries: list          # symbol counts
    metrics: list             # CostMetrics per geometry (same order)
    eqns: list                # list[EqnCost] per geometry
    scans: list               # scan_costs() per geometry
    matched: bool             # eqn lists pair positionally across geometries
    jaxprs: list = dataclasses.field(default_factory=list)  # ClosedJaxprs

    def fits(self) -> dict:
        pts = list(zip(self.geometries, self.metrics))
        return {
            "flops": fit_linear([(n, m.flops) for n, m in pts]),
            "bytes": fit_linear([(n, m.bytes) for n, m in pts]),
            "serial_depth": fit_linear(
                [(n, m.serial_depth) for n, m in pts]
            ),
        }

    def passes(self) -> int:
        """T-scaling sequential passes: scan equations whose total cost
        grows with the symbol count (scan lists paired by position across
        geometries — scan COUNT is structurally stable even where
        associative-scan trees reshape).  Falls back to the structural
        scan count when the lists don't pair."""
        if len(self.scans) < 2 or len(self.scans[0]) != len(self.scans[-1]):
            return self.metrics[0].n_scan_eqns
        n = 0
        for (g1, t1, f1), (g2, t2, f2) in zip(self.scans[0], self.scans[-1]):
            if f2 > f1 or t2 > t1:
                n += 1
        return n

    def dense_pair_eqns(self, n_states: int) -> list:
        """Equations doing O(T·S²) dense-pair work at the max geometry:
        TOTAL result footprint (out_elems x loop multiplicity, so a dense
        per-step [S, S] op inside a T-trip scan is counted at its full
        O(T·S²), not one application) >= (S²/2)·T elements.  Reduced
        streams run [T, 2, 2] (4/sym) and fixed tables are O(1), so the
        S²/2 threshold (32/sym for the flagship S=8) cleanly separates a
        reintroduced dense pair op (64/sym) from everything legitimate."""
        T = self.geometries[-1]
        threshold = (n_states * n_states // 2) * T
        return [
            c for c in self.eqns[-1]
            if c.out_elems * c.mult >= threshold
            and c.prim not in _MOVEMENT_PRIMS
        ]


def trace_entry(
    contract, scales: Optional[tuple] = None
) -> EntryCosts:
    """Trace one Contract at each geometry scale and package the costs.

    Non-scalable entries (no time geometry) are traced once; their fits
    degenerate to fixed-only."""
    import jax

    if scales is None:
        scales = getattr(contract, "cost_scales", (1, 2))
    if not getattr(contract, "scalable", True):
        scales = (1,)
    geometries, metrics, eqn_lists, scan_lists, jaxprs = [], [], [], [], []
    for s in scales:
        fn, args, *_rest = contract.make(s)
        closed = jax.make_jaxpr(fn)(*args)
        geometries.append(max(contract.base_symbols, 1) * s)
        metrics.append(cost_jaxpr(closed))
        eqn_lists.append(eqn_costs(closed))
        scan_lists.append(scan_costs(closed))
        jaxprs.append(closed)
    matched = len(eqn_lists) >= 2 and all(
        len(e) == len(eqn_lists[0]) for e in eqn_lists
    ) and all(
        a.prim == b.prim
        for a, b in zip(eqn_lists[0], eqn_lists[-1])
    )
    return EntryCosts(
        name=contract.name, geometries=geometries, metrics=metrics,
        eqns=eqn_lists, scans=scan_lists, matched=matched, jaxprs=jaxprs,
    )


# -- fixed-cost attribution --------------------------------------------------


def _group_agg(costs: list) -> dict:
    """Sum flops/bytes/depth/out_elems per eqn group (file:function).

    Group keys come from source functions, so the aggregation is robust to
    associative-scan trees reshaping with geometry (where positional
    eqn pairing is not)."""
    agg: dict[str, dict] = {}
    for c in costs:
        g = agg.setdefault(
            c.group,
            {"prims": set(), "flops": 0, "bytes": 0, "depth": 0,
             "n_eqns": 0},
        )
        g["prims"].add(c.prim)
        g["n_eqns"] += 1
        g["flops"] += c.flops
        g["bytes"] += c.bytes
        g["depth"] += c.depth
    return agg


def attribute(entry: EntryCosts, top: int = 12) -> dict:
    """Decompose an entry's cost by eqn GROUP (file:function) into
    per-symbol and fixed terms — the table that names which equations
    carry the size-independent work.

    Group-aggregated (lo and hi geometries summed per group, then fitted),
    so it works even where the graph reshapes with geometry.  Returns
    {"groups": [...], "totals": {...}}; groups sorted by fixed-flops
    share, descending."""
    if len(entry.eqns) < 2:
        return {"groups": [], "totals": {}, "matched": entry.matched}
    n_lo, n_hi = entry.geometries[0], entry.geometries[-1]
    dn = max(n_hi - n_lo, 1)
    lo, hi = _group_agg(entry.eqns[0]), _group_agg(entry.eqns[-1])
    groups = []
    for name in sorted(set(lo) | set(hi)):
        a = lo.get(name, {"prims": set(), "flops": 0, "bytes": 0,
                          "depth": 0, "n_eqns": 0})
        b = hi.get(name, a)
        row = {"group": name,
               "prims": sorted(a["prims"] | b["prims"]),
               "n_eqns": b["n_eqns"]}
        for field in ("flops", "bytes", "depth"):
            slope = (b[field] - a[field]) / dn
            row[f"{field}_per_symbol"] = slope
            row[f"{field}_fixed"] = a[field] - slope * n_lo
        groups.append(row)
    groups.sort(key=lambda g: g["flops_fixed"], reverse=True)
    totals = {k: f.as_dict() for k, f in entry.fits().items()}
    return {
        "groups": groups[:top],
        "n_groups": len(groups),
        "totals": totals,
        # Serial WORK totals over ALL groups (not just the top slice) —
        # distinct from totals["serial_depth"], which is the critical path.
        "depth_work_fixed": sum(g["depth_fixed"] for g in groups),
        "matched": entry.matched,
        "geometries": entry.geometries,
    }


def attribution_table(entry: EntryCosts, top: int = 12) -> str:
    """Markdown attribution table for BASELINE.md / the CLI.

    The depth column is per-group SERIAL WORK (summed chain-step
    applications — how much sequential stepping the group contributes);
    the graph's CRITICAL PATH (the latency bound, which overlapping chains
    share) is a separate footer line, since the two are different metrics
    and group serial work legitimately exceeds the critical path."""
    att = attribute(entry, top=top)
    if not att.get("groups"):
        return (
            f"(entry {entry.name}: single geometry — no fixed-vs-per-symbol "
            "attribution)"
        )
    lines = [
        f"| eqn group ({entry.name}) | prims | per-symbol flops | "
        "fixed flops | fixed bytes | fixed serial work |",
        "|---|---|---|---|---|---|",
    ]
    for g in att["groups"]:
        prims = ",".join(g["prims"][:4]) + ("…" if len(g["prims"]) > 4 else "")
        lines.append(
            f"| `{g['group']}` | {prims} | {g['flops_per_symbol']:.2f} | "
            f"{g['flops_fixed']:.0f} | {g['bytes_fixed']:.0f} | "
            f"{g['depth_fixed']:.0f} |"
        )
    t = att["totals"]
    lines.append(
        f"| **total** | | {t['flops']['per_symbol']:.2f} | "
        f"{t['flops']['fixed']:.0f} | {t['bytes']['fixed']:.0f} | "
        f"{att['depth_work_fixed']:.0f} |"
    )
    lines.append(
        f"\ncritical path (the serial-latency bound): fixed "
        f"{t['serial_depth']['fixed']:.0f} steps, "
        f"{t['serial_depth']['per_symbol']:.4g} steps/symbol"
    )
    return "\n".join(lines)
