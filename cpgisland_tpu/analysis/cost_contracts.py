"""graftcheck Layer 3 — quantitative cost contracts + the COSTS.json lockfile.

Built on :mod:`~cpgisland_tpu.analysis.costmodel`.  Two halves:

**The lockfile** (``COSTS.json``, committed): per contract-registry entry,
the cost fingerprint (per-geometry metrics, per-symbol/fixed fits, pass
count, primitive histogram) captured on a platform, with per-metric
tolerances.  ``python -m cpgisland_tpu.analysis --costs`` re-traces the
registry and diffs against the lockfile — a drifted metric fails CI with
the *named drifting primitives* (the histogram diff), so "a reintroduced
dense op / doubled scan depth / grown epilogue" is a red build on CPU in
seconds instead of a mystery regression on relay-TPU minutes.
``--update-costs`` re-baselines after a verified change and prints what
moved.  Entries that left the registry but linger in the lockfile are
reported like stale waivers.

**The quantitative contracts** — graph-cost assertions the boolean layer
cannot express:

- ``cost.reduced-no-dense-pair`` — reduced (onehot) engine graphs contain
  ZERO equations materializing an O(T·S²) dense-pair tensor (>= S²/2
  result elements per symbol).  The r4 reduction's whole win was deleting
  these; one stray dense xi/products op silently re-pays the K²/4 factor.
- ``cost.em-body-fixed-share`` — the fused EM while-body's FIXED cost
  share (flops and bytes, from the linear fit) stays under
  ``FIXED_SHARE_MAX`` at the 16 Mi reference geometry: the epilogue
  (M-step, convergence delta, stats assembly) must stay model-sized.
- ``cost.pass-structure`` — T-scaling sequential pass counts match the
  BASELINE.md-documented pass structure (3-pass decode/posterior, 2-pass
  chunked EM: fwd + bwd chains; the chunked stats reduction is a
  throughput contraction, not a serial pass).
- ``cost.serial-depth-lanes`` — serial-chain depth slope per symbol stays
  under a per-family bound: depth must scale with LANES (T/lane_T), never
  with T (a per-symbol sequential walk is the one structure every kernel
  here was built to avoid).

The quantitative contracts run on the CPU XLA twins (identical arithmetic
to the chip kernels, CLAUDE.md); on a TPU backend the pass degrades to the
lockfile diff against a ``tpu`` platform section when one exists, plus the
live fingerprint capture (pallas_call bodies are opaque leaves there).
"""

from __future__ import annotations

import dataclasses
import json
import os
from typing import Optional

from cpgisland_tpu.analysis import costmodel
from cpgisland_tpu.analysis.contracts import (
    Contract,
    ContractResult,
    default_contracts,
    fused_em_make,
)

LOCKFILE_VERSION = 1
LOCKFILE_NAME = "COSTS.json"

# Fixed share of the fused EM while-body cost (flops AND bytes) allowed at
# the reference geometry.  Measured today: ~7e-7 flops / ~1.6e-5 bytes —
# the pin is ~600x headroom, sized so a genuinely fixed-cost epilogue
# growth (>= ~100 MFLOP, e.g. an accidental model-cross-product in the
# loop) trips it while model-sized drift is the lockfile's job.
FIXED_SHARE_MAX = 0.01
REFERENCE_T = 16 * 2**20  # the size-curve's 16 Mi knee (BASELINE.md)

# T-scaling sequential pass counts, pinned to the documented pass
# structure (BASELINE.md roofline + "Pass-count collapse" r9 section).
# Decode keeps its 3 passes (products/backpointers/backtrace — pass B
# needs pass A's entering vectors, pass C needs pass B's exits).  The
# reduced probability-space paths run the r9 CO-SCHEDULED fwd/bwd pass
# (fb_onehot._oh_fwdbwd_kernel / its one-scan XLA twin): posterior =
# products + fused fwd/bwd (conf is an elementwise epilogue), exact-seq
# EM = products + fused fwd/bwd (z-normalized stats are a throughput
# contraction), chunked EM = ONE fused fwd/bwd pass.  The dense chunked
# path keeps its split fwd + bwd (its cs-scaled stats need the split
# backward's true Rabiner scaling).
EXPECTED_PASSES = {
    "decode.xla": 3,
    "decode.onehot": 3,
    # The family generalization: the order-2 dinucleotide member keeps the
    # flagship's 3-pass reduced decode structure (same pass triple, bigger
    # pair table — family.partition_of).
    "decode.family.dinuc_cpg": 3,
    "decode.batch_flat.onehot": 3,
    "decode.batch_flat.scores.onehot": 3,
    "posterior.onehot": 2,
    "em.seq.onehot": 2,
    # The TRUE-ONE-PASS matrix arm (ISSUE 17): the matrix-carried
    # co-scheduled kernel emits the per-lane transfer totals itself, so
    # the standalone products pass disappears — ONE T-scaling pass; the
    # r7 [NL,2,2] boundary combine is an associative O(NL) epilogue (not
    # a lax.scan over T) and entry application/stats/conf are elementwise
    # or throughput contractions.  The 2-pass entries above are RETAINED:
    # they are the shipped default and the A/B baseline until the chip
    # sweep (graftune one_pass.* tasks) decides the flip.
    "posterior.onehot.onepass": 1,
    "em.seq.onehot.onepass": 1,
    "em.chunked.xla": 2,
    "em.chunked.onehot": 1,
    # Multi-model kernel occupancy (r12): THREE members' chains in one
    # stacked launch set cost the SAME T-scaling pass counts as one member
    # — constant in N, the whole point.  A member de-stacking back to its
    # own sequential pass set fails here naming the regrown scans.
    "decode.batch_flat.onehot.stacked3": 3,
    "posterior.onehot.stacked3": 2,
    "em.chunked.onehot.stacked3": 1,
}

# Serial-depth slope ceilings (critical-path steps per SYMBOL).  Lane
# entries grow depth only via the lane count (1/lane_T per symbol times a
# tiny boundary-combine body — measured <= 3e-4); decode grows via the
# block combine (1/block_size x the combine depth — measured ~1.7e-2).  A
# per-symbol sequential walk would measure >= 1.
DEPTH_SLOPE_MAX = {
    "decode.": 0.05,
    "posterior.": 0.01,
    "em.seq.": 0.01,
    "em.chunked.": 0.01,
}

_QUANT_RULES = (
    ("cost.lockfile", "live cost fingerprints match COSTS.json within "
     "per-metric tolerances; drifts name the drifting primitives"),
    ("cost.reduced-no-dense-pair", "reduced (onehot) engine graphs contain "
     "zero O(T*S^2) dense-pair equations"),
    ("cost.em-body-fixed-share", "fused EM while-body fixed cost share "
     f"< {FIXED_SHARE_MAX} at the 16 Mi reference geometry"),
    ("cost.pass-structure", "T-scaling sequential pass counts match the "
     "documented pass structure (3-pass decode/posterior, 2-pass chunked)"),
    ("cost.serial-depth-lanes", "serial depth scales with lanes, never "
     "with T (per-symbol depth slope under the per-family ceiling)"),
)


def quantitative_rules() -> list:
    """(name, description) pairs for --list-rules / the JSON payload."""
    return list(_QUANT_RULES)


DEFAULT_TOLERANCES = {
    # Relative, on the fitted per_symbol/fixed values and raw totals.
    # Tight: a trace is a deterministic function of (code, jax version),
    # so drift means the GRAPH changed — the workflow is --update-costs
    # after verifying, not widening the tolerance.
    "flops": 0.02,
    "bytes": 0.02,
    "serial_depth": 0.02,
    # Exact-integer structure: any change is a real graph change.
    "n_eqns": 0,
    "passes": 0,
}


def default_lockfile_path() -> str:
    pkg = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    return os.path.join(os.path.dirname(pkg), LOCKFILE_NAME)


def _fused_em_entry() -> Contract:
    return Contract(
        name="em.fused",
        make=lambda scale=1: fused_em_make(scale),
        base_symbols=8 * 1024,
        cost_scales=(16, 32),
    )


def cost_entries() -> list:
    """The cost registry: every boolean-layer contract entry + the fused
    EM loop (whose while-body is the per-iteration cost the size curve
    measures)."""
    return default_contracts() + [_fused_em_entry()]


def _n_states() -> int:
    from cpgisland_tpu.models import presets

    return presets.durbin_cpg8().n_states


# -- fingerprints ------------------------------------------------------------


def fingerprint(entry: costmodel.EntryCosts, while_body: Optional[dict] = None) -> dict:
    fp = {
        "geometries": list(entry.geometries),
        "passes": entry.passes(),
        "metrics": [m.as_dict() for m in entry.metrics],
        "fits": {k: f.as_dict() for k, f in entry.fits().items()},
    }
    if while_body is not None:
        fp["while_body"] = while_body
    return fp


def _while_body_fits(entry: costmodel.EntryCosts) -> Optional[dict]:
    """Per-iteration while-body cost fits, from an already-traced entry's
    retained jaxprs (no re-trace — the fused EM entry is the most
    expensive trace in the registry)."""
    points_f, points_b = [], []
    for T, closed in zip(entry.geometries, entry.jaxprs):
        bodies = costmodel.while_body_costs(closed)
        if not bodies:
            return None
        body = bodies[0][1]
        points_f.append((T, sum(c.flops for c in body)))
        points_b.append((T, sum(c.bytes for c in body)))
    return {
        "flops": costmodel.fit_linear(points_f).as_dict(),
        "bytes": costmodel.fit_linear(points_b).as_dict(),
    }


def trace_all() -> tuple:
    """Trace every cost entry once; returns ({name: EntryCosts},
    {name: while-body fits or None})."""
    traced: dict[str, costmodel.EntryCosts] = {}
    bodies: dict[str, Optional[dict]] = {}
    for c in cost_entries():
        traced[c.name] = costmodel.trace_entry(c)
        if c.name == "em.fused":
            bodies[c.name] = _while_body_fits(traced[c.name])
    return traced, bodies


def live_fingerprints(traced=None, bodies=None) -> dict:
    if traced is None:
        traced, bodies = trace_all()
    return {
        name: fingerprint(e, (bodies or {}).get(name))
        for name, e in traced.items()
    }


# -- the lockfile ------------------------------------------------------------


def load_lockfile(path: Optional[str] = None) -> Optional[dict]:
    path = path or default_lockfile_path()
    if not os.path.exists(path):
        return None
    with open(path, "r", encoding="utf-8") as fh:
        return json.load(fh)


def write_lockfile(
    fingerprints: dict, path: Optional[str] = None,
    platform: Optional[str] = None,
) -> str:
    import jax

    path = path or default_lockfile_path()
    platform = platform or jax.default_backend()
    data = load_lockfile(path) or {
        "version": LOCKFILE_VERSION,
        "tolerances": dict(DEFAULT_TOLERANCES),
        "platforms": {},
    }
    data["platforms"][platform] = {
        "jax": jax.__version__,
        "entries": fingerprints,
    }
    tmp = path + ".tmp"
    with open(tmp, "w", encoding="utf-8") as fh:
        json.dump(data, fh, indent=1, sort_keys=True)
        fh.write("\n")
    os.replace(tmp, path)
    return path


@dataclasses.dataclass
class CostDiff:
    violations: list        # hard failures (metric drift, missing entries)
    notes: list             # advisory (stale entries, absent platform)
    stale: list             # lockfile entries no longer in the registry
    checked: int = 0

    @property
    def ok(self) -> bool:
        return not self.violations

    def as_dict(self) -> dict:
        return dataclasses.asdict(self) | {"ok": self.ok}


def _rel_drift(live: float, locked: float) -> float:
    denom = max(abs(locked), 1.0)
    return abs(live - locked) / denom


def _prim_drift(live_m: dict, locked_m: dict) -> str:
    """The 'named drifting primitives': structural histogram deltas, and —
    when counts are unchanged but a primitive's COST moved (the grown-
    epilogue class) — the per-primitive flops deltas."""
    live_prims, locked_prims = live_m["prims"], locked_m["prims"]
    deltas = []
    for p in sorted(set(live_prims) | set(locked_prims)):
        d = live_prims.get(p, 0) - locked_prims.get(p, 0)
        if d:
            deltas.append(f"{p}{d:+d}")
    lf = live_m.get("prim_flops", {})
    kf = locked_m.get("prim_flops", {})
    for p in sorted(set(lf) | set(kf)):
        a, b = kf.get(p, 0), lf.get(p, 0)
        if _rel_drift(b, a) > 0.02:
            deltas.append(f"{p} flops {a:.3g}->{b:.3g}")
    return ", ".join(deltas[:8]) if deltas else "(histogram unchanged)"


def diff_costs(
    live: dict, lock: Optional[dict], platform: str
) -> CostDiff:
    """Diff live fingerprints against the lockfile's platform section."""
    diff = CostDiff(violations=[], notes=[], stale=[])
    if lock is None:
        diff.violations.append(
            f"no {LOCKFILE_NAME} lockfile — run --update-costs to baseline"
        )
        return diff
    section = lock.get("platforms", {}).get(platform)
    if section is None:
        diff.notes.append(
            f"lockfile has no '{platform}' section (captured platforms: "
            f"{sorted(lock.get('platforms', {}))}) — cost diff skipped; "
            "run --update-costs on this platform to baseline it"
        )
        return diff
    tol = {**DEFAULT_TOLERANCES, **lock.get("tolerances", {})}
    locked_entries = section.get("entries", {})
    diff.stale = sorted(set(locked_entries) - set(live))
    for name in diff.stale:
        diff.notes.append(
            f"stale lockfile entry '{name}': no longer in the contract "
            "registry (remove via --update-costs)"
        )
    for name in sorted(live):
        if name not in locked_entries:
            diff.violations.append(
                f"{name}: not in the lockfile — new entries must be "
                "baselined via --update-costs"
            )
            continue
        diff.checked += 1
        lv, lk = live[name], locked_entries[name]
        prim_note = _prim_drift(lv["metrics"][-1], lk["metrics"][-1])
        if lv["geometries"] != lk["geometries"]:
            diff.violations.append(
                f"{name}: traced geometries {lv['geometries']} != lockfile "
                f"{lk['geometries']} (registry geometry changed — "
                "--update-costs)"
            )
            continue
        # Integer metrics: the tolerance is ABSOLUTE slack (0 = exact,
        # 1 = +-1, ...) — never a disable switch.
        if abs(lv["passes"] - lk["passes"]) > tol["passes"]:
            diff.violations.append(
                f"{name}: T-scaling pass count {lk['passes']} -> "
                f"{lv['passes']}; drifting prims: {prim_note}"
            )
        for gi, (lm, km) in enumerate(zip(lv["metrics"], lk["metrics"])):
            if abs(lm["n_eqns"] - km["n_eqns"]) > tol["n_eqns"]:
                diff.violations.append(
                    f"{name}@{lv['geometries'][gi]}: eqn count "
                    f"{km['n_eqns']} -> {lm['n_eqns']}; drifting prims: "
                    f"{_prim_drift(lm, km)}"
                )
                break  # one structural message per entry is enough
        for metric in ("flops", "bytes", "serial_depth"):
            for term in ("per_symbol", "fixed"):
                lvv = lv["fits"][metric][term]
                lkv = lk["fits"][metric][term]
                d = _rel_drift(lvv, lkv)
                if d > tol[metric]:
                    diff.violations.append(
                        f"{name}: {metric}.{term} {lkv:.6g} -> {lvv:.6g} "
                        f"({d:+.1%} > tol {tol[metric]:.0%}); drifting "
                        f"prims: {prim_note}"
                    )
        wb_l, wb_k = lv.get("while_body"), lk.get("while_body")
        if (wb_l is None) != (wb_k is None):
            diff.violations.append(
                f"{name}: while-body fingerprint "
                f"{'appeared' if wb_l else 'vanished'} vs lockfile"
            )
        elif wb_l and wb_k:
            for metric in ("flops", "bytes"):
                for term in ("per_symbol", "fixed"):
                    d = _rel_drift(wb_l[metric][term], wb_k[metric][term])
                    if d > tol[metric]:
                        diff.violations.append(
                            f"{name}: while_body.{metric}.{term} "
                            f"{wb_k[metric][term]:.6g} -> "
                            f"{wb_l[metric][term]:.6g} ({d:+.1%} > tol "
                            f"{tol[metric]:.0%}); drifting prims: "
                            f"{prim_note}"
                        )
    return diff


def update_summary(live: dict, lock: Optional[dict], platform: str) -> list:
    """Human-readable per-entry summary of what --update-costs changed."""
    out = []
    old = ((lock or {}).get("platforms", {}).get(platform, {})
           .get("entries", {}))
    for name in sorted(set(live) | set(old)):
        if name not in old:
            out.append(f"+ {name} (new entry)")
        elif name not in live:
            out.append(f"- {name} (stale entry removed)")
        else:
            lo, hi = old[name]["fits"]["flops"], live[name]["fits"]["flops"]
            if old[name] == live[name]:
                continue
            out.append(
                f"~ {name}: flops/sym {lo['per_symbol']:.4g} -> "
                f"{hi['per_symbol']:.4g}, fixed {lo['fixed']:.4g} -> "
                f"{hi['fixed']:.4g}; prims "
                f"{_prim_drift(live[name]['metrics'][-1], old[name]['metrics'][-1])}"
            )
    return out


# -- the quantitative contracts ----------------------------------------------


def _dense_pair_contract(traced: dict) -> ContractResult:
    violations, notes = [], {}
    S = _n_states()
    for name, e in traced.items():
        if "onehot" not in name or len(e.geometries) < 2:
            continue
        bad = e.dense_pair_eqns(S)
        for c in bad[:4]:
            violations.append(
                f"{name}: {c.prim} in {c.group} materializes "
                f"{c.out_elems / e.geometries[-1]:.0f} result elems/symbol "
                f">= S^2/2={S * S // 2} — an O(T*S^2) dense-pair tensor on "
                "a reduced path (the r4 reduction exists to delete these)"
            )
    notes["reduced_entries_checked"] = sum(
        1 for n, e in traced.items()
        if "onehot" in n and len(e.geometries) >= 2
    )
    return ContractResult(
        name="cost.reduced-no-dense-pair", ok=not violations,
        violations=violations, notes=notes,
    )


def _fixed_share_contract(bodies: dict) -> ContractResult:
    violations, notes = [], {}
    wb = bodies.get("em.fused")
    if wb is None:
        violations.append(
            "fused EM trace produced no while-loop body (the fused driver's "
            "structure changed under this contract)"
        )
    else:
        for metric in ("flops", "bytes"):
            fit = costmodel.LinearFit(**wb[metric])
            total = fit.at(REFERENCE_T)
            share = max(fit.fixed, 0.0) / max(total, 1.0)
            notes[f"{metric}_fixed_share_16Mi"] = round(share, 9)
            if share > FIXED_SHARE_MAX:
                violations.append(
                    f"fused EM while-body fixed {metric} share at 16 Mi = "
                    f"{share:.2%} > {FIXED_SHARE_MAX:.0%} (fixed "
                    f"{fit.fixed:.3g} vs per-symbol {fit.per_symbol:.3g}) — "
                    "the per-iteration epilogue grew beyond model-sized"
                )
    return ContractResult(
        name="cost.em-body-fixed-share", ok=not violations,
        violations=violations, notes=notes,
    )


def _pass_structure_contract(traced: dict) -> ContractResult:
    violations, notes = [], {}
    for name, expected in EXPECTED_PASSES.items():
        e = traced.get(name)
        if e is None:
            violations.append(f"{name}: pinned entry missing from registry")
            continue
        got = e.passes()
        notes[name] = got
        if got != expected:
            violations.append(
                f"{name}: {got} T-scaling sequential passes, documented "
                f"structure is {expected} (BASELINE.md pass accounting) — "
                "a pass was added or fused; re-document or fix"
            )
    return ContractResult(
        name="cost.pass-structure", ok=not violations, violations=violations,
        notes=notes,
    )


def _depth_scaling_contract(traced: dict) -> ContractResult:
    violations, notes = [], {}
    for name, e in traced.items():
        ceiling = next(
            (v for k, v in DEPTH_SLOPE_MAX.items() if name.startswith(k)),
            None,
        )
        if ceiling is None or len(e.geometries) < 2:
            continue
        slope = e.fits()["serial_depth"].per_symbol
        notes[name] = round(slope, 7)
        if slope > ceiling:
            violations.append(
                f"{name}: serial depth grows {slope:.4g} steps/symbol > "
                f"{ceiling} — the sequential chain scales with T, not "
                "lanes (a per-symbol serial walk re-entered this path)"
            )
    return ContractResult(
        name="cost.serial-depth-lanes", ok=not violations,
        violations=violations, notes=notes,
    )


def run_cost_contracts(traced=None, bodies=None) -> list:
    """The quantitative contracts on live traces (CPU XLA twins)."""
    if traced is None:
        traced, bodies = trace_all()
    return [
        _dense_pair_contract(traced),
        _fixed_share_contract(bodies or {}),
        _pass_structure_contract(traced),
        _depth_scaling_contract(traced),
    ]


# -- the full pass (CLI / CI / bench / driver entry) -------------------------


def run_cost_pass(
    lockfile_path: Optional[str] = None, update: bool = False
) -> dict:
    """Trace, diff against the lockfile, run the quantitative contracts.

    Returns {"ok", "diff", "contracts", "updated", "summary"} — the CLI,
    ci_checks.sh, __graft_entry__ and bench.py all consume this one shape.
    On a TPU backend the quantitative contracts are skipped (they pin the
    CPU XLA-twin structure; pallas bodies are opaque) and only the
    lockfile diff runs, against a 'tpu' section when one exists.
    """
    import jax

    platform = jax.default_backend()
    traced, bodies = trace_all()
    live = live_fingerprints(traced, bodies)
    lock = load_lockfile(lockfile_path)
    out: dict = {"platform": platform, "updated": False}
    if update:
        out["summary"] = update_summary(live, lock, platform)
        path = write_lockfile(live, lockfile_path, platform)
        out["updated"] = True
        out["path"] = path
        lock = load_lockfile(lockfile_path)
    diff = diff_costs(live, lock, platform)
    results = (
        run_cost_contracts(traced, bodies) if platform != "tpu" else []
    )
    out["diff"] = diff.as_dict()
    out["contracts"] = [r.as_dict() for r in results]
    out["ok"] = diff.ok and all(r.ok for r in results)
    return out


def format_failure(report: dict) -> str:
    """One-line JSON summary of a failing run_cost_pass report — the shared
    formatting for every caller that raises on it (bench parity gate,
    __graft_entry__ self-check)."""
    return json.dumps({
        "diff": report["diff"]["violations"],
        "contracts": {
            r["name"]: r["violations"]
            for r in report["contracts"] if not r["ok"]
        },
    })
