"""Layer 2: jaxpr contracts over the registered decode/posterior/EM entries.

The AST lint catches what source *spells*; this pass checks what the
traced graphs *contain*.  Every registered entry point is traced with
``jax.make_jaxpr`` on small abstract inputs — tracing needs no TPU, so the
whole pass certifies on CPU in seconds — and asserted against:

- **no-f64**: no float64/complex128 values anywhere in the graph (device
  paths are f32/int; an f64 leak silently halves VPU throughput on chip
  and usually means a stray numpy double crossed the trace boundary);
- **no-callbacks**: no ``pure_callback``/``io_callback``/``debug_callback``
  primitives in hot graphs (a callback is a host round trip per invocation
  — 50-100 ms each over this setup's relay);
- **pallas-free off-TPU**: the reduced (onehot) engines must trace to
  their XLA scan twins off-TPU — the Pallas interpreter evaluates the
  select-derived backpointer chains pathologically slowly (CLAUDE.md), so
  a pallas_call in a CPU graph of these entries is a routing bug.  On TPU
  the same entries must *contain* pallas_call (the kernels actually
  engaged on the silicon that produces published numbers — bench.py's
  parity phase re-checks this on the capturing backend);
- **auto-routing off-TPU**: ``resolve_*_engine("auto")`` must never pick a
  Pallas lowering off-TPU, and ``get_passes`` must resolve every engine —
  i.e., every TPU kernel engine has a registered off-TPU twin;
- **dispatch stability**: executing an entry twice on same-shape inputs
  must not recompile (``obs.no_new_compiles`` — the recompile sentinel
  from PR 1), so steady-state loops stay one-dispatch.

Run via ``python -m cpgisland_tpu.analysis --contracts``, from
``tests/test_graftcheck_self.py``, from ``bench.py --extended``'s parity
phase, and from ``__graft_entry__.py``'s self-check.
"""

from __future__ import annotations

import dataclasses
from typing import Callable, Iterable, Optional

CALLBACK_PRIMS = frozenset({
    "pure_callback", "io_callback", "debug_callback", "callback",
    "host_callback", "outside_call",
})
BANNED_DTYPES = ("float64", "complex128")


@dataclasses.dataclass
class Contract:
    name: str
    # (scale=1) -> (fn, args, args2) — args2 is a same-shape/different-data
    # input set for the dispatch-stability check (None skips it).  ``scale``
    # multiplies the entry's time geometry (symbol count); the cost layer
    # (analysis/costmodel.py) traces each entry at >=2 scales to decompose
    # per-symbol vs fixed cost.  Entries with no time geometry (e.g. the
    # model-sized M-step) set ``scalable=False`` and ignore ``scale``.
    make: Callable[..., tuple]
    allow_pallas_off_tpu: bool = False
    expect_pallas_on_tpu: bool = False
    stability: bool = False
    allow_f64: bool = False
    scalable: bool = True
    base_symbols: int = 0  # symbols traced at scale=1 (0 = no time geometry)
    # Geometry scales the cost layer traces at.  The FB/lane entries pad up
    # to the 128-lane grid, so their scales must put BOTH geometries past
    # the padding plateau (base 4096-8192 x 16/32 = 128/256 lanes at
    # lane_T=512) or every metric reads as "fixed".  Tracing is abstract —
    # a big geometry costs the same to trace as a small one.
    cost_scales: tuple = (1, 2)


@dataclasses.dataclass
class ContractResult:
    name: str
    ok: bool
    violations: list
    notes: dict

    def as_dict(self) -> dict:
        return dataclasses.asdict(self)


def _sub_jaxprs(value):
    import jax

    if isinstance(value, jax.core.ClosedJaxpr):
        yield value.jaxpr
    elif isinstance(value, jax.core.Jaxpr):
        yield value
    elif isinstance(value, (list, tuple)):
        for v in value:
            yield from _sub_jaxprs(v)
    elif isinstance(value, dict):
        for v in value.values():
            yield from _sub_jaxprs(v)


def _walk_eqns(jaxpr, seen=None):
    seen = seen if seen is not None else set()
    if id(jaxpr) in seen:
        return
    seen.add(id(jaxpr))
    for eqn in jaxpr.eqns:
        yield eqn
        for v in eqn.params.values():
            for sub in _sub_jaxprs(v):
                yield from _walk_eqns(sub, seen)


def inspect_jaxpr(closed) -> dict:
    """Primitive counts + banned-dtype sightings for a ClosedJaxpr."""
    prims: dict[str, int] = {}
    bad_dtypes: list[str] = []
    for eqn in _walk_eqns(closed.jaxpr):
        name = eqn.primitive.name
        prims[name] = prims.get(name, 0) + 1
        for var in eqn.outvars:
            aval = getattr(var, "aval", None)
            dt = str(getattr(aval, "dtype", ""))
            if dt in BANNED_DTYPES:
                bad_dtypes.append(f"{name} -> {dt}")
    return {"prims": prims, "bad_dtypes": bad_dtypes}


def check_contract(c: Contract, execute: bool = True) -> ContractResult:
    import jax

    from cpgisland_tpu import obs as obs_mod

    on_tpu = jax.default_backend() == "tpu"
    violations: list[str] = []
    notes: dict = {"backend": jax.default_backend()}
    fn, args, args2 = c.make()
    closed = jax.make_jaxpr(fn)(*args)
    info = inspect_jaxpr(closed)
    n_pallas = info["prims"].get("pallas_call", 0)
    notes["pallas_calls"] = n_pallas
    notes["n_eqns"] = sum(info["prims"].values())

    for cb in sorted(set(info["prims"]) & CALLBACK_PRIMS):
        violations.append(
            f"callback primitive {cb!r} in hot graph "
            f"(x{info['prims'][cb]}): each invocation is a host round trip"
        )
    if info["bad_dtypes"] and not c.allow_f64:
        violations.append(
            "f64 on the device path: " + ", ".join(info["bad_dtypes"][:5])
        )
    if not on_tpu and n_pallas and not c.allow_pallas_off_tpu:
        violations.append(
            f"{n_pallas} pallas_call(s) in the off-TPU graph: this entry "
            "must route to its XLA twin off-TPU (interpreter pathology)"
        )
    if on_tpu and c.expect_pallas_on_tpu and not n_pallas:
        violations.append(
            "no pallas_call in the TPU graph: the kernels this entry "
            "certifies did not engage"
        )

    if execute and c.stability and args2 is not None:
        try:
            jax.block_until_ready(fn(*args))  # warm the cache
            with obs_mod.no_new_compiles(tag=f"contract:{c.name}"):
                jax.block_until_ready(fn(*args2))
        except obs_mod.RecompileError as e:
            violations.append(f"dispatch surface unstable: {e}")
        else:
            notes["stability"] = "ok"

    return ContractResult(
        name=c.name, ok=not violations, violations=violations, notes=notes
    )


# Symbol-stream prep markers: the reduced pair stream's two-level
# forward-fill is the ONLY cummax on any EM path (viterbi_onehot.pair_stream
# — the sequential symbol-only derivation ops.prepared hoists out of the
# loop), so a cummax inside the fused EM while body means the prep was
# re-materialized per iteration.
PREP_MARKER_PRIMS = frozenset({"cummax"})


def while_body_prims(closed) -> dict:
    """Primitive counts restricted to while-loop BODY jaxprs (all nesting
    levels) of a ClosedJaxpr — the fused EM loop's per-iteration cost."""
    counts: dict[str, int] = {}
    for eqn in _walk_eqns(closed.jaxpr):
        if eqn.primitive.name != "while":
            continue
        for sub in _sub_jaxprs(eqn.params.get("body_jaxpr")):
            for inner in _walk_eqns(sub):
                counts[inner.primitive.name] = (
                    counts.get(inner.primitive.name, 0) + 1
                )
    return counts


def fused_em_make(scale: int = 1, with_prep: bool = True):
    """(fn, args) for the fused-EM while-loop program on the flagship
    chunked onehot backend at a scaled geometry — shared by the
    ``em.body.invariant-free`` contract and the cost layer's ``em.fused``
    entry (analysis/costmodel.py).  Returns (fn, args, prep): ``prep`` is
    the resolved PreparedStreams (None when the backend produced none —
    itself a violation the caller reports)."""
    import jax.numpy as jnp

    from cpgisland_tpu.train import baum_welch
    from cpgisland_tpu.train.backends import LocalBackend

    params = _flagship()
    n = 8 * scale
    o1, _ = _obs_pair(n * 1024, "uint8")
    chunks = jnp.asarray(o1).reshape(n, 1024)
    lengths = jnp.full(n, 1024, jnp.int32)
    backend = LocalBackend(mode="rescaled", engine="onehot")
    if with_prep:
        stats_fn, prep = backend.fused_stats_with_prep(params, chunks, lengths)
    else:
        # The inline-prep twin never consumes prepared streams — don't pay
        # the prep build just to discard it.
        stats_fn = backend.fused_stats_fn(params, chunks, lengths)
        prep = None
    p32 = params.astype(jnp.float32)
    fn = baum_welch._fused_em_fn(stats_fn, 3, with_prep)
    args = (p32, chunks, lengths, jnp.float32(0.0), prep)
    return fn, args, prep


def _em_body_contract() -> ContractResult:
    """em.body.invariant-free: the fused EM while_loop body jaxpr must
    contain NO symbol-stream prep primitives when prepared streams are
    threaded (train.backends.*.fused_stats_with_prep -> baum_welch's
    prepared-aware loop).  Self-proving: the SAME program traced WITHOUT
    the prepared streams must show the markers — if it doesn't, the marker
    set has rotted and the contract fails rather than passing vacuously.
    """
    import jax

    violations: list[str] = []
    notes: dict = {"backend": jax.default_backend()}
    fn, args, prep = fused_em_make()
    if prep is None:
        violations.append(
            "LocalBackend(engine='onehot') returned no prepared streams — "
            "the fused EM loop would re-prepare per iteration"
        )
    else:
        closed = jax.make_jaxpr(fn)(*args)
        body = while_body_prims(closed)
        notes["body_eqns"] = sum(body.values())
        hits = sorted(set(body) & PREP_MARKER_PRIMS)
        if not body:
            violations.append(
                "no while-loop body found in the fused EM trace (the fused "
                "driver's structure changed under this contract)"
            )
        if hits:
            violations.append(
                "symbol-stream prep primitives inside the fused EM while "
                f"body: {hits} — the prepared streams did not reach the loop"
            )
        # Detector self-proof on the synthetic violation: the inline-prep
        # twin of the same loop MUST show the markers.
        fn0, args0, _ = fused_em_make(with_prep=False)
        closed0 = jax.make_jaxpr(fn0)(*args0)
        body0 = while_body_prims(closed0)
        notes["inline_markers"] = sorted(set(body0) & PREP_MARKER_PRIMS)
        if not set(body0) & PREP_MARKER_PRIMS:
            violations.append(
                "detector self-proof failed: the inline-prep loop body "
                "shows no prep markers (PREP_MARKER_PRIMS has rotted)"
            )
    return ContractResult(
        name="em.body.invariant-free", ok=not violations,
        violations=violations, notes=notes,
    )


def _serve_flush_contract() -> ContractResult:
    """serve.flush.dispatch-stable: the broker's flush program must be
    dispatch-stable across requests — after one warmup flush per geometry
    (pow2-padded record shapes), further flushes of the SAME geometry must
    trigger ZERO fresh XLA compiles (``obs.no_new_compiles``).  A daemon
    that recompiles per request would pay the remote-compile HTTP round
    trip on the serving path, which is exactly what the broker's pow2
    padding discipline (shared with the batch pipelines) exists to prevent.
    """
    import numpy as np

    from cpgisland_tpu import obs as obs_mod
    from cpgisland_tpu.serve.broker import BrokerConfig, RequestBroker
    from cpgisland_tpu.serve.session import Session

    violations: list[str] = []
    notes: dict = {}

    def stream(broker: RequestBroker, seed: int, base: int) -> None:
        # Mixed decode + posterior, two tenants, fixed length set (the
        # geometry); content varies per seed so a stale-constant cache hit
        # cannot masquerade as shape stability.
        rng = np.random.default_rng(seed)
        for i, n in enumerate((900, 1500, 2200, 3100)):
            broker.submit(
                request_id=base + i,
                tenant="t0" if i % 2 == 0 else "t1",
                kind="decode" if i % 2 == 0 else "posterior",
                symbols=rng.integers(0, 4, size=n).astype(np.uint8),
                name=f"r{base + i}",
            )
        broker.drain()

    try:
        sess = Session(_flagship(), name="contract", private_breaker=True)
        broker = RequestBroker(
            sess, BrokerConfig(flush_symbols=1 << 15, flush_deadline_s=0.0)
        )
        stream(broker, seed=0, base=0)  # warmup: compiles per geometry
        notes["warm_flushes"] = broker.flushes
        try:
            with obs_mod.no_new_compiles("serve.flush") as led:
                stream(broker, seed=1, base=100)
            notes["steady_compiles"] = led.compiles
        except obs_mod.RecompileError as e:
            violations.append(str(e))
        notes["flushes"] = broker.flushes
    except Exception as e:  # a broker that cannot serve at all is a failure
        violations.append(f"broker run failed: {type(e).__name__}: {e}")
    return ContractResult(
        name="serve.flush.dispatch-stable", ok=not violations,
        violations=violations, notes=notes,
    )


def _routing_contract() -> ContractResult:
    """Off-TPU, 'auto' must resolve to non-Pallas engines, and get_passes
    must resolve every engine name (every TPU engine has an off-TPU twin)."""
    import jax

    from cpgisland_tpu.models import presets
    from cpgisland_tpu.ops.viterbi_parallel import get_passes
    from cpgisland_tpu.parallel.decode import resolve_engine
    from cpgisland_tpu.parallel.posterior import resolve_fb_engine as post_eng
    from cpgisland_tpu.train.backends import resolve_fb_engine as train_eng

    params = presets.durbin_cpg8()
    on_tpu = jax.default_backend() == "tpu"
    violations: list[str] = []
    notes: dict = {"backend": jax.default_backend()}
    picks = {
        "decode": resolve_engine("auto", params),
        "posterior": post_eng("auto", params),
        "train": train_eng("auto", params, "rescaled"),
    }
    notes["auto_picks"] = picks
    if not on_tpu:
        for site, pick in picks.items():
            if pick in ("pallas", "onehot"):
                violations.append(
                    f"{site} auto-routes engine {pick!r} off-TPU (Pallas "
                    "lowerings are TPU-only; off-TPU must pick the XLA twin)"
                )
    for eng in ("xla", "pallas", "onehot"):
        try:
            passes = get_passes(eng)
            if len(passes) != 3 or not all(callable(p) for p in passes):
                raise TypeError("engine did not resolve to a pass triple")
        except Exception as e:
            violations.append(f"get_passes({eng!r}) has no registered twin: {e}")
    return ContractResult(
        name="engines.routing", ok=not violations, violations=violations,
        notes=notes,
    )


# -- the entry-point registry ------------------------------------------------


def _flagship():
    from cpgisland_tpu.models import presets

    return presets.durbin_cpg8()


def _obs_pair(n: int, dtype, seeds=(0, 1)):
    import jax.numpy as jnp
    import numpy as np

    rngs = [np.random.default_rng(s) for s in seeds]
    return tuple(
        jnp.asarray(r.integers(0, 4, size=n).astype(dtype)) for r in rngs
    )


def _decode_contract(engine: str, **kw) -> Contract:
    def make(scale: int = 1):
        from cpgisland_tpu.ops.viterbi_parallel import viterbi_parallel

        params = _flagship()
        o1, o2 = _obs_pair(2048 * scale, "int32")
        fn = lambda o: viterbi_parallel(
            params, o, block_size=256, return_score=True, engine=engine
        )
        return fn, (o1,), (o2,)

    return Contract(
        name=f"decode.{engine}", make=make, base_symbols=2048, **kw
    )


def _decode_batch_flat_contract(return_score: bool = False) -> Contract:
    def make(scale: int = 1):
        from cpgisland_tpu.ops.viterbi_parallel import viterbi_parallel_batch

        params = _flagship()
        T = 512 * scale
        o1, o2 = _obs_pair(4 * T, "int32")
        import jax.numpy as jnp

        lengths = jnp.full(4, T, jnp.int32)
        fn = lambda c: viterbi_parallel_batch(
            params, c.reshape(4, T), lengths, block_size=256,
            return_score=return_score, engine="onehot",
        )
        return fn, (o1,), (o2,)

    tag = "scores.onehot" if return_score else "onehot"
    return Contract(
        name=f"decode.batch_flat.{tag}", make=make, expect_pallas_on_tpu=True,
        base_symbols=4 * 512,
    )


def _posterior_contract(onehot: bool, one_pass: bool = False, **kw) -> Contract:
    def make(scale: int = 1):
        import jax.numpy as jnp
        import numpy as np

        from cpgisland_tpu.ops import fb_pallas

        params = _flagship()
        o1, o2 = _obs_pair(4096 * scale, "uint8")
        mask = jnp.asarray((np.arange(8) < 4).astype(np.float32))
        fn = lambda o: fb_pallas._seq_posterior_core(
            params, o, o.shape[0], mask, 512, 256, axis=None, onehot=onehot,
            one_pass=one_pass,
        )[0]
        return fn, (o1,), (o2,)

    tag = "onehot" if onehot else "dense"
    if one_pass:
        tag += ".onepass"
    return Contract(
        name=f"posterior.{tag}", make=make, base_symbols=4096,
        cost_scales=(16, 32), **kw
    )


def _em_chunked_contract(engine: str, **kw) -> Contract:
    def make(scale: int = 1):
        import jax.numpy as jnp

        from cpgisland_tpu.train.backends import LocalBackend

        params = _flagship()
        # Scale the CHUNK COUNT (the per-symbol axis of this layout); chunk
        # length is the reference's fixed 64 Ki-class geometry.
        n = 8 * scale
        o1, o2 = _obs_pair(n * 1024, "uint8")
        lengths = jnp.full(n, 1024, jnp.int32)
        backend = LocalBackend(mode="rescaled", engine=engine)
        fn = lambda c: backend(params, c.reshape(n, 1024), lengths)
        return fn, (o1,), (o2,)

    return Contract(
        name=f"em.chunked.{engine}", make=make, base_symbols=8 * 1024,
        cost_scales=(16, 32), **kw
    )


def _em_seq_contract(onehot: bool, one_pass: bool = False, **kw) -> Contract:
    def make(scale: int = 1):
        from cpgisland_tpu.ops import fb_pallas

        params = _flagship()
        o1, o2 = _obs_pair(8192 * scale, "uint8")
        fn = lambda o: fb_pallas.seq_stats_pallas(
            params, o, o.shape[0], lane_T=512, t_tile=256, onehot=onehot,
            one_pass=one_pass,
        )
        return fn, (o1,), (o2,)

    tag = "onehot" if onehot else "dense"
    if one_pass:
        tag += ".onepass"
    return Contract(
        name=f"em.seq.{tag}", make=make, base_symbols=8192,
        cost_scales=(16, 32), **kw
    )


def _pair_obs(n: int, seeds=(0, 1)):
    """Pair-recoded observation pair for the order-2 family entries (prev
    threaded so the first position is real — the reduced engines' entry
    contract)."""
    import jax.numpy as jnp
    import numpy as np

    from cpgisland_tpu.utils import codec

    out = []
    for s in seeds:
        r = np.random.default_rng(s)
        base = r.integers(0, 4, size=n + 1).astype(np.uint8)
        out.append(jnp.asarray(
            codec.recode_pairs(base[1:], prev=int(base[0])).astype(np.int32)
        ))
    return tuple(out)


def _decode_family_contract() -> Contract:
    """decode.family.dinuc_cpg: the order-2 dinucleotide member through the
    REDUCED engine — the family layer's generalization claim as a traced
    contract (16 blocks of 2; the same pass triple as decode.onehot, off-TPU
    it must trace to the XLA twins)."""

    def make(scale: int = 1):
        from cpgisland_tpu.models import presets
        from cpgisland_tpu.ops.viterbi_parallel import viterbi_parallel

        params = presets.dinuc_cpg()
        o1, o2 = _pair_obs(2048 * scale)
        fn = lambda o: viterbi_parallel(
            params, o, block_size=256, return_score=True, engine="onehot"
        )
        return fn, (o1,), (o2,)

    return Contract(
        name="decode.family.dinuc_cpg", make=make,
        expect_pallas_on_tpu=True, base_symbols=2048,
    )


def _fb_family_contract() -> Contract:
    """fb.family.dinuc_cpg: the dinucleotide member's forward-backward
    (posterior marginals) through the plain dense XLA route — the reduced
    engines' parity TWIN for the K=32 member (which, since the K<=8 lift,
    also routes reduced through resolve_fb_engine); this entry pins the
    twin itself (no pallas anywhere, f64/callback-free, dispatch-stable)."""

    def make(scale: int = 1):
        import jax.numpy as jnp

        from cpgisland_tpu.models import presets
        from cpgisland_tpu.ops.forward_backward import posterior_marginals

        params = presets.dinuc_cpg()
        o1, o2 = _pair_obs(2048 * scale)
        fn = lambda o: posterior_marginals(params, o)[0]
        return fn, (o1,), (o2,)

    return Contract(
        name="fb.family.dinuc_cpg", make=make, base_symbols=2048,
        stability=True,
    )


def _compare_loglik_contract() -> Contract:
    """compare.loglik: the comparison workload's scoring pass
    (forward_backward.sequence_loglik) — per-model log-odds are differences
    of this program's outputs, so it must stay f64/callback-free and
    dispatch-stable across same-shape records."""

    def make(scale: int = 1):
        from cpgisland_tpu.models import presets
        from cpgisland_tpu.ops.forward_backward import sequence_loglik

        params = presets.durbin_cpg8()
        o1, o2 = _obs_pair(2048 * scale, "int32")
        fn = lambda o: sequence_loglik(params, o)
        return fn, (o1,), (o2,)

    return Contract(
        name="compare.loglik", make=make, base_symbols=2048, stability=True,
    )


def _family_trio():
    """Three same-alphabet reduced members — the stacked contracts' cast
    (flagship + two random one-hot-partitioned families)."""
    import jax

    from cpgisland_tpu.models import presets

    return (
        presets.durbin_cpg8(),
        presets.random_hmm(jax.random.PRNGKey(1), 8, 4, partition=2),
        presets.random_hmm(jax.random.PRNGKey(2), 8, 4, partition=2),
    )


def _posterior_stacked_contract() -> Contract:
    """posterior.onehot.stacked3: THREE members' reduced chains in one
    stacked launch set — the pass pin asserts the multi-model posterior
    costs ONE pass set (2 T-scaling passes), not 3x (the de-stacking
    regression graftcost exists to catch)."""

    def make(scale: int = 1):
        import jax.numpy as jnp
        import numpy as np

        from cpgisland_tpu.ops import fb_pallas

        params_list = _family_trio()
        o1, o2 = _obs_pair(4096 * scale, "uint8")
        masks = tuple(
            jnp.asarray((np.arange(8) < 4).astype(np.float32))
            for _ in params_list
        )
        fn = lambda o: fb_pallas._seq_posterior_core_stacked(
            params_list, o, o.shape[0], masks, 512, 256, axis=None
        )[0]
        return fn, (o1,), (o2,)

    return Contract(
        name="posterior.onehot.stacked3", make=make, base_symbols=4096,
        cost_scales=(16, 32), expect_pallas_on_tpu=True,
    )


def _em_chunked_stacked_contract() -> Contract:
    """em.chunked.onehot.stacked3: the stacked multi-model E-step
    (train.backends.FamilyEStep) — ONE co-scheduled chain pass for all
    three members."""

    def make(scale: int = 1):
        import jax.numpy as jnp

        from cpgisland_tpu.train.backends import FamilyEStep

        params_list = _family_trio()
        n = 8 * scale
        o1, o2 = _obs_pair(n * 1024, "uint8")
        lengths = jnp.full(n, 1024, jnp.int32)
        estep = FamilyEStep()
        fn = lambda c: estep(params_list, c.reshape(n, 1024), lengths)
        return fn, (o1,), (o2,)

    return Contract(
        name="em.chunked.onehot.stacked3", make=make, base_symbols=8 * 1024,
        cost_scales=(16, 32), expect_pallas_on_tpu=True,
    )


def _decode_batch_flat_stacked_contract() -> Contract:
    """decode.batch_flat.onehot.stacked3: three members' flat batched
    decode in one stacked pass triple (shared reset-step stream)."""

    def make(scale: int = 1):
        import jax.numpy as jnp

        from cpgisland_tpu.ops.viterbi_onehot import (
            decode_batch_flat_stacked_jit,
        )

        params_list = _family_trio()
        T = 512 * scale
        o1, o2 = _obs_pair(4 * T, "int32")
        lengths = jnp.full(4, T, jnp.int32)
        fn = lambda c: decode_batch_flat_stacked_jit(
            params_list, c.reshape(4, T), lengths, block_size=256
        )
        return fn, (o1,), (o2,)

    return Contract(
        name="decode.batch_flat.onehot.stacked3", make=make,
        expect_pallas_on_tpu=True, base_symbols=4 * 512,
    )


def _mstep_contract() -> Contract:
    def make(scale: int = 1):
        import jax.numpy as jnp

        from cpgisland_tpu.ops.forward_backward import SuffStats
        from cpgisland_tpu.train.baum_welch import mstep

        params = _flagship()
        K, M = params.n_states, params.n_symbols

        def stats(scale):
            return SuffStats(
                init=jnp.full((K,), scale), trans=jnp.full((K, K), scale),
                emit=jnp.full((K, M), scale), loglik=jnp.float32(-scale),
                n_seqs=jnp.float32(1.0),
            )

        return mstep, (params, stats(1.0)), (params, stats(2.0))

    return Contract(name="em.mstep", make=make, stability=True, scalable=False)


def default_contracts() -> list[Contract]:
    """The registry: one entry per (path, engine) the published numbers and
    the test suite rely on.  Expectations encode CLAUDE.md's routing rules:
    dense Pallas engines MAY appear off-TPU only under the interpreter
    (tests exercise them); the reduced onehot engines must trace to their
    XLA twins off-TPU and to real kernels on TPU."""
    return [
        _decode_contract("xla", stability=True),
        _decode_contract("pallas", allow_pallas_off_tpu=True,
                         expect_pallas_on_tpu=True),
        _decode_contract("onehot", expect_pallas_on_tpu=True),
        _decode_batch_flat_contract(),
        # The r6 score path: exact per-record scores off the flat stream
        # (the vmap route is explicit-opt-in only — VERDICT r5 #3).
        _decode_batch_flat_contract(return_score=True),
        _posterior_contract(False, allow_pallas_off_tpu=True,
                            expect_pallas_on_tpu=True),
        _posterior_contract(True, expect_pallas_on_tpu=True),
        # The true-one-pass matrix arm (ISSUE 17): the products pass folded
        # into the co-scheduled launch — ONE T-scaling pass, pinned in
        # EXPECTED_PASSES next to the retained 2-pass entries above.
        _posterior_contract(True, one_pass=True, expect_pallas_on_tpu=True),
        _em_chunked_contract("xla", stability=True),
        _em_chunked_contract("onehot", expect_pallas_on_tpu=True),
        _em_seq_contract(True, expect_pallas_on_tpu=True),
        _em_seq_contract(True, one_pass=True, expect_pallas_on_tpu=True),
        _mstep_contract(),
        # Model-family entries: the order-2 dinucleotide member through the
        # reduced decode engine + its dense FB route, and the comparison
        # workload's scoring pass (family.compare).
        _decode_family_contract(),
        _fb_family_contract(),
        _compare_loglik_contract(),
        # Multi-model kernel occupancy (ROADMAP item 2): N members' chains
        # in ONE launch set — the pass pins assert constant T-scaling pass
        # counts in N (a de-stacked member re-growing its own pass set is
        # a red build naming the regrown scans).
        _posterior_stacked_contract(),
        _em_chunked_stacked_contract(),
        _decode_batch_flat_stacked_contract(),
    ]


def run_contracts(
    names: Optional[Iterable[str]] = None, execute: bool = True
) -> list[ContractResult]:
    """Trace + check every registered contract (plus the routing check).

    ``execute=False`` skips the dispatch-stability executions (pure
    tracing — used where dispatches are expensive, e.g. a relayed TPU).
    """
    wanted = set(names) if names is not None else None
    results: list[ContractResult] = []
    if wanted is None or "engines.routing" in wanted:
        results.append(_routing_contract())
    if wanted is None or "em.body.invariant-free" in wanted:
        try:
            results.append(_em_body_contract())
        except Exception as e:
            results.append(
                ContractResult(
                    name="em.body.invariant-free", ok=False,
                    violations=[f"trace failed: {type(e).__name__}: {e}"],
                    notes={},
                )
            )
    # The serve contract EXECUTES flushes (that is the point — compile
    # stability is a runtime property), so it follows the same
    # execute-gating as the stability contracts: skipped where dispatches
    # are expensive (execute=False, e.g. a relayed TPU).
    if execute and (wanted is None or "serve.flush.dispatch-stable" in wanted):
        results.append(_serve_flush_contract())
    for c in default_contracts():
        if wanted is not None and c.name not in wanted:
            continue
        try:
            results.append(check_contract(c, execute=execute))
        except Exception as e:  # a contract that cannot even trace is a failure
            results.append(
                ContractResult(
                    name=c.name, ok=False,
                    violations=[f"trace failed: {type(e).__name__}: {e}"],
                    notes={},
                )
            )
    return results


def summarize(results: list[ContractResult]) -> dict:
    """Compact summary for bench extras / metrics sidecars."""
    return {
        "checked": len(results),
        "ok": all(r.ok for r in results),
        "violations": {
            r.name: r.violations for r in results if not r.ok
        },
    }
