"""R4 ``maxplus-normalize`` and R5 ``no-stats-in-bwd-chain``.

R4 — max-plus scores drift ~-1.3 nat/symbol, so an unnormalized f32
product chain reaches magnitudes where the ulp exceeds the O(1) per-state
differences every argmax depends on (ops.viterbi_parallel.nrm_maxplus).
Inside ``parallel/`` (the cross-device stitching layer, where a missed
normalization silently corrupts genome-scale decodes), every
``maxplus_matmul`` combine must flow straight into ``nrm_maxplus`` /
``nrm_maxplus_vec`` / ``scan_block_products`` (or the probability-space
``_nrm_m``/``_nrm_v`` twins).

R5 — count-tensor accumulation inside the sequential backward walk is
banned (CLAUDE.md: it serializes the stats reduction into the recurrence
chain; the chunked path reduces counts in the separate throughput-bound
stats pass).  The exemption is light per-position *emission* that never
re-enters the carry — the ``_bwd_conf_kernel`` pattern — which this rule
does not flag (it only looks at additive self-updates).  Genuinely needed
carried sums take an inline waiver.
"""

from __future__ import annotations

import ast
import re
from typing import Iterator

from cpgisland_tpu.analysis import astutil
from cpgisland_tpu.analysis.core import FileContext, Finding, register

MAXPLUS_COMBINES = frozenset({"maxplus_matmul"})
NORMALIZERS = frozenset({
    "nrm_maxplus", "nrm_maxplus_vec", "scan_block_products", "_nrm_m", "_nrm_v",
})


@register(
    "maxplus-normalize",
    "max-plus combines in parallel/ must flow through nrm_maxplus "
    "(unnormalized f32 products quantize at genome length)",
    origin="CLAUDE.md: viterbi_parallel.scan_block_products / nrm_maxplus — "
    "f32 ulp exceeds per-state differences at chromosome magnitude",
)
def check_maxplus_normalize(ctx: FileContext) -> Iterator[Finding]:
    if "/parallel/" not in f"/{ctx.relpath}":
        return
    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.Call):
            continue
        name = ctx.call_name(node)
        if not astutil.matches(name, MAXPLUS_COMBINES):
            continue
        parent = getattr(node, "parent", None)
        if isinstance(parent, ast.Call) and astutil.matches(
            ctx.call_name(parent), NORMALIZERS
        ):
            continue
        yield ctx.finding(
            "maxplus-normalize",
            node,
            "maxplus_matmul result is not normalized in place; wrap it as "
            "nrm_maxplus(maxplus_matmul(...)) — unnormalized f32 max-plus "
            "products quantize per-state differences at genome length",
        )


STATS_NAME_RE = re.compile(
    r"(?i)(^|_)(xi|gamma|count|counts|stat|stats|trans|emit|init|acc|num|denom)"
    r"($|_|s$)"
)
SCAN_NAMES = frozenset({"jax.lax.scan", "lax.scan", "scan"})
FORI_NAMES = frozenset({"jax.lax.fori_loop", "lax.fori_loop", "fori_loop"})


def _is_reverse_scan(ctx: FileContext, call: ast.Call) -> bool:
    if not astutil.matches(ctx.call_name(call), SCAN_NAMES):
        return False
    for kw in call.keywords:
        if kw.arg == "reverse" and isinstance(kw.value, ast.Constant):
            return bool(kw.value.value)
    return False


def _body_functions(ctx: FileContext, call: ast.Call):
    """The function-ish first argument of a scan/fori call, resolved."""
    from cpgisland_tpu.analysis.rules_jit import _unwrap_target

    args = call.args
    if astutil.matches(ctx.call_name(call), FORI_NAMES):
        cand = args[2] if len(args) >= 3 else None
    else:
        cand = args[0] if args else None
    target = _unwrap_target(ctx, cand) if cand is not None else None
    return [target] if target is not None else []


def _bwd_contexts(ctx: FileContext):
    """(context_node, label) pairs whose bodies form a sequential backward
    walk: reverse=True scan bodies, and fori/loop bodies inside functions
    whose name marks them as backward kernels/assemblies."""
    for node in ast.walk(ctx.tree):
        if isinstance(node, ast.Call):
            if _is_reverse_scan(ctx, node):
                for body in _body_functions(ctx, node):
                    yield body, "reverse scan body"
        elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            if re.search(r"(^|_)(bwd|backward)(_|$)", node.name):
                for sub in ast.walk(node):
                    if isinstance(sub, ast.Call) and astutil.matches(
                        ctx.call_name(sub), FORI_NAMES
                    ):
                        for body in _body_functions(ctx, sub):
                            yield body, f"backward walk in {node.name!r}"


def _accumulations(body: ast.AST):
    """Additive self-updates onto stats-named targets inside ``body``."""
    for node in ast.walk(body):
        if isinstance(node, ast.AugAssign) and isinstance(node.op, ast.Add) \
                and isinstance(node.target, ast.Name) \
                and STATS_NAME_RE.search(node.target.id):
            yield node, node.target.id
        elif isinstance(node, ast.Assign) and len(node.targets) == 1 \
                and isinstance(node.targets[0], ast.Name) \
                and STATS_NAME_RE.search(node.targets[0].id):
            tname = node.targets[0].id
            v = node.value
            if isinstance(v, ast.BinOp) and isinstance(v.op, ast.Add) and any(
                isinstance(n, ast.Name) and n.id == tname
                for n in ast.walk(v)
            ):
                yield node, tname
        elif isinstance(node, ast.Call) and isinstance(node.func, ast.Attribute) \
                and node.func.attr == "add":
            # x.at[...].add(...) scatter-accumulate
            base = node.func.value
            if isinstance(base, ast.Subscript) and isinstance(
                base.value, ast.Attribute
            ) and base.value.attr == "at" and isinstance(
                base.value.value, ast.Name
            ) and STATS_NAME_RE.search(base.value.value.id):
                yield node, base.value.value.id


@register(
    "no-stats-in-bwd-chain",
    "no count-tensor accumulation inside sequential backward scan carries "
    "(reduce counts in a separate throughput-bound pass)",
    origin="CLAUDE.md: accumulating stats INSIDE the sequential backward "
    "walk is banned; light per-position emission (_bwd_conf_kernel) is the "
    "allowed exception",
)
def check_no_stats_in_bwd_chain(ctx: FileContext) -> Iterator[Finding]:
    seen: set[int] = set()
    for body, label in _bwd_contexts(ctx):
        for node, name in _accumulations(body):
            if id(node) in seen:
                continue
            seen.add(id(node))
            yield ctx.finding(
                "no-stats-in-bwd-chain",
                node,
                f"accumulation onto {name!r} inside a {label}: stats sums "
                "serialize into the backward recurrence chain; emit "
                "per-position values and reduce them in a separate pass "
                "(the _bwd_conf_kernel pattern is emission, not accumulation)",
            )
