"""graftcheck core: findings, inline waivers, file contexts, the rule run.

The lint layer is pure ``ast`` — no tracing, no devices, and the analysis
modules themselves import no jax (the parent package import does pull jax,
a hard dependency, via its compat-shim installer; that one-time import is
the whole cost).  Linting the full package takes well under a second, so
the CLI works as a pre-commit/CI gate on any host with the package's deps.

Waiver syntax (inline, reviewed like code; shown with a ``<rule>``
placeholder so this docstring is not itself parsed as a waiver)::

    x = big_table.item()  # graftcheck: allow(<rule>) -- <why>

A waiver on a code line covers findings reported on that line; a waiver on
a standalone comment line covers the next line (the first line of the
statement below it).  The ``-- <reason>`` is REQUIRED: a waiver without a
justification is itself a finding (``waiver-syntax``), so every exemption
in the tree documents why the rule does not apply.

Hot-path registration for the host-sync rule uses the same comment channel
(``# graftcheck: hot-path`` on or directly above a ``def``) plus the central
registry in :mod:`cpgisland_tpu.analysis.config`.
"""

from __future__ import annotations

import ast
import dataclasses
import os
import re
from typing import Callable, Iterable, Iterator, Optional

from cpgisland_tpu.analysis import astutil
from cpgisland_tpu.analysis.config import hot_functions_for

WAIVER_RE = re.compile(
    r"#\s*graftcheck:\s*allow\(([\w\-, ]+)\)(?:\s*--\s*(?P<reason>.*\S))?"
)
HOT_MARK_RE = re.compile(r"#\s*graftcheck:\s*hot-path\b")


@dataclasses.dataclass
class Finding:
    rule: str
    path: str
    line: int
    col: int
    message: str
    waived: bool = False
    waiver_reason: str = ""

    def format(self) -> str:
        tag = " (waived: %s)" % self.waiver_reason if self.waived else ""
        return f"{self.path}:{self.line}:{self.col}: [{self.rule}] {self.message}{tag}"

    def as_dict(self) -> dict:
        return dataclasses.asdict(self)


@dataclasses.dataclass
class Waiver:
    line: int  # line the waiver comment sits on (1-based)
    rules: tuple[str, ...]
    reason: str
    applies_to: int  # line whose findings it covers
    used: bool = False


def source_comments(source: str) -> dict[int, tuple[str, bool]]:
    """line -> (comment text, standalone?) via tokenize, so waiver/hot-path
    markers inside string literals and docstrings are NOT parsed as live.
    Falls back to a plain line scan if tokenization fails."""
    import io
    import tokenize

    out: dict[int, tuple[str, bool]] = {}
    try:
        for tok in tokenize.generate_tokens(io.StringIO(source).readline):
            if tok.type == tokenize.COMMENT:
                out[tok.start[0]] = (tok.string, tok.line.lstrip().startswith("#"))
    except (tokenize.TokenError, IndentationError, SyntaxError):
        for i, text in enumerate(source.splitlines(), start=1):
            if "#" in text:
                _, _, comment = text.partition("#")
                out[i] = ("#" + comment, text.lstrip().startswith("#"))
    return out


def parse_waivers(source: str) -> tuple[list[Waiver], list[tuple[int, str]]]:
    """Returns (waivers, syntax_errors) for one file's comments."""
    waivers: list[Waiver] = []
    errors: list[tuple[int, str]] = []
    for i, (text, standalone) in sorted(source_comments(source).items()):
        m = WAIVER_RE.search(text)
        if not m:
            if re.search(r"graftcheck:\s*allow", text):
                errors.append(
                    (i, "malformed waiver; expected "
                        "'# graftcheck: allow(<rule>) -- <reason>'")
                )
            continue
        rules = tuple(r.strip() for r in m.group(1).split(",") if r.strip())
        reason = (m.group("reason") or "").strip()
        if not reason:
            errors.append(
                (i, "waiver missing its justification "
                    "('# graftcheck: allow(<rule>) -- <reason>')")
            )
            continue
        waivers.append(
            Waiver(line=i, rules=rules, reason=reason,
                   applies_to=i + 1 if standalone else i)
        )
    return waivers, errors


class FileContext:
    """Everything a rule needs about one source file, parsed once."""

    def __init__(self, path: str, source: str, relpath: Optional[str] = None):
        self.path = path
        self.relpath = (relpath or path).replace(os.sep, "/")
        self.source = source
        self.lines = source.splitlines()
        self.tree = astutil.attach_parents(ast.parse(source, filename=path))
        self.imports = astutil.ImportMap(self.tree)
        self.module_ints = {
            **astutil.imported_int_constants(self.tree, self.imports),
            **astutil.module_int_constants(self.tree),
        }
        self.comments = source_comments(source)
        self.waivers, self.waiver_errors = parse_waivers(source)
        self.hot_functions = self._collect_hot_functions()

    def _collect_hot_functions(self) -> set[str]:
        hot = set(hot_functions_for(self.relpath))
        marked = {
            ln for ln, (text, _) in self.comments.items()
            if HOT_MARK_RE.search(text)
        }
        for node in ast.walk(self.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                deco_first = min(
                    [d.lineno for d in node.decorator_list] or [node.lineno]
                )
                if marked & {node.lineno, node.lineno - 1, deco_first - 1}:
                    hot.add(node.name)
        return hot

    def call_name(self, call: ast.Call) -> Optional[str]:
        return astutil.call_name(self.imports, call)

    def finding(self, rule: str, node: ast.AST, message: str) -> Finding:
        return Finding(
            rule=rule,
            path=self.relpath,
            line=getattr(node, "lineno", 1),
            col=getattr(node, "col_offset", 0) + 1,
            message=message,
        )


@dataclasses.dataclass
class Rule:
    name: str
    description: str
    check: Callable[[FileContext], Iterator[Finding]]
    origin: str = ""  # the CLAUDE.md/BASELINE.md gotcha this encodes


_REGISTRY: dict[str, Rule] = {}


def register(name: str, description: str, origin: str = ""):
    def deco(fn):
        _REGISTRY[name] = Rule(name, description, fn, origin)
        return fn

    return deco


def all_rules() -> dict[str, Rule]:
    # Import for side effects exactly once; rule modules self-register.
    from cpgisland_tpu.analysis import (  # noqa: F401
        rules_hotpath,
        rules_hygiene,
        rules_jit,
        rules_numerics,
        rules_pallas,
        rules_sync,
    )

    return dict(_REGISTRY)


@dataclasses.dataclass
class LintResult:
    findings: list[Finding]
    files_checked: int
    unused_waivers: list[tuple[str, Waiver]]

    @property
    def unwaived(self) -> list[Finding]:
        return [f for f in self.findings if not f.waived]

    @property
    def waived(self) -> list[Finding]:
        return [f for f in self.findings if f.waived]

    @property
    def ok(self) -> bool:
        return not self.unwaived


def discover_files(paths: Iterable[str]) -> list[str]:
    out: list[str] = []
    skip_dirs = {"__pycache__", ".git", "fixtures", "node_modules", ".venv"}
    for p in paths:
        if os.path.isfile(p):
            out.append(p)
            continue
        for root, dirs, files in os.walk(p):
            dirs[:] = sorted(d for d in dirs if d not in skip_dirs)
            out.extend(
                os.path.join(root, f) for f in sorted(files) if f.endswith(".py")
            )
    return out


def _apply_waivers(ctx: FileContext, findings: list[Finding]) -> None:
    for f in findings:
        for w in ctx.waivers:
            if f.line == w.applies_to and f.rule in w.rules:
                f.waived = True
                f.waiver_reason = w.reason
                w.used = True
                break


def lint_file(
    path: str,
    rules: Optional[dict[str, Rule]] = None,
    relpath: Optional[str] = None,
    source: Optional[str] = None,
) -> tuple[list[Finding], list[Waiver]]:
    """Lint one file; returns (findings incl. waived, that file's waivers)."""
    rules = rules if rules is not None else all_rules()
    if source is None:
        with open(path, "r", encoding="utf-8") as fh:
            source = fh.read()
    rel = (relpath or path).replace(os.sep, "/")
    try:
        ctx = FileContext(path, source, relpath=rel)
    except SyntaxError as e:
        return [
            Finding("parse-error", rel, e.lineno or 1, (e.offset or 0) + 1,
                    f"file does not parse: {e.msg}")
        ], []
    findings: list[Finding] = []
    for line, msg in ctx.waiver_errors:
        findings.append(Finding("waiver-syntax", ctx.relpath, line, 1, msg))
    for rule in rules.values():
        findings.extend(rule.check(ctx))
    _apply_waivers(ctx, findings)
    findings.sort(key=lambda f: (f.line, f.col, f.rule))
    return findings, ctx.waivers


def run_lint(
    paths: Iterable[str],
    rule_names: Optional[Iterable[str]] = None,
    base: Optional[str] = None,
) -> LintResult:
    """Lint every ``*.py`` under ``paths``; ``rule_names`` restricts rules.

    ``base`` (default: cwd) makes reported paths repo-relative.
    """
    rules = all_rules()
    if rule_names is not None:
        unknown = set(rule_names) - set(rules)
        if unknown:
            raise ValueError(f"unknown rule(s): {sorted(unknown)}")
        rules = {k: v for k, v in rules.items() if k in set(rule_names)}
    base = base or os.getcwd()
    findings: list[Finding] = []
    unused: list[tuple[str, Waiver]] = []
    files = discover_files(paths)
    for path in files:
        rel = os.path.relpath(path, base)
        if rel.startswith(".."):
            rel = path
        file_findings, waivers = lint_file(path, rules, relpath=rel)
        findings.extend(file_findings)
        # A waiver only counts as stale if a rule it names actually RAN
        # this invocation — under --rules subsets, waivers for unselected
        # rules are out of scope, not stale.
        unused.extend(
            (rel, w) for w in waivers
            if not w.used and set(w.rules) & set(rules)
        )
    return LintResult(
        findings=findings, files_checked=len(files), unused_waivers=unused
    )
