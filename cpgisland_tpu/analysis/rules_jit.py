"""R1 ``jit-big-closure`` and R6 ``retrace-hazard``: the jit-wrapper rules.

R1 — remote compile ships the program bytes over HTTP, and a jitted
function that *closes over* an array constant bakes those bytes into the
module (a 256 MiB baked constant = HTTP 413, CLAUDE.md).  Arrays must be
traced ARGUMENTS.  The rule flags jit/pjit/pallas-wrapped functions whose
free variables resolve to array-constructor expressions in module or
enclosing-function scope.  Small literal tables (<= 64 elements written
out in source) are exempt — they are the lane-broadcast constants kernels
legitimately bake.

R6 — a jitted callable taking a raw Python scalar retraces on every new
value (and a shape-varying arg recompiles per shape).  Any parameter that
is int/bool/str-annotated or int/bool/str-defaulted must appear in
``static_argnums``/``static_argnames`` — or the call site must bucket it
(pow2 record bucketing, chunking.bucket_records).
"""

from __future__ import annotations

import ast
from typing import Iterator, Optional

from cpgisland_tpu.analysis import astutil
from cpgisland_tpu.analysis.core import FileContext, Finding, register

JIT_NAMES = frozenset({
    "jax.jit", "jit", "pjit", "jax.pjit", "jax.experimental.pjit.pjit",
})
PALLAS_CALL_NAMES = frozenset({
    "pl.pallas_call", "pallas_call", "jax.experimental.pallas.pallas_call",
})
PARTIAL_NAMES = frozenset({"functools.partial", "partial"})
# Transparent combinators: jit(vmap(f)) etc. — analyze f.
TRANSPARENT = frozenset({
    "jax.vmap", "vmap", "jax.shard_map", "shard_map", "jax.pmap", "pmap",
    "jax.named_call", "jax.checkpoint", "jax.remat",
    "jax.experimental.shard_map.shard_map",
})

ARRAY_MAKERS = frozenset({
    "array", "asarray", "zeros", "ones", "full", "empty", "eye", "arange",
    "linspace", "load", "fromfile", "frombuffer", "loadtxt", "identity",
    "tile", "repeat", "concatenate", "stack", "broadcast_to",
})
ARRAY_MODULES = ("np.", "numpy.", "jnp.", "jax.numpy.")

SMALL_LITERAL_MAX = 64


def _literal_size(node: ast.AST) -> Optional[int]:
    """Element count of a nested literal list/tuple of constants, else None."""
    if isinstance(node, ast.Constant):
        return 1
    if isinstance(node, (ast.List, ast.Tuple)):
        total = 0
        for el in node.elts:
            n = _literal_size(el)
            if n is None:
                return None
            total += n
        return total
    return None


def _is_array_maker(ctx: FileContext, node: ast.AST) -> bool:
    """Does this expression construct an ndarray (np.*/jnp.* factory call)?"""
    if not isinstance(node, ast.Call):
        return False
    name = ctx.call_name(node)
    if name is None:
        return False
    if not (name.startswith(ARRAY_MODULES) or name.startswith("jax.numpy")):
        return False
    tail = name.rsplit(".", 1)[-1]
    if tail not in ARRAY_MAKERS:
        return False
    # Small literal tables written out in source are fine to bake.
    if tail in ("array", "asarray") and node.args:
        n = _literal_size(node.args[0])
        if n is not None and n <= SMALL_LITERAL_MAX:
            return False
    return True


def _unwrap_target(ctx: FileContext, node: ast.AST, depth: int = 0):
    """Resolve the function object a jit wrapper wraps, through partial()
    and transparent combinators.  Returns an ast node (def or Lambda) or
    None when the target is opaque (a call result, an attribute, ...)."""
    if depth > 4 or node is None:
        return None
    if isinstance(node, ast.Lambda):
        return node
    if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
        return node
    if isinstance(node, ast.Name):
        # Innermost enclosing scope that binds the name to a def.
        for fn in astutil.enclosing_functions(node):
            for sub in astutil.walk_scope(fn):
                if isinstance(sub, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                        and sub.name == node.id:
                    return sub
            assigns = astutil.single_assignments(fn)
            if node.id in assigns:
                return _unwrap_target(ctx, assigns[node.id], depth + 1)
            if node.id in astutil.bound_names(fn):
                return None  # bound to something opaque in this scope
        return astutil.top_level_defs(ctx.tree).get(node.id)
    if isinstance(node, ast.Call):
        name = ctx.call_name(node)
        if astutil.matches(name, PARTIAL_NAMES | TRANSPARENT):
            return _unwrap_target(ctx, node.args[0] if node.args else None,
                                  depth + 1)
    return None


def _jit_sites(ctx: FileContext):
    """Yield (report_node, target_fn_node_or_None, static_names, static_nums)
    for every jit/pjit wrapper in the file — decorators and call sites."""

    def statics(call: Optional[ast.Call]) -> tuple[set, set]:
        names: set[str] = set()
        nums: set[int] = set()
        if call is None:
            return names, nums
        for kw in call.keywords:
            if kw.arg == "static_argnames":
                for n in ast.walk(kw.value):
                    if isinstance(n, ast.Constant) and isinstance(n.value, str):
                        names.add(n.value)
            elif kw.arg == "static_argnums":
                for n in ast.walk(kw.value):
                    if isinstance(n, ast.Constant) and isinstance(n.value, int):
                        nums.add(n.value)
        return names, nums

    for node in ast.walk(ctx.tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            for deco in node.decorator_list:
                if astutil.matches(ctx.imports.canonical(deco), JIT_NAMES):
                    yield deco, node, set(), set()
                elif isinstance(deco, ast.Call):
                    name = ctx.call_name(deco)
                    if astutil.matches(name, JIT_NAMES):
                        yield deco, node, *statics(deco)
                    elif astutil.matches(name, PARTIAL_NAMES) and deco.args \
                            and astutil.matches(
                                ctx.imports.canonical(deco.args[0]), JIT_NAMES
                            ):
                        yield deco, node, *statics(deco)
        elif isinstance(node, ast.Call):
            if astutil.matches(ctx.call_name(node), JIT_NAMES) and node.args:
                in_deco = any(
                    isinstance(p, (ast.FunctionDef, ast.AsyncFunctionDef))
                    and node in p.decorator_list
                    for p in astutil.parents(node)
                )
                if not in_deco:
                    target = _unwrap_target(ctx, node.args[0])
                    yield node, target, *statics(node)


@register(
    "jit-big-closure",
    "jit/pjit/pallas-wrapped functions must not close over array constants "
    "(pass arrays as traced arguments)",
    origin="CLAUDE.md: remote compile ships program bytes over HTTP; a "
    "256 MiB baked constant = HTTP 413",
)
def check_jit_big_closure(ctx: FileContext) -> Iterator[Finding]:
    targets = []
    for report, target, _names, _nums in _jit_sites(ctx):
        if target is not None:
            targets.append((report, target))
    # pallas_call kernels bake their closures into every program too.
    for node in ast.walk(ctx.tree):
        if isinstance(node, ast.Call) and astutil.matches(
            ctx.call_name(node), PALLAS_CALL_NAMES
        ) and node.args:
            target = _unwrap_target(ctx, node.args[0])
            if target is not None:
                targets.append((node, target))

    module_assigns = {
        t.targets[0].id: t.value
        for t in ctx.tree.body
        if isinstance(t, ast.Assign) and len(t.targets) == 1
        and isinstance(t.targets[0], ast.Name)
    }
    seen: set[tuple[int, str]] = set()
    for report, target in targets:
        free = astutil.free_loads(target)
        enclosing = astutil.enclosing_functions(target)
        for name, load in free.items():
            value = None
            for fn in enclosing:
                assigns = astutil.single_assignments(fn)
                if name in assigns:
                    value = assigns[name]
                    break
                if name in astutil.bound_names(fn):
                    break  # rebound / parameter: can't prove, stay quiet
            else:
                value = module_assigns.get(name)
            if value is not None and _is_array_maker(ctx, value):
                key = (load.lineno, name)
                if key in seen:
                    continue
                seen.add(key)
                yield ctx.finding(
                    "jit-big-closure",
                    load,
                    f"jitted function closes over array constant {name!r} "
                    f"(built at line {value.lineno}); pass it as a traced "
                    "argument — baked constants ship in the compiled module",
                )


SCALARISH = frozenset({"int", "bool", "str"})


@register(
    "retrace-hazard",
    "jitted callables must declare raw Python scalar params as "
    "static_argnums/static_argnames (or bucket shapes pow2)",
    origin="CLAUDE.md: distinct tail lengths recompile per record; pad to "
    "the span / bucket pow2 so shapes don't recompile",
)
def check_retrace_hazard(ctx: FileContext) -> Iterator[Finding]:
    for report, target, static_names, static_nums in _jit_sites(ctx):
        if target is None or isinstance(target, ast.Lambda):
            continue
        params = astutil.func_params(target)
        for i, p in enumerate(params):
            hazard = None
            ann = p.annotation
            if isinstance(ann, ast.Name) and ann.id in SCALARISH:
                hazard = f"annotated {ann.id}"
            elif isinstance(ann, ast.Constant) and isinstance(ann.value, str) \
                    and ann.value in SCALARISH:
                hazard = f"annotated {ann.value}"
            if hazard is None:
                default = _default_for(target, i, len(params))
                if isinstance(default, ast.Constant) and isinstance(
                    default.value, (int, bool, str)
                ) and default.value is not None:
                    hazard = f"defaulted to {default.value!r}"
            if hazard and p.arg not in static_names and i not in static_nums:
                # Anchor decorator-form findings at the def line: that is
                # where a human reads the signature and writes the waiver
                # (a decorator can span lines and predate the def).
                anchor = (
                    target
                    if report in getattr(target, "decorator_list", [])
                    else report
                )
                yield ctx.finding(
                    "retrace-hazard",
                    anchor,
                    f"jitted {target.name!r} takes Python scalar "
                    f"{p.arg!r} ({hazard}) without static_argnums/"
                    "static_argnames: every new value retraces",
                )


def _default_for(fn: ast.AST, index: int, n_params: int) -> Optional[ast.AST]:
    a = fn.args
    pos = [*a.posonlyargs, *a.args]
    if index < len(pos):
        d_index = index - (len(pos) - len(a.defaults))
        return a.defaults[d_index] if 0 <= d_index < len(a.defaults) else None
    k_index = index - len(pos)
    if k_index < len(a.kwonlyargs):
        return a.kw_defaults[k_index]
    return None
