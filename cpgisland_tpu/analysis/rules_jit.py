"""R1 ``jit-big-closure`` and R6 ``retrace-hazard``: the jit-wrapper rules.

R1 — remote compile ships the program bytes over HTTP, and a jitted
function that *closes over* an array constant bakes those bytes into the
module (a 256 MiB baked constant = HTTP 413, CLAUDE.md).  Arrays must be
traced ARGUMENTS.  The rule flags jit/pjit/pallas-wrapped functions whose
free variables resolve to array-constructor expressions in module or
enclosing-function scope.  Small literal tables (<= 64 elements written
out in source) are exempt — they are the lane-broadcast constants kernels
legitimately bake.

R6 — a jitted callable taking a raw Python scalar retraces on every new
value (and a shape-varying arg recompiles per shape).  Any parameter that
is int/bool/str-annotated or int/bool/str-defaulted must appear in
``static_argnums``/``static_argnames`` — or the call site must bucket it
(pow2 record bucketing, chunking.bucket_records).

R7 — ``jit-const-capture``: a **host** numpy array constructed INSIDE a
traced body (``np.zeros((1<<20, 64))`` in a jit/pallas target) is not an
op — it becomes a jaxpr constvar baked into the compiled module, the same
HTTP 413 axis as R1 but invisible to R1's closure analysis.  Flagged when
the element count is statically estimable and the byte size reaches
memmodel's remote-compile constant budget (the 256 MiB cliff / margin);
``jnp.*`` constructors are traced ops and exempt.  The jaxpr half of the
same check runs in Layer 6 (scale_contracts' per-entry const_bytes).

R8 — ``trace-time-consult``: graftune's "consultation is HOST-side only"
rule.  A ``tune.lookup``/``pick_lane_T``-style call reachable from inside
a traced body freezes the pre-sweep winner into the jit cache — the
program never retraces when TUNING.json updates, so an applied sweep
silently doesn't apply.  Consult host-side and pass the resolved knob as
an explicit (static) argument; in-trace fallbacks use the PURE heuristics
(``legacy_lane_T``) only.
"""

from __future__ import annotations

import ast
from typing import Iterator, Optional

from cpgisland_tpu.analysis import astutil
from cpgisland_tpu.analysis.core import FileContext, Finding, register

JIT_NAMES = frozenset({
    "jax.jit", "jit", "pjit", "jax.pjit", "jax.experimental.pjit.pjit",
})
PALLAS_CALL_NAMES = frozenset({
    "pl.pallas_call", "pallas_call", "jax.experimental.pallas.pallas_call",
})
PARTIAL_NAMES = frozenset({"functools.partial", "partial"})
# Transparent combinators: jit(vmap(f)) etc. — analyze f.
TRANSPARENT = frozenset({
    "jax.vmap", "vmap", "jax.shard_map", "shard_map", "jax.pmap", "pmap",
    "jax.named_call", "jax.checkpoint", "jax.remat",
    "jax.experimental.shard_map.shard_map",
})

ARRAY_MAKERS = frozenset({
    "array", "asarray", "zeros", "ones", "full", "empty", "eye", "arange",
    "linspace", "load", "fromfile", "frombuffer", "loadtxt", "identity",
    "tile", "repeat", "concatenate", "stack", "broadcast_to",
})
ARRAY_MODULES = ("np.", "numpy.", "jnp.", "jax.numpy.")

SMALL_LITERAL_MAX = 64


def _literal_size(node: ast.AST) -> Optional[int]:
    """Element count of a nested literal list/tuple of constants, else None."""
    if isinstance(node, ast.Constant):
        return 1
    if isinstance(node, (ast.List, ast.Tuple)):
        total = 0
        for el in node.elts:
            n = _literal_size(el)
            if n is None:
                return None
            total += n
        return total
    return None


def _is_array_maker(ctx: FileContext, node: ast.AST) -> bool:
    """Does this expression construct an ndarray (np.*/jnp.* factory call)?"""
    if not isinstance(node, ast.Call):
        return False
    name = ctx.call_name(node)
    if name is None:
        return False
    if not (name.startswith(ARRAY_MODULES) or name.startswith("jax.numpy")):
        return False
    tail = name.rsplit(".", 1)[-1]
    if tail not in ARRAY_MAKERS:
        return False
    # Small literal tables written out in source are fine to bake.
    if tail in ("array", "asarray") and node.args:
        n = _literal_size(node.args[0])
        if n is not None and n <= SMALL_LITERAL_MAX:
            return False
    return True


def _unwrap_target(ctx: FileContext, node: ast.AST, depth: int = 0):
    """Resolve the function object a jit wrapper wraps, through partial()
    and transparent combinators.  Returns an ast node (def or Lambda) or
    None when the target is opaque (a call result, an attribute, ...)."""
    if depth > 4 or node is None:
        return None
    if isinstance(node, ast.Lambda):
        return node
    if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
        return node
    if isinstance(node, ast.Name):
        # Innermost enclosing scope that binds the name to a def.
        for fn in astutil.enclosing_functions(node):
            for sub in astutil.walk_scope(fn):
                if isinstance(sub, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                        and sub.name == node.id:
                    return sub
            assigns = astutil.single_assignments(fn)
            if node.id in assigns:
                return _unwrap_target(ctx, assigns[node.id], depth + 1)
            if node.id in astutil.bound_names(fn):
                return None  # bound to something opaque in this scope
        return astutil.top_level_defs(ctx.tree).get(node.id)
    if isinstance(node, ast.Call):
        name = ctx.call_name(node)
        if astutil.matches(name, PARTIAL_NAMES | TRANSPARENT):
            return _unwrap_target(ctx, node.args[0] if node.args else None,
                                  depth + 1)
    return None


def _jit_sites(ctx: FileContext):
    """Yield (report_node, target_fn_node_or_None, static_names, static_nums)
    for every jit/pjit wrapper in the file — decorators and call sites."""

    def statics(call: Optional[ast.Call]) -> tuple[set, set]:
        names: set[str] = set()
        nums: set[int] = set()
        if call is None:
            return names, nums
        for kw in call.keywords:
            if kw.arg == "static_argnames":
                for n in ast.walk(kw.value):
                    if isinstance(n, ast.Constant) and isinstance(n.value, str):
                        names.add(n.value)
            elif kw.arg == "static_argnums":
                for n in ast.walk(kw.value):
                    if isinstance(n, ast.Constant) and isinstance(n.value, int):
                        nums.add(n.value)
        return names, nums

    for node in ast.walk(ctx.tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            for deco in node.decorator_list:
                if astutil.matches(ctx.imports.canonical(deco), JIT_NAMES):
                    yield deco, node, set(), set()
                elif isinstance(deco, ast.Call):
                    name = ctx.call_name(deco)
                    if astutil.matches(name, JIT_NAMES):
                        yield deco, node, *statics(deco)
                    elif astutil.matches(name, PARTIAL_NAMES) and deco.args \
                            and astutil.matches(
                                ctx.imports.canonical(deco.args[0]), JIT_NAMES
                            ):
                        yield deco, node, *statics(deco)
        elif isinstance(node, ast.Call):
            if astutil.matches(ctx.call_name(node), JIT_NAMES) and node.args:
                in_deco = any(
                    isinstance(p, (ast.FunctionDef, ast.AsyncFunctionDef))
                    and node in p.decorator_list
                    for p in astutil.parents(node)
                )
                if not in_deco:
                    target = _unwrap_target(ctx, node.args[0])
                    yield node, target, *statics(node)


@register(
    "jit-big-closure",
    "jit/pjit/pallas-wrapped functions must not close over array constants "
    "(pass arrays as traced arguments)",
    origin="CLAUDE.md: remote compile ships program bytes over HTTP; a "
    "256 MiB baked constant = HTTP 413",
)
def check_jit_big_closure(ctx: FileContext) -> Iterator[Finding]:
    targets = []
    for report, target, _names, _nums in _jit_sites(ctx):
        if target is not None:
            targets.append((report, target))
    # pallas_call kernels bake their closures into every program too.
    for node in ast.walk(ctx.tree):
        if isinstance(node, ast.Call) and astutil.matches(
            ctx.call_name(node), PALLAS_CALL_NAMES
        ) and node.args:
            target = _unwrap_target(ctx, node.args[0])
            if target is not None:
                targets.append((node, target))

    module_assigns = {
        t.targets[0].id: t.value
        for t in ctx.tree.body
        if isinstance(t, ast.Assign) and len(t.targets) == 1
        and isinstance(t.targets[0], ast.Name)
    }
    seen: set[tuple[int, str]] = set()
    for report, target in targets:
        free = astutil.free_loads(target)
        enclosing = astutil.enclosing_functions(target)
        for name, load in free.items():
            value = None
            for fn in enclosing:
                assigns = astutil.single_assignments(fn)
                if name in assigns:
                    value = assigns[name]
                    break
                if name in astutil.bound_names(fn):
                    break  # rebound / parameter: can't prove, stay quiet
            else:
                value = module_assigns.get(name)
            if value is not None and _is_array_maker(ctx, value):
                key = (load.lineno, name)
                if key in seen:
                    continue
                seen.add(key)
                yield ctx.finding(
                    "jit-big-closure",
                    load,
                    f"jitted function closes over array constant {name!r} "
                    f"(built at line {value.lineno}); pass it as a traced "
                    "argument — baked constants ship in the compiled module",
                )


SCALARISH = frozenset({"int", "bool", "str"})


@register(
    "retrace-hazard",
    "jitted callables must declare raw Python scalar params as "
    "static_argnums/static_argnames (or bucket shapes pow2)",
    origin="CLAUDE.md: distinct tail lengths recompile per record; pad to "
    "the span / bucket pow2 so shapes don't recompile",
)
def check_retrace_hazard(ctx: FileContext) -> Iterator[Finding]:
    for report, target, static_names, static_nums in _jit_sites(ctx):
        if target is None or isinstance(target, ast.Lambda):
            continue
        params = astutil.func_params(target)
        for i, p in enumerate(params):
            hazard = None
            ann = p.annotation
            if isinstance(ann, ast.Name) and ann.id in SCALARISH:
                hazard = f"annotated {ann.id}"
            elif isinstance(ann, ast.Constant) and isinstance(ann.value, str) \
                    and ann.value in SCALARISH:
                hazard = f"annotated {ann.value}"
            if hazard is None:
                default = _default_for(target, i, len(params))
                if isinstance(default, ast.Constant) and isinstance(
                    default.value, (int, bool, str)
                ) and default.value is not None:
                    hazard = f"defaulted to {default.value!r}"
            if hazard and p.arg not in static_names and i not in static_nums:
                # Anchor decorator-form findings at the def line: that is
                # where a human reads the signature and writes the waiver
                # (a decorator can span lines and predate the def).
                anchor = (
                    target
                    if report in getattr(target, "decorator_list", [])
                    else report
                )
                yield ctx.finding(
                    "retrace-hazard",
                    anchor,
                    f"jitted {target.name!r} takes Python scalar "
                    f"{p.arg!r} ({hazard}) without static_argnums/"
                    "static_argnames: every new value retraces",
                )


def _default_for(fn: ast.AST, index: int, n_params: int) -> Optional[ast.AST]:
    a = fn.args
    pos = [*a.posonlyargs, *a.args]
    if index < len(pos):
        d_index = index - (len(pos) - len(a.defaults))
        return a.defaults[d_index] if 0 <= d_index < len(a.defaults) else None
    k_index = index - len(pos)
    if k_index < len(a.kwonlyargs):
        return a.kw_defaults[k_index]
    return None


# -- R7: jit-const-capture ---------------------------------------------------

# Host-numpy prefixes whose constructor results are CONSTANTS under trace
# (jnp.* constructors are traced ops and exempt).
HOST_ARRAY_MODULES = ("np.", "numpy.")

_DTYPE_BYTES = {
    "float64": 8, "double": 8, "float32": 4, "single": 4, "float16": 2,
    "half": 2, "bfloat16": 2, "int64": 8, "int32": 4, "int16": 2,
    "int8": 1, "uint64": 8, "uint32": 4, "uint16": 2, "uint8": 1,
    "bool": 1, "bool_": 1, "complex64": 8, "complex128": 16,
}
_NUMPY_DEFAULT_BYTES = 8  # host numpy defaults to float64/int64


def _const_int(node: ast.AST) -> Optional[int]:
    if isinstance(node, ast.Constant) and isinstance(node.value, int) \
            and not isinstance(node.value, bool):
        return node.value
    if isinstance(node, ast.BinOp) and isinstance(node.op, ast.LShift):
        lo, hi = _const_int(node.left), _const_int(node.right)
        return lo << hi if lo is not None and hi is not None else None
    if isinstance(node, ast.BinOp) and isinstance(node.op, ast.Mult):
        lo, hi = _const_int(node.left), _const_int(node.right)
        return lo * hi if lo is not None and hi is not None else None
    return None


def _shape_elems(node: ast.AST) -> Optional[int]:
    """Element count of a statically-written shape (int or tuple of ints)."""
    n = _const_int(node)
    if n is not None:
        return n
    if isinstance(node, (ast.Tuple, ast.List)):
        total = 1
        for el in node.elts:
            d = _const_int(el)
            if d is None:
                return None
            total *= d
        return total
    return None


def _dtype_bytes(call: ast.Call) -> int:
    for kw in call.keywords:
        if kw.arg != "dtype":
            continue
        v = kw.value
        name = None
        if isinstance(v, ast.Attribute):
            name = v.attr
        elif isinstance(v, ast.Name):
            name = v.id
        elif isinstance(v, ast.Constant) and isinstance(v.value, str):
            name = v.value
        if name in _DTYPE_BYTES:
            return _DTYPE_BYTES[name]
    return _NUMPY_DEFAULT_BYTES


def _host_const_bytes(ctx: FileContext, node: ast.AST) -> Optional[int]:
    """Statically-estimable byte size of a host-numpy constructor call
    inside a traced body, else None (unestimable stays quiet)."""
    if not isinstance(node, ast.Call):
        return None
    name = ctx.call_name(node)
    if name is None or not name.startswith(HOST_ARRAY_MODULES):
        return None
    tail = name.rsplit(".", 1)[-1]
    if tail not in ARRAY_MAKERS:
        return None
    elems: Optional[int] = None
    if tail in ("zeros", "ones", "empty", "full", "broadcast_to") and node.args:
        elems = _shape_elems(node.args[0])
    elif tail in ("arange", "linspace") and node.args:
        if len(node.args) == 1:
            elems = _const_int(node.args[0])
        elif len(node.args) >= 2:
            lo, hi = _const_int(node.args[0]), _const_int(node.args[1])
            if lo is not None and hi is not None:
                elems = max(hi - lo, 0)
    elif tail in ("eye", "identity") and node.args:
        n = _const_int(node.args[0])
        if n is not None:
            m = _const_int(node.args[1]) if len(node.args) > 1 else n
            elems = n * m if m is not None else None
    elif tail in ("array", "asarray") and node.args:
        elems = _literal_size(node.args[0])
    if elems is None:
        return None
    return elems * _dtype_bytes(node)


def _traced_targets(ctx: FileContext):
    """Every (reason, def/Lambda node) the tracer reaches in this file:
    jit/pjit targets, pallas_call kernels, and defs handed to lax control
    flow / transparent combinators (scan bodies, shard_map'd fns — the
    fb_sharded pattern where the jit wrapper lives in another function)."""
    for report, target, _names, _nums in _jit_sites(ctx):
        if target is not None:
            yield "jit target", target
    combinators = TRACE_COMBINATORS | TRANSPARENT | PALLAS_CALL_NAMES
    passed_names: set[str] = set()
    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.Call):
            continue
        name = ctx.call_name(node)
        if not astutil.matches(name, combinators):
            continue
        short = (name or "?").rsplit(".", 1)[-1]
        for arg in node.args:
            resolved = _unwrap_target(ctx, arg)
            if resolved is not None:
                yield f"passed to {short}", resolved
            elif isinstance(arg, ast.Name):
                passed_names.add(arg.id)
    if passed_names:
        # Fall back to name matching for targets _unwrap_target can't
        # resolve across function boundaries (`body = _make_body(...)`
        # then `shard_map(body, ...)` in a sibling function): any def
        # sharing a passed name is conservatively traced.
        for node in ast.walk(ctx.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                    and node.name in passed_names:
                yield "passed by name to a traced combinator", node


TRACE_COMBINATORS = frozenset({
    "jax.lax.scan", "lax.scan", "jax.lax.while_loop", "lax.while_loop",
    "jax.lax.cond", "lax.cond", "jax.lax.fori_loop", "lax.fori_loop",
    "jax.lax.switch", "lax.switch", "jax.lax.map", "lax.map",
    "jax.lax.associative_scan", "lax.associative_scan",
})


@register(
    "jit-const-capture",
    "host-numpy arrays built INSIDE traced bodies become jaxpr constvars "
    "baked into the compiled module; estimable constructions at/above the "
    "memmodel remote-const budget must move out (traced argument or jnp)",
    origin="CLAUDE.md: remote compile ships program bytes over HTTP; a "
    "256 MiB baked constant = HTTP 413 — R1 catches closures, this "
    "catches in-body np.* construction (Layer 6 checks the jaxpr side)",
)
def check_jit_const_capture(ctx: FileContext) -> Iterator[Finding]:
    from cpgisland_tpu.analysis import memmodel

    budget = memmodel.remote_const_budget()
    seen: set[int] = set()
    for reason, target in _traced_targets(ctx):
        for node in ast.walk(target):
            size = _host_const_bytes(ctx, node)
            if size is None or size < budget:
                continue
            if node.lineno in seen:
                continue
            seen.add(node.lineno)
            yield ctx.finding(
                "jit-const-capture",
                node,
                f"host-numpy constant of ~{size >> 20} MiB built inside a "
                f"traced body ({reason}): it bakes into the compiled "
                f"module as a constvar (budget {budget >> 20} MiB, the "
                "HTTP 413 cliff) — build with jnp.* or pass it as a "
                "traced argument",
            )


# -- R8: trace-time-consult --------------------------------------------------

# Knob-consultation calls that freeze their answer into the jit cache when
# reached from a traced body.  Matched on the canonical dotted name's tail
# two components (module-alias-proof); bare-name calls match the tail.
CONSULT_NAMES = frozenset({
    "tune.lookup", "tune.tuned_lane_T", "tune.generation",
    "tune.default_fused", "tune.default_one_pass", "tune.default_stacked",
    "tune.default_block_size", "tune.default_t_tile", "tune.default_engine",
})
CONSULT_TAILS = frozenset({"pick_lane_T"})


def _is_consult(name: Optional[str]) -> bool:
    if name is None:
        return False
    parts = name.split(".")
    if parts[-1] in CONSULT_TAILS:
        return True
    return ".".join(parts[-2:]) in CONSULT_NAMES or name in CONSULT_NAMES


@register(
    "trace-time-consult",
    "graftune consultation (tune.lookup/tuned_lane_T/default_*/"
    "pick_lane_T) must stay HOST-side: a consult reachable from a traced "
    "body freezes the pre-sweep winner into the jit cache",
    origin="CLAUDE.md graftune RULES: a trace-time lookup freezes "
    "pre-sweep knobs into the jit cache — an applied sweep silently "
    "never applies; resolve host-side, pass the knob as a static arg "
    "(in-trace fallbacks use the pure legacy heuristics)",
)
def check_trace_time_consult(ctx: FileContext) -> Iterator[Finding]:
    seen: set[int] = set()
    for reason, target in _traced_targets(ctx):
        for node in ast.walk(target):
            if not isinstance(node, ast.Call):
                continue
            name = ctx.call_name(node)
            if not _is_consult(name):
                continue
            if node.lineno in seen:
                continue
            seen.add(node.lineno)
            yield ctx.finding(
                "trace-time-consult",
                node,
                f"tuning consultation {name!r} inside a traced body "
                f"({reason}): the winner freezes into the jit cache at "
                "trace time and TUNING.json updates never apply — "
                "consult host-side and pass the knob explicitly",
            )
