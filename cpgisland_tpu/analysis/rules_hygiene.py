"""Hygiene tier: ``unused-import`` and ``shadow-builtin``.

The container this repo grows in has no ruff/mypy baked in (and the
no-new-deps rule forbids installing them), so graftcheck carries the two
hygiene checks the CI script would otherwise get from ruff — enough to
keep import rot and builtin shadowing out of the tree.  ``tools/
ci_checks.sh`` still runs the real ruff when one is on PATH; the
``[tool.ruff]`` config in pyproject.toml is the richer source of truth.
"""

from __future__ import annotations

import ast
from typing import Iterator

from cpgisland_tpu.analysis import astutil
from cpgisland_tpu.analysis.core import FileContext, Finding, register


@register(
    "unused-import",
    "module-level imports must be referenced (or marked with noqa / "
    "re-exported via __all__)",
    origin="satellite: ruff-equivalent hygiene baked into graftcheck "
    "(no ruff in the container)",
)
def check_unused_import(ctx: FileContext) -> Iterator[Finding]:
    if ctx.relpath.endswith("__init__.py"):
        return  # re-export surface: unused-looking imports are the point
    used: set[str] = set()
    for node in ast.walk(ctx.tree):
        if isinstance(node, ast.Name) and isinstance(node.ctx, ast.Load):
            used.add(node.id)
        elif isinstance(node, ast.Attribute):
            root = node
            while isinstance(root, ast.Attribute):
                root = root.value
            if isinstance(root, ast.Name):
                used.add(root.id)
        elif isinstance(node, ast.Constant) and isinstance(node.value, str):
            used.add(node.value)  # __all__ entries, getattr strings
    for node in ctx.tree.body:
        if not isinstance(node, (ast.Import, ast.ImportFrom)):
            continue
        if isinstance(node, ast.ImportFrom) and node.module == "__future__":
            continue
        line = ctx.lines[node.lineno - 1] if node.lineno <= len(ctx.lines) else ""
        if "noqa" in line:
            continue
        for a in node.names:
            if a.name == "*":
                continue
            bound = (a.asname or a.name).split(".")[0]
            if bound not in used and f"{bound}." not in ctx.source:
                yield Finding(
                    "unused-import", ctx.relpath, node.lineno, node.col_offset + 1,
                    f"import {bound!r} is never used",
                )


SHADOWABLE = frozenset({
    "list", "dict", "set", "tuple", "type", "id", "input", "object", "print",
    "len", "sum", "max", "min", "range", "filter", "map", "all", "any",
    "bytes", "str", "int", "float", "bool", "hash", "next", "iter", "vars",
})


@register(
    "shadow-builtin",
    "function parameters and assignments must not shadow Python builtins",
    origin="satellite: ruff-equivalent hygiene baked into graftcheck "
    "(no ruff in the container)",
)
def check_shadow_builtin(ctx: FileContext) -> Iterator[Finding]:
    for node in ast.walk(ctx.tree):
        if isinstance(node, astutil.FunctionNode):
            for p in astutil.func_params(node):
                if p.arg in SHADOWABLE:
                    yield Finding(
                        "shadow-builtin", ctx.relpath, p.lineno, p.col_offset + 1,
                        f"parameter {p.arg!r} shadows a builtin",
                    )
        elif isinstance(node, ast.Assign):
            for t in node.targets:
                if isinstance(t, ast.Name) and t.id in SHADOWABLE:
                    yield Finding(
                        "shadow-builtin", ctx.relpath, t.lineno, t.col_offset + 1,
                        f"assignment to {t.id!r} shadows a builtin",
                    )
