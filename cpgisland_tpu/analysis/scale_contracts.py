"""graftcheck Layer 6 — scale-signature contracts + the SCALE.json lockfile.

:mod:`~cpgisland_tpu.analysis.scalemodel` is the engine; this module is
the registry + lockfile: every Layer-2 entry that consumes the fused /
one-pass self-normalized beta directions gets a consumer-level trace
with the beta stream as an EXPLICIT tagged argument, the dataflow derives
its scale signature, and the signature is checked against the DECLARED
expectation (the ops modules' ``SCALE_TAGS`` tables) and against the
committed ``SCALE.json``.

Why consumer-level traces rather than marker primitives: graftcost pins
``n_eqns`` with tolerance 0 on every shipped entry — a tagging primitive
inside the shipped graphs would drift every cost fingerprint.  The
consumers here take their beta streams as arguments, so tagging is free,
and engine parity (XLA twin == Pallas kernel, both platforms) is already
pinned by Layer 2/tests — certifying the twins certifies the contract
arithmetic of the kernels.

The two contract families:

- ``scale.free-consumers`` — entries consuming self-normalized directions
  (posterior fused/one-pass conf+MPM, the em-seq/em-chunked znorm stats,
  the one-pass matrix epilogues) must derive scale-FREE outputs in the
  tagged betas.  The r9 chunked pairing bug (cs-scaled stats kernel fed
  self-normalized betas) derives ``deg:1`` here and is a finding.
- ``scale.exact-arms`` — the exact arms declare their INTENDED nonzero
  signature and the dataflow must confirm it: the split-pass cs-scaled
  stats kernel's ``macc`` is degree 1 in its cs-scaled betas, the flat
  decode's true-score return is degree 1 in a ``log_pi`` offset (max-plus
  mode) while its path stays free, and ``mat_loglik_lanes`` is pinned
  log-domain (``mixed`` — its exactness is the telescoping identity,
  runtime-parity-tested, not a homogeneity fact).

The lockfile follows the COSTS.json conventions (per-platform sections,
atomic replace, drift names the entry); staleness follows TUNING.json:
every entry is stamped with the :func:`tune.table.costs_fingerprint` of
the COSTS.json entries its kernels live under, so a kernel reshape that
re-baselines graftcost automatically STALES the scale signature — a
stale entry degrades to a report-only note (routing is never touched;
re-derive with ``--update-scale``).
"""

from __future__ import annotations

import dataclasses
import json
import os
from typing import Callable, Optional

LOCKFILE_VERSION = 1
LOCKFILE_NAME = "SCALE.json"


def default_lockfile_path() -> str:
    here = os.path.dirname(os.path.abspath(__file__))
    return os.path.join(os.path.dirname(os.path.dirname(here)), LOCKFILE_NAME)


# ---------------------------------------------------------------------------
# Rule metadata (for --list-rules; must not import jax).

_QUANT_RULES = (
    ("scale.free-consumers",
     "every registered consumer of self-normalized beta directions "
     "(posterior fused/one-pass conf+MPM, em-seq/em-chunked znorm stats, "
     "the one-pass matrix epilogues) derives scale-FREE outputs in the "
     "tagged beta stream",
     "r9: the co-scheduled backward self-normalizes, so fused betas are "
     "per-position directions; pairing them with the cs-scaled chunked "
     "stats kernel was a documented-but-unchecked bug class"),
    ("scale.exact-arms",
     "exact arms declare and verify their intended nonzero scale degree: "
     "split-pass cs-scaled macc = deg 1 in betas, flat-decode true scores "
     "= deg 1 in a log_pi offset (paths free), mat_loglik_lanes pinned "
     "log-domain",
     "true-score returns and cs-scaled stats are EXACT by scale "
     "bookkeeping — a signature drift means the bookkeeping moved"),
    ("scale.lockfile",
     "per-entry scale signatures match the committed SCALE.json; entries "
     "whose dependent COSTS.json fingerprint drifted degrade to "
     "report-only staleness notes (the TUNING.json freshness rule)",
     "kernel reshapes must re-derive, not silently re-certify"),
    ("scale.const-bytes",
     "no registered entry bakes constvars above memmodel's remote-compile "
     "constant budget into its traced graph",
     "a 256 MiB baked constant = HTTP 413 at the remote-compile relay "
     "(CLAUDE.md)"),
)


def quantitative_rules() -> list:
    """Static rule metadata for --list-rules (no jax import)."""
    return [
        {"name": n, "description": d, "origin": o} for n, d, o in _QUANT_RULES
    ]


# ---------------------------------------------------------------------------
# The entry registry.


@dataclasses.dataclass(frozen=True)
class ScaleEntry:
    """One certified consumer: a traceable fn with explicit tagged args."""

    name: str                 # keyed like the Layer-2/COSTS.json entries
    tagged: str               # human label of the tagged input
    mode: str                 # "linear" (prob space) | "maxplus" (log space)
    outputs: tuple            # output names, aligned with the fn's returns
    expect: dict              # output name -> "free" | "deg:k" | "mixed"
    costs_entries: tuple      # COSTS.json entries whose fingerprint keys staleness
    make: Callable            # () -> (fn, args, tagged_argnums)
    note: str = ""
    tags_key: str = ""        # "<ops module>:<SCALE_TAGS key>" cross-check


def _declared_tags(tags_key: str) -> dict:
    """Resolve an ops module's SCALE_TAGS declaration for cross-checking
    (the registration hook: the expectation lives NEXT TO the kernel)."""
    mod_name, _, key = tags_key.partition(":")
    import importlib

    mod = importlib.import_module(f"cpgisland_tpu.ops.{mod_name}")
    return mod.SCALE_TAGS[key]


def check_declarations(entries=None) -> list:
    """Every entry with a tags_key must agree with the ops module's
    SCALE_TAGS declaration (tagged input, mode, per-output expectation) —
    a mismatch means the registry and the kernel-side contract drifted
    apart.  Pure metadata: no tracing, no devices."""
    if entries is None:
        entries = default_entries()
    problems = []
    for e in entries:
        if not e.tags_key:
            continue
        try:
            decl = _declared_tags(e.tags_key)
        except (ImportError, KeyError, AttributeError) as exc:
            problems.append(
                f"{e.name}: tags_key '{e.tags_key}' unresolvable: {exc!r}")
            continue
        if decl.get("mode", "linear") != e.mode:
            problems.append(
                f"{e.name}: mode {e.mode!r} != declared "
                f"{decl.get('mode')!r} at {e.tags_key}")
        if decl.get("tagged") != e.tagged:
            problems.append(
                f"{e.name}: tagged {e.tagged!r} != declared "
                f"{decl.get('tagged')!r} at {e.tags_key}")
        if decl.get("outputs") != e.expect:
            problems.append(
                f"{e.name}: expectation {e.expect} != declared "
                f"{decl.get('outputs')} at {e.tags_key}")
    return problems


def _flagship():
    from cpgisland_tpu.models import presets

    return presets.durbin_cpg8()


def _reduced_streams(Tp=16, NL=4, seed=0):
    """Small positive reduced streams + pair/length plumbing for the
    consumer traces (values are irrelevant to the dataflow — only shapes
    and the graph structure matter)."""
    import numpy as np
    import jax.numpy as jnp

    from cpgisland_tpu.ops import fb_onehot

    params = _flagship()
    S, K = params.n_symbols, params.n_states
    gt = fb_onehot._groups(params)
    rng = np.random.default_rng(seed)

    def pos(shape):
        return jnp.asarray(rng.uniform(0.1, 1.0, shape).astype(np.float32))

    pair2 = jnp.asarray(rng.integers(0, S * S, size=(Tp, NL)).astype(np.int32))
    return dict(
        params=params, S=S, K=K, gt=gt, Tp=Tp, NL=NL,
        pair2=pair2,
        esym2=fb_onehot.decode_esym(pair2, S),
        lens2=jnp.full((1, NL), Tp, jnp.int32),
        al2=pos((Tp, 2, NL)), b2=pos((Tp, 2, NL)),
        alK=pos((Tp, K, NL)), bK=pos((Tp, K, NL)),
        va=pos((Tp, fb_onehot.GROUP * fb_onehot.GROUP, NL)),
        a0=pos((K, NL)), b0=pos((K, NL)),
        enters_red=pos((fb_onehot.GROUP, NL)),
        enters_full=pos((K, NL)),
        pair0_mask=jnp.ones((1, NL), jnp.float32),
        conf_mask=jnp.asarray(
            rng.integers(0, 2, K).astype(np.float32)),
    )


def _mk_posterior_fused():
    from cpgisland_tpu.ops import fb_pallas

    s = _reduced_streams()

    def fn(alphas, betas):
        return fb_pallas._conf_path_from_streams(
            alphas, betas, s["lens2"], s["conf_mask"])

    return fn, (s["alK"], s["bK"]), (1,)


def _mk_conf_reduced():
    from cpgisland_tpu.ops import fb_onehot

    s = _reduced_streams()

    def fn(al2, b2):
        return fb_onehot.conf_from_reduced(
            al2, b2, s["esym2"], s["lens2"], s["conf_mask"], s["gt"])

    return fn, (s["al2"], s["b2"]), (1,)


def _mk_znorm_stats(chunked: bool):
    import jax.numpy as jnp

    from cpgisland_tpu.ops import fb_onehot

    s = _reduced_streams()
    if chunked:
        # The fused/one-pass CHUNKED routing: zero enters, all-zero
        # pair0_mask (the only znorm configuration the route may build).
        enters_red = jnp.zeros_like(s["enters_red"])
        enters_full = jnp.zeros_like(s["enters_full"])
        pair0_mask = jnp.zeros_like(s["pair0_mask"])
    else:
        enters_red, enters_full, pair0_mask = (
            s["enters_red"], s["enters_full"], s["pair0_mask"])

    def fn(al2, b2):
        return fb_onehot.run_seq_stats_onehot(
            s["params"], al2, b2, s["pair2"], s["lens2"], s["gt"],
            enters_red, enters_full, pair0_mask, s["Tp"])

    return fn, (s["al2"], s["b2"]), (1,)


def _mk_cs_stats():
    from cpgisland_tpu.ops import fb_onehot

    s = _reduced_streams()

    def fn(al2, b2):
        return fb_onehot.run_stats_onehot(
            s["params"], al2, b2, s["pair2"], s["lens2"], s["gt"], s["Tp"])

    return fn, (s["al2"], s["b2"]), (1,)


def _mk_onepass_em():
    import jax.numpy as jnp

    from cpgisland_tpu.ops import fb_onehot

    s = _reduced_streams()
    zr = jnp.zeros_like(s["enters_red"])
    zf = jnp.zeros_like(s["enters_full"])
    zm = jnp.zeros_like(s["pair0_mask"])

    def fn(al2, b2):
        macc, emit_red, _ll = fb_onehot.run_seq_stats_onehot(
            s["params"], al2, b2, s["pair2"], s["lens2"], s["gt"],
            zr, zf, zm, s["Tp"])
        ll = fb_onehot.mat_loglik_lanes(s["va"], al2, s["lens2"])
        return macc, emit_red, ll

    return fn, (s["al2"], s["b2"]), (1,)


def _mk_onepass_posterior():
    from cpgisland_tpu.ops import fb_onehot, fb_pallas

    s = _reduced_streams()
    wb = s["va"]  # same geometry; values are irrelevant to the dataflow

    def fn(a0, b0):
        al2, b2 = fb_onehot.contract_mat_streams(
            s["va"], wb, a0, b0, s["gt"], s["esym2"])
        alphas = fb_onehot.scatter_streams(al2, s["gt"], s["esym2"], s["K"])
        betas = fb_onehot.scatter_streams(b2, s["gt"], s["esym2"], s["K"])
        return fb_pallas._conf_path_from_streams(
            alphas, betas, s["lens2"], s["conf_mask"])

    return fn, (s["a0"], s["b0"]), (1,)


def _mk_mat_epilogue():
    from cpgisland_tpu.ops import fb_onehot

    s = _reduced_streams()
    wb = s["va"]

    def fn(a0, b0):
        return fb_onehot.contract_mat_streams(
            s["va"], wb, a0, b0, s["gt"], s["esym2"])

    return fn, (s["a0"], s["b0"]), (1,)


def _mk_mat_loglik():
    from cpgisland_tpu.ops import fb_onehot

    s = _reduced_streams()

    def fn(va, al2):
        return fb_onehot.mat_loglik_lanes(va, al2, s["lens2"])

    return fn, (s["va"], s["al2"]), (0,)


def _mk_decode_score():
    import dataclasses as dc

    import numpy as np
    import jax.numpy as jnp

    from cpgisland_tpu.ops import viterbi_parallel as vp

    params = _flagship()
    rng = np.random.default_rng(0)
    obs = jnp.asarray(rng.integers(0, params.n_symbols, 64).astype(np.int32))

    def fn(dv):
        p = dc.replace(params, log_pi=params.log_pi + dv)
        return vp.viterbi_parallel(
            p, obs, block_size=32, return_score=True, engine="onehot")

    return fn, (jnp.float32(0.0),), (0,)


def default_entries() -> list:
    """The shipped registry: every fused/one-pass direction consumer plus
    the declared exact arms (names align with COSTS.json where a 1:1
    entry exists)."""
    return [
        ScaleEntry(
            name="posterior.onehot", tags_key="fb_pallas:_conf_path_from_streams", tagged="betas", mode="linear",
            outputs=("conf", "path"),
            expect={"conf": "free", "path": "free"},
            costs_entries=("posterior.onehot",),
            make=_mk_posterior_fused,
            note="fused want_path branch: gamma normalize + MPM argmax over "
                 "self-normalized beta directions"),
        ScaleEntry(
            name="posterior.conf.onehot", tags_key="fb_onehot:conf_from_reduced", tagged="betas2", mode="linear",
            outputs=("conf",),
            expect={"conf": "free"},
            costs_entries=("posterior.onehot",),
            make=_mk_conf_reduced,
            note="reduced conf ratio (the _bwd_conf_kernel contract)"),
        ScaleEntry(
            name="posterior.onehot.onepass", tagged="beta0", mode="linear",
            outputs=("conf", "path"),
            expect={"conf": "free", "path": "free"},
            costs_entries=("posterior.onehot.onepass",),
            make=_mk_onepass_posterior,
            note="matrix epilogue -> scatter -> conf+MPM; free in the "
                 "backward boundary direction"),
        ScaleEntry(
            name="em.seq.onehot", tags_key="fb_onehot:run_seq_stats_onehot", tagged="betas2", mode="linear",
            outputs=("macc", "emit_red", "ll"),
            expect={"macc": "free", "emit_red": "free", "ll": "free"},
            costs_entries=("em.seq.onehot",),
            make=lambda: _mk_znorm_stats(chunked=False),
            note="znorm stats with real enters: per-pair xi normalization "
                 "cancels any per-position beta scale"),
        ScaleEntry(
            name="em.chunked.onehot", tags_key="fb_onehot:run_seq_stats_onehot", tagged="betas2", mode="linear",
            outputs=("macc", "emit_red", "ll"),
            expect={"macc": "free", "emit_red": "free", "ll": "free"},
            costs_entries=("em.chunked.onehot",),
            make=lambda: _mk_znorm_stats(chunked=True),
            note="the ONLY legal fused/one-pass chunked stats routing: "
                 "znorm kernel with zero enters + all-zero pair0_mask"),
        ScaleEntry(
            name="em.seq.onehot.onepass", tagged="betas2", mode="linear",
            outputs=("macc", "emit_red", "ll"),
            expect={"macc": "free", "emit_red": "free", "ll": "free"},
            costs_entries=("em.seq.onehot.onepass",),
            make=_mk_onepass_em,
            note="one-pass stats composite: znorm stats are free in the "
                 "contracted betas; the lane loglik never reads them"),
        ScaleEntry(
            name="fb.mat.epilogue", tags_key="fb_onehot:contract_mat_streams", tagged="beta0", mode="linear",
            outputs=("alphas2", "betas2"),
            expect={"alphas2": "free", "betas2": "deg:1"},
            costs_entries=(
                "posterior.onehot.onepass", "em.seq.onehot.onepass"),
            make=_mk_mat_epilogue,
            note="contract_mat_streams: betas2 is LINEAR in the backward "
                 "boundary direction (consumers must erase it; alphas2 "
                 "never sees it)"),
        ScaleEntry(
            name="em.chunked.onehot.split", tags_key="fb_onehot:run_stats_onehot", tagged="betas2", mode="linear",
            outputs=("macc", "emit_red", "ll"),
            expect={"macc": "deg:1", "emit_red": "free", "ll": "free"},
            costs_entries=("em.chunked.onehot",),
            make=_mk_cs_stats,
            note="EXACT split-pass arm: macc is degree 1 in the cs-scaled "
                 "betas by construction (inv_cs carries the scale) — the "
                 "pairing guard (fb_onehot.run_stats_onehot betas_scale) "
                 "keeps self-normalized directions out at runtime"),
        ScaleEntry(
            name="em.seq.onepass.loglik", tags_key="fb_onehot:mat_loglik_lanes", tagged="va", mode="linear",
            outputs=("ll",),
            expect={"ll": "mixed"},
            costs_entries=("em.seq.onehot.onepass",),
            make=_mk_mat_loglik,
            note="pinned log-domain: exactness is the telescoping identity "
                 "(runtime-parity-tested), NOT a homogeneity fact — a "
                 "'free' derivation here would mean the loglik stopped "
                 "reading the matrix totals"),
        ScaleEntry(
            name="decode.score.onehot",
            tags_key="viterbi_onehot:viterbi_parallel.onehot",
            tagged="log_pi offset",
            mode="maxplus",
            outputs=("path", "score"),
            expect={"path": "free", "score": "deg:1"},
            costs_entries=("decode.onehot",),
            make=_mk_decode_score,
            note="true-score contract: scores shift by exactly the log_pi "
                 "offset (max-plus degree 1), paths are offset-invariant"),
    ]


# ---------------------------------------------------------------------------
# Derivation + the declared-expectation contracts.


def check_function(fn, args, tagged_argnums, expect, outputs,
                   mode: str = "linear", name: str = "<fn>") -> list:
    """Trace + analyze one consumer; return expectation-violation strings
    (with equation provenance).  The public harness the tests and planted
    fixtures use."""
    from cpgisland_tpu.analysis import scalemodel

    report, closed = scalemodel.trace_scales(
        fn, args, tagged_argnums, mode=mode)
    prov = scalemodel.out_provenance(closed)
    sig = report.signature()
    if len(sig) != len(outputs):
        return [
            f"{name}: output arity mismatch — {len(outputs)} declared, "
            f"{len(sig)} traced"
        ]
    violations = []
    for i, (out_name, got) in enumerate(zip(outputs, sig)):
        want = expect[out_name]
        if not _matches(want, got):
            scale = report.out_scales[i]
            where = (scale.why if scale.kind == "mixed"
                     else prov[i] if i < len(prov) else "<unknown>")
            violations.append(
                f"{name}: output '{out_name}' expected {want}, derived "
                f"{got} in tagged input — {where}")
    return violations


def _matches(want: str, got: str) -> bool:
    if want == "free":
        return got in ("free", "any")
    return got == want


def derive_entry(entry: ScaleEntry) -> dict:
    """Trace one entry; returns its live record (signature + const bytes +
    expectation violations)."""
    from cpgisland_tpu.analysis import scalemodel

    fn, args, tagged = entry.make()
    report, closed = scalemodel.trace_scales(
        fn, args, tagged, mode=entry.mode)
    prov = scalemodel.out_provenance(closed)
    sig = report.signature()
    record = {
        "tagged": entry.tagged,
        "mode": entry.mode,
        "signature": dict(zip(entry.outputs, sig)),
        "costs_entries": list(entry.costs_entries),
    }
    violations = []
    if len(sig) != len(entry.outputs):
        violations.append(
            f"{entry.name}: output arity mismatch — "
            f"{len(entry.outputs)} declared, {len(sig)} traced")
    else:
        for i, (out_name, got) in enumerate(zip(entry.outputs, sig)):
            want = entry.expect[out_name]
            if not _matches(want, got):
                scale = report.out_scales[i]
                where = (scale.why if scale.kind == "mixed"
                         else prov[i] if i < len(prov) else "<unknown>")
                rule = ("scale.free-consumers" if want == "free"
                        else "scale.exact-arms")
                violations.append(
                    f"[{rule}] {entry.name}: output '{out_name}' expected "
                    f"{want}, derived {got} in tagged {entry.tagged} — "
                    f"{where}")
    cb = scalemodel.const_bytes(closed)
    record["const_bytes"] = cb
    from cpgisland_tpu.analysis import memmodel

    budget = memmodel.remote_const_budget()
    if cb > budget:
        violations.append(
            f"[scale.const-bytes] {entry.name}: {cb} baked constant bytes "
            f"> remote-compile budget {budget} (the HTTP 413 cliff)")
    return record, violations


def live_entries(entries=None):
    """(records, violations) over the registry — traced on the current
    (CPU) backend."""
    if entries is None:
        entries = default_entries()
    records, violations = {}, []
    for e in entries:
        rec, viol = derive_entry(e)
        records[e.name] = rec
        violations.extend(viol)
    return records, violations


# ---------------------------------------------------------------------------
# Lockfile (COSTS.json conventions + TUNING.json staleness).


def _fingerprint(costs_entries) -> str:
    from cpgisland_tpu.tune import table

    return table.costs_fingerprint(tuple(costs_entries))


def load_lockfile(path: Optional[str] = None) -> Optional[dict]:
    path = path or default_lockfile_path()
    if not os.path.exists(path):
        return None
    with open(path) as f:
        return json.load(f)


def write_lockfile(records: dict, path: Optional[str] = None,
                   platform: str = "cpu") -> str:
    import jax

    path = path or default_lockfile_path()
    lock = load_lockfile(path) or {
        "version": LOCKFILE_VERSION, "platforms": {}}
    stamped = {}
    for name, rec in sorted(records.items()):
        stamped[name] = dict(
            rec, costs_fingerprint=_fingerprint(rec["costs_entries"]))
    lock["platforms"][platform] = {
        "jax": jax.__version__, "entries": stamped}
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(lock, f, indent=1, sort_keys=True)
        f.write("\n")
    os.replace(tmp, path)
    return path


@dataclasses.dataclass
class ScaleDiff:
    violations: list
    notes: list
    stale: list
    checked: int = 0

    @property
    def ok(self) -> bool:
        return not self.violations

    def as_dict(self) -> dict:
        return {
            "violations": self.violations, "notes": self.notes,
            "stale": self.stale, "checked": self.checked, "ok": self.ok,
        }


def diff_scales(live: dict, lock: Optional[dict],
                platform: str = "cpu") -> ScaleDiff:
    """Compare live signatures against the lockfile.  Fingerprint-drifted
    entries degrade to report-only staleness notes (the TUNING.json rule:
    a kernel reshape re-derives, it does not silently re-certify)."""
    d = ScaleDiff([], [], [])
    if lock is None:
        d.violations.append(
            f"no {LOCKFILE_NAME} lockfile — run "
            "`python -m cpgisland_tpu.analysis --update-scale` and commit")
        return d
    plats = lock.get("platforms", {})
    if platform not in plats:
        d.notes.append(
            f"{LOCKFILE_NAME} has no '{platform}' section — skipped "
            "(derive with --update-scale on this platform)")
        return d
    locked = plats[platform].get("entries", {})
    for name, rec in sorted(live.items()):
        if name not in locked:
            d.violations.append(
                f"scale entry '{name}' missing from {LOCKFILE_NAME} — "
                "re-baseline with --update-scale")
            continue
        lrec = locked[name]
        want_fp = _fingerprint(rec["costs_entries"])
        have_fp = lrec.get("costs_fingerprint")
        if have_fp != want_fp:
            d.stale.append(name)
            d.notes.append(
                f"scale stale '{name}': dependent COSTS.json fingerprint "
                f"drifted ({have_fp} -> {want_fp}) — signature is "
                "report-only until --update-scale re-derives it "
                f"(live: {rec['signature']})")
            continue
        d.checked += 1
        if lrec.get("signature") != rec["signature"]:
            d.violations.append(
                f"[scale.lockfile] '{name}' signature drifted: locked "
                f"{lrec.get('signature')} vs live {rec['signature']} — "
                "verify the consumer change, then --update-scale")
    for name in sorted(set(locked) - set(live)):
        d.notes.append(
            f"locked scale entry '{name}' no longer registered — "
            "--update-scale will drop it")
    return d


def update_summary(live: dict, lock: Optional[dict],
                   platform: str = "cpu") -> list:
    out = []
    locked = ((lock or {}).get("platforms", {})
              .get(platform, {}).get("entries", {}))
    for name, rec in sorted(live.items()):
        if name not in locked:
            out.append(f"new scale entry {name}: {rec['signature']}")
        elif locked[name].get("signature") != rec["signature"]:
            out.append(
                f"scale {name}: {locked[name].get('signature')} -> "
                f"{rec['signature']}")
    return out


def run_scale_pass(lockfile_path: Optional[str] = None,
                   update: bool = False, entries=None) -> dict:
    """Derive, check declared expectations, diff against SCALE.json.

    Returns {"ok", "diff", "entries", "violations", "updated", "summary",
    "path", "platform"} — the same consumption shape as the cost/mem
    passes.  On a TPU backend the pass SKIPS (the signatures certify the
    CPU XLA twins; pallas bodies are opaque to the dataflow) with a note.
    """
    import jax

    platform = jax.default_backend()
    out: dict = {"platform": platform, "updated": False}
    if platform == "tpu":
        out["diff"] = ScaleDiff(
            [], [f"scale pass skipped on '{platform}' — the dataflow "
                 "certifies the CPU XLA twins (engine parity is pinned by "
                 "Layer 2); run on CPU"], []).as_dict()
        out["entries"] = {}
        out["violations"] = []
        out["ok"] = True
        return out
    violations = check_declarations(entries)
    records, derive_viol = live_entries(entries)
    violations.extend(derive_viol)
    lock = load_lockfile(lockfile_path)
    if update:
        out["summary"] = update_summary(records, lock, "cpu")
        path = write_lockfile(records, lockfile_path, "cpu")
        out["updated"] = True
        out["path"] = path
        lock = load_lockfile(lockfile_path)
    diff = diff_scales(records, lock, "cpu")
    out["diff"] = diff.as_dict()
    out["entries"] = records
    out["violations"] = violations
    out["ok"] = diff.ok and not violations
    return out


def format_failure(report: dict) -> str:
    """One-line JSON summary of a failing run_scale_pass report."""
    return json.dumps({
        "violations": report.get("violations", []),
        "diff": report.get("diff", {}).get("violations", []),
    })
