"""graftsync static model: locks, held regions, and the lock-order graph.

Layer 4's shared machinery.  Everything here is plain-``ast`` analysis (no
jax, no execution, same as the rest of the lint layer): this module models

- **lock identities** — instance attributes assigned ``threading.Lock()`` /
  ``RLock()`` / ``Condition()`` in a class, module-level lock globals, and
  function-local locks.  ``threading.Condition(self._lock)`` aliases to the
  SAME lock group as ``self._lock`` (one underlying mutex — ``with
  self._cv`` and ``with self._lock`` guard the same state and must never be
  treated as two locks);
- **held regions** — a statement-level walk of every function tracking which
  lock groups are held (``with <lock>:`` nesting).  Methods and module
  functions whose name ends in ``_locked`` are analyzed as running with
  their owner's locks already held (the ``_ready_locked`` convention);
  lambdas inherit the current held set (they are condition-variable
  predicates and immediately-invoked callbacks in this codebase), nested
  ``def``s do not (they may run on any thread later);
- **the acquires-while-holding graph** — an edge ``A -> B`` whenever B is
  acquired (directly, or transitively through a resolvable call) while A is
  held.  Call resolution is three-tier: exact (imported names canonicalized
  through the file's imports to a scanned module function), same-class
  (``self.method()``), and method-name fallback (``x.allowed()`` matches
  every scanned method named ``allowed`` that acquires a lock — the
  conservative tier that catches ``session lock -> breaker lock`` without
  type inference).  A cycle in the graph is a static deadlock; a
  non-reentrant lock reachable under itself is a self-deadlock.

:func:`run_sync` builds the graph across a file set (the CLI's ``--sync``
pass and the repo self-test); the per-file ``sync-lock-order`` rule in
:mod:`rules_sync` runs the same machinery on one file so fixtures and
single-file CLI runs behave like every other lint rule.
"""

from __future__ import annotations

import ast
import dataclasses
import os
from typing import Iterable, Iterator, Optional

from cpgisland_tpu.analysis import astutil
from cpgisland_tpu.analysis.core import FileContext, Finding, discover_files

LOCK_FACTORIES = {
    "threading.Lock": "lock",
    "threading.RLock": "rlock",
    "threading.Condition": "cond",
}

#: attribute method calls treated as WRITES to the receiver (container
#: mutation: ``self._queue.append(x)`` mutates ``_queue``).
MUTATORS = frozenset({
    "append", "appendleft", "add", "discard", "remove", "pop", "popleft",
    "popitem", "clear", "update", "setdefault", "extend", "insert",
    "move_to_end",
})


@dataclasses.dataclass(frozen=True)
class Lock:
    """One lock group.  ``scope`` is the owning class name ('' for module
    scope, 'fn:<name>' for function locals); ``kind`` is 'lock' / 'rlock' /
    'cond' (a Condition over its own implicit lock behaves like an RLock
    for reentrancy purposes only through its owner — we model Lock and
    Condition as non-reentrant, RLock as reentrant)."""

    module: str
    scope: str
    name: str
    kind: str

    @property
    def label(self) -> str:
        scope = f"{self.scope}." if self.scope else ""
        return f"{self.module}::{scope}{self.name}"

    @property
    def reentrant(self) -> bool:
        return self.kind == "rlock"


@dataclasses.dataclass(frozen=True)
class Edge:
    src: Lock
    dst: Lock
    path: str
    line: int
    via: str  # '' for a direct nested `with`, else the call that carries it


class FileSyncModel:
    """Per-file lock model: lock identities + per-function info."""

    def __init__(self, ctx: FileContext):
        self.ctx = ctx
        self.module = ctx.relpath
        # class name -> {attr -> Lock}; aliases resolved to one group.
        self.class_locks: dict[str, dict[str, Lock]] = {}
        # module-global name -> Lock
        self.module_locks: dict[str, Lock] = {}
        # class name -> attrs assigned queue.Queue(...) / threading.Thread(...)
        self.queue_attrs: dict[str, set[str]] = {}
        self.thread_attrs: dict[str, set[str]] = {}
        self._collect_locks()

    # -- lock discovery ------------------------------------------------------

    def _factory_kind(self, value: ast.AST) -> Optional[str]:
        if not isinstance(value, ast.Call):
            return None
        canon = self.ctx.imports.canonical(value.func)
        return LOCK_FACTORIES.get(canon or "")

    def _collect_locks(self) -> None:
        for node in ast.walk(self.ctx.tree):
            if isinstance(node, ast.ClassDef):
                self._collect_class(node)
        # Module-level lock globals (two passes: Condition(lock) aliasing).
        for _pass in (0, 1):
            for node in self.ctx.tree.body:
                if not (isinstance(node, ast.Assign) and len(node.targets) == 1
                        and isinstance(node.targets[0], ast.Name)):
                    continue
                kind = self._factory_kind(node.value)
                if kind is None:
                    continue
                name = node.targets[0].id
                alias = self._cond_alias(node.value, kind, self.module_locks)
                self.module_locks[name] = alias if alias is not None else Lock(
                    self.module, "", name, kind
                )

    def _cond_alias(self, call: ast.Call, kind: str,
                    known: dict[str, Lock]) -> Optional[Lock]:
        """``Condition(<known lock>)`` shares the underlying mutex: alias it
        to the existing group instead of minting a second identity."""
        if kind != "cond" or not call.args:
            return None
        arg = call.args[0]
        if isinstance(arg, ast.Name):
            return known.get(arg.id)
        if (isinstance(arg, ast.Attribute) and isinstance(arg.value, ast.Name)
                and arg.value.id == "self"):
            return known.get(arg.attr)
        return None

    def _collect_class(self, cls: ast.ClassDef) -> None:
        locks: dict[str, Lock] = {}
        queues: set[str] = set()
        threads: set[str] = set()
        methods = [
            n for n in cls.body
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))
        ]
        for _pass in (0, 1):  # second pass resolves Condition(self._lock)
            for m in methods:
                for node in astutil.walk_scope(m):
                    if not (isinstance(node, ast.Assign)
                            and len(node.targets) == 1):
                        continue
                    t = node.targets[0]
                    if not (isinstance(t, ast.Attribute)
                            and isinstance(t.value, ast.Name)
                            and t.value.id == "self"):
                        continue
                    kind = self._factory_kind(node.value)
                    if kind is not None:
                        alias = self._cond_alias(node.value, kind, locks)
                        locks[t.attr] = alias if alias is not None else Lock(
                            self.module, cls.name, t.attr, kind
                        )
                        continue
                    canon = (
                        self.ctx.imports.canonical(node.value.func)
                        if isinstance(node.value, ast.Call) else None
                    )
                    if canon == "queue.Queue":
                        queues.add(t.attr)
                    elif canon == "threading.Thread":
                        threads.add(t.attr)
        if locks:
            self.class_locks[cls.name] = locks
        if queues:
            self.queue_attrs[cls.name] = queues
        if threads:
            self.thread_attrs[cls.name] = threads

    # -- lock-expression resolution -----------------------------------------

    def local_locks(self, fn: ast.AST, fn_label: str) -> dict[str, Lock]:
        out: dict[str, Lock] = {}
        for node in astutil.walk_scope(fn):
            if (isinstance(node, ast.Assign) and len(node.targets) == 1
                    and isinstance(node.targets[0], ast.Name)):
                kind = self._factory_kind(node.value)
                if kind is not None:
                    alias = self._cond_alias(node.value, kind, out)
                    name = node.targets[0].id
                    out[name] = alias if alias is not None else Lock(
                        self.module, f"fn:{fn_label}", name, kind
                    )
        return out

    def resolver(self, class_name: Optional[str],
                 locals_map: dict[str, Lock]):
        """A ``resolve(expr) -> Lock | None`` closure for one function."""
        class_map = self.class_locks.get(class_name or "", {})

        def resolve(expr: ast.AST) -> Optional[Lock]:
            if (isinstance(expr, ast.Attribute)
                    and isinstance(expr.value, ast.Name)
                    and expr.value.id == "self"):
                return class_map.get(expr.attr)
            if isinstance(expr, ast.Name):
                return locals_map.get(expr.id) or self.module_locks.get(expr.id)
            return None

        return resolve


# -- held-region walking -----------------------------------------------------


def walk_held(
    fn: ast.AST, resolve, base_held: frozenset
) -> Iterator[tuple[ast.AST, frozenset]]:
    """Yield ``(node, held_locks)`` over ``fn``'s own scope.

    ``with <lock>:`` bodies extend the held set.  Nested ``def`` bodies are
    walked with an EMPTY held set (they may execute later, on any thread);
    lambdas inherit the current held set (cv predicates, inline callbacks).
    """

    def walk(node: ast.AST, held: frozenset) -> Iterator:
        yield node, held
        if isinstance(node, ast.With):
            body_held = set(held)
            for item in node.items:
                yield from walk(item.context_expr, held)
                if item.optional_vars is not None:
                    yield from walk(item.optional_vars, held)
                lk = resolve(item.context_expr)
                if lk is not None:
                    body_held.add(lk)
            frozen = frozenset(body_held)
            for child in node.body:
                yield from walk(child, frozen)
        elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            for child in ast.iter_child_nodes(node):
                yield from walk(child, frozenset())
        elif isinstance(node, ast.Lambda):
            for child in ast.iter_child_nodes(node):
                yield from walk(child, held)
        else:
            for child in ast.iter_child_nodes(node):
                yield from walk(child, held)

    for child in ast.iter_child_nodes(fn):
        yield from walk(child, base_held)


def base_held_for(name: str, lock_groups: Iterable[Lock]) -> frozenset:
    """The ``_locked`` suffix convention: such a function runs with its
    owner's locks already held (callers acquire; see broker._ready_locked)."""
    if name.endswith("_locked"):
        return frozenset(lock_groups)
    return frozenset()


def iter_functions(model: FileSyncModel):
    """Yield ``(class_name_or_None, fn_node, qualname)`` for every function
    in the file (module functions and direct class methods; nested defs are
    visited through their parents' walks, not as entries)."""
    tree = model.ctx.tree
    for node in tree.body:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            yield None, node, node.name
    for node in ast.walk(tree):
        if isinstance(node, ast.ClassDef):
            for m in node.body:
                if isinstance(m, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    yield node.name, m, f"{node.name}.{m.name}"


def attr_write_p(node: ast.Attribute) -> bool:
    """Is this ``self.x`` attribute node a WRITE (assignment, deletion,
    subscript store, augmented assignment, or container mutator call)?"""
    if isinstance(node.ctx, (ast.Store, ast.Del)):
        return True
    parent = getattr(node, "parent", None)
    if (isinstance(parent, ast.Subscript) and parent.value is node
            and isinstance(parent.ctx, (ast.Store, ast.Del))):
        return True
    if (isinstance(parent, ast.Attribute) and parent.value is node
            and parent.attr in MUTATORS):
        gp = getattr(parent, "parent", None)
        if isinstance(gp, ast.Call) and gp.func is parent:
            return True
    return False


def name_write_p(node: ast.Name, global_names: set[str]) -> bool:
    """Module-global write: a ``global``-declared rebind, a subscript store,
    or a container mutator call on a module-level name."""
    if isinstance(node.ctx, (ast.Store, ast.Del)):
        return node.id in global_names
    parent = getattr(node, "parent", None)
    if (isinstance(parent, ast.Subscript) and parent.value is node
            and isinstance(parent.ctx, (ast.Store, ast.Del))):
        return True
    if (isinstance(parent, ast.Attribute) and parent.value is node
            and parent.attr in MUTATORS):
        gp = getattr(parent, "parent", None)
        if isinstance(gp, ast.Call) and gp.func is parent:
            return True
    return False


def declared_globals(fn: ast.AST) -> set[str]:
    out: set[str] = set()
    for node in astutil.walk_scope(fn):
        if isinstance(node, ast.Global):
            out.update(node.names)
    return out


# -- the cross-file lock-order graph -----------------------------------------


@dataclasses.dataclass
class _FnInfo:
    model: FileSyncModel
    class_name: Optional[str]
    node: ast.AST
    qualname: str
    direct: set  # locks acquired via `with` anywhere in the body
    calls: list  # (call node, held-at-call)


class LockGraph:
    """Acquires-while-holding edges across a set of file models."""

    def __init__(self, models: list[FileSyncModel]):
        self.models = models
        self.fns: dict[tuple[str, str], _FnInfo] = {}
        # method-name fallback index: bare name -> [(module, qualname)]
        self.by_method: dict[str, list[tuple[str, str]]] = {}
        self.edges: list[Edge] = []
        self.self_deadlocks: list[tuple[Lock, str, int, str]] = []
        self._collect()
        self._trans = self._transitive_acquires()
        self._build_edges()

    # -- phase 1: per-function direct acquires + call sites ------------------

    def _collect(self) -> None:
        for model in self.models:
            for class_name, fn, qual in iter_functions(model):
                locals_map = model.local_locks(fn, qual)
                resolve = model.resolver(class_name, locals_map)
                # Owner's locks for the `_locked` convention: class locks
                # AND module locks — a module-level `_sweep_dead_locked`
                # runs with the module lock held, and modeling it with an
                # empty held set would drop its acquires-while-holding
                # edges from the deadlock graph.
                groups = (
                    set(model.class_locks.get(class_name or "", {}).values())
                    | set(model.module_locks.values())
                )
                base = base_held_for(fn.name, groups)
                direct: set = set()
                calls: list = []
                for node, held in walk_held(fn, resolve, base):
                    if isinstance(node, ast.With):
                        for item in node.items:
                            lk = resolve(item.context_expr)
                            if lk is not None:
                                direct.add(lk)
                                self._note_acquire(lk, held, model, node, "")
                    elif isinstance(node, ast.Call):
                        calls.append((node, held))
                info = _FnInfo(model, class_name, fn, qual, direct, calls)
                self.fns[(model.module, qual)] = info
                bare = fn.name
                self.by_method.setdefault(bare, []).append(
                    (model.module, qual)
                )

    def _note_acquire(
        self, lk: Lock, held: frozenset, model: FileSyncModel,
        node: ast.AST, via: str,
    ) -> None:
        for h in held:
            if h == lk:
                if not lk.reentrant:
                    self.self_deadlocks.append(
                        (lk, model.module, node.lineno, via)
                    )
                continue
            self.edges.append(Edge(
                src=h, dst=lk, path=model.module,
                line=getattr(node, "lineno", 1), via=via,
            ))

    # -- phase 2: call resolution + transitive acquire sets ------------------

    def _resolve_call(self, info: _FnInfo, call: ast.Call) -> list:
        """Scanned functions a call may enter (exact > self-method >
        method-name fallback; the fallback only matches methods that acquire
        locks, bounding its noise to lock-relevant call sites)."""
        func = call.func
        model = info.model
        out: list[tuple[str, str]] = []
        canon = model.ctx.imports.canonical(func)
        if canon and canon.startswith("cpgisland_tpu."):
            rel = canon[len("cpgisland_tpu."):]
            mod_path, _, fname = rel.rpartition(".")
            suffix = mod_path.replace(".", "/") + ".py"
            for m in self.models:
                if m.module.endswith(suffix) and (m.module, fname) in self.fns:
                    out.append((m.module, fname))
        if isinstance(func, ast.Name):
            key = (model.module, func.id)
            if key in self.fns:
                out.append(key)
        if isinstance(func, ast.Attribute):
            if (isinstance(func.value, ast.Name) and func.value.id == "self"
                    and info.class_name):
                key = (model.module, f"{info.class_name}.{func.attr}")
                if key in self.fns:
                    out.append(key)
            if not out:
                for mod, qual in self.by_method.get(func.attr, ()):
                    if "." in qual:  # methods only — the conservative tier
                        out.append((mod, qual))
        return out

    def _transitive_acquires(self) -> dict:
        trans = {k: set(v.direct) for k, v in self.fns.items()}
        for _ in range(8):  # fixpoint (call-chain depth bound)
            changed = False
            for key, info in self.fns.items():
                acc = trans[key]
                before = len(acc)
                for call, _held in info.calls:
                    for callee in self._resolve_call(info, call):
                        acc |= trans.get(callee, set())
                if len(acc) != before:
                    changed = True
            if not changed:
                break
        return trans

    def _build_edges(self) -> None:
        for info in self.fns.values():
            for call, held in info.calls:
                if not held:
                    continue
                acquired: set = set()
                via_names: dict = {}
                for callee in self._resolve_call(info, call):
                    for lk in self._trans.get(callee, ()):  # noqa: B020
                        acquired.add(lk)
                        via_names.setdefault(lk, callee[1])
                for lk in acquired:
                    self._note_acquire(
                        lk, held, info.model, call, via_names.get(lk, "?")
                    )

    # -- cycles --------------------------------------------------------------

    def unique_edges(self) -> dict:
        """(src, dst) -> representative Edge (first site seen)."""
        out: dict = {}
        for e in self.edges:
            out.setdefault((e.src, e.dst), e)
        return out

    def cycles(self) -> list[list[Edge]]:
        """Elementary cycles in the order graph (DFS; each reported once)."""
        uniq = self.unique_edges()
        adj: dict = {}
        for (src, dst), e in uniq.items():
            adj.setdefault(src, []).append((dst, e))
        seen_cycles: set = set()
        out: list[list[Edge]] = []

        def dfs(start: Lock, cur: Lock, path: list[Edge], on_path: set):
            for nxt, e in adj.get(cur, ()):
                if nxt == start:
                    cyc = path + [e]
                    key = frozenset((x.src, x.dst) for x in cyc)
                    if key not in seen_cycles:
                        seen_cycles.add(key)
                        out.append(cyc)
                elif nxt not in on_path:
                    dfs(start, nxt, path + [e], on_path | {nxt})

        for node in adj:
            dfs(node, node, [], {node})
        return out


# -- the public pass ---------------------------------------------------------


@dataclasses.dataclass
class SyncReport:
    files_checked: int
    locks: list[Lock]
    edges: list[Edge]
    findings: list[Finding]

    @property
    def ok(self) -> bool:
        return not self.findings

    def summary(self) -> dict:
        return {
            "files_checked": self.files_checked,
            "locks": sorted(lk.label for lk in self.locks),
            "edges": sorted(
                f"{e.src.label} -> {e.dst.label}"
                for e in {(e.src, e.dst): e for e in self.edges}.values()
            ),
            "violations": [f.format() for f in self.findings],
        }


def build_models(paths: Iterable[str], base: Optional[str] = None):
    base = base or os.getcwd()
    models: list[FileSyncModel] = []
    for path in discover_files(paths):
        rel = os.path.relpath(path, base)
        if rel.startswith(".."):
            rel = path
        try:
            with open(path, "r", encoding="utf-8") as fh:
                ctx = FileContext(path, fh.read(),
                                  relpath=rel.replace(os.sep, "/"))
        except (OSError, SyntaxError):
            continue  # parse errors are the lint layer's finding, not ours
        models.append(FileSyncModel(ctx))
    return models


def graph_findings(graph: LockGraph) -> list[Finding]:
    findings: list[Finding] = []
    for cyc in graph.cycles():
        locks = " -> ".join([e.src.label for e in cyc] + [cyc[0].src.label])
        sites = "; ".join(
            f"{e.path}:{e.line}"
            + (f" (via {e.via})" if e.via else "") for e in cyc
        )
        findings.append(Finding(
            "sync-lock-order", cyc[0].path, cyc[0].line, 1,
            f"lock-order cycle (static deadlock): {locks} — acquisition "
            f"sites: {sites}; pick one global order and stick to it",
        ))
    for lk, path, line, via in graph.self_deadlocks:
        findings.append(Finding(
            "sync-lock-order", path, line, 1,
            f"non-reentrant lock {lk.label} may be re-acquired while "
            f"already held"
            + (f" (through a call into {via})" if via else "")
            + " — a plain Lock/Condition self-deadlocks here; restructure "
            "or use the _locked-suffix convention for the inner helper",
        ))
    return findings


def run_sync(
    paths: Optional[Iterable[str]] = None, base: Optional[str] = None
) -> SyncReport:
    """Build the cross-module lock-order graph over ``paths`` (default: the
    installed package) and report cycles/self-deadlocks."""
    if paths is None:
        pkg = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        paths = [pkg]
        base = base or os.path.dirname(pkg)
    models = build_models(paths, base=base)
    graph = LockGraph(models)
    locks: set = set()
    for m in models:
        locks.update(m.module_locks.values())
        for d in m.class_locks.values():
            locks.update(d.values())
    return SyncReport(
        files_checked=len(models),
        locks=sorted(locks, key=lambda lk: lk.label),
        edges=graph.edges,
        findings=graph_findings(graph),
    )
