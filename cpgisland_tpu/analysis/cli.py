"""graftcheck CLI: ``python -m cpgisland_tpu.analysis [paths...]``.

Exit codes: 0 clean (waived findings allowed), 1 violations (lint findings
or contract violations), 2 usage error.  The default run is the pure-AST
lint layer (no tracing, no devices — sub-second past the package import);
``--contracts`` adds the jaxpr contract pass, which traces the registered
entry points on abstract inputs (CPU, seconds); ``--costs`` adds Layer 3 —
the quantitative cost pass (COSTS.json lockfile diff + cost contracts),
re-baselined with ``--update-costs`` after a verified change.
``--cost-table ENTRY`` prints the per-group fixed-vs-per-symbol
attribution table (the BASELINE.md size-curve decomposition).
``--sync`` adds Layer 4's cross-module pass — the lock-order graph over
the whole file set (static deadlock detection; still pure AST, no jax) —
on top of the per-file sync rules that already run in the lint layer.
``--mem`` adds Layer 5 — the memory pass (MEMORY.json lockfile diff +
VMEM/HBM contracts), re-baselined with ``--update-mem``; ``--mem-table
KERNEL`` prints one modeled kernel's VMEM buffer breakdown.
``--scale`` adds Layer 6 — the scale pass (jaxpr homogeneity dataflow
over the fused/one-pass direction consumers + the SCALE.json lockfile
diff), re-baselined with ``--update-scale``.
"""

from __future__ import annotations

import argparse
import json
import os
import sys


def _default_paths() -> list[str]:
    """The package itself, resolved from the installed location so the CLI
    works from any cwd."""
    pkg = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    return [pkg]


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m cpgisland_tpu.analysis",
        description="graftcheck: project lint + jaxpr contract checker "
        "enforcing the codebase's TPU invariants",
    )
    ap.add_argument("paths", nargs="*", help="files/dirs to lint "
                    "(default: the cpgisland_tpu package)")
    ap.add_argument("--rules", default=None,
                    help="comma-separated rule subset (see --list-rules)")
    ap.add_argument("--list-rules", action="store_true",
                    help="list rules with their origin stories and exit")
    ap.add_argument("--json", action="store_true", dest="as_json",
                    help="machine-readable findings on stdout")
    ap.add_argument("--show-waived", action="store_true",
                    help="also print waived findings")
    ap.add_argument("--strict-waivers", action="store_true",
                    help="fail on waivers that cover nothing (stale waivers)")
    ap.add_argument("--no-lint", action="store_true",
                    help="skip the AST lint layer")
    ap.add_argument("--sync", action="store_true",
                    help="also run the Layer-4 cross-module lock-order "
                    "graph (graftsync: cycles and self-deadlocks across "
                    "files; the per-file sync rules run in the lint layer)")
    ap.add_argument("--contracts", action="store_true",
                    help="also run the jaxpr contract pass (imports jax)")
    ap.add_argument("--no-exec", action="store_true",
                    help="contracts: trace only, skip the dispatch-stability "
                    "execution checks")
    ap.add_argument("--costs", action="store_true",
                    help="run the Layer-3 cost pass: diff live cost "
                    "fingerprints against COSTS.json and check the "
                    "quantitative cost contracts (imports jax)")
    ap.add_argument("--update-costs", action="store_true",
                    help="re-baseline COSTS.json from the live traces and "
                    "print a diff summary (implies --costs)")
    ap.add_argument("--costs-file", default=None,
                    help="lockfile path (default: <repo>/COSTS.json)")
    ap.add_argument("--cost-table", default=None, metavar="ENTRY",
                    help="print the fixed-vs-per-symbol attribution table "
                    "for one cost entry (e.g. em.seq.onehot) and exit")
    ap.add_argument("--mem", action="store_true",
                    help="run the Layer-5 memory pass: diff live HBM "
                    "liveness fingerprints + shipped-knob VMEM footprints "
                    "against MEMORY.json and check the memory contracts "
                    "(imports jax)")
    ap.add_argument("--update-mem", action="store_true",
                    help="re-baseline MEMORY.json from the live traces "
                    "and print a diff summary (implies --mem)")
    ap.add_argument("--mem-file", default=None,
                    help="mem lockfile path (default: <repo>/MEMORY.json)")
    ap.add_argument("--mem-table", default=None, metavar="KERNEL",
                    help="print the VMEM buffer breakdown for one modeled "
                    "kernel (e.g. fb.fwdbwd.onehot) and exit")
    ap.add_argument("--scale", action="store_true",
                    help="run the Layer-6 scale pass: derive homogeneity "
                    "signatures for every registered fused/one-pass "
                    "direction consumer, check the declared expectations, "
                    "and diff against SCALE.json (imports jax)")
    ap.add_argument("--update-scale", action="store_true",
                    help="re-baseline SCALE.json from the live derivations "
                    "and print a diff summary (implies --scale)")
    ap.add_argument("--scale-file", default=None,
                    help="scale lockfile path (default: <repo>/SCALE.json)")
    ap.add_argument("--tune", action="store_true",
                    help="report the graftune winner table (TUNING.json): "
                    "fresh vs stale winners for this platform, stale rows "
                    "NAMED with their COSTS.json fingerprint-drift reason "
                    "(stale-waiver UX — advisory, staleness is the design "
                    "working; re-sweep with tools/graftune.py)")
    ap.add_argument("--tune-file", default=None,
                    help="winner-table path (default: <repo>/TUNING.json)")
    ap.add_argument("--platform", default="cpu",
                    help="contracts backend: cpu (default — the pass is "
                    "designed to certify without a TPU) | tpu | auto "
                    "(whatever jax picks)")
    args = ap.parse_args(argv)

    from cpgisland_tpu.analysis import core

    if args.list_rules:
        for rule in core.all_rules().values():
            print(f"{rule.name}: {rule.description}")
            if rule.origin:
                print(f"    origin: {rule.origin}")
        # Layer 3 (quantitative cost contracts) — listed without importing
        # jax: the rule table is static metadata.
        from cpgisland_tpu.analysis import cost_contracts

        for name, desc in cost_contracts.quantitative_rules():
            print(f"{name}: {desc}")
            print("    origin: BASELINE.md size curve — ~8-11 ms fixed "
                  "in-graph cost/iter bounds em-seq2d; cost regressions "
                  "must fail statically, not on relay-TPU")
        # Layer 5 (memory contracts) — same static metadata path.
        from cpgisland_tpu.analysis import mem_contracts

        for name, desc in mem_contracts.quantitative_rules():
            print(f"{name}: {desc}")
            print("    origin: every memory cliff here was found "
                  "empirically on chip — 131072-lane assembly compile "
                  "failure, bk>=8192 scoped-VMEM, the 128 Mi shard, the "
                  "~15 GB island OOM; graftmem makes them static")
        # Layer 6 (scale contracts) — same static metadata path.
        from cpgisland_tpu.analysis import scale_contracts

        for rule in scale_contracts.quantitative_rules():
            print(f"{rule['name']}: {rule['description']}")
            print(f"    origin: {rule['origin']}")
        return 0

    rc = 0
    payload: dict = {}

    if args.cost_table:
        _pin_platform(args.platform)
        from cpgisland_tpu.analysis import cost_contracts, costmodel

        entries = {c.name: c for c in cost_contracts.cost_entries()}
        if args.cost_table not in entries:
            print(
                f"error: unknown cost entry {args.cost_table!r} "
                f"(have: {sorted(entries)})", file=sys.stderr,
            )
            return 2
        traced = costmodel.trace_entry(entries[args.cost_table])
        print(costmodel.attribution_table(traced))
        return 0

    if args.mem_table:
        from cpgisland_tpu.analysis import mem_contracts, memmodel

        known = set(memmodel.kernels()) | set(mem_contracts.shipped_knobs())
        if args.mem_table not in known:
            print(
                f"error: unknown kernel {args.mem_table!r} "
                f"(have: {sorted(known)})", file=sys.stderr,
            )
            return 2
        print(mem_contracts.mem_table(args.mem_table))
        return 0

    if not args.no_lint:
        paths = args.paths or _default_paths()
        missing = [p for p in paths if not os.path.exists(p)]
        if missing:
            print(f"error: no such path(s): {missing}", file=sys.stderr)
            return 2
        rule_names = (
            [r.strip() for r in args.rules.split(",") if r.strip()]
            if args.rules else None
        )
        try:
            result = core.run_lint(paths, rule_names=rule_names)
        except ValueError as e:
            print(f"error: {e}", file=sys.stderr)
            return 2
        shown = result.findings if args.show_waived else result.unwaived
        stale = result.unused_waivers
        if args.as_json:
            payload["findings"] = [f.as_dict() for f in result.findings]
            payload["files_checked"] = result.files_checked
            payload["unused_waivers"] = [
                {"path": rel, "line": w.line, "rules": list(w.rules),
                 "reason": w.reason}
                for rel, w in stale
            ]
        else:
            for f in shown:
                print(f.format())
            for rel, w in stale:
                line = (
                    f"{rel}:{w.line}:1: [waiver-unused] waiver for "
                    f"{','.join(w.rules)} covers no finding"
                )
                # Advisory note by default; a first-class violation line
                # under --strict-waivers.
                print(line if args.strict_waivers else f"note: {line}",
                      file=sys.stdout if args.strict_waivers else sys.stderr)
        ok = result.ok and not (args.strict_waivers and stale)
        if not args.as_json:
            print(
                f"graftcheck: {result.files_checked} file(s), "
                f"{len(result.unwaived)} violation(s), "
                f"{len(result.waived)} waived",
                file=sys.stderr,
            )
        if not ok:
            rc = 1

    if args.sync:
        from cpgisland_tpu.analysis import synccheck

        report = synccheck.run_sync(args.paths or None)
        if args.as_json:
            payload["sync"] = report.summary()
        else:
            for f in report.findings:
                print(f.format())
            uniq = {(e.src, e.dst) for e in report.edges}
            print(
                f"graftsync: {report.files_checked} file(s), "
                f"{len(report.locks)} lock(s), {len(uniq)} order edge(s), "
                f"{len(report.findings)} violation(s)",
                file=sys.stderr,
            )
        if not report.ok:
            rc = 1

    if args.contracts:
        _pin_platform(args.platform)
        from cpgisland_tpu.analysis import contracts

        results = contracts.run_contracts(execute=not args.no_exec)
        bad = [r for r in results if not r.ok]
        if args.as_json:
            payload["contracts"] = [r.as_dict() for r in results]
        else:
            for r in results:
                status = "ok" if r.ok else "VIOLATION"
                print(f"contract {r.name}: {status}", file=sys.stderr)
                for v in r.violations:
                    print(f"    {v}")
        if not args.as_json:
            print(
                f"graftcheck contracts: {len(results)} entry point(s), "
                f"{len(bad)} violating",
                file=sys.stderr,
            )
        if bad:
            rc = 1

    if args.costs or args.update_costs:
        _pin_platform(args.platform)
        from cpgisland_tpu.analysis import cost_contracts

        report = cost_contracts.run_cost_pass(
            lockfile_path=args.costs_file, update=args.update_costs
        )
        if args.as_json:
            payload["costs"] = report
        else:
            if report["updated"]:
                summary = report.get("summary") or ["(no changes)"]
                print(f"costs: re-baselined {report['path']}", file=sys.stderr)
                for line in summary:
                    print(f"    {line}", file=sys.stderr)
            for v in report["diff"]["violations"]:
                print(f"cost drift: {v}")
            for n in report["diff"]["notes"]:
                print(f"note: {n}", file=sys.stderr)
            for r in report["contracts"]:
                status = "ok" if r["ok"] else "VIOLATION"
                print(f"cost contract {r['name']}: {status}", file=sys.stderr)
                for v in r["violations"]:
                    print(f"    {v}")
            print(
                f"graftcost: {report['diff']['checked']} entry point(s) "
                f"diffed, {len(report['contracts'])} cost contract(s), "
                f"{'ok' if report['ok'] else 'VIOLATIONS'}",
                file=sys.stderr,
            )
        if not report["ok"]:
            rc = 1

    if args.mem or args.update_mem:
        _pin_platform(args.platform)
        from cpgisland_tpu.analysis import mem_contracts

        report = mem_contracts.run_mem_pass(
            lockfile_path=args.mem_file, update=args.update_mem
        )
        if args.as_json:
            payload["mem"] = report
        else:
            if report["updated"]:
                summary = report.get("summary") or ["(no changes)"]
                print(f"mem: re-baselined {report['path']}", file=sys.stderr)
                for line in summary:
                    print(f"    {line}", file=sys.stderr)
            for v in report["diff"]["violations"]:
                print(f"mem drift: {v}")
            for n in report["diff"]["notes"]:
                print(f"note: {n}", file=sys.stderr)
            for r in report["contracts"]:
                status = "ok" if r["ok"] else "VIOLATION"
                print(f"mem contract {r['name']}: {status}", file=sys.stderr)
                for v in r["violations"]:
                    print(f"    {v}")
            print(
                f"graftmem: {report['diff']['checked']} entry point(s) + "
                f"{report['diff']['kernels_checked']} kernel row(s) "
                f"diffed, {len(report['contracts'])} mem contract(s), "
                f"{'ok' if report['ok'] else 'VIOLATIONS'}",
                file=sys.stderr,
            )
        if not report["ok"]:
            rc = 1

    if args.scale or args.update_scale:
        _pin_platform(args.platform)
        from cpgisland_tpu.analysis import scale_contracts

        report = scale_contracts.run_scale_pass(
            lockfile_path=args.scale_file, update=args.update_scale
        )
        if args.as_json:
            payload["scale"] = report
        else:
            if report["updated"]:
                summary = report.get("summary") or ["(no changes)"]
                print(f"scale: re-baselined {report['path']}",
                      file=sys.stderr)
                for line in summary:
                    print(f"    {line}", file=sys.stderr)
            for v in report["violations"]:
                print(f"scale violation: {v}")
            for v in report["diff"]["violations"]:
                print(f"scale drift: {v}")
            for n in report["diff"]["notes"]:
                print(f"note: {n}", file=sys.stderr)
            print(
                f"graftscale: {report['diff']['checked']} entry point(s) "
                f"diffed, {len(report['diff']['stale'])} stale, "
                f"{'ok' if report['ok'] else 'VIOLATIONS'}",
                file=sys.stderr,
            )
        if not report["ok"]:
            rc = 1

    if args.tune:
        _pin_platform(args.platform)
        from cpgisland_tpu.tune import table as tune_table

        report = tune_table.table_report(path=args.tune_file)
        if args.as_json:
            payload["tune"] = report
        else:
            for row in report["stale_entries"]:
                # Advisory, the stale-waiver UX: a stale winner means the
                # router already fell back to the hard-coded default —
                # the self-invalidation IS the feature, the note is the
                # re-sweep reminder.
                print(
                    f"note: tune stale: {row['key']}: {row['reason']}",
                    file=sys.stderr,
                )
            if "note" in report:
                print(f"note: {report['note']}", file=sys.stderr)
            print(
                f"graftune: {report['entries']} winner(s) for "
                f"'{report['platform']}' — {report['fresh']} fresh, "
                f"{report['stale']} stale ({report['path']})",
                file=sys.stderr,
            )

    if args.as_json:
        payload["ok"] = rc == 0
        print(json.dumps(payload))
    return rc


def _pin_platform(platform: str) -> None:
    if platform != "auto":
        # Pin via jax.config BEFORE backend init: this dev box's site
        # plugin ignores the JAX_PLATFORMS env var (CLAUDE.md).
        import jax

        jax.config.update("jax_platforms", platform)


if __name__ == "__main__":
    sys.exit(main())
