"""graftcheck Layer 5 — memory contracts + the MEMORY.json lockfile.

Built on :mod:`~cpgisland_tpu.analysis.memmodel`.  Two halves, the
COSTS.json workflow verbatim (``analysis/cost_contracts.py``):

**The lockfile** (``MEMORY.json``, committed): per contract-registry
entry, the HBM liveness fingerprint (peak live bytes at >=2 geometries,
per-symbol/fixed fits, materialized-allocation totals, the named O(T)
allocation groups, fused-EM while-body peak) plus the modeled VMEM
footprint of every registered kernel at its SHIPPED knobs — captured per
platform with per-metric tolerances.  ``python -m cpgisland_tpu.analysis
--mem`` re-traces/re-models and diffs; a drift fails CI NAMING the
drifting buffers (the allocation-group diff / the kernel buffer
breakdown), so "a whole-record temp re-entered the island reduction" or
"a stacked kernel quietly grew a per-member slab" is a red build on CPU
in seconds instead of a device OOM minutes into a relay-TPU run.
``--update-mem`` re-baselines after a verified change; stale entries are
reported like stale waivers.

**The quantitative contracts** — memory assertions the cost layer cannot
express:

- ``mem.vmem-budget`` — every registered kernel at its shipped knobs
  (including the stacked M=3 launches) fits the 16 MiB v5e VMEM model
  with the stated reserve headroom; violations name the offending
  buffers.
- ``mem.no-linear-temps`` — the blocked island reduction materializes NO
  allocation group scaling O(T) (the r4 whole-record formulation OOMed
  ~15 GB of s32[T] temps), and the fused-EM while-body peak stays within
  its per-symbol stream budget.
- ``mem.seq-shard-budget`` — the 112 Mi whole-sequence shard budget and
  the 128 Mi remote-compile failure BOTH fall out of the HBM model for a
  16 GB chip, and train.backends.SEQ_SHARD_BUDGET equals the derived
  cap.
- ``mem.stacked-envelope`` — the max feasible member count M per stacked
  kernel family at current knobs matches the pinned envelope (PR 12's
  kernels scale VMEM with M; the envelope is the static guard).

The liveness fingerprints trace on the current backend (CPU XLA twins in
CI — identical arithmetic to the chip kernels); the closed-form VMEM
contracts are platform-independent arithmetic and run everywhere,
including bench.py's on-TPU parity phase.
"""

from __future__ import annotations

import dataclasses
import json
import os
from typing import Optional

from cpgisland_tpu.analysis import memmodel
from cpgisland_tpu.analysis.costmodel import fit_linear
from cpgisland_tpu.analysis.contracts import Contract, ContractResult

LOCKFILE_VERSION = 1
LOCKFILE_NAME = "MEMORY.json"

# Allocation-group slope (bytes/symbol) above which a group counts as an
# O(T) temporary.  2.0 sits above the island path's one legitimate
# linear allocation (the 1 B/sym int8 pad-concatenate of its own input)
# and below the OOM class it exists to catch (a whole-record s32 temp is
# >= 4 B/sym; the r4 formulation paid ~40 — memmodel.ISLAND_BLOCK_BPS).
LINEAR_TEMP_BPS = 2.0

# Fused-EM while-body peak-live ceiling, bytes per symbol.  Measured on
# the CPU twin trace: ~246 B/sym (the one-pass chunked reduced E-step
# holds pair streams + both 2-component chains + scattered stat
# workspaces live at once).  The pin carries ~1.5x headroom — a dense
# xi re-pairing (K^2 rows, +hundreds of B/sym) or a de-blocked temp
# trips it; model-sized drift is the lockfile's job.
EM_BODY_BPS_MAX = 384.0

# The pinned stacked envelope: max feasible members per stacked kernel
# family at the shipped knobs (decode families at the M=3 block cap the
# flat-decode guard enforces; fb families at the 512x256 lane tiles).
# M=3 — the shipped stacked3 contracts' geometry — must be feasible for
# every family; fb.fwdbwd sits EXACTLY at its envelope, which is the
# re-sweep obligation BASELINE.md records against PR 12.
STACKED_ENVELOPE = {
    "decode.products.onehot": 64,          # search ceiling — not binding
    "decode.backpointers.onehot": 22,
    "decode.backpointers.onehot.scores": 2,   # at bk=4096
    "decode.backtrace.onehot": 2,             # at bk=4096
    "fb.fwdbwd.onehot": 3,
    "fb.stats.onehot": 6,
}
_STACKED_SEARCH_CEILING = 64

_QUANT_RULES = (
    ("mem.lockfile", "live HBM-liveness fingerprints and shipped-knob "
     "VMEM footprints match MEMORY.json within tolerances; drifts name "
     "the drifting buffers/groups"),
    ("mem.vmem-budget", "every registered kernel at its shipped knobs "
     "(incl. stacked M=3) fits the 16 MiB v5e VMEM model with the "
     "stated reserve"),
    ("mem.no-linear-temps", "the blocked island reduction materializes "
     "no O(T) allocation group; the fused-EM while-body peak stays "
     f"under {EM_BODY_BPS_MAX:.0f} B/symbol"),
    ("mem.seq-shard-budget", "the 112 Mi whole-seq shard budget and the "
     "128 Mi failure both fall out of the HBM model; SEQ_SHARD_BUDGET "
     "== the derived cap"),
    ("mem.stacked-envelope", "max feasible stacked member count per "
     "kernel family matches the pinned envelope (M=3 feasible "
     "everywhere)"),
)


def quantitative_rules() -> list:
    return list(_QUANT_RULES)


DEFAULT_TOLERANCES = {
    # Relative, on peak/alloc fits and raw per-geometry totals.  Tight for
    # the same reason as COSTS.json: a trace is deterministic, drift means
    # the GRAPH changed — re-baseline with --update-mem after verifying.
    "peak_bytes": 0.02,
    "alloc_bytes": 0.02,
    "while_body_peak": 0.02,
    # The kernel VMEM section is closed-form arithmetic: exact.
    "kernel_vmem": 0,
    # O(T) allocation groups: the NAME set must match exactly, and each
    # surviving group's recorded slope (3-decimal-rounded B/sym in the
    # fingerprint) is compared at this relative tolerance (0 = exact).
    "linear_groups": 0,
}


def default_lockfile_path() -> str:
    pkg = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    return os.path.join(os.path.dirname(pkg), LOCKFILE_NAME)


# -- the registry ------------------------------------------------------------


def _islands_entry() -> Contract:
    """The blocked on-device island-calling reduction — the entry whose
    whole-record ancestor OOMed ~15 GB of s32[T] temps (CLAUDE.md r4).
    Block width is pinned SMALL relative to the traced geometries so an
    O(T) temp cannot hide inside 'one block'."""

    def make(scale: int = 1):
        import numpy as np

        import jax.numpy as jnp

        from cpgisland_tpu.ops import islands_device

        T = 32768 * scale
        rng = np.random.default_rng(0)
        path = jnp.asarray(
            rng.integers(0, 8, size=T).astype(np.int8)
        )

        def fn(p):
            return islands_device._device_calls(
                p, cap=256, min_len=200, gc_threshold=0.5,
                oe_threshold=0.6, block_w=4096,
            )

        return fn, (path,), None

    return Contract(
        name="islands.device.blocked", make=make, base_symbols=32768,
        cost_scales=(1, 2),
    )


def mem_entries() -> list:
    """The liveness registry: every Layer-2/3 contract entry (same cast,
    same geometries — the graftcost methodology) + the fused-EM loop +
    the blocked island reduction."""
    from cpgisland_tpu.analysis.cost_contracts import cost_entries

    return cost_entries() + [_islands_entry()]


# Shipped knob tuples per registered kernel — what mem.vmem-budget checks
# and what the MEMORY.json `kernels` section pins.  Decode kernels run the
# flat default bk=4096 x 128 lanes; the fb lane kernels run DEFAULT_T_TILE
# =512 x the 256-lane fast tile (fb_pallas._fb_lane_tile); the stacked
# @M3 rows run the M=3 block cap the flat-decode guard enforces.
def shipped_knobs() -> dict:
    fb = memmodel.Knobs(lane_tile=256)
    bk3 = memmodel.stacked_block_cap(3, scores=True)
    out = {}
    for name in memmodel.kernels():
        if name.startswith(("fb.", "assembly.")):
            out[name] = fb
        else:
            out[name] = memmodel.Knobs()
    out["assembly.seqstats.onehot"] = fb.replace(lane_T=65536)
    for name in memmodel.STACKED_KERNELS:
        base = fb if name.startswith("fb.") else memmodel.Knobs(
            block_size=bk3
        )
        out[name + "@M3"] = base.replace(stacked_m=3)
    return out


def _kernel_for(name: str) -> str:
    return name.split("@", 1)[0]


def kernel_fingerprints() -> dict:
    """{name: footprint dict} for every shipped-knob kernel row."""
    return {
        name: memmodel.footprint(_kernel_for(name), knobs).as_dict()
        for name, knobs in shipped_knobs().items()
    }


# -- liveness fingerprints ---------------------------------------------------


@dataclasses.dataclass
class MemEntry:
    """One registry entry traced at each geometry."""

    name: str
    geometries: list
    metrics: list              # memmodel.LiveMetrics per geometry

    def fits(self) -> dict:
        pts = list(zip(self.geometries, self.metrics))
        return {
            "peak_bytes": fit_linear([(n, m.peak_bytes) for n, m in pts]),
            "alloc_bytes": fit_linear(
                [(n, m.alloc_bytes) for n, m in pts]
            ),
            "while_body_peak": fit_linear(
                [(n, m.while_body_peak) for n, m in pts]
            ),
        }

    def linear_groups(self) -> list:
        if len(self.metrics) < 2:
            return []
        return memmodel.linear_alloc_groups(
            self.metrics[0], self.metrics[-1],
            self.geometries[0], self.geometries[-1],
            min_bps=LINEAR_TEMP_BPS,
        )


def trace_mem_entry(contract) -> MemEntry:
    import jax

    # Source-group attribution must not depend on what THIS PROCESS traced
    # earlier: a jit-cache hit reuses a jaxpr whose source frames point at
    # the ORIGINAL trace site, so a shared helper first traced under a
    # different entry would smear that entry's groups into this one.  A
    # fresh trace cache per entry makes the fingerprint a function of the
    # entry alone (the same reason tests/conftest.py clears caches per
    # module).
    jax.clear_caches()
    scales = getattr(contract, "cost_scales", (1, 2))
    if not getattr(contract, "scalable", True):
        scales = (1,)
    geometries, metrics = [], []
    for s in scales:
        fn, args, *_rest = contract.make(s)
        closed = jax.make_jaxpr(fn)(*args)
        geometries.append(max(contract.base_symbols, 1) * s)
        metrics.append(memmodel.live_metrics(closed))
    return MemEntry(name=contract.name, geometries=geometries,
                    metrics=metrics)


def trace_mem_all() -> dict:
    return {c.name: trace_mem_entry(c) for c in mem_entries()}


def fingerprint(entry: MemEntry) -> dict:
    return {
        "geometries": list(entry.geometries),
        "metrics": [
            {k: v for k, v in m.as_dict().items() if k != "groups"}
            for m in entry.metrics
        ],
        "fits": {k: f.as_dict() for k, f in entry.fits().items()},
        "linear_groups": [
            [g, round(bps, 3)] for g, bps in entry.linear_groups()
        ],
    }


def live_fingerprints(traced: Optional[dict] = None) -> dict:
    if traced is None:
        traced = trace_mem_all()
    return {name: fingerprint(e) for name, e in traced.items()}


# -- the lockfile ------------------------------------------------------------


def load_lockfile(path: Optional[str] = None) -> Optional[dict]:
    path = path or default_lockfile_path()
    if not os.path.exists(path):
        return None
    with open(path, "r", encoding="utf-8") as fh:
        return json.load(fh)


def write_lockfile(
    fingerprints: dict, path: Optional[str] = None,
    platform: Optional[str] = None, kernels: Optional[dict] = None,
) -> str:
    import jax

    path = path or default_lockfile_path()
    platform = platform or jax.default_backend()
    data = load_lockfile(path) or {
        "version": LOCKFILE_VERSION,
        "tolerances": dict(DEFAULT_TOLERANCES),
        "platforms": {},
    }
    data["platforms"][platform] = {
        "jax": jax.__version__,
        "entries": fingerprints,
        "kernels": kernels if kernels is not None else kernel_fingerprints(),
    }
    tmp = path + ".tmp"
    with open(tmp, "w", encoding="utf-8") as fh:
        json.dump(data, fh, indent=1, sort_keys=True)
        fh.write("\n")
    os.replace(tmp, path)
    return path


@dataclasses.dataclass
class MemDiff:
    violations: list
    notes: list
    stale: list
    checked: int = 0           # liveness registry entries diffed
    kernels_checked: int = 0   # shipped-knob kernel VMEM rows diffed

    @property
    def ok(self) -> bool:
        return not self.violations

    def as_dict(self) -> dict:
        return dataclasses.asdict(self) | {"ok": self.ok}


def _rel_drift(live: float, locked: float) -> float:
    denom = max(abs(locked), 1.0)
    return abs(live - locked) / denom


def _buffer_drift(live_k: dict, locked_k: dict) -> str:
    """The 'named drifting buffers' of one kernel row."""
    lb, kb = live_k.get("buffers", {}), locked_k.get("buffers", {})
    deltas = []
    for b in sorted(set(lb) | set(kb)):
        a, c = kb.get(b, 0), lb.get(b, 0)
        if a != c:
            deltas.append(f"{b} {a}->{c}B")
    return ", ".join(deltas[:6]) if deltas else "(buffers unchanged)"


def diff_mem(live: dict, lock: Optional[dict], platform: str,
             kernels: Optional[dict] = None) -> MemDiff:
    """Diff live fingerprints (+ shipped-knob kernel footprints) against
    the lockfile's platform section."""
    diff = MemDiff(violations=[], notes=[], stale=[])
    if lock is None:
        diff.violations.append(
            f"no {LOCKFILE_NAME} lockfile — run --update-mem to baseline"
        )
        return diff
    section = lock.get("platforms", {}).get(platform)
    if section is None:
        diff.notes.append(
            f"lockfile has no '{platform}' section (captured platforms: "
            f"{sorted(lock.get('platforms', {}))}) — mem diff skipped; "
            "run --update-mem on this platform to baseline it"
        )
        return diff
    tol = {**DEFAULT_TOLERANCES, **lock.get("tolerances", {})}
    locked_entries = section.get("entries", {})
    diff.stale = sorted(set(locked_entries) - set(live))
    for name in diff.stale:
        diff.notes.append(
            f"stale lockfile entry '{name}': no longer in the mem "
            "registry (remove via --update-mem)"
        )
    for name in sorted(live):
        if name not in locked_entries:
            diff.violations.append(
                f"{name}: not in the lockfile — new entries must be "
                "baselined via --update-mem"
            )
            continue
        diff.checked += 1
        lv, lk = live[name], locked_entries[name]
        if lv["geometries"] != lk["geometries"]:
            diff.violations.append(
                f"{name}: traced geometries {lv['geometries']} != "
                f"lockfile {lk['geometries']} (--update-mem)"
            )
            continue
        lg_l = dict((g, b) for g, b in lv["linear_groups"])
        lg_k = dict((g, b) for g, b in lk["linear_groups"])
        if set(lg_l) != set(lg_k):
            grew = sorted(set(lg_l) - set(lg_k))
            gone = sorted(set(lg_k) - set(lg_l))
            diff.violations.append(
                f"{name}: O(T) allocation groups drifted — new: "
                f"{grew or '[]'}, vanished: {gone or '[]'} (a temporary "
                "whose live size scales with T entered or left this "
                "entry)"
            )
        for g in sorted(set(lg_l) & set(lg_k)):
            if _rel_drift(lg_l[g], lg_k[g]) > tol["linear_groups"]:
                diff.violations.append(
                    f"{name}: O(T) group {g} slope {lg_k[g]:.3f} -> "
                    f"{lg_l[g]:.3f} B/symbol (> tol "
                    f"{tol['linear_groups']:.0%}) — the temporary's "
                    "per-symbol footprint changed"
                )
        for metric in ("peak_bytes", "alloc_bytes", "while_body_peak"):
            for term in ("per_symbol", "fixed"):
                a = lk["fits"][metric][term]
                b = lv["fits"][metric][term]
                d = _rel_drift(b, a)
                if d > tol[metric]:
                    diff.violations.append(
                        f"{name}: {metric}.{term} {a:.6g} -> {b:.6g} "
                        f"({d:+.1%} > tol {tol[metric]:.0%})"
                    )
    _diff_kernel_section(diff, kernels, section, tol)
    return diff


def _diff_kernel_section(diff: MemDiff, kernels: Optional[dict],
                         section: dict, tol: dict) -> None:
    """Diff the shipped-knob kernel VMEM rows (closed-form arithmetic —
    runs on any platform, including the trace-free on-TPU parity mode)."""
    live_k = kernels if kernels is not None else kernel_fingerprints()
    locked_k = section.get("kernels", {})
    for name in sorted(set(live_k) - set(locked_k)):
        diff.violations.append(
            f"kernel {name}: not in the lockfile — baseline via "
            "--update-mem"
        )
    for name in sorted(set(locked_k) - set(live_k)):
        diff.notes.append(
            f"stale lockfile kernel '{name}' (remove via --update-mem)"
        )
        diff.stale.append(f"kernel:{name}")
    for name in sorted(set(live_k) & set(locked_k)):
        diff.kernels_checked += 1
        if abs(live_k[name]["total"] - locked_k[name]["total"]) > \
                tol["kernel_vmem"]:
            diff.violations.append(
                f"kernel {name}: modeled VMEM {locked_k[name]['total']} "
                f"-> {live_k[name]['total']} B; drifting buffers: "
                f"{_buffer_drift(live_k[name], locked_k[name])}"
            )


def diff_kernels_only(lock: Optional[dict], platform: str,
                      kernels: Optional[dict] = None) -> MemDiff:
    """The trace-free diff: only the kernel VMEM section, against any
    platform section that carries one (kernel rows are closed-form and
    platform-independent, so a cpu-captured section is authoritative on
    TPU too — bench's parity phase uses this)."""
    diff = MemDiff(violations=[], notes=["liveness traces skipped "
                                         "(kernel-section diff only)"],
                   stale=[])
    if lock is None:
        diff.violations.append(
            f"no {LOCKFILE_NAME} lockfile — run --update-mem to baseline"
        )
        return diff
    platforms = lock.get("platforms", {})
    section = platforms.get(platform)
    if section is None and platforms:
        # Fall back to any captured section: the kernel rows don't trace.
        fallback = sorted(platforms)[0]
        section = platforms[fallback]
        diff.notes.append(
            f"no '{platform}' section; kernel rows diffed against "
            f"'{fallback}' (closed-form — platform-independent)"
        )
    if section is None:
        diff.notes.append(
            "lockfile has no captured platform sections — kernel diff "
            "skipped; run --update-mem to baseline"
        )
        return diff
    tol = {**DEFAULT_TOLERANCES, **lock.get("tolerances", {})}
    _diff_kernel_section(diff, kernels, section, tol)
    return diff


def update_summary(live: dict, lock: Optional[dict], platform: str) -> list:
    out = []
    old = ((lock or {}).get("platforms", {}).get(platform, {})
           .get("entries", {}))
    for name in sorted(set(live) | set(old)):
        if name not in old:
            out.append(f"+ {name} (new entry)")
        elif name not in live:
            out.append(f"- {name} (stale entry removed)")
        elif old[name] != live[name]:
            a = old[name]["fits"]["peak_bytes"]
            b = live[name]["fits"]["peak_bytes"]
            out.append(
                f"~ {name}: peak B/sym {a['per_symbol']:.4g} -> "
                f"{b['per_symbol']:.4g}, fixed {a['fixed']:.4g} -> "
                f"{b['fixed']:.4g}"
            )
    return out


# -- the quantitative contracts ----------------------------------------------


def _vmem_budget_contract(kernels: Optional[dict] = None) -> ContractResult:
    violations, notes = [], {}
    rows = kernels if kernels is not None else kernel_fingerprints()
    knobs = shipped_knobs()
    worst = None
    for name in sorted(rows):
        f = memmodel.feasible(_kernel_for(name), knobs[name])
        if not f.ok:
            violations.append(f.reason)
        head = 1.0 - f.total / f.limit
        if worst is None or head < worst[1]:
            worst = (name, head)
    notes["kernels_checked"] = len(rows)
    if worst is not None:
        notes["tightest"] = {
            "kernel": worst[0], "headroom": round(worst[1], 4),
        }
    notes["vmem_limit"] = memmodel.vmem_limit()
    return ContractResult(
        name="mem.vmem-budget", ok=not violations, violations=violations,
        notes=notes,
    )


def _linear_temps_contract(traced: dict) -> ContractResult:
    violations, notes = [], {}
    isl = traced.get("islands.device.blocked")
    if isl is None:
        violations.append(
            "islands.device.blocked missing from the mem registry"
        )
    else:
        bad = isl.linear_groups()
        notes["island_linear_groups"] = [
            [g, round(b, 2)] for g, b in bad
        ]
        for g, bps in bad[:4]:
            violations.append(
                f"islands.device.blocked: allocation group {g} grows "
                f"{bps:.1f} B/symbol — an O(T) temporary in the BLOCKED "
                "island reduction (the whole-record formulation's ~15 GB "
                "s32[T] OOM class; temps must be O(block_w))"
            )
    em = traced.get("em.fused")
    if em is None:
        violations.append("em.fused missing from the mem registry")
    elif len(em.geometries) >= 2:
        slope = em.fits()["while_body_peak"].per_symbol
        notes["em_body_peak_bps"] = round(slope, 3)
        if slope > EM_BODY_BPS_MAX:
            top = memmodel.linear_alloc_groups(
                em.metrics[0], em.metrics[-1],
                em.geometries[0], em.geometries[-1], min_bps=4.0,
            )[:4]
            violations.append(
                f"em.fused: while-body peak live grows {slope:.1f} "
                f"B/symbol > {EM_BODY_BPS_MAX:.0f} — the fused EM "
                "iteration's working set outgrew its stream budget; "
                "fattest O(T) groups: "
                + ", ".join(f"{g}({b:.0f}B/sym)" for g, b in top)
            )
    return ContractResult(
        name="mem.no-linear-temps", ok=not violations,
        violations=violations, notes=notes,
    )


def _seq_shard_contract() -> ContractResult:
    from cpgisland_tpu.train import backends

    violations, notes = [], {}
    derived = memmodel.max_seq_shard()
    notes["derived_cap_symbols"] = derived
    notes["bytes_per_symbol"] = memmodel.seq_shard_bytes_per_symbol()
    notes["streams"] = dict(memmodel.SEQ_STREAM_BYTES)
    if backends.SEQ_SHARD_BUDGET != derived:
        violations.append(
            f"SEQ_SHARD_BUDGET {backends.SEQ_SHARD_BUDGET} != the model's "
            f"derived cap {derived} — the budget and the model diverged "
            "(retune memmodel.SEQ_STREAM_BYTES or re-measure the budget)"
        )
    if memmodel.seq_shard_bytes(112 << 20) > memmodel.hbm_limit():
        violations.append(
            "the model rejects the measured-good 112 Mi shard"
        )
    if memmodel.seq_shard_bytes(128 << 20) <= memmodel.hbm_limit():
        violations.append(
            "the model admits the measured-failing 128 Mi shard"
        )
    return ContractResult(
        name="mem.seq-shard-budget", ok=not violations,
        violations=violations, notes=notes,
    )


def _stacked_envelope_contract() -> ContractResult:
    violations, notes = [], {}
    knobs = shipped_knobs()
    for kernel, pinned in STACKED_ENVELOPE.items():
        # The envelope pins M at the CURRENT shipped knobs (decode at the
        # flat default bk=4096; fb at the 512x256 lane tiles) — the @M3
        # rows' reduced block is the guard's consequence, not the pin.
        base = knobs[kernel]
        got = min(
            memmodel.max_stacked_m(kernel, base), _STACKED_SEARCH_CEILING
        )
        notes[kernel] = got
        if got != pinned:
            violations.append(
                f"{kernel}: max feasible stacked M is {got}, pinned "
                f"envelope is {pinned} — a per-member VMEM slab grew or "
                "shrank (update STACKED_ENVELOPE only after verifying, "
                "and re-sweep the stacked knobs at the next capture)"
            )
        if not memmodel.feasible(kernel, knobs[kernel + "@M3"]).ok:
            violations.append(
                f"{kernel}: the shipped stacked M=3 geometry (the "
                "stacked-block-cap guard's knobs) no longer fits the "
                "VMEM model"
            )
    return ContractResult(
        name="mem.stacked-envelope", ok=not violations,
        violations=violations, notes=notes,
    )


def run_mem_contracts(traced: Optional[dict] = None) -> list:
    if traced is None:
        traced = trace_mem_all()
    return [
        _vmem_budget_contract(),
        _linear_temps_contract(traced),
        _seq_shard_contract(),
        _stacked_envelope_contract(),
    ]


# -- the full pass (CLI / CI / bench / driver entry) -------------------------


def run_mem_pass(
    lockfile_path: Optional[str] = None, update: bool = False,
    trace: bool = True,
) -> dict:
    """Model, trace, diff against the lockfile, run the contracts.

    Returns {"ok", "diff", "contracts", "updated", "summary"} — the same
    shape as cost_contracts.run_cost_pass, consumed by the CLI,
    ci_checks.sh, __graft_entry__ and bench.py.  ``trace=False`` skips
    the liveness traces (closed-form contracts + kernel-section diff
    only — the cheap on-TPU parity mode; the liveness fingerprints pin
    the CPU XLA-twin structure)."""
    import jax

    if update and not trace:
        raise ValueError(
            "run_mem_pass(update=True, trace=False) would baseline an "
            "EMPTY entries section, erasing this platform's liveness "
            "fingerprints — re-baselining requires the traces"
        )
    platform = jax.default_backend()
    kernels = kernel_fingerprints()
    traced = trace_mem_all() if trace else {}
    live = live_fingerprints(traced) if trace else {}
    lock = load_lockfile(lockfile_path)
    out: dict = {"platform": platform, "updated": False}
    if update:
        out["summary"] = update_summary(live, lock, platform)
        path = write_lockfile(live, lockfile_path, platform, kernels)
        out["updated"] = True
        out["path"] = path
        lock = load_lockfile(lockfile_path)
    if trace:
        diff = diff_mem(live, lock, platform, kernels)
        contracts = run_mem_contracts(traced)
    else:
        diff = diff_kernels_only(lock, platform, kernels)
        contracts = [
            _vmem_budget_contract(kernels),
            _seq_shard_contract(),
            _stacked_envelope_contract(),
        ]
    out["diff"] = diff.as_dict()
    out["contracts"] = [r.as_dict() for r in contracts]
    out["ok"] = diff.ok and all(r.ok for r in contracts)
    return out


def format_failure(report: dict) -> str:
    """One-line JSON summary of a failing run_mem_pass report (shared by
    the bench parity gate and __graft_entry__'s self-check)."""
    return json.dumps({
        "diff": report["diff"]["violations"],
        "contracts": {
            r["name"]: r["violations"]
            for r in report["contracts"] if not r["ok"]
        },
    })


def mem_table(kernel: str, knobs: Optional[memmodel.Knobs] = None) -> str:
    """Markdown buffer-breakdown table for one kernel (--mem-table)."""
    fp = memmodel.footprint(_kernel_for(kernel),
                            knobs or shipped_knobs().get(
                                kernel, memmodel.Knobs()))
    lines = [
        f"| buffer ({kernel}) | shape | kind | bytes (buffered) |",
        "|---|---|---|---|",
    ]
    for b in sorted(fp.buffers, key=lambda b: b.cost, reverse=True):
        shape = "x".join(str(d) for d in b.shape)
        lines.append(f"| `{b.name}` | {shape} | {b.kind} | {b.cost} |")
    lines.append(
        f"| **total** | | | {fp.total} / limit {memmodel.vmem_limit()} "
        f"(headroom {fp.headroom():.1%}) |"
    )
    return "\n".join(lines)
