"""graftcheck Layer 5 — the static memory model (graftmem).

Layer 3 (graftcost) measures what a traced graph *costs*; this layer
models what it *allocates*.  Two halves, both deliberately approximate
but deterministic and stable — fingerprints, not a profiler:

**The per-kernel VMEM footprint model.**  Every Pallas kernel family in
ops/ is registered here as a buffer-list builder parameterized over the
knob tuple (:class:`Knobs`: lane_T, t_tile, lane_tile, block_size, S, K,
stacked M) — block-spec tiles x dtype bytes, VMEM scratch, fori-carried
chain state, and the lane-broadcast tables, with HBM-streamed blocks
paying a x2 double-buffering factor (the Mosaic pipeline keeps the next
grid block in flight).  The same builders serve two callers: the CI
contracts check the SHIPPED knobs fit the 16 MiB v5e VMEM model with
headroom, and the knob autotuner (ROADMAP#1) calls :func:`feasible` to
prune a sweep before paying a relay-TPU compile.  This repo's history is
the motivation: every one of these cliffs was found empirically on chip
— the exact-EM assembly failing to compile at 131072 lanes, ``bk >=
8192`` failing scoped-VMEM compile on the batched decode route, the
whole-record island formulation OOMing ~15 GB of ``s32[T]`` temps, and
PR 12's stacked kernels scaling VMEM with member count M with no static
guard.

**The jaxpr HBM liveness pass.**  :func:`peak_live_bytes` runs an
interval analysis over a traced graph's equations (a var is live from
its defining eqn to its last use; loop bodies contribute their own inner
peak on top of the carried state) giving peak live bytes, and
:func:`alloc_groups` attributes materialized allocations to their
``file:function`` source groups so a diff can NAME the buffer that grew.
Run at two abstract geometries (the graftcost methodology) the per-group
allocation slopes expose the island-OOM bug class statically: a
temporary whose live size scales O(T) where the blocked formulation
keeps it O(T/blocks).

Calibration honesty: the hardware constants (16 MiB VMEM, a 10%
compiler-slack reserve, 16 GiB HBM with a 2 GiB runtime reserve) and the
per-symbol stream table of the whole-sequence E-step are MODELS fitted
to the measured cliffs recorded in CLAUDE.md/BASELINE.md, not ab-initio
derivations; tests/test_graftmem.py pins each predicted limit to bracket
its measured counterpart, and a documented discrepancy there is a pinned
note, not a silent pass.

No jax at module level: the closed-form half is pure arithmetic (ops
routing consults it at import time); the liveness half imports jax
lazily inside functions.
"""

from __future__ import annotations

import dataclasses
from typing import Optional

# -- the hardware model ------------------------------------------------------

# v5e per-core VMEM.  The model budgets against vmem_limit(): a reserve
# slice is held back for compiler spills, semaphores and metadata — a
# kernel modeled within a few percent of the full 16 MiB fails on chip in
# practice (the bk>=8192 scoped-VMEM class).
VMEM_BYTES = 16 << 20
VMEM_RESERVE = 0.10

# v5e chip HBM and the runtime/program/weights reserve the whole-sequence
# shard model budgets against (CLAUDE.md: 120 Mi compiled, 128 Mi failed).
HBM_BYTES = 16 << 30
HBM_RESERVE = 2 << 30

LANE_TILE = 128
ROW_TILE = 8
GROUP = 2          # reduced chain components (the r4 one-hot reduction)
# HBM<->VMEM pipelining factor on RESULT streams: a written block pays a
# write+drain revision buffer while the next block fills; input streams
# prefetch in place.  CALIBRATED: the one assignment consistent with both
# the shipped configs (dense stats at t_tile=512 x 256 lanes compiles; 512
# lanes "blows VMEM" — fb_pallas._fb_lane_tile) and the measured cliffs
# (bk>=8192 scoped-VMEM failure, the 131072-lane assembly compile failure).
DOUBLE = 2


# Remote compile ships program bytes over HTTP: a jit-baked 256 MiB
# constant = HTTP 413 at the relay (CLAUDE.md).  The budget holds a wide
# margin under the cliff — closures should carry tables, never data;
# big arrays are ARGUMENTS.
REMOTE_CONST_CLIFF = 256 << 20
REMOTE_CONST_MARGIN = 8


def vmem_limit() -> int:
    """The modeled per-kernel VMEM budget (16 MiB minus the reserve)."""
    return int(VMEM_BYTES * (1.0 - VMEM_RESERVE))


def remote_const_budget() -> int:
    """Max total baked-constant bytes a traced program may carry before
    the remote-compile HTTP 413 cliff is a risk (cliff / margin)."""
    return REMOTE_CONST_CLIFF // REMOTE_CONST_MARGIN


def hbm_limit() -> int:
    """The modeled per-chip HBM budget for one program's live streams."""
    return HBM_BYTES - HBM_RESERVE


# -- knobs -------------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class Knobs:
    """The tunable geometry tuple every footprint builder is a function of.

    Defaults are the shipped production settings (fb_pallas/viterbi_onehot
    module constants); the autotuner enumerates replacements via
    :meth:`replace`."""

    lane_T: int = 8192         # serial chain length per lane (pick_lane_T)
    t_tile: int = 512          # time-tile of the lane kernels' grid
    lane_tile: int = LANE_TILE  # lanes per kernel instance (128 or 256)
    block_size: int = 4096     # decode step-block bk (decode_batch_flat)
    n_states: int = 8          # K (flagship 8; dinuc member 32)
    n_symbols: int = 4         # S
    stacked_m: int = 1         # stacked members per launch (PR 12)
    itemsize: int = 4          # f32/i32 stream element

    def replace(self, **kw) -> "Knobs":
        return dataclasses.replace(self, **kw)


@dataclasses.dataclass(frozen=True)
class Buffer:
    """One modeled VMEM allocation of a kernel instance.

    kind: ``stream`` (HBM-read grid block, prefetched in place), ``out``
    (streamed result block — pays the x``DOUBLE`` write-pipelining
    factor), ``resident`` (lane-broadcast table / operand held for the
    whole launch) or ``scratch`` (pltpu.VMEM scratch + fori-carried chain
    state)."""

    name: str
    shape: tuple
    itemsize: int = 4
    kind: str = "stream"

    @property
    def nbytes(self) -> int:
        n = self.itemsize
        for d in self.shape:
            n *= int(d)
        return n

    @property
    def cost(self) -> int:
        if self.kind == "out":
            return self.nbytes * DOUBLE
        return self.nbytes

    def describe(self) -> str:
        shape = "x".join(str(d) for d in self.shape)
        return f"{self.name}[{shape}]({self.kind})={self.cost}B"


@dataclasses.dataclass
class Footprint:
    """A kernel's modeled VMEM working set at one knob tuple."""

    kernel: str
    knobs: Knobs
    buffers: list

    @property
    def total(self) -> int:
        return sum(b.cost for b in self.buffers)

    def headroom(self, limit: Optional[int] = None) -> float:
        limit = vmem_limit() if limit is None else limit
        return 1.0 - self.total / limit

    def top(self, n: int = 4) -> list:
        return sorted(self.buffers, key=lambda b: b.cost, reverse=True)[:n]

    def as_dict(self) -> dict:
        return {
            "total": self.total,
            "limit": vmem_limit(),
            "headroom": round(self.headroom(), 4),
            "buffers": {b.name: b.cost for b in self.buffers},
        }


@dataclasses.dataclass
class Feasibility:
    """:func:`feasible`'s verdict — the autotuner's pruning unit."""

    ok: bool
    kernel: str
    total: int
    limit: int
    offenders: list        # Buffer list, largest first, when not ok
    reason: str = ""

    def as_dict(self) -> dict:
        return {
            "ok": self.ok, "kernel": self.kernel, "total": self.total,
            "limit": self.limit, "reason": self.reason,
            "offenders": [b.describe() for b in self.offenders],
        }


# -- the kernel registry -----------------------------------------------------
#
# One builder per Pallas kernel family; each returns the instance's buffer
# list from the knob tuple, mirroring the pallas_call block specs in ops/
# (viterbi_pallas/viterbi_onehot: [bk, lane_tile] step-stream blocks over a
# lane-tile grid; fb_pallas/fb_onehot: [t_tile, K|GROUP, lane_tile] stream
# blocks over a (lane tile, t tile) grid).  nreal pair-table rows follow
# viterbi_onehot.prepare_pairs: S*S real pairs + S PAD carriers + identity.


def _pair_rows(S: int) -> int:
    return S * S + S + 1


def _k_decode_products_dense(k: Knobs) -> list:
    K, S = k.n_states, k.n_symbols
    return [
        Buffer("steps", (k.block_size, k.lane_tile)),
        Buffer("A", (K, K), kind="resident"),
        Buffer("emit", (K, S), kind="resident"),
        Buffer("P_out", (K * K, k.lane_tile), kind="out"),
        Buffer("C_carry", (K * K, k.lane_tile), kind="scratch"),
    ]


def _k_decode_backpointers_dense(k: Knobs) -> list:
    K, S = k.n_states, k.n_symbols
    return [
        Buffer("steps", (k.block_size, k.lane_tile)),
        Buffer("venter", (K, k.lane_tile), kind="resident"),
        Buffer("A", (K, K), kind="resident"),
        Buffer("emit", (K, S), kind="resident"),
        Buffer("bp_out", (k.block_size, k.lane_tile), kind="out"),
        Buffer("dexit_out", (K, k.lane_tile), kind="out"),
        Buffer("ftab_out", (1, k.lane_tile), kind="out"),
        Buffer("delta_carry", (K, k.lane_tile), kind="scratch"),
    ]


def _k_decode_backtrace_dense(k: Knobs) -> list:
    return [
        Buffer("bp", (k.block_size, k.lane_tile)),
        Buffer("exit", (1, k.lane_tile), kind="resident"),
        Buffer("path_out", (k.block_size, k.lane_tile), kind="out"),
    ]


def _k_decode_products_onehot(k: Knobs) -> list:
    M = k.stacked_m
    return [
        Buffer("pair", (k.block_size, k.lane_tile)),
        Buffer("tab", (4 * M * _pair_rows(k.n_symbols), k.lane_tile),
               kind="resident"),
        Buffer("C_out", (4 * M, k.lane_tile), kind="out"),
        Buffer("C_scr", (4 * M, k.lane_tile), kind="scratch"),
    ]


def _k_decode_backpointers_onehot(k: Knobs, scores: bool) -> list:
    M = k.stacked_m
    bufs = [
        Buffer("pair", (k.block_size, k.lane_tile)),
        Buffer("venter", (GROUP * M, k.lane_tile), kind="resident"),
        Buffer("tab", (4 * M * _pair_rows(k.n_symbols), k.lane_tile),
               kind="resident"),
        Buffer("bp_out", (M * k.block_size // ROW_TILE, k.lane_tile),
               kind="out"),
        Buffer("dexit_out", (GROUP * M, k.lane_tile), kind="out"),
        Buffer("ebits_out", (M, k.lane_tile), kind="out"),
        Buffer("chain_carry", (GROUP * M, k.lane_tile), kind="scratch"),
    ]
    if scores:
        bufs.append(
            Buffer("dmax_out", (M * k.block_size, k.lane_tile), kind="out")
        )
    return bufs


def _k_decode_backtrace_onehot(k: Knobs) -> list:
    M = k.stacked_m
    return [
        Buffer("bp", (M * k.block_size // ROW_TILE, k.lane_tile)),
        Buffer("pair", (k.block_size, k.lane_tile)),
        Buffer("idtab", (M * GROUP * _pair_rows(k.n_symbols), k.lane_tile),
               kind="resident"),
        Buffer("exit", (M, k.lane_tile), kind="resident"),
        Buffer("path_out", (M * k.block_size, k.lane_tile), kind="out"),
    ]


def _k_fb_fwd_dense(k: Knobs) -> list:
    K, S = k.n_states, k.n_symbols
    return [
        Buffer("steps", (k.t_tile, k.lane_tile)),
        Buffer("lens", (1, k.lane_tile), kind="resident"),
        Buffer("a0", (K, k.lane_tile), kind="resident"),
        Buffer("A", (K, K), kind="resident"),
        Buffer("emit", (K, S), kind="resident"),
        Buffer("alphas_out", (k.t_tile, K, k.lane_tile), kind="out"),
        Buffer("v_carry", (K, k.lane_tile), kind="scratch"),
    ]


def _k_fb_bwd_dense(k: Knobs) -> list:
    K, S = k.n_states, k.n_symbols
    return [
        Buffer("steps_next", (k.t_tile, k.lane_tile)),
        Buffer("cs_next", (k.t_tile, k.lane_tile)),
        Buffer("lens", (1, k.lane_tile), kind="resident"),
        Buffer("A", (K, K), kind="resident"),
        Buffer("emit", (K, S), kind="resident"),
        Buffer("beta0", (K, k.lane_tile), kind="resident"),
        Buffer("betas_out", (k.t_tile, K, k.lane_tile), kind="out"),
        Buffer("beta_carry", (K, k.lane_tile), kind="scratch"),
    ]


def _k_fb_conf_dense(k: Knobs) -> list:
    K = k.n_states
    return _k_fb_bwd_dense(k)[:-2] + [
        Buffer("alphas", (k.t_tile, K, k.lane_tile)),
        Buffer("conf_mask", (K, 1), kind="resident"),
        Buffer("conf_out", (k.t_tile, k.lane_tile), kind="out"),
        Buffer("beta_carry", (K, k.lane_tile), kind="scratch"),
    ]


def _k_fb_stats_dense(k: Knobs) -> list:
    K, S = k.n_states, k.n_symbols
    return [
        Buffer("alphas", (k.t_tile, K, k.lane_tile)),
        Buffer("betas", (k.t_tile, K, k.lane_tile)),
        Buffer("steps", (k.t_tile, k.lane_tile)),
        Buffer("lens", (1, k.lane_tile), kind="resident"),
        Buffer("A", (K, K), kind="resident"),
        Buffer("emit", (K, S), kind="resident"),
        Buffer("macc_out", (K * K, k.lane_tile), kind="out"),
        Buffer("emit_out", (K * S, k.lane_tile), kind="out"),
        Buffer("ll_out", (1, k.lane_tile), kind="out"),
        Buffer("macc_scr", (K * K, k.lane_tile), kind="scratch"),
        Buffer("emit_scr", (K * S, k.lane_tile), kind="scratch"),
        Buffer("aprev_scr", (K, k.lane_tile), kind="scratch"),
    ]


def _oh_chain_bufs(k: Knobs, fused: bool) -> list:
    """Shared stream/table set of the reduced chain kernels (fwd/bwd and
    the r9 co-scheduled fwd+bwd): per-member [t_tile, GROUP, lane_tile]
    stream outputs riding a shared symbol-only pair stream."""
    M = k.stacked_m
    bufs = [
        Buffer("pair", (k.t_tile, k.lane_tile)),
        Buffer("lens", (1, k.lane_tile), kind="resident"),
        Buffer("tab", (4 * M * _pair_rows(k.n_symbols), k.lane_tile),
               kind="resident"),
        Buffer("a0", (GROUP * M, k.lane_tile), kind="resident"),
        Buffer("alphas_out", (k.t_tile, GROUP * M, k.lane_tile), kind="out"),
        Buffer("chain_carry", (2 * GROUP * M, k.lane_tile), kind="scratch"),
    ]
    if fused:
        bufs += [
            Buffer("pair_next", (k.t_tile, k.lane_tile)),
            Buffer("beta0", (GROUP * M, k.lane_tile), kind="resident"),
            Buffer("betas_out", (k.t_tile, GROUP * M, k.lane_tile),
                   kind="out"),
        ]
    return bufs


def _k_fb_fwd_onehot(k: Knobs) -> list:
    return _oh_chain_bufs(k, fused=False)


def _k_fb_fwdbwd_onehot(k: Knobs) -> list:
    return _oh_chain_bufs(k, fused=True)


def _k_fb_fwdbwdmat_onehot(k: Knobs) -> list:
    """The true-one-pass matrix-carried co-scheduled kernel
    (fb_onehot._oh_fwdbwd_mat_kernel): both directions carry the [2,2]
    transfer-matrix form — 4 rows per direction per member instead of 2 —
    and stream [t_tile, 4*M, lane_tile] matrix blocks both ways, which is
    what buys folding the products pass in.  The doubled out-streams are
    exactly the VMEM trade: M=1 fits at every shipped tile; M=3 stacked
    does NOT at the production 256-lane reduced tile (max_stacked_m pins
    the verdict at 1 there, so stacked stays on the 2-pass arm —
    deliberately NOT in STACKED_KERNELS/STACKED_ENVELOPE)."""
    M = k.stacked_m
    return [
        Buffer("pair", (k.t_tile, k.lane_tile)),
        Buffer("pair_next", (k.t_tile, k.lane_tile)),
        Buffer("lens", (1, k.lane_tile), kind="resident"),
        Buffer("tab", (4 * M * _pair_rows(k.n_symbols), k.lane_tile),
               kind="resident"),
        Buffer("va_out", (k.t_tile, 4 * M, k.lane_tile), kind="out"),
        Buffer("wb_out", (k.t_tile, 4 * M, k.lane_tile), kind="out"),
        Buffer("mat_carry", (2 * 4 * M, k.lane_tile), kind="scratch"),
    ]


def _k_fb_conf_onehot(k: Knobs) -> list:
    return _oh_chain_bufs(k, fused=False) + [
        Buffer("cs_next", (k.t_tile, k.lane_tile)),
        Buffer("conf_out", (k.t_tile, k.lane_tile), kind="out"),
    ]


def _oh_stats_bufs(k: Knobs, seq: bool) -> list:
    """The reduced-stream stats kernels (_oh_stats_kernel and the
    z-normalized _oh_seq_stats_kernel): the K^2 count rows appear THREE
    ways per member — the fori-carried accumulator, the VMEM scratch it
    flushes into, and the streamed output block — which is what makes K
    the envelope knob (fb_onehot.ONEHOT_MAX_STATES) and M the stacked
    one."""
    K, S = k.n_states, k.n_symbols
    M = k.stacked_m
    bufs = [
        Buffer("alphas2", (k.t_tile, GROUP * M, k.lane_tile)),
        Buffer("betas2", (k.t_tile, GROUP * M, k.lane_tile)),
        Buffer("pair", (k.t_tile, k.lane_tile)),
        Buffer("lens", (1, k.lane_tile), kind="resident"),
        Buffer("brtab", (S * GROUP * M, k.lane_tile), kind="resident"),
        Buffer("gttab", (S * GROUP * M, k.lane_tile), kind="resident"),
        Buffer("macc_out", (M * K * K, k.lane_tile), kind="out"),
        Buffer("emit_out", (M * S * GROUP, k.lane_tile), kind="out"),
        Buffer("ll_out", (M, k.lane_tile), kind="out"),
        Buffer("macc_scr", (M * K * K, k.lane_tile), kind="scratch"),
        Buffer("macc_carry", (M * K * K, k.lane_tile), kind="scratch"),
        Buffer("emit_scr", (M * S * GROUP, k.lane_tile), kind="scratch"),
        Buffer("aprev_scr", (M * K, k.lane_tile), kind="scratch"),
    ]
    if seq:
        bufs += [
            Buffer("tab", (4 * M * _pair_rows(S), k.lane_tile),
                   kind="resident"),
            Buffer("enters_full", (M * K, k.lane_tile), kind="resident"),
            Buffer("enters_red", (GROUP * M, k.lane_tile), kind="resident"),
            Buffer("pair0_mask", (1, k.lane_tile), kind="resident"),
        ]
    return bufs


def _k_fb_stats_onehot(k: Knobs) -> list:
    return _oh_stats_bufs(k, seq=False)


def _k_fb_seqstats_onehot(k: Knobs) -> list:
    return _oh_stats_bufs(k, seq=True)


def _k_decode_vmap_onehot(k: Knobs) -> list:
    """The vmap-of-pallas batched decode route (viterbi_parallel_batch's
    ``vmap_records=True`` opt-in): batching materializes every stream as a
    batch-wide VMEM slab (CLAUDE.md r5), so the operand pays write-class
    revision buffering too — the factor that puts the predicted block cap
    in the measured bracket (bk=4096 ran 16 records, bk>=8192 failed
    scoped-VMEM compile outright), independent of the record count."""
    bufs = _k_decode_backpointers_onehot(k, scores=True)
    return [
        dataclasses.replace(b, kind="out") if b.name == "pair" else b
        for b in bufs
    ]


def _k_assembly_seqstats_onehot(k: Knobs) -> list:
    """Scoped-VMEM model of the EXACT-seq XLA stats assembly — the
    non-pow2-S route on TPU and the pre-r4 path whose remote compile
    FAILED at 131072 lanes (CLAUDE.md).  The fused contraction
    MATERIALIZES, per lane column, the full serial chain of its einsum
    partial rows (full-K a-prev and w rows) plus the beta directions and
    the z normalizer — written streams, so they pay the write-pipelining
    factor.  CALIBRATED: the operand set is chosen from
    fb_onehot._xla_znorm_stats's live streams so the predicted cap lands
    in the measured [65536 compiles, 131072 fails) bracket — pinned by
    tests/test_graftmem.py, revisit at the next capture."""
    K = k.n_states
    return [
        Buffer("aprev_full", (k.lane_T, K), kind="out"),
        Buffer("wz_full", (k.lane_T, K), kind="out"),
        Buffer("betas_dir", (k.lane_T, GROUP), kind="out"),
        Buffer("z_norm", (k.lane_T, 1), kind="out"),
    ]


_BUILDERS: dict = {
    "decode.products.dense": _k_decode_products_dense,
    "decode.backpointers.dense": _k_decode_backpointers_dense,
    "decode.backtrace.dense": _k_decode_backtrace_dense,
    "decode.products.onehot": _k_decode_products_onehot,
    "decode.backpointers.onehot":
        lambda k: _k_decode_backpointers_onehot(k, scores=False),
    "decode.backpointers.onehot.scores":
        lambda k: _k_decode_backpointers_onehot(k, scores=True),
    "decode.backtrace.onehot": _k_decode_backtrace_onehot,
    "decode.vmap.onehot": _k_decode_vmap_onehot,
    "fb.fwd.dense": _k_fb_fwd_dense,
    "fb.bwd.dense": _k_fb_bwd_dense,
    "fb.conf.dense": _k_fb_conf_dense,
    "fb.stats.dense": _k_fb_stats_dense,
    "fb.fwd.onehot": _k_fb_fwd_onehot,
    "fb.fwdbwd.onehot": _k_fb_fwdbwd_onehot,
    "fb.fwdbwdmat.onehot": _k_fb_fwdbwdmat_onehot,
    "fb.conf.onehot": _k_fb_conf_onehot,
    "fb.stats.onehot": _k_fb_stats_onehot,
    "fb.seqstats.onehot": _k_fb_seqstats_onehot,
    "assembly.seqstats.onehot": _k_assembly_seqstats_onehot,
}

# Kernel families whose launches stack M members (PR 12); the envelope
# contract enumerates M over these.
STACKED_KERNELS = (
    "decode.products.onehot",
    "decode.backpointers.onehot",
    "decode.backpointers.onehot.scores",
    "decode.backtrace.onehot",
    "fb.fwdbwd.onehot",
    "fb.stats.onehot",
)


def kernels() -> list:
    return sorted(_BUILDERS)


def footprint(kernel: str, knobs: Optional[Knobs] = None) -> Footprint:
    if kernel not in _BUILDERS:
        raise KeyError(
            f"unknown kernel {kernel!r} (have: {kernels()})"
        )
    k = knobs or Knobs()
    return Footprint(kernel=kernel, knobs=k, buffers=_BUILDERS[kernel](k))


def feasible(
    kernel: str, knobs: Optional[Knobs] = None, **overrides
) -> Feasibility:
    """Does ``kernel`` at ``knobs`` fit the modeled VMEM budget?

    THE autotuner pruning API (ROADMAP#1): a knob tuple rejected here
    need not pay a relay-TPU compile to be ruled out.  Overrides are
    Knobs fields (``feasible("fb.fwdbwd.onehot", t_tile=512)``)."""
    k = (knobs or Knobs()).replace(**overrides) if overrides else (
        knobs or Knobs()
    )
    fp = footprint(kernel, k)
    limit = vmem_limit()
    ok = fp.total <= limit
    return Feasibility(
        ok=ok, kernel=kernel, total=fp.total, limit=limit,
        offenders=[] if ok else fp.top(3),
        reason="" if ok else (
            f"{kernel}: modeled VMEM {fp.total} B > limit {limit} B "
            f"({VMEM_BYTES >> 20} MiB - {VMEM_RESERVE:.0%} reserve); "
            "largest: " + ", ".join(b.describe() for b in fp.top(3))
        ),
    )


# -- derived caps (the shipped routing consults these) -----------------------


def lane_feasible(
    lane_T: int, onehot: bool = False, long_lanes: bool = False,
    knobs: Optional[Knobs] = None,
) -> bool:
    """Is a ``lane_T``-long lane memory-feasible for this path?

    Dense lanes and the kernelized (``long_lanes``) reduced path check
    their t-tiled chain kernels — lane_T never enters a block spec there,
    so the model admits every rate-table entry (the dense 32768 knee is a
    PERF knee, not memory).  The plain reduced path must also run the
    exact-seq XLA stats assembly (the non-kernelized consumer), whose
    scoped-VMEM model is what bans 131072 — the same cap
    fb_pallas.pick_lane_T shipped as a hard-coded filter before graftmem.
    """
    k = (knobs or Knobs()).replace(lane_T=lane_T)
    if not onehot:
        return feasible("fb.stats.dense", k).ok
    if long_lanes:
        return feasible("fb.seqstats.onehot", k).ok
    return feasible("assembly.seqstats.onehot", k).ok


def max_flat_block(
    scores: bool = True, stacked_m: int = 1, knobs: Optional[Knobs] = None,
) -> int:
    """Largest power-of-two decode block ``bk`` that fits the flat-decode
    kernel set (the score variant's dmax rows are the fat ones).  The
    measured anchor: bk >= 8192 failed scoped-VMEM compile on the batched
    decode route (CLAUDE.md r5)."""
    base = knobs or Knobs()
    kernel = (
        "decode.backpointers.onehot.scores" if scores
        else "decode.backtrace.onehot"
    )
    bk = 8
    while True:
        k = base.replace(block_size=bk * 2, stacked_m=stacked_m)
        if not feasible(kernel, k).ok or bk >= (1 << 24):
            return bk
        bk *= 2


def flat_block_feasibility(
    bk: int, scores: bool = True, stacked_m: int = 1,
) -> Feasibility:
    """Feasibility of one flat-decode block size across its pass kernels —
    the gate decode_batch_flat consults on TPU (worst kernel reported)."""
    worst: Optional[Feasibility] = None
    ks = ["decode.products.onehot", "decode.backpointers.onehot",
          "decode.backtrace.onehot"]
    if scores:
        ks.append("decode.backpointers.onehot.scores")
    knobs = Knobs(block_size=bk, stacked_m=stacked_m)
    for kernel in ks:
        f = feasible(kernel, knobs)
        if worst is None or f.total > worst.total:
            worst = f
        if not f.ok:
            return f
    return worst


def max_vmap_block(knobs: Optional[Knobs] = None) -> int:
    """Largest power-of-two block the vmap batched-decode route fits —
    the route whose bk >= 8192 scoped-VMEM compile failure is the
    measured anchor (the flat route's own cap sits one notch higher and
    is chip-unmeasured; tests pin the distinction)."""
    base = knobs or Knobs()
    bk = 8
    while feasible(
        "decode.vmap.onehot", base.replace(block_size=bk * 2)
    ).ok and bk < (1 << 24):
        bk *= 2
    return bk


def max_stacked_m(kernel: str, knobs: Optional[Knobs] = None) -> int:
    """Largest member count M for one stacked kernel family at the given
    knobs (default: shipped) — the mem.stacked-envelope pin."""
    base = knobs or Knobs()
    m = 0
    while m < 256 and feasible(kernel, base.replace(stacked_m=m + 1)).ok:
        m += 1
    return m


def stacked_block_cap(
    stacked_m: int, scores: bool = False, knobs: Optional[Knobs] = None,
) -> int:
    """Largest power-of-two decode block feasible for an M-member stacked
    flat decode — the static guard PR 12 shipped without ('on-chip, large
    M wants a smaller block_size', viterbi_onehot)."""
    return max_flat_block(scores=scores, stacked_m=stacked_m, knobs=knobs)


def max_onehot_states(knobs: Optional[Knobs] = None) -> int:
    """Largest power-of-two state count K the reduced stats kernels fit at
    the production 256-lane tile — the model's twin of
    fb_onehot.ONEHOT_MAX_STATES (the K*K count rows appear three ways in
    VMEM: carry, scratch, out block)."""
    base = (knobs or Knobs()).replace(lane_tile=256)
    K = 2
    while feasible("fb.seqstats.onehot", base.replace(n_states=K * 2)).ok:
        K *= 2
        if K >= 1 << 12:
            break
    return K


# -- whole-sequence shard HBM model (SEQ_SHARD_BUDGET's oracle) --------------

# Modeled peak live HBM per whole-sequence E-step symbol, by named stream
# (K=8 f32 streams unless noted).  CALIBRATED to the measured bracket: a
# 120 Mi shard compiled and ran on one 16 GB v5e, 128 Mi failed remote
# compile (CLAUDE.md r4) — the xi partial-row term is sized so
# max_seq_shard() lands at the shipped 112 Mi with that bracket intact;
# the liveness pass cross-checks the total against the traced em.seq
# slope (mem.seq-shard-budget notes).
SEQ_STREAM_BYTES = {
    "symbols_u8": 1,
    "steps_next_i32": 4,
    "alphas_f32xK": 32,
    "cs_f32": 4,
    "cs_next_f32": 4,
    "betas_f32xK": 32,
    "gamma_f32xK": 32,
    "xi_partial_rows": 11,
}
SEQ_SHARD_GRANULE = 16 << 20   # shards quantize to 16 Mi (lane-grid pow2s)


def seq_shard_bytes_per_symbol() -> int:
    return sum(SEQ_STREAM_BYTES.values())


def seq_shard_bytes(n_symbols: int) -> int:
    """Modeled peak live HBM of an ``n``-symbol whole-sequence E-step."""
    return n_symbols * seq_shard_bytes_per_symbol()


def max_seq_shard() -> int:
    """Largest whole-sequence per-shard symbol count the HBM model admits
    on one chip, floored to the 16 Mi granule — train.backends derives
    SEQ_SHARD_BUDGET from this (pinned == 112 Mi by routing-parity test).
    """
    raw = hbm_limit() // seq_shard_bytes_per_symbol()
    return (raw // SEQ_SHARD_GRANULE) * SEQ_SHARD_GRANULE


def seq_shard_report(n_symbols: int) -> dict:
    """The actionable numbers a SEQ_SHARD_BUDGET rejection carries
    (mem_reject event): predicted footprint, budget, max fit, streams."""
    return {
        "predicted_bytes": seq_shard_bytes(n_symbols),
        "hbm_limit_bytes": hbm_limit(),
        "bytes_per_symbol": seq_shard_bytes_per_symbol(),
        "max_fit_symbols": max_seq_shard(),
        "streams": dict(SEQ_STREAM_BYTES),
    }


# -- island-calling device memory model --------------------------------------

# The blocked island reduction's device temp is ~ISLAND_BLOCK_BPS x
# BLOCK_W regardless of T (ops/islands_device.py: "~40 B x BLOCK_W");
# the whole-record formulation the r4 OOM killed paid the same rate times
# T (~15 GB of s32[T] temps at 320 Mi).  Compact output columns are
# ISLAND_COLS int32 rows of the cap.
ISLAND_BLOCK_BPS = 40
ISLAND_COLS = 8


def island_block_bytes(block_w: int) -> int:
    return ISLAND_BLOCK_BPS * block_w


def island_columns_bytes(cap: int) -> int:
    return ISLAND_COLS * 4 * cap


def island_cap_report(n_calls: int, ceiling: int) -> dict:
    """Actionable numbers for an island cap-overflow mem_reject: the
    column footprint the true call count would need vs the ceiling's."""
    return {
        "n_calls": int(n_calls),
        "cap_ceiling": int(ceiling),
        "predicted_bytes": island_columns_bytes(int(n_calls)),
        "ceiling_bytes": island_columns_bytes(int(ceiling)),
        "max_fit_calls": int(ceiling),
    }


# -- jaxpr HBM liveness ------------------------------------------------------

# Prims whose results XLA materializes as fresh buffers; everything here
# is attribution policy, not physics.  Pure-layout prims alias or fuse
# away and would mis-flag O(T) "allocations" on reshape-heavy entries.
_NONMATERIAL_PRIMS = frozenset({
    "reshape", "transpose", "squeeze", "broadcast_in_dim", "slice",
    "rev", "bitcast_convert_type", "copy", "split", "iota",
    "stop_gradient", "device_put",
})

_LOOP_SUB_KEYS = ("jaxpr", "body_jaxpr", "cond_jaxpr", "branches")


def _aval_bytes(aval) -> int:
    dt = getattr(aval, "dtype", None)
    itemsize = getattr(dt, "itemsize", 4)
    n = int(itemsize)
    for d in getattr(aval, "shape", ()) or ():
        n *= int(d)
    return n


def _sub_jaxprs_of(eqn):
    from cpgisland_tpu.analysis.costmodel import _closed_of

    for v in eqn.params.values():
        yield from _closed_of(v)


def peak_live_bytes(closed, include_args: bool = True) -> int:
    """Interval-analysis peak of live buffer bytes over a (Closed)Jaxpr.

    A var is live from its defining equation until its last use (args and
    consts from the start); at each equation the inner peak of any loop
    body rides on top of the state live at that point — scan/while body
    temps are per-iteration (they do NOT scale by trip count; the stacked
    ys outputs already carry their full [trips, ...] avals at this
    level).  Deterministic, allocator-free: a stable fingerprint for the
    lockfile, not a simulator."""
    import jax

    jaxpr = getattr(closed, "jaxpr", closed)
    last_use: dict = {}
    for i, eqn in enumerate(jaxpr.eqns):
        for v in eqn.invars:
            if not isinstance(v, jax.core.Literal):
                last_use[id(v)] = i
    for v in jaxpr.outvars:
        if not isinstance(v, jax.core.Literal):
            last_use[id(v)] = len(jaxpr.eqns)

    live: dict = {}
    if include_args:
        for v in list(jaxpr.constvars) + list(jaxpr.invars):
            live[id(v)] = _aval_bytes(v.aval)
    peak = sum(live.values())
    for i, eqn in enumerate(jaxpr.eqns):
        inner = 0
        if eqn.primitive.name in ("scan", "while", "cond"):
            inner = max(
                (peak_live_bytes(s, include_args=False)
                 for s in _sub_jaxprs_of(eqn)),
                default=0,
            )
        elif eqn.primitive.name != "pallas_call":
            subs = list(_sub_jaxprs_of(eqn))
            if subs:
                inner = max(
                    peak_live_bytes(s, include_args=False) for s in subs
                )
        for v in eqn.outvars:
            live[id(v)] = _aval_bytes(v.aval)
        peak = max(peak, sum(live.values()) + inner)
        for v in list(eqn.invars) + list(eqn.outvars):
            if not isinstance(v, jax.core.Literal) and \
                    last_use.get(id(v), -1) <= i:
                live.pop(id(v), None)
    return peak


def alloc_groups(closed) -> dict:
    """Materialized allocation bytes per ``file:function`` source group.

    Loop bodies count ONE iteration (per-iteration working set — trip
    scaling is the liveness pass's job via the stacked outvar avals);
    layout-only prims are excluded so a reshape of an argument does not
    read as an allocation.  The group names are what a violation prints:
    'the s32[T] temp grew in islands_device.py:_scan_calls'."""
    from cpgisland_tpu.analysis.costmodel import _eqn_group

    out: dict = {}

    def walk(j):
        jaxpr = getattr(j, "jaxpr", j)
        for eqn in jaxpr.eqns:
            if eqn.primitive.name not in _NONMATERIAL_PRIMS:
                g = _eqn_group(eqn)
                out[g] = out.get(g, 0) + sum(
                    _aval_bytes(v.aval) for v in eqn.outvars
                )
            for s in _sub_jaxprs_of(eqn):
                walk(s)

    walk(closed)
    return out


def while_body_peaks(closed) -> list:
    """Peak live bytes inside each while-loop body (one iteration) — the
    fused-EM body's working set, the mem twin of
    costmodel.while_body_costs."""
    jaxpr = getattr(closed, "jaxpr", closed)
    out: list = []

    def walk(j):
        for eqn in getattr(j, "jaxpr", j).eqns:
            if eqn.primitive.name == "while":
                from cpgisland_tpu.analysis.costmodel import _closed_of

                for s in _closed_of(eqn.params["body_jaxpr"]):
                    out.append(peak_live_bytes(s, include_args=True))
            for s in _sub_jaxprs_of(eqn):
                walk(s)

    walk(jaxpr)
    return out


@dataclasses.dataclass
class LiveMetrics:
    """One traced geometry's liveness fingerprint."""

    peak_bytes: int
    arg_bytes: int
    out_bytes: int
    alloc_bytes: int           # total materialized allocations
    groups: dict               # file:function -> alloc bytes
    while_body_peak: int       # 0 when the entry has no while loop

    def as_dict(self) -> dict:
        return {
            "peak_bytes": self.peak_bytes,
            "arg_bytes": self.arg_bytes,
            "out_bytes": self.out_bytes,
            "alloc_bytes": self.alloc_bytes,
            "groups": dict(sorted(self.groups.items())),
            "while_body_peak": self.while_body_peak,
        }


def live_metrics(closed) -> LiveMetrics:
    jaxpr = getattr(closed, "jaxpr", closed)
    groups = alloc_groups(closed)
    bodies = while_body_peaks(closed)
    return LiveMetrics(
        peak_bytes=peak_live_bytes(closed),
        arg_bytes=sum(
            _aval_bytes(v.aval)
            for v in list(jaxpr.constvars) + list(jaxpr.invars)
        ),
        out_bytes=sum(_aval_bytes(v.aval) for v in jaxpr.outvars),
        alloc_bytes=sum(groups.values()),
        groups=groups,
        while_body_peak=max(bodies, default=0),
    )


def linear_alloc_groups(
    lo: LiveMetrics, hi: LiveMetrics, n_lo: int, n_hi: int,
    min_bps: float = 1.0,
) -> list:
    """Groups whose materialized allocation grows >= ``min_bps`` bytes per
    symbol between the two geometries — the named O(T) temporaries.
    Sorted by slope, descending: [(group, bytes_per_symbol)]."""
    dn = max(n_hi - n_lo, 1)
    out = []
    for g in set(lo.groups) | set(hi.groups):
        slope = (hi.groups.get(g, 0) - lo.groups.get(g, 0)) / dn
        if slope >= min_bps:
            out.append((g, slope))
    out.sort(key=lambda kv: (-kv[1], kv[0]))
    return out
