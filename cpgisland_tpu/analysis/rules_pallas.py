"""R2 ``pallas-sublane-align``: the Mosaic kernel-shape rules.

Encodes the constraints honed on this codebase (CLAUDE.md "Mosaic
constraints"):

- dynamic sublane offsets into (8, 128)-tiled VMEM must be *provably*
  8-aligned — write them as ``i * ROW_TILE``, not ``Tt - 8 - i*8``.  A
  dynamic start that mixes in an opaque term (a shape, a non-constant
  parameter) is unprovable and flags;
- kernel values are rank-2 (sublane, lane) only: explicit >=3-D shape
  literals in ``reshape``/``broadcast_to``/``zeros``/... flag;
- Mosaic cannot broadcast ``[1,1] -> [8,128]``: scalar-indexed table loads
  (``tab_ref[i, j]``) fed to ``broadcast_to`` flag — tables must be
  lane-broadcast OUTSIDE the kernel (``_bcast_tab``) and read as [1, LT]
  rows.

Kernel discovery: any function passed as the first argument to
``pl.pallas_call`` (resolved through ``functools.partial``), plus any
function whose name matches ``*_kernel`` and takes ``*_ref`` parameters.
"""

from __future__ import annotations

import ast
from typing import Iterator, Optional

from cpgisland_tpu.analysis import astutil
from cpgisland_tpu.analysis.core import FileContext, Finding, register
from cpgisland_tpu.analysis.rules_jit import PALLAS_CALL_NAMES, _unwrap_target

DS_NAMES = frozenset({"pl.ds", "ds", "pl.dslice", "dslice",
                      "jax.experimental.pallas.ds",
                      "jax.experimental.pallas.dslice"})
SHAPE_CALLS = frozenset({"reshape", "broadcast_to", "zeros", "ones", "full",
                         "empty"})

# Alignment lattice values for sublane-offset expressions.
CONST = "const"      # folds to a Python int at lint time (static offset)
ALIGNED = "aligned"  # dynamic, but provably ≡ 0 (mod 8)
STATIC = "static"    # trace-time Python value of unknown alignment
DYN = "dyn"          # dynamic, not provably aligned


def _find_kernels(ctx: FileContext) -> dict[str, ast.AST]:
    kernels: dict[int, ast.AST] = {}
    for node in ast.walk(ctx.tree):
        if isinstance(node, ast.Call) and astutil.matches(
            ctx.call_name(node), PALLAS_CALL_NAMES
        ) and node.args:
            target = _unwrap_target(ctx, node.args[0])
            if isinstance(target, (ast.FunctionDef, ast.AsyncFunctionDef,
                                   ast.Lambda)):
                kernels[id(target)] = target
        elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            if node.name.endswith("_kernel") and any(
                p.arg.endswith("_ref") for p in astutil.func_params(node)
            ):
                kernels[id(node)] = node
    return {str(k): v for k, v in kernels.items()}


class _AlignChecker:
    """Alignment lattice over one use site's scope chain.

    The kernel's own parameters are Python-static at trace time (they come
    in via functools.partial); parameters of functions NESTED in the kernel
    are loop carries/counters (fori/scan bodies) and classify as dynamic.
    Name lookups merge single-assignment maps outermost -> innermost.
    """

    def __init__(self, ctx: FileContext, kernel: ast.AST, use_site: ast.AST):
        self.ctx = ctx
        self.consts = ctx.module_ints
        chain = [kernel]
        for fn in reversed(astutil.enclosing_functions(use_site)):
            # Only scopes inside the kernel matter (the walk starts there).
            if fn is kernel or any(p is kernel for p in astutil.parents(fn)):
                if fn is not kernel:
                    chain.append(fn)
        self.static_params = {p.arg for p in astutil.func_params(kernel)}
        self.dyn_params = set()
        self.env: dict[str, ast.expr] = {}
        for fn in chain:
            if fn is not kernel:
                self.dyn_params |= {p.arg for p in astutil.func_params(fn)}
            self.env.update(astutil.single_assignments(fn))

    def classify(self, node: ast.AST, depth: int = 0) -> tuple[str, Optional[int]]:
        if depth > 8:
            return (DYN, None)
        v = astutil.const_int(node, self.consts)
        if v is not None:
            return (CONST, v)
        if isinstance(node, ast.Name):
            if node.id in self.dyn_params:
                return (DYN, None)
            if node.id in self.env:
                return self.classify(self.env[node.id], depth + 1)
            if node.id in self.static_params:
                return (STATIC, None)  # Python-static kernel parameter
            # loop counters, for targets, program_id results: dynamic
            return (DYN, None)
        if isinstance(node, ast.BinOp):
            a, av = self.classify(node.left, depth + 1)
            b, bv = self.classify(node.right, depth + 1)
            if isinstance(node.op, ast.Mult):
                if (a == CONST and av is not None and av % 8 == 0 and av != 0) or (
                    b == CONST and bv is not None and bv % 8 == 0 and bv != 0
                ):
                    return (ALIGNED, None)
                if ALIGNED in (a, b) and DYN not in (a, b):
                    return (ALIGNED, None)
                if a == CONST and b == CONST:
                    return (CONST, None)
                if DYN in (a, b) or ALIGNED in (a, b):
                    return (DYN, None)
                return (STATIC, None)
            if isinstance(node.op, (ast.Add, ast.Sub)):
                kinds = {a, b}
                if kinds <= {CONST}:
                    return (CONST, None)
                ok = lambda k, kv: k == ALIGNED or (
                    k == CONST and kv is not None and kv % 8 == 0
                )
                if ok(a, av) and ok(b, bv):
                    return (ALIGNED, None)
                if DYN in kinds or ALIGNED in kinds:
                    return (DYN, None)
                return (STATIC, None)
        if isinstance(node, ast.Call):
            return (DYN, None)
        return (STATIC, None)

    def offset_misaligned(self, start: ast.AST) -> Optional[str]:
        """None when fine; else a message describing why the start flags."""
        kind, value = self.classify(start)
        if kind == CONST:
            return None  # static offset: Mosaic handles (or rejects) it at compile
        if kind == ALIGNED:
            return None
        if kind == STATIC:
            return None  # pure trace-time value, no dynamic component
        expr = ast.unparse(start) if hasattr(ast, "unparse") else "<expr>"
        return (
            f"dynamic sublane offset `{expr}` is not provably 8-aligned; "
            "write it as `i * ROW_TILE` (Mosaic's fast path needs dynamic "
            "sublane starts ≡ 0 mod 8)"
        )


def _ds_start(ctx: FileContext, node: ast.AST) -> Optional[ast.AST]:
    """The start expression when ``node`` is a pl.ds(...) call."""
    if isinstance(node, ast.Call) and astutil.matches(
        ctx.call_name(node), DS_NAMES
    ) and node.args:
        return node.args[0]
    return None


def _is_scalar_index(node: ast.AST) -> bool:
    """True for an index element that selects a single row/element (not a
    slice, not a pl.ds)."""
    return not isinstance(node, (ast.Slice, ast.Call, ast.Tuple))


@register(
    "pallas-sublane-align",
    "Pallas kernel refs: dynamic sublane offsets must be provably 8-aligned, "
    "values rank-2 only, tables lane-broadcast outside the kernel",
    origin="CLAUDE.md Mosaic constraints: write offsets as i * ROW_TILE, "
    "not Tt - 8 - i*8; Mosaic cannot broadcast [1,1]->[8,128] (_bcast_tab)",
)
def check_pallas_sublane_align(ctx: FileContext) -> Iterator[Finding]:
    for kernel in _find_kernels(ctx).values():
        for node in ast.walk(kernel):
            # (a) pl.ds sublane starts: ref[pl.ds(start, n), ...] — only the
            # leading index of a 2-D subscript is the sublane axis (rank-3
            # refs carry an untiled leading dim; their pl.ds use is rare and
            # positionally ambiguous, so only the canonical form is checked).
            if isinstance(node, ast.Subscript) and isinstance(
                node.value, ast.Name
            ) and node.value.id.endswith("_ref"):
                idx = node.slice
                elems = list(idx.elts) if isinstance(idx, ast.Tuple) else [idx]
                if len(elems) <= 2:
                    start = _ds_start(ctx, elems[0])
                    if start is not None:
                        checker = _AlignChecker(ctx, kernel, node)
                        msg = checker.offset_misaligned(start)
                        if msg:
                            yield ctx.finding("pallas-sublane-align", node, msg)
            # (b) explicit >= 3-D shape literals: rank-2 values only.
            if isinstance(node, ast.Call):
                name = ctx.call_name(node) or ""
                tail = name.rsplit(".", 1)[-1]
                if tail in SHAPE_CALLS or (
                    isinstance(node.func, ast.Attribute)
                    and node.func.attr in ("reshape", "broadcast_to")
                ):
                    for arg in list(node.args) + [
                        kw.value for kw in node.keywords if kw.arg == "shape"
                    ]:
                        if isinstance(arg, ast.Tuple) and len(arg.elts) >= 3:
                            yield ctx.finding(
                                "pallas-sublane-align",
                                node,
                                f"rank-{len(arg.elts)} value constructed "
                                "inside a Pallas kernel; Mosaic wants rank-2 "
                                "(sublane, lane) values only",
                            )
                            break
                # (c) broadcasting a scalar-indexed ref load: [1,1]->[8,128].
                if tail == "broadcast_to" and node.args:
                    src = node.args[0]
                    if isinstance(src, ast.Subscript) and isinstance(
                        src.value, ast.Name
                    ) and src.value.id.endswith("_ref"):
                        idx = src.slice
                        elems = (
                            list(idx.elts)
                            if isinstance(idx, ast.Tuple)
                            else [idx]
                        )
                        if len(elems) >= 2 and all(
                            _is_scalar_index(e) for e in elems
                        ):
                            yield ctx.finding(
                                "pallas-sublane-align",
                                node,
                                "broadcast of a scalar-indexed ref load "
                                "([1,1] -> tile) — Mosaic cannot; "
                                "lane-broadcast the table OUTSIDE the kernel "
                                "(_bcast_tab) and read [1, LT] rows",
                            )
