"""Shared AST machinery for the graftcheck lint rules.

Everything here is plain-``ast`` analysis — no jax import, no execution —
so the whole lint layer runs on any host in milliseconds per file.  The
helpers encode the small amount of semantic resolution the rules need:

- import-alias canonicalization (``pl.pallas_call`` ->
  ``jax.experimental.pallas.pallas_call``) so rules match call sites no
  matter how a module spells its imports;
- best-effort integer constant folding over module constants (``ROW_TILE``,
  ``OUTER_TILE // ROW_TILE``) for the Mosaic alignment rule;
- scope walks (bound vs free names, single-assignment maps) for the
  closure and hot-path rules.
"""

from __future__ import annotations

import ast
from typing import Iterator, Optional


def attach_parents(tree: ast.AST) -> ast.AST:
    """Set ``node.parent`` on every node (rules walk upward for context)."""
    for node in ast.walk(tree):
        for child in ast.iter_child_nodes(node):
            child.parent = node  # type: ignore[attr-defined]
    tree.parent = None  # type: ignore[attr-defined]
    return tree


def parents(node: ast.AST) -> Iterator[ast.AST]:
    cur = getattr(node, "parent", None)
    while cur is not None:
        yield cur
        cur = getattr(cur, "parent", None)


def dotted_name(node: ast.AST) -> Optional[str]:
    """``a.b.c`` for a Name/Attribute chain, else None."""
    parts = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


class ImportMap:
    """Alias -> canonical dotted path, from a module's import statements."""

    def __init__(self, tree: ast.AST) -> None:
        self.aliases: dict[str, str] = {}
        for node in ast.walk(tree):
            if isinstance(node, ast.Import):
                for a in node.names:
                    self.aliases[a.asname or a.name.split(".")[0]] = (
                        a.name if a.asname else a.name.split(".")[0]
                    )
            elif isinstance(node, ast.ImportFrom) and node.module and not node.level:
                for a in node.names:
                    if a.name == "*":
                        continue
                    self.aliases[a.asname or a.name] = f"{node.module}.{a.name}"

    def canonical(self, node: ast.AST) -> Optional[str]:
        """Canonical dotted path of a Name/Attribute expression, resolving
        the leading alias through this module's imports."""
        dn = dotted_name(node)
        if dn is None:
            return None
        head, _, rest = dn.partition(".")
        base = self.aliases.get(head, head)
        return f"{base}.{rest}" if rest else base


def call_name(imports: ImportMap, call: ast.Call) -> Optional[str]:
    return imports.canonical(call.func)


def matches(canonical: Optional[str], targets: frozenset[str] | set[str]) -> bool:
    """True when ``canonical`` equals a target or ends with ``.<target>``
    for single-segment targets (tolerates re-export paths like
    ``jax.experimental.pallas`` vs ``jax._src.pallas``)."""
    if canonical is None:
        return False
    if canonical in targets:
        return True
    tail = canonical.rsplit(".", 1)[-1]
    return any("." not in t and t == tail for t in targets)


# -- integer constant folding ------------------------------------------------


def const_int(node: ast.AST, env: dict[str, int]) -> Optional[int]:
    """Fold ``node`` to a Python int using ``env`` for Name lookups."""
    if isinstance(node, ast.Constant) and isinstance(node.value, int) \
            and not isinstance(node.value, bool):
        return node.value
    if isinstance(node, ast.Name):
        return env.get(node.id)
    if isinstance(node, ast.UnaryOp) and isinstance(node.op, ast.USub):
        v = const_int(node.operand, env)
        return -v if v is not None else None
    if isinstance(node, ast.BinOp):
        a = const_int(node.left, env)
        b = const_int(node.right, env)
        if a is None or b is None:
            return None
        try:
            if isinstance(node.op, ast.Add):
                return a + b
            if isinstance(node.op, ast.Sub):
                return a - b
            if isinstance(node.op, ast.Mult):
                return a * b
            if isinstance(node.op, ast.FloorDiv):
                return a // b
            if isinstance(node.op, ast.Mod):
                return a % b
            if isinstance(node.op, ast.LShift):
                return a << b
            if isinstance(node.op, ast.RShift):
                return a >> b
            if isinstance(node.op, ast.Pow):
                return a**b
        except (ZeroDivisionError, OverflowError, ValueError):
            return None
    return None


def module_int_constants(tree: ast.Module) -> dict[str, int]:
    """Top-level ``NAME = <int-foldable>`` assignments, folded in order."""
    env: dict[str, int] = {}
    for node in tree.body:
        if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                and isinstance(node.targets[0], ast.Name):
            v = const_int(node.value, env)
            if v is not None:
                env[node.targets[0].id] = v
    return env


_MODULE_INT_CACHE: dict[str, dict[str, int]] = {}


def _module_ints_for_path(path: str, depth: int) -> dict[str, int]:
    if path in _MODULE_INT_CACHE:
        return _MODULE_INT_CACHE[path]
    _MODULE_INT_CACHE[path] = {}  # cycle guard
    try:
        with open(path, "r", encoding="utf-8") as fh:
            tree = ast.parse(fh.read(), filename=path)
    except (OSError, SyntaxError):
        return {}
    env = module_int_constants(tree)
    if depth > 0:
        env = {**imported_int_constants(tree, ImportMap(tree), depth - 1), **env}
    _MODULE_INT_CACHE[path] = env
    return env


def imported_int_constants(
    tree: ast.Module, imports: ImportMap, depth: int = 2
) -> dict[str, int]:
    """Fold int constants imported from sibling cpgisland_tpu modules
    (``from cpgisland_tpu.ops.viterbi_onehot import ROW_TILE`` -> {ROW_TILE:
    8}) so the Mosaic alignment rule sees tile sizes across module lines.
    Source files are located from the installed package, parsed once, and
    cached; unresolvable imports are silently skipped."""
    import os

    pkg_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    out: dict[str, int] = {}
    for node in tree.body:
        if not (isinstance(node, ast.ImportFrom) and node.module
                and node.module.startswith("cpgisland_tpu.")):
            continue
        rel = node.module.split(".", 1)[1].replace(".", os.sep) + ".py"
        env = _module_ints_for_path(os.path.join(pkg_root, rel), depth)
        for a in node.names:
            if a.name in env:
                out[a.asname or a.name] = env[a.name]
    return out


# -- scopes ------------------------------------------------------------------

FunctionNode = (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)


def func_params(fn: ast.AST) -> list[ast.arg]:
    a = fn.args
    return [*a.posonlyargs, *a.args, *a.kwonlyargs] + (
        [a.vararg] if a.vararg else []
    ) + ([a.kwarg] if a.kwarg else [])


def walk_scope(fn: ast.AST) -> Iterator[ast.AST]:
    """Walk a function body WITHOUT descending into nested def/lambda."""
    stack = list(ast.iter_child_nodes(fn))
    while stack:
        node = stack.pop()
        yield node
        if not isinstance(node, FunctionNode):
            stack.extend(ast.iter_child_nodes(node))


def bound_names(fn: ast.AST) -> set[str]:
    """Names bound in ``fn``'s own scope: params, assignments, loop/with/
    comprehension targets, imports, nested def names."""
    out = {p.arg for p in func_params(fn)}
    for node in walk_scope(fn):
        if isinstance(node, ast.Name) and isinstance(node.ctx, (ast.Store, ast.Del)):
            out.add(node.id)
        elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)):
            out.add(node.name)
        elif isinstance(node, (ast.Import, ast.ImportFrom)):
            for a in node.names:
                out.add((a.asname or a.name).split(".")[0])
        elif isinstance(node, (ast.Global, ast.Nonlocal)):
            out.difference_update(node.names)
    return out


def free_loads(fn: ast.AST) -> dict[str, ast.Name]:
    """Free variables of ``fn`` (loads not bound at any nesting level inside
    it), mapped to one representative Name node.  Comprehension targets and
    nested-function locals are treated as bound — this approximates Python
    scoping closely enough for closure detection."""
    bound: set[str] = set()
    loads: dict[str, ast.Name] = {}

    def visit(f: ast.AST, outer_bound: set[str]) -> None:
        here = outer_bound | bound_names(f)
        for node in walk_scope(f):
            if isinstance(node, ast.Name) and isinstance(node.ctx, ast.Load):
                if node.id not in here:
                    loads.setdefault(node.id, node)
            elif isinstance(node, FunctionNode):
                visit(node, here)

    visit(fn, set())
    return loads


def single_assignments(fn: ast.AST) -> dict[str, ast.expr]:
    """Name -> value for names assigned exactly once by a plain ``=`` in
    ``fn``'s own scope (and never augmented/deleted)."""
    counts: dict[str, int] = {}
    values: dict[str, ast.expr] = {}
    for node in walk_scope(fn):
        if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                and isinstance(node.targets[0], ast.Name):
            name = node.targets[0].id
            counts[name] = counts.get(name, 0) + 1
            values[name] = node.value
        elif isinstance(node, (ast.AugAssign, ast.AnnAssign)) \
                and isinstance(getattr(node, "target", None), ast.Name):
            counts[node.target.id] = counts.get(node.target.id, 0) + 2
        elif isinstance(node, ast.Name) and isinstance(node.ctx, (ast.Store, ast.Del)):
            parent = getattr(node, "parent", None)
            if not (isinstance(parent, ast.Assign) and len(parent.targets) == 1
                    and parent.targets[0] is node):
                counts[node.id] = counts.get(node.id, 0) + 2
    return {k: v for k, v in values.items() if counts.get(k) == 1}


def enclosing_function(node: ast.AST) -> Optional[ast.AST]:
    for p in parents(node):
        if isinstance(p, FunctionNode):
            return p
    return None


def enclosing_functions(node: ast.AST) -> list[ast.AST]:
    return [p for p in parents(node) if isinstance(p, FunctionNode)]


def top_level_defs(tree: ast.Module) -> dict[str, ast.AST]:
    return {
        n.name: n
        for n in tree.body
        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))
    }
