"""Hierarchical span tracing (the Dapper model, host-side).

A :class:`Tracer` records parent/child spans around pipeline phases (encode,
EM iterations, decode span sweeps, island calling, multi-host gathers) with
wall time, caller-defined item counts, and the owning process index.  Every
completed span carries the :class:`~cpgisland_tpu.obs.ledger.Ledger` deltas
accumulated while it was innermost-or-ancestor (children are included in
their parents — spans nest, counters aggregate upward), so a metrics stream
alone reconstructs where compiles, blocking dispatches, and transfer bytes
went.

Export targets:

- JSONL ``span`` events through the owning Observer's MetricsLogger
  (``cpgisland_tpu.obs.Observer`` wires this up);
- a Chrome-trace / Perfetto-loadable JSON (``write_chrome_trace``): one
  complete ("ph": "X") event per span, ``pid`` = JAX process index,
  microsecond timestamps relative to tracer start.

No jax import at module level: tracing must be constructible before platform
selection (the CLI picks the backend after parsing flags).
"""

from __future__ import annotations

import contextlib
import dataclasses
import json
import sys
import time
from typing import Iterator, Optional

# Dropping spans beyond this bound trades perfect traces on degenerate
# million-record inputs for bounded host memory; the drop count is reported.
MAX_SPANS = 100_000


def process_index_or_none():
    """JAX process index WITHOUT triggering backend initialization, or None
    while it is undecidable (jax not imported / backend not initialized yet).

    Calling ``jax.process_index()`` eagerly would initialize the backend and
    defeat the CLI's deferred platform selection, so this only reads it once
    a backend exists.  Callers that demote on non-zero ranks must NOT cache
    a None-as-0 answer: before ``jax.distributed.initialize`` every host
    looks like process 0 (the MetricsLogger re-resolves until decidable).
    """
    jax = sys.modules.get("jax")
    if jax is None:
        return None
    try:
        from jax._src import xla_bridge

        if not xla_bridge._backends:
            return None
        return jax.process_index()
    except Exception:
        return None


def process_index() -> int:
    """Like :func:`process_index_or_none` but 0 when undecidable."""
    idx = process_index_or_none()
    return 0 if idx is None else idx


@dataclasses.dataclass
class SpanRecord:
    name: str
    span_id: int
    parent_id: int  # 0 = root
    depth: int
    t0_s: float  # relative to tracer start
    wall_s: float = 0.0
    items: float = 0.0
    unit: str = "items"
    attrs: dict = dataclasses.field(default_factory=dict)
    counters: dict = dataclasses.field(default_factory=dict)  # ledger deltas


class Tracer:
    """Span stack + completed-span log.  Host code here is single-threaded
    (the pipeline drivers), so a plain list stack suffices."""

    def __init__(self, ledger=None, on_end=None) -> None:
        self._ledger = ledger
        self._on_end = on_end
        self._t0 = time.perf_counter()
        self._stack: list[SpanRecord] = []
        self._next_id = 1
        self.spans: list[SpanRecord] = []
        self.dropped = 0

    @property
    def current(self) -> Optional[SpanRecord]:
        return self._stack[-1] if self._stack else None

    @contextlib.contextmanager
    def span(
        self, name: str, items: float = 0.0, unit: str = "items", **attrs
    ) -> Iterator[SpanRecord]:
        parent = self._stack[-1] if self._stack else None
        sp = SpanRecord(
            name=name,
            span_id=self._next_id,
            parent_id=parent.span_id if parent else 0,
            depth=len(self._stack),
            t0_s=time.perf_counter() - self._t0,
            items=items,
            unit=unit,
            attrs=dict(attrs),
        )
        self._next_id += 1
        snap = self._ledger.snapshot() if self._ledger is not None else None
        self._stack.append(sp)
        try:
            yield sp
        finally:
            sp.wall_s = time.perf_counter() - self._t0 - sp.t0_s
            if snap is not None:
                sp.counters = self._ledger.delta(snap)
            self._stack.pop()
            if len(self.spans) < MAX_SPANS:
                self.spans.append(sp)
            else:
                self.dropped += 1
            if self._on_end is not None:
                self._on_end(sp)

    # -- Chrome-trace export ------------------------------------------------

    def to_chrome_trace(self) -> dict:
        """Chrome-trace JSON object (the ``traceEvents`` array form) loadable
        by chrome://tracing and Perfetto."""
        pid = process_index()
        events: list[dict] = [
            {
                "name": "process_name",
                "ph": "M",
                "pid": pid,
                "tid": 0,
                "args": {"name": f"cpgisland host {pid}"},
            }
        ]
        for sp in self.spans:
            args = {"items": sp.items, "unit": sp.unit, **sp.attrs, **sp.counters}
            events.append(
                {
                    "name": sp.name,
                    "ph": "X",
                    "ts": round(sp.t0_s * 1e6, 3),
                    "dur": round(sp.wall_s * 1e6, 3),
                    "pid": pid,
                    "tid": 0,
                    "args": args,
                }
            )
        return {"traceEvents": events, "displayTimeUnit": "ms"}

    def write_chrome_trace(self, path: str) -> None:
        with open(path, "w") as f:
            json.dump(self.to_chrome_trace(), f)
