"""Streaming SLO metrics: fixed-layout log-binned histograms + serve rollups.

``Histogram`` is the single primitive: a fixed log-spaced bin layout shared
by every instance (so any two histograms merge exactly — integer bin-count
addition, associative and lossless), plus exact count/sum/min/max tracked
alongside the bins.  Quantiles are bin estimates (geometric bin midpoint,
clamped to the observed [min, max]); the layout's quarter-octave growth
bounds the relative error of any quantile at ~9%.

``ServeMetrics`` is the serve-layer rollup: queue->result latency, flush
size/occupancy/wall histograms, and per-tenant/per-model/per-device request
+ symbol throughput counters.  Everything here is lock-disciplined for the
Layer-4 rules: each histogram owns one leaf lock, ``ServeMetrics`` owns one
leaf lock for the throughput table, and no I/O or foreign-lock acquisition
ever happens under either (merge copies the source under its own lock
FIRST, then folds into the destination — sequential, never nested, so the
lock graph stays edge-free).
"""

from __future__ import annotations

import math
import threading
from typing import Dict, List, Tuple

# One shared layout so all histograms are merge-compatible.  Bin i covers
# [LO * 2**(i*LOG2_GROWTH), LO * 2**((i+1)*LOG2_GROWTH)); quarter-octave
# bins (~19% wide) over 72 octaves span 1e-9 .. ~4.7e12 — microsecond
# latencies and multi-Gi symbol counts both land in-range.
LO = 1e-9
LOG2_GROWTH = 0.25
N_BINS = 288

_INV_LOG2_GROWTH = 1.0 / LOG2_GROWTH
_LOG2_LO = math.log2(LO)


def bin_index(value: float) -> int:
    """Bin for ``value`` under the shared layout (clamped at both ends)."""
    if not value > LO:  # catches <=LO, 0, negatives and NaN
        return 0
    i = int((math.log2(value) - _LOG2_LO) * _INV_LOG2_GROWTH)
    return min(max(i, 0), N_BINS - 1)


def bin_edges(i: int) -> Tuple[float, float]:
    lo = LO * 2.0 ** (i * LOG2_GROWTH)
    return lo, LO * 2.0 ** ((i + 1) * LOG2_GROWTH)


class Histogram:
    """Fixed-layout log-binned histogram; exact merge, estimated quantiles."""

    __slots__ = ("_lock", "_counts", "count", "sum", "min", "max")

    def __init__(self) -> None:
        self._lock = threading.Lock()
        # Sparse: bin index -> count.  Serve latency distributions touch a
        # handful of the 288 bins; a dict keeps wire forms small.
        self._counts: Dict[int, int] = {}
        self.count = 0
        self.sum = 0.0
        self.min = math.inf
        self.max = -math.inf

    # -- writers -------------------------------------------------------------

    def observe(self, value: float) -> None:
        v = float(value)
        i = bin_index(v)
        with self._lock:  # graftsync: leaf lock, no I/O below
            self._counts[i] = self._counts.get(i, 0) + 1
            self.count += 1
            self.sum += v
            if v < self.min:
                self.min = v
            if v > self.max:
                self.max = v

    def merge(self, other: "Histogram") -> "Histogram":
        """Fold ``other`` into self.  Exact: integer bin adds.

        Locks are taken sequentially (copy other, then update self), never
        nested — no lock-order edge between histogram instances.
        """
        counts, count, total, mn, mx = other._copy()
        with self._lock:
            for i, c in counts.items():
                self._counts[i] = self._counts.get(i, 0) + c
            self.count += count
            self.sum += total
            if mn < self.min:
                self.min = mn
            if mx > self.max:
                self.max = mx
        return self

    # -- readers -------------------------------------------------------------

    def _copy(self) -> Tuple[Dict[int, int], int, float, float, float]:
        with self._lock:
            return dict(self._counts), self.count, self.sum, self.min, self.max

    def quantile(self, q: float) -> float:
        """Estimated q-quantile: geometric midpoint of the holding bin,
        clamped to the exact observed [min, max]."""
        counts, count, _, mn, mx = self._copy()
        if count == 0:
            return 0.0
        target = max(1, math.ceil(q * count))
        cum = 0
        for i in sorted(counts):
            cum += counts[i]
            if cum >= target:
                lo, hi = bin_edges(i)
                mid = math.sqrt(lo * hi)
                return min(max(mid, mn), mx)
        return mx

    def snapshot(self) -> dict:
        counts, count, total, mn, mx = self._copy()
        if count == 0:
            return {"count": 0, "sum": 0.0, "mean": 0.0, "min": 0.0,
                    "max": 0.0, "p50": 0.0, "p95": 0.0, "p99": 0.0}
        return {
            "count": count,
            "sum": total,
            "mean": total / count,
            "min": mn,
            "max": mx,
            "p50": self.quantile(0.50),
            "p95": self.quantile(0.95),
            "p99": self.quantile(0.99),
        }

    # -- wire form (kind=stats responses, sidecar snapshots, merges) ---------

    def to_wire(self) -> dict:
        counts, count, total, mn, mx = self._copy()
        return {
            "layout": {"lo": LO, "log2_growth": LOG2_GROWTH, "n_bins": N_BINS},
            "bins": {str(i): c for i, c in sorted(counts.items())},
            "count": count,
            "sum": total,
            "min": None if count == 0 else mn,
            "max": None if count == 0 else mx,
        }

    @classmethod
    def from_wire(cls, wire: dict) -> "Histogram":
        lay = wire.get("layout", {})
        if (lay.get("lo"), lay.get("log2_growth"), lay.get("n_bins")) != (
            LO, LOG2_GROWTH, N_BINS,
        ):
            raise ValueError(f"incompatible histogram layout: {lay!r}")
        h = cls()
        h._counts = {int(i): int(c) for i, c in wire.get("bins", {}).items()}
        h.count = int(wire.get("count", 0))
        h.sum = float(wire.get("sum", 0.0))
        mn, mx = wire.get("min"), wire.get("max")
        h.min = math.inf if mn is None else float(mn)
        h.max = -math.inf if mx is None else float(mx)
        return h


class ServeMetrics:
    """Serve-layer SLO rollup: latency/flush histograms + throughput table.

    The histograms carry their own leaf locks; ``_lock`` guards only the
    per-(scope, key) throughput counters.  No I/O under any of them.
    """

    def __init__(self) -> None:
        self.latency_s = Histogram()       # queue->result wall per request
        self.flush_symbols = Histogram()   # symbols per flush
        self.flush_requests = Histogram()  # occupancy: requests per flush
        self.flush_wall_s = Histogram()    # device wall per flush
        self._lock = threading.Lock()
        # (scope, key) -> [requests, symbols]; scope in tenant/model/device.
        self._through: Dict[Tuple[str, str], List[int]] = {}

    def note_result(self, *, tenant: str, model: str, device: str,
                    n_symbols: int, latency_s: float,
                    host: str = "") -> None:
        self.latency_s.observe(latency_s)
        keys = (("tenant", tenant or "-"), ("model", model or "-"),
                ("device", device or "-"))
        if host:
            # Host scope only under a routing tier — single-broker daemons
            # keep their exact legacy wire shape (snapshots/merges handle
            # arbitrary scopes, so the conditional key merges fine).
            keys += (("host", host),)
        with self._lock:  # graftsync: leaf lock, no I/O below
            for key in keys:
                ent = self._through.get(key)
                if ent is None:
                    ent = self._through[key] = [0, 0]
                ent[0] += 1
                ent[1] += int(n_symbols)

    def note_flush(self, *, n_requests: int, symbols: int,
                   wall_s: float) -> None:
        self.flush_requests.observe(float(n_requests))
        self.flush_symbols.observe(float(symbols))
        self.flush_wall_s.observe(wall_s)

    def merge(self, other: "ServeMetrics") -> "ServeMetrics":
        self.latency_s.merge(other.latency_s)
        self.flush_symbols.merge(other.flush_symbols)
        self.flush_requests.merge(other.flush_requests)
        self.flush_wall_s.merge(other.flush_wall_s)
        with other._lock:
            src = {k: list(v) for k, v in other._through.items()}
        with self._lock:
            for key, (nreq, nsym) in src.items():
                ent = self._through.get(key)
                if ent is None:
                    ent = self._through[key] = [0, 0]
                ent[0] += nreq
                ent[1] += nsym
        return self

    def throughput(self) -> dict:
        with self._lock:
            items = sorted(self._through.items())
        out: Dict[str, dict] = {}
        for (scope, key), (nreq, nsym) in items:
            out.setdefault(scope, {})[key] = {"requests": nreq, "symbols": nsym}
        return out

    def snapshot(self) -> dict:
        return {
            "latency_s": self.latency_s.snapshot(),
            "flush_symbols": self.flush_symbols.snapshot(),
            "flush_requests": self.flush_requests.snapshot(),
            "flush_wall_s": self.flush_wall_s.snapshot(),
            "throughput": self.throughput(),
        }

    def to_wire(self) -> dict:
        return {
            "latency_s": self.latency_s.to_wire(),
            "flush_symbols": self.flush_symbols.to_wire(),
            "flush_requests": self.flush_requests.to_wire(),
            "flush_wall_s": self.flush_wall_s.to_wire(),
            "throughput": self.throughput(),
        }

    @classmethod
    def from_wire(cls, wire: dict) -> "ServeMetrics":
        m = cls()
        m.latency_s = Histogram.from_wire(wire["latency_s"])
        m.flush_symbols = Histogram.from_wire(wire["flush_symbols"])
        m.flush_requests = Histogram.from_wire(wire["flush_requests"])
        m.flush_wall_s = Histogram.from_wire(wire["flush_wall_s"])
        with m._lock:
            for scope, table in wire.get("throughput", {}).items():
                for key, ent in table.items():
                    m._through[(scope, key)] = [
                        int(ent["requests"]), int(ent["symbols"])]
        return m


__all__ = [
    "LO", "LOG2_GROWTH", "N_BINS", "bin_index", "bin_edges",
    "Histogram", "ServeMetrics",
]
