"""Render obs metrics into the end-of-run report table.

Two entry points share one renderer: :func:`render_summary` formats a live
``Observer.summary()`` dict (the CLI's ``--obs-report``), and
:func:`summarize_jsonl` rebuilds the same structure from a metrics JSONL
file on disk (``tools/obs_report.py``) — so a production run's phase walls,
dispatch/compile counts, transfer bytes, and per-phase engine choices are
reconstructable from the metrics stream alone, with no live process.
"""

from __future__ import annotations

import json
from typing import IO, Iterable, Union

_LEDGER_KEYS = ("compiles", "compile_s", "dispatches", "fetch_bytes", "upload_bytes")
_MAX_REPORT_TRACES = 10_000


def summarize_jsonl(source: Union[str, IO[str], Iterable[str]]) -> dict:
    """Aggregate a metrics JSONL stream into an Observer.summary()-shaped
    dict.  Span records aggregate by name; ``engine_decision`` (and other
    deduped) events count by payload; a trailing ``obs_summary`` record, when
    present, supplies authoritative ledger totals and dedupe counts (the
    stream only carries first occurrences of deduped events)."""
    own = isinstance(source, str)
    f = open(source) if own else source
    spans: dict = {}
    decisions: dict = {}
    engine_by_span: dict = {}
    ledger: dict = {}
    violations: list = []
    traces: list = []
    slo = None
    summary_rec = None
    try:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except ValueError:
                continue  # a clipped tail line must not sink the report
            ev = rec.get("event")
            if ev == "span":
                name = rec.get("name", "?")
                a = spans.setdefault(
                    name,
                    {
                        "count": 0, "wall_s": 0.0, "items": 0.0,
                        "unit": rec.get("unit", "items"),
                        "compiles": 0, "compile_s": 0.0, "dispatches": 0,
                        "fetch_bytes": 0, "upload_bytes": 0,
                    },
                )
                a["count"] += 1
                a["wall_s"] += rec.get("wall_s", 0.0)
                a["items"] += rec.get("items", 0.0)
                for k in _LEDGER_KEYS:
                    a[k] += rec.get(k, 0)
            elif ev == "engine_decision":
                label = "engine_decision{" + ", ".join(
                    f"{k}={rec[k]}"
                    for k in sorted(rec)
                    if k not in ("ts", "event", "process_index")
                ) + "}"
                decisions[label] = decisions.get(label, 0) + 1
                if rec.get("span") and rec.get("choice") is not None:
                    engine_by_span.setdefault(rec["span"], set()).add(
                        f"{rec.get('site')}->{rec.get('choice')}"
                    )
            elif ev == "request_trace":
                if len(traces) < _MAX_REPORT_TRACES:
                    traces.append(rec)
            elif ev == "slo_snapshot":
                slo = rec  # last one wins: the freshest rollup
            elif ev == "obs_summary":
                summary_rec = rec
    finally:
        if own:
            f.close()
    if summary_rec is not None:
        ledger = summary_rec.get("ledger", {})
        # The stream carries only first occurrences of deduped events; the
        # summary has the true counts.
        decisions = summary_rec.get("decisions", decisions)
        violations = summary_rec.get("watchdog_violations", [])
    out = {
        "spans": spans,
        "ledger": ledger,
        "decisions": decisions,
        "watchdog_violations": violations,
        "engine_by_span": {k: sorted(v) for k, v in engine_by_span.items()},
        "request_traces": traces,
        "slo": slo,
    }
    if summary_rec is not None:
        out["process_index"] = summary_rec.get("process_index", 0)
    return out


def _mb(n: float) -> str:
    return f"{n / 2**20:.1f}"


def render_summary(summary: dict) -> str:
    """One fixed-width table: per-phase wall, items, throughput, dispatches,
    compiles, transfer bytes — then engine decisions, ledger totals, and any
    watchdog violations."""
    lines = []
    spans = summary.get("spans", {})
    hdr = (
        f"{'phase':<16}{'count':>6}{'wall_s':>9}{'items':>14}{'Msym/s':>9}"
        f"{'disp':>6}{'comp':>6}{'comp_s':>8}{'fetchMB':>9}{'upMB':>8}"
    )
    lines.append(hdr)
    lines.append("-" * len(hdr))
    for name, a in spans.items():
        tput = a["items"] / a["wall_s"] / 1e6 if a["wall_s"] > 0 and a["items"] else 0.0
        lines.append(
            f"{name:<16}{a['count']:>6}{a['wall_s']:>9.3f}{a['items']:>14.0f}"
            f"{tput:>9.1f}{a['dispatches']:>6}{a['compiles']:>6}"
            f"{a['compile_s']:>8.3f}{_mb(a['fetch_bytes']):>9}"
            f"{_mb(a['upload_bytes']):>8}"
        )
    engine_by_span = summary.get("engine_by_span") or {}
    if engine_by_span:
        lines.append("")
        lines.append("engine per phase:")
        for name, choices in engine_by_span.items():
            lines.append(f"  {name}: {'; '.join(choices)}")
    decisions = summary.get("decisions", {})
    if decisions:
        lines.append("")
        lines.append("decisions:")
        for label, n in decisions.items():
            lines.append(f"  {n:>6}x {label}")
    ledger = summary.get("ledger", {})
    if ledger:
        lines.append("")
        lines.append(
            "ledger totals: "
            f"compiles={ledger.get('compiles', 0)} "
            f"({ledger.get('compile_s', 0.0):.2f}s), "
            f"cache_hits={ledger.get('cache_hits', 0)}, "
            f"dispatches={ledger.get('dispatches', 0)}, "
            f"fetched {_mb(ledger.get('fetch_bytes', 0))} MB, "
            f"uploaded {_mb(ledger.get('upload_bytes', 0))} MB"
        )
    pc = summary.get("prepared_cache")
    if pc:
        lines.append("")
        lines.append(
            "prepared cache: "
            f"hits={pc.get('hits', 0)}, misses={pc.get('misses', 0)}, "
            f"entries={pc.get('entries', 0)} "
            f"({_mb(pc.get('resident_bytes', 0))} MB resident), "
            f"evictions dead/capacity/explicit="
            f"{pc.get('evictions_dead', 0)}/"
            f"{pc.get('evictions_capacity', 0)}/"
            f"{pc.get('evictions_explicit', 0)}"
        )
    viol = summary.get("watchdog_violations", [])
    if viol:
        lines.append("")
        lines.append(f"WATCHDOG: {len(viol)} implausible-throughput flag(s):")
        for v in viol:
            lines.append(
                f"  {v['name']}: {v['msym_per_s']} Msym/s "
                f"(ceiling {v['ceiling_msym_per_s']})"
            )
    return "\n".join(lines)


def render_lineage(traces: list, request_id: int | None = None) -> str:
    """graftscope lineage: per-request hop tables (relative wall per hop)
    followed by per-flush composition (which requests rode which flush on
    which device).  ``request_id`` filters to one request's trace."""
    lines: list = []
    flushes: dict = {}
    shown = 0
    for tr in traces:
        hops = tr.get("hops") or []
        for h in hops:
            if h.get("hop") == "flush.enter" and h.get("flush") is not None:
                ent = flushes.setdefault(
                    h["flush"],
                    {"device": h.get("device", ""), "ids": [], "routes": {}},
                )
                ent["ids"].append(tr.get("id"))
                r = tr.get("route", "")
                ent["routes"][r] = ent["routes"].get(r, 0) + 1
        if request_id is not None and tr.get("id") != request_id:
            continue
        shown += 1
        head = (
            f"request {tr.get('id')} tenant={tr.get('tenant')} "
            f"kind={tr.get('kind')} model={tr.get('model') or '-'} "
            f"route={tr.get('route')} device={tr.get('device') or '-'} "
            f"ok={tr.get('ok')} n_symbols={tr.get('n_symbols')} "
            f"latency={1e3 * (tr.get('latency_s') or 0.0):.2f} ms"
        )
        lines.append(head)
        t0 = hops[0].get("t") if hops else None
        for h in hops:
            dt = 0.0 if t0 is None else (h.get("t", t0) - t0)
            extra = ", ".join(
                f"{k}={v}" for k, v in h.items()
                if k not in ("hop", "t") and v not in (None, "")
            )
            lines.append(f"  +{1e3 * dt:>9.3f} ms  {h.get('hop'):<16} {extra}")
    if request_id is not None and shown == 0:
        lines.append(f"request {request_id}: no trace in this stream")
    if request_id is None and flushes:
        lines.append("")
        lines.append("flush composition:")
        for fid in sorted(flushes):
            ent = flushes[fid]
            routes = ", ".join(
                f"{r or '?'}x{n}" for r, n in sorted(ent["routes"].items())
            )
            lines.append(
                f"  flush {fid} device={ent['device'] or '-'} "
                f"requests={len(ent['ids'])} [{routes}] "
                f"ids={sorted(i for i in ent['ids'] if i is not None)}"
            )
    return "\n".join(lines)


def render_slo(slo: dict) -> str:
    """One block per histogram from an slo_snapshot record (or a live
    Scope.snapshot()['metrics'])."""
    m = slo.get("slo", slo)  # accept the raw JSONL record or the rollup
    if "latency_s" not in m:
        m = m.get("metrics", {})
    lines = ["slo snapshot:"]
    for key, unit, scale in (
        ("latency_s", "ms", 1e3), ("flush_wall_s", "ms", 1e3),
        ("flush_symbols", "sym", 1), ("flush_requests", "req", 1),
    ):
        s = m.get(key)
        if not s or not s.get("count"):
            continue
        lines.append(
            f"  {key:<16} n={s['count']:<7} p50={scale * s['p50']:.2f} {unit}"
            f"  p95={scale * s['p95']:.2f} {unit}"
            f"  p99={scale * s['p99']:.2f} {unit}"
            f"  max={scale * s['max']:.2f} {unit}"
        )
    thr = m.get("throughput") or {}
    for scope_name, table in sorted(thr.items()):
        row = ", ".join(
            f"{k}: {v['requests']} req / {v['symbols']} sym"
            for k, v in sorted(table.items())
        )
        lines.append(f"  by {scope_name}: {row}")
    return "\n".join(lines)


def render_flight(dump: Union[str, dict]) -> str:
    """Render a flight-recorder artifact (the ``*.flight.json`` a dying or
    shutting-down daemon persists) as a readable event timeline."""
    if isinstance(dump, str):
        with open(dump) as f:
            dump = json.load(f)
    events = dump.get("events", [])
    lines = [
        f"flight recorder: reason={dump.get('reason')} pid={dump.get('pid')} "
        f"{len(events)} event(s) (of {dump.get('events_seen')} seen, "
        f"ring capacity {dump.get('capacity')})"
    ]
    t0 = events[0].get("t") if events else None
    for ev in events:
        dt = 0.0 if t0 is None else ev.get("t", t0) - t0
        extra = ", ".join(
            f"{k}={v}" for k, v in ev.items()
            if k not in ("kind", "t") and v not in (None, "")
        )
        lines.append(f"  +{dt:>9.3f} s  {ev.get('kind'):<20} {extra}")
    return "\n".join(lines)


def render_file(path: str, request_id: int | None = None) -> str:
    summary = summarize_jsonl(path)
    parts = [render_summary(summary)]
    if summary.get("slo"):
        parts.append(render_slo(summary["slo"]))
    if summary.get("request_traces"):
        parts.append("request lineage:")
        parts.append(render_lineage(summary["request_traces"], request_id))
    return "\n\n".join(parts)
