"""Plausibility watchdog: flag relay-phantom throughputs in ANY run.

bench.py learned the hard way (CLAUDE.md r4) that the dev relay can serve
PHANTOM ~0 ms results — ``block_until_ready`` returning without execution —
which inflate throughput 5-100x.  Its defense, ``_check_plausible``, only
protected benchmarks; this module generalizes it into the library so any
instrumented run (an Observer span with ``unit="sym"``) is checked against
per-path ceilings derived from the enforced BASELINE.md marker figures.

Ceiling = ``factor`` (default 2.5) x the published Msym/s for that path —
tight enough that a phantom inflating one path 5x is flagged, loose enough
that genuine run-to-run variance never is — with a global
``PLAUSIBLE_MAX_SYM_PER_S`` net above everything.  A flagged span means the
numbers (and possibly the RESULTS — a phantom dispatch never executed) of
that region are suspect: re-run in a fresh process.

BASELINE.md markers are parsed with the same ``<!--num:key-->`` format
tools/pubnum.py owns (tests assert the two regexes agree so they cannot
drift).  When the repo docs aren't present (installed package), ceilings
degrade to the global net only.

No jax import: pure host-side arithmetic on span (items, seconds).
"""

from __future__ import annotations

import logging
import os
import re
from typing import Optional

log = logging.getLogger(__name__)

# Must stay textually identical to tools/pubnum.py::_NUM_RE (drift-guarded
# by tests/test_obs.py).
NUM_RE = re.compile(r"<!--num:([\w.]+)-->([-\d.]+)<!--/num-->")

# No single-chip path on this hardware exceeds ~2.2 Gsym/s; anything past
# this outer net is a phantom result, not a measurement.
PLAUSIBLE_MAX_SYM_PER_S = 20e9

DEFAULT_CEILING_FACTOR = 2.5

# bench.py path name -> enforced BASELINE.md marker key.
PATH_BASELINE_KEY = {
    "decode": "decode_msym",
    "decode-2state": "decode2_msym",
    "em": "em_msym",
    "em-2state": "em2_msym",
    "em-seq": "em_seq_msym",
    "em-seq2d": "em_seq2d_msym",
    "posterior": "posterior_msym",
    "batched-decode": "batched_msym",
}

# Observer span name -> bench path whose ceiling applies.  Pipeline spans
# include host work the kernel figures don't, so real runs sit far BELOW
# these ceilings — only a phantom (or a >2.5x breakthrough) crosses them.
SPAN_PATH = {
    "decode": "decode",
    "decode+islands": "decode",
    "posterior": "posterior",
    "span-totals": "posterior",
    "em_iter": "em",
    # The fused trainer's one span covers K iterations; its items are
    # iteration-scaled (n_sym * iters), so the per-iteration em ceiling
    # applies to it directly.
    "em_fused": "em",
}


def _repo_root() -> str:
    return os.path.dirname(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    )


def baseline_numbers(baseline_path: Optional[str] = None) -> dict:
    """{marker key: float} parsed from BASELINE.md; {} when unavailable."""
    if baseline_path is None:
        baseline_path = os.path.join(_repo_root(), "BASELINE.md")
    try:
        with open(baseline_path) as f:
            text = f.read()
    except OSError:
        return {}
    out = {}
    for key, val in NUM_RE.findall(text):
        try:
            out[key] = float(val)
        except ValueError:
            continue
    return out


def path_ceilings(
    factor: float = DEFAULT_CEILING_FACTOR,
    baseline_path: Optional[str] = None,
) -> dict:
    """{bench path: ceiling in sym/s} from the enforced marker figures."""
    nums = baseline_numbers(baseline_path)
    return {
        path: factor * nums[key] * 1e6
        for path, key in PATH_BASELINE_KEY.items()
        if key in nums
    }


class ImplausibleThroughput(RuntimeError):
    pass


class Watchdog:
    """Per-span plausibility checks.

    mode: "off" — disabled; "warn" (library default) — log + count, the run
    continues (production must not crash on a measurement anomaly, but the
    metrics stream records it); "raise" — bench behavior, the phase aborts
    so a phantom can never enter a captured artifact.
    """

    def __init__(
        self,
        mode: str = "warn",
        factor: float = DEFAULT_CEILING_FACTOR,
        baseline_path: Optional[str] = None,
    ) -> None:
        if mode not in ("off", "warn", "raise"):
            raise ValueError(f"watchdog mode must be off|warn|raise, got {mode!r}")
        self.mode = mode
        self.factor = factor
        self._baseline_path = baseline_path
        self._ceilings: Optional[dict] = None
        self.violations: list[dict] = []

    def _path_ceiling(self, path: Optional[str]) -> float:
        if self._ceilings is None:
            self._ceilings = path_ceilings(self.factor, self._baseline_path)
        return self._ceilings.get(path, float("inf")) if path else float("inf")

    @staticmethod
    def _n_devices() -> int:
        """Local device count WITHOUT initializing a backend (1 when
        undecidable).  The marker figures are SINGLE-CHIP rates; a pipeline
        span legitimately sustains ~n_devices x that on a mesh, so per-path
        ceilings scale by it — a relay phantom still lands orders of
        magnitude above."""
        import sys

        jax = sys.modules.get("jax")
        if jax is None:
            return 1
        try:
            from jax._src import xla_bridge

            if not xla_bridge._backends:
                return 1
            return max(1, jax.local_device_count())
        except Exception:
            return 1

    def check(
        self, name: str, items: float, seconds: float, path: Optional[str] = None
    ) -> Optional[dict]:
        """Check one measurement; returns the violation record (also kept in
        ``self.violations``) or None.  ``path`` defaults to the SPAN_PATH
        mapping for ``name``."""
        if self.mode == "off" or items <= 0 or seconds <= 0:
            return None
        tput = items / seconds
        path = path if path is not None else SPAN_PATH.get(name)
        ceiling = min(
            self._path_ceiling(path) * self._n_devices(),
            PLAUSIBLE_MAX_SYM_PER_S,
        )
        if tput <= ceiling:
            return None
        rec = {
            "name": name,
            "path": path,
            "msym_per_s": round(tput / 1e6, 1),
            "ceiling_msym_per_s": round(ceiling / 1e6, 1),
        }
        self.violations.append(rec)
        msg = (
            f"implausible throughput in {name!r}: {tput/1e6:.1f} Msym/s exceeds "
            f"the {ceiling/1e6:.0f} Msym/s ceiling "
            f"({self.factor}x the enforced BASELINE.md figure for "
            f"{path!r})" if ceiling < PLAUSIBLE_MAX_SYM_PER_S else
            f"implausible throughput in {name!r}: {tput/1e6:.1f} Msym/s exceeds "
            f"the global {PLAUSIBLE_MAX_SYM_PER_S/1e6:.0f} Msym/s net"
        )
        msg += (
            " — likely a relay phantom result (a dispatch that never "
            "executed); results from this region are suspect, re-run in a "
            "fresh process"
        )
        if self.mode == "raise":
            raise ImplausibleThroughput(msg)
        log.warning("%s", msg)
        return rec
