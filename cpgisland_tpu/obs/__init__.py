"""Runtime telemetry subsystem (spans + ledger + engine decisions + watchdog).

The reference program has two log lines of observability total
(CpGIslandFinder.java:147,228); production-scale runs here need to answer
"where did the time, the round trips, and the compiles go, and which engine
actually ran" from a single metrics file.  This package is the layer the
whole stack reports through:

- :mod:`~cpgisland_tpu.obs.trace` — hierarchical span tracer (JSONL events +
  Chrome-trace/Perfetto export);
- :mod:`~cpgisland_tpu.obs.ledger` — dispatch & compile ledger (JAX hooks)
  and the :func:`no_new_compiles` recompile sentinel;
- :mod:`~cpgisland_tpu.obs.watchdog` — plausibility ceilings generalizing
  bench.py's ``_check_plausible`` into the library;
- engine-decision events: every ``resolve_*_engine`` choice, ``pick_lane_T``
  geometry, SEQ_SHARD_BUDGET rejection, pad-FIRST dense demotion, and island
  cap-overflow retry reports through :func:`event`, so a run's routing is
  reconstructable from its metrics stream.

The resilience layer (``cpgisland_tpu/resilience/``) reports through the
same stream: ``dispatch_fault`` / ``dispatch_slow`` (one per supervised
attempt — no unledgered retries), ``engine_degraded`` / ``engine_restored``
(circuit-breaker trips and recoveries, plus ``*.breaker_demotion``
engine-decision events at routing time), ``integrity_violation`` (phantom
sentinel detections), ``manifest_resume`` (records replayed from a resume
manifest), and ``invalid_symbols`` (codec policy counts).

**Off by default, zero device cost.**  Library call sites use the
module-level :func:`span` / :func:`event` / :func:`note_fetch` /
:func:`note_upload` helpers, which are no-ops (one global ``None`` check)
until an :class:`Observer` is installed — via :func:`observe`, the CLI's
``--metrics`` / ``--obs-report`` / ``--trace-dir`` flags, or bench.py's
``--metrics-out``.  Even when enabled, the subsystem only counts work that
already happens (it piggybacks on existing fetches and sync points) and
never issues a device dispatch of its own.

No jax import at module level: the CLI imports this before platform
selection.
"""

from __future__ import annotations

import contextlib
import logging
import os
import threading
from typing import Iterator, Optional

from cpgisland_tpu.obs import ledger as ledger_mod
from cpgisland_tpu.obs.ledger import (  # noqa: F401  (public re-exports)
    Ledger,
    RecompileError,
    device_scope,
    no_new_compiles,
)
from cpgisland_tpu.obs.trace import SpanRecord, Tracer, process_index
from cpgisland_tpu.obs.watchdog import Watchdog

log = logging.getLogger(__name__)

_ACTIVE: Optional["Observer"] = None


def current() -> Optional["Observer"]:
    return _ACTIVE


def enabled() -> bool:
    return _ACTIVE is not None


class Observer:
    """One observed region: tracer + ledger + metrics sink + watchdog.

    Use as a context manager (or through :func:`observe`).  Installing sets
    the module-level active observer that the no-op helpers route to;
    exiting uninstalls the JAX hooks, writes the Chrome trace (when
    ``trace_dir`` is given), and emits an ``obs_summary`` event with ledger
    totals, engine-decision counts, and watchdog violations.
    """

    def __init__(
        self,
        metrics=None,
        trace_dir: Optional[str] = None,
        watchdog: str = "warn",
    ) -> None:
        from cpgisland_tpu.utils import profiling

        if isinstance(metrics, str):
            metrics = profiling.MetricsLogger(metrics)
            self._own_metrics = True
        else:
            self._own_metrics = False
        self.metrics = metrics if metrics is not None else profiling.null()
        self.trace_dir = trace_dir
        self.ledger = Ledger()
        self.tracer = Tracer(ledger=self.ledger, on_end=self._on_span_end)
        self.watchdog = Watchdog(mode=watchdog)
        # Event state behind one lock: serve's transport threads emit
        # rejection events while the worker loop emits serve_flush and
        # Session.close emits prepared_evict — the same multi-writer reality
        # the Ledger lock covers one layer down.  Each critical section is a
        # few dict/list ops; metrics I/O stays outside it.
        self._events_lock = threading.Lock()
        self.events: list[dict] = []
        self._event_counts: dict = {}
        self._dropped_events = 0
        self._uninstall = None

    # -- lifecycle ----------------------------------------------------------

    def __enter__(self) -> "Observer":
        global _ACTIVE
        if _ACTIVE is not None:
            raise RuntimeError("an Observer is already active (no nesting)")
        from cpgisland_tpu.obs import ledger as ledger_mod

        self._uninstall = ledger_mod.install(self.ledger)
        _ACTIVE = self
        self.metrics.log("obs_start", process_index=process_index())
        return self

    def __exit__(self, *exc) -> None:
        global _ACTIVE
        _ACTIVE = None
        if self._uninstall is not None:
            self._uninstall()
            self._uninstall = None
        self.metrics.log("obs_summary", **self.summary())
        if self.trace_dir:
            os.makedirs(self.trace_dir, exist_ok=True)
            path = os.path.join(self.trace_dir, "trace.json")
            self.tracer.write_chrome_trace(path)
            log.info("chrome trace written to %s (open in Perfetto)", path)
        if self._own_metrics:
            self.metrics.close()

    # -- emission -----------------------------------------------------------

    def _on_span_end(self, sp: SpanRecord) -> None:
        self.metrics.log(
            "span",
            name=sp.name,
            span_id=sp.span_id,
            parent_id=sp.parent_id,
            depth=sp.depth,
            wall_s=round(sp.wall_s, 6),
            items=sp.items,
            unit=sp.unit,
            **sp.attrs,
            **sp.counters,
        )
        if sp.unit == "sym":
            self.watchdog.check(sp.name, sp.items, sp.wall_s)

    # Memory bounds for degenerate inputs (spans have trace.MAX_SPANS):
    # distinct deduped payloads and retained non-deduped events are capped,
    # with overflow counted in the summary rather than growing unbounded.
    MAX_EVENTS = 10_000
    MAX_DISTINCT_DECISIONS = 10_000

    def emit_event(self, name: str, dedupe: bool = False, **fields) -> None:
        """Log a structured event, attributed to the innermost open span.

        ``dedupe=True`` (engine decisions, lane geometry) logs only the
        FIRST occurrence of an identical payload and counts the rest — a
        100k-scaffold file must not write 100k identical routing lines; the
        counts surface in ``obs_summary``.  Call sites must key deduped
        payloads on BOUNDED values (e.g. pow2 buckets, not raw lengths).
        """
        # Fleet attribution: events emitted on a device worker's thread carry
        # the originating device label (bounded set — dedupe keys stay safe).
        dev = ledger_mod.current_device()
        if dev and "device" not in fields:
            fields["device"] = dev
        if dedupe:
            key = (name, tuple(sorted(fields.items())))
            with self._events_lock:
                n = self._event_counts.get(key)
                if n is None and len(self._event_counts) >= self.MAX_DISTINCT_DECISIONS:
                    self._dropped_events += 1
                    return
                self._event_counts[key] = (n or 0) + 1
            if n:
                return
        cur = self.tracer.current
        rec = {"span": cur.name if cur else None, **fields}
        with self._events_lock:
            if len(self.events) < self.MAX_EVENTS:
                self.events.append({"event": name, **rec})
            else:
                self._dropped_events += 1
        self.metrics.log(name, **rec)

    # -- summary / report ---------------------------------------------------

    def _span_aggregate(self) -> dict:
        agg: dict = {}
        for sp in self.tracer.spans:
            a = agg.setdefault(
                sp.name,
                {
                    "count": 0,
                    "wall_s": 0.0,
                    "items": 0.0,
                    "unit": sp.unit,
                    "compiles": 0,
                    "compile_s": 0.0,
                    "dispatches": 0,
                    "fetch_bytes": 0,
                    "upload_bytes": 0,
                },
            )
            a["count"] += 1
            a["wall_s"] += sp.wall_s
            a["items"] += sp.items
            for k in ("compiles", "compile_s", "dispatches", "fetch_bytes", "upload_bytes"):
                a[k] += sp.counters.get(k, 0)
        for a in agg.values():
            a["wall_s"] = round(a["wall_s"], 4)
            a["compile_s"] = round(a["compile_s"], 4)
        return agg

    def _decision_counts(self) -> dict:
        with self._events_lock:
            counts = dict(self._event_counts)
        out: dict = {}
        for (name, fields), n in counts.items():
            label = name + "{" + ", ".join(f"{k}={v}" for k, v in fields) + "}"
            out[label] = n
        return out

    def summary(self) -> dict:
        with self._events_lock:
            dropped_events = self._dropped_events
        out = {
            "process_index": process_index(),
            "spans": self._span_aggregate(),
            "dropped_spans": self.tracer.dropped,
            "dropped_events": dropped_events,
            "ledger": self.ledger.totals(),
            "decisions": self._decision_counts(),
            "watchdog_violations": self.watchdog.violations,
        }
        # Prepared-stream cache lifecycle (hits/misses/evictions/occupancy):
        # long-lived serving processes watch resident_bytes/evictions here.
        # Lazy + guarded: obs must stay importable before jax/platform
        # selection, and a summary must never fail on telemetry.
        try:
            from cpgisland_tpu.ops.prepared import cache_stats

            out["prepared_cache"] = cache_stats()
        except Exception:
            pass
        return out

    def report(self) -> str:
        """End-of-run report table (the CLI's ``--obs-report``)."""
        from cpgisland_tpu.obs import report as report_mod

        return report_mod.render_summary(self.summary())


@contextlib.contextmanager
def observe(
    metrics=None, trace_dir: Optional[str] = None, watchdog: str = "warn"
) -> Iterator[Observer]:
    """Install an Observer for a region: ``with obs.observe("m.jsonl"):``."""
    ob = Observer(metrics=metrics, trace_dir=trace_dir, watchdog=watchdog)
    with ob:
        yield ob


# -- zero-cost module-level helpers (the API library code calls) ------------


@contextlib.contextmanager
def span(name: str, items: float = 0.0, unit: str = "items", **attrs):
    ob = _ACTIVE
    if ob is None:
        yield None
        return
    with ob.tracer.span(name, items=items, unit=unit, **attrs) as sp:
        yield sp


def event(name: str, _dedupe: bool = False, **fields) -> None:
    ob = _ACTIVE
    if ob is None:
        return
    ob.emit_event(name, dedupe=_dedupe, **fields)


def engine_decision(site: str, choice: str, **fields) -> None:
    """Structured routing event — deduped (see Observer.emit_event)."""
    ob = _ACTIVE
    if ob is None:
        return
    ob.emit_event("engine_decision", dedupe=True, site=site, choice=choice, **fields)


def note_fetch(x):
    """Piggyback accounting for a device->host fetch that the caller is
    already performing (e.g. an ``np.asarray`` on a device array).  Returns
    its argument; adds NO dispatch of its own."""
    ob = _ACTIVE
    if ob is not None:
        from cpgisland_tpu.obs.ledger import _tree_nbytes

        ob.ledger.count_fetch(_tree_nbytes(x))
    return x


def note_upload(x):
    """Piggyback accounting for a host->device upload the caller is already
    performing (e.g. a ``jnp.asarray`` on a host array)."""
    ob = _ACTIVE
    if ob is not None:
        from cpgisland_tpu.obs.ledger import _tree_nbytes

        ob.ledger.count_upload(_tree_nbytes(x))
    return x
