"""Dispatch & compile ledger: count what crosses the host/device boundary.

On this project's dev setup every blocking dispatch pays a ~50-100 ms relay
round trip and every cache-miss compile ships program bytes over HTTP
(CLAUDE.md), so "how many dispatches / compiles / uploaded bytes did this
phase cost" is the first question any slow run raises.  The ledger answers
it without adding any device work of its own:

- **compiles** — a wrapper around ``jax._src.compiler.backend_compile`` (the
  single funnel every true cache-miss XLA compile passes through; in-memory
  jit cache hits and persistent-cache hits never reach it) records one
  entry per fresh executable with the MLIR module name, its abstract input
  types (the shapes — what you need to diagnose shape-driven recompiles),
  and compile wall time.  A ``jax.monitoring`` listener counts persistent
  compilation-cache hits alongside.
- **dispatches / bytes** — counting wrappers over the public blocking APIs
  (``jax.block_until_ready``, ``jax.device_get``, ``jax.device_put``) plus
  the ``count_fetch``/``count_upload`` piggyback hooks the pipeline calls at
  its existing ``np.asarray`` fetch sites.  Transfers routed through other
  entry points (e.g. ``jnp.asarray`` inside library internals) are NOT
  counted — the ledger is a lower bound by design, attributed where the
  pipeline already blocks, never a new sync point.

The :func:`no_new_compiles` recompile sentinel asserts a steady-state region
(e.g. EM iterations 2..N over fixed shapes) triggers zero fresh compiles,
reporting the offending module names + abstract shapes when it fires.

Everything installs/uninstalls explicitly; nothing is patched at import.
"""

from __future__ import annotations

import contextlib
import threading
import time
from typing import Iterator

_MAX_COMPILE_RECORDS = 4096

# Fleet attribution: which device's worker thread is currently executing.
# Thread-local by construction (each _DeviceWorker pins one label for its
# own thread), so reads need no lock; "" = unattributed (single-device /
# non-fleet paths, whose counters keep their exact legacy meaning).
_DEVICE = threading.local()


def current_device() -> str:
    return getattr(_DEVICE, "label", "")


@contextlib.contextmanager
def device_scope(label: str) -> Iterator[None]:
    """Attribute ledger counts + obs events on this thread to ``label``."""
    prev = getattr(_DEVICE, "label", "")
    _DEVICE.label = str(label)
    try:
        yield
    finally:
        _DEVICE.label = prev


# Pod attribution: which routed HOST's work is executing on this thread —
# the routing tier (serve/router.py) wraps each host's flush execution in
# host_scope, one fault-domain level above device_scope.  Same thread-local
# construction, same "" = unattributed legacy meaning.
_HOST = threading.local()


def current_host() -> str:
    return getattr(_HOST, "label", "")


@contextlib.contextmanager
def host_scope(label: str) -> Iterator[None]:
    """Attribute ledger counts on this thread to host ``label`` (composes
    with :func:`device_scope`: a fleet worker under a router carries
    both).  ``host_scope("")`` is a no-op wrapper."""
    prev = getattr(_HOST, "label", "")
    _HOST.label = str(label)
    try:
        yield
    finally:
        _HOST.label = prev


class RecompileError(RuntimeError):
    """A region asserted compile-free saw fresh XLA compiles."""

    def __init__(self, msg: str, records: list):
        super().__init__(msg)
        self.records = records


class Ledger:
    """Host-side counters behind one ledger lock.

    The serve subsystem made the host side multi-threaded (PR 8: the worker
    loop dispatches flushes while transport threads encode, submit, and
    fetch) — compile callbacks, ``note_fetch`` piggybacks, and span snapshot
    deltas now race without a mutex, and a torn ``+=`` silently undercounts
    the exact quantities the relay gotchas make load-bearing.  Every
    mutation and multi-field read takes ``_lock``; each is a few field ops,
    so ``no_new_compiles``/``note_fetch`` stay cheap on the hot path (one
    uncontended acquire, no allocation, no device work)."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self.compiles = 0
        self.compile_s = 0.0
        self.cache_hits = 0  # persistent compilation-cache hits
        self.dispatches = 0  # blocking host<->device round trips
        self.fetch_bytes = 0  # device -> host
        self.upload_bytes = 0  # host -> device
        self.compile_records: list[dict] = []
        # Per-device attribution (fleet): label -> counter dict.  Bumped
        # ALONGSIDE the global fields under the same lock — the globals keep
        # their exact legacy totals, devices are a partition of the tagged
        # subset.  "" (no device_scope active) is never stored.
        self.per_device: dict[str, dict] = {}
        # Per-HOST attribution (routing tier): same partition contract one
        # fault-domain level up — hosts partition the host_scope-tagged
        # subset; the globals stay the exact totals.
        self.per_host: dict[str, dict] = {}

    def _host_ent_locked(self) -> dict | None:
        # Caller holds self._lock.
        label = current_host()
        if not label:
            return None
        ent = self.per_host.get(label)
        if ent is None:
            ent = self.per_host[label] = {
                "compiles": 0, "dispatches": 0,
                "fetch_bytes": 0, "upload_bytes": 0,
            }
        return ent

    def _device_ent_locked(self) -> dict | None:
        # Caller holds self._lock.
        label = current_device()
        if not label:
            return None
        ent = self.per_device.get(label)
        if ent is None:
            ent = self.per_device[label] = {
                "compiles": 0, "dispatches": 0,
                "fetch_bytes": 0, "upload_bytes": 0,
            }
        return ent

    # -- recording ----------------------------------------------------------

    def record_compile(self, name: str, arg_types: list, secs: float) -> None:
        with self._lock:
            self.compiles += 1
            self.compile_s += secs
            ent = self._device_ent_locked()
            if ent is not None:
                ent["compiles"] += 1
            hent = self._host_ent_locked()
            if hent is not None:
                hent["compiles"] += 1
            if len(self.compile_records) < _MAX_COMPILE_RECORDS:
                self.compile_records.append(
                    {"name": name, "arg_types": arg_types,
                     "secs": round(secs, 4)}
                )

    def count_cache_hit(self) -> None:
        with self._lock:
            self.cache_hits += 1

    def count_dispatch(self) -> None:
        with self._lock:
            self.dispatches += 1
            ent = self._device_ent_locked()
            if ent is not None:
                ent["dispatches"] += 1
            hent = self._host_ent_locked()
            if hent is not None:
                hent["dispatches"] += 1

    def count_fetch(self, nbytes: int) -> None:
        with self._lock:
            self.dispatches += 1
            self.fetch_bytes += int(nbytes)
            ent = self._device_ent_locked()
            if ent is not None:
                ent["dispatches"] += 1
                ent["fetch_bytes"] += int(nbytes)
            hent = self._host_ent_locked()
            if hent is not None:
                hent["dispatches"] += 1
                hent["fetch_bytes"] += int(nbytes)

    def count_upload(self, nbytes: int) -> None:
        # An upload IS a round trip on the relay (and the docstring promises
        # device_put is a counted sync point) — count it as a dispatch too.
        with self._lock:
            self.dispatches += 1
            self.upload_bytes += int(nbytes)
            ent = self._device_ent_locked()
            if ent is not None:
                ent["dispatches"] += 1
                ent["upload_bytes"] += int(nbytes)
            hent = self._host_ent_locked()
            if hent is not None:
                hent["dispatches"] += 1
                hent["upload_bytes"] += int(nbytes)

    # -- span attribution ---------------------------------------------------

    def snapshot(self) -> tuple:
        with self._lock:
            return (
                self.compiles,
                self.compile_s,
                self.dispatches,
                self.fetch_bytes,
                self.upload_bytes,
            )

    def delta(self, snap: tuple) -> dict:
        with self._lock:
            return {
                "compiles": self.compiles - snap[0],
                "compile_s": round(self.compile_s - snap[1], 4),
                "dispatches": self.dispatches - snap[2],
                "fetch_bytes": self.fetch_bytes - snap[3],
                "upload_bytes": self.upload_bytes - snap[4],
            }

    def totals(self) -> dict:
        with self._lock:
            out = {
                "compiles": self.compiles,
                "compile_s": round(self.compile_s, 4),
                "cache_hits": self.cache_hits,
                "dispatches": self.dispatches,
                "fetch_bytes": self.fetch_bytes,
                "upload_bytes": self.upload_bytes,
            }
            if self.per_device:
                out["per_device"] = {
                    k: dict(v) for k, v in sorted(self.per_device.items())
                }
            if self.per_host:
                out["per_host"] = {
                    k: dict(v) for k, v in sorted(self.per_host.items())
                }
            return out

    def device_totals(self) -> dict:
        with self._lock:
            return {k: dict(v) for k, v in sorted(self.per_device.items())}

    def host_totals(self) -> dict:
        with self._lock:
            return {k: dict(v) for k, v in sorted(self.per_host.items())}


def _tree_nbytes(x) -> int:
    try:
        import jax

        return sum(
            getattr(leaf, "nbytes", 0) or 0
            for leaf in jax.tree_util.tree_leaves(x)
        )
    except Exception:
        return getattr(x, "nbytes", 0) or 0


def _module_info(args: tuple, kwargs: dict) -> tuple[str, list]:
    """(module name, abstract input types) of the MLIR module in a
    backend_compile call — best-effort, never raises (observability must not
    sink a compile)."""
    name, types = "<unknown>", []
    try:
        from jax._src.lib.mlir import ir

        mod = None
        for x in list(args) + list(kwargs.values()):
            if hasattr(x, "operation") and hasattr(x, "body"):
                mod = x
                break
        if mod is None:
            return name, types
        name = ir.StringAttr(mod.operation.attributes["sym_name"]).value
        for op in mod.body.operations:
            try:
                ftype = ir.FunctionType(
                    ir.TypeAttr(op.attributes["function_type"]).value
                )
                types = [str(t) for t in ftype.inputs[:16]]
                break
            except Exception:
                continue
    except Exception:
        pass
    return name, types


_installed_uninstall = None  # module-level: at most one ledger installed


def install(ledger: Ledger, compile_only: bool = False):
    """Install the JAX hooks feeding ``ledger``; returns an uninstall
    callable.  At most one ledger can be installed at a time (the Observer
    enforces a single active observer; the standalone sentinel installs only
    when no observer is active)."""
    global _installed_uninstall
    if _installed_uninstall is not None:
        raise RuntimeError("an obs Ledger is already installed")

    import jax
    from jax._src import compiler as _compiler

    state = {"live": True}
    orig_bc = _compiler.backend_compile

    def _backend_compile(*a, **k):
        t0 = time.perf_counter()
        out = orig_bc(*a, **k)
        secs = time.perf_counter() - t0
        if state["live"]:
            name, types = _module_info(a, k)
            ledger.record_compile(name, types, secs)
        return out

    _compiler.backend_compile = _backend_compile

    def _on_event(event: str, **kw) -> None:
        if state["live"] and event == "/jax/compilation_cache/cache_hits":
            ledger.count_cache_hit()

    jax.monitoring.register_event_listener(_on_event)

    restores = []
    if not compile_only:
        orig_block = jax.block_until_ready
        orig_get = jax.device_get
        orig_put = jax.device_put

        def block_until_ready(x):
            if state["live"]:
                ledger.count_dispatch()
            return orig_block(x)

        def device_get(x):
            if state["live"]:
                ledger.count_fetch(_tree_nbytes(x))
            return orig_get(x)

        def device_put(x, *a, **k):
            if state["live"]:
                ledger.count_upload(_tree_nbytes(x))
            return orig_put(x, *a, **k)

        jax.block_until_ready = block_until_ready
        jax.device_get = device_get
        jax.device_put = device_put
        restores = [
            ("block_until_ready", orig_block),
            ("device_get", orig_get),
            ("device_put", orig_put),
        ]

    def uninstall() -> None:
        global _installed_uninstall
        state["live"] = False
        _compiler.backend_compile = orig_bc
        for attr, orig in restores:
            setattr(jax, attr, orig)
        try:
            from jax._src import monitoring as _mon

            _mon._unregister_event_listener_by_callback(_on_event)
        except Exception:
            pass  # dead listener stays registered but inert (live flag)
        _installed_uninstall = None

    _installed_uninstall = uninstall
    return uninstall


@contextlib.contextmanager
def no_new_compiles(tag: str = "steady-state", allow: int = 0) -> Iterator[Ledger]:
    """Assert a region triggers no fresh XLA compiles (the recompile
    sentinel).  Reuses the active observer's ledger when one is installed,
    else installs a temporary compile-only hook.  Raises
    :class:`RecompileError` naming each fresh module and its abstract input
    shapes when more than ``allow`` compiles happen.
    """
    from cpgisland_tpu import obs

    ob = obs.current()
    if ob is not None:
        led: Ledger = ob.ledger
        un = None
    else:
        led = Ledger()
        un = install(led, compile_only=True)
    start = led.compiles
    try:
        yield led
        new = led.compiles - start
        if ob is not None:
            ob.emit_event("recompile_sentinel", tag=tag, new_compiles=new)
        if new > allow:
            fresh = led.compile_records[-min(new, len(led.compile_records)):]
            detail = "; ".join(
                f"{r['name']}({', '.join(r['arg_types'][:6])})" for r in fresh
            )
            raise RecompileError(
                f"recompile sentinel [{tag}]: {new} fresh XLA compile(s) in a "
                f"region asserted compile-free (allow={allow}): {detail}",
                fresh,
            )
    finally:
        if un is not None:
            un()
