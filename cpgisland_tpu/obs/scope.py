"""graftscope: request-scoped serve telemetry.

Three pieces, all off-by-default and dispatch-free:

- **Request lineage** (``Scope`` + module ``hop``/``complete``): a trace is
  minted at admission (keyed by the broker-assigned request id) and every
  serve-layer station appends a hop — ``admit``, ``journal.admit``,
  ``taken`` (queue residency), ``flush.enter`` (flush id + device + route
  group), ``executed`` (route + device wall), ``requeue`` (failover),
  ``journal.complete``, ``respond``.  Hops are plain dicts with a
  ``time.monotonic()`` stamp taken under the scope lock, so append order is
  timestamp order.  On completion the closed trace is emitted as ONE
  ``request_trace`` obs event (it lands in the existing ``--metrics``
  JSONL sink) and folded into the streaming SLO histograms.
- **Streaming SLO metrics**: ``Scope.metrics`` is an
  :class:`~cpgisland_tpu.obs.metrics.ServeMetrics` — mergeable log-binned
  histograms for queue->result latency and flush size/occupancy/wall plus
  per-tenant/per-model/per-device throughput.  Snapshots are served by the
  ``kind=stats`` wire request and the ``--metrics-interval`` emitter.
- **Flight recorder** (``FlightRecorder``): a bounded ring of the last N
  lineage/health/fault events, persisted atomically (tmp + ``os.replace``)
  next to the journal on shutdown, on ``SimulatedKill`` (graftfault tees
  into :func:`on_kill` before raising), and on unhandled worker death.

Lock discipline (Layer 4): ``Scope._lock`` and ``FlightRecorder._lock``
are leaves — nothing is acquired and no I/O happens while holding them
(persist snapshots under the lock, writes outside).  Broker/fleet/health
code calls in while holding their own locks, which only adds
``<owner> -> scope`` leaf edges to the cross-module graph.  The module
``_ACTIVE`` handle is read unlocked by design (same pattern as
``obs._ACTIVE`` / ``faultplan._ACTIVE``): installs happen at daemon/test
setup, and a stale read degrades to a dropped telemetry hop, never a
wrong serve result.
"""

from __future__ import annotations

import collections
import contextlib
import json
import os
import threading
import time
from typing import Any, Callable, Dict, List, Optional

from cpgisland_tpu import obs as _obs
from cpgisland_tpu.obs.metrics import ServeMetrics

DEFAULT_RING = 2048
DEFAULT_MAX_TRACES = 10_000


class FlightRecorder:
    """Bounded in-memory ring of telemetry events + atomic persistence."""

    def __init__(self, capacity: int = DEFAULT_RING,
                 path: Optional[str] = None) -> None:
        self.capacity = int(capacity)
        self.path = path
        self._lock = threading.Lock()
        self._ring: collections.deque = collections.deque(maxlen=self.capacity)
        self._seen = 0
        self._persists = 0

    def record(self, kind: str, **fields: Any) -> None:
        ev = {"t": time.time(), "kind": kind}
        ev.update(fields)
        with self._lock:  # graftsync: leaf lock, no I/O below
            self._ring.append(ev)
            self._seen += 1

    def snapshot(self) -> List[dict]:
        with self._lock:
            return list(self._ring)

    def stats(self) -> dict:
        with self._lock:
            return {"events": len(self._ring), "seen": self._seen,
                    "capacity": self.capacity, "persists": self._persists}

    def persist(self, reason: str, path: Optional[str] = None) -> Optional[str]:
        """Atomically write the ring next to the journal.  Best-effort: a
        postmortem writer must never turn a crash into a different crash."""
        dst = path or self.path
        if dst is None:
            return None
        with self._lock:
            events = list(self._ring)
            seen = self._seen
            self._persists += 1
        payload = {
            "reason": reason,
            "ts": time.time(),
            "pid": os.getpid(),
            "capacity": self.capacity,
            "events_seen": seen,
            "events": events,
        }
        tmp = f"{dst}.tmp.{os.getpid()}"
        try:
            with open(tmp, "w", encoding="utf-8") as f:
                json.dump(payload, f)
                f.flush()
                os.fsync(f.fileno())
            os.replace(tmp, dst)
            return dst
        except OSError:
            with contextlib.suppress(OSError):
                os.remove(tmp)
            return None


class Scope:
    """Per-request lineage + SLO rollup + flight recorder for one daemon."""

    def __init__(self, *, flight_path: Optional[str] = None,
                 ring_capacity: int = DEFAULT_RING,
                 max_traces: int = DEFAULT_MAX_TRACES) -> None:
        self._lock = threading.Lock()
        self._traces: Dict[int, dict] = {}      # live rid -> trace
        self.traces: List[dict] = []            # closed traces (bounded)
        self.max_traces = int(max_traces)
        self.dropped_traces = 0
        self._flush_seq = 0
        self.metrics = ServeMetrics()
        self.recorder = FlightRecorder(ring_capacity, flight_path)

    # -- lineage -------------------------------------------------------------

    def hop(self, rid: int, name: str, **fields: Any) -> None:
        h = {"hop": name}
        h.update(fields)
        with self._lock:  # graftsync: leaf lock, no I/O below
            tr = self._traces.get(rid)
            if tr is None:
                tr = self._traces[rid] = {"id": rid, "t0": time.monotonic(),
                                          "ts0": time.time(), "hops": []}
            if name == "admit":
                for k in ("tenant", "kind", "model", "n_symbols"):
                    if k in fields:
                        tr[k] = fields[k]
            h["t"] = time.monotonic()  # stamped under the lock: append
            tr["hops"].append(h)       # order IS timestamp order

    def next_flush_id(self) -> int:
        with self._lock:
            self._flush_seq += 1
            return self._flush_seq

    def complete(self, rid: int, *, ok: bool, route: str, fault: bool = False,
                 replayed: bool = False, n_symbols: int = 0,
                 device: str = "") -> None:
        now = time.monotonic()
        with self._lock:
            tr = self._traces.pop(rid, None)
            if tr is None:
                return
            latency = now - tr["t0"]
            tr["hops"].append({"hop": "respond", "ok": ok, "route": route,
                               "fault": fault, "replayed": replayed, "t": now})
            tr.update(ok=ok, route=route, fault=fault, replayed=replayed,
                      latency_s=latency)
            dev = device or tr.get("device", "")
            if not dev:
                # Last device-carrying hop wins: a requeued request is
                # attributed to the device that actually served it, not
                # the one that faulted it away.
                for h in reversed(tr["hops"]):
                    if h.get("device"):
                        dev = h["device"]
                        break
            tr["device"] = dev
            # Host memberships (routing tier): every "host" hop in order,
            # consecutive repeats collapsed — a failed-over request shows
            # BOTH hosts; the LAST one is the serving attribution.
            hosts: List[str] = []
            for h in tr["hops"]:
                if h.get("hop") == "host" and h.get("host"):
                    if not hosts or hosts[-1] != h["host"]:
                        hosts.append(str(h["host"]))
            if hosts:
                tr["hosts"] = hosts
            host = hosts[-1] if hosts else ""
            if len(self.traces) < self.max_traces:
                self.traces.append(tr)
            else:
                self.dropped_traces += 1
        # Below: metrics + event emission OUTSIDE the scope lock (the obs
        # event path takes the observer's own lock and may write JSONL).
        self.metrics.note_result(
            tenant=str(tr.get("tenant", "")), model=str(tr.get("model", "")),
            device=dev, host=host,
            n_symbols=int(tr.get("n_symbols", n_symbols) or 0),
            latency_s=latency)
        self.recorder.record(
            "request", id=rid, tenant=tr.get("tenant"), route=route, ok=ok,
            fault=fault, replayed=replayed, device=dev,
            **({"host": host} if host else {}),
            latency_ms=round(latency * 1e3, 3))
        _obs.event("request_trace", id=rid,
                   tenant=tr.get("tenant"), kind=tr.get("kind"),
                   model=tr.get("model"), n_symbols=tr.get("n_symbols"),
                   route=route, ok=ok, fault=fault, replayed=replayed,
                   device=dev, **({"hosts": hosts} if hosts else {}),
                   latency_s=round(latency, 6), hops=tr["hops"])

    def flush_done(self, fid: int, *, device: str, n_requests: int,
                   symbols: int, wall_s: float) -> None:
        self.metrics.note_flush(n_requests=n_requests, symbols=symbols,
                                wall_s=wall_s)
        self.recorder.record("flush", flush=fid, device=device,
                             n_requests=n_requests, symbols=symbols,
                             wall_ms=round(wall_s * 1e3, 3))

    # -- recorder hooks ------------------------------------------------------

    def record(self, kind: str, **fields: Any) -> None:
        self.recorder.record(kind, **fields)

    def on_kill(self, point: str, tag: str) -> Optional[str]:
        self.recorder.record("kill", point=point, tag=tag)
        return self.recorder.persist(f"kill:{point}")

    def on_worker_death(self, label: str, exc: BaseException) -> Optional[str]:
        self.recorder.record("worker_death", device=label, error=repr(exc))
        return self.recorder.persist(f"worker_death:{label}")

    # -- snapshots -----------------------------------------------------------

    def snapshot(self) -> dict:
        with self._lock:
            open_reqs = len(self._traces)
            closed = len(self.traces)
            dropped = self.dropped_traces
        return {
            "metrics": self.metrics.snapshot(),
            "open_requests": open_reqs,
            "completed_requests": closed,
            "dropped_traces": dropped,
            "flight": self.recorder.stats(),
        }


# The live handle.  Read UNLOCKED on serve hot paths (one global load when
# telemetry is off); mutated only via install()/uninstall() under _HANDLE_LOCK.
# Registered in analysis.config.SYNC_UNGUARDED with this justification.
_ACTIVE: Optional[Scope] = None
_HANDLE_LOCK = threading.Lock()


def active() -> Optional[Scope]:
    return _ACTIVE


def enabled() -> bool:
    return _ACTIVE is not None


def install(scope: Scope) -> Scope:
    global _ACTIVE
    with _HANDLE_LOCK:
        _ACTIVE = scope
    return scope


def uninstall(scope: Optional[Scope] = None) -> None:
    global _ACTIVE
    with _HANDLE_LOCK:
        if scope is None or _ACTIVE is scope:
            _ACTIVE = None


@contextlib.contextmanager
def scoped(*, flight_path: Optional[str] = None,
           ring_capacity: int = DEFAULT_RING,
           max_traces: int = DEFAULT_MAX_TRACES):
    """Install a fresh Scope for the block; persist the recorder on exit."""
    sc = Scope(flight_path=flight_path, ring_capacity=ring_capacity,
               max_traces=max_traces)
    install(sc)
    try:
        yield sc
    finally:
        uninstall(sc)
        sc.recorder.persist("shutdown")


# -- module-level helpers: one unlocked global read when telemetry is off ----


def hop(rid: int, name: str, **fields: Any) -> None:
    s = _ACTIVE
    if s is not None:
        s.hop(rid, name, **fields)


def complete(rid: int, **kw: Any) -> None:
    s = _ACTIVE
    if s is not None:
        s.complete(rid, **kw)


def next_flush_id() -> Optional[int]:
    s = _ACTIVE
    if s is not None:
        return s.next_flush_id()
    return None


def flush_done(fid: int, **kw: Any) -> None:
    s = _ACTIVE
    if s is not None:
        s.flush_done(fid, **kw)


def record(kind: str, **fields: Any) -> None:
    s = _ACTIVE
    if s is not None:
        s.recorder.record(kind, **fields)


def on_kill(point: str, tag: str) -> None:
    s = _ACTIVE
    if s is not None:
        s.on_kill(point, tag)


def on_worker_death(label: str, exc: BaseException) -> None:
    s = _ACTIVE
    if s is not None:
        s.on_worker_death(label, exc)


class SnapshotEmitter:
    """Periodic ``slo_snapshot`` JSONL emission for ``--metrics-interval``.

    One daemon thread; each tick emits the scope's SLO snapshot (plus any
    caller-supplied live payload — queue depth, fleet health) through the
    active observer's metrics sink, and drops a compact ``snapshot`` event
    into the flight recorder so postmortems carry a metric timeline.
    ``stop()`` joins the thread (graftsync thread-lifecycle rule).
    """

    def __init__(self, scope: Scope, interval_s: float,
                 extra_fn: Optional[Callable[[], dict]] = None) -> None:
        self.scope = scope
        self.interval_s = float(interval_s)
        self.extra_fn = extra_fn
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def start(self) -> "SnapshotEmitter":
        t = threading.Thread(target=self._run, name="graftscope-emitter",
                             daemon=True)
        self._thread = t
        t.start()
        return self

    def _run(self) -> None:
        while not self._stop.wait(self.interval_s):
            self.emit_once()

    def emit_once(self) -> None:
        payload: dict = {"slo": self.scope.metrics.snapshot()}
        if self.extra_fn is not None:
            try:
                extra = self.extra_fn()
            except Exception:  # live stats must not kill the emitter
                extra = None
            if extra:
                payload.update(extra)
        _obs.event("slo_snapshot", **payload)
        lat = payload["slo"]["latency_s"]
        self.scope.recorder.record(
            "snapshot", requests=lat["count"],
            p50_ms=round(lat["p50"] * 1e3, 3),
            p99_ms=round(lat["p99"] * 1e3, 3),
            queued_requests=payload.get("stats", {}).get("queued_requests"))

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=10.0)
            self._thread = None


__all__ = [
    "Scope", "FlightRecorder", "SnapshotEmitter", "active", "enabled",
    "install", "uninstall", "scoped", "hop", "complete", "next_flush_id",
    "flush_done", "record", "on_kill", "on_worker_death",
]
