"""Command-line interface.

Two forms:

1. **Reference-compatible positional form** (CpGIslandFinder.java:346-357):

       python -m cpgisland_tpu TRAIN TEST ISLANDS_OUT MODEL_OUT CONVERGENCE NUM_ITERS

   Six positional args exactly like the reference's ``main``: train on TRAIN
   with the Durbin 8-state init, dump the trained model text to MODEL_OUT,
   decode TEST and write island records to ISLANDS_OUT (the reference calls
   this file "stateSeqFile" but writes island calls to it).  Full compat
   semantics (header bases encoded, remainders dropped, per-chunk island reset).

2. **Subcommand form** with explicit flags:

       python -m cpgisland_tpu train  FILE --model-out m.txt --iters 10 ...
       python -m cpgisland_tpu decode FILE --model m.txt --islands-out i.txt ...
       python -m cpgisland_tpu run    TRAIN TEST --islands-out i.txt ...
"""

from __future__ import annotations

import argparse
import logging
import os
import sys
from typing import Optional, Sequence

log = logging.getLogger(__name__)

_SUBCOMMANDS = ("train", "decode", "posterior", "compare", "run", "serve")


def _select_platform(argv: list) -> list:
    """Apply --platform/-P (or $CPGISLAND_PLATFORM) before any jax use.

    The axon TPU plugin ignores the JAX_PLATFORMS env var, so forcing CPU must
    go through jax.config — and that must happen before the backend
    initializes, hence this pre-parse step ahead of the pipeline imports.
    """
    platform = os.environ.get("CPGISLAND_PLATFORM", "")
    out = []
    i = 0
    while i < len(argv):
        a = argv[i]
        if a in ("--platform", "-P") and i + 1 < len(argv):
            platform = argv[i + 1]
            i += 2
            continue
        if a.startswith("--platform="):
            platform = a.split("=", 1)[1]
            i += 1
            continue
        out.append(a)
        i += 1
    if platform and platform != "auto":
        import jax

        jax.config.update("jax_platforms", platform)
    return out


def _common_flags(p: argparse.ArgumentParser) -> None:
    p.add_argument(
        "--backend",
        choices=("local", "spmd", "seq", "seq2d"),
        default="local",
        help="E-step backend: one device / chunk-sharded mesh psum / exact "
        "whole-sequence sequence-parallel / per-record 2-D data x seq mesh "
        "(the last two have no chunk-boundary approximation; seq2d needs "
        "--clean).  In a multi-process job, spmd --clean builds its input "
        "by byte-range sharded encoding: each host parses only ~1/P of the "
        "training file (HDFS-input-split equivalent)",
    )
    p.add_argument("--numerics", choices=("log", "rescaled"), default="rescaled", dest="mode")
    p.add_argument(
        "--engine",
        choices=("auto", "xla", "pallas", "onehot"),
        default="auto",
        help="kernel lowering (auto: on TPU, the reduced one-hot kernels "
        "for eligible models, else the dense Pallas kernels)",
    )
    p.add_argument(
        "--clean",
        action="store_true",
        help="FASTA-aware encoding, no dropped remainders, no island clipping "
        "(default is reference-compatible behavior)",
    )
    p.add_argument(
        "--preset",
        choices=("durbin8", "two_state"),
        default="durbin8",
        help="initial model preset (durbin8: the reference's 8-state CpG+- "
        "table; two_state: minimal island/background model — decode needs "
        "--island-states 0 with it)",
    )
    p.add_argument(
        "--trace-dir",
        help="capture a jax.profiler device trace into this directory "
        "(TensorBoard format; SURVEY.md §5 tracing) plus, with the obs "
        "subsystem, a Chrome-trace/Perfetto span trace (trace.json)",
    )
    _add_obs_flags(p)
    _add_symbol_cache_flag(p)
    p.add_argument("-v", "--verbose", action="store_true")


def _add_obs_flags(p: argparse.ArgumentParser) -> None:
    p.add_argument(
        "--metrics",
        help="write a JSONL runtime-telemetry stream (spans, engine "
        "decisions, dispatch/compile ledger) to this path; render it later "
        "with tools/obs_report.py",
    )
    p.add_argument(
        "--obs-report",
        action="store_true",
        help="print an end-of-run observability table (per-phase wall, "
        "dispatches, compiles, transfer bytes, engine choices)",
    )


def _add_symbol_cache_flag(p: argparse.ArgumentParser) -> None:
    p.add_argument(
        "--symbol-cache",
        help="pre-encoded symbol cache prefix (clean mode): built on first "
        "use, repeat runs over the same FASTA skip the host text parse — "
        "the measured end-to-end bottleneck (BASELINE.md)",
    )


def _preset_params(presets, name: str):
    return presets.two_state_cpg() if name == "two_state" else presets.durbin_cpg8()


def build_parser() -> argparse.ArgumentParser:
    ap = argparse.ArgumentParser(prog="cpgisland", description=__doc__)
    sub = ap.add_subparsers(dest="cmd", required=True)

    t = sub.add_parser("train", help="Baum-Welch EM training")
    t.add_argument("training_file")
    t.add_argument("--model-out", required=True)
    t.add_argument("--iters", type=int, default=10)
    t.add_argument("--convergence", type=float, default=0.005)
    t.add_argument("--init-model", help="start from a model text file instead of the Durbin preset")
    t.add_argument("--checkpoint-dir")
    _add_em_fuse_flag(t)
    _add_invalid_symbols_flag(t)
    _common_flags(t)

    d = sub.add_parser("decode", help="Viterbi decode + island calling")
    d.add_argument("test_file")
    d.add_argument("--model", help="model text file (default: the --preset model)")
    d.add_argument("--islands-out", required=True)
    d.add_argument("--min-len", type=int, default=None, help="clean mode only")
    d.add_argument(
        "--island-engine",
        choices=("auto", "host", "device"),
        default="auto",
        help="island caller placement (clean mode): device keeps the decoded "
        "path on-chip and returns only the call records (auto: device on TPU)",
    )
    _add_island_cap_flag(d)
    _add_island_states_flag(d)
    _add_prefetch_flag(d)
    _add_invalid_symbols_flag(d)
    _add_resilience_flags(d)
    _common_flags(d)

    po = sub.add_parser(
        "posterior",
        help="soft decoding: per-position island confidence (forward-backward "
        "posteriors; the soft counterpart of `decode`)",
    )
    po.add_argument("test_file")
    po.add_argument("--model", help="model text file (default: the --preset model)")
    po.add_argument(
        "--confidence-out",
        help=".npy of float32 P(in island) per symbol (optional: an "
        "--islands-out-only run writes no per-symbol file at all)",
    )
    po.add_argument(
        "--mpm-path-out",
        help=".npy int8 max-posterior-marginal state path (soft state_path_out)",
    )
    po.add_argument(
        "--islands-out",
        help="call CpG islands from the MPM path (clean semantics, "
        "decode-format records) — the soft counterpart of `decode`; may be "
        "the ONLY output (island-only runs skip the confidence dump and, "
        "on TPU, reduce the path to call records on device)",
    )
    po.add_argument("--min-len", type=int, default=None,
                    help="minimum island length for --islands-out")
    po.add_argument(
        "--island-engine",
        choices=("auto", "host", "device"),
        default="auto",
        help="island caller placement: device keeps the MPM path on-chip and "
        "returns only the call records (auto: device on TPU when eligible)",
    )
    _add_island_cap_flag(po)
    _add_island_states_flag(po)
    _add_prefetch_flag(po)
    _add_invalid_symbols_flag(po)
    _add_resilience_flags(po)
    # Only the flags posterior honors (it is always clean/FASTA-aware) — NOT
    # _common_flags, whose --backend/--numerics/--clean would be silently
    # ignored here.
    po.add_argument(
        "--engine",
        choices=("auto", "xla", "pallas", "onehot"),
        default="auto",
        help="forward-backward lowering (auto: on TPU, the reduced one-hot "
        "kernels for eligible models, else the dense fused kernels)",
    )
    po.add_argument(
        "--preset", choices=("durbin8", "two_state"), default="durbin8",
        help="initial model preset (two_state needs --island-states 0)",
    )
    po.add_argument("--trace-dir", help="capture a jax.profiler device trace")
    _add_obs_flags(po)
    _add_symbol_cache_flag(po)
    po.add_argument("-v", "--verbose", action="store_true")

    cp = sub.add_parser(
        "compare",
        help="multi-model posterior comparison: N family members over one "
        "FASTA stream — per-model log-odds vs a baseline, per-model "
        "islands, and a per-position winning-model track in the reference "
        "island format (clean/FASTA semantics)",
    )
    cp.add_argument("test_file")
    cp.add_argument(
        "--models",
        default="durbin8,two_state,null",
        help="comma-separated family members: built-in names "
        "(durbin8,two_state,dinuc_cpg,null,null16) and/or NAME=MODEL.txt "
        "entries (loaded model text; island states inferred for 2M-state "
        "layouts).  Default: the 3-model cast durbin8,two_state,null",
    )
    cp.add_argument("--out", required=True, help="comparison report path")
    cp.add_argument(
        "--baseline",
        help="member name for the log-odds denominator (default: the one "
        "null member when present, else the first member)",
    )
    cp.add_argument("--min-len", type=int, default=None,
                    help="minimum island length for the emitted tracks")
    cp.add_argument(
        "--threshold", type=float, default=None,
        help="winner-track confidence threshold (default 0.5): a position "
        "below it on every member falls back to background",
    )
    cp.add_argument(
        "--engine", choices=("auto", "xla", "pallas", "onehot"),
        default="auto",
        help="kernel lowering request applied to every member (auto "
        "resolves per member's family eligibility)",
    )
    cp.add_argument(
        "--no-stacked", action="store_true",
        help="disable the stacked multi-model dispatch (same-order reduced "
        "members in ONE launch set; results are bit-identical either way "
        "— this is the launch-level A/B escape hatch)",
    )
    _add_invalid_symbols_flag(cp)
    _add_obs_flags(cp)
    _add_symbol_cache_flag(cp)
    cp.add_argument("--trace-dir", help="capture a jax.profiler device trace")
    cp.add_argument("-v", "--verbose", action="store_true")

    sv = sub.add_parser(
        "serve",
        help="persistent serving daemon: JSONL requests over stdin/stdout "
        "(or --socket), heterogeneous decode/posterior requests coalesced "
        "into flat-stream flushes against warm executables — see "
        "cpgisland_tpu/serve/transport.py for the protocol",
    )
    sv.add_argument("--model", help="model text file (default: the --preset model)")
    sv.add_argument(
        "--preset", choices=("durbin8", "two_state"), default="durbin8",
        help="model preset when no --model is given (two_state needs "
        "--island-states 0)",
    )
    sv.add_argument(
        "--engine", choices=("auto", "xla", "pallas", "onehot"),
        default="auto",
        help="kernel lowering for the session (auto: reduced one-hot "
        "kernels on TPU for eligible models)",
    )
    sv.add_argument(
        "--island-engine", choices=("auto", "host", "device"), default="auto",
        help="island caller placement (auto: device on TPU)",
    )
    sv.add_argument("--min-len", type=int, default=None)
    sv.add_argument(
        "--flush-symbols", type=_positive_int, default=8 << 20,
        help="flush budget: a flush closes when this many symbols are "
        "queued (default 8 Mi)",
    )
    sv.add_argument(
        "--flush-deadline-ms", type=float, default=50.0,
        help="bounded latency: a flush also closes when the oldest queued "
        "request has waited this long (default 50 ms)",
    )
    sv.add_argument(
        "--tenant-max-requests", type=_positive_int, default=256,
        help="per-tenant queued-request cap (admission past it is rejected "
        "with a backpressure error)",
    )
    sv.add_argument(
        "--tenant-max-symbols", type=_positive_int, default=512 << 20,
        help="per-tenant queued-symbol cap",
    )
    sv.add_argument(
        "--no-stacked", action="store_true", dest="no_stacked",
        help="disable multi-model kernel stacking (compare flushes + "
        "mixed-model decode flushes run the sequential per-model arm; "
        "results identical modulo the flat decoder's pinned tie contract)",
    )
    sv.add_argument(
        "--family", metavar="NAMES", default="",
        help="comma-separated family member names "
        "(durbin8,two_state,dinuc_cpg,null,null16) to register alongside "
        "the default model: requests may then carry model=NAME routing "
        "and kind=compare with models=[...] — each member gets its own "
        "session with a private breaker (per-model fault isolation)",
    )
    sv.add_argument(
        "--socket", metavar="PATH",
        help="serve a local AF_UNIX socket instead of stdin/stdout "
        "(JSONL, one client connection at a time; the broker stays warm "
        "across connections)",
    )
    sv.add_argument(
        "--tcp", metavar="HOST:PORT", default="",
        help="also (or instead) listen on a TCP socket — the "
        "cross-machine door for a multi-host routing tier "
        "(serve/router.py) or remote serve_client consumers; with "
        "--socket both listeners feed one mux (shared request-id "
        "space)",
    )
    sv.add_argument(
        "--fleet", type=int, default=0, metavar="N",
        help="device pool: one session set + flush worker per local "
        "device (first N devices; 0 = the single worker loop).  Faulting "
        "devices are health-probed and quarantined, their flushes "
        "requeued intact onto healthy devices — see "
        "cpgisland_tpu/serve/fleet.py",
    )
    sv.add_argument(
        "--metrics-interval", type=float, default=0.0, metavar="SECONDS",
        help="emit a periodic slo_snapshot record (graftscope latency/flush "
        "histograms + queue depth + fleet health) into the --metrics JSONL "
        "every SECONDS; also enables request-lineage telemetry (0 = off)",
    )
    _add_island_cap_flag(sv)
    _add_island_states_flag(sv)
    _add_invalid_symbols_flag(sv)
    _add_resilience_flags(sv)
    _add_obs_flags(sv)
    sv.add_argument("--trace-dir", help="capture a jax.profiler device trace")
    sv.add_argument("-v", "--verbose", action="store_true")

    r = sub.add_parser("run", help="train then decode (the reference main())")
    r.add_argument("training_file")
    r.add_argument("test_file")
    r.add_argument("--islands-out", required=True)
    r.add_argument("--model-out", required=True)
    r.add_argument("--iters", type=int, default=10)
    r.add_argument("--convergence", type=float, default=0.005)
    _add_island_states_flag(r)
    _add_em_fuse_flag(r)
    _add_prefetch_flag(r)
    _common_flags(r)

    return ap


def _positive_int(s: str) -> int:
    v = int(s)
    if v < 1:
        raise argparse.ArgumentTypeError(f"must be >= 1, got {v}")
    return v


def _add_em_fuse_flag(p: argparse.ArgumentParser) -> None:
    p.add_argument(
        "--em-fuse",
        choices=("auto", "on", "off"),
        default="auto",
        help="EM loop execution: auto/on runs every iteration inside ONE "
        "compiled program with the convergence test on device (K "
        "steady-state iterations pay one blocking round trip instead of "
        "K+); off keeps the reference's per-iteration host cadence.  auto "
        "falls back to the host loop when --checkpoint-dir is given "
        "(per-iteration snapshots need the model on the host)",
    )


def _add_prefetch_flag(p: argparse.ArgumentParser) -> None:
    p.add_argument(
        "--prefetch",
        type=int,
        default=0,
        metavar="N",
        help="clean mode: depth of the double-buffered streaming executor "
        "— a background thread encodes record r+1 while the device "
        "processes record r, and span uploads are issued ahead of the "
        "sweep that consumes them; decode with the device island engine "
        "additionally defers call-column fetches until the next dispatch "
        "is in flight.  0 (default) = strictly serial; results are "
        "bit-identical either way",
    )


def _add_invalid_symbols_flag(p: argparse.ArgumentParser) -> None:
    p.add_argument(
        "--invalid-symbols",
        choices=("skip", "mask", "fail"),
        default="skip",
        help="clean mode: what to do with bytes that are neither bases nor "
        "whitespace (N runs, ambiguity codes...). skip drops them (the "
        "reference's behavior), mask encodes them as the PAD sentinel "
        "(identity DP step — island coordinates then match the original "
        "FASTA), fail aborts on the first one (Hadoop's "
        "skip-bad-records-off default). Counts surface as obs events",
    )


def _add_resilience_flags(p: argparse.ArgumentParser) -> None:
    p.add_argument(
        "--integrity-check",
        action="store_true",
        help="verify every supervised device dispatch with a canary fetch "
        "(distinct seed fold) + plausibility ceilings, re-dispatching on a "
        "phantom/stale result — bench.py's relay defenses as a production "
        "guard; costs one tiny extra round trip per dispatch",
    )
    p.add_argument(
        "--resume",
        action="store_true",
        help="clean mode: write a per-record completion manifest "
        "(<islands-out>.manifest.jsonl unless --manifest names one) and "
        "skip records it already marks complete — a killed run resumes "
        "with byte-identical final output. Incompatible with per-symbol "
        "stream outputs",
    )
    p.add_argument(
        "--manifest",
        metavar="PATH",
        help="explicit manifest path for --resume (also enables manifest "
        "WRITING without resuming when given alone)",
    )


def _add_island_cap_flag(p: argparse.ArgumentParser) -> None:
    p.add_argument(
        "--island-cap", type=_positive_int, default=None,
        help="initial device-side output-buffer size in island calls "
        "(device engine; default 128 Ki). Overflow retries the calling "
        "pass at the true count (up to a 4 Mi ceiling against degenerate "
        "inputs) — this only tunes the starting allocation",
    )


def _add_island_states_flag(p: argparse.ArgumentParser) -> None:
    p.add_argument(
        "--island-states",
        help="comma-separated island state ids for models whose states don't "
        "encode bases (e.g. '0' for the two_state preset); composition then "
        "comes from the observations; clean mode only",
    )


def _parse_island_states(parser: argparse.ArgumentParser, args, compat: bool):
    if not getattr(args, "island_states", None):
        return None
    if compat:
        parser.error("--island-states requires --clean")
    try:
        return tuple(int(s) for s in args.island_states.split(","))
    except ValueError:
        parser.error(
            f"--island-states must be comma-separated integers, got {args.island_states!r}"
        )


def main(argv: Optional[Sequence[str]] = None) -> int:
    argv = _select_platform(list(sys.argv[1:] if argv is None else argv))
    # Deferred: importing the pipeline pulls in jax; platform choice must win.
    from cpgisland_tpu import pipeline
    from cpgisland_tpu.models import presets
    from cpgisland_tpu.models.hmm import load_text

    # Reference-compatible 6-positional-arg form.
    if len(argv) == 6 and argv[0] not in _SUBCOMMANDS:
        logging.basicConfig(level=logging.INFO, format="%(levelname)s %(name)s: %(message)s")
        train_f, test_f, islands_out, model_out, convergence, num_iters = argv
        pipeline.run(
            train_f,
            test_f,
            islands_out,
            model_out,
            convergence=float(convergence),
            num_iters=int(num_iters),
        )
        return 0

    args = build_parser().parse_args(argv)
    logging.basicConfig(
        level=logging.DEBUG if args.verbose else logging.INFO,
        format="%(levelname)s %(name)s: %(message)s",
    )
    # Subcommands without a --clean flag (posterior) are always clean.
    compat = not getattr(args, "clean", True)

    import contextlib

    from cpgisland_tpu import obs as obs_mod
    from cpgisland_tpu.utils import profiling

    trace_ctx = (
        profiling.trace(args.trace_dir) if args.trace_dir else contextlib.nullcontext()
    )
    # The obs subsystem is off unless asked for: any of --metrics,
    # --obs-report, --trace-dir turns it on (a trace-dir run gets the
    # Chrome-trace span export alongside the jax.profiler capture).
    observer = (
        obs_mod.Observer(
            metrics=getattr(args, "metrics", None), trace_dir=args.trace_dir
        )
        if (
            getattr(args, "metrics", None)
            or getattr(args, "obs_report", False)
            or args.trace_dir
        )
        else None
    )
    with trace_ctx, (observer if observer is not None else contextlib.nullcontext()):
        rc = _run_command(args, compat, pipeline, presets, load_text, observer)
    if observer is not None and getattr(args, "obs_report", False):
        print(observer.report())
    return rc


def _run_command(args, compat, pipeline, presets, load_text, observer=None) -> int:
    metrics = observer.metrics if observer is not None else None
    if getattr(args, "symbol_cache", None) and compat:
        build_parser().error(
            "--symbol-cache is FASTA-aware and requires --clean"
        )
    if args.cmd == "train":
        params = load_text(args.init_model) if args.init_model else _preset_params(presets, args.preset)
        res = pipeline.train_file(
            args.training_file,
            params=params,
            num_iters=args.iters,
            convergence=args.convergence,
            backend=args.backend,
            mode=args.mode,
            engine=args.engine,
            compat=compat,
            checkpoint_dir=args.checkpoint_dir,
            model_out=args.model_out,
            symbol_cache=args.symbol_cache,
            metrics=metrics,
            fuse=args.em_fuse,
            invalid_symbols=args.invalid_symbols,
        )
        print(
            f"trained: iters={res.iterations} converged={res.converged} "
            f"final_loglik={res.logliks[-1] if res.logliks else float('nan'):.4f}"
        )
        return 0

    if args.cmd == "decode":
        if args.min_len is not None and compat:
            build_parser().error("--min-len requires --clean (the reference has no length filter)")
        if args.prefetch and compat:
            build_parser().error(
                "--prefetch streams FASTA records and requires --clean "
                "(the compat path encodes the whole file up front)"
            )
        if (args.resume or args.manifest) and compat:
            build_parser().error(
                "--resume manifests are per-record and require --clean"
            )
        if args.invalid_symbols != "skip" and compat:
            build_parser().error(
                "--invalid-symbols mask|fail requires --clean (compat "
                "reproduces the reference's skip-everything encode)"
            )
        island_states = _parse_island_states(build_parser(), args, compat)
        params = load_text(args.model) if args.model else _preset_params(presets, args.preset)
        res = pipeline.decode_file(
            args.test_file,
            params,
            islands_out=args.islands_out,
            compat=compat,
            min_len=args.min_len,
            engine=args.engine,
            island_states=island_states,
            island_engine=args.island_engine,
            island_cap=args.island_cap,
            symbol_cache=args.symbol_cache,
            metrics=metrics,
            prefetch=args.prefetch,
            integrity_check=args.integrity_check,
            resume=args.resume,
            manifest_path=args.manifest,
            invalid_symbols=args.invalid_symbols,
        )
        print(f"decoded {res.n_symbols} symbols in {res.n_chunks} chunks; {len(res.calls)} islands")
        return 0

    if args.cmd == "posterior":
        if args.min_len is not None and not args.islands_out:
            build_parser().error("--min-len only applies with --islands-out")
        if (args.resume or args.manifest) and (
            args.confidence_out or args.mpm_path_out or not args.islands_out
        ):
            build_parser().error(
                "--resume needs an island-only run: --islands-out without "
                "--confidence-out/--mpm-path-out (per-symbol streams are "
                "not resumable)"
            )
        if not (args.confidence_out or args.mpm_path_out or args.islands_out):
            build_parser().error(
                "nothing to do: pass --confidence-out, --mpm-path-out, "
                "and/or --islands-out"
            )
        island_states = _parse_island_states(build_parser(), args, compat=False)
        params = load_text(args.model) if args.model else _preset_params(presets, args.preset)
        if island_states is None:
            err = pipeline.island_layout_error(params, island_states)
            if err:
                build_parser().error(f"--preset {args.preset}: {err}")
        res = pipeline.posterior_file(
            args.test_file,
            params,
            confidence_out=args.confidence_out,
            mpm_path_out=args.mpm_path_out,
            islands_out=args.islands_out,
            min_len=args.min_len,
            island_states=island_states,
            engine=args.engine,
            island_engine=args.island_engine,
            island_cap=args.island_cap,
            symbol_cache=args.symbol_cache,
            metrics=metrics,
            prefetch=args.prefetch,
            integrity_check=args.integrity_check,
            resume=args.resume,
            manifest_path=args.manifest,
            invalid_symbols=args.invalid_symbols,
        )
        extra = (
            f"; {len(res.calls)} islands -> {args.islands_out}"
            if res.calls is not None
            else ""
        )
        print(
            f"posterior: {res.n_symbols} symbols in {res.n_records} records; "
            f"mean island confidence {res.mean_island_confidence:.4f}{extra}"
        )
        return 0

    if args.cmd == "compare":
        from cpgisland_tpu import family

        members = []
        seen = set()
        for tok in args.models.split(","):
            tok = tok.strip()
            if not tok:
                continue
            if "=" in tok:
                name, path = tok.split("=", 1)
                m = family.member_from_params(name, load_text(path))
            else:
                m = family.builtin_member(tok)
            if m.name in seen:
                build_parser().error(f"duplicate member name {m.name!r}")
            seen.add(m.name)
            members.append(m)
        if not members:
            build_parser().error("--models named no members")
        # Pre-flight argument validation only — runtime data errors from
        # the pipeline itself must surface as real tracebacks, not usage
        # errors (the decode/posterior subcommands' convention).
        try:
            family.resolve_baseline(members, args.baseline)
        except ValueError as e:
            build_parser().error(str(e))
        res = pipeline.compare_file(
            args.test_file,
            members,
            out=args.out,
            engine=args.engine,
            baseline=args.baseline,
            min_len=args.min_len,
            threshold=args.threshold,
            symbol_cache=args.symbol_cache,
            invalid_symbols=args.invalid_symbols,
            metrics=metrics,
            stacked=not args.no_stacked,
        )
        n_winner = sum(len(rc.winner_calls) for rc in res.records)
        print(
            f"compared {len(res.member_names)} models over "
            f"{res.n_symbols} symbols in {res.n_records} records; "
            f"baseline {res.baseline}; {n_winner} winner-track islands "
            f"-> {args.out}"
        )
        return 0

    if args.cmd == "serve":
        from cpgisland_tpu.serve import transport

        if args.resume and not args.manifest:
            build_parser().error(
                "serve --resume needs --manifest PATH (there is no output "
                "file to anchor a default manifest name)"
            )
        island_states = _parse_island_states(build_parser(), args, compat=False)
        # transport._build_broker reads the PARSED tuple off args.
        args.island_states = island_states
        params = load_text(args.model) if args.model else _preset_params(presets, args.preset)
        if island_states is None:
            err = pipeline.island_layout_error(params, None)
            if err:
                build_parser().error(f"--preset {args.preset}: {err}")
        return transport.serve_main(args, params)

    if args.cmd == "run":
        if args.prefetch and compat:
            build_parser().error(
                "--prefetch streams FASTA records and requires --clean"
            )
        island_states = _parse_island_states(build_parser(), args, compat)
        params = _preset_params(presets, args.preset)
        # Same pairing check decode_file performs (the one shared predicate) —
        # but at parse time, not after an hours-long training run completes.
        err = pipeline.island_layout_error(params, island_states)
        if err:
            build_parser().error(f"--preset {args.preset}: {err}")
        res = pipeline.run(
            args.training_file,
            args.test_file,
            args.islands_out,
            args.model_out,
            convergence=args.convergence,
            num_iters=args.iters,
            params=params,
            backend=args.backend,
            mode=args.mode,
            compat=compat,
            engine=args.engine,
            island_states=island_states,
            symbol_cache=args.symbol_cache,
            fuse=args.em_fuse,
            prefetch=args.prefetch,
        )
        print(f"{len(res.calls)} islands -> {args.islands_out}")
        return 0

    raise AssertionError("unreachable")


if __name__ == "__main__":
    sys.exit(main())
