"""Named model-family members: first-class, comparable model objects.

A :class:`Member` bundles everything the pipelines need to run a model as
part of a family — the params, which states count as "island" (the island
callers' and posterior masks' input), and the observation ORDER (1 = the
base alphabet the codec emits; 2 = the pair/dinucleotide alphabet,
:func:`cpgisland_tpu.utils.codec.recode_pairs`).  Members route through
the existing engine registry / flat-stream batching / prepared caching
like any params — the family layer adds structure, not kernels.

The built-in registry covers the comparison workload's default cast:

- ``durbin8`` — the flagship 8-state reference model (reduced-eligible);
- ``two_state`` — the minimal island/background model (dense engines);
- ``dinuc_cpg`` — the order-2 dinucleotide CpG model over the pair
  alphabet (reduced-eligible on the decode path: 16 blocks of 2);
- ``null`` / ``null16`` — single-state background scoring models (base /
  pair alphabet), the log-odds denominators.
"""

from __future__ import annotations

import dataclasses
from typing import Optional

import numpy as np

from cpgisland_tpu.family import partition as partition_mod
from cpgisland_tpu.models.hmm import HmmParams

__all__ = [
    "Member", "MEMBER_NAMES", "builtin_member", "members_from_names",
    "default_members", "member_from_params",
]


@dataclasses.dataclass(frozen=True)
class Member:
    """One model of a family (see module docstring).

    ``island_states`` may be empty — a pure scoring model (the null
    members) has no island track and never wins a winner-track position.
    ``order=2`` members consume the PAIR-recoded stream; :meth:`encode`
    is the one place that recode decision lives.
    """

    name: str
    params: HmmParams
    island_states: tuple = ()
    order: int = 1
    description: str = ""

    def __post_init__(self):
        if self.order not in (1, 2):
            raise ValueError(f"member order must be 1 or 2, got {self.order}")
        # Members consume codec streams by construction: order-1 = the
        # 4-symbol base alphabet, order-2 = the 16-symbol pair recode.  A
        # mismatched alphabet would silently score the wrong stream (a
        # pair model fed base symbols nan-collapses on its structural
        # zeros), so it is a construction error, not a runtime surprise.
        want_S = 4 if self.order == 1 else 16
        if self.params.n_symbols != want_S:
            raise ValueError(
                f"member {self.name!r}: order-{self.order} members consume "
                f"the {want_S}-symbol codec stream, but the model has "
                f"n_symbols={self.params.n_symbols}"
            )
        K = self.params.n_states
        bad = [s for s in self.island_states if not 0 <= int(s) < K]
        if bad:
            raise ValueError(
                f"member {self.name!r}: island states {bad} outside "
                f"0..{K - 1}"
            )

    def encode(self, symbols: np.ndarray, prev: Optional[int] = None) -> np.ndarray:
        """The member's observation stream for a base-alphabet record —
        identity for order-1, the codec pair recode for order-2 (``prev``
        = the base before the record/span, the continuation threading).

        Order-2 members require a PAD-free base stream (the codec's
        default 'skip' policy): a masked/PAD input position would recode
        to a pair PAD the forward-backward machinery scores as a clamped
        observation, which pair-chained models' structural transition
        zeros turn into a dead chain (see codec.recode_pairs)."""
        if self.order == 1:
            return np.asarray(symbols)
        from cpgisland_tpu.utils import codec

        s = np.asarray(symbols)
        if s.size and int(s.max()) >= codec.N_SYMBOLS:
            raise ValueError(
                f"order-2 member {self.name!r} needs a PAD-free base "
                "stream (contains symbols >= 4) — encode with the default "
                "invalid_symbols='skip' policy"
            )
        return codec.recode_pairs(s, prev=prev)

    @property
    def partition(self):
        """The member's emission-support partition (family.partition_of) —
        None for non-partitioned members.  Members with EQUAL partition
        signatures share symbol-only prepared streams over one placed
        record (ops.prepared keys on placed-array identity + geometry)."""
        return partition_mod.partition_of(self.params)

    @property
    def is_null(self) -> bool:
        return not self.island_states


def _builtin_builders():
    from cpgisland_tpu.models import presets

    return {
        "durbin8": lambda: Member(
            "durbin8", presets.durbin_cpg8(), tuple(range(4)), 1,
            "flagship 8-state reference CpG model (reduced engines)",
        ),
        "two_state": lambda: Member(
            "two_state", presets.two_state_cpg(), (0,), 1,
            "minimal island/background model (dense engines)",
        ),
        "dinuc_cpg": lambda: Member(
            "dinuc_cpg", presets.dinuc_cpg(), presets.DINUC_ISLAND_STATES, 2,
            "order-2 dinucleotide CpG model over the pair alphabet "
            "(reduced decode engines; 16 blocks of 2)",
        ),
        "null": lambda: Member(
            "null", presets.null_background(4), (), 1,
            "single-state background scoring model (base alphabet)",
        ),
        "null16": lambda: Member(
            "null16", presets.null_background(16), (), 2,
            "single-state background scoring model (pair alphabet)",
        ),
    }


MEMBER_NAMES = ("durbin8", "two_state", "dinuc_cpg", "null", "null16")


def builtin_member(name: str) -> Member:
    """Build one built-in member by name (ValueError on unknown names —
    the CLI/serve admission surface)."""
    builders = _builtin_builders()
    if name not in builders:
        raise ValueError(
            f"unknown family member {name!r}; built-ins: "
            f"{', '.join(MEMBER_NAMES)}"
        )
    return builders[name]()


def member_from_params(
    name: str, params: HmmParams, *, island_states=None,
    order: Optional[int] = None,
) -> Member:
    """Wrap loaded/trained params as a member.  ``island_states=None``
    infers the reference labeling (first n_symbols states) for 2M-state
    models and the empty set otherwise; ``order=None`` infers the stream
    order from the alphabet (4 symbols = base, 16 = pair recode — a
    loaded pair-alphabet model fed the base stream would nan-collapse on
    its structural zeros, so the inference is a correctness guard, and
    any other alphabet must be rejected).  Pass both explicitly for
    anything unusual."""
    if order is None:
        if params.n_symbols == 4:
            order = 1
        elif params.n_symbols == 16:
            order = 2
        else:
            raise ValueError(
                f"member {name!r}: cannot infer stream order for "
                f"n_symbols={params.n_symbols} (codec streams are "
                "4-symbol base or 16-symbol pair)"
            )
    if island_states is None:
        island_states = (
            tuple(range(params.n_symbols))
            if params.n_states == 2 * params.n_symbols
            else ()
        )
    return Member(name, params, tuple(sorted(island_states)), order)


def members_from_names(names) -> list:
    """Resolve a list of member names (the CLI's --models form), checking
    uniqueness."""
    seen = set()
    out = []
    for n in names:
        if n in seen:
            raise ValueError(f"duplicate member name {n!r}")
        seen.add(n)
        out.append(builtin_member(n))
    return out


def default_members() -> list:
    """The default 3-model comparison cast: flagship vs minimal vs null."""
    return members_from_names(("durbin8", "two_state", "null"))
