"""Multi-model posterior comparison: N family members over one record.

The cross-model workload ROADMAP item 2 calls for: score CpG+/-, the
2-state model, the dinucleotide model, and the null background over the
SAME symbol stream and report, per member, the record log-likelihood,
the log-odds against a baseline member, the posterior island-confidence
track, and the member's island calls — plus a per-position WINNER track
(which member is most confident of an island at each position) emitted in
the reference island text format.

Exactness contract: each member's confidence/path comes from the SAME
shared record unit the posterior pipeline runs
(``pipeline._posterior_record_unit`` — pow2-padded geometry, supervised
dispatch, breaker-gated engine resolution), so a comparison is
BIT-IDENTICAL to N independent posterior runs of the same records; the
comparison layer only adds the scoring pass
(``ops.forward_backward.sequence_loglik``) and host-side track algebra.
Members of the same order share ONE host stream (the pair recode is
computed once, not per member); device placement is currently per member
unit — fusing members onto one placed stream/launch is the occupancy
half of ROADMAP item 2, still open.  Order-2 members consume the
pair-recoded stream (codec.recode_pairs), which is position-aligned with
the base stream, so every track below lives on base-stream coordinates.

Null members (empty ``island_states``) are scoring-only: their
confidence is identically zero by construction (no island states), so no
posterior dispatch is paid for them — they enter the log-odds
denominators and the winner track's background fallback.

Comparability note: members of equal ``order`` score the same number of
emissions and their log-odds are directly interpretable; an order-2
member scores T-1 pair emissions vs an order-1 member's T, so cross-order
odds carry that structural offset — compare like with like (pair members
against ``null16``).
"""

from __future__ import annotations

import dataclasses
import logging
from typing import Optional

import numpy as np

from cpgisland_tpu.family.members import Member

log = logging.getLogger(__name__)

__all__ = [
    "MemberResult", "RecordComparison", "compare_record", "winner_calls",
]

#: A winner-track position must beat this island confidence to be claimed
#: by a member; everything else falls back to the background (-1).
DEFAULT_WINNER_THRESHOLD = 0.5


@dataclasses.dataclass
class MemberResult:
    """One member's result over one record (base-stream coordinates)."""

    name: str
    loglik: float
    log_odds: float  # loglik - baseline member's loglik (natural log)
    conf: np.ndarray  # [T] float32 P(position in island | record)
    calls: object  # IslandCalls from the member's own MPM path


@dataclasses.dataclass
class RecordComparison:
    record: str
    n_symbols: int
    baseline: str
    members: list  # [MemberResult] in input member order
    winner: np.ndarray  # [T] int8 member index, -1 = background/no island
    winner_calls: object  # IslandCalls, names = winning member names

    def member(self, name: str) -> MemberResult:
        for m in self.members:
            if m.name == name:
                return m
        raise KeyError(name)


def resolve_baseline(members, baseline: Optional[str]) -> int:
    """Index of the log-odds baseline member: an explicit name, else the
    single null member when exactly one exists, else the first member."""
    if baseline is not None:
        for i, m in enumerate(members):
            if m.name == baseline:
                return i
        raise ValueError(
            f"baseline {baseline!r} is not one of "
            f"{[m.name for m in members]}"
        )
    nulls = [i for i, m in enumerate(members) if m.is_null]
    return nulls[0] if len(nulls) == 1 else 0


def _member_context(member: Member, sessions, engine: str, supervisor):
    """(engine request, supervisor) for one member — a serve
    :class:`~cpgisland_tpu.serve.session.Session` when the caller maps one
    to this member's name (per-model fault domains: that session's breaker
    gates the dispatches), else the call-level defaults."""
    sess = None if sessions is None else sessions.get(member.name)
    if sess is None:
        from cpgisland_tpu import resilience

        sup = (
            supervisor if supervisor is not None
            else resilience.default_supervisor()
        )
        return engine, sup
    if sess.params is not member.params:
        raise ValueError(
            f"session for member {member.name!r} is bound to different "
            "params — one Session serves ONE model"
        )
    return sess.engine, sess.supervisor


def _pad_pow2(stream: np.ndarray, pad_sym: int, floor: int = 1 << 14):
    """Pow2-pad a stream for the scoring pass — the same bucket discipline
    as the posterior record unit, so repeat geometries share compiles."""
    from cpgisland_tpu.pipeline import _round_pow2

    T = stream.shape[0]
    Tp = _round_pow2(max(T, 1), floor=floor)
    if Tp == T:
        return stream
    return np.concatenate(
        [stream, np.full(Tp - T, pad_sym, dtype=stream.dtype)]
    )


def winner_track(
    confs: np.ndarray, threshold: float = DEFAULT_WINNER_THRESHOLD
) -> np.ndarray:
    """[N, T] member confidences -> [T] int8 winner index.

    winner[t] = the member with the highest island confidence at t when
    that confidence exceeds ``threshold``; -1 (background) otherwise.
    Ties break to the lower member index (input order)."""
    if confs.shape[0] > 127:
        raise ValueError("winner track is int8: at most 127 members")
    if not threshold >= 0.0:
        # A negative threshold would claim every position for the argmax
        # member — including null members' exact-zero columns, which
        # winner_calls (correctly) never emits; fail fast instead of
        # producing a winner array inconsistent with the emitted track.
        raise ValueError(
            f"winner threshold must be >= 0 (confidences are "
            f"probabilities), got {threshold}"
        )
    best = np.argmax(confs, axis=0).astype(np.int8)
    return np.where(
        confs[best, np.arange(confs.shape[1])] > threshold, best,
        np.int8(-1),
    )


def _sorted_calls(calls):
    from cpgisland_tpu.ops.islands import IslandCalls

    order = np.argsort(calls.beg, kind="stable")
    return IslandCalls(
        beg=calls.beg[order], end=calls.end[order],
        length=calls.length[order], gc_content=calls.gc_content[order],
        oe_ratio=calls.oe_ratio[order],
        names=None if calls.names is None else calls.names[order],
    )


def winner_calls(
    members, winner: np.ndarray, symbols: np.ndarray,
    min_len: Optional[int] = None,
):
    """The winner track as reference-format island records: runs where
    member m wins become intervals (1-based, base-stream coordinates)
    with GC/obs-exp composition from the BASE observations and the
    winning member's name in the name column — one merged,
    position-sorted list."""
    from cpgisland_tpu.ops import islands as islands_mod
    from cpgisland_tpu.ops.islands import IslandCalls

    parts = []
    for idx, m in enumerate(members):
        if m.is_null:
            continue  # confidence 0 never exceeds the threshold
        c = islands_mod.call_islands_obs(
            winner, symbols, island_states=(idx,), min_len=min_len
        )
        parts.append(c.with_names(m.name))
    return _sorted_calls(IslandCalls.concatenate(parts))


def compare_record(
    members,
    symbols: np.ndarray,
    *,
    record: str = "",
    engine: str = "auto",
    baseline: Optional[str] = None,
    min_len: Optional[int] = None,
    threshold: float = DEFAULT_WINNER_THRESHOLD,
    prev: Optional[int] = None,
    sessions=None,
    supervisor=None,
    stacked: Optional[bool] = None,
    streams_handle=None,
) -> RecordComparison:
    """Compare ``members`` over one base-alphabet record (see module
    docstring).

    ``sessions``: optional mapping member-name -> serve Session; a mapped
    member's dispatches run under that session's supervisor/breaker (the
    daemon's per-model fault domains).  ``prev`` threads the base before
    the record into order-2 recodes (stream continuations).

    Each order's stream is encoded, pow2-padded AND device-placed ONCE,
    shared by every member of that order (scoring pass + posterior units
    — zero duplicate uploads on the second member).  ``stacked``
    additionally groups same-order members whose resolved FB engine is
    the reduced ``'onehot'`` into ONE stacked launch set
    (family.stacked) — per-member results stay bit-identical to the
    sequential arm; a failing stacked unit falls back to it.  The
    ``None`` default consults the graftune winner table
    (``stacked.compare``) and falls back to the shipped True; an
    explicit bool always wins.
    ``streams_handle``: an ops.prepared.PreparedStreams owning the stacked
    group's symbol-only prep (the serve registry passes its shared one).
    """
    import jax.numpy as jnp

    from cpgisland_tpu import obs as obs_mod
    from cpgisland_tpu import pipeline
    from cpgisland_tpu.family import stacked as stacked_mod
    from cpgisland_tpu.ops import islands as islands_mod
    from cpgisland_tpu.ops.forward_backward import sequence_loglik
    from cpgisland_tpu.parallel.posterior import (
        place_record_span,
        prepare_record_span,
        resolve_fb_engine,
    )

    if not members:
        raise ValueError("compare needs at least one member")
    if stacked is None:
        from cpgisland_tpu import tune

        stacked = tune.default_stacked("compare")
    names = [m.name for m in members]
    if len(set(names)) != len(names):
        raise ValueError(f"duplicate member names: {names}")
    symbols = np.ascontiguousarray(symbols, dtype=np.uint8)
    T = symbols.shape[0]
    b_idx = resolve_baseline(members, baseline)

    ctxs = [_member_context(m, sessions, engine, supervisor) for m in members]
    # Per-ORDER stream cache: every same-order member consumes identical
    # bytes (base stream / one pair recode), so encode + pow2-pad once AND
    # device-place once — the scoring pass shares one uploaded buffer and
    # the posterior units one placed span (zero re-preps / duplicate
    # uploads on the second member of an order; ledger-asserted in tests).
    streams: dict = {}
    for m in members:
        if m.order in streams:
            continue
        st = m.encode(symbols, prev=prev)
        padded = _pad_pow2(st, m.params.n_symbols)
        streams[m.order] = {
            "stream": st,
            "padded_dev": jnp.asarray(obs_mod.note_upload(padded)),
            "placed": None,  # posterior span placement, built on demand
        }

    def order_placed(m):
        """The order's ONE posterior placement (same pow2 bucket as
        _posterior_record_unit, so sharing it is bit-identical)."""
        ent = streams[m.order]
        if ent["placed"] is None:
            ent["placed"] = place_record_span(
                m.params, ent["stream"],
                pad_to=pipeline._round_pow2(
                    ent["stream"].shape[0], floor=1 << 14
                ),
            )
        return ent["placed"]

    logliks: list = []
    for i, m in enumerate(members):
        _eng, sup = ctxs[i]
        ent = streams[m.order]

        def ll_unit(pd=ent["padded_dev"], m=m, L=ent["stream"].shape[0]):
            return float(obs_mod.note_fetch(np.asarray(
                sequence_loglik(m.params, pd, L)
            )))

        logliks.append(sup.run(
            ll_unit, what="compare.loglik", engine="fb.xla",
            items=float(T),
        ))

    fb_engs: list = []
    for i, m in enumerate(members):
        eng, sup = ctxs[i]
        fb_engs.append(
            None if (m.is_null or T == 0)
            else resolve_fb_engine(eng, m.params, breaker=sup.breaker)
        )

    confs = np.zeros((len(members), T), np.float32)
    paths: dict = {}
    for _order, idxs in stacked_mod.stack_groups(
        members, fb_engs, enabled=stacked
    ).items():
        group = [members[i] for i in idxs]
        ent = streams[group[0].order]
        placed = order_placed(group[0])
        # streams_handle: a PreparedStreams (used when its alphabet
        # matches this group's) or a provider n_symbols -> PreparedStreams
        # (the serve registry's per-alphabet shared handles).
        sh = streams_handle
        if callable(sh):
            sh = sh(group[0].params.n_symbols)
        elif sh is not None and sh.S != group[0].params.n_symbols:
            sh = None
        prep = (
            None if sh is None
            else prepare_record_span(
                group[0].params, placed, ent["stream"].shape[0],
                engine="onehot", want_path=True, streams=sh,
            )
        )
        try:
            g_confs, g_paths = stacked_mod.stacked_posterior_records(
                group, ent["stream"], placed=placed, prepared=prep,
                sup=ctxs[idxs[0]][1],
            )
        except Exception as e:
            # The group re-runs member-by-member below, each under its own
            # session — the per-model fault domains as the degraded path.
            log.error(
                "stacked compare dispatch failed (%s: %s); falling back to "
                "sequential member units", type(e).__name__, e,
            )
        else:
            for k, i in enumerate(idxs):
                confs[i] = g_confs[k]
                paths[i] = np.asarray(g_paths[k])

    calls: list = []
    for i, m in enumerate(members):
        if m.is_null or T == 0:
            calls.append(islands_mod._empty_calls().with_names(m.name))
            continue
        if i not in paths:
            eng, sup = ctxs[i]
            conf, path = pipeline._posterior_record_unit(
                m.params, streams[m.order]["stream"], m.island_states,
                engine=eng, fb_eng=fb_engs[i], want_path=True,
                return_device=False, sup=sup, placed=order_placed(m),
            )
            confs[i] = np.asarray(conf)
            paths[i] = np.asarray(path)
        # Membership from the member's own MPM path, composition from the
        # BASE observations (position-aligned for order-2 members too).
        calls.append(
            islands_mod.call_islands_obs(
                paths[i], symbols,
                island_states=m.island_states, min_len=min_len,
            ).with_names(m.name)
        )

    winner = winner_track(confs, threshold) if T else np.zeros(0, np.int8)
    results = [
        MemberResult(
            name=m.name, loglik=logliks[i],
            log_odds=logliks[i] - logliks[b_idx],
            conf=confs[i], calls=calls[i],
        )
        for i, m in enumerate(members)
    ]
    return RecordComparison(
        record=record, n_symbols=T, baseline=members[b_idx].name,
        members=results, winner=winner,
        winner_calls=winner_calls(members, winner, symbols, min_len=min_len),
    )
