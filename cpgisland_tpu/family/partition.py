"""Emission-support partition analysis — THE eligibility oracle for the
reduced engines.

The one-hot reduction (ops.viterbi_onehot / ops.fb_onehot — the repo's
single biggest perf lever) collapses the K-state DP to a G-state
block-conditioned chain.  What actually makes that factorization valid is
not "the flagship 8-state model" but a property of the EMISSION SUPPORT:
whenever the per-symbol supports {s : B[s, o] > 0} partition the states
into disjoint blocks, the score vector at time t is exactly zero (LOG_ZERO
in max-plus) outside block(o_t), so the recurrence is exactly a
block-to-block recurrence whose per-step matrix is the [G, G] slice of A
between block(o_{t-1}) and block(o_t).

This module computes that structure ONCE — :func:`partition_of` — and
every routing/eligibility decision derives from it:

- ``viterbi_onehot.supports`` / ``fb_onehot.supports`` are thin wrappers
  over :func:`reduced_eligible` (the engines' current domain: one-hot
  states, uniform blocks of exactly :data:`REDUCED_GROUP`);
- the four engine routers (parallel.decode.resolve_engine,
  parallel.posterior.resolve_fb_engine, train.backends.resolve_fb_engine,
  train.backends._seq_onehot) all consult the same functions instead of
  carrying four copies of the check;
- the chunked-EM stats kernel's extra power-of-two-alphabet constraint
  lives in :func:`reduced_stats_eligible` (one copy, previously inlined in
  train.backends).

The analysis itself is MORE general than the engines' current domain: it
reports block structure for any partitioned emission matrix (arbitrary
block count and size, states supporting several symbols of one block).
:class:`EmissionPartition` carries the entry-group / prev-sym threading
metadata — ``group_table[sym]`` is the block a segment entered on symbol
``sym``, which is exactly what the reduced engines' ``prev0`` /
``device_entry_sym`` threading conditions on.

Tri-state convention (shared with the old ``supports_concrete``): the
analysis needs CONCRETE params — under tracing it returns None
("undecidable"); validation sites treat None as "trust the caller",
auto-selection sites as "don't upgrade".
"""

from __future__ import annotations

import dataclasses
from typing import Optional, Union

import numpy as np

from cpgisland_tpu.models.hmm import LOG_ZERO, HmmParams

__all__ = [
    "REDUCED_GROUP",
    "EmissionPartition",
    "partition_concrete",
    "partition_of",
    "reduced_eligible",
    "reduced_eligible_concrete",
    "reduced_stats_eligible",
]

# Block size the reduced kernels implement (2 states per chain step, 2-bit
# backpointers).  ops.viterbi_onehot.GROUP re-exports this value.
REDUCED_GROUP = 2


@dataclasses.dataclass(frozen=True)
class EmissionPartition:
    """Block structure of a partitioned emission matrix.

    ``blocks[b]`` is the ascending tuple of state ids in block b;
    ``block_of_symbol[o]`` / ``block_of_state[k]`` map symbols and states to
    their block id; ``group_table[o]`` is the ascending state ids supporting
    symbol o (``-1``-padded to the largest block) — for uniform-size
    partitions this is exactly the [S, G] group table the reduced kernels
    build per step (``ops.viterbi_onehot._groups``), and ``group_table[
    prev_sym]`` is the entry group the prev-sym threading conditions a
    segment/span on.
    """

    n_states: int
    n_symbols: int
    blocks: tuple  # tuple[tuple[int, ...], ...]
    block_of_symbol: np.ndarray  # [S] int32
    block_of_state: np.ndarray  # [K] int32
    onehot: bool  # every state supports exactly ONE symbol
    uniform: Optional[int]  # the common block size, or None if ragged

    @property
    def n_blocks(self) -> int:
        return len(self.blocks)

    @property
    def group_table(self) -> np.ndarray:
        """[S, max_block] int32 ascending supporting-state ids, -1 pad."""
        width = max(len(b) for b in self.blocks)
        out = np.full((self.n_symbols, width), -1, np.int32)
        for o in range(self.n_symbols):
            states = self.blocks[int(self.block_of_symbol[o])]
            out[o, : len(states)] = states
        return out

    @property
    def reduced(self) -> bool:
        """Inside the reduced engines' implemented domain: one-hot states
        (each state emits exactly one symbol — so each symbol owns its
        block) in uniform blocks of exactly REDUCED_GROUP states."""
        return self.onehot and self.uniform == REDUCED_GROUP

    def entry_group(self, sym: int) -> tuple:
        """States a segment can occupy when its entering symbol is ``sym``
        — the prev-sym threading metadata."""
        return self.blocks[int(self.block_of_symbol[sym])]


def partition_concrete(
    params: HmmParams,
) -> Union[EmissionPartition, bool, None]:
    """Tri-state partition analysis: an :class:`EmissionPartition` when the
    emission supports partition the states, ``False`` when concrete params
    do not partition, ``None`` when the params are traced (undecidable at
    trace time)."""
    try:
        logB = np.asarray(params.log_B)
    except Exception:
        return None  # traced params — a host decision cannot be made
    if logB.ndim != 2:
        return False
    K, S = logB.shape
    # Entries must be real probabilities or structural zeros — anything in
    # between (nan/inf garbage) disqualifies the structure outright.
    if not np.all(np.isfinite(logB) | (logB <= LOG_ZERO / 2)):
        return False
    supp = logB > LOG_ZERO / 2  # [K, S]
    if not supp.any(axis=0).all():
        return False  # a symbol no state emits
    if not supp.any(axis=1).all():
        return False  # a silent state belongs to no block
    # Partition condition: per-symbol supports pairwise EQUAL or DISJOINT.
    # Group symbols by support signature; disjointness then reduces to "no
    # state appears in two distinct signatures".
    sig_to_block: dict = {}
    block_states: list = []
    block_of_symbol = np.empty(S, np.int32)
    for o in range(S):
        key = tuple(np.nonzero(supp[:, o])[0].tolist())
        b = sig_to_block.get(key)
        if b is None:
            b = len(block_states)
            sig_to_block[key] = b
            block_states.append(key)
        block_of_symbol[o] = b
    block_of_state = np.full(K, -1, np.int32)
    for b, states in enumerate(block_states):
        for k in states:
            if block_of_state[k] >= 0:
                return False  # overlapping, non-equal supports
            block_of_state[k] = b
    sizes = {len(b) for b in block_states}
    return EmissionPartition(
        n_states=K,
        n_symbols=S,
        blocks=tuple(block_states),
        block_of_symbol=block_of_symbol,
        block_of_state=block_of_state,
        onehot=bool(np.all(supp.sum(axis=1) == 1)),
        uniform=sizes.pop() if len(sizes) == 1 else None,
    )


def partition_of(params: HmmParams) -> Optional[EmissionPartition]:
    """The partition, or None (traced params OR non-partitioned emissions).
    Callers that must distinguish the two use :func:`partition_concrete`."""
    p = partition_concrete(params)
    return p if isinstance(p, EmissionPartition) else None


def reduced_eligible_concrete(params: HmmParams) -> Optional[bool]:
    """Tri-state reduced-engine eligibility (the old
    ``viterbi_onehot.supports_concrete`` contract): True/False on concrete
    params, None when traced."""
    p = partition_concrete(params)
    if p is None:
        return None
    return bool(p is not False and p.reduced)


def reduced_eligible(params: HmmParams) -> bool:
    """Host-side reduced-engine eligibility: the emission supports
    partition the states into uniform one-hot blocks of REDUCED_GROUP.
    False under tracing — engine selection is a host decision."""
    return reduced_eligible_concrete(params) is True


def reduced_stats_eligible(params: HmmParams) -> bool:
    """Eligibility for the reduced-stream chunked-EM stats kernel
    (fb_onehot._oh_stats_kernel): reduced_eligible AND power-of-two
    n_symbols — the kernel's in-register scatter lowers only for pow2
    alphabets, which 2-states-per-symbol alone does not guarantee
    (previously inlined in train.backends.resolve_fb_engine)."""
    S = params.n_symbols
    return reduced_eligible(params) and S & (S - 1) == 0
