"""Stacked multi-model dispatch: same-order reduced members in ONE launch.

The occupancy half of ROADMAP item 2: ``family.compare`` (and the serve
broker's compare flushes) evaluate N members over the SAME symbol stream,
and until now paid N sequential launch sets — N x the per-pass fixed cost
the r8 attribution showed dominates.  Different members' reduced chains
over one pair stream are exactly as independent as the r9 fused kernel's
fwd/bwd pair, so members that (a) share a stream order (hence an
alphabet) and (b) resolve to the reduced ``onehot`` FB engine group into
ONE stacked dispatch (parallel.posterior.posterior_sharded_stacked →
ops.fb_onehot's stacked kernels).

Exactness contract: the stacked unit's per-member confidence/path is
BIT-IDENTICAL to the member's own sequential record unit on the same
placed stream/geometry (the stacked kernels run the single-model
arithmetic per member, op for op) — so grouping changes scheduling, never
results.  Members outside the domain (dense engines, null scorers, traced
breaker demotions) stay on the sequential arm; a stacked unit whose
supervised dispatch ultimately fails falls back to the sequential arm
too, restoring the per-model fault domains as the degraded path.
"""

from __future__ import annotations

import logging

log = logging.getLogger(__name__)

__all__ = ["stack_groups", "stacked_posterior_records"]


def stack_groups(members, fb_engines, enabled: bool = True) -> dict:
    """order -> member-index list for same-order members whose RESOLVED FB
    engine is ``'onehot'`` (the stacked kernels' domain).  Groups need at
    least 2 members — a singleton gains nothing from stacking.  ``fb_engines``
    aligns with ``members`` (None for members that run no posterior)."""
    if not enabled:
        return {}
    by_order: dict = {}
    for i, m in enumerate(members):
        if m.is_null or fb_engines[i] != "onehot":
            continue
        by_order.setdefault(m.order, []).append(i)
    return {o: ix for o, ix in by_order.items() if len(ix) >= 2}


def stacked_posterior_records(
    members,
    symbols,
    *,
    placed=None,
    pad_to=None,
    prepared=None,
    sup=None,
    what: str = "compare.stacked",
):
    """ONE stacked dispatch for a group: per-member (conf [T], path [T])
    host arrays over one record (supervised as one unit — the group's
    caller chooses the supervising session; on give-up the caller falls
    back to sequential per-member units under their own supervisors)."""
    from cpgisland_tpu import obs as obs_mod
    from cpgisland_tpu import resilience
    from cpgisland_tpu.parallel.posterior import posterior_sharded_stacked

    params_list = tuple(m.params for m in members)
    island_states = [m.island_states for m in members]
    sup = sup if sup is not None else resilience.default_supervisor()

    def unit():
        # Host-fetching inside the unit blocks it, so a device fault
        # surfaces where the supervisor's retry re-dispatches (the shared
        # record-unit discipline of pipeline._posterior_record_unit).
        confs, paths = posterior_sharded_stacked(
            params_list, symbols, island_states, want_path=True,
            pad_to=pad_to, placed=placed, prepared=prepared,
        )
        return confs, paths

    obs_mod.event(
        "stacked_dispatch", _dedupe=True, kind="compare",
        n_members=len(members), order=int(members[0].order),
    )
    return sup.run(
        unit, what=what, engine="fb.onehot.stacked",
        items=float(symbols.size) * len(members),
    )
