"""Model-family layer: emission-support partition analysis, named family
members, and the multi-model posterior-comparison workload.

- :mod:`cpgisland_tpu.family.partition` — ``partition_of(params)``, THE
  eligibility oracle behind the reduced (onehot) engines and all four
  engine routers; block-structure + entry-group threading metadata.
- :mod:`cpgisland_tpu.family.members` — first-class named models
  (flagship, two-state, order-2 dinucleotide over the pair alphabet,
  null background) routing through the existing engine registry.
- :mod:`cpgisland_tpu.family.compare` — N members over one prepared
  stream: per-model log-odds, per-model islands, winner track.
- :mod:`cpgisland_tpu.family.stacked` — multi-model kernel occupancy:
  same-order reduced members grouped into ONE stacked launch set
  (ops.fb_onehot's stacked kernels), bit-identical to the sequential arm.
"""

from cpgisland_tpu.family import stacked  # noqa: F401  (public submodule)
from cpgisland_tpu.family.compare import (
    DEFAULT_WINNER_THRESHOLD,
    MemberResult,
    RecordComparison,
    compare_record,
    resolve_baseline,
    winner_track,
)
from cpgisland_tpu.family.members import (
    MEMBER_NAMES,
    Member,
    builtin_member,
    default_members,
    member_from_params,
    members_from_names,
)
from cpgisland_tpu.family.partition import (
    REDUCED_GROUP,
    EmissionPartition,
    partition_concrete,
    partition_of,
    reduced_eligible,
    reduced_eligible_concrete,
    reduced_stats_eligible,
)

__all__ = [
    "REDUCED_GROUP",
    "EmissionPartition",
    "partition_concrete",
    "partition_of",
    "reduced_eligible",
    "reduced_eligible_concrete",
    "reduced_stats_eligible",
    "Member",
    "MEMBER_NAMES",
    "builtin_member",
    "member_from_params",
    "members_from_names",
    "default_members",
    "MemberResult",
    "RecordComparison",
    "compare_record",
    "resolve_baseline",
    "winner_track",
    "DEFAULT_WINNER_THRESHOLD",
]
