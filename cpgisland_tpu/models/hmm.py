"""HMM model core: the (pi, A, B) parameter pytree, kept in log space.

Replaces the reference's Mahout ``HmmModel`` (initial-prob Vector, transition
Matrix, emission Matrix; accessed at CpGIslandFinder.java:204-206).  We store
log-probabilities because every TPU dynamic program (Viterbi max-plus scan,
forward-backward log-semiring scan) consumes them directly; probability-space
views are computed on demand.

Serialization:
- ``dump_text`` / ``load_text`` reproduce the reference's plain-text model dump
  byte layout (per state: one pi line, one transition row, one emission row;
  CpGIslandFinder.java:207-224).
- npz round-trip lives in ``cpgisland_tpu.utils.checkpoint``.
"""

from __future__ import annotations

import dataclasses
from typing import IO, Union

import jax
import jax.numpy as jnp
import numpy as np

# log(0) stand-in. Finite so that (-inf) - (-inf) never produces NaNs inside
# jitted log-semiring arithmetic; exp(LOG_ZERO) underflows to exactly 0.0f.
LOG_ZERO = -1e30


def _log(p: jnp.ndarray) -> jnp.ndarray:
    return jnp.where(p > 0, jnp.log(jnp.maximum(p, 1e-300)), LOG_ZERO)


@jax.tree_util.register_dataclass
@dataclasses.dataclass(frozen=True)
class HmmParams:
    """HMM parameters in log space.

    log_pi: [K]    initial state log-probabilities
    log_A:  [K, K] transition log-probabilities, rows sum (in prob space) to 1
    log_B:  [K, M] emission log-probabilities, rows sum to 1
    """

    log_pi: jnp.ndarray
    log_A: jnp.ndarray
    log_B: jnp.ndarray

    @property
    def n_states(self) -> int:
        return self.log_pi.shape[-1]

    @property
    def n_symbols(self) -> int:
        return self.log_B.shape[-1]

    @property
    def pi(self) -> jnp.ndarray:
        return jnp.exp(self.log_pi)

    @property
    def A(self) -> jnp.ndarray:
        return jnp.exp(self.log_A)

    @property
    def B(self) -> jnp.ndarray:
        return jnp.exp(self.log_B)

    @classmethod
    def from_probs(cls, pi, A, B, dtype=jnp.float32) -> "HmmParams":
        pi = jnp.asarray(pi, dtype=dtype)
        A = jnp.asarray(A, dtype=dtype)
        B = jnp.asarray(B, dtype=dtype)
        if A.shape != (pi.shape[0], pi.shape[0]) or B.shape[0] != pi.shape[0]:
            raise ValueError(f"inconsistent shapes pi={pi.shape} A={A.shape} B={B.shape}")
        return cls(log_pi=_log(pi), log_A=_log(A), log_B=_log(B))

    def astype(self, dtype) -> "HmmParams":
        return HmmParams(
            log_pi=self.log_pi.astype(dtype),
            log_A=self.log_A.astype(dtype),
            log_B=self.log_B.astype(dtype),
        )

    def max_abs_diff(self, other: "HmmParams") -> jnp.ndarray:
        """Max absolute difference in probability space — the convergence metric
        (the reference's MR driver stops when |model_t+1 - model_t| < epsilon,
        CpGIslandFinder.java:96,200-201)."""
        return jnp.maximum(
            jnp.max(jnp.abs(self.pi - other.pi)),
            jnp.maximum(
                jnp.max(jnp.abs(self.A - other.A)),
                jnp.max(jnp.abs(self.B - other.B)),
            ),
        )

    def validate(self, atol: float = 1e-4) -> None:
        """Raise if any distribution row is not (approximately) stochastic."""
        for name, row_sums in (
            ("pi", np.asarray(jnp.sum(self.pi))),
            ("A", np.asarray(jnp.sum(self.A, axis=-1))),
            ("B", np.asarray(jnp.sum(self.B, axis=-1))),
        ):
            if not np.allclose(row_sums, 1.0, atol=atol):
                raise ValueError(f"{name} rows not stochastic: sums={row_sums}")


def sample_sequence(params: HmmParams, key, length: int):
    """Generate (states [T], observations [T]) from the model.

    The generative twin of decoding (Mahout's HmmEvaluator exposes the same
    pair of operations; the reference driver only ever decodes,
    CpGIslandFinder.java:260).  Used for synthetic-genome fixtures and
    planted-island recovery tests.
    """
    k_init, k_scan = jax.random.split(key)
    s0 = jax.random.categorical(k_init, params.log_pi)

    def step(state, k):
        k_trans, k_emit = jax.random.split(k)
        obs = jax.random.categorical(k_emit, params.log_B[state])
        nxt = jax.random.categorical(k_trans, params.log_A[state])
        return nxt, (state, obs)

    _, (states, obs) = jax.lax.scan(step, s0, jax.random.split(k_scan, length))
    return states.astype(jnp.int32), obs.astype(jnp.uint8)


def java_double_str(d: float) -> str:
    """Format ``d`` exactly as Java ``Double.toString(double)`` would.

    The reference's model dump concatenates Double.toString values
    (CpGIslandFinder.java:209-222), whose grammar differs from Python repr:
    decimal form iff 1e-3 <= |d| < 1e7, otherwise ``d.dddE±x`` scientific
    notation with an unpadded exponent and no '+' (so 2.5e-4 prints
    "2.5E-4", not "0.00025"); a fraction part is always present ("1.0",
    "1.0E7").  Digits are the shortest sequence that round-trips — Python
    repr's contract, which matches Double.toString as specified (and as
    implemented exactly since JDK 19's Ryu rewrite).
    """
    import math
    from decimal import Decimal

    if math.isnan(d):
        return "NaN"
    if math.isinf(d):
        return "Infinity" if d > 0 else "-Infinity"
    sign = "-" if math.copysign(1.0, d) < 0 else ""
    if d == 0.0:
        return sign + "0.0"
    _, digits, exp = Decimal(repr(abs(d))).as_tuple()
    ds = "".join(map(str, digits)).rstrip("0") or "0"
    E = len(digits) + exp - 1  # value = ds[0].ds[1:] * 10**E
    if -3 <= E <= 6:
        if E < 0:
            return sign + "0." + "0" * (-E - 1) + ds
        ip = ds[: E + 1].ljust(E + 1, "0")
        return sign + ip + "." + (ds[E + 1 :] or "0")
    return sign + ds[0] + "." + (ds[1:] or "0") + "E" + str(E)


def dump_text(params: HmmParams, fp: Union[str, IO[str]]) -> None:
    """Write the reference's plain-text model dump, byte-identical.

    Layout (CpGIslandFinder.java:207-224): for each hidden state i, three lines —
    pi(i); the 8 transition probs A[i, :] space-separated with a trailing space;
    the 4 emission probs B[i, :] likewise.  Numbers are formatted with
    :func:`java_double_str` (Java ``Double.toString`` semantics — the
    reference writes `Double.toString(model.get(i, j))` values, and trained
    cross-block leakage probs fall below 1e-3 where Java switches to
    scientific notation).
    """
    own = isinstance(fp, str)
    f = open(fp, "w") if own else fp
    try:
        pi = np.asarray(params.pi, dtype=np.float64)
        A = np.asarray(params.A, dtype=np.float64)
        B = np.asarray(params.B, dtype=np.float64)
        for i in range(params.n_states):
            f.write(java_double_str(float(pi[i])))
            f.write("\n")
            f.write("".join(java_double_str(float(v)) + " " for v in A[i]))
            f.write("\n")
            f.write("".join(java_double_str(float(v)) + " " for v in B[i]))
            f.write("\n")
    finally:
        if own:
            f.close()


def load_text(fp: Union[str, IO[str]], dtype=jnp.float32) -> HmmParams:
    """Parse a model dump written by :func:`dump_text`."""
    own = isinstance(fp, str)
    f = open(fp) if own else fp
    try:
        lines = [ln.strip() for ln in f.read().splitlines() if ln.strip()]
    finally:
        if own:
            f.close()
    if len(lines) % 3 != 0:
        raise ValueError(f"model text has {len(lines)} non-empty lines, not a multiple of 3")
    k = len(lines) // 3
    pi = np.array([float(lines[3 * i]) for i in range(k)])
    A = np.array([[float(v) for v in lines[3 * i + 1].split()] for i in range(k)])
    B = np.array([[float(v) for v in lines[3 * i + 2].split()] for i in range(k)])
    return HmmParams.from_probs(pi, A, B, dtype=dtype)
