"""Named model presets.

``durbin_cpg8`` is the flagship: the 8-state CpG+/CpG- model the reference
hardcodes as its Baum-Welch initialization (numeric tables at
CpGIslandFinder.java:155-173; the transition probabilities within each +/- block
are the Durbin et al. "Biological Sequence Analysis" CpG tables, with 0.0025
uniform cross-block leakage so each row sums to exactly 1.0).

State ids match the reference's hidden-state map (CpGIslandFinder.java:182-189):
0..3 = A+ C+ G+ T+ (inside a CpG island), 4..7 = A- C- G- T- (outside).
Emissions are deterministic one-hot (state X+- emits x with p=1), which makes the
emission matrix a fixed point of EM: structural zeros stay zero through
Baum-Welch, so training only ever updates transitions and initials.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from cpgisland_tpu.models.hmm import HmmParams

HIDDEN_STATE_NAMES = ("A+", "C+", "G+", "T+", "A-", "C-", "G-", "T-")
EMITTED_STATE_NAMES = ("a", "c", "g", "t")

# Initial distribution: islands are rarer than background
# (CpGIslandFinder.java:155).
_DURBIN_PI = np.array([0.05, 0.05, 0.05, 0.05, 0.2, 0.2, 0.2, 0.2])

# Within-block rows are the Durbin et al. CpG-island (+) and background (-)
# dinucleotide tables; 0.0025 per-entry cross-block leakage
# (CpGIslandFinder.java:157-164).
_LEAK = 0.0025
_DURBIN_PLUS = np.array(
    [
        [0.170, 0.274, 0.426, 0.120],
        [0.170, 0.358, 0.274, 0.188],
        [0.161, 0.329, 0.375, 0.125],
        [0.079, 0.345, 0.384, 0.182],
    ]
)
_DURBIN_MINUS = np.array(
    [
        [0.300, 0.205, 0.275, 0.210],
        [0.393, 0.137, 0.088, 0.372],
        [0.248, 0.246, 0.288, 0.208],
        [0.177, 0.239, 0.282, 0.292],
    ]
)


def durbin_cpg8(dtype=jnp.float32) -> HmmParams:
    """The 8-state A+-C+-G+-T+- CpG model (reference init, java:155-173)."""
    A = np.full((8, 8), _LEAK)
    A[:4, :4] = _DURBIN_PLUS
    A[4:, 4:] = _DURBIN_MINUS
    B = np.zeros((8, 4))
    B[np.arange(8), np.arange(8) % 4] = 1.0  # one-hot: X+- emits x
    return HmmParams.from_probs(_DURBIN_PI, A, B, dtype=dtype)


def two_state_cpg(p_stay_island: float = 0.999, p_stay_bg: float = 0.9995, dtype=jnp.float32) -> HmmParams:
    """A minimal 2-state island/background model (BASELINE.md config 1).

    State 0 = island (GC-rich emissions), state 1 = background (uniform-ish).
    """
    pi = np.array([0.1, 0.9])
    A = np.array(
        [
            [p_stay_island, 1.0 - p_stay_island],
            [1.0 - p_stay_bg, p_stay_bg],
        ]
    )
    B = np.array(
        [
            [0.15, 0.35, 0.35, 0.15],  # island: C/G enriched
            [0.30, 0.20, 0.20, 0.30],  # background: A/T enriched
        ]
    )
    return HmmParams.from_probs(pi, A, B, dtype=dtype)


#: Island (first-half) state ids of the dinucleotide model, the pair-alphabet
#: analogue of the flagship's states 0..3.
DINUC_ISLAND_STATES = tuple(range(16))

#: Pair-symbol index of the CpG dinucleotide ("CG" = prev C, cur G) in the
#: recoded alphabet (codec.recode_pairs) — the event the Gardiner-Garden/
#: Frommer obs/exp filter counts.
CPG_PAIR = 1 * 4 + 2


def dinuc_cpg(dtype=jnp.float32) -> HmmParams:
    """Order-2 (dinucleotide-emission) CpG model over the PAIR alphabet.

    The biology the reference's Gardiner-Garden/Frommer filters chase
    (CpGIslandFinder.java:290-339: GC content + CpG obs/exp over called
    runs) lives in DINUCLEOTIDES — the GGF obs/exp statistic literally
    counts the CG pair.  This member makes that signal a first-class
    observation: the codec recodes the stream to the 16-symbol pair
    alphabet (:func:`cpgisland_tpu.utils.codec.recode_pairs`, ``pair =
    prev * 4 + cur``; :data:`CPG_PAIR` is the CpG event itself) and the
    model's 32 states are (pair, +/-) — state ``sign * 16 + pair`` emits
    exactly its own pair, so the emission support partitions the states
    into 16 blocks of 2 and the model routes through the reduced
    block-conditioned engines (family.partition_of) like the flagship.

    Transitions chain pairs: (a, b, s) -> (b, c, s') with within-sign
    probability equal to the Durbin table ``P_s[b, c]`` and the flagship's
    0.0025 cross-sign leakage per reachable target; transitions to
    non-chaining pairs (prev of the next pair != cur of this one) are
    structural zeros.  Rows sum to exactly 1.0 (4 within-sign entries
    summing 1 - 4*LEAK + 4 leak entries), and one-hot emissions are EM
    fixed points, so training preserves the family structure — exactly
    like the flagship.

    The first pair of a record has no left context and recodes to the
    SELF-CONTEXT pair ``(c0, c0)`` (chain-consistent and in-alphabet —
    codec.recode_pairs documents why an out-of-alphabet marker would dead-
    end the structural transition zeros); spans/continuations thread
    ``prev`` through recode_pairs instead.  The lift is exact: every
    complete-path probability equals the flagship's times the constant
    1/4 prior split of the opening pair state, so log-likelihoods differ
    by exactly -log 4 and posteriors are identical (pinned in tests).
    """
    A = np.zeros((32, 32))
    for sign, tab in ((0, _DURBIN_PLUS), (1, _DURBIN_MINUS)):
        for a in range(4):
            for b in range(4):
                row = sign * 16 + a * 4 + b
                for c in range(4):
                    A[row, sign * 16 + b * 4 + c] = tab[b, c]
                    A[row, (1 - sign) * 16 + b * 4 + c] = _LEAK
    # Same island/background prior mass split as the flagship (0.2 / 0.8),
    # uniform within each sign's 16 pairs.
    pi = np.concatenate([np.full(16, 0.2 / 16), np.full(16, 0.8 / 16)])
    B = np.zeros((32, 16))
    B[np.arange(32), np.arange(32) % 16] = 1.0
    return HmmParams.from_probs(pi, A, B, dtype=dtype)


def _background_stationary() -> np.ndarray:
    """Stationary distribution of the (leak-free, row-renormalized) Durbin
    background chain — the GGF-style expected base composition outside
    islands."""
    P = _DURBIN_MINUS / _DURBIN_MINUS.sum(axis=1, keepdims=True)
    w, v = np.linalg.eig(P.T)
    i = int(np.argmin(np.abs(w - 1.0)))
    pi = np.real(v[:, i])
    pi = np.abs(pi)
    return pi / pi.sum()


def null_background(n_symbols: int = 4, dtype=jnp.float32) -> HmmParams:
    """Single-state null/background scoring model — the log-odds
    denominator of the multi-model comparison workload (family.compare).

    The Gardiner-Garden/Frommer criteria are threshold tests against
    EXPECTED background composition; this member is that expectation as a
    scoreable model: one state, self-transition 1, emitting the stationary
    composition of the Durbin background chain.  ``n_symbols=4`` emits
    base frequencies; ``n_symbols=16`` emits the stationary dinucleotide
    joint ``pi(a) * P-(b|a)`` over the pair alphabet (the order-2 members'
    comparison partner).  No island states — a comparison's winner track
    falls back to it exactly where no island model beats background.
    """
    statv = _background_stationary()
    if n_symbols == 4:
        B = statv[None, :]
    elif n_symbols == 16:
        P = _DURBIN_MINUS / _DURBIN_MINUS.sum(axis=1, keepdims=True)
        B = (statv[:, None] * P).reshape(1, 16)
    else:
        raise ValueError(
            f"null_background supports the base (4) and pair (16) "
            f"alphabets, got n_symbols={n_symbols}"
        )
    return HmmParams.from_probs(
        np.ones(1), np.ones((1, 1)), B / B.sum(), dtype=dtype
    )


def random_hmm(
    key: jax.Array, n_states: int, n_symbols: int, dtype=jnp.float32,
    partition: "int | None" = None,
) -> HmmParams:
    """Random row-stochastic model (the reference's commented-out
    ``buildRandomModel`` alternative, CpGIslandFinder.java:153).

    ``partition``: emission-support group size G — instead of random
    emissions, build ONE-HOT emissions with exactly G states per symbol
    (state k emits symbol ``k % n_symbols``; requires ``n_states == G *
    n_symbols``), so tests can generate family-eligible models of
    arbitrary (power-of-two or otherwise) block count ``n_symbols``.
    ``partition=2`` models are reduced-engine eligible
    (family.partition_of -> .reduced); transitions and initials stay
    random either way.
    """
    k_pi, k_a, k_b = jax.random.split(key, 3)
    pi = jax.random.dirichlet(k_pi, jnp.ones(n_states))
    A = jax.random.dirichlet(k_a, jnp.ones(n_states), shape=(n_states,))
    if partition is not None:
        if n_states != partition * n_symbols:
            raise ValueError(
                f"partition={partition} needs n_states == partition * "
                f"n_symbols, got {n_states} != {partition} * {n_symbols}"
            )
        B = np.zeros((n_states, n_symbols))
        B[np.arange(n_states), np.arange(n_states) % n_symbols] = 1.0
        B = jnp.asarray(B)
    else:
        B = jax.random.dirichlet(k_b, jnp.ones(n_symbols), shape=(n_states,))
    return HmmParams.from_probs(pi, A, B, dtype=dtype)
