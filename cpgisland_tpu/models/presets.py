"""Named model presets.

``durbin_cpg8`` is the flagship: the 8-state CpG+/CpG- model the reference
hardcodes as its Baum-Welch initialization (numeric tables at
CpGIslandFinder.java:155-173; the transition probabilities within each +/- block
are the Durbin et al. "Biological Sequence Analysis" CpG tables, with 0.0025
uniform cross-block leakage so each row sums to exactly 1.0).

State ids match the reference's hidden-state map (CpGIslandFinder.java:182-189):
0..3 = A+ C+ G+ T+ (inside a CpG island), 4..7 = A- C- G- T- (outside).
Emissions are deterministic one-hot (state X+- emits x with p=1), which makes the
emission matrix a fixed point of EM: structural zeros stay zero through
Baum-Welch, so training only ever updates transitions and initials.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from cpgisland_tpu.models.hmm import HmmParams

HIDDEN_STATE_NAMES = ("A+", "C+", "G+", "T+", "A-", "C-", "G-", "T-")
EMITTED_STATE_NAMES = ("a", "c", "g", "t")

# Initial distribution: islands are rarer than background
# (CpGIslandFinder.java:155).
_DURBIN_PI = np.array([0.05, 0.05, 0.05, 0.05, 0.2, 0.2, 0.2, 0.2])

# Within-block rows are the Durbin et al. CpG-island (+) and background (-)
# dinucleotide tables; 0.0025 per-entry cross-block leakage
# (CpGIslandFinder.java:157-164).
_LEAK = 0.0025
_DURBIN_PLUS = np.array(
    [
        [0.170, 0.274, 0.426, 0.120],
        [0.170, 0.358, 0.274, 0.188],
        [0.161, 0.329, 0.375, 0.125],
        [0.079, 0.345, 0.384, 0.182],
    ]
)
_DURBIN_MINUS = np.array(
    [
        [0.300, 0.205, 0.275, 0.210],
        [0.393, 0.137, 0.088, 0.372],
        [0.248, 0.246, 0.288, 0.208],
        [0.177, 0.239, 0.282, 0.292],
    ]
)


def durbin_cpg8(dtype=jnp.float32) -> HmmParams:
    """The 8-state A+-C+-G+-T+- CpG model (reference init, java:155-173)."""
    A = np.full((8, 8), _LEAK)
    A[:4, :4] = _DURBIN_PLUS
    A[4:, 4:] = _DURBIN_MINUS
    B = np.zeros((8, 4))
    B[np.arange(8), np.arange(8) % 4] = 1.0  # one-hot: X+- emits x
    return HmmParams.from_probs(_DURBIN_PI, A, B, dtype=dtype)


def two_state_cpg(p_stay_island: float = 0.999, p_stay_bg: float = 0.9995, dtype=jnp.float32) -> HmmParams:
    """A minimal 2-state island/background model (BASELINE.md config 1).

    State 0 = island (GC-rich emissions), state 1 = background (uniform-ish).
    """
    pi = np.array([0.1, 0.9])
    A = np.array(
        [
            [p_stay_island, 1.0 - p_stay_island],
            [1.0 - p_stay_bg, p_stay_bg],
        ]
    )
    B = np.array(
        [
            [0.15, 0.35, 0.35, 0.15],  # island: C/G enriched
            [0.30, 0.20, 0.20, 0.30],  # background: A/T enriched
        ]
    )
    return HmmParams.from_probs(pi, A, B, dtype=dtype)


def random_hmm(key: jax.Array, n_states: int, n_symbols: int, dtype=jnp.float32) -> HmmParams:
    """Random row-stochastic model (the reference's commented-out
    ``buildRandomModel`` alternative, CpGIslandFinder.java:153)."""
    k_pi, k_a, k_b = jax.random.split(key, 3)
    pi = jax.random.dirichlet(k_pi, jnp.ones(n_states))
    A = jax.random.dirichlet(k_a, jnp.ones(n_states), shape=(n_states,))
    B = jax.random.dirichlet(k_b, jnp.ones(n_symbols), shape=(n_states,))
    return HmmParams.from_probs(pi, A, B, dtype=dtype)
