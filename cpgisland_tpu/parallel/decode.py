"""Sequence-parallel Viterbi over a device mesh (no island clipping).

The reference decodes 1 MiB chunks one at a time on a single JVM
(CpGIslandFinder.java:256-260), resetting island state at every boundary
(SURVEY.md C12).  Here one long sequence is sharded across the mesh's devices
along time; each device runs the blockwise passes of ops.viterbi_parallel over
its shard, and the cross-shard stitching is exact:

- forward message: device transfer matrices ([K, K] max-plus products) are
  `all_gather`ed, so every device computes its exact entering score vector;
- backward message: device composition tables ([K] exit->entry maps) are
  `all_gather`ed, so every device anchors its exit state to the global argmax.

Total communication per decode: two all_gathers of D*K*K and D*K elements over
ICI — independent of sequence length.  The decoded path comes back sharded
(out_spec P(axis)); islands can then be called over the whole genome with no
boundary artifacts, fixing the reference's clipping quirk.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from cpgisland_tpu import obs as obs_mod
from cpgisland_tpu import resilience
from cpgisland_tpu.family import partition as family_partition
from cpgisland_tpu.models.hmm import HmmParams
from cpgisland_tpu.ops import viterbi_onehot, viterbi_pallas
from cpgisland_tpu.ops.viterbi_parallel import (
    DEFAULT_BLOCK,
    _enter_vectors,
    _identity_logmat,
    _step_tables,
    _suffix_compositions,
    get_passes,
    maxplus_matmul,
    nrm_maxplus,
    nrm_maxplus_vec,
)
from cpgisland_tpu.parallel.mesh import SEQ_AXIS, fetch_sharded_prefix, make_mesh


def decode_engine_twin(engine: str, params: HmmParams) -> Optional[str]:
    """Next rung of the decode engines' parity-twin ladder
    (resilience.breaker.kernel_ladder with the DECODE eligibility: Pallas
    needs TPU + the 3-bit backpointer packing).  Results stay exact across
    a demotion because the twins are parity-pinned (PARITY.md C10)."""
    from cpgisland_tpu.resilience.breaker import kernel_ladder

    return kernel_ladder(
        jax.default_backend() == "tpu" and viterbi_pallas.supports(params)
    )(engine)


def resolve_engine(engine: str, params: HmmParams, *, breaker=None) -> str:
    """'auto' picks the reduced one-hot kernels on TPU when the model's
    emission structure supports them (ops.viterbi_onehot — the flagship
    8-state model does), else the dense Pallas kernels when the model fits
    their 3-bit backpointer packing, else the XLA scans (incl. the CPU test
    mesh, where Pallas would run interpreted).  Under 'auto', engines
    tripped by the resilience breaker (repeated dispatch faults) demote
    down the parity-twin ladder for the cooldown window; an EXPLICIT
    engine request is honored as-is — silently swapping a named engine
    would mislabel bench/parity measurements that exist to certify that
    specific lowering.  ``breaker``: which EngineBreaker gates the
    demotion — a serve Session passes its own so one tenant's faults
    cannot demote the whole process (default: the process-global one)."""
    if engine == "auto":
        resolved = "xla"
        if jax.default_backend() == "tpu":
            # The ONE eligibility oracle (family.partition): the reduced
            # block-conditioned engines serve any member whose emission
            # support partitions the states into one-hot pairs — flagship,
            # dinuc_cpg, random partition=2 families alike.
            if family_partition.reduced_eligible(params):
                resolved = "onehot"
            elif viterbi_pallas.supports(params):
                resolved = "pallas"
        obs_mod.engine_decision(
            site="decode.resolve_engine", choice=resolved, requested=engine
        )
        if breaker is None:
            breaker = resilience.get_breaker()
        return breaker.degrade(
            "decode", resolved, lambda e: decode_engine_twin(e, params)
        )
    if engine not in ("xla", "pallas", "onehot"):
        raise ValueError(f"unknown engine {engine!r}; expected auto|xla|pallas|onehot")
    if engine == "pallas" and not viterbi_pallas.supports(params):
        raise ValueError(f"pallas engine needs n_states <= 8, got {params.n_states}")
    if engine == "onehot" and not family_partition.reduced_eligible(params):
        raise ValueError(
            "onehot engine needs a one-hot emission-support partition with "
            "2 states per symbol (family.partition_of; concrete params)"
        )
    obs_mod.engine_decision(
        site="decode.resolve_engine", choice=engine, requested=engine
    )
    return engine


def _engine_for_record(eng: str, obs: np.ndarray, params: HmmParams) -> str:
    """Demote 'onehot' to a dense engine for records outside its exactness
    domain (first position has no real emission — the reduced chain has no
    entry group there; see ops.viterbi_onehot's module docstring).  The
    demotion target honors the dense engines' own eligibility: the Pallas
    kernels only on TPU and only when the 3-bit backpointer packing fits."""
    if eng == "onehot" and (obs.shape[0] == 0 or int(obs[0]) >= params.n_symbols):
        if jax.default_backend() == "tpu" and viterbi_pallas.supports(params):
            demoted = "pallas"
        else:
            demoted = "xla"
        obs_mod.engine_decision(
            site="decode.pad_first_demotion", choice=demoted, requested=eng
        )
        return demoted
    return eng


def _prev_real_symbol(obs: np.ndarray, lo: int, n_symbols: int) -> int:
    """Last real symbol strictly before obs[lo] (host scan; O(PAD run))."""
    i = lo - 1
    while i >= 0 and int(obs[i]) >= n_symbols:
        i -= 1
    return int(obs[i]) if i >= 0 else 0


# The per-device entry-symbol helper lives with the reduced engines
# (ops.viterbi_onehot.device_entry_sym) — shared by decode and FB.
_device_entry_sym = viterbi_onehot.device_entry_sym


def _shard_body(block_size: int, axis: str, engine: str = "xla",
                continuation: bool = False):
    """Per-device decode body (runs under shard_map).

    body(params, obs_shard [L], v_entry [K], exit_anchor [], prev0 []) ->
    (path [L] sharded, prev_exit [] replicated).

    ``continuation=False`` is the standalone decode: the segment starts the
    sequence, so device 0's first symbol is the init (its emission folds into
    v0) and ``v_entry`` is ignored.  ``continuation=True`` decodes a LATER
    span of a longer sequence: every position is a real step and ``v_entry``
    is the (normalized) score vector at the previous span's last position.
    ``exit_anchor`` >= 0 pins the segment's final state (the next span's
    entry, threaded by the span driver); < 0 uses the local argmax.
    ``prev_exit`` is the state just before the segment's first step — the
    previous span's exit under the global argmax path.
    """
    products, backpointers, backtrace = get_passes(engine)

    def body(params: HmmParams, obs_shard: jnp.ndarray, v_entry: jnp.ndarray,
             exit_anchor: jnp.ndarray, prev0: jnp.ndarray):
        K = params.n_states
        pad_sym = params.n_symbols
        _, emit_ext = _step_tables(params)
        d = jax.lax.axis_index(axis)
        n_dev = jax.lax.axis_size(axis)
        obs_c = jnp.minimum(obs_shard.astype(jnp.int32), pad_sym)

        prev_d = (
            _device_entry_sym(obs_c, pad_sym, axis, prev0)
            if engine == "onehot" else None
        )
        if continuation:
            v0_local = v_entry
            steps = obs_c
        else:
            # Device 0's first symbol is the init (its emission folds into
            # v0); it becomes an identity step so every device has exactly L
            # steps, and "state after step k" is the state at local position
            # k on all devices.
            v0_local = params.log_pi + emit_ext[obs_c[0]]
            steps = obs_c.at[0].set(jnp.where(d == 0, pad_sym, obs_c[0]))
        nb = steps.shape[0] // block_size
        steps2 = steps.reshape(nb, block_size).T

        incl, _, total = products(params, steps2, prev_d)

        # Forward stitch: v_enter(shard d) = v0 (x) prod of earlier shards.
        # Device totals/prefixes are normalized (nrm_maxplus): scores must
        # never accumulate sequence-length magnitude in f32.
        totals = jax.lax.all_gather(total, axis)  # [D, K, K]
        v0 = jax.lax.all_gather(v0_local, axis)[0]  # device 0's init vector

        def fwd(carry, t):
            return nrm_maxplus(maxplus_matmul(carry, t)), carry

        _, prefixes = jax.lax.scan(fwd, _identity_logmat(K) + v0[:, None] * 0.0, totals)
        my_prefix = prefixes[d]  # [K, K] product of shards 0..d-1
        v_shard = nrm_maxplus_vec(jnp.max(v0[:, None] + my_prefix, axis=0))  # [K]

        v_enter = _enter_vectors(v_shard, incl)
        delta_blocks, F, bps = backpointers(params, v_enter, steps2, prev_d)

        # Backward stitch: global argmax composed through later shards' maps.
        Gsuf = _suffix_compositions(F)
        ftables = jax.lax.all_gather(Gsuf[0], axis)  # [D, K]
        delta_last = jax.lax.all_gather(delta_blocks[-1], axis)[n_dev - 1]
        s_local = jnp.argmax(delta_last).astype(jnp.int32)
        s_final = jnp.where(exit_anchor >= 0, exit_anchor.astype(jnp.int32), s_local)

        def bwd(s, ft):
            return ft[s], s

        # exit[D-1] = s_final; exit[d] = ftable_{d+1}[exit[d+1]].  The reverse
        # scan emits exit[1..D-1] at ys positions and exit[0] as final carry.
        exit0, exits_tail = jax.lax.scan(bwd, s_final, ftables[1:], reverse=True)
        exits_dev = jnp.concatenate([exit0[None], exits_tail])
        my_exit = exits_dev[d]

        # Per-block exits anchored at my_exit, then the light backtrace.
        block_exits = jnp.concatenate([Gsuf[1:, :][:, my_exit], my_exit[None]])
        path = backtrace(bps, block_exits)
        # Every device computes the same prev_exit; the pmax is a semantic
        # no-op that makes the replication provable to the vma checker.
        prev_exit = jax.lax.pmax(ftables[0][exits_dev[0]], axis)
        return path, prev_exit

    return body


@functools.lru_cache(maxsize=32)
def _sharded_fn(mesh: Mesh, block_size: int, engine: str = "xla",
                continuation: bool = False):
    """Compile the sharded decode once per (mesh, block_size, engine,
    continuation); params are a traced argument, so model updates never
    trigger recompilation."""
    axis = mesh.axis_names[0]
    body = _shard_body(block_size, axis, engine, continuation)
    # check_vma can't see through pallas_call out_shapes; disable for that engine.
    return jax.jit(
        jax.shard_map(
            body,
            mesh=mesh,
            in_specs=(P(), P(axis), P(), P(), P()),
            out_specs=(P(axis), P()),
            check_vma=engine == "xla",
        )
    )


def _span_total_body(block_size: int, axis: str, engine: str,
                     continuation: bool):
    """Products-only body: the span's normalized max-plus transfer operator.

    Sweep A of the span-exact decode — no backpointers, no path memory; just
    each device's block products composed across the mesh (replicated out).
    """
    products, _, _ = get_passes(engine)

    def body(params: HmmParams, obs_shard: jnp.ndarray,
             prev0: jnp.ndarray) -> jnp.ndarray:
        K = params.n_states
        pad_sym = params.n_symbols
        d = jax.lax.axis_index(axis)
        obs_c = jnp.minimum(obs_shard.astype(jnp.int32), pad_sym)
        prev_d = (
            _device_entry_sym(obs_c, pad_sym, axis, prev0)
            if engine == "onehot" else None
        )
        if continuation:
            steps = obs_c
        else:
            # First span: position 0 is the init (emission folded into v0 by
            # the decode body), so its step is identity here too.
            steps = obs_c.at[0].set(jnp.where(d == 0, pad_sym, obs_c[0]))
        steps2 = steps.reshape(steps.shape[0] // block_size, block_size).T
        _, _, total = products(params, steps2, prev_d)
        totals = jax.lax.all_gather(total, axis)  # [D, K, K]

        def fwd(carry, t):
            return nrm_maxplus(maxplus_matmul(carry, t)), None

        span_total, _ = jax.lax.scan(
            fwd, _identity_logmat(K) + totals[0] * 0.0, totals
        )
        # Identical on every device; pmax makes that provable to the checker.
        return jax.lax.pmax(span_total, axis)

    return body


@functools.lru_cache(maxsize=32)
def _span_total_fn(mesh: Mesh, block_size: int, engine: str,
                   continuation: bool):
    axis = mesh.axis_names[0]
    body = _span_total_body(block_size, axis, engine, continuation)
    return jax.jit(
        jax.shard_map(
            body,
            mesh=mesh,
            in_specs=(P(), P(axis), P()),
            out_specs=P(),
            check_vma=engine == "xla",
        )
    )


def viterbi_sharded(
    params: HmmParams,
    obs,
    *,
    mesh: Optional[Mesh] = None,
    block_size: int = DEFAULT_BLOCK,
    engine: str = "auto",
    return_device: bool = False,
    supervisor: Optional[resilience.DispatchSupervisor] = None,
):
    """Decode one long sequence sharded over a mesh's devices.

    Pads with the PAD sentinel to a multiple of (devices * block_size) — PAD
    steps are identity, so the result is exact.  Returns the [T] decoded path
    as host ndarray, or as a device-resident array with ``return_device=True``
    (so a fused consumer — e.g. the device island caller — avoids the
    4 B/symbol device->host transfer entirely).

    The dispatch+fetch unit runs under the resilience supervisor (bounded
    retries of fault-shaped errors; jit dispatch is pure, so re-running the
    unit is always safe).  With ``return_device=True`` nothing blocks here
    — the supervised blocking point is then the caller's (the pipeline's
    record units).
    """
    if mesh is None:
        mesh = make_mesh(axis=SEQ_AXIS)
    sup = supervisor if supervisor is not None else resilience.default_supervisor()
    obs = np.asarray(obs)
    T = obs.shape[0]
    # Engine demotion is gated by the SUPERVISOR's breaker: a serve Session
    # hands its per-session supervisor down here, so its faults demote this
    # session's routing only (default supervisor = the process-global one).
    eng = _engine_for_record(
        resolve_engine(engine, params, breaker=sup.breaker), obs, params
    )
    prev0 = jnp.int32(int(obs[0]) if T and int(obs[0]) < params.n_symbols else 0)
    arr = _place_span(mesh, obs, block_size, params.n_symbols)
    # Positional args throughout: lru_cache keys positional vs keyword calls
    # differently, and a mixed style would compile the same fn twice.
    fn = _sharded_fn(mesh, block_size, eng, False)

    def unit():
        path, _ = fn(params, arr, jnp.zeros(params.n_states, jnp.float32),
                     jnp.int32(-1), prev0)
        return _fetch_path(path, T, return_device)

    # items gates the sentinel's throughput ceiling and must only be set on
    # units that BLOCK internally: with return_device=True this unit is an
    # async dispatch (the lazy [:T] slice), so items/dt would be a
    # nonsense ~dispatch-latency rate that flags every healthy run.
    return sup.run(
        unit, what="decode.record", engine=f"decode.{eng}",
        items=0.0 if return_device else float(T),
    )


def _place_span(mesh: Mesh, piece: np.ndarray, block_size: int, pad_sym: int):
    """PAD-pad to the mesh quantum and device_put with P(axis)."""
    n_dev = mesh.shape[mesh.axis_names[0]]
    rem = (-piece.shape[0]) % (n_dev * block_size)
    if rem:
        piece = np.concatenate([piece, np.full(rem, pad_sym, dtype=piece.dtype)])
    return jax.device_put(
        jnp.asarray(piece), NamedSharding(mesh, P(mesh.axis_names[0]))
    )


def _fetch_path(path, T: int, return_device: bool):
    """Multi-host-safe fetch — the shared parallel.mesh implementation."""
    return fetch_sharded_prefix(path, T, return_device)


def viterbi_sharded_spans(
    params: HmmParams,
    obs,
    *,
    span: int,
    mesh: Optional[Mesh] = None,
    block_size: int = DEFAULT_BLOCK,
    engine: str = "auto",
    return_device: bool = False,
    prefetch: bool = False,
    supervisor: Optional[resilience.DispatchSupervisor] = None,
):
    """EXACT decode of a sequence longer than one pass's device-memory budget.

    The record is processed in ``span``-symbol pieces, each decoded
    sequence-parallel over the mesh, with the cross-span stitching carried by
    the same messages the cross-device stitching uses
    (parallel.decode._shard_body): a forward sweep of [K, K] max-plus span
    transfer operators gives every span its exact entering score vector, and
    a reverse decode sweep threads each span's exit state through the next
    span's exit->entry composition table — so no DP restart happens anywhere
    and the result equals a one-shot decode of the whole record (the
    boundary artifact the reference bakes in at every 1 MiB chunk,
    CpGIslandFinder.java:256,262-268, stays fixed at ANY length).

    Peak device memory is one span's backpointers; the only extra work vs
    span-independent decoding is the products-only forward sweep (~1/3 of a
    decode pass).  Returns the per-span paths in forward order (device
    arrays with ``return_device=True``).

    ``prefetch=True`` double-buffers the span uploads: span s+1's pad +
    async ``device_put`` is issued BEFORE blocking on span s's transfer
    total, so the host->device transfer (the dominant span-path cost on any
    interconnect) overlaps the device's products sweep.  Results are
    bit-identical to the serial order — only dispatch timing changes; peak
    HBM is unchanged (both orders hold every span until sweep B consumes
    it, the tail span just arrives one sweep earlier).
    """
    if mesh is None:
        mesh = make_mesh(axis=SEQ_AXIS)
    sup = supervisor if supervisor is not None else resilience.default_supervisor()
    obs = np.asarray(obs)
    # Breaker-gated demotion scoped to the supervisor's breaker (a serve
    # Session's faults demote that session only — see viterbi_sharded).
    eng = _engine_for_record(
        resolve_engine(engine, params, breaker=sup.breaker), obs, params
    )
    T = obs.shape[0]
    if T <= span:
        return [
            viterbi_sharded(
                params, obs, mesh=mesh, block_size=block_size, engine=eng,
                return_device=return_device, supervisor=sup,
            )
        ]
    pad_sym = params.n_symbols
    n_spans = -(-T // span)

    # Each span's symbols are device-placed ONCE and reused by both sweeps:
    # the host->device upload is the dominant cost of the span path on any
    # interconnect (PCIe or this dev setup's HTTP relay), and sweep A + B
    # would otherwise pay it twice.  Holding every span = the record's own
    # size in HBM (uint8), freed span by span as sweep B consumes them.
    def place(s: int):
        lo = s * span
        real = min(span, T - lo)
        piece = obs[lo : lo + real]
        if real < span:
            # Pad the ragged tail to the full span (identity PAD steps) so
            # every span shares ONE compiled shape — distinct tail lengths
            # would otherwise recompile the sharded decode per record.
            piece = np.concatenate(
                [piece, np.full(span - real, pad_sym, piece.dtype)]
            )
        return _place_span(mesh, piece, block_size, pad_sym)

    placed: dict = {}

    # Sweep A (forward): normalized span transfer operators -> every span's
    # exact entering score vector, composed on host (tiny [K]x[K,K] max-plus).
    # A PAD first symbol contributes no emission (the pass-through contract,
    # matching emit_ext's zero pad row in the one-shot decode).
    v = np.asarray(params.log_pi, np.float32)
    if int(obs[0]) < params.n_symbols:
        v = v + np.asarray(params.log_B, np.float32)[:, int(obs[0])]
    enters = [v - v.max()]

    def span_prev0(s: int) -> jnp.ndarray:
        """The symbol before span s (the onehot engine's entry group; other
        engines ignore it).  Span 0's entry is its own position 0."""
        lo = s * span
        return jnp.int32(
            _prev_real_symbol(obs, lo, params.n_symbols)
            if lo else (int(obs[0]) if int(obs[0]) < params.n_symbols else 0)
        )

    if prefetch:
        placed[0] = place(0)
    for s in range(n_spans - 1):
        if s not in placed:
            placed[s] = place(s)

        def total_unit(s=s):
            # Supervised dispatch+fetch: a retry re-runs the span's products
            # sweep on its (still-placed) symbols, so a transient fault or
            # phantom costs one span, never the record.
            total_dev = _span_total_fn(mesh, block_size, eng, s > 0)(
                params, placed[s], span_prev0(s)
            )
            if prefetch and s + 1 not in placed:
                # Overlap: span s+1's upload is in flight while the device
                # runs span s's products sweep (total_dev is an async
                # dispatch; the np.asarray below is the blocking point).
                # This also pre-places the tail span, which sweep B
                # otherwise uploads serially.
                placed[s + 1] = place(s + 1)
            return obs_mod.note_fetch(np.asarray(total_dev))

        total = sup.run(
            total_unit, what="decode.span_total", engine=f"decode.{eng}",
            items=float(span),
        )
        v = (enters[-1][:, None] + total).max(axis=0)
        enters.append((v - v.max()).astype(np.float32))

    # Sweep B (reverse): decode each span anchored at the following span's
    # entry state; prev_exit threads the anchor to the earlier span.  Only
    # the ANCHOR (one scalar) is serially required between spans — the big
    # per-span PATH drain is deferred one span (r6 backtrace/drain
    # overlap): while span s's three passes execute, the PREVIOUS span's
    # already-computed path starts its device->host copy asynchronously
    # (copy_to_host_async between the dispatch and the anchor block), so
    # the 4 B/symbol download hides behind device compute instead of
    # serializing between span programs.  PR 5 deferred-fetch discipline:
    # a poisoned buffer recomputes from the still-placed span symbols.
    # Peak host-visible state grows by one span's int32 path; results are
    # bit-identical to the serial order.
    paths: list = [None] * n_spans
    anchor = -1  # last span: local argmax
    pending = None  # (span index, device path, recompute args)

    def _start_host_copy(path_dev) -> None:
        if return_device:
            return  # caller keeps device arrays; nothing to drain
        try:
            path_dev.copy_to_host_async()
        except (AttributeError, RuntimeError):
            pass  # purely a latency hint; the blocking fetch still works

    def _drain(pend):
        ps, path_dev, re_args = pend
        state = {"dev": path_dev}

        def unit():
            if state["dev"] is None:  # retry after a poisoned fetch
                state["dev"], _ = _sharded_fn(mesh, block_size, eng, ps > 0)(
                    params, *re_args
                )
            try:
                return _fetch_path(
                    state["dev"], min(span, T - ps * span), return_device
                )
            except Exception:
                state["dev"] = None
                raise

        # items=0: with the async copy already issued this unit's blocking
        # wall is ~transfer-remainder (possibly ~0 s) — a rate gate here
        # would flag healthy runs (the r8 sentinel lesson on non-blocking
        # units); the span_unit's rate gate covers the program itself.
        paths[ps] = sup.run(
            unit, what="decode.span_path", engine=f"decode.{eng}", items=0.0
        )
        placed.pop(ps, None)

    for s in reversed(range(n_spans)):
        arr = placed.get(s)
        if arr is None:  # the tail span — sweep A never placed it
            arr = place(s)
            placed[s] = arr
        fn = _sharded_fn(mesh, block_size, eng, s > 0)

        def span_unit(s=s, arr=arr, fn=fn, anchor=anchor, pend=pending):
            path, prev_exit = fn(
                params, arr, jnp.asarray(enters[s]), jnp.int32(anchor),
                span_prev0(s)
            )
            if pend is not None:
                # This span's program is dispatched; overlap the previous
                # span's path download with its execution.
                _start_host_copy(pend[1])
            # graftcheck: allow(hot-path-host-sync) -- anchor threading between spans is inherently serial (one scalar per span); counted by the obs ledger's device_get hook
            a = int(jax.device_get(prev_exit))
            return a, path

        prev_anchor = anchor
        # The unit blocks on the program (the anchor fetch), so the rate
        # gate stays armed; the deferred path drain is the next unit's job.
        anchor, path_dev = sup.run(
            span_unit, what="decode.span", engine=f"decode.{eng}",
            items=float(min(span, T - s * span)),
        )
        if pending is not None:
            _drain(pending)
        pending = (
            s, path_dev,
            (arr, jnp.asarray(enters[s]), jnp.int32(prev_anchor),
             span_prev0(s)),
        )
    _drain(pending)
    return paths
