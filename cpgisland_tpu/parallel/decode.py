"""Sequence-parallel Viterbi over a device mesh (no island clipping).

The reference decodes 1 MiB chunks one at a time on a single JVM
(CpGIslandFinder.java:256-260), resetting island state at every boundary
(SURVEY.md C12).  Here one long sequence is sharded across the mesh's devices
along time; each device runs the blockwise passes of ops.viterbi_parallel over
its shard, and the cross-shard stitching is exact:

- forward message: device transfer matrices ([K, K] max-plus products) are
  `all_gather`ed, so every device computes its exact entering score vector;
- backward message: device composition tables ([K] exit->entry maps) are
  `all_gather`ed, so every device anchors its exit state to the global argmax.

Total communication per decode: two all_gathers of D*K*K and D*K elements over
ICI — independent of sequence length.  The decoded path comes back sharded
(out_spec P(axis)); islands can then be called over the whole genome with no
boundary artifacts, fixing the reference's clipping quirk.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from cpgisland_tpu.models.hmm import HmmParams
from cpgisland_tpu.ops import viterbi_pallas
from cpgisland_tpu.ops.viterbi_parallel import (
    DEFAULT_BLOCK,
    _enter_vectors,
    _identity_logmat,
    _step_tables,
    _suffix_compositions,
    get_passes,
    maxplus_matmul,
)
from cpgisland_tpu.parallel.mesh import SEQ_AXIS, make_mesh


def resolve_engine(engine: str, params: HmmParams) -> str:
    """'auto' picks the Pallas kernels on TPU when the model fits their 3-bit
    backpointer packing, the XLA scans otherwise (incl. the CPU test mesh,
    where Pallas would run interpreted)."""
    if engine == "auto":
        if jax.default_backend() == "tpu" and viterbi_pallas.supports(params):
            return "pallas"
        return "xla"
    if engine not in ("xla", "pallas"):
        raise ValueError(f"unknown engine {engine!r}; expected auto|xla|pallas")
    if engine == "pallas" and not viterbi_pallas.supports(params):
        raise ValueError(f"pallas engine needs n_states <= 8, got {params.n_states}")
    return engine


def _shard_body(block_size: int, axis: str, engine: str = "xla"):
    """Per-device decode body (runs under shard_map).  obs_shard: [L]."""
    products, backpointers, backtrace = get_passes(engine)

    def body(params: HmmParams, obs_shard: jnp.ndarray) -> jnp.ndarray:
        K = params.n_states
        pad_sym = params.n_symbols
        _, emit_ext = _step_tables(params)
        d = jax.lax.axis_index(axis)
        n_dev = jax.lax.axis_size(axis)
        obs_c = jnp.minimum(obs_shard.astype(jnp.int32), pad_sym)

        # Device 0's first symbol is the init (its emission folds into v0); it
        # becomes an identity step so every device has exactly L steps, and
        # "state after step k" is the state at local position k on all devices.
        v0_local = params.log_pi + emit_ext[obs_c[0]]
        steps = obs_c.at[0].set(jnp.where(d == 0, pad_sym, obs_c[0]))
        nb = steps.shape[0] // block_size
        steps2 = steps.reshape(nb, block_size).T

        incl, total = products(params, steps2)

        # Forward stitch: v_enter(shard d) = v0 (x) prod of earlier shards.
        totals = jax.lax.all_gather(total, axis)  # [D, K, K]
        v0 = jax.lax.all_gather(v0_local, axis)[0]  # device 0's init vector

        def fwd(carry, t):
            return maxplus_matmul(carry, t), carry

        _, prefixes = jax.lax.scan(fwd, _identity_logmat(K) + v0[:, None] * 0.0, totals)
        my_prefix = prefixes[d]  # [K, K] product of shards 0..d-1
        v_shard = jnp.max(v0[:, None] + my_prefix, axis=0)  # [K]

        v_enter = _enter_vectors(v_shard, incl)
        delta_blocks, F, bps = backpointers(params, v_enter, steps2)

        # Backward stitch: global argmax composed through later shards' maps.
        Gsuf = _suffix_compositions(F)
        ftables = jax.lax.all_gather(Gsuf[0], axis)  # [D, K]
        delta_last = jax.lax.all_gather(delta_blocks[-1], axis)[n_dev - 1]
        s_final = jnp.argmax(delta_last).astype(jnp.int32)

        def bwd(s, ft):
            return ft[s], s

        # exit[D-1] = s_final; exit[d] = ftable_{d+1}[exit[d+1]].  The reverse
        # scan emits exit[1..D-1] at ys positions and exit[0] as final carry.
        exit0, exits_tail = jax.lax.scan(bwd, s_final, ftables[1:], reverse=True)
        exits_dev = jnp.concatenate([exit0[None], exits_tail])
        my_exit = exits_dev[d]

        # Per-block exits anchored at my_exit, then the light backtrace.
        block_exits = jnp.concatenate([Gsuf[1:, :][:, my_exit], my_exit[None]])
        return backtrace(bps, block_exits)

    return body


@functools.lru_cache(maxsize=32)
def _sharded_fn(mesh: Mesh, block_size: int, engine: str = "xla"):
    """Compile the sharded decode once per (mesh, block_size, engine); params
    are a traced argument, so model updates never trigger recompilation."""
    axis = mesh.axis_names[0]
    body = _shard_body(block_size, axis, engine)
    # check_vma can't see through pallas_call out_shapes; disable for that engine.
    return jax.jit(
        jax.shard_map(
            body,
            mesh=mesh,
            in_specs=(P(), P(axis)),
            out_specs=P(axis),
            check_vma=engine != "pallas",
        )
    )


def viterbi_sharded(
    params: HmmParams,
    obs,
    *,
    mesh: Optional[Mesh] = None,
    block_size: int = DEFAULT_BLOCK,
    engine: str = "auto",
    return_device: bool = False,
):
    """Decode one long sequence sharded over a mesh's devices.

    Pads with the PAD sentinel to a multiple of (devices * block_size) — PAD
    steps are identity, so the result is exact.  Returns the [T] decoded path
    as host ndarray, or as a device-resident array with ``return_device=True``
    (so a fused consumer — e.g. the device island caller — avoids the
    4 B/symbol device->host transfer entirely).
    """
    if mesh is None:
        mesh = make_mesh(axis=SEQ_AXIS)
    n_dev = mesh.shape[mesh.axis_names[0]]
    obs = np.asarray(obs)
    T = obs.shape[0]
    pad_sym = params.n_symbols
    rem = (-T) % (n_dev * block_size)
    if rem:
        obs = np.concatenate([obs, np.full(rem, pad_sym, dtype=obs.dtype)])

    fn = _sharded_fn(mesh, block_size, resolve_engine(engine, params))
    arr = jax.device_put(jnp.asarray(obs), NamedSharding(mesh, P(mesh.axis_names[0])))
    path = fn(params, arr)
    if return_device:
        return path[:T]
    if not path.is_fully_addressable:
        # Multi-host global mesh: the sharded output spans non-addressable
        # devices, so a plain fetch raises; gather every host a full copy
        # over DCN (the host-side path is for island calling / dumps, which
        # every process replicates anyway).  Gating on addressability — not
        # process_count — keeps per-host meshes in multi-process jobs on the
        # direct fetch, where a gather would splice other hosts' unrelated
        # decodes.  Device-resident consumers should prefer
        # return_device=True and reduce on device instead.
        from jax.experimental import multihost_utils

        return np.asarray(multihost_utils.process_allgather(path, tiled=True))[:T]
    return np.asarray(path)[:T]
