"""Sequence-parallel posterior (soft) decoding over a device mesh.

The soft twin of parallel.decode: per-position island confidence
P(position in island | whole record) computed through the SAME lane-parallel
forward-backward machinery as the E-step — fused Pallas kernels on TPU
(ops.fb_pallas._seq_posterior_core), the blockwise XLA lane path elsewhere
(parallel.fb_sharded._one_seq_local_posterior) — with boundary messages
making the result exact across lanes, devices, and (via enter/exit
directions threaded by pipeline.posterior_file) sequential spans of records
larger than one pass.

The reference's Mahout surface exposes only hard Viterbi decoding
(HmmEvaluator.decode, CpGIslandFinder.java:260); this module is its soft
completion at decode-class throughput.  Cross-device communication per pass:
one all_gather of [K] init directions and one of [K, K] transfer totals —
independent of sequence length, identical to the E-step's exchange
(parallel.fb_sharded.device_boundary_messages).
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from cpgisland_tpu import obs as obs_module
from cpgisland_tpu.models.hmm import HmmParams
from cpgisland_tpu.ops import fb_pallas
from cpgisland_tpu.parallel.fb_sharded import (
    DEFAULT_BLOCK,
    _lane_pass_products,
    _nrm_m,
    _one_seq_local_posterior,
    shard_sequence,
)
from cpgisland_tpu.parallel.mesh import (
    SEQ_AXIS,
    fetch_sharded_prefix,
    make_mesh,
)

_HI = jax.lax.Precision.HIGHEST


def fb_engine_twin(engine: str, params: HmmParams) -> Optional[str]:
    """Next rung of the FB engines' parity-twin ladder
    (resilience.breaker.kernel_ladder with the FB eligibility).  The twins
    are parity-pinned (2e-5 posterior parity, tests/test_fb_onehot.py /
    test_fb_pallas.py)."""
    from cpgisland_tpu.resilience.breaker import kernel_ladder

    return kernel_ladder(
        jax.default_backend() == "tpu" and fb_pallas.supports(params)
    )(engine)


def _onehot_fb_ok(params: HmmParams) -> bool:
    """The reduced FB engine's state envelope: the chains are K-free, but
    the boundary glue/stats accumulators scatter [K] rows — bounded by
    fb_onehot.ONEHOT_MAX_STATES (32, the dinuc member's K)."""
    from cpgisland_tpu.ops.fb_onehot import ONEHOT_MAX_STATES

    return params.n_states <= ONEHOT_MAX_STATES


def resolve_fb_engine(engine: str, params: HmmParams, *, breaker=None) -> str:
    """'auto' picks the reduced one-hot FB kernels on TPU when the model's
    emission structure supports them (ops.fb_onehot — the flagship 8-state
    preset does), else the dense fused kernels when the model fits their
    lane packing, else the XLA lane path (incl. the CPU test mesh).  Under
    'auto', engines tripped by the resilience breaker demote down the
    parity-twin ladder for the cooldown window; explicit requests are
    honored as-is (see parallel.decode.resolve_engine).  ``breaker``: the
    EngineBreaker gating the demotion (a serve Session passes its own;
    default the process-global one)."""
    from cpgisland_tpu import resilience
    from cpgisland_tpu.family import partition as family_partition

    if engine == "auto":
        resolved = "xla"
        if jax.default_backend() == "tpu":
            # family.partition_of — the one eligibility oracle shared with
            # the decode/train routers.  The reduced engine's chains are
            # K-free (2 components), so its envelope is the reduced one
            # (fb_onehot.ONEHOT_MAX_STATES — admits the 32-state dinuc
            # member, ROADMAP item 2's K<=8 lift), while the dense fused
            # kernels keep their n_states <= 8 lane packing.
            if family_partition.reduced_eligible(params) and _onehot_fb_ok(
                params
            ):
                resolved = "onehot"
            elif fb_pallas.supports(params):
                resolved = "pallas"
        obs_module.engine_decision(
            site="posterior.resolve_fb_engine", choice=resolved, requested=engine
        )
        if breaker is None:
            breaker = resilience.get_breaker()
        return breaker.degrade(
            "fb", resolved, lambda e: fb_engine_twin(e, params)
        )
    if engine not in ("xla", "pallas", "onehot"):
        raise ValueError(
            f"unknown engine {engine!r}; expected auto|xla|pallas|onehot"
        )
    if engine == "pallas" and not fb_pallas.supports(params):
        raise ValueError(
            f"pallas FB kernels need n_states <= 8, got {params.n_states}"
        )
    if engine == "onehot" and not (
        _onehot_fb_ok(params)
        and family_partition.reduced_eligible(params)
    ):
        raise ValueError(
            "onehot FB kernels need a one-hot emission-support partition "
            "with 2 states per symbol (family.partition_of; concrete "
            "params) inside the reduced state envelope (n_states <= "
            "fb_onehot.ONEHOT_MAX_STATES)"
        )
    obs_module.engine_decision(
        site="posterior.resolve_fb_engine", choice=engine, requested=engine
    )
    return engine


@functools.lru_cache(maxsize=32)
def _posterior_fn(
    mesh: Mesh,
    block_size: int,
    engine: str,
    first: bool,
    want_path: bool,
    lane_T: int,
    t_tile: int,
    fused: bool = True,
    one_pass: bool = False,
):
    """Compiled sharded posterior: fn(params, obs, lens, mask, enter, exit)
    -> (conf P(axis), path P(axis)).  enter/exit are always arrays — the
    uniform direction IS the free-end anchor, and enter is ignored when
    ``first`` — so one cache entry serves every span of a record.
    ``fused``: the r9 co-scheduled fwd/bwd pass (False = the split 3-pass
    A/B arm, kernel-engine paths only).  ``one_pass``: the r17
    matrix-carried true one-pass arm (onehot engine only)."""
    axis = mesh.axis_names[0]

    def body(params, obs_shard, len_shard, island_mask, enter_dir, exit_dir,
             prev_sym):
        if engine in ("pallas", "onehot"):
            return fb_pallas._seq_posterior_core(
                params, obs_shard, len_shard[0], island_mask, lane_T, t_tile,
                axis=axis, enter_dir=enter_dir, exit_dir=exit_dir,
                first=first, want_path=want_path,
                onehot=engine == "onehot", prev_sym=prev_sym, fused=fused,
                one_pass=one_pass,
            )
        return _one_seq_local_posterior(
            params, obs_shard, len_shard[0], island_mask,
            axis=axis, block_size=block_size,
            enter_dir=enter_dir, exit_dir=exit_dir,
            first=first, want_path=want_path,
        )

    return jax.jit(
        jax.shard_map(
            body,
            mesh=mesh,
            in_specs=(P(), P(axis), P(axis), P(), P(), P(), P()),
            out_specs=(P(axis), P(axis)),
            check_vma=engine == "xla",
        )
    )


@functools.lru_cache(maxsize=32)
def _transfer_total_fn(mesh: Mesh, block_size: int, first: bool):
    """Compiled sharded span transfer operator (probability space): the
    cheap products-only forward sweep of span threading (XLA lane path;
    single-device TPU callers use fb_pallas.seq_transfer_total_pallas).
    Returns the replicated [K, K] normalized operator of the whole span."""
    axis = mesh.axis_names[0]

    def body(params: HmmParams, obs_shard: jnp.ndarray, len_shard: jnp.ndarray):
        K = params.n_states
        incl = _lane_pass_products(
            params, obs_shard, len_shard[0],
            axis=axis, block_size=block_size, first=first,
        )["incl"]
        totals = jax.lax.all_gather(incl[-1], axis)  # [D, K, K]

        def comp(C, Tk):
            return _nrm_m(jnp.matmul(C, Tk, precision=_HI)), None

        total, _ = jax.lax.scan(
            comp, jnp.eye(K, dtype=incl.dtype) + incl[-1] * 0.0, totals
        )
        # Identical on every device; pmax makes replication provable.
        return jax.lax.pmax(total, axis)

    return jax.jit(
        jax.shard_map(
            body,
            mesh=mesh,
            in_specs=(P(), P(axis), P(axis)),
            out_specs=P(),
        )
    )


def _place(mesh: Mesh, obs: np.ndarray, block_size: int, pad_sym: int,
           length: Optional[int] = None, pad_to: Optional[int] = None):
    """PAD-pad and device_put one sequence with P(axis) + per-shard lengths.

    ``pad_to`` bucket-pads the sequence before sharding (the compiled fns
    specialize on the padded shape — scaffold-heavy files would otherwise
    compile once per distinct record size); ``length`` is the real symbol
    count (default: the input size), which the cores mask by.
    """
    axis = mesh.axis_names[0]
    n_dev = mesh.shape[axis]
    obs = np.asarray(obs)
    n = obs.shape[0] if length is None else int(length)
    if pad_to is not None and pad_to > obs.shape[0]:
        obs = np.concatenate(
            [obs, np.full(pad_to - obs.shape[0], pad_sym, obs.dtype)]
        )
    obs_p, _ = shard_sequence(obs, n_dev, block_size, pad_sym)
    L = obs_p.shape[0] // n_dev
    lengths = np.clip(n - np.arange(n_dev) * L, 0, L).astype(np.int32)
    sharding = NamedSharding(mesh, P(axis))
    return (
        jax.device_put(jnp.asarray(obs_p), sharding),
        jax.device_put(jnp.asarray(lengths), sharding),
    )


def island_mask(params: HmmParams, island_states) -> np.ndarray:
    mask = np.zeros(params.n_states, np.float32)
    mask[list(island_states)] = 1.0
    return mask


def _prev_sym_arg(engine: str, first: bool, prev_sym) -> jnp.ndarray:
    """Validate/convert the public wrappers' ``prev_sym`` argument.

    The reduced onehot kernels condition a continuation span's entry group
    on the symbol BEFORE the span; a caller who forgets it would get
    silently wrong (clamped-seed) conditioning, so onehot + first=False +
    None raises here — the in-kernel _lane_streams check cannot fire once a
    wrapper has already converted None to an array.
    """
    if prev_sym is None:
        if not first and engine == "onehot":
            raise ValueError(
                "onehot continuation spans (first=False) need prev_sym — "
                "the symbol immediately before this span"
            )
        return jnp.int32(0)
    return jnp.asarray(prev_sym, jnp.int32)


def prepare_record_span(
    params: HmmParams,
    placed,
    length: int,
    *,
    engine: str = "auto",
    first: bool = True,
    prev_sym: Optional[int] = None,
    want_path: bool = False,
    t_tile: Optional[int] = None,
    mesh: Optional[Mesh] = None,
    streams=None,
    breaker=None,
):
    """One span's PreparedSeq (ops.prepared), shared by BOTH span sweeps.

    ``streams``: the caller's ops.prepared.PreparedStreams handle (one per
    input — pipeline.posterior_file holds one per record) so every span's
    artifact books against the same handle/cache; a fresh cache lookup
    otherwise.

    The span-threaded posterior lane-lays-out and pair-streams the SAME
    placed span twice — once for the transfer-total sweep (A) and once for
    the posterior sweep (B).  This builds the symbol-only prep ONCE per
    placed span (identity-cached, so repeated calls are free) for the
    single-device fused engines; returns None when the mesh shards the
    span (the sharded bodies' collective threading preps inline) or the
    engine has no prepared form — callers then fall back to inline prep.

    The prep's lane geometry is the POSTERIOR sweep's pick; the products-
    only transfer sweep runs the same lanes (its reduced kernel has no
    long-lane constraint), so one prep serves both.
    """
    if mesh is None:
        mesh = make_mesh(axis=SEQ_AXIS)
    if mesh.shape[mesh.axis_names[0]] != 1:
        return None
    eng = resolve_fb_engine(engine, params, breaker=breaker)
    if eng not in ("pallas", "onehot"):
        return None
    from cpgisland_tpu.ops import prepared as prep_mod

    oh = eng == "onehot"
    arr = placed[0]
    lane_T = fb_pallas.pick_lane_T(
        arr.shape[0], onehot=oh, long_lanes=oh and not want_path
    )
    if streams is None:
        streams = prep_mod.PreparedStreams(params.n_symbols)
    return streams.seq(
        arr, int(length), lane_T=lane_T,
        t_tile=t_tile if t_tile is not None else fb_pallas.DEFAULT_T_TILE,
        first=first, onehot=oh,
        prev_sym=None if (first or prev_sym is None) else int(prev_sym),
    )


def place_record_span(
    params: HmmParams,
    piece,
    *,
    mesh: Optional[Mesh] = None,
    block_size: int = DEFAULT_BLOCK,
    pad_to: Optional[int] = None,
):
    """Device-place one span's symbols ONCE for both span sweeps.

    The span-threaded posterior uploads each span for the transfer-total
    sweep and again for the posterior sweep unless the caller pre-places it
    here and passes the result as ``placed=`` to transfer_total_sharded and
    posterior_sharded — halving the host->device transfer, the dominant
    span-path cost on any interconnect.
    """
    if mesh is None:
        mesh = make_mesh(axis=SEQ_AXIS)
    return _place(
        mesh, np.asarray(piece), block_size, params.n_symbols, pad_to=pad_to
    )


def posterior_sharded(
    params: HmmParams,
    obs,
    island_states,
    *,
    mesh: Optional[Mesh] = None,
    block_size: int = DEFAULT_BLOCK,
    engine: str = "auto",
    lane_T: Optional[int] = None,
    t_tile: Optional[int] = None,
    enter_dir=None,
    exit_dir=None,
    first: bool = True,
    want_path: bool = False,
    return_device: bool = False,
    pad_to: Optional[int] = None,
    placed=None,
    prev_sym: Optional[int] = None,
    prepared=None,
    fused: Optional[bool] = None,
    one_pass: Optional[bool] = None,
    breaker=None,
):
    """Island confidence (and optional MPM path) for one sequence, sharded
    along time over the mesh.

    ``breaker``: the EngineBreaker gating auto-routing's parity-twin
    demotion (a serve Session passes its own; default process-global).

    ``fused`` (kernel engines): the r9 co-scheduled fwd/bwd pass; False
    keeps the split 3-pass structure (the pass-fusion A/B arm).  The
    ``None`` default consults the graftune winner table
    (``fused.posterior``) and falls back to the shipped True — explicit
    values always win.

    ``one_pass`` (onehot engine): the r17 matrix-carried TRUE one-pass
    arm — products + fwd/bwd in ONE T-scaling launch.  ``None`` consults
    ``one_pass.posterior`` and falls back to the shipped False (the
    2-pass arm stays the default until a chip capture flips it);
    explicit values always win.  Takes precedence over ``fused``.

    ``prepared`` (from :func:`prepare_record_span`; single-device fused
    engines only): the span's symbol-only prep — the pass then runs the
    fused core directly with it, skipping the per-sweep lane/pair-stream
    rebuild; geometry (incl. lane_T) comes from the prep.

    enter_dir/exit_dir ([K] direction vectors) thread span-boundary messages
    for records processed in multiple spans (pipeline.posterior_file);
    defaults are the sequence start (``first=True``) and the free end.
    ``pad_to`` bucket-pads the input so varied record sizes share compiled
    shapes.  ``placed`` (from place_record_span) reuses an already-uploaded
    (arr, lens) pair instead of re-placing ``obs`` — ``obs`` then only
    supplies the true length.  Returns (conf [T] f32, path [T] int32 or
    None).
    """
    if mesh is None:
        mesh = make_mesh(axis=SEQ_AXIS)
    if fused is None:
        from cpgisland_tpu import tune

        fused = tune.default_fused("posterior")
    if one_pass is None:
        from cpgisland_tpu import tune

        one_pass = tune.default_one_pass("posterior")
    eng = resolve_fb_engine(engine, params, breaker=breaker)
    one_pass = one_pass and eng == "onehot"
    tt = t_tile if t_tile is not None else fb_pallas.DEFAULT_T_TILE
    T = int(np.asarray(obs).shape[0]) if placed is None else int(obs.shape[0])
    K = params.n_states
    arr, lens = (
        placed
        if placed is not None
        else _place(
            mesh, np.asarray(obs), block_size, params.n_symbols, pad_to=pad_to
        )
    )
    # Lane length by PER-SHARD size (r4 sweep: long lanes are much faster
    # once they fill the 128-lane grid; short inputs keep short lanes).
    lt = (
        lane_T
        if lane_T is not None
        else fb_pallas.pick_lane_T(
            arr.shape[0] // mesh.shape[mesh.axis_names[0]], onehot=eng == "onehot",
            # The conf kernel path handles long lanes; the want_path branch
            # runs XLA passes over scattered [Tp, K, NL] streams, which do
            # not compile at 131072.
            long_lanes=eng == "onehot" and not want_path,
        )
    )
    mask = jnp.asarray(island_mask(params, island_states))
    enter = (
        jnp.zeros(K, jnp.float32) if enter_dir is None
        else jnp.asarray(enter_dir, jnp.float32)
    )
    exit_ = (
        jnp.full(K, 1.0 / K, jnp.float32) if exit_dir is None
        else jnp.asarray(exit_dir, jnp.float32)
    )
    if (
        prepared is not None
        and mesh.shape[mesh.axis_names[0]] == 1
        and eng in ("pallas", "onehot")
    ):
        # Single-device fused branch with the span's shared prep: the
        # direct core is math-identical to the 1-device shard_map body
        # (device_boundary_messages over one device degenerates to the
        # axis=None seed/anchor), and the prep's geometry wins.
        conf, path = fb_pallas.seq_posterior_pallas(
            params, arr, T, mask,
            enter_dir=None if first else enter, exit_dir=exit_,
            first=first, want_path=want_path,
            lane_T=prepared.lane_T, t_tile=tt, onehot=eng == "onehot",
            prev_sym=_prev_sym_arg(eng, first, prev_sym),
            prepared=prepared, fused=fused, one_pass=one_pass,
        )
    else:
        fn = _posterior_fn(
            mesh, block_size, eng, first, want_path, lt, tt, fused, one_pass
        )
        conf, path = fn(
            params, arr, lens, mask, enter, exit_,
            _prev_sym_arg(eng, first, prev_sym),
        )
    conf = fetch_sharded_prefix(conf, T, return_device)
    path = fetch_sharded_prefix(path, T, return_device) if want_path else None
    return conf, path


@functools.lru_cache(maxsize=32)
def _posterior_fn_stacked(
    mesh: Mesh,
    block_size: int,
    n_members: int,
    want_path: bool,
    lane_T: int,
    t_tile: int,
    fused: bool = True,
):
    """Compiled stacked sharded posterior: fn(params_tuple, obs, lens,
    masks_tuple) -> (conf [M, T] P(None, axis), path [M, T]) — the
    multi-model twin of :func:`_posterior_fn` (first spans only; the
    comparison workload's record units are whole records)."""
    axis = mesh.axis_names[0]
    del block_size, n_members  # part of the cache key, not the body

    def body(params_list, obs_shard, len_shard, masks):
        return fb_pallas._seq_posterior_core_stacked(
            params_list, obs_shard, len_shard[0], masks, lane_T, t_tile,
            axis=axis, want_path=want_path, fused=fused,
        )

    return jax.jit(
        jax.shard_map(
            body,
            mesh=mesh,
            in_specs=(P(), P(axis), P(axis), P()),
            out_specs=(P(None, axis), P(None, axis)),
            check_vma=False,
        )
    )


def posterior_sharded_stacked(
    params_list,
    obs,
    island_states_list,
    *,
    mesh: Optional[Mesh] = None,
    block_size: int = DEFAULT_BLOCK,
    lane_T: Optional[int] = None,
    t_tile: Optional[int] = None,
    want_path: bool = False,
    return_device: bool = False,
    pad_to: Optional[int] = None,
    placed=None,
    prepared=None,
    fused: Optional[bool] = None,
):
    """STACKED island confidence (and optional MPM paths) for M reduced
    members over ONE record: every member's chains run in one stacked
    launch set over one shared placed stream (the occupancy half of
    ROADMAP item 2).  Per-member outputs are bit-identical to M
    :func:`posterior_sharded` calls with ``engine='onehot'`` on the same
    input/geometry — callers gate membership on the resolved engine being
    'onehot' (family.stacked).  ``placed``: the order's ONE uploaded
    (arr, lens) pair, shared with the sequential arm and the scoring pass
    (zero duplicate uploads).  Returns (conf [M, T], path [M, T] or None).
    """
    if mesh is None:
        mesh = make_mesh(axis=SEQ_AXIS)
    if fused is None:
        from cpgisland_tpu import tune

        fused = tune.default_fused("posterior")
    params_list = tuple(params_list)
    tt = t_tile if t_tile is not None else fb_pallas.DEFAULT_T_TILE
    T = int(np.asarray(obs).shape[0]) if placed is None else int(obs.shape[0])
    arr, lens = (
        placed
        if placed is not None
        else _place(
            mesh, np.asarray(obs), block_size,
            params_list[0].n_symbols, pad_to=pad_to,
        )
    )
    lt = (
        lane_T
        if lane_T is not None
        else fb_pallas.pick_lane_T(
            arr.shape[0] // mesh.shape[mesh.axis_names[0]], onehot=True,
            long_lanes=not want_path,
        )
    )
    masks = tuple(
        jnp.asarray(island_mask(p, s))
        for p, s in zip(params_list, island_states_list)
    )
    if (
        prepared is not None
        and mesh.shape[mesh.axis_names[0]] == 1
    ):
        conf, path = fb_pallas.seq_posterior_pallas_stacked(
            params_list, arr, T, masks, want_path=want_path,
            lane_T=prepared.lane_T, t_tile=tt, prepared=prepared,
            fused=fused,
        )
    else:
        fn = _posterior_fn_stacked(
            mesh, block_size, len(params_list), want_path, lt, tt, fused
        )
        conf, path = fn(params_list, arr, lens, masks)
    def rows(x):
        # Per-member prefix fetch through the one multi-host-safe helper
        # (each row is sharded along the time axis like the single-model
        # outputs); M is small, so M tiny fetches beat a bespoke gather.
        got = [
            fetch_sharded_prefix(x[m], T, return_device)
            for m in range(len(params_list))
        ]
        return jnp.stack(got) if return_device else np.stack(
            [np.asarray(g) for g in got]
        )

    confs = rows(conf)
    return confs, rows(path) if want_path else None


def transfer_total_sharded(
    params: HmmParams,
    obs,
    *,
    mesh: Optional[Mesh] = None,
    block_size: int = DEFAULT_BLOCK,
    engine: str = "auto",
    first: bool = True,
    pad_to: Optional[int] = None,
    placed=None,
    prev_sym: Optional[int] = None,
    return_device: bool = False,
    prepared=None,
    breaker=None,
):
    """One span's normalized [K, K] probability-space transfer operator
    (sweep A of span-threaded posterior processing).  ``placed`` (from
    place_record_span) reuses an already-uploaded span; ``obs`` then only
    supplies the true length.  ``prev_sym``: the symbol before the span —
    REQUIRED for onehot continuation spans (first=False), where it
    conditions the reduced chain's entry group.  ``return_device=True``
    skips the blocking host fetch and returns the (async-dispatched) device
    [K, K] — the overlapped pipeline uploads the NEXT span while this one's
    products sweep runs, fetching all totals afterwards."""
    if mesh is None:
        mesh = make_mesh(axis=SEQ_AXIS)
    n_dev = mesh.shape[mesh.axis_names[0]]
    eng = resolve_fb_engine(engine, params, breaker=breaker)
    out = None
    if n_dev == 1 and eng in ("pallas", "onehot"):
        # Single-chip TPU: the products Pallas kernel is much faster than
        # the XLA lane scan for this sweep.
        oh = eng == "onehot"
        ps = _prev_sym_arg(eng, first, prev_sym)
        if placed is not None:
            # ``prepared`` (prepare_record_span): reuse the span's shared
            # symbol-only prep — its lane geometry wins so sweep A and
            # sweep B run the same layout from one prep.
            out = fb_pallas.seq_transfer_total_pallas(
                params, placed[0], int(obs.shape[0]), first=first,
                lane_T=(
                    prepared.lane_T if prepared is not None
                    else fb_pallas.pick_lane_T(placed[0].shape[0], onehot=oh)
                ),
                onehot=oh, prev_sym=ps, prepared=prepared,
            )
        else:
            obs = np.asarray(obs)
            n = obs.shape[0]
            if pad_to is not None and pad_to > n:
                obs = np.concatenate(
                    [obs, np.full(pad_to - n, params.n_symbols, obs.dtype)]
                )
            out = fb_pallas.seq_transfer_total_pallas(
                params, jnp.asarray(obs), n, first=first,
                lane_T=fb_pallas.pick_lane_T(obs.shape[0], onehot=oh),
                onehot=oh, prev_sym=ps,
            )
    else:
        arr, lens = (
            placed
            if placed is not None
            else _place(
                mesh, np.asarray(obs), block_size, params.n_symbols, pad_to=pad_to
            )
        )
        out = _transfer_total_fn(mesh, block_size, first)(params, arr, lens)
    if return_device:
        return out
    return obs_module.note_fetch(np.asarray(out))
