"""Device-mesh construction helpers.

The framework's distribution substrate is a `jax.sharding.Mesh` over which XLA
collectives run on ICI (and DCN across hosts) — the TPU-native replacement for
the reference's Hadoop cluster (SURVEY.md §5).  A 1-D ``data`` axis carries
chunk-parallel training (C8); ``SEQ_AXIS`` names the axis used for
sequence-parallel decoding.

Multi-host: every helper here builds meshes from ``jax.devices()``, which is
the GLOBAL device list once :func:`initialize_multihost` (or
``jax.distributed.initialize``) has run on each host of a pod — the same
`shard_map`/`psum` programs then span hosts with XLA routing collectives over
ICI within a slice and DCN across slices, no code changes.  This replaces the
reference's Hadoop cluster membership; there is no NCCL/MPI layer to manage.
"""

from __future__ import annotations

import logging
import os
from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh

log = logging.getLogger(__name__)

DATA_AXIS = "data"
SEQ_AXIS = "seq"


def make_mesh(n_devices: Optional[int] = None, axis: str = DATA_AXIS) -> Mesh:
    """A 1-D mesh over the first ``n_devices`` devices (default: all)."""
    devs = jax.devices()
    if n_devices is not None:
        if n_devices > len(devs):
            raise ValueError(f"requested {n_devices} devices, have {len(devs)}")
        devs = devs[:n_devices]
    return Mesh(np.asarray(devs), (axis,))


def make_mesh2d(
    dp: int,
    sp: Optional[int] = None,
    axes: Sequence[str] = (DATA_AXIS, SEQ_AXIS),
) -> Mesh:
    """A 2-D (data x seq) mesh: sequences over ``dp`` rows, time over ``sp``
    columns.  On real hardware XLA maps the trailing (seq) axis to the
    fastest ICI neighbours, so the per-step boundary all_gathers stay local
    to a row."""
    devs = jax.devices()
    if sp is None:
        if len(devs) % dp != 0:
            raise ValueError(f"{len(devs)} devices not divisible by dp={dp}")
        sp = len(devs) // dp
    if dp * sp > len(devs):
        raise ValueError(f"requested {dp}x{sp} devices, have {len(devs)}")
    return Mesh(np.asarray(devs[: dp * sp]).reshape(dp, sp), tuple(axes))


def auto_mesh2d(n_sequences: int, axes: Sequence[str] = (DATA_AXIS, SEQ_AXIS)) -> Mesh:
    """Pick a balanced dp x sp split of all devices for ``n_sequences``.

    dp is the largest divisor of the device count not exceeding the sequence
    count, so no data row idles; remaining devices go to sequence
    parallelism (e.g. 8 devices, 3 chromosomes -> 2 x 4)."""
    n = len(jax.devices())
    dp = max(d for d in range(1, n + 1) if n % d == 0 and d <= max(1, n_sequences))
    return make_mesh2d(dp, n // dp, axes=axes)


def initialize_multihost(
    coordinator_address: Optional[str] = None,
    num_processes: Optional[int] = None,
    process_id: Optional[int] = None,
) -> int:
    """Join this process to a multi-host run (the DCN membership step).

    Thin wrapper over ``jax.distributed.initialize``: on TPU pods the
    arguments default from the cluster environment (TPU metadata /
    JAX_COORDINATOR_ADDRESS etc.), so a bare ``initialize_multihost()`` on
    every host is enough; no-ops when already initialized or when explicitly
    told this is a single-process run (all args None AND no cluster env).
    Returns the global device count afterwards.

    After this, :func:`make_mesh` / :func:`make_mesh2d` / :func:`auto_mesh2d`
    build GLOBAL meshes and the training entry points run unchanged — each
    host feeds only its input shard: SpmdBackend.place selects this process's
    contiguous chunk block (utils.chunking.process_shard) and assembles the
    global array via jax.make_array_from_process_local_data, mirroring the
    reference's HDFS input splits (CpGIslandFinder.java:108-147).
    """
    import jax.distributed as jd

    explicit = any(a is not None for a in (coordinator_address, num_processes, process_id))

    # State queries, not error-message matching: jd.initialize raises
    # RuntimeError both for re-entry and for late calls, and its wording is
    # not a stable API.  Query the two states directly instead.
    if getattr(jd, "is_initialized", lambda: False)():
        return len(jax.devices())  # idempotent re-entry
    if _backends_initialized() and not explicit and not _cluster_env():
        # The XLA backend is already up, no cluster was requested explicitly,
        # and nothing in the environment says this is a pod: a single-process
        # run that called this late — fine.  On a real pod (cluster env
        # present) we fall through and let jd.initialize raise, because
        # silently degrading would have every host train alone.
        log.info("backend already initialized; continuing single-process")
        return len(jax.devices())

    try:
        jd.initialize(
            coordinator_address=coordinator_address,
            num_processes=num_processes,
            process_id=process_id,
        )
    except ValueError:
        if explicit or _cluster_env():
            # Explicit-but-broken args, or a cluster environment whose
            # auto-detection failed: silently degrading would have every
            # host train alone — stay a hard error.
            raise
        # No cluster environment to auto-detect from: single-process run.
        log.info("no multi-host cluster environment detected; running single-process")
    return len(jax.devices())


def _backends_initialized() -> bool:
    """Has any XLA backend already been created in this process?

    Uses the xla_bridge state query when present (jax>=0.4-era private API,
    stable in practice); conservatively reports False otherwise, which routes
    through jd.initialize and surfaces its own error."""
    try:
        from jax._src import xla_bridge

        return bool(xla_bridge.backends_are_initialized())
    except Exception:
        return False


# Environment markers jax.distributed's auto-detection feeds on — if any is
# set, this process is part of a cluster and must never silently degrade.
_CLUSTER_ENV_VARS = (
    "JAX_COORDINATOR_ADDRESS",
    "MEGASCALE_COORDINATOR_ADDRESS",
    "SLURM_JOB_NUM_NODES",
    "OMPI_COMM_WORLD_SIZE",
)


def _cluster_env() -> bool:
    if any(os.environ.get(v) for v in _CLUSTER_ENV_VARS):
        return True
    # TPU plugins set TPU_WORKER_HOSTNAMES even on one host ("localhost");
    # only a multi-entry list means an actual pod.
    return "," in os.environ.get("TPU_WORKER_HOSTNAMES", "")


def local_device_count() -> int:
    """Devices attached to THIS process (not the global pod count)."""
    return jax.local_device_count()


def fetch_sharded_prefix(x, T: int, return_device: bool):
    """Return the first T elements of a P(axis)-sharded per-position array —
    on device (``return_device=True``) or as a host ndarray.

    The ONE implementation of the multi-host subtlety (parallel.decode and
    parallel.posterior both fetch through here): on a multi-host global mesh
    the sharded output spans non-addressable devices, so a plain fetch
    raises; gather every host a full copy over DCN (the host-side result is
    for island calling / dumps, which every process replicates anyway).
    Gating on addressability — not process_count — keeps per-host meshes in
    multi-process jobs on the direct fetch, where a gather would splice
    other hosts' unrelated results.  Device-resident consumers should prefer
    ``return_device=True`` and reduce on device instead.
    """
    if return_device:
        return x[:T]
    from cpgisland_tpu import obs

    if not x.is_fully_addressable:
        from jax.experimental import multihost_utils

        with obs.span("multihost-gather", items=float(T), unit="sym"):
            return obs.note_fetch(
                np.asarray(multihost_utils.process_allgather(x, tiled=True))
            )[:T]
    return obs.note_fetch(np.asarray(x))[:T]
