"""Device-mesh construction helpers.

The framework's distribution substrate is a `jax.sharding.Mesh` over which XLA
collectives run on ICI (and DCN across hosts) — the TPU-native replacement for
the reference's Hadoop cluster (SURVEY.md §5).  A 1-D ``data`` axis carries
chunk-parallel training (C8); ``SEQ_AXIS`` names the axis used for
sequence-parallel decoding.
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh

DATA_AXIS = "data"
SEQ_AXIS = "seq"


def make_mesh(n_devices: Optional[int] = None, axis: str = DATA_AXIS) -> Mesh:
    """A 1-D mesh over the first ``n_devices`` devices (default: all)."""
    devs = jax.devices()
    if n_devices is not None:
        if n_devices > len(devs):
            raise ValueError(f"requested {n_devices} devices, have {len(devs)}")
        devs = devs[:n_devices]
    return Mesh(np.asarray(devs), (axis,))


def make_mesh2d(
    dp: int,
    sp: Optional[int] = None,
    axes: Sequence[str] = (DATA_AXIS, SEQ_AXIS),
) -> Mesh:
    """A 2-D (data x seq) mesh: sequences over ``dp`` rows, time over ``sp``
    columns.  On real hardware XLA maps the trailing (seq) axis to the
    fastest ICI neighbours, so the per-step boundary all_gathers stay local
    to a row."""
    devs = jax.devices()
    if sp is None:
        if len(devs) % dp != 0:
            raise ValueError(f"{len(devs)} devices not divisible by dp={dp}")
        sp = len(devs) // dp
    if dp * sp > len(devs):
        raise ValueError(f"requested {dp}x{sp} devices, have {len(devs)}")
    return Mesh(np.asarray(devs[: dp * sp]).reshape(dp, sp), tuple(axes))


def auto_mesh2d(n_sequences: int, axes: Sequence[str] = (DATA_AXIS, SEQ_AXIS)) -> Mesh:
    """Pick a balanced dp x sp split of all devices for ``n_sequences``.

    dp is the largest divisor of the device count not exceeding the sequence
    count, so no data row idles; remaining devices go to sequence
    parallelism (e.g. 8 devices, 3 chromosomes -> 2 x 4)."""
    n = len(jax.devices())
    dp = max(d for d in range(1, n + 1) if n % d == 0 and d <= max(1, n_sequences))
    return make_mesh2d(dp, n // dp, axes=axes)


def local_device_count() -> int:
    return len(jax.devices())
