"""Sequence-parallel forward-backward: exact whole-sequence E-step over a mesh.

The reference's trainer APPROXIMATES one long genome as independent
65,536-symbol chunks — every chunk restarts from pi and no expected transition
count crosses a chunk boundary (the Mahout mapper contract,
CpGIslandFinder.java:130-141,200-201).  This module computes the EXACT
sufficient statistics of the undivided sequence, sharded along time across the
mesh (SURVEY.md §5 "Long-sequence scaling": forward-backward as a (+,x)
semiring scan with boundary-message exchange over ICI).

Structure per device (mirroring ops.viterbi_parallel's blockwise layout — a
`lax.scan` of ``block_size`` sequential steps over ``n_blocks`` parallel
lanes):

1. **Pass A (operators)** — each lane forms the probability-space product of
   its block's step matrices S_t = A * B[:, o_t] (one [nb,K]x[K,K] batched
   matmul per step, normalized per step to stay in f32 range).  An
   `associative_scan` over lane products + a tiny cross-device `all_gather` of
   the [K, K] per-device totals give every lane its EXACT (normalized)
   entering alpha — the forward boundary message.
2. **Pass B (forward)** — lanes re-run the scaled forward recurrence from
   their true entering vectors, storing normalized alphas and the per-step
   scale factors whose logs sum (via `psum`) to the exact sequence
   log-likelihood.
3. **Pass C (backward + stats)** — suffix operator products (lane-level scan
   + the same gathered device totals) give every lane its exact entering beta
   DIRECTION from the right; a reverse scan fuses the beta recurrence with
   gamma/xi accumulation.  Scale-free trick: true gamma_t and xi_t each sum
   to 1 over their indices, so normalizing the per-step outer products
   reconstructs them exactly from the beta direction alone — no scale chain
   has to cross device boundaries.

Total cross-device communication per E-step: one all_gather of [K, K] totals
and one of [K] init vectors — independent of sequence length, riding ICI.

Boundary pairs (the expected transition counts the reference DROPS at chunk
boundaries) are owned by the later block/device: its lane-0 xi uses the
entering alpha message, so every adjacent pair in the genome is counted
exactly once.

**2-D mesh (data x seq)**: :func:`sharded_stats2d_fn` runs a BATCH of
sequences (e.g. chromosomes) with sequences sharded over the ``data`` axis
and each sequence's time dimension over the ``seq`` axis — dp x sp on one
mesh, the composition SURVEY.md §2 lists as the scale-out shape.  Collectives
stay per-row (seq axis) plus one final psum over both axes.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from cpgisland_tpu.models.hmm import HmmParams
from cpgisland_tpu.ops.forward_backward import SuffStats
from cpgisland_tpu.parallel.mesh import SEQ_AXIS, make_mesh

DEFAULT_BLOCK = 1024
_HI = jax.lax.Precision.HIGHEST
_TINY = 1e-30


def _nrm_v(v):
    return v / jnp.maximum(jnp.sum(v, axis=-1, keepdims=True), _TINY)


def _nrm_m(m):
    return m / jnp.maximum(jnp.sum(m, axis=(-2, -1), keepdims=True), _TINY)


def _prob_tables(params: HmmParams):
    """Probability-space step tables with a trailing identity PAD row.

    Sp_ext[s] = A * B[:, s] (column-scaled transition matrix) for s < M;
    Sp_ext[M] = I so PAD steps are exact pass-throughs.  B_ext[s] = B[:, s],
    with B_ext[M] = 1 (emission identity).
    """
    K = params.n_states
    A = jnp.exp(params.log_A)
    B = jnp.exp(params.log_B)  # [K, M]
    Sp = A[None, :, :] * B.T[:, None, :]  # [M, K, K]
    Sp_ext = jnp.concatenate([Sp, jnp.eye(K, dtype=A.dtype)[None]], axis=0)
    B_ext = jnp.concatenate([B.T, jnp.ones((1, K), A.dtype)], axis=0)
    return Sp_ext, B_ext


def _select(table_flat: jnp.ndarray, syms: jnp.ndarray) -> jnp.ndarray:
    """Exact one-hot row selection (TPU gathers are slow; see viterbi_parallel)."""
    oh = jax.nn.one_hot(syms, table_flat.shape[0], dtype=table_flat.dtype)
    return jnp.matmul(oh, table_flat, precision=_HI)


def _matmul_combine(a, b):
    """Normalized batched matrix product — the (+,x) semiring combine."""
    return _nrm_m(jnp.einsum("...ij,...jk->...ik", a, b, precision=_HI))


def device_boundary_messages(a0_local, total_dev, d, axis,
                             start_dir=None, end_dir=None):
    """Cross-device boundary-message exchange (the ONE implementation).

    One all_gather of the raw local init vectors and one of the [K, K]
    per-device transfer totals; tiny prefix/suffix scans then pick THIS
    device's entering-alpha direction and exiting-beta direction.  Used by
    both the XLA lane path (_one_seq_local_stats) and the fused-kernel path
    (ops.fb_pallas._seq_stats_core) so the numerics cannot diverge.

    ``start_dir``/``end_dir`` generalize the endpoints for span threading
    (pipeline-level processing of records larger than one pass): the prefix
    scan seeds from ``start_dir`` instead of device 0's local init direction
    (the entering-alpha message from the PREVIOUS span) and the suffix scan
    from ``end_dir`` instead of the free-end uniform direction (the
    exiting-beta message from the NEXT span).

    Returns (a0_raw_dev0 [K], enter_dir [K], exit_dir [K]).
    """
    a0_raw = jax.lax.all_gather(a0_local, axis)[0]  # device 0's init vector
    a0n = _nrm_v(a0_raw)
    totals = jax.lax.all_gather(total_dev, axis)  # [D, K, K]

    def pstep(v, Tk):
        return _nrm_v(jnp.matmul(v, Tk, precision=_HI)), v

    seed = a0n if start_dir is None else _nrm_v(start_dir + a0n * 0.0)
    _, enters_dev = jax.lax.scan(pstep, seed, totals)

    if end_dir is None:
        end_dir = jnp.full(a0n.shape, 1.0, a0n.dtype) / a0n.shape[-1]
    anchor = _nrm_v(end_dir + a0n * 0.0)

    def sstep(b, Tk):
        return _nrm_v(jnp.matmul(Tk, b, precision=_HI)), b

    _, exits_dev = jax.lax.scan(sstep, anchor, totals, reverse=True)
    return a0_raw, enters_dev[d], exits_dev[d]


def _lane_pass_products(
    params: HmmParams,
    obs_shard: jnp.ndarray,
    length: jnp.ndarray,
    *,
    axis: str,
    block_size: int,
    first: bool = True,
):
    """Pass A + the lane layout for one device shard (the ONE XLA copy of
    the packing/masking math): per-lane normalized operator products and
    their inclusive prefix.  Consumed by _one_seq_lane_setup and by
    parallel.posterior's span transfer-total sweep."""
    K, M = params.n_states, params.n_symbols
    L = obs_shard.shape[0]
    nb = L // block_size
    d = jax.lax.axis_index(axis)

    A = jnp.exp(params.log_A)
    Sp_ext, B_ext = _prob_tables(params)
    Sp_flat = Sp_ext.reshape(M + 1, K * K)

    obs_c = jnp.minimum(obs_shard.astype(jnp.int32), M)  # clamp stray values to PAD
    pos_valid = jnp.arange(L) < length
    # The global init's emission folds into v0, so its step is identity
    # (exactly the viterbi_parallel / parallel.decode trick).
    is_init = (jnp.arange(L) == 0) & (d == 0) & first
    step_valid = pos_valid & ~is_init
    sel_sym = jnp.where(step_valid, jnp.where(pos_valid, obs_c, M), M)
    emit_sym = jnp.where(pos_valid, jnp.minimum(obs_c, M - 1), 0)

    # [bs, nb] block layout: lane b covers positions [b*bs, (b+1)*bs).
    def to2(x):
        return x.reshape(nb, block_size).T

    sel2, emit2 = to2(sel_sym), to2(emit_sym)
    sv2, pv2 = to2(step_valid), to2(pos_valid)

    v0_local = jnp.exp(params.log_pi) * B_ext[jnp.minimum(obs_c[0], M - 1)]

    # Pass A: per-lane operator products (normalized each step).
    eye_b = jnp.broadcast_to(
        jnp.eye(K, dtype=A.dtype)[None] + (sel2[0, :, None, None] * 0).astype(A.dtype),
        (nb, K, K),
    )

    def passA(C, syms_k):
        sel = _select(Sp_flat, syms_k).reshape(nb, K, K)
        return _nrm_m(jnp.einsum("nij,njk->nik", C, sel, precision=_HI)), None

    P_lane, _ = jax.lax.scan(passA, eye_b, sel2)  # [nb, K, K]
    incl = jax.lax.associative_scan(_matmul_combine, P_lane, axis=0)
    return dict(
        K=K, M=M, nb=nb, d=d, A=A, B_ext=B_ext, eye_b=eye_b,
        sel2=sel2, emit2=emit2, sv2=sv2, pv2=pv2,
        P_lane=P_lane, incl=incl, v0_local=v0_local,
    )


def _one_seq_lane_setup(
    params: HmmParams,
    obs_shard: jnp.ndarray,
    length: jnp.ndarray,
    *,
    axis: str,
    block_size: int,
    enter_dir=None,
    exit_dir=None,
    first: bool = True,
):
    """Shared passes A/B for one time-sharded sequence: lane products ->
    boundary messages -> stored alphas/scales + per-lane exiting-beta
    directions.  Consumed by the stats pass (_one_seq_local_stats) and the
    posterior pass (_one_seq_local_posterior).

    ``first`` (static) marks the sequence's first span: global position 0 is
    the init (identity step, emission folded into v0).  ``enter_dir`` /
    ``exit_dir`` thread span-boundary messages exactly like
    device_boundary_messages threads device boundaries.
    """
    lay = _lane_pass_products(
        params, obs_shard, length, axis=axis, block_size=block_size, first=first
    )
    K, M, nb, d = lay["K"], lay["M"], lay["nb"], lay["d"]
    A, B_ext, eye_b = lay["A"], lay["B_ext"], lay["eye_b"]
    sel2, emit2, sv2, pv2 = lay["sel2"], lay["emit2"], lay["sv2"], lay["pv2"]
    P_lane, incl, v0_local = lay["P_lane"], lay["incl"], lay["v0_local"]

    v0_raw, v_enter_dev, beta_exit_dev = device_boundary_messages(
        v0_local, incl[-1], d, axis,
        start_dir=None if first else enter_dir,
        end_dir=exit_dir,
    )

    excl = jnp.concatenate([eye_b[:1], incl[:-1]], axis=0)
    enters = _nrm_v(jnp.einsum("k,nkj->nj", v_enter_dev, excl, precision=_HI))

    # --- Pass B: scaled forward from true entering vectors -----------
    def passB(alpha, inp):
        syms_k, sv_k = inp
        bcol = _select(B_ext, syms_k)  # [nb, K]
        raw = jnp.einsum("nk,kj->nj", alpha, A, precision=_HI) * bcol
        c = jnp.sum(raw, axis=-1)
        new = raw / jnp.maximum(c, _TINY)[:, None]
        alpha = jnp.where(sv_k[:, None], new, alpha)
        c = jnp.where(sv_k, c, 1.0)
        return alpha, (alpha, c)

    _, (alphas, cs) = jax.lax.scan(passB, enters, (sel2, sv2))  # [bs, nb, K], [bs, nb]
    # The init's folded-emission scale belongs to device 0 — and only when
    # it actually observed a symbol (an all-padding stream has loglik 0).
    # Span-threading callers get DIRECTION-relative logliks only (the scale
    # of a continuation span's entering message is unknown by design).
    loglik = jnp.sum(jnp.where(sv2, jnp.log(cs), 0.0)) + jnp.where(
        (d == 0) & first & (length > 0),
        jnp.log(jnp.maximum(jnp.sum(v0_raw), _TINY)),
        0.0,
    )

    # --- backward boundary messages: beta_exit_dev from the exchange above.
    # Lane-level suffix products P_b @ P_{b+1} @ ... (flip-scan-flip: the
    # combine sees flipped operands, so apply them flipped back).
    Rsuf = jax.lax.associative_scan(
        lambda a, b: _matmul_combine(b, a), P_lane, axis=0, reverse=True
    )
    beta_exits = jnp.concatenate(
        [
            _nrm_v(jnp.einsum("nij,j->ni", Rsuf[1:], beta_exit_dev, precision=_HI)),
            beta_exit_dev[None],
        ],
        axis=0,
    )  # [nb, K]
    return dict(
        K=K, M=M, nb=nb, d=d, A=A, B_ext=B_ext, eye_b=eye_b,
        sel2=sel2, emit2=emit2, sv2=sv2, pv2=pv2,
        enters=enters, alphas=alphas, cs=cs, loglik=loglik,
        beta_exits=beta_exits,
    )


def _one_seq_local_stats(
    params: HmmParams,
    obs_shard: jnp.ndarray,
    length: jnp.ndarray,
    *,
    axis: str,
    block_size: int,
) -> SuffStats:
    """This device's (un-psummed) statistics for one time-sharded sequence.

    obs_shard: [L] symbols (PAD >= n_symbols allowed in the trailing pad);
    length: [] count of real symbols in this shard.  Real symbols must be a
    contiguous global prefix (pads only trail the sequence).  Collectives run
    over ``axis``; the caller psums the result over the mesh.
    """
    s = _one_seq_lane_setup(
        params, obs_shard, length, axis=axis, block_size=block_size
    )
    K, M, nb, d = s["K"], s["M"], s["nb"], s["d"]
    A, B_ext, eye_b = s["A"], s["B_ext"], s["eye_b"]
    sel2, emit2, sv2, pv2 = s["sel2"], s["emit2"], s["sv2"], s["pv2"]
    enters, alphas, loglik = s["enters"], s["alphas"], s["loglik"]
    beta_exits = s["beta_exits"]
    block_size = sel2.shape[0]

    # --- Pass C: fused backward + gamma/xi accumulation ---------------
    a_prev = jnp.concatenate([enters[None], alphas[:-1]], axis=0)  # [bs, nb, K]
    sel_next2 = jnp.concatenate([sel2[1:], jnp.full((1, nb), M, sel2.dtype)], axis=0)
    svn2 = jnp.concatenate([sv2[1:], jnp.zeros((1, nb), bool)], axis=0)
    last2 = jnp.zeros((block_size, nb), bool).at[-1].set(True)

    trans0 = jnp.zeros((nb, K, K), A.dtype) + eye_b * 0.0
    emit0 = jnp.zeros((nb, K, M), A.dtype) + enters[:, :, None] * 0.0

    def passC(carry, inp):
        beta_next, trans_acc, emit_acc = carry
        alpha_t, aprev_t, sym_t, sym_next, sv_t, pv_t, svn_t, last_t = inp
        w = _select(B_ext, sym_next) * beta_next  # [nb, K]
        beta_rec = _nrm_v(jnp.einsum("nk,jk->nj", w, A, precision=_HI))
        beta_t = jnp.where(
            last_t[:, None],
            beta_exits,
            jnp.where(svn_t[:, None], beta_rec, beta_next),
        )
        # gamma_t: true value sums to 1 -> normalize reconstructs scale.
        gamma = _nrm_v(alpha_t * beta_t)
        oh = jax.nn.one_hot(sym_t, M, dtype=A.dtype)  # emit2 is pre-clamped to < M
        # graftcheck: allow(no-stats-in-bwd-chain) -- XLA lane assembly: lanes are time-parallel and XLA schedules the sums off the per-lane recurrence; the ban targets the Pallas kernels' serial chain (CLAUDE.md)
        emit_acc = emit_acc + jnp.where(
            pv_t[:, None, None], gamma[:, :, None] * oh[:, None, :], 0.0
        )
        # xi for the (t-1 -> t) pair, owned by position t; lane-0 pairs use
        # the entering-alpha boundary message (aprev_t == enters there).
        bcol_t = _select(B_ext, sym_t)
        xr = aprev_t[:, :, None] * A[None] * (bcol_t * beta_t)[:, None, :]
        xi = xr / jnp.maximum(jnp.sum(xr, axis=(-2, -1), keepdims=True), _TINY)
        # graftcheck: allow(no-stats-in-bwd-chain) -- XLA lane assembly (see the emit_acc waiver above)
        trans_acc = trans_acc + jnp.where(sv_t[:, None, None], xi, 0.0)
        return (beta_t, trans_acc, emit_acc), None

    # emission one-hot uses the REAL symbol layout (emit2), not sel2.
    (beta_first, trans_l, emit_l), _ = jax.lax.scan(
        passC,
        (beta_exits, trans0, emit0),
        (alphas, a_prev, emit2, sel_next2, sv2, pv2, svn2, last2),
        reverse=True,
    )

    gamma0 = _nrm_v(alphas[0, 0] * beta_first[0])
    at_init = (d == 0) & (length > 0)
    return SuffStats(
        init=jnp.where(at_init, gamma0, jnp.zeros_like(gamma0)),
        trans=jnp.sum(trans_l, axis=0),
        emit=jnp.sum(emit_l, axis=0),
        loglik=loglik,
        n_seqs=jnp.where(at_init, 1, 0).astype(jnp.int32),
    )


def _one_seq_local_posterior(
    params: HmmParams,
    obs_shard: jnp.ndarray,
    length: jnp.ndarray,
    island_mask: jnp.ndarray,
    *,
    axis: str,
    block_size: int,
    enter_dir=None,
    exit_dir=None,
    first: bool = True,
    want_path: bool = False,
):
    """This device's per-position island confidence (XLA lane path).

    The posterior twin of _one_seq_local_stats: same passes A/B and boundary
    messages, but pass C emits conf[t] = sum_{k in islands} gamma[t, k] (and
    optionally the max-posterior-marginal state) per position instead of
    accumulating count tensors.  gamma is scale-free (normalized
    alpha_t * beta_t), so beta DIRECTIONS give exact posteriors across lane,
    device, and span boundaries.  Returns (conf [L] f32, path [L] int32).
    """
    s = _one_seq_lane_setup(
        params, obs_shard, length, axis=axis, block_size=block_size,
        enter_dir=enter_dir, exit_dir=exit_dir, first=first,
    )
    nb, A, B_ext = s["nb"], s["A"], s["B_ext"]
    sel2, sv2, pv2 = s["sel2"], s["sv2"], s["pv2"]
    alphas, beta_exits = s["alphas"], s["beta_exits"]
    M = s["M"]
    bs = sel2.shape[0]

    sel_next2 = jnp.concatenate([sel2[1:], jnp.full((1, nb), M, sel2.dtype)], axis=0)
    svn2 = jnp.concatenate([sv2[1:], jnp.zeros((1, nb), bool)], axis=0)
    last2 = jnp.zeros((bs, nb), bool).at[-1].set(True)
    mask = island_mask.astype(A.dtype)

    def passP(beta_next, inp):
        alpha_t, sym_next, sv_next, last_t, pv_t = inp
        w = _select(B_ext, sym_next) * beta_next  # [nb, K]
        beta_rec = _nrm_v(jnp.einsum("nk,jk->nj", w, A, precision=_HI))
        beta_t = jnp.where(
            last_t[:, None],
            beta_exits,
            jnp.where(sv_next[:, None], beta_rec, beta_next),
        )
        gamma = _nrm_v(alpha_t * beta_t)
        conf_t = jnp.where(pv_t, jnp.sum(gamma * mask[None, :], axis=-1), 0.0)
        path_t = jnp.where(pv_t, jnp.argmax(gamma, axis=-1), 0).astype(jnp.int32)
        return beta_t, (conf_t, path_t)

    _, (conf2, path2) = jax.lax.scan(
        passP, beta_exits, (alphas, sel_next2, svn2, last2, pv2), reverse=True
    )
    # [bs, nb] lane layout back to global order.
    conf = conf2.T.reshape(-1)
    path = path2.T.reshape(-1) if want_path else jnp.zeros(conf.shape, jnp.int32)
    return conf, path


def _shard_stats_body(block_size: int, axis: str):
    """1-D per-device E-step body (one sequence over the whole mesh)."""

    def body(params: HmmParams, obs_shard: jnp.ndarray, len_shard: jnp.ndarray) -> SuffStats:
        local = _one_seq_local_stats(
            params, obs_shard, len_shard[0], axis=axis, block_size=block_size
        )
        return jax.lax.psum(local, axis)

    return body


def _shard_stats2d_body(
    block_size: int,
    data_axis: str,
    seq_axis: str,
    engine: str = "xla",
    lane_T: int | None = None,
    t_tile: int | None = None,
    one_pass: bool = False,
):
    """2-D per-device E-step body: sequences over ``data``, time over ``seq``.

    obs_tile: [R, L] — R local sequences' shards; len_tile: [R, 1].  The R
    sequences run through one lax.scan (the three-pass program is traced
    once, whatever R is); every step's collectives involve only this device's
    seq row.  ``engine="pallas"`` lowers each sequence's shard through the
    fused kernels (ops.fb_pallas._seq_stats_core with reduce=False — each
    device returns its LOCAL partial and the single psum over both axes at
    the end reduces everything once, same as the XLA branch).
    """

    def body(params: HmmParams, obs_tile: jnp.ndarray, len_tile: jnp.ndarray) -> SuffStats:
        K, M = params.n_states, params.n_symbols

        if engine in ("pallas", "onehot"):
            from cpgisland_tpu.ops import fb_pallas

            # Trace-time knob discipline (graftune's "consultation is
            # HOST-side only"): this body is traced under shard_map/jit,
            # so a pick_lane_T call here would freeze the tuned winner
            # into the compiled program (no retrace when TUNING.json
            # updates).  Callers that want the tuned winner resolve it
            # host-side and pass ``lane_T`` explicitly (Seq2DBackend
            # does); the in-trace fallback is the PURE rate-table
            # heuristic — a deterministic function of the static shard
            # shape, identical to pick_lane_T wherever no fresh tuned
            # winner applies.
            lt = (
                lane_T
                if lane_T is not None
                else fb_pallas.legacy_lane_T(
                    obs_tile.shape[1], onehot=engine == "onehot",
                    # NO long lanes in the 2-D body: 131072 measured 800
                    # vs 864 (65536) / 867 (16384) Msym/s on the 32 Mi
                    # single-row group (r5 sweep, tools/bench_seq2d.py) —
                    # the standalone seq path's 131072 win does not carry
                    # over to the per-row scan.
                    long_lanes=False,
                )
            )
            tt = t_tile if t_tile is not None else fb_pallas.DEFAULT_T_TILE

            def one_seq(obs_row, length):
                return fb_pallas._seq_stats_core(
                    params, obs_row, length, lt, tt,
                    axis=seq_axis, reduce=False, onehot=engine == "onehot",
                    one_pass=one_pass,
                )
        else:
            def one_seq(obs_row, length):
                return _one_seq_local_stats(
                    params, obs_row, length, axis=seq_axis, block_size=block_size
                )

        def scan_body(acc, inp):
            obs_row, len_row = inp
            return acc + one_seq(obs_row, len_row[0]), None

        # lax.scan (not a Python loop) so the three-pass program is traced
        # once, not R times — R can be dozens of chromosomes per row.  The
        # device-varying zero keeps the carry's type consistent with the body
        # output under shard_map.
        dv = obs_tile[0, 0] * 0
        init = jax.tree_util.tree_map(
            lambda z: z + dv.astype(z.dtype), SuffStats.zeros(K, M)
        )
        total, _ = jax.lax.scan(scan_body, init, (obs_tile, len_tile))
        return jax.lax.psum(total, (data_axis, seq_axis))

    return body


@functools.lru_cache(maxsize=32)
def sharded_stats_fn(mesh: Mesh, block_size: int):
    """Compiled placed-array entry point: fn(params, obs_flat, lengths).

    obs_flat: [D * L] symbols placed with P(axis) (L a multiple of
    block_size); lengths: [D] int32 placed with P(axis) — the layout
    :func:`shard_sequence` + a NamedSharding device_put produce.  Cached per
    (mesh, block_size); params stay traced so model updates never recompile.
    """
    axis = mesh.axis_names[0]
    body = _shard_stats_body(block_size, axis)
    return jax.jit(
        jax.shard_map(
            body,
            mesh=mesh,
            in_specs=(P(), P(axis), P(axis)),
            out_specs=P(),
        )
    )


@functools.lru_cache(maxsize=32)
def sharded_stats2d_fn(
    mesh: Mesh,
    block_size: int,
    engine: str = "xla",
    lane_T: int | None = None,
    t_tile: int | None = None,
    one_pass: bool = False,
):
    """Compiled 2-D entry point: fn(params, obs [N, T], lengths [N, sp]).

    ``mesh`` must be 2-D (data, seq).  obs rows are whole padded sequences
    placed with P(data, seq); lengths[n, s] is sequence n's real-symbol count
    in seq-shard s, placed with P(data, seq).  ``engine="pallas"`` lowers
    each per-row shard through the fused kernels (TPU; interpreted
    elsewhere), with ``lane_T``/``t_tile`` overriding the kernel defaults.
    ``one_pass`` arms the matrix-carried one-pass onehot arm per row
    (no-op off the onehot kernel-stats route — fb_pallas gates it).
    """
    data_axis, seq_axis = mesh.axis_names
    body = _shard_stats2d_body(
        block_size, data_axis, seq_axis, engine, lane_T, t_tile, one_pass
    )
    return jax.jit(
        jax.shard_map(
            body,
            mesh=mesh,
            in_specs=(P(), P(data_axis, seq_axis), P(data_axis, seq_axis)),
            out_specs=P(),
            # pallas_call output types are opaque to the varying-axes
            # checker — the project-wide pattern for pallas-under-shard_map
            # (see parallel.decode, SpmdBackend).
            check_vma=engine == "xla",
        )
    )


@functools.lru_cache(maxsize=32)
def sharded_stats2d_rows_fn(mesh: Mesh, engine: str, t_tile: int = 512,
                            prep_meta: tuple | None = None):
    """Whole-record chunked-kernel fast path for SMALL-record 2-D groups.

    A record that fits ONE kernel lane needs none of the sequence-parallel
    machinery: the chunked E-step kernels already treat each lane as an
    independent sequence, and with a whole record per lane their stats are
    EXACT (the 64 Ki chunk-independence approximation only exists when a
    record spans chunks).  Rows shard over ``data``; requires the group's
    seq axis to be trivial (sp == 1 — auto_mesh2d's layout whenever rows
    >= devices).  Replaces a per-row lax.scan of full three-pass
    sequence-parallel programs — the scan serialized R tiny programs per
    iteration, the dominant seq2d cost for many-scaffold inputs.

    ``prep_meta`` = (S, N_local, T, t_tile, onehot): the returned fn
    additionally accepts per-device prepared chunked streams (ops.prepared,
    built by Seq2DBackend's sharded prep builder) as a 4th ``prepared``
    argument — the symbol-only lane/pair prep then never re-derives per EM
    iteration.
    """
    data_axis, seq_axis = mesh.axis_names

    def body(params: HmmParams, obs_tile: jnp.ndarray, len_tile: jnp.ndarray,
             prepared=None) -> SuffStats:
        if engine in ("pallas", "onehot"):
            from cpgisland_tpu.ops import fb_pallas

            st = fb_pallas.batch_stats_pallas(
                params, obs_tile, len_tile[:, 0], t_tile=t_tile,
                onehot=engine == "onehot", prepared=prepared,
            )
        else:
            from cpgisland_tpu.ops.forward_backward import batch_stats

            st = batch_stats(params, obs_tile, len_tile[:, 0], mode="rescaled")
        return jax.lax.psum(st, (data_axis, seq_axis))

    row_specs = (P(), P(data_axis, seq_axis), P(data_axis, seq_axis))
    if prep_meta is None:
        def body3(params, obs_tile, len_tile):
            return body(params, obs_tile, len_tile)

        return jax.jit(
            jax.shard_map(
                body3,
                mesh=mesh,
                in_specs=row_specs,
                out_specs=P(),
                check_vma=engine == "xla",
            )
        )
    from cpgisland_tpu.ops import prepared as prep_mod

    S, N_local, T, tt, onehot = prep_meta
    compiled = jax.jit(
        jax.shard_map(
            body,
            mesh=mesh,
            in_specs=row_specs + (
                prep_mod.chunked_spec_tree(
                    S, N_local, T, tt, onehot, data_axis
                ),
            ),
            out_specs=P(),
            check_vma=engine == "xla",
        )
    )
    return prep_mod.kw_prepared_shim(compiled)


@functools.lru_cache(maxsize=32)
def sharded_stats_pallas_fn(mesh: Mesh, lane_T: int, t_tile: int,
                            onehot: bool = False, fused: bool = True,
                            one_pass: bool = False):
    """Fused-kernel twin of :func:`sharded_stats_fn` (same placed-array
    contract): per-device lane products + boundary-message exchange run the
    chunked Pallas forward/backward kernels on each shard — exact
    whole-sequence statistics at kernel speed across the mesh.  ``onehot``
    routes the reduced kernels for one-hot-emission models; ``fused``
    co-schedules their fwd/bwd chains (False = the split r9 A/B arm —
    SeqBackend threads its ``fuse_fb`` here so the chip A/B works on
    multi-device meshes too); ``one_pass`` arms the matrix-carried arm
    that also folds the products pass in (SeqBackend threads its
    ``one_pass``; gated to the onehot kernel-stats route in fb_pallas)."""
    from cpgisland_tpu.ops import fb_pallas

    axis = mesh.axis_names[0]

    def body(params, obs_shard, len_shard):
        return fb_pallas._seq_stats_core(
            params, obs_shard, len_shard[0], lane_T, t_tile, axis=axis,
            onehot=onehot, fused=fused, one_pass=one_pass,
        )

    return jax.jit(
        jax.shard_map(
            body,
            mesh=mesh,
            in_specs=(P(), P(axis), P(axis)),
            out_specs=P(),
            check_vma=False,  # pallas_call output types are opaque to vma
        )
    )


def shard_sequence(obs: np.ndarray, n_shards: int, block_size: int = DEFAULT_BLOCK, pad_value: int = 4):
    """Split one symbol stream into per-device shards (padded, with lengths).

    Returns (obs_padded [n_shards * L] uint8, lengths [n_shards] int32).
    """
    obs = np.ascontiguousarray(obs, dtype=np.uint8)
    T = obs.shape[0]
    quantum = n_shards * block_size
    padded_T = max(quantum, ((T + quantum - 1) // quantum) * quantum)
    if padded_T != T:
        obs = np.concatenate([obs, np.full(padded_T - T, pad_value, dtype=np.uint8)])
    L = padded_T // n_shards
    lengths = np.clip(T - np.arange(n_shards) * L, 0, L).astype(np.int32)
    return obs, lengths


def shard_lengths(seq_lengths: np.ndarray, T_padded: int, sp: int) -> np.ndarray:
    """Per-(sequence, seq-shard) real-symbol counts: [N] -> [N, sp]."""
    L = T_padded // sp
    starts = np.arange(sp) * L
    return np.clip(np.asarray(seq_lengths)[:, None] - starts[None, :], 0, L).astype(np.int32)


def seq_stats_sharded(
    params: HmmParams,
    obs,
    *,
    mesh: Mesh | None = None,
    block_size: int = DEFAULT_BLOCK,
) -> SuffStats:
    """Exact whole-sequence sufficient statistics, sequence-parallel over a mesh.

    The drop-in "one long genome" alternative to chunked
    ops.forward_backward.batch_stats: identical SuffStats contract, but with no
    independence approximation at 65,536-symbol boundaries.
    """
    if mesh is None:
        mesh = make_mesh(axis=SEQ_AXIS)
    n_dev = mesh.shape[mesh.axis_names[0]]
    obs_p, lengths = shard_sequence(np.asarray(obs), n_dev, block_size, params.n_symbols)
    axis = mesh.axis_names[0]
    arr = jax.device_put(jnp.asarray(obs_p), NamedSharding(mesh, P(axis)))
    lens = jax.device_put(jnp.asarray(lengths), NamedSharding(mesh, P(axis)))
    return sharded_stats_fn(mesh, block_size)(params, arr, lens)


def pad_batch2d(
    chunks: np.ndarray,
    lengths: np.ndarray,
    dp: int,
    sp: int,
    block_size: int,
    pad_value: int,
):
    """Pad an [N, T] sequence batch for a dp x sp mesh.

    Rows (sequences) pad to a multiple of dp with zero-length rows; columns
    pad to a multiple of sp * block_size with ``pad_value``.  The single
    source of truth for the 2-D layout — both Seq2DBackend and the standalone
    helper go through here.
    """
    chunks = np.asarray(chunks)
    lengths = np.asarray(lengths)
    n, T = chunks.shape
    quantum = sp * block_size
    T_pad = max(quantum, -(-T // quantum) * quantum)
    n_pad = -(-n // dp) * dp
    if (n_pad, T_pad) == (n, T):
        return chunks, lengths.astype(np.int32)
    obs = np.full((n_pad, T_pad), pad_value, dtype=np.uint8)
    obs[:n, :T] = chunks
    out_lengths = np.zeros(n_pad, np.int32)
    out_lengths[:n] = lengths
    return obs, out_lengths


def place_batch2d(mesh: Mesh, chunks, lengths):
    """Device-place a padded [N, T] batch + [N] lengths on a 2-D mesh.

    Returns (obs P(data, seq), per-shard lengths [N, sp] P(data, seq)) — the
    exact input layout of :func:`sharded_stats2d_fn`.
    """
    da, sa = mesh.axis_names
    chunks = np.asarray(chunks)
    lengths2d = shard_lengths(np.asarray(lengths), chunks.shape[1], mesh.shape[sa])
    sharding = NamedSharding(mesh, P(da, sa))
    return (
        jax.device_put(jnp.asarray(chunks), sharding),
        jax.device_put(jnp.asarray(lengths2d), sharding),
    )


def pack_ragged(sequences, pad_value: int):
    """Pack ragged 1-D symbol arrays into a padded [N, T_max] matrix + lengths.

    Peak memory is the matrix plus the input arrays — callers with
    chromosome-scale records that can re-stream their source should build the
    matrix record-by-record instead (pipeline.train_file's two-pass load).
    """
    if len(sequences) == 0:
        raise ValueError("no sequences")
    lengths = np.array([len(s) for s in sequences], dtype=np.int32)
    rows = np.full((len(sequences), max(1, int(lengths.max()))), pad_value, dtype=np.uint8)
    for i, s in enumerate(sequences):
        rows[i, : len(s)] = np.asarray(s, dtype=np.uint8)
    return rows, lengths


def batch_seq_stats_sharded(
    params: HmmParams,
    sequences,
    *,
    mesh: Mesh,
    block_size: int = DEFAULT_BLOCK,
) -> SuffStats:
    """Exact statistics for a batch of independent sequences on a 2-D mesh.

    ``sequences`` is a list of 1-D symbol arrays (e.g. one per chromosome).
    Sequences are distributed over the mesh's first (data) axis; each
    sequence's time dimension is sharded over the second (seq) axis.  The
    result equals the SUM of per-sequence exact whole-sequence statistics.
    """
    if len(mesh.axis_names) != 2:
        raise ValueError(f"need a 2-D (data, seq) mesh, got axes {mesh.axis_names}")
    da, sa = mesh.axis_names
    dp, sp = mesh.shape[da], mesh.shape[sa]
    pad = params.n_symbols
    rows, seq_lengths = pack_ragged(list(sequences), pad)
    obs, lengths = pad_batch2d(rows, seq_lengths, dp, sp, block_size, pad)
    arr, lens = place_batch2d(mesh, obs, lengths)
    return sharded_stats2d_fn(mesh, block_size, "xla")(params, arr, lens)
