"""Checkpoint / resume for training state.

The reference persists the model to HDFS every EM iteration (the MR driver's
modelIn/modelOut paths, CpGIslandFinder.java:64-89,200-203) but has no resume
logic in the driver.  Here checkpoints are a first-class subsystem (SURVEY.md
§5): each EM iteration can snapshot (pi, A, B, iteration, log-likelihood
history) to a single ``.npz``, and training can resume from any snapshot.  The
reference's plain-text dump (models.hmm.dump_text) is kept alongside for format
compatibility.
"""

from __future__ import annotations

import os
import tempfile
from dataclasses import dataclass, field
from typing import Optional, Sequence

import numpy as np

from cpgisland_tpu.models.hmm import HmmParams


@dataclass
class TrainState:
    """Everything needed to resume Baum-Welch mid-run."""

    params: HmmParams
    iteration: int = 0
    logliks: list = field(default_factory=list)


def save(path: str, state: TrainState) -> None:
    """Atomically write a TrainState snapshot as .npz (write temp + rename)."""
    d = os.path.dirname(os.path.abspath(path)) or "."
    os.makedirs(d, exist_ok=True)
    fd, tmp = tempfile.mkstemp(dir=d, suffix=".npz.tmp")
    try:
        with os.fdopen(fd, "wb") as f:
            np.savez(
                f,
                pi=np.asarray(state.params.pi, dtype=np.float64),
                A=np.asarray(state.params.A, dtype=np.float64),
                B=np.asarray(state.params.B, dtype=np.float64),
                iteration=np.int64(state.iteration),
                logliks=np.asarray(state.logliks, dtype=np.float64),
            )
        os.replace(tmp, path)
    except BaseException:
        if os.path.exists(tmp):
            os.unlink(tmp)
        raise


def load(path: str) -> TrainState:
    with np.load(path) as z:
        params = HmmParams.from_probs(z["pi"], z["A"], z["B"])
        return TrainState(
            params=params,
            iteration=int(z["iteration"]),
            logliks=list(z["logliks"]),
        )


def latest(directory: str, prefix: str = "ckpt_") -> Optional[str]:
    """Path of the highest-iteration checkpoint in a directory, or None."""
    if not os.path.isdir(directory):
        return None
    best: tuple[int, Optional[str]] = (-1, None)
    for name in os.listdir(directory):
        if name.startswith(prefix) and name.endswith(".npz"):
            try:
                it = int(name[len(prefix) : -len(".npz")])
            except ValueError:
                continue
            if it > best[0]:
                best = (it, os.path.join(directory, name))
    return best[1]


def checkpoint_path(directory: str, iteration: int, prefix: str = "ckpt_") -> str:
    return os.path.join(directory, f"{prefix}{iteration:06d}.npz")
