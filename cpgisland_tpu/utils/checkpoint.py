"""Checkpoint / resume for training state.

The reference persists the model to HDFS every EM iteration (the MR driver's
modelIn/modelOut paths, CpGIslandFinder.java:64-89,200-203) but has no resume
logic in the driver.  Here checkpoints are a first-class subsystem (SURVEY.md
§5): each EM iteration can snapshot (pi, A, B, iteration, log-likelihood
history), and training can resume from any snapshot.  Two storage formats:

- ``.npz`` (default) — one atomic file per snapshot, no extra deps in the
  loop; right-sized for a model of 8 + 64 + 32 parameters.
- Orbax (``format="orbax"``) — `orbax.checkpoint.StandardCheckpointer`
  directories; the ecosystem-standard format when checkpoints must
  interoperate with other JAX tooling or move to cloud storage.

:func:`load` and :func:`latest` auto-detect the format, so ``resume`` works
over a directory containing either.  The reference's plain-text dump
(models.hmm.dump_text) is kept alongside for format compatibility.
"""

from __future__ import annotations

import logging
import os
import tempfile
from dataclasses import dataclass, field
from typing import Optional

import numpy as np

from cpgisland_tpu.models.hmm import HmmParams

log = logging.getLogger(__name__)


def _import_orbax():
    try:
        import orbax.checkpoint as ocp
    except ImportError as e:
        raise ImportError(
            "the 'orbax' checkpoint format needs orbax-checkpoint — install "
            "with `pip install cpgisland-tpu[orbax]` (or use the default "
            "'npz' format, which has no extra dependencies)"
        ) from e
    return ocp


@dataclass
class TrainState:
    """Everything needed to resume Baum-Welch mid-run."""

    params: HmmParams
    iteration: int = 0
    logliks: list = field(default_factory=list)


def _state_tree(state: TrainState) -> dict:
    # orbax_leaf: orbax 0.7 rejects numpy SCALAR leaves (np.int64) — 0-d
    # ndarrays round-trip identically on every release (utils.compat).
    from cpgisland_tpu.utils.compat import orbax_leaf

    return {
        "pi": np.asarray(state.params.pi, dtype=np.float64),
        "A": np.asarray(state.params.A, dtype=np.float64),
        "B": np.asarray(state.params.B, dtype=np.float64),
        "iteration": orbax_leaf(np.int64(state.iteration)),
        "logliks": np.asarray(state.logliks, dtype=np.float64),
    }


def _state_from_tree(z) -> TrainState:
    return TrainState(
        params=HmmParams.from_probs(z["pi"], z["A"], z["B"]),
        iteration=int(z["iteration"]),
        logliks=list(np.atleast_1d(np.asarray(z["logliks"]))),
    )


def save(path: str, state: TrainState, format: str = "npz") -> None:
    """Write a TrainState snapshot — atomic .npz or an Orbax directory."""
    if format == "orbax":
        ocp = _import_orbax()

        with ocp.StandardCheckpointer() as ckptr:
            # Orbax wants an absolute, non-existing target dir; its own
            # tmp-then-rename gives atomicity.  Strip the npz suffix so the
            # two formats share checkpoint_path().
            target = os.path.abspath(path[: -len(".npz")] if path.endswith(".npz") else path)
            os.makedirs(os.path.dirname(target), exist_ok=True)
            ckptr.save(target, _state_tree(state), force=True)
        return
    if format != "npz":
        raise ValueError(f"unknown checkpoint format {format!r} (npz|orbax)")
    d = os.path.dirname(os.path.abspath(path)) or "."
    os.makedirs(d, exist_ok=True)
    fd, tmp = tempfile.mkstemp(dir=d, suffix=".npz.tmp")
    try:
        with os.fdopen(fd, "wb") as f:
            np.savez(f, **_state_tree(state))
            # fsync BEFORE the rename: os.replace is atomic against a
            # process kill, but without the sync a machine crash can leave
            # the renamed file with unwritten pages — exactly the truncated
            # checkpoint latest() must then skip.
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)
    except BaseException:
        if os.path.exists(tmp):
            os.unlink(tmp)
        raise


def load(path: str) -> TrainState:
    """Load a snapshot; the format is auto-detected (npz file / Orbax dir)."""
    if os.path.isdir(path):
        ocp = _import_orbax()

        with ocp.StandardCheckpointer() as ckptr:
            # Target-less restore: orbax logs an unsafe-topology warning, but
            # these are host-only numpy trees whose shapes _state_from_tree
            # validates implicitly (from_probs checks pi/A/B consistency).
            return _state_from_tree(ckptr.restore(os.path.abspath(path)))
    with np.load(path) as z:
        return _state_from_tree(z)


def latest(
    directory: str, prefix: str = "ckpt_", validate: bool = True
) -> Optional[str]:
    """Path of the highest-iteration LOADABLE checkpoint in a directory
    (either format), or None.

    ``validate=True`` (default) actually loads each candidate, newest
    first, and SKIPS corrupt or truncated files with a warning instead of
    letting resume crash on them — a killed run's half-written snapshot
    (or a machine crash's unsynced pages) must cost one iteration of
    progress, not the whole resume.  The models here are ~100 parameters,
    so a validation load is microseconds.  ``validate=False`` restores the
    old name-only behavior.
    """
    if not os.path.isdir(directory):
        return None
    candidates: list[tuple[int, str]] = []
    for name in os.listdir(directory):
        if not name.startswith(prefix):
            continue
        stem = name[: -len(".npz")] if name.endswith(".npz") else name
        try:
            it = int(stem[len(prefix):])
        except ValueError:
            continue
        full = os.path.join(directory, name)
        if not (name.endswith(".npz") or os.path.isdir(full)):
            continue
        candidates.append((it, full))
    for _, full in sorted(candidates, reverse=True):
        if not validate:
            return full
        try:
            load(full)
            return full
        except Exception as e:
            log.warning(
                "skipping corrupt/truncated checkpoint %s (%s: %s); trying "
                "the previous snapshot", full, type(e).__name__, e,
            )
    return None


def checkpoint_path(
    directory: str, iteration: int, prefix: str = "ckpt_", format: str = "npz"
) -> str:
    name = f"{prefix}{iteration:06d}"
    return os.path.join(directory, name + (".npz" if format == "npz" else ""))
