"""Bounded background prefetch: overlap host encode with device compute.

The serial pipeline runs encode -> upload -> compute -> fetch strictly in
sequence per record, so at genome scale the wall clock is the SUM of host
FASTA parsing and device work even though they use disjoint resources
(BASELINE.md's end-to-end breakdown: the host encode rivals the 8-chip
decode).  :class:`RecordPrefetcher` moves the record iterator onto a
background thread with a BOUNDED queue: while the device decodes record r,
the host is already parsing/encoding record r+1 (the producer's work is
file I/O and NumPy byte ops, which release the GIL), and the queue bound
keeps peak host memory at ``depth`` records instead of the whole file.

Semantics are exactly the serial iterator's: items come out in order, a
producer exception re-raises at the consumer's next() — the point where the
serial loop would have raised — and close() joins the thread
deterministically (no leaked threads across pytest modules).

Telemetry (zero cost when the obs subsystem is off): the prefetcher tracks
produce time, consumer stall time, and queue depth, and emits ONE
``prefetch_stream`` event at close with the overlap ratio —
``(produce_s - stall_s) / produce_s``, i.e. the fraction of host encode
wall that was hidden behind device compute.
"""

from __future__ import annotations

import logging
import queue
import threading
import time
from typing import Iterable, Iterator

from cpgisland_tpu import obs

log = logging.getLogger(__name__)

_DONE = ("done", None)


class RecordPrefetcher:
    """Background-thread iterator wrapper with a bounded queue.

    ``depth`` bounds both the lookahead and the host memory held in flight;
    1 is classic double buffering (one item cooking while one is consumed).
    Use as a context manager, or call :meth:`close` in a ``finally`` — the
    producer thread is joined there, never abandoned.
    """

    def __init__(
        self,
        it: Iterable,
        depth: int = 2,
        name: str = "records",
        join_timeout_s: float = 30.0,
    ):
        if depth < 1:
            raise ValueError(f"prefetch depth must be >= 1, got {depth}")
        self.name = name
        self.depth = depth
        self.join_timeout_s = join_timeout_s
        self._q: queue.Queue = queue.Queue(maxsize=depth)
        self._stop = threading.Event()
        self._closed = False
        self.records = 0
        self.produce_s = 0.0  # producer time spent in next(it)
        self.stall_s = 0.0  # consumer time spent waiting on an empty queue
        self.max_depth = 0
        self._depth_sum = 0
        self._it = iter(it)
        self._thread = threading.Thread(
            target=self._produce,
            args=(self._it,),
            name=f"cpgisland-prefetch-{name}",
            daemon=True,
        )
        self._thread.start()

    # -- producer ------------------------------------------------------------

    def _put(self, item) -> bool:
        """Enqueue, yielding to a close() signal; False when closing."""
        while not self._stop.is_set():
            try:
                self._q.put(item, timeout=0.05)
                return True
            except queue.Full:
                continue
        return False

    def _produce(self, it: Iterator) -> None:
        try:
            while not self._stop.is_set():
                t0 = time.perf_counter()
                try:
                    item = next(it)
                except StopIteration:
                    self._put(_DONE)
                    return
                self.produce_s += time.perf_counter() - t0
                if not self._put(("item", item)):
                    return
        except BaseException as e:  # re-raised at the consumer's next()
            self._put(("exc", e))

    # -- consumer ------------------------------------------------------------

    def __iter__(self) -> "RecordPrefetcher":
        return self

    def __next__(self):
        if self._closed:
            raise StopIteration
        d = self._q.qsize()
        self._depth_sum += d
        self.max_depth = max(self.max_depth, d)
        t0 = time.perf_counter()
        kind, payload = self._q.get()
        self.stall_s += time.perf_counter() - t0
        if kind == "item":
            self.records += 1
            return payload
        if kind == "exc":
            self._finish()
            raise payload
        self._finish()  # "done"
        raise StopIteration

    # -- lifecycle -----------------------------------------------------------

    def _finish(self) -> None:
        """Stop + join the producer and emit the telemetry event once."""
        if self._closed:
            return
        self._closed = True
        self._stop.set()
        # Join in short slices, draining the queue between them: a producer
        # blocked on a FULL queue (its put slot could be re-filled between a
        # single drain and the join) always finds room to observe the stop
        # flag, so close is deterministic for every producer that is not
        # stuck inside next(it) itself — consumer-side pipeline errors
        # mid-stream included, not just clean exhaustion.
        deadline = time.perf_counter() + self.join_timeout_s
        while self._thread.is_alive():
            while True:
                try:
                    self._q.get_nowait()
                except queue.Empty:
                    break
            self._thread.join(timeout=0.05)
            if time.perf_counter() >= deadline:
                break
        if self._thread.is_alive():
            # The producer is stuck inside a long next(it) (e.g. a huge
            # record's encode on a slow filesystem) and cannot observe the
            # stop flag until it returns.  The daemon flag keeps it from
            # blocking interpreter exit; a finalizer thread takes over the
            # generator close the moment the producer does return, so the
            # wrapped iterator's resources (open FASTA handles) are still
            # released deterministically-on-exit rather than at GC time.
            log.warning(
                "prefetch producer %r still running after %.0f s join "
                "timeout (stuck in the underlying record iterator); a "
                "finalizer thread will close the wrapped iterator when it "
                "returns",
                self._thread.name, self.join_timeout_s,
            )
            threading.Thread(
                target=_join_then_close,
                args=(self._thread, self._it),
                name=f"{self._thread.name}-finalizer",
                daemon=True,
            ).start()
        else:
            # Producer exited: release the wrapped generator's resources
            # (file handles of an abandoned mid-file FASTA parse) now, not
            # at GC time.  Safe only here — a generator cannot be closed
            # while another thread is executing it.
            _close_iter(self._it)
        overlap_s = max(0.0, self.produce_s - self.stall_s)
        obs.event(
            "prefetch_stream",
            stream=self.name,
            depth=self.depth,
            records=self.records,
            produce_s=round(self.produce_s, 4),
            stall_s=round(self.stall_s, 4),
            overlap_s=round(overlap_s, 4),
            overlap_ratio=(
                round(overlap_s / self.produce_s, 4) if self.produce_s else 1.0
            ),
            mean_depth=(
                round(self._depth_sum / max(1, self.records + 1), 2)
            ),
            max_depth=self.max_depth,
        )

    def close(self) -> None:
        self._finish()

    def __enter__(self) -> "RecordPrefetcher":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


def _close_iter(it) -> None:
    """close() a wrapped generator if it has one; never raises (the close
    runs on error paths that must keep the ORIGINAL exception)."""
    close = getattr(it, "close", None)
    if close is not None:
        try:
            close()
        except Exception:
            log.warning("closing the wrapped record iterator failed", exc_info=True)


def _join_then_close(thread: threading.Thread, it) -> None:
    """Finalizer-thread body: wait out a producer stuck in next(it), then
    release the wrapped generator (a generator cannot be closed while
    another thread is executing it)."""
    thread.join()
    _close_iter(it)


def maybe_prefetch(it: Iterable, depth: int, name: str):
    """``depth > 0`` wraps ``it`` in a RecordPrefetcher, else returns it
    unchanged — the one switch the pipeline entry points use.  Returns
    (iterable, closer), so call sites hold exactly one ``finally``.  The
    serial closer closes the wrapped generator: a consumer-side pipeline
    error mid-stream must release the underlying FASTA handle
    deterministically in BOTH modes, not only when the prefetch thread is
    in play."""
    if depth and depth > 0:
        pf = RecordPrefetcher(it, depth=depth, name=name)
        return pf, pf.close
    return it, lambda: _close_iter(it)
