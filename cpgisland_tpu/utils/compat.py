"""JAX version shims.

The framework targets the current jax API (``jax.shard_map`` with
``check_vma``); CI images sometimes carry an older jax (0.4.x) where
shard_map still lives at ``jax.experimental.shard_map.shard_map`` with the
``check_rep`` spelling.  :func:`install` bridges the gap in-place so every
call site can use the one modern spelling — a no-op on current jax.
"""

from __future__ import annotations

import functools


def install() -> None:
    import jax

    if not hasattr(jax.lax, "axis_size"):
        def axis_size(axis_name):
            from jax._src import core as _core

            return _core.get_axis_env().axis_size(axis_name)

        jax.lax.axis_size = axis_size

    if hasattr(jax, "shard_map"):
        return
    try:
        from jax.experimental.shard_map import shard_map as _legacy
    except ImportError:  # pragma: no cover - no known jax lacks both
        return

    @functools.wraps(_legacy)
    def shard_map(f, *, mesh, in_specs, out_specs, check_vma: bool = True, **kw):
        return _legacy(
            f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
            check_rep=check_vma, **kw,
        )

    jax.shard_map = shard_map
