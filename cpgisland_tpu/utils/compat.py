"""JAX-ecosystem version shims.

The framework targets the current jax API (``jax.shard_map`` with
``check_vma``); CI images sometimes carry an older jax (0.4.x) where
shard_map still lives at ``jax.experimental.shard_map.shard_map`` with the
``check_rep`` spelling.  :func:`install` bridges the gap in-place so every
call site can use the one modern spelling — a no-op on current jax.

Sibling shims for the rest of the ecosystem live here too:
:func:`orbax_leaf` (checkpoint-tree leaf coercion across orbax's
supported-type tightening) and :func:`cpu_multiprocess_collectives`
(whether this jax can run cross-process computations on the CPU backend —
the capability the real multi-host test needs).
"""

from __future__ import annotations

import functools


def orbax_leaf(x):
    """Coerce a checkpoint-tree leaf to a type every orbax release accepts.

    orbax-checkpoint 0.7 tightened ``StandardCheckpointer``'s supported leaf
    types to (int, float, np.ndarray, jax.Array): a numpy SCALAR such as
    ``np.int64(3)`` — accepted by earlier releases — now raises
    ``Unsupported type`` at save.  A 0-d ndarray round-trips identically on
    every release, so scalars are wrapped as 0-d arrays here.
    """
    import numpy as np

    if isinstance(x, np.generic):  # numpy scalar (np.int64, np.float64, ...)
        return np.asarray(x)
    return x


def jax_version() -> tuple:
    """(major, minor, patch) of the installed jax, zeros on parse failure."""
    import jax

    parts = []
    for p in str(jax.__version__).split(".")[:3]:
        digits = "".join(c for c in p if c.isdigit())
        parts.append(int(digits) if digits else 0)
    while len(parts) < 3:
        parts.append(0)
    return tuple(parts)


def cpu_multiprocess_collectives() -> bool:
    """Can this jax run multi-process computations on the CPU backend?

    jax 0.4.x's XLA:CPU rejects any computation spanning processes
    ("Multiprocess computations aren't implemented on the CPU backend"), so
    ``process_allgather`` — and with it the byte-range-sharded input path —
    only works across processes on TPU there.  jax >= 0.5 ships CPU
    cross-process collectives (Gloo).  Callers (the real 2-process test)
    use this to skip with a reason instead of failing on an environment
    limitation.
    """
    return jax_version() >= (0, 5, 0)


def install() -> None:
    import jax

    if not hasattr(jax.lax, "axis_size"):
        def axis_size(axis_name):
            from jax._src import core as _core

            return _core.get_axis_env().axis_size(axis_name)

        jax.lax.axis_size = axis_size

    if hasattr(jax, "shard_map"):
        return
    try:
        from jax.experimental.shard_map import shard_map as _legacy
    except ImportError:  # pragma: no cover - no known jax lacks both
        return

    @functools.wraps(_legacy)
    def shard_map(f, *, mesh, in_specs, out_specs, check_vma: bool = True, **kw):
        return _legacy(
            f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
            check_rep=check_vma, **kw,
        )

    jax.shard_map = shard_map
