"""ctypes loader for the native runtime library (native/codec.cpp).

Loads ``native/libcpgnative.so``, building it with the in-tree Makefile on
first use if a C++ toolchain is present.  Everything degrades gracefully: if
the library can't be built or loaded (or ``CPGISLAND_NATIVE=0``), callers get
``None`` and fall back to the NumPy implementations — the native path is a
throughput optimization, never a requirement.  pybind11 isn't in this image,
hence ctypes (SURVEY.md §0: the reference has no native components at all;
ours replaces its JVM stream IO, CpGIslandFinder.java:112-128).
"""

from __future__ import annotations

import ctypes
import logging
import os
import subprocess
import threading
from typing import Optional

import numpy as np

log = logging.getLogger(__name__)

_ABI = 3
_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
_NATIVE_DIR = os.path.join(_REPO_ROOT, "native")
_SO_PATH = os.path.join(_NATIVE_DIR, "libcpgnative.so")

# FASTA streaming-state bits (must match native/codec.cpp).
IN_HEADER = 1
AT_LINE_START = 2

_lock = threading.Lock()
_lib: Optional[ctypes.CDLL] = None
_tried = False


def _build() -> bool:
    src = os.path.join(_NATIVE_DIR, "codec.cpp")
    if not os.path.exists(src):
        return False
    try:
        subprocess.run(
            ["make", "-C", _NATIVE_DIR],
            check=True,
            capture_output=True,
            timeout=120,
        )
        return os.path.exists(_SO_PATH)
    except (OSError, subprocess.SubprocessError) as e:
        log.debug("native build failed: %s", e)
        return False


def load() -> Optional[ctypes.CDLL]:
    """The shared library, or None if unavailable/disabled."""
    global _lib, _tried
    if _lib is not None or _tried:
        return _lib
    with _lock:
        if _lib is not None or _tried:
            return _lib
        _tried = True
        if os.environ.get("CPGISLAND_NATIVE", "1") == "0":
            return None
        needs_build = not os.path.exists(_SO_PATH) or (
            os.path.getmtime(_SO_PATH)
            < os.path.getmtime(os.path.join(_NATIVE_DIR, "codec.cpp"))
        )
        if needs_build and not _build():
            return None
        try:
            try:
                lib = ctypes.CDLL(_SO_PATH)
            except OSError:
                # Stale or foreign-platform artifact (e.g. built elsewhere):
                # rebuild for this platform and retry once.
                os.unlink(_SO_PATH)
                if not _build():
                    return None
                lib = ctypes.CDLL(_SO_PATH)
            lib.cpg_native_abi.restype = ctypes.c_uint32
            if lib.cpg_native_abi() != _ABI:
                log.warning("stale native library (abi mismatch); rebuilding")
                # dlclose the stale image first: dlopen matches by pathname and
                # would otherwise hand the old mapping straight back.
                import _ctypes

                handle = lib._handle
                del lib
                _ctypes.dlclose(handle)
                os.unlink(_SO_PATH)
                if not _build():
                    return None
                lib = ctypes.CDLL(_SO_PATH)
                lib.cpg_native_abi.restype = ctypes.c_uint32
                if lib.cpg_native_abi() != _ABI:
                    log.warning("rebuilt native library still abi-mismatched; disabling")
                    return None
            lib.cpg_encode.restype = ctypes.c_size_t
            lib.cpg_encode.argtypes = [
                ctypes.c_char_p,
                ctypes.c_size_t,
                ctypes.POINTER(ctypes.c_uint8),
            ]
            lib.cpg_encode_fasta.restype = ctypes.c_size_t
            lib.cpg_encode_fasta.argtypes = [
                ctypes.c_char_p,
                ctypes.c_size_t,
                ctypes.POINTER(ctypes.c_uint8),
                ctypes.POINTER(ctypes.c_uint32),
            ]
            lib.cpg_count_segments.restype = ctypes.c_size_t
            lib.cpg_count_segments.argtypes = [
                ctypes.c_char_p,
                ctypes.c_size_t,
                ctypes.c_int,
                ctypes.c_int,
                ctypes.POINTER(ctypes.c_size_t),
                ctypes.POINTER(ctypes.c_size_t),
                ctypes.c_size_t,
            ]
            lib.cpg_encode_segments.restype = ctypes.c_size_t
            lib.cpg_encode_segments.argtypes = [
                ctypes.c_char_p,
                ctypes.POINTER(ctypes.c_size_t),
                ctypes.POINTER(ctypes.c_size_t),
                ctypes.c_size_t,
                ctypes.c_int,
                ctypes.POINTER(ctypes.c_uint8),
            ]
            _lib = lib
        except OSError as e:
            log.debug("native load failed: %s", e)
            _lib = None
    return _lib


def available() -> bool:
    return load() is not None


def _compact(out: np.ndarray, n: int) -> np.ndarray:
    """Slice the encode output, copying when the slack is large.

    A bare ``out[:n]`` view pins the whole input-sized buffer; for
    skip-dominated blocks (FASTA N-runs span tens of Mbp in GRCh38) that
    inflates peak memory to raw-bytes-read instead of symbols-kept.  Dense
    blocks (newlines only, ~1.5% slack) keep the view to skip the memcpy.
    """
    if n < (out.size // 8) * 7:
        return out[:n].copy()
    return out[:n]


def encode(data: bytes) -> Optional[np.ndarray]:
    """Native twin of codec.encode_bytes; None when the library is absent."""
    lib = load()
    if lib is None:
        return None
    out = np.empty(len(data), dtype=np.uint8)
    n = lib.cpg_encode(
        data, len(data), out.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8))
    )
    return _compact(out, n)


def encode_mt(
    data, *, fasta: bool = False, threads: int = 0
) -> Optional[np.ndarray]:
    """Parallel whole-buffer fused (strip+)encode; None if library absent.

    Two native passes (count, then write at exact per-thread offsets), so the
    output allocation is exactly the symbol count — no input-sized scratch.
    ``data`` must be a complete buffer starting at a line start (bytes or a
    uint8 array); ``threads<=0`` = auto (hardware concurrency, ~4 MiB/thread
    floor).
    """
    lib = load()
    if lib is None:
        return None
    if isinstance(data, np.ndarray):
        data = np.ascontiguousarray(data, dtype=np.uint8)
        buf = data.ctypes.data_as(ctypes.c_char_p)
        n = data.size
    else:
        buf = data
        n = len(data)
    if n == 0:
        return np.zeros(0, dtype=np.uint8)
    # Segments API: one count fan-out, one write fan-out — the input is
    # scanned exactly twice regardless of size.
    max_seg = 256
    bounds = (ctypes.c_size_t * (max_seg + 1))()
    counts = (ctypes.c_size_t * max_seg)()
    nseg = lib.cpg_count_segments(buf, n, int(fasta), threads, bounds, counts, max_seg)
    if nseg == 0:
        # n > 0 was handled above, so 0 is the C API's capacity-error
        # sentinel (more segments than max_seg) — never a silent empty result.
        raise RuntimeError(f"native cpg_count_segments needed more than {max_seg} segments")
    total = sum(counts[:nseg])
    out = np.empty(total, dtype=np.uint8)
    written = lib.cpg_encode_segments(
        buf, bounds, counts, nseg, int(fasta),
        out.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
    )
    if written != total:
        raise RuntimeError(f"native encode_mt wrote {written}, counted {total}")
    return out


class FastaEncoder:
    """Stateful fused header-strip + encode for streaming blocks."""

    def __init__(self) -> None:
        self._state = ctypes.c_uint32(AT_LINE_START)
        self._lib = load()

    @property
    def available(self) -> bool:
        return self._lib is not None

    def feed(self, data: bytes) -> np.ndarray:
        assert self._lib is not None
        out = np.empty(len(data), dtype=np.uint8)
        n = self._lib.cpg_encode_fasta(
            data,
            len(data),
            out.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
            ctypes.byref(self._state),
        )
        return _compact(out, n)
