"""Tracing, phase timing, and structured metrics (SURVEY.md §5).

The reference has no tracing or metrics at all — two SLF4J lines total
(CpGIslandFinder.java:147,228).  Here:

- :func:`trace` — context manager around ``jax.profiler.trace`` producing a
  TensorBoard-loadable XPlane trace of device execution.
- :class:`PhaseTimer` — wall-clock + throughput accounting per pipeline phase
  (encode, train, decode, islands), printable and exportable.
- :class:`MetricsLogger` — append-only JSONL event stream (one object per
  line: ts, event, fields) for per-iteration EM stats, decode throughput,
  island counts; `None`-safe so call sites never branch.

NaN policy: JAX purity already rules out data races (SURVEY.md §5); numeric
health is guarded by :func:`check_finite` on small model tensors (cheap) and
by ``jax.config.update("jax_debug_nans", True)`` for deep debugging.
"""

from __future__ import annotations

import contextlib
import dataclasses
import json
import logging
import os
import time
from typing import IO, Iterator, Optional, Union

import numpy as np

log = logging.getLogger(__name__)


@contextlib.contextmanager
def trace(log_dir: str, enabled: bool = True) -> Iterator[None]:
    """Capture a jax.profiler device trace into ``log_dir`` (TensorBoard format)."""
    if not enabled:
        yield
        return
    import jax

    os.makedirs(log_dir, exist_ok=True)
    with jax.profiler.trace(log_dir):
        yield
    log.info("profiler trace written to %s", log_dir)


@dataclasses.dataclass
class Phase:
    name: str
    seconds: float = 0.0
    items: float = 0.0  # symbols, chunks, ... caller-defined unit
    unit: str = "items"

    @property
    def throughput(self) -> float:
        return self.items / self.seconds if self.seconds > 0 else 0.0


class PhaseTimer:
    """Accumulates wall-clock and throughput per named phase.

    >>> pt = PhaseTimer()
    >>> with pt.phase("decode", items=1 << 20, unit="sym"):
    ...     pass
    """

    def __init__(self) -> None:
        self.phases: dict[str, Phase] = {}

    @contextlib.contextmanager
    def phase(self, name: str, items: float = 0.0, unit: str = "items") -> Iterator[None]:
        t0 = time.perf_counter()
        try:
            yield
        finally:
            dt = time.perf_counter() - t0
            p = self.phases.setdefault(name, Phase(name, unit=unit))
            p.seconds += dt
            p.items += items
            p.unit = unit

    def report(self) -> str:
        lines = []
        for p in self.phases.values():
            tp = f" ({p.throughput / 1e6:.2f} M{p.unit}/s)" if p.items else ""
            lines.append(f"{p.name}: {p.seconds:.3f}s{tp}")
        return "\n".join(lines)

    def as_dict(self) -> dict:
        return {
            p.name: {"seconds": p.seconds, p.unit: p.items, "throughput": p.throughput}
            for p in self.phases.values()
        }


class MetricsLogger:
    """Append-only JSONL metrics stream.

    Every record: ``{"ts": <unix float>, "event": <str>, ...fields}``.
    ``MetricsLogger(None)`` (or the module-level :func:`null`) swallows events,
    so instrumented code never needs None checks.
    """

    def __init__(self, sink: Optional[Union[str, IO[str]]] = None) -> None:
        self._own = isinstance(sink, str)
        self._f: Optional[IO[str]] = open(sink, "a") if self._own else sink

    def log(self, event: str, **fields) -> None:
        if self._f is None:
            return
        rec = {"ts": time.time(), "event": event}
        rec.update(fields)
        self._f.write(json.dumps(rec, default=float) + "\n")
        self._f.flush()

    def close(self) -> None:
        if self._own and self._f is not None:
            self._f.close()
            self._f = None

    def __enter__(self) -> "MetricsLogger":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


def null() -> MetricsLogger:
    return MetricsLogger(None)


def check_finite(tree, where: str = "") -> None:
    """Raise FloatingPointError if any leaf of a (small) pytree is NaN/inf.

    Intended for model-sized tensors (pi, A, B, loglik) after each EM
    iteration — O(K^2) work, so safe to leave on in production.
    """
    import jax

    bad = []
    for path, leaf in jax.tree_util.tree_flatten_with_path(tree)[0]:
        arr = np.asarray(leaf)
        if arr.dtype.kind == "f" and not np.isfinite(arr).all():
            bad.append(jax.tree_util.keystr(path))
    if bad:
        raise FloatingPointError(f"non-finite values{' in ' + where if where else ''}: {bad}")
