"""Tracing, phase timing, and structured metrics (SURVEY.md §5).

The reference has no tracing or metrics at all — two SLF4J lines total
(CpGIslandFinder.java:147,228).  Here:

- :func:`trace` — context manager around ``jax.profiler.trace`` producing a
  TensorBoard-loadable XPlane trace of device execution.
- :class:`PhaseTimer` — wall-clock + throughput accounting per pipeline phase
  (encode, train, decode, islands), printable and exportable.
- :class:`MetricsLogger` — append-only JSONL event stream (one object per
  line: ts, event, fields) for per-iteration EM stats, decode throughput,
  island counts; `None`-safe so call sites never branch.

NaN policy: JAX purity already rules out data races (SURVEY.md §5); numeric
health is guarded by :func:`check_finite` on small model tensors (cheap) and
by ``jax.config.update("jax_debug_nans", True)`` for deep debugging.
"""

from __future__ import annotations

import contextlib
import dataclasses
import json
import logging
import os
import time
from typing import IO, Iterator, Optional, Union

import numpy as np

log = logging.getLogger(__name__)


@contextlib.contextmanager
def trace(log_dir: str, enabled: bool = True) -> Iterator[None]:
    """Capture a jax.profiler device trace into ``log_dir`` (TensorBoard format)."""
    if not enabled:
        yield
        return
    import jax

    os.makedirs(log_dir, exist_ok=True)
    with jax.profiler.trace(log_dir):
        yield
    log.info("profiler trace written to %s", log_dir)


@dataclasses.dataclass
class Phase:
    name: str
    seconds: float = 0.0
    items: float = 0.0  # symbols, chunks, ... caller-defined unit
    unit: str = "items"

    @property
    def throughput(self) -> float:
        return self.items / self.seconds if self.seconds > 0 else 0.0


class PhaseTimer:
    """Accumulates wall-clock and throughput per named phase.

    >>> pt = PhaseTimer()
    >>> with pt.phase("decode", items=1 << 20, unit="sym"):
    ...     pass
    """

    def __init__(self) -> None:
        self.phases: dict[str, Phase] = {}

    @contextlib.contextmanager
    def phase(self, name: str, items: float = 0.0, unit: str = "items") -> Iterator[None]:
        # Mirror every phase as an obs span (no-op until an Observer is
        # active): the pipeline's existing timing discipline IS the span
        # instrumentation, so enabling telemetry adds no new sync points.
        from cpgisland_tpu import obs

        t0 = time.perf_counter()
        try:
            with obs.span(name, items=items, unit=unit):
                yield
        finally:
            dt = time.perf_counter() - t0
            p = self.phases.setdefault(name, Phase(name, unit=unit))
            p.seconds += dt
            if unit == p.unit:
                p.items += items
            else:
                # Keep the FIRST unit and DROP the mismatched items:
                # last-writer-wins silently corrupted throughput math, and
                # summing chunks into syms would corrupt it just as silently.
                # Wall time still accumulates (it is unit-independent).
                log.warning(
                    "phase %r re-entered with unit %r; keeping first unit %r "
                    "and dropping the %s mismatched items (summing mixed "
                    "units would corrupt throughput)",
                    name, unit, p.unit, items,
                )

    def report(self) -> str:
        lines = []
        for p in self.phases.values():
            tp = f" ({p.throughput / 1e6:.2f} M{p.unit}/s)" if p.items else ""
            lines.append(f"{p.name}: {p.seconds:.3f}s{tp}")
        return "\n".join(lines)

    def as_dict(self) -> dict:
        return {
            p.name: {"seconds": p.seconds, p.unit: p.items, "throughput": p.throughput}
            for p in self.phases.values()
        }

    @staticmethod
    def merge(dicts: list) -> dict:
        """Aggregate :meth:`as_dict` outputs from several hosts into one.

        Hosts run phases CONCURRENTLY in a pod job, so per-phase wall is the
        MAX across hosts and items SUM; throughput is recomputed as
        sum-items / max-wall — the meaningful cross-host rate.  Mismatched
        units for the same phase raise (summing syms into chunks is the
        corruption the unit fix above exists to prevent).
        """
        out: dict = {}
        for d in dicts:
            for name, rec in d.items():
                unit_keys = [
                    k for k in rec if k not in ("seconds", "throughput")
                ]
                unit = unit_keys[0] if unit_keys else "items"
                if name not in out:
                    out[name] = {"seconds": 0.0, unit: 0.0}
                prev_units = [
                    k for k in out[name] if k not in ("seconds", "throughput")
                ]
                if prev_units and unit != prev_units[0]:
                    raise ValueError(
                        f"phase {name!r}: unit mismatch across hosts "
                        f"({prev_units[0]!r} vs {unit!r})"
                    )
                out[name]["seconds"] = max(out[name]["seconds"], rec["seconds"])
                out[name][unit] += rec.get(unit, 0.0)
        for name, rec in out.items():
            unit = [k for k in rec if k not in ("seconds", "throughput")][0]
            rec["throughput"] = (
                rec[unit] / rec["seconds"] if rec["seconds"] > 0 else 0.0
            )
        return out


class MetricsLogger:
    """Append-only JSONL metrics stream.

    Every record: ``{"ts": <unix float>, "event": <str>,
    "process_index": <int>, ...fields}``.  ``MetricsLogger(None)`` (or the
    module-level :func:`null`) swallows events, so instrumented code never
    needs None checks.

    Multi-host safety: in a pod job every process runs the same driver code,
    so a path sink would be written P times (or clobbered on shared
    filesystems).  By default only process 0 writes — non-zero processes
    demote to a null sink at first use; pass ``all_processes=True`` to keep
    every host writing (give each its own path) — records carry
    ``process_index`` either way, so merged streams stay attributable.  The
    check re-resolves on every :meth:`log` call until the JAX backend is
    actually initialized (resolving must not itself initialize it, and
    before ``jax.distributed.initialize`` EVERY host looks like process 0 —
    caching that answer would defeat the demotion); records written during
    that window carry ``process_index: 0``.
    """

    def __init__(
        self,
        sink: Optional[Union[str, IO[str]]] = None,
        all_processes: bool = False,
    ) -> None:
        self._own = isinstance(sink, str)
        self._f: Optional[IO[str]] = open(sink, "a") if self._own else sink
        self._all_processes = all_processes
        self._pidx: Optional[int] = None  # None = undecidable so far

    def log(self, event: str, **fields) -> None:
        if self._f is None:
            return
        pidx = self._pidx
        if pidx is None:
            from cpgisland_tpu.obs.trace import process_index_or_none

            pidx = process_index_or_none()
            if pidx is not None:
                self._pidx = pidx  # decidable now: cache forever
                if pidx != 0 and not self._all_processes:
                    self.close()
                    self._f = None
                    return
        rec = {"ts": time.time(), "event": event,
               "process_index": 0 if pidx is None else pidx}
        rec.update(fields)
        self._f.write(json.dumps(rec, default=float) + "\n")
        self._f.flush()

    def close(self) -> None:
        if self._own and self._f is not None:
            self._f.close()
            self._f = None

    def __enter__(self) -> "MetricsLogger":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


def null() -> MetricsLogger:
    return MetricsLogger(None)


def check_finite(tree, where: str = "") -> None:
    """Raise FloatingPointError if any leaf of a (small) pytree is NaN/inf.

    Intended for model-sized tensors (pi, A, B, loglik) after each EM
    iteration — O(K^2) work, so safe to leave on in production.
    """
    import jax

    bad = []
    for path, leaf in jax.tree_util.tree_flatten_with_path(tree)[0]:
        arr = np.asarray(leaf)
        if arr.dtype.kind == "f" and not np.isfinite(arr).all():
            bad.append(jax.tree_util.keystr(path))
    if bad:
        raise FloatingPointError(f"non-finite values{' in ' + where if where else ''}: {bad}")
