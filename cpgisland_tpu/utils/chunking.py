"""Chunk framing: long symbol streams -> fixed-size [num_chunks, chunk_size] batches.

Reference framing (both with silent remainder drop):
- training shards of 0x10000 = 65,536 symbols (CpGIslandFinder.java:130-141)
- decode chunks of 0x100000 = 1,048,576 symbols (CpGIslandFinder.java:256-259)

The reference drops any trailing remainder (< one chunk) on the floor in both
paths — that is the ``drop_remainder=True`` compat mode.  The clean mode pads
the final chunk with a PAD sentinel and returns true lengths so no data is lost;
downstream ops mask padded positions.
"""

from __future__ import annotations

from dataclasses import dataclass

import numpy as np

TRAIN_CHUNK = 0x10000  # CpGIslandFinder.java:130
DECODE_CHUNK = 0x100000  # CpGIslandFinder.java:256
PAD_SYMBOL = 4  # one past the 4 real symbols; ops treat it as "no observation"


@dataclass(frozen=True)
class Chunked:
    """A framed batch of symbol chunks.

    chunks:  [num_chunks, chunk_size] uint8 (PAD_SYMBOL in padded tail positions)
    lengths: [num_chunks] int32 true lengths (== chunk_size except possibly last)
    total:   total number of real symbols framed (sum of lengths)
    """

    chunks: np.ndarray
    lengths: np.ndarray
    total: int

    @property
    def num_chunks(self) -> int:
        return int(self.chunks.shape[0])

    @property
    def chunk_size(self) -> int:
        return int(self.chunks.shape[1])


def frame(symbols: np.ndarray, chunk_size: int, *, drop_remainder: bool = False) -> Chunked:
    """Frame a 1-D symbol array into fixed-size chunks.

    drop_remainder=True reproduces the reference's silent drop of the trailing
    partial chunk (CpGIslandFinder.java:130 `count % 0x10000 == 0` gate with no
    final flush; same pattern at :256).
    """
    symbols = np.ascontiguousarray(symbols, dtype=np.uint8)
    n = symbols.shape[0]
    n_full, rem = divmod(n, chunk_size)
    if drop_remainder or rem == 0:
        chunks = symbols[: n_full * chunk_size].reshape(n_full, chunk_size)
        lengths = np.full(n_full, chunk_size, dtype=np.int32)
        return Chunked(chunks=chunks, lengths=lengths, total=n_full * chunk_size)
    chunks = np.full((n_full + 1, chunk_size), PAD_SYMBOL, dtype=np.uint8)
    chunks[:n_full] = symbols[: n_full * chunk_size].reshape(n_full, chunk_size)
    chunks[n_full, :rem] = symbols[n_full * chunk_size :]
    lengths = np.full(n_full + 1, chunk_size, dtype=np.int32)
    lengths[n_full] = rem
    return Chunked(chunks=chunks, lengths=lengths, total=n)


def process_shard(
    chunked: Chunked,
    process_index: int,
    process_count: int,
) -> Chunked:
    """THIS host's contiguous block of a globally-framed chunk batch.

    The multi-host input-sharding step (SURVEY.md §5 DCN role), mirroring the
    reference's HDFS input splits (CpGIslandFinder.java:108-147): the global
    batch is padded with empty chunks to a process_count multiple and process
    p takes rows [p*n_local, (p+1)*n_local).  Contiguous blocks — not strided
    rows — so the local block lines up with the process's addressable devices
    under a NamedSharding over the data axis (global device order enumerates
    process 0's devices first), which is what
    ``jax.make_array_from_process_local_data`` assumes in SpmdBackend.place.

    ``total`` in the result is the LOCAL real-symbol count (this shard's
    contribution); the union of all shards covers every global chunk exactly
    once.
    """
    if not (0 <= process_index < process_count):
        raise ValueError(f"process_index {process_index} not in [0, {process_count})")
    padded = pad_to_multiple(chunked, process_count)
    n_local = padded.num_chunks // process_count
    lo = process_index * n_local
    chunks = padded.chunks[lo : lo + n_local]
    lengths = padded.lengths[lo : lo + n_local]
    return Chunked(chunks=chunks, lengths=lengths, total=int(lengths.sum()))


def pad_to_multiple(chunked: Chunked, multiple: int) -> Chunked:
    """Pad the batch dim with empty (all-PAD, length-0) chunks to a multiple.

    Needed to shard a chunk batch evenly over a device mesh axis: empty chunks
    contribute zero sufficient statistics, so results are unchanged.
    """
    n = chunked.num_chunks
    target = ((n + multiple - 1) // multiple) * multiple
    if target == n:
        return chunked
    extra = target - n
    pad_chunks = np.full((extra, chunked.chunk_size), PAD_SYMBOL, dtype=np.uint8)
    pad_lengths = np.zeros(extra, dtype=np.int32)
    return Chunked(
        chunks=np.concatenate([chunked.chunks, pad_chunks]),
        lengths=np.concatenate([chunked.lengths, pad_lengths]),
        total=chunked.total,
    )
