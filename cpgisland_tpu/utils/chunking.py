"""Chunk framing: long symbol streams -> fixed-size [num_chunks, chunk_size] batches.

Reference framing (both with silent remainder drop):
- training shards of 0x10000 = 65,536 symbols (CpGIslandFinder.java:130-141)
- decode chunks of 0x100000 = 1,048,576 symbols (CpGIslandFinder.java:256-259)

The reference drops any trailing remainder (< one chunk) on the floor in both
paths — that is the ``drop_remainder=True`` compat mode.  The clean mode pads
the final chunk with a PAD sentinel and returns true lengths so no data is lost;
downstream ops mask padded positions.
"""

from __future__ import annotations

from dataclasses import dataclass

import numpy as np

TRAIN_CHUNK = 0x10000  # CpGIslandFinder.java:130
DECODE_CHUNK = 0x100000  # CpGIslandFinder.java:256
PAD_SYMBOL = 4  # one past the 4 real symbols; ops treat it as "no observation"


@dataclass(frozen=True)
class Chunked:
    """A framed batch of symbol chunks.

    chunks:  [num_chunks, chunk_size] uint8 (PAD_SYMBOL in padded tail positions)
    lengths: [num_chunks] int32 true lengths (== chunk_size except possibly last)
    total:   total number of real symbols framed (sum of lengths)
    """

    chunks: np.ndarray
    lengths: np.ndarray
    total: int

    @property
    def num_chunks(self) -> int:
        return int(self.chunks.shape[0])

    @property
    def chunk_size(self) -> int:
        return int(self.chunks.shape[1])


def frame(symbols: np.ndarray, chunk_size: int, *, drop_remainder: bool = False) -> Chunked:
    """Frame a 1-D symbol array into fixed-size chunks.

    drop_remainder=True reproduces the reference's silent drop of the trailing
    partial chunk (CpGIslandFinder.java:130 `count % 0x10000 == 0` gate with no
    final flush; same pattern at :256).
    """
    symbols = np.ascontiguousarray(symbols, dtype=np.uint8)
    n = symbols.shape[0]
    n_full, rem = divmod(n, chunk_size)
    if drop_remainder or rem == 0:
        chunks = symbols[: n_full * chunk_size].reshape(n_full, chunk_size)
        lengths = np.full(n_full, chunk_size, dtype=np.int32)
        return Chunked(chunks=chunks, lengths=lengths, total=n_full * chunk_size)
    chunks = np.full((n_full + 1, chunk_size), PAD_SYMBOL, dtype=np.uint8)
    chunks[:n_full] = symbols[: n_full * chunk_size].reshape(n_full, chunk_size)
    chunks[n_full, :rem] = symbols[n_full * chunk_size :]
    lengths = np.full(n_full + 1, chunk_size, dtype=np.int32)
    lengths[n_full] = rem
    return Chunked(chunks=chunks, lengths=lengths, total=n)


@dataclass(frozen=True)
class Bucketed:
    """A length-bucketed batch of whole sequences (the seq2d training input).

    Padding every record to the GLOBAL maximum length — the reference-shaped
    dense [n_records, max_len] matrix — costs O(records x max_len) host RAM
    (~113 GB for a GRCh38 assembly: ~455 records, max 249 Mbp).  Bucketing
    pads each record only to its power-of-two size class and bounds each
    group's total symbols, so host peak is ~2x the raw input and each group
    can pick its own dp x sp mesh split (many-rows scaffold groups go
    data-parallel, single-row chromosome groups go sequence-parallel).

    chunks:  tuple of [N_g, T_g] uint8 group matrices (PAD in tails)
    lengths: tuple of [N_g] int32 true lengths
    total:   total real symbols across all groups
    """

    chunks: tuple
    lengths: tuple
    total: int

    @property
    def num_chunks(self) -> int:
        return int(sum(c.shape[0] for c in self.chunks))

    @property
    def num_groups(self) -> int:
        return len(self.chunks)


def bucket_records(
    records,
    *,
    floor: int = 1 << 16,
    budget: int = 1 << 28,
    pad_value: int = PAD_SYMBOL,
) -> Bucketed:
    """Stream whole records into power-of-two length buckets.

    ``records`` is an iterable of 1-D symbol arrays (e.g. one per FASTA
    record — pipeline.train_file streams them so the raw records are never
    all resident).  Each record pads to the next power of two >= ``floor``;
    groups within a size class close when they reach ``budget`` total
    symbols, so no single allocation exceeds max(budget, one record's padded
    size).  Group order follows first-record arrival order; rows within a
    group follow file order.
    """
    open_groups: dict[int, list] = {}  # T -> list of pending raw records
    sealed: list[tuple[np.ndarray, np.ndarray]] = []
    total = 0

    def seal(T: int) -> None:
        recs = open_groups.pop(T)
        if not recs:
            return
        mat = np.full((len(recs), T), pad_value, np.uint8)
        lens = np.empty(len(recs), np.int32)
        for i, r in enumerate(recs):
            mat[i, : r.shape[0]] = r
            lens[i] = r.shape[0]
        sealed.append((mat, lens))

    for rec in records:
        rec = np.ascontiguousarray(rec, dtype=np.uint8)
        n = rec.shape[0]
        total += n
        T = floor
        while T < n:
            T <<= 1
        # Buffer RAW records and assemble the padded matrix only at seal:
        # peak host RAM stays proportional to content (one group's records
        # plus its padded matrix), never an eager budget-sized allocation
        # per open size class.
        open_groups.setdefault(T, []).append(rec)
        if len(open_groups[T]) >= max(1, budget // T):
            seal(T)
    for T in list(open_groups):
        seal(T)
    if not sealed:
        raise ValueError("no records to bucket")
    return Bucketed(
        chunks=tuple(c for c, _ in sealed),
        lengths=tuple(l for _, l in sealed),
        total=total,
    )


@dataclass(frozen=True)
class LocalShard:
    """THIS process's contiguous block of a globally-framed chunk batch,
    built WITHOUT any process ever materializing the global input.

    chunks/lengths follow the Chunked layout; ``total`` is the LOCAL real
    symbol count; ``global_rows`` is the padded global row count
    (= chunks.shape[0] * process_count).  SpmdBackend.prepare/place assemble
    the global device array from these via
    jax.make_array_from_process_local_data.
    """

    chunks: np.ndarray
    lengths: np.ndarray
    total: int
    global_rows: int

    @property
    def num_chunks(self) -> int:
        return int(self.chunks.shape[0])

    @property
    def chunk_size(self) -> int:
        return int(self.chunks.shape[1])


def _shard_row_range(p: int, n_local: int, C: int, total: int):
    """Global symbol range [lo, hi) covered by process p's row block."""
    lo = min(p * n_local * C, total)
    hi = min((p + 1) * n_local * C, total)
    return lo, hi


def _spill_ranges(q: int, counts: np.ndarray, n_local: int, C: int):
    """Process q's head/tail spill: symbols it HOLDS outside the row range
    it OWNS.  Pure math from the count exchange — every process computes
    every other's spill shape, so the data gather has a static layout."""
    offsets = np.concatenate([[0], np.cumsum(counts)])
    total = int(offsets[-1])
    O_q, n_q = int(offsets[q]), int(counts[q])
    lo, hi = _shard_row_range(q, n_local, C, total)
    head = (O_q, min(O_q + n_q, max(O_q, lo)))  # held before owned range
    tail = (max(O_q, min(O_q + n_q, hi)), O_q + n_q)  # held after it
    return head, tail


def _spill_buffer(syms: np.ndarray, q: int, counts: np.ndarray, n_local: int,
                  C: int, width: int) -> np.ndarray:
    """[2, width] padded (head, tail) spill data for the gather."""
    offsets = np.concatenate([[0], np.cumsum(counts)])
    O_q = int(offsets[q])
    (h0, h1), (t0, t1) = _spill_ranges(q, counts, n_local, C)
    buf = np.zeros((2, width), np.uint8)
    buf[0, : h1 - h0] = syms[h0 - O_q : h1 - O_q]
    buf[1, : t1 - t0] = syms[t0 - O_q : t1 - O_q]
    return buf


def distributed_chunked(
    path: str,
    chunk_size: int = TRAIN_CHUNK,
    *,
    pad_multiple: int,
    skip_headers: bool = True,
    process_index: int | None = None,
    process_count: int | None = None,
    symbol_cache: str | None = None,
    gather=None,
) -> LocalShard:
    """Build THIS process's block of the global chunk framing of a file,
    with each process encoding only its own ~1/P byte range.

    The file layer of the multi-host input-sharding contract
    (process_shard's row split, extended down so no host parses the whole
    file — the reference's HDFS input splits, CpGIslandFinder.java:108-147):

    1. each process encodes its line-aligned byte range
       (codec.encode_byte_range);
    2. one tiny all-gather of symbol counts fixes every process's global
       symbol offset — and with it the exact shape of every process's
       boundary "spill" (symbols it holds but whose chunk rows belong to a
       neighbor);
    3. one bounded all-gather of those spills lets each process assemble
       exactly its own PAD-framed rows.

    ``pad_multiple``: the mesh data-axis size — global rows pad to it (with
    zero-length rows), matching SpmdBackend.prepare's padding of the
    single-host path bit for bit.  Clean framing only (the remainder row is
    kept, padded).  ``symbol_cache``: per-host byte-range encode cache
    prefix (codec.encode_byte_range_cached) — pod repeat-runs skip the text
    parse.  ``gather`` injects the collective for tests; the default is
    identity for one process and multihost_utils.process_allgather
    otherwise.
    """
    import jax

    p = jax.process_index() if process_index is None else process_index
    P = jax.process_count() if process_count is None else process_count
    if gather is None:
        if P == 1:
            gather = lambda x: np.asarray(x)[None]
        else:
            from jax.experimental import multihost_utils

            gather = lambda x: np.asarray(
                multihost_utils.process_allgather(np.asarray(x))
            )

    from cpgisland_tpu.utils import codec

    syms = codec.encode_byte_range_cached(
        path, p, P, symbol_cache, skip_headers=skip_headers
    )
    counts = gather(np.asarray([syms.size], np.int64)).reshape(-1)
    offsets = np.concatenate([[0], np.cumsum(counts)])
    total = int(offsets[-1])
    if total == 0:
        raise ValueError(f"no symbols in {path}")
    C = chunk_size
    N = -(-total // C)
    global_rows = -(-N // pad_multiple) * pad_multiple
    if global_rows % P:
        raise ValueError(
            f"padded row count {global_rows} not divisible by "
            f"process_count {P}; pad_multiple must be a multiple of it"
        )
    n_local = global_rows // P

    # Bounded spill exchange (shape known to everyone from the counts).
    widths = [
        max(h1 - h0, t1 - t0)
        for q in range(P)
        for (h0, h1), (t0, t1) in [_spill_ranges(q, counts, n_local, C)]
    ]
    width = max(widths)
    spills = (
        gather(_spill_buffer(syms, p, counts, n_local, C, width))
        if width > 0
        else np.zeros((P, 2, 0), np.uint8)
    )

    # Assemble this process's symbol window from its own range + spills.
    lo, hi = _shard_row_range(p, n_local, C, total)
    flat = np.full(n_local * C, PAD_SYMBOL, np.uint8)

    def fill(g0: int, g1: int, data: np.ndarray) -> None:
        a, b = max(g0, lo), min(g1, hi)
        if a < b:
            flat[a - lo : b - lo] = data[a - g0 : b - g0]

    O_p = int(offsets[p])
    fill(O_p, O_p + int(counts[p]), syms)
    for q in range(P):
        if q == p:
            continue
        (h0, h1), (t0, t1) = _spill_ranges(q, counts, n_local, C)
        fill(h0, h1, spills[q, 0, : h1 - h0])
        fill(t0, t1, spills[q, 1, : t1 - t0])

    row_starts = (p * n_local + np.arange(n_local)) * C
    lengths = np.clip(total - row_starts, 0, C).astype(np.int32)
    return LocalShard(
        chunks=flat.reshape(n_local, C),
        lengths=lengths,
        total=int(lengths.sum()),
        global_rows=global_rows,
    )


def process_shard(
    chunked: Chunked,
    process_index: int,
    process_count: int,
) -> Chunked:
    """THIS host's contiguous block of a globally-framed chunk batch.

    The multi-host input-sharding step (SURVEY.md §5 DCN role), mirroring the
    reference's HDFS input splits (CpGIslandFinder.java:108-147): the global
    batch is padded with empty chunks to a process_count multiple and process
    p takes rows [p*n_local, (p+1)*n_local).  Contiguous blocks — not strided
    rows — so the local block lines up with the process's addressable devices
    under a NamedSharding over the data axis (global device order enumerates
    process 0's devices first), which is what
    ``jax.make_array_from_process_local_data`` assumes in SpmdBackend.place.

    ``total`` in the result is the LOCAL real-symbol count (this shard's
    contribution); the union of all shards covers every global chunk exactly
    once.
    """
    if not (0 <= process_index < process_count):
        raise ValueError(f"process_index {process_index} not in [0, {process_count})")
    padded = pad_to_multiple(chunked, process_count)
    n_local = padded.num_chunks // process_count
    lo = process_index * n_local
    chunks = padded.chunks[lo : lo + n_local]
    lengths = padded.lengths[lo : lo + n_local]
    return Chunked(chunks=chunks, lengths=lengths, total=int(lengths.sum()))


def pad_to_multiple(chunked: Chunked, multiple: int) -> Chunked:
    """Pad the batch dim with empty (all-PAD, length-0) chunks to a multiple.

    Needed to shard a chunk batch evenly over a device mesh axis: empty chunks
    contribute zero sufficient statistics, so results are unchanged.
    """
    n = chunked.num_chunks
    target = ((n + multiple - 1) // multiple) * multiple
    if target == n:
        return chunked
    extra = target - n
    pad_chunks = np.full((extra, chunked.chunk_size), PAD_SYMBOL, dtype=np.uint8)
    pad_lengths = np.zeros(extra, dtype=np.int32)
    return Chunked(
        chunks=np.concatenate([chunked.chunks, pad_chunks]),
        lengths=np.concatenate([chunked.lengths, pad_lengths]),
        total=chunked.total,
    )
