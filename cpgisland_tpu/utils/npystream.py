"""Streaming one-pass .npy writer (header patched with the final length).

Genome-scale per-position outputs (posterior confidence, state-path dumps)
are written record by record as they are computed; accumulating them in host
RAM to hand numpy.save one big array would peak at O(genome) twice over
(the list of parts plus the concatenation).  The total length is unknown
until the FASTA stream ends, so the writer reserves a fixed-size header slot
up front, streams raw element bytes, and rewrites the real npy 1.0 header on
close — the result is byte-compatible with numpy.save / numpy.load
(including mmap_mode) for 1-D arrays.
"""

from __future__ import annotations

import struct

import numpy as np

# npy 1.0: magic (6) + version (2) + header-length uint16 (2) + header text.
_SLOT = 128
_MAGIC = b"\x93NUMPY\x01\x00"


class NpyStreamWriter:
    """Append-only 1-D .npy writer; use as a context manager or call close().

    The final header must fit the reserved slot: dtype descr plus up to a
    ~19-digit element count — comfortably within 128 bytes.
    """

    def __init__(self, path: str, dtype):
        self.dtype = np.dtype(dtype)
        self._n = 0
        self._f = open(path, "wb")
        self._f.write(b"\x00" * _SLOT)

    def write(self, arr) -> None:
        arr = np.ascontiguousarray(arr, dtype=self.dtype)
        arr.tofile(self._f)
        self._n += arr.size

    @property
    def count(self) -> int:
        return self._n

    def close(self) -> None:
        if self._f.closed:
            return
        header = (
            "{'descr': %r, 'fortran_order': False, 'shape': (%d,), }"
            % (np.lib.format.dtype_to_descr(self.dtype), self._n)
        ).encode("latin1")
        pad = _SLOT - len(_MAGIC) - 2 - len(header) - 1
        if pad < 0:  # pragma: no cover — needs a >100-char dtype descr
            raise ValueError("npy header slot overflow")
        header += b" " * pad + b"\n"
        self._f.seek(0)
        self._f.write(_MAGIC)
        self._f.write(struct.pack("<H", len(header)))
        self._f.write(header)
        self._f.close()

    def __enter__(self) -> "NpyStreamWriter":
        return self

    def __exit__(self, *exc) -> None:
        self.close()
