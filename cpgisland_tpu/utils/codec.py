"""DNA sequence codec: text -> uint8 symbol arrays.

Reference semantics (CpGIslandFinder.java:112-128 and :238-254): stream characters,
map A/a->0, C/c->1, G/g->2, T/t->3, and silently skip every other character
(newlines, N bases, digits, ...).  Notably the reference does NOT treat FASTA
header lines specially, so the a/c/g/t characters inside a header such as
">chr21 GRCh38 alt" would be encoded as bases.  We keep that behavior behind
``skip_headers=False`` (compat) and fix it with ``skip_headers=True`` (clean).

The implementation is a vectorized 256-entry lookup table over raw bytes rather
than a per-character loop: encoding whole chromosomes is memory-bandwidth bound
and runs at GB/s in NumPy; a streaming variant bounds peak host memory.  When
the native runtime library is available (utils.native, built from
native/codec.cpp), the streaming path uses its fused single-pass
strip-and-encode kernel instead; both paths are parity-tested against each
other (tests/test_native_codec.py).
"""

from __future__ import annotations

import os
from typing import Iterator, Optional, Union

import numpy as np

from cpgisland_tpu.utils import native

# Symbol ids (match the reference's emitted-state map, CpGIslandFinder.java:191-194).
A, C, G, T = 0, 1, 2, 3
N_SYMBOLS = 4
SKIP = 0xFF  # sentinel for "not a base" in the LUT

_LUT = np.full(256, SKIP, dtype=np.uint8)
for _ch, _val in ((b"Aa", A), (b"Cc", C), (b"Gg", G), (b"Tt", T)):
    _LUT[_ch[0]] = _val
    _LUT[_ch[1]] = _val

_BASE_CHARS = np.array([ord("a"), ord("c"), ord("g"), ord("t")], dtype=np.uint8)

# ---------------------------------------------------------------------------
# Invalid-symbol policy (the Hadoop skip-bad-records parity knob).
#
# The reference silently drops EVERY non-base character (its char loop,
# CpGIslandFinder.java:112-128) — that stays the default ("skip"), because
# compat mode owes the reference byte-fidelity and clean mode inherits it
# for backward compatibility.  The explicit policies make the behavior a
# decision instead of an accident:
#   - "skip": drop invalid bytes (reference semantics; Hadoop with
#     skip-bad-records ENABLED);
#   - "mask": encode invalid bytes as the PAD sentinel (N_SYMBOLS) — an
#     identity DP step, so N runs decode through exactly and island
#     coordinates keep matching the original FASTA positions;
#   - "fail": raise InvalidSymbolError on the first invalid byte (Hadoop's
#     DEFAULT — a bad record fails the job unless skipping is opted into).
# Structural whitespace is never "invalid" — line breaks are file format,
# not data.  Counts surface as one ``invalid_symbols`` obs event per file
# whenever a non-default policy is engaged.

INVALID_POLICIES = ("skip", "mask", "fail")
MASK_SYMBOL = N_SYMBOLS  # == the chunking PAD sentinel: an identity DP step

_WS_LUT = np.zeros(256, dtype=bool)
for _b in b" \t\r\n\v\f":
    _WS_LUT[_b] = True


class InvalidSymbolError(ValueError):
    """A byte that is neither a base nor whitespace under ``invalid='fail'``."""

    def __init__(self, count: int, first_byte: int, first_offset: int):
        super().__init__(
            f"{count} invalid symbol byte(s) in the input (first: "
            f"{bytes([first_byte])!r} at buffer offset {first_offset}); "
            "pass invalid='skip' to drop them (the reference's behavior) or "
            "invalid='mask' to encode them as the PAD sentinel"
        )
        self.count = count
        self.first_byte = first_byte
        self.first_offset = first_offset


def _check_policy(invalid: str) -> None:
    if invalid not in INVALID_POLICIES:
        raise ValueError(
            f"invalid-symbol policy must be one of {INVALID_POLICIES}, "
            f"got {invalid!r}"
        )


def _note_invalid(path: str, policy: str, count: int) -> None:
    if count <= 0:
        return
    from cpgisland_tpu import obs

    obs.event("invalid_symbols", path=path, policy=policy, count=int(count))


def encode_bytes(
    data: Union[bytes, bytearray, memoryview, np.ndarray],
    *,
    invalid: str = "skip",
    _count=None,
) -> np.ndarray:
    """Encode raw sequence bytes to a uint8 symbol array.

    ``invalid="skip"`` (default) mirrors the reference's char loop
    (CpGIslandFinder.java:112-128) — every character that is not one of
    ACGTacgt is dropped.  See the invalid-symbol policy block above for
    "mask"/"fail".  ``_count`` (internal): one-element list accumulating
    the invalid-byte count across streamed blocks.
    """
    raw = np.frombuffer(data, dtype=np.uint8) if not isinstance(data, np.ndarray) else data
    if invalid == "skip" and _count is None:
        coded = _LUT[raw]
        return coded[coded != SKIP]
    _check_policy(invalid)
    coded = _LUT[raw]
    is_base = coded != SKIP
    inv = ~is_base & ~_WS_LUT[raw]
    n_inv = int(inv.sum())
    if _count is not None:
        _count[0] += n_inv
    if n_inv and invalid == "fail":
        off = int(np.flatnonzero(inv)[0])
        raise InvalidSymbolError(n_inv, int(raw[off]), off)
    if invalid == "mask":
        keep = is_base | inv
        return np.where(inv, np.uint8(MASK_SYMBOL), coded)[keep]
    return coded[is_base]


def encode(text: Union[str, bytes], *, invalid: str = "skip") -> np.ndarray:
    """Encode a string (or bytes) of sequence text. Non-base characters skipped
    (or masked/failed under an explicit ``invalid`` policy)."""
    if isinstance(text, str):
        text = text.encode("ascii", errors="replace")
    return encode_bytes(text, invalid=invalid)


def strip_fasta_headers(data: bytes) -> bytes:
    """Remove FASTA header lines ('>' at line start, through end-of-line)."""
    return _strip_headers_stateful(data, False, True)[0]


def iter_encoded_blocks(
    path: str,
    *,
    skip_headers: bool = False,
    read_size: int = 1 << 24,
    start: int = 0,
    end: Optional[int] = None,
    invalid: str = "skip",
) -> Iterator[np.ndarray]:
    """Stream-encode a file (or a byte range of it) in bounded-memory blocks.

    ``skip_headers=False`` reproduces the reference exactly (headers encoded as
    bases, CpGIslandFinder.java:112-128); ``True`` is the fixed FASTA-aware mode.
    Header lines may span read boundaries, so a small carry tracks whether we are
    inside a header and whether the next byte starts a line.  Uses the native
    fused kernel when available (identical semantics, parity-tested).

    ``start``/``end`` bound the byte range (the multi-host sharded-encode
    path, :func:`encode_byte_range`); ``start`` MUST be a line start so the
    header state machine begins clean.

    A non-default ``invalid`` policy routes through the NumPy path (the
    native kernel bakes in skip semantics) and emits one
    ``invalid_symbols`` obs event for the file when bytes were affected.
    """
    _check_policy(invalid)
    fasta_enc = (
        native.FastaEncoder() if skip_headers and invalid == "skip" else None
    )
    use_native = (
        fasta_enc.available if fasta_enc is not None
        else (native.available() and invalid == "skip")
    )
    count = [0]
    in_header, at_line_start = False, True
    try:
        with open(path, "rb", buffering=0) as f:
            if start:
                f.seek(start)
            remaining = None if end is None else end - start
            while remaining is None or remaining > 0:
                data = f.read(
                    read_size if remaining is None else min(read_size, remaining)
                )
                if not data:
                    return
                if remaining is not None:
                    remaining -= len(data)
                if use_native:
                    syms = fasta_enc.feed(data) if skip_headers else native.encode(data)
                else:
                    if skip_headers:
                        data, in_header, at_line_start = _strip_headers_stateful(
                            data, in_header, at_line_start
                        )
                    syms = encode_bytes(
                        data, invalid=invalid,
                        _count=count if invalid != "skip" else None,
                    )
                if syms.size:
                    yield syms
    finally:
        if invalid != "skip":
            _note_invalid(path, invalid, count[0])


def _strip_headers_stateful(
    data: bytes, in_header: bool, at_line_start: bool
) -> tuple[bytes, bool, bool]:
    """Strip header spans: a header opens only at a '>' that begins a line.

    Single source of truth for the header rule — both the whole-buffer
    (:func:`strip_fasta_headers`) and streaming (:func:`iter_encoded_blocks`)
    paths use it, so they cannot diverge on inputs like a mid-line '>'.
    """
    out = bytearray()
    i = 0
    n = len(data)
    while i < n:
        if in_header:
            nl = data.find(b"\n", i)
            if nl == -1:
                return bytes(out), True, False
            i = nl + 1
            in_header = False
            at_line_start = True
        else:
            if at_line_start and data[i : i + 1] == b">":
                in_header = True
                continue
            nl = data.find(b"\n", i)
            if nl == -1:
                out += data[i:]
                return bytes(out), False, False
            out += data[i : nl + 1]
            i = nl + 1
            at_line_start = True
    return bytes(out), in_header, at_line_start


# Above this size the parallel whole-buffer native path wins over streaming;
# below it, thread spawn + the extra count pass cost more than they save.
_MT_THRESHOLD = 8 << 20


def encode_file(
    path: str,
    *,
    skip_headers: bool = False,
    threads: int = 0,
    invalid: str = "skip",
) -> np.ndarray:
    """Encode an entire file into one symbol array.

    Large files take the multithreaded native path (native/codec.cpp
    segments API: parallel per-segment count, then write at exact offsets, so
    peak memory is file size + symbol count); small files and library-less
    environments stream through :func:`iter_encoded_blocks`.  A non-default
    ``invalid`` policy (mask/fail — see the policy block above) always
    streams through the NumPy path.
    """
    _check_policy(invalid)
    try:
        size = os.path.getsize(path)
    except OSError:
        size = 0
    if size >= _MT_THRESHOLD and native.available() and invalid == "skip":
        data = np.fromfile(path, dtype=np.uint8)
        out = native.encode_mt(data, fasta=skip_headers, threads=threads)
        if out is not None:
            return out
    blocks = list(
        iter_encoded_blocks(path, skip_headers=skip_headers, invalid=invalid)
    )
    if not blocks:
        return np.zeros(0, dtype=np.uint8)
    return np.concatenate(blocks)


def iter_fasta_records(
    path: str, *, read_size: int = 1 << 24, invalid: str = "skip"
) -> Iterator[tuple[str, np.ndarray]]:
    """Stream (name, symbols) per FASTA record in bounded memory per block.

    The record name is the header token up to the first whitespace (">chr21
    GRCh38 alt" -> "chr21").  Leading sequence before any header yields a
    record named "".  The reference has no notion of records at all — it
    encodes the whole char stream including headers (CpGIslandFinder.java
    :112-128); this iterator powers the clean path's per-chromosome decode so
    islands can never span a chromosome boundary.

    Blocks without a '>' take a bulk-encode fast path (native kernel when
    available), so multi-GiB single-chromosome files stream at codec speed.
    A non-default ``invalid`` policy (mask/fail) routes through the NumPy
    encode and emits one ``invalid_symbols`` obs event for the file.
    """
    _check_policy(invalid)
    name = ""
    bufs: list[np.ndarray] = []
    have_record = False
    in_header = False
    header_frag = b""
    at_line_start = True
    count = [0]

    def _bulk(seg: Union[bytes, memoryview]) -> Optional[np.ndarray]:
        if isinstance(seg, memoryview):
            seg = bytes(seg)
        if invalid != "skip":
            return encode_bytes(seg, invalid=invalid, _count=count)
        out = native.encode(seg)
        return out if out is not None else encode_bytes(seg)

    with open(path, "rb", buffering=0) as f:
        while True:
            data = f.read(read_size)
            if not data:
                break
            if not in_header and b">" not in data:
                syms = _bulk(data)
                if syms.size:
                    bufs.append(syms)
                    have_record = True
                at_line_start = data.endswith(b"\n")
                continue
            i, n = 0, len(data)
            while i < n:
                if in_header:
                    nl = data.find(b"\n", i)
                    if nl == -1:
                        header_frag += data[i:]
                        i = n
                        continue
                    header_frag += data[i:nl]
                    name = header_frag.decode("ascii", "replace").split()[0] if header_frag.strip() else ""
                    header_frag = b""
                    in_header = False
                    at_line_start = True
                    i = nl + 1
                    continue
                if at_line_start and data[i : i + 1] == b">":
                    if have_record:
                        yield name, _concat(bufs)
                        bufs = []
                    have_record = True
                    in_header = True
                    header_frag = b""
                    i += 1
                    continue
                nxt = data.find(b">", i)
                nl_end = n if nxt == -1 else nxt
                # '>' only opens a header at a line start; scan to the last
                # newline before it so a mid-line '>' stays in sequence data.
                if nxt != -1 and data[nxt - 1 : nxt] != b"\n":
                    nl = data.find(b"\n", nxt)
                    nl_end = n if nl == -1 else nl + 1
                syms = _bulk(memoryview(data)[i:nl_end])
                if syms.size:
                    bufs.append(syms)
                    have_record = True
                at_line_start = data[nl_end - 1 : nl_end] == b"\n"
                i = nl_end
    if in_header and header_frag.strip():
        name = header_frag.decode("ascii", "replace").split()[0]
    if have_record:
        yield name, _concat(bufs)
    if invalid != "skip":
        _note_invalid(path, invalid, count[0])


def _concat(bufs: list) -> np.ndarray:
    if not bufs:
        return np.zeros(0, dtype=np.uint8)
    return np.concatenate(bufs)


def _line_boundary(f, pos: int, size: int) -> int:
    """The canonical cut point at-or-after ``pos``: just past the first
    newline at offset >= pos-1 (so a cut already at a line start stays put).

    Both sides of a shared cut evaluate this identically, so byte ranges
    tile the file exactly.  Returns ``size`` when no newline remains.
    """
    if pos <= 0:
        return 0
    if pos >= size:
        return size
    f.seek(pos - 1)
    scan_from = pos - 1
    while True:
        block = f.read(1 << 20)
        if not block:
            return size
        nl = block.find(b"\n")
        if nl != -1:
            return scan_from + nl + 1
        scan_from += len(block)


def encode_byte_range(
    path: str,
    part: int,
    n_parts: int,
    *,
    skip_headers: bool = True,
    read_size: int = 1 << 24,
) -> np.ndarray:
    """Encode only this part's line-aligned byte range of the file.

    The multi-host input-sharding primitive (SURVEY.md §5 DCN role): process
    p encodes ~1/P of the file instead of all of it — the reference gets the
    same effect from HDFS input splits (CpGIslandFinder.java:108-147).
    Ranges cut at line starts, so the FASTA header state machine starts
    clean in every part and the concatenation over parts equals the
    whole-file encode exactly (tested).
    """
    if not 0 <= part < n_parts:
        raise ValueError(f"part {part} not in [0, {n_parts})")
    size = os.path.getsize(path)
    with open(path, "rb", buffering=0) as f:
        lo = _line_boundary(f, part * size // n_parts, size)
        hi = (
            size
            if part == n_parts - 1
            else _line_boundary(f, (part + 1) * size // n_parts, size)
        )
    # One shared streaming-encode loop (iter_encoded_blocks) — the header
    # carry / native dispatch must not fork between whole-file and ranged use.
    return _concat(
        list(
            iter_encoded_blocks(
                path, skip_headers=skip_headers, read_size=read_size,
                start=lo, end=hi,
            )
        )
    )


# ---------------------------------------------------------------------------
# Pre-encoded symbol cache
#
# BASELINE.md measures host encode as the end-to-end bottleneck next to
# multi-chip decode (host_encode_vs_8chip_decode < 0.1): re-runs of decode /
# posterior / training over the same FASTA pay the full text parse every
# time.  The cache stores the encode ONCE — symbols as a streamed .npy
# (memmap-loadable: repeat runs read pages straight from the OS cache, no
# parse, no copy), plus record names/offsets and a source fingerprint.
# Clean-mode (FASTA-aware) semantics only: the compat path exists for
# byte-fidelity with the reference, not throughput.

_CACHE_VERSION = 1


def _source_fingerprint(path: str) -> dict:
    st = os.stat(path)
    return {"size": st.st_size, "mtime_ns": st.st_mtime_ns}


def symbol_cache_paths(cache: str) -> tuple[str, str]:
    """(symbols .npy path, metadata .npz path) for a cache prefix."""
    return cache + ".symbols.npy", cache + ".meta.npz"


def write_symbol_cache(path: str, cache: str) -> int:
    """Encode ``path`` (FASTA-aware) into a symbol cache at prefix ``cache``.

    Returns the total symbol count.  Both sidecars are built under temp
    names and ``os.rename``d into place (symbols first, metadata last): a
    concurrent reader that already validated the cache keeps its open memmap
    of the OLD symbols file (the rename unlinks the name, not the inode),
    and validation can never observe a metadata file whose symbols aren't
    fully in place.  Multi-process jobs sharing a cache prefix on one FS are
    therefore safe without external locking.
    """
    from cpgisland_tpu.utils.npystream import NpyStreamWriter

    sym_p, meta_p = symbol_cache_paths(cache)
    # Temp names keep the real extensions (np.savez appends ".npz" to names
    # without it) and carry the pid so concurrent builders never collide.
    sym_tmp = f"{cache}.tmp.{os.getpid()}.symbols.npy"
    meta_tmp = f"{cache}.tmp.{os.getpid()}.meta.npz"
    # Fingerprint BEFORE the parse: a source replaced mid-encode must leave
    # a cache that validates as STALE (old fingerprint vs new file), never
    # one that matches the new file while holding the old file's symbols.
    fp = _source_fingerprint(path)
    names: list[str] = []
    offsets: list[int] = [0]
    try:
        with NpyStreamWriter(sym_tmp, np.uint8) as w:
            for name, syms in iter_fasta_records(path):
                names.append(name)
                w.write(syms)
                offsets.append(w.count)
            total = w.count
        np.savez(
            meta_tmp,
            version=_CACHE_VERSION,
            names=np.asarray(names, dtype=object),
            offsets=np.asarray(offsets, dtype=np.int64),
            **fp,
        )
        os.rename(sym_tmp, sym_p)
        os.rename(meta_tmp, meta_p)
    finally:
        for p in (sym_tmp, meta_tmp):
            if os.path.exists(p):
                os.unlink(p)
    return total


def open_symbol_cache(path: str, cache: str):
    """(names, offsets, symbols-memmap) if a VALID cache exists, else None.

    Validity = matching cache version and source size/mtime fingerprint —
    an edited FASTA silently invalidates its stale cache.
    """
    sym_p, meta_p = symbol_cache_paths(cache)
    if not (os.path.exists(sym_p) and os.path.exists(meta_p)):
        return None
    try:
        meta = np.load(meta_p, allow_pickle=True)
        fp = _source_fingerprint(path)
        if (
            int(meta["version"]) != _CACHE_VERSION
            or int(meta["size"]) != fp["size"]
            or int(meta["mtime_ns"]) != fp["mtime_ns"]
        ):
            return None
        symbols = np.load(sym_p, mmap_mode="r")
        offsets = np.asarray(meta["offsets"], np.int64)
        if symbols.shape[0] != int(offsets[-1]):
            return None
        return list(meta["names"]), offsets, symbols
    except Exception:
        return None


def encode_file_cached(
    path: str, cache: Optional[str], *, skip_headers: bool,
    invalid: str = "skip",
) -> np.ndarray:
    """encode_file with an optional read-through symbol cache.

    Cache semantics are FASTA-aware (headers stripped), so only
    ``skip_headers=True`` (clean mode) can be served from it; the compat
    encoding falls through to a direct parse.  Caches store skip-encoded
    symbols, so a non-default ``invalid`` policy bypasses them.
    """
    if invalid != "skip":
        _check_policy(invalid)
        return encode_file(path, skip_headers=skip_headers, invalid=invalid)
    if cache is None or not skip_headers:
        return encode_file(path, skip_headers=skip_headers)
    hit = open_symbol_cache(path, cache)
    if hit is None:
        write_symbol_cache(path, cache)
        hit = open_symbol_cache(path, cache)
        if hit is None:  # pragma: no cover — racing writer or unwritable dir
            return encode_file(path, skip_headers=True)
    return hit[2]


def iter_fasta_records_cached(
    path: str, cache: Optional[str] = None, *, invalid: str = "skip"
):
    """iter_fasta_records with an optional read-through symbol cache.

    ``cache`` is a file prefix (e.g. the FASTA path itself): a valid cache
    yields memmap slices (no parse, no copy — the repeat-run fast path); a
    missing/stale one is built first, then served.  ``cache=None`` streams
    the file directly.  Caches store skip-encoded symbols, so a
    non-default ``invalid`` policy bypasses them (logged once).
    """
    if invalid != "skip":
        _check_policy(invalid)
        if cache is not None:
            import logging

            logging.getLogger(__name__).info(
                "symbol cache bypassed: invalid-symbol policy %r differs "
                "from the cache's skip encoding", invalid,
            )
        yield from iter_fasta_records(path, invalid=invalid)
        return
    if cache is None:
        yield from iter_fasta_records(path)
        return
    hit = open_symbol_cache(path, cache)
    if hit is None:
        write_symbol_cache(path, cache)
        hit = open_symbol_cache(path, cache)
        if hit is None:  # pragma: no cover — racing writer or unwritable dir
            yield from iter_fasta_records(path)
            return
    names, offsets, symbols = hit
    for i, name in enumerate(names):
        yield name, symbols[offsets[i] : offsets[i + 1]]


def encode_byte_range_cached(
    path: str,
    part: int,
    n_parts: int,
    cache: Optional[str],
    *,
    skip_headers: bool = True,
) -> np.ndarray:
    """encode_byte_range with an optional per-host read-through cache.

    The multi-host twin of encode_file_cached: each process caches ONLY its
    own byte range (sidecar ``{cache}.range{part}of{n_parts}.npz``), so pod
    repeat-runs skip the text parse without any host ever touching the
    whole file.  Atomic temp+rename write; the cache key includes the
    (part, n_parts) split so a resized pod rebuilds automatically, and the
    source fingerprint invalidates on edit like the whole-file cache.
    """
    if cache is None or not skip_headers:
        return encode_byte_range(path, part, n_parts, skip_headers=skip_headers)
    side = f"{cache}.range{part}of{n_parts}.npz"
    fp = _source_fingerprint(path)
    try:
        got = np.load(side)
        if (
            int(got["version"]) == _CACHE_VERSION
            and int(got["size"]) == fp["size"]
            and int(got["mtime_ns"]) == fp["mtime_ns"]
        ):
            return np.asarray(got["symbols"], np.uint8)
    except Exception:
        pass
    syms = encode_byte_range(path, part, n_parts, skip_headers=True)
    tmp = f"{cache}.tmp.{os.getpid()}.range.npz"
    try:
        np.savez(tmp, version=_CACHE_VERSION, symbols=syms, **fp)
        os.rename(tmp, side)
    except OSError:  # unwritable cache dir: serve the encode anyway
        pass
    finally:
        if os.path.exists(tmp):
            os.unlink(tmp)
    return syms


def decode_symbols(symbols: np.ndarray) -> str:
    """Inverse mapping (0..3 -> 'acgt') for debugging and test fixtures."""
    return _BASE_CHARS[np.asarray(symbols, dtype=np.uint8)].tobytes().decode("ascii")


def recode_pairs(
    symbols: np.ndarray, n_symbols: int = N_SYMBOLS,
    prev: Optional[int] = None,
) -> np.ndarray:
    """Recode a base-alphabet stream to the PAIR (dinucleotide) alphabet.

    ``out[t] = symbols[t-1] * n_symbols + symbols[t]`` — S^2 pair symbols,
    position-aligned with the input so island coordinates and prev-sym
    threading carry over unchanged.  This is the codec-layer half of the
    order-2 family members (family.members.dinuc): the model stays a plain
    first-order HMM, the OBSERVATION carries the left context.

    Positions with no real left context — the stream's first position
    unless ``prev`` supplies the symbol before it (span/stream
    continuation threading, the engines' ``prev_sym`` contract), and any
    real position directly after a PAD/masked input symbol — recode to
    the SELF-CONTEXT pair ``(cur, cur)``.  Self-context keeps the stream
    fully in-alphabet and CHAIN-CONSISTENT (the only property consecutive
    pairs must satisfy is prev-of-next == cur-of-this, which any pair
    ending in ``cur`` provides): pair-chained models like
    ``presets.dinuc_cpg`` carry structural transition zeros between
    non-chaining pairs, and the forward-backward machinery scores
    in-length PAD sentinels as clamped observations (its PAD handling is
    positional/tail-based), so an out-of-alphabet "no context" marker
    would zero the chain outright rather than skip the position.  The
    cost is one fabricated left context per segment opening — position 0
    only, under the default skip-policy encode.  A PAD input symbol
    itself stays PAD (order-2 members reject such streams at encode —
    see family.members.Member.encode).

    uint8 output (n_symbols <= 15; the DNA alphabet's pair space is 16
    symbols + PAD 16).
    """
    if n_symbols * n_symbols >= 255:
        raise ValueError(
            f"pair alphabet {n_symbols}^2 does not fit the uint8 symbol "
            "stream"
        )
    s = np.asarray(symbols)
    pad = np.uint8(n_symbols * n_symbols)
    out = np.full(s.shape, pad, dtype=np.uint8)
    if s.size == 0:
        return out
    cur = s.astype(np.int32)
    prv = np.empty_like(cur)
    prv[1:] = cur[:-1]
    prv[0] = (
        int(prev) if prev is not None and 0 <= int(prev) < n_symbols
        else n_symbols
    )
    real = cur < n_symbols
    # Unknown left context -> self-context (see docstring).
    prv = np.where(real & (prv >= n_symbols), cur, prv)
    out[real] = (prv[real] * n_symbols + cur[real]).astype(np.uint8)
    return out
