"""cpgisland_tpu — a TPU-native CpG-island-finding framework.

A ground-up JAX / XLA / Pallas re-design of the capabilities of the reference
(ErangaD/CpGIsland: a Hadoop-MapReduce Baum-Welch HMM trainer plus a sequential
Viterbi CpG-island caller, /root/reference/CpGIslandFinder.java):

- DNA codec + chunk framing        (reference: CpGIslandFinder.java:112-147, 238-259)
- 8-state CpG HMM model core       (reference: CpGIslandFinder.java:155-173)
- Baum-Welch EM with a mapper/reducer contract whose distributed backend is
  `shard_map` + `psum` over a TPU mesh instead of Hadoop shuffle+reduce
                                   (reference: CpGIslandFinder.java:200-201)
- Viterbi decode as a parallel max-plus scan
                                   (reference: CpGIslandFinder.java:256-260)
- Island calling post-processor    (reference: CpGIslandFinder.java:262-339)
- Model serialization (reference text format + npz checkpoints)
                                   (reference: CpGIslandFinder.java:207-224)
"""

from cpgisland_tpu.utils import compat as _compat

_compat.install()  # jax version shims (jax.shard_map on older 0.4.x)

from cpgisland_tpu.models.hmm import HmmParams
from cpgisland_tpu.models import presets
from cpgisland_tpu.utils import codec, chunking

__version__ = "0.1.0"

__all__ = ["HmmParams", "presets", "codec", "chunking", "__version__"]
