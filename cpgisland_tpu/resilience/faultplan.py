"""graftfault: deterministic, seeded fault-injection plans for the serve fleet.

The reference program inherited chaos-for-free from Hadoop: every cluster
ran task failures daily, so MAHOUT-627's re-execution path was exercised by
production itself.  This stack's failover machinery (fleet quarantine,
flush requeue, the admission journal) would otherwise only ever run when
the relay actually misbehaves — which is exactly when nobody is watching a
test.  graftfault closes that gap: declarative fault PLANS ("the 3rd
supervised dispatch faults past the retry budget", "phantom result on
device dev0", "SIGKILL between the journal admit and the flush") armed
around a workload, with every injection ledgered as a
``graftfault_injected`` obs event so tests can assert the chaos actually
happened.

Injection points are pre-placed in production code and cost ONE module
global read when no plan is armed (the common case — production never pays
for the harness):

- ``dispatch`` / ``dispatch.wall`` — the dispatch supervisor's attempt
  body (``resilience/policy.py``): ``fault`` raises a retry-shaped
  RuntimeError, ``phantom`` raises :class:`~cpgisland_tpu.resilience.
  sentinel.PhantomResult`, ``slow`` pads the measured attempt wall so the
  ``dispatch_slow`` escalation fires without sleeping.
- ``sentinel`` — :meth:`IntegritySentinel.verify` entry.
- ``journal.pre_admit`` / ``journal.post_admit`` / ``flush.enter`` /
  ``journal.pre_complete`` / ``journal.post_complete`` — the serve
  broker's write-ahead journal phase boundaries; ``kill`` raises
  :class:`SimulatedKill` (a BaseException: nothing between the injection
  point and the test harness may catch it, modelling SIGKILL's
  nothing-else-runs semantics — what survives is exactly what was already
  flushed to disk, which is the crash-consistency contract under test).
- ``transport.read`` — the socket mux reader loop; ``disconnect`` raises
  OSError, modelling a connection dying mid-stream.
- ``host.submit`` / ``host.flush`` — the routing tier's host-level
  injection points (``serve/router.py``).  ``host.submit`` sits between
  the router and one host's broker: ``disconnect`` models a transport
  partition (the router records a connection fault and routes around the
  host).  ``host.flush`` sits in the host worker's flush loop:
  ``kill`` models host-granularity SIGKILL (the worker thread dies, the
  router marks the host dead and fails its journaled admissions over to
  a survivor).  Two composites complete the host catalogue without new
  points: a ``flush.enter`` kill with ``match="@<host>"`` (the broker's
  tag carries its ``host_label``) is a host death MID-FLUSH — admits
  journaled, no completions; a ``journal.post_admit`` kill is a host
  dying with an admit journal-visible but never acknowledged to the
  queue.

Determinism: each Fault matches arrivals at its point by a per-plan
ORDINAL counter (``nth``/``times``), optionally filtered by a ``match``
substring of the site tag (tags carry the supervisor/session name, which
for fleet sessions embeds the device label — ``match="@dev0"`` targets one
device, whose supervised dispatches are serialized on its worker thread,
making per-device ordinals fully deterministic).  Across concurrent
workers the global interleaving may vary; plans are written so the
asserted outcome (bit-identity with the fault-free run, zero dropped
admitted requests) is interleaving-invariant.

No jax import, ever — the CLI pulls :mod:`cpgisland_tpu.resilience` in
before platform selection.
"""

from __future__ import annotations

import contextlib
import dataclasses
import logging
import random
import threading
from typing import Optional

from cpgisland_tpu import obs
from cpgisland_tpu.resilience.sentinel import PhantomResult

log = logging.getLogger(__name__)

__all__ = [
    "Fault",
    "FaultPlan",
    "ManualClock",
    "SimulatedKill",
    "active",
    "arm",
    "check",
    "disarm",
    "host_matrix",
    "matrix",
    "wall_pad",
]

KINDS = ("fault", "phantom", "slow", "kill", "disconnect")


class SimulatedKill(BaseException):
    """graftfault's SIGKILL stand-in.  BaseException on purpose: the broad
    ``except Exception`` fault isolation in the serve stack must NOT catch
    it — a real SIGKILL runs no handlers, and the journal tests exist to
    prove that what was flushed to disk alone reconstructs the run."""


@dataclasses.dataclass(frozen=True)
class Fault:
    """One declarative fault: at injection point ``point``, on matching
    arrivals ``nth .. nth+times-1`` (1-based, per plan), perform ``kind``.

    ``match`` filters by substring of the site tag ('' = every arrival at
    the point counts).  ``pad_s`` is the wall padding for ``slow`` faults
    (must exceed the retry policy's ``slow_attempt_s`` to escalate).
    """

    point: str
    kind: str = "fault"
    nth: int = 1
    times: int = 1
    match: str = ""
    pad_s: float = 600.0

    def __post_init__(self):
        if self.kind not in KINDS:
            raise ValueError(f"kind must be one of {KINDS}, got {self.kind!r}")
        if self.nth < 1 or self.times < 1:
            raise ValueError(f"nth/times are 1-based counts ({self})")


class FaultPlan:
    """A set of :class:`Fault` directives plus per-point arrival counters.

    Arm with :func:`arm`/:func:`active`; every performed injection is
    appended to ``self.injected`` (and emitted as a ``graftfault_injected``
    obs event) so a test can assert the chaos it scheduled actually ran.
    """

    def __init__(self, faults, *, name: str = "plan",
                 seed: Optional[int] = None) -> None:
        self.faults = tuple(faults)
        self.name = name
        self.seed = seed
        self.injected: list[dict] = []
        self._arrivals: dict[int, int] = {}
        self._lock = threading.Lock()

    def _consult_locked(self, point: str, tag: str):
        """(action Fault or None, slow pad seconds) for one arrival."""
        pad = 0.0
        action: Optional[Fault] = None
        for i, f in enumerate(self.faults):
            if f.point != point or (f.match and f.match not in tag):
                continue
            n = self._arrivals[i] = self._arrivals.get(i, 0) + 1
            if not (f.nth <= n < f.nth + f.times):
                continue
            rec = {
                "plan": self.name, "point": point, "kind": f.kind,
                "tag": tag, "arrival": n,
            }
            self.injected.append(rec)
            if f.kind == "slow":
                pad += f.pad_s
            elif action is None:
                action = f
        return action, pad

    def check(self, point: str, tag: str) -> None:
        with self._lock:
            action, _pad = self._consult_locked(point, tag)
        if action is None:
            return
        # Ledger OUTSIDE the plan lock (obs has its own locking).
        obs.event(
            "graftfault_injected", plan=self.name, point=point,
            kind=action.kind, tag=tag,
        )
        # graftscope flight recorder: the injection must be attributable in
        # a postmortem.  Lazy import (obs.scope imports obs; keeping the
        # resilience layer import-light at module load) and best-effort —
        # telemetry must never change what the chaos harness injects.
        try:
            from cpgisland_tpu.obs import scope as scope_mod

            scope_mod.record(
                "graftfault_injected", plan=self.name, point=point,
                fault_kind=action.kind, tag=tag,
            )
            if action.kind == "kill":
                # Persist the ring BEFORE raising: a SimulatedKill
                # propagates uncaught by contract, so this is the last
                # instant the postmortem artifact can be written.
                scope_mod.on_kill(point, tag)
        except Exception:
            pass
        log.warning(
            "graftfault[%s]: injecting %s at %s [%s]",
            self.name, action.kind, point, tag,
        )
        if action.kind == "kill":
            raise SimulatedKill(f"graftfault: simulated SIGKILL at {point}")
        if action.kind == "phantom":
            raise PhantomResult(
                f"graftfault: injected phantom result at {point} [{tag}]"
            )
        if action.kind == "disconnect":
            raise OSError(
                f"graftfault: injected connection death at {point} [{tag}]"
            )
        raise RuntimeError(
            f"graftfault: injected device fault at {point} [{tag}]"
        )

    def wall_pad(self, point: str, tag: str) -> float:
        with self._lock:
            _action, pad = self._consult_locked(point, tag)
        if pad > 0.0:
            obs.event(
                "graftfault_injected", plan=self.name, point=point,
                kind="slow", tag=tag, pad_s=pad,
            )
            try:
                from cpgisland_tpu.obs import scope as scope_mod

                scope_mod.record(
                    "graftfault_injected", plan=self.name, point=point,
                    fault_kind="slow", tag=tag, pad_s=pad,
                )
            except Exception:
                pass
        return pad


# The armed plan.  Written under _LOCK; READ unlocked on every supervised
# dispatch (the zero-cost-when-disarmed contract) — registered in
# analysis.config.SYNC_UNGUARDED with the justification.
_LOCK = threading.Lock()
_ACTIVE: Optional[FaultPlan] = None


def arm(plan: FaultPlan) -> FaultPlan:
    """Install ``plan`` as the process-wide active plan."""
    global _ACTIVE
    with _LOCK:
        if _ACTIVE is not None:
            raise RuntimeError(
                f"a graftfault plan ({_ACTIVE.name!r}) is already armed"
            )
        _ACTIVE = plan
    log.info("graftfault: armed plan %r (%d fault(s))", plan.name,
             len(plan.faults))
    return plan


def disarm() -> None:
    global _ACTIVE
    with _LOCK:
        _ACTIVE = None


@contextlib.contextmanager
def active(plan: FaultPlan):
    """``with faultplan.active(plan): <workload>`` — arm around a region."""
    arm(plan)
    try:
        yield plan
    finally:
        disarm()


def check(point: str, tag: str = "") -> None:
    """Production-side injection point: no-op unless a plan is armed and a
    fault matches this arrival (then it raises the mapped exception)."""
    plan = _ACTIVE
    if plan is None:
        return
    plan.check(point, tag)


def wall_pad(point: str, tag: str = "") -> float:
    """Seconds to ADD to a measured wall at this point (``slow`` faults);
    0.0 unless a plan is armed."""
    plan = _ACTIVE
    if plan is None:
        return 0.0
    return plan.wall_pad(point, tag)


class ManualClock:
    """Deterministic ``now_fn`` for breaker/health cooldown tests: time
    advances only when the test says so (no sleeps, no flakes)."""

    def __init__(self, t: float = 0.0) -> None:
        self._lock = threading.Lock()
        self._t = float(t)

    def __call__(self) -> float:
        with self._lock:
            return self._t

    def advance(self, dt: float) -> float:
        with self._lock:
            self._t += float(dt)
            return self._t


def matrix(seed: int, *, attempts: int = 4) -> list:
    """The CI chaos matrix for one seed: dispatch-level plans whose
    ordinals vary with the seed.  ``attempts`` should be the retry
    policy's ``max_retries + 1`` so 'past the budget' plans really exhaust
    it.  Kill/disconnect plans are phase-targeted and parameterized
    directly by the tests (they need a journal/socket around them)."""
    rng = random.Random(seed)
    return [
        FaultPlan(
            [Fault("dispatch", kind="fault", nth=rng.randint(1, 3),
                   times=attempts)],
            name=f"s{seed}-device-fault", seed=seed,
        ),
        FaultPlan(
            [Fault("dispatch", kind="phantom", nth=rng.randint(1, 3),
                   times=attempts)],
            name=f"s{seed}-phantom", seed=seed,
        ),
        FaultPlan(
            [Fault("dispatch", kind="fault", nth=rng.randint(1, 4),
                   times=1)],
            name=f"s{seed}-transient", seed=seed,
        ),
        FaultPlan(
            [Fault("dispatch.wall", kind="slow", nth=rng.randint(1, 2),
                   times=2)],
            name=f"s{seed}-slow", seed=seed,
        ),
    ]


def host_matrix(seed: int, *, hosts=("host0", "host1")) -> list:
    """The host-chaos matrix for one seed: each plan kills/partitions ONE
    host (seed-chosen victim) at a different phase of its life.  The
    asserted outcome is plan-invariant: the surviving host completes
    every journaled admission bit-identically, zero drops, zero double
    executions.  ``journal.post_admit`` has no host in its tag — the
    test kills the victim itself after the submit raises (the plan just
    plants the crash at the phase boundary)."""
    rng = random.Random(seed)
    victim = hosts[rng.randrange(len(hosts))]
    return [
        FaultPlan(
            [Fault("flush.enter", kind="kill", nth=rng.randint(1, 2),
                   match=f"@{victim}")],
            name=f"s{seed}-host-midflush-kill", seed=seed,
        ),
        FaultPlan(
            [Fault("host.flush", kind="kill", nth=rng.randint(1, 3),
                   match=victim)],
            name=f"s{seed}-host-kill", seed=seed,
        ),
        FaultPlan(
            [Fault("host.submit", kind="disconnect", nth=1, times=2,
                   match=victim)],
            name=f"s{seed}-host-partition", seed=seed,
        ),
        FaultPlan(
            [Fault("journal.post_admit", kind="kill",
                   nth=rng.randint(1, 3))],
            name=f"s{seed}-host-admit-unacked", seed=seed,
        ),
    ]
