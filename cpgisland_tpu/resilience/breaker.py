"""Engine degradation ladder: per-engine circuit breaker with cooldown.

The fast engines here are TPU-shaped and have slower but parity-pinned
twins: the reduced one-hot decode/FB kernels fall back to the dense Pallas
kernels, those to the XLA scans, and the device island caller to the host
NumPy caller (PARITY.md pins each pair bit-identical or within documented
rounding).  When a fast engine faults REPEATEDLY — a Mosaic miscompile on a
new driver, a kernel-shaped relay failure — retrying it forever turns every
record into a retry storm.  The breaker instead trips that engine after
``threshold`` consecutive faults: routing (``resolve_engine`` /
``resolve_fb_engine`` / the island-engine policy) then demotes to the next
rung for ``cooldown_s``, results stay exact, and an ``engine_degraded``
obs event records the decision.  After the cooldown one probe is allowed
through (half-open); success restores the engine (``engine_restored``),
another fault re-trips it for a fresh cooldown.

Engines are identified by namespaced keys — ``decode.onehot``,
``fb.pallas``, ``islands.device`` — so a decode-side fault never degrades
the training router.  State is process-global (one hardware reality per
process) behind :func:`get_breaker`; tests install their own via
:func:`set_breaker` or ``resilience.reset()``.
"""

from __future__ import annotations

import dataclasses
import logging
import threading
import time
from typing import Callable, Dict, Optional

from cpgisland_tpu import obs

log = logging.getLogger(__name__)

DEFAULT_THRESHOLD = 3
DEFAULT_COOLDOWN_S = 60.0


@dataclasses.dataclass
class _EngineState:
    consecutive_faults: int = 0
    tripped_at: Optional[float] = None
    half_open: bool = False
    trips: int = 0


class EngineBreaker:
    """Consecutive-fault circuit breaker over namespaced engine keys.

    ``clock`` is injectable (monotonic seconds) so cooldown expiry is
    testable without sleeping; ``now_fn`` is an alias for it (the name the
    fleet health machinery and graftfault's :class:`~cpgisland_tpu.
    resilience.faultplan.ManualClock` use — a given ``now_fn`` wins), so
    one deterministic clock can drive the breaker AND the device health
    cooldowns in lockstep.
    """

    def __init__(
        self,
        *,
        threshold: int = DEFAULT_THRESHOLD,
        cooldown_s: float = DEFAULT_COOLDOWN_S,
        clock: Callable[[], float] = time.monotonic,
        now_fn: Optional[Callable[[], float]] = None,
    ) -> None:
        if threshold < 1:
            raise ValueError(f"threshold must be >= 1, got {threshold}")
        self.threshold = threshold
        self.cooldown_s = cooldown_s
        self.clock = now_fn if now_fn is not None else clock
        self._state: Dict[str, _EngineState] = {}
        # The supervisor may be driven from a deferred thunk while another
        # record dispatches; keep the tiny state transitions atomic.
        self._lock = threading.Lock()

    def _st_locked(self, engine: str) -> _EngineState:
        # _locked suffix: callers hold self._lock (the graftsync convention).
        return self._state.setdefault(engine, _EngineState())

    # -- accounting (fed by the dispatch supervisor) -------------------------

    def record_fault(self, engine: str, error: Optional[BaseException] = None) -> None:
        with self._lock:
            st = self._st_locked(engine)
            st.consecutive_faults += 1
            if st.tripped_at is not None:
                if st.half_open:
                    # The post-cooldown probe failed: re-trip for a fresh
                    # cooldown window.
                    st.tripped_at = self.clock()
                    st.half_open = False
                    st.trips += 1
                    self._emit_degraded(engine, st, error, probe_failed=True)
                return
            if st.consecutive_faults >= self.threshold:
                st.tripped_at = self.clock()
                st.half_open = False
                st.trips += 1
                self._emit_degraded(engine, st, error, probe_failed=False)

    def record_success(self, engine: str) -> None:
        with self._lock:
            st = self._state.get(engine)
            if st is None:
                return
            if st.tripped_at is not None and st.half_open:
                st.tripped_at = None
                st.half_open = False
                st.consecutive_faults = 0
                obs.event("engine_restored", engine=engine, trips=st.trips)
                log.info(
                    "engine %r restored after cooldown probe succeeded", engine
                )
                return
            st.consecutive_faults = 0

    def _emit_degraded(
        self, engine: str, st: _EngineState, error, probe_failed: bool
    ) -> None:
        obs.event(
            "engine_degraded",
            engine=engine,
            faults=st.consecutive_faults,
            cooldown_s=self.cooldown_s,
            probe_failed=probe_failed,
            error=(f"{type(error).__name__}: {error}"[:200] if error else None),
        )
        log.warning(
            "engine %r degraded after %d consecutive fault(s)%s; routing "
            "falls back to its parity twin for %.0f s (results stay exact "
            "— the twins are parity-pinned)",
            engine, st.consecutive_faults,
            " (cooldown probe failed)" if probe_failed else "",
            self.cooldown_s,
        )

    # -- routing -------------------------------------------------------------

    def allowed(self, engine: str) -> bool:
        """May routing pick this engine now?  After the cooldown elapses the
        first call flips the breaker half-open and admits ONE probe (whose
        success/fault then restores or re-trips)."""
        with self._lock:
            st = self._state.get(engine)
            if st is None or st.tripped_at is None:
                return True
            if st.half_open:
                return True
            if self.clock() - st.tripped_at >= self.cooldown_s:
                st.half_open = True
                return True
            return False

    def tripped(self, engine: str) -> bool:
        """Currently tripped AND still cooling down (no probe admitted)."""
        return not self.allowed(engine)

    def degrade(
        self, site: str, engine: str, ladder: Callable[[str], Optional[str]]
    ) -> str:
        """Walk ``engine`` down its parity-twin ladder past tripped rungs.

        ``ladder(engine)`` returns the next rung or None at the bottom (the
        last rung always runs — an exact-if-slow answer beats none).  Every
        demotion step emits a deduped ``engine_decision`` routing event.
        """
        cur = engine
        while not self.allowed(f"{site}.{cur}"):
            nxt = ladder(cur)
            if nxt is None:
                break
            obs.engine_decision(
                site=f"{site}.breaker_demotion", choice=nxt, requested=cur
            )
            log.warning(
                "%s engine %r is tripped (cooldown); demoting to parity "
                "twin %r", site, cur, nxt,
            )
            cur = nxt
        return cur


def kernel_ladder(pallas_eligible: bool) -> Callable[[str], Optional[str]]:
    """THE parity-twin ladder shared by the decode/FB/EM routers:
    onehot -> pallas (when the dense kernels are eligible for this
    model/backend) -> xla -> None.  One copy so a future rung change cannot
    diverge per site; each router supplies its own eligibility predicate
    (viterbi_pallas.supports vs fb_pallas.supports, on-TPU)."""

    def twin(engine: str) -> Optional[str]:
        if engine == "onehot":
            return "pallas" if pallas_eligible else "xla"
        if engine == "pallas":
            return "xla"
        return None

    return twin


_BREAKER: Optional[EngineBreaker] = None


def get_breaker() -> EngineBreaker:
    global _BREAKER
    if _BREAKER is None:
        _BREAKER = EngineBreaker()
    return _BREAKER


def set_breaker(breaker: Optional[EngineBreaker]) -> None:
    """Install a process-global breaker (tests: inject a fake clock)."""
    global _BREAKER
    _BREAKER = breaker
