"""Resumable serving pipelines: per-record JSONL manifests.

Training has checkpoints (``utils/checkpoint.py``); until now a killed
decode of a 3 Gbase assembly restarted from symbol zero.  The manifest is
the serving-side analogue: ``decode_file``/``posterior_file`` append one
JSON line per COMPLETED record (its island calls serialized exactly, plus
the per-record confidence contribution on the posterior path), flushed as
each record lands.  A resumed run (``--resume``) validates the header
(source fingerprint, model digest, output-affecting config), skips every
completed record — reconstructing its calls from the manifest instead of
recomputing — and produces byte-identical final output, because:

- integers round-trip through JSON exactly;
- the gc/oe floats are serialized as ``float.hex()`` (bit-exact f64
  round-trip — ``%f`` re-formatting of a reconstructed value can therefore
  never differ from the original run's);
- records are the calling granularity (clean semantics call islands per
  record), so skipping whole records cannot move any call.

Crash tolerance: lines are appended + flushed per record, and the loader
ignores a truncated final line — a kill mid-write costs at most the record
being written.  A header that does not match the current run (edited
source, different model, different ``min_len``/island states) discards the
manifest with a warning and starts fresh: silently resuming across a
semantic change would be corruption, recomputing is merely slower.

Per-symbol streams (``state_path_out``, ``confidence_out``,
``mpm_path_out``) are NOT resumable — the pipeline rejects manifests for
runs that request them.

Two-phase admission journal (r15, the serve daemon's write-ahead log):
completion-only records replay finished work, but a daemon killed
MID-FLUSH used to silently drop every request it had ACCEPTED and not yet
completed — the client got an ack, the work evaporated.
:meth:`RunManifest.record_admitted` writes an ``admit`` line (with the
request payload) BEFORE a request becomes visible to any flush consumer;
:meth:`RunManifest.record_done` is the matching completion.  On resume,
:meth:`admitted_incomplete` returns every admitted-but-incomplete entry so
the serve broker can re-execute them (``journal_replay``), while completed
entries keep replaying bit-identically with zero device work.  Loaders
older than this phase ignore ``admit`` lines (they only read
``kind == "record"``), so the file format is forward-compatible both ways.

Thread contract: the fleet's device workers append completions
concurrently; every mutator runs under ``RunManifest._lock`` (a leaf —
nothing else is ever acquired under it).
"""

from __future__ import annotations

import hashlib
import json
import logging
import os
import threading
from typing import Optional

import numpy as np

from cpgisland_tpu import obs

log = logging.getLogger(__name__)

MANIFEST_VERSION = 1


def params_digest(params) -> str:
    """Stable content digest of a model's tables (f64-normalized)."""
    h = hashlib.sha256()
    for leaf in (params.log_pi, params.log_A, params.log_B):
        h.update(np.asarray(leaf, dtype=np.float64).tobytes())
    return h.hexdigest()


def source_fingerprint(path: str) -> dict:
    st = os.stat(path)
    return {"size": st.st_size, "mtime_ns": st.st_mtime_ns}


def calls_to_wire(calls) -> Optional[dict]:
    """IslandCalls -> JSON-safe dict with bit-exact float round-trip."""
    if calls is None:
        return None
    return {
        "beg": np.asarray(calls.beg).tolist(),
        "end": np.asarray(calls.end).tolist(),
        "length": np.asarray(calls.length).tolist(),
        "gc": [float(v).hex() for v in np.asarray(calls.gc_content)],
        "oe": [float(v).hex() for v in np.asarray(calls.oe_ratio)],
        "names": (
            None if calls.names is None else [str(n) for n in calls.names]
        ),
    }


def calls_from_wire(wire: Optional[dict]):
    """Inverse of :func:`calls_to_wire`; None stays None (a record that
    contributed no IslandCalls entry)."""
    if wire is None:
        return None
    from cpgisland_tpu.ops.islands import IslandCalls

    return IslandCalls(
        beg=np.asarray(wire["beg"], np.int64),
        end=np.asarray(wire["end"], np.int64),
        length=np.asarray(wire["length"], np.int64),
        gc_content=np.asarray([float.fromhex(v) for v in wire["gc"]], np.float64),
        oe_ratio=np.asarray([float.fromhex(v) for v in wire["oe"]], np.float64),
        names=(
            None if wire["names"] is None
            else np.asarray(wire["names"], dtype=object)
        ),
    )


def _fold_journal_lines(lines: list, completed: dict, admitted: dict,
                        *, path: str = "") -> int:
    """Replay journal lines (header excluded) into the (completed,
    admitted) maps — the ONE copy of the line-kind state machine, shared
    by the live resume loader and the read-only :meth:`RunManifest.
    scan_incomplete` scan so the two views of a journal can never drift.
    ``admit`` entries keep their FULL record (payload included — both
    callers read from disk, where payloads persist).  Tolerates a
    truncated/unparseable tail (kill mid-append): folding stops there.
    Returns the byte length of the intact prefix consumed."""
    valid = 0
    for ln in lines:
        if not ln.endswith("\n"):
            # Killed mid-append: everything before this line is intact,
            # which is the resume contract (the partial tail — even a
            # complete JSON object missing only its newline — is
            # dropped and recomputed).
            log.warning(
                "manifest %s: discarding a truncated trailing line "
                "(killed mid-append)", path,
            )
            break
        try:
            rec = json.loads(ln)
        except json.JSONDecodeError:
            log.warning(
                "manifest %s: discarding an unparseable trailing line "
                "(killed mid-append)", path,
            )
            break
        valid += len(ln.encode("utf-8"))
        if rec.get("kind") == "record":
            completed[int(rec["index"])] = rec
            # Resolved: the admit payload need not stay resident.
            admitted.pop(int(rec["index"]), None)
        elif rec.get("kind") == "admit":
            if int(rec["index"]) in completed:
                # An admit AFTER a completion means the id was reused
                # for a NEW request (the broker discards a completion
                # only on identity mismatch before re-admitting) — the
                # old record must not shadow the newer admit, or the
                # reused request silently vanishes from restart
                # re-execution.
                completed.pop(int(rec["index"]))
            admitted[int(rec["index"])] = rec
        elif rec.get("kind") == "fail":
            # Terminal failure: the admit is RESOLVED (delivered as an
            # error) — not replayable, not re-executed on restart, and
            # the id is free for a fresh admit.
            admitted.pop(int(rec["index"]), None)
    return valid


class RunManifest:
    """Append-only per-record completion log for one serving run.

    ``header`` must contain every field that affects the output bytes
    (mode, source path + fingerprint, model digest, min_len, island states,
    invalid-symbol policy); a resumed run whose header differs starts
    fresh.  Use as a context manager or ``close()`` in a ``finally``.
    """

    def __init__(self, path: str, *, header: dict, resume: bool) -> None:
        self.path = path
        self.header = {"kind": "run", "version": MANIFEST_VERSION, **header}
        self._completed: dict[int, dict] = {}
        self._admitted: dict[int, dict] = {}  # admit lines (two-phase journal)
        self._valid_bytes = 0  # prefix of intact newline-terminated lines
        self.skipped = 0  # records served from the manifest this run
        # Leaf lock: fleet workers journal completions concurrently.
        self._lock = threading.Lock()
        loaded = bool(resume) and self._load()
        d = os.path.dirname(os.path.abspath(path))
        os.makedirs(d, exist_ok=True)
        if loaded:
            # Reconcile a truncated tail BEFORE appending: a kill mid-write
            # leaves a partial final line, and appending straight after it
            # would merge two lines into garbage that breaks the NEXT
            # resume's parse (losing every record after it).
            try:
                if os.path.getsize(path) != self._valid_bytes:
                    with open(path, "rb+") as f:
                        f.truncate(self._valid_bytes)
            except OSError:
                loaded = False
                self._completed.clear()
                self._admitted.clear()
        self._f = open(path, "a" if loaded else "w", encoding="utf-8")
        if not loaded:
            with self._lock:
                self._append_locked(self.header)
        else:
            obs.event(
                "manifest_resume", path=path,
                records_completed=len(self._completed),
            )
            log.info(
                "resuming from manifest %s: %d record(s) already complete",
                path, len(self._completed),
            )

    # -- load ----------------------------------------------------------------

    def _load(self) -> bool:
        """Parse an existing manifest; False = absent/mismatched (start
        fresh).  Tolerates a truncated final line (kill mid-append): the
        intact newline-terminated prefix is kept (``_valid_bytes``, which
        __init__ truncates to before appending — appending straight after a
        partial line would merge two lines into garbage and break the NEXT
        resume's parse)."""
        try:
            with open(self.path, encoding="utf-8") as f:
                lines = f.read().splitlines(True)
        except OSError:
            return False
        if not lines or not lines[0].endswith("\n"):
            return False  # missing or truncated header: start fresh
        try:
            head = json.loads(lines[0])
        except json.JSONDecodeError:
            log.warning("manifest %s: unreadable header; starting fresh", self.path)
            return False
        if head != self.header:
            diff = {
                k for k in set(head) | set(self.header)
                if head.get(k) != self.header.get(k)
            }
            log.warning(
                "manifest %s does not match this run (differs in %s); "
                "starting fresh — resuming across a semantic change would "
                "corrupt the output", self.path, sorted(diff),
            )
            return False
        self._load_lines(lines)
        return True

    def _load_lines(self, lines: list) -> None:
        # Construction-time only, but the maps are lock-guarded state
        # everywhere else — hold the lock here too (uncontended).
        with self._lock:
            self._valid_bytes = len(lines[0].encode("utf-8"))
            self._load_lines_locked(lines[1:])

    def _load_lines_locked(self, lines: list) -> None:
        self._valid_bytes += _fold_journal_lines(
            lines, self._completed, self._admitted, path=self.path
        )

    @classmethod
    def scan_incomplete(cls, path: str) -> list:
        """Read-only journal scan: admit records (WITH their re-execution
        payloads) lacking a completion, in index order.  This is the
        cross-host failover's view of a DEAD host's journal: the live
        object's :meth:`admitted_incomplete` holds payload-free stubs
        (nothing in-life reads payloads), so a surviving host adopting a
        dead peer's admissions must come back to DISK, where
        :meth:`record_admitted` persisted the full payload (flushed per
        line).  No header validation (there is no run to validate
        against — the adopter checks each record's key itself) and no
        file mutation; an absent/unreadable journal scans as empty."""
        try:
            with open(path, encoding="utf-8") as f:
                lines = f.read().splitlines(True)
        except OSError:
            return []
        if not lines or not lines[0].endswith("\n"):
            return []
        completed: dict = {}
        admitted: dict = {}
        _fold_journal_lines(lines[1:], completed, admitted, path=path)
        return [
            rec for idx, rec in sorted(admitted.items())
            if idx not in completed
        ]

    # -- progress ------------------------------------------------------------

    def completed(self, index: int, name: str, n_symbols: int,
                  *, discard_mismatch: bool = True) -> Optional[dict]:
        """The completion record for this (index, name, size) — or None if
        it must be (re)computed.  Identity mismatches (same index, different
        record) discard the stale entry loudly — unless
        ``discard_mismatch=False`` (the serve broker's in-life duplicate
        probe: a colliding id from ANOTHER client must not destroy the
        legitimate owner's replay entry)."""
        with self._lock:
            rec = self._completed.get(index)
            if rec is None:
                return None
            if rec.get("name") != name or int(rec.get("n_symbols", -1)) != n_symbols:
                if discard_mismatch:
                    log.warning(
                        "manifest %s: record %d is %r (%d symbols) on disk "
                        "but %r (%d symbols) in the input; recomputing it",
                        self.path, index, rec.get("name"),
                        rec.get("n_symbols"), name, n_symbols,
                    )
                    del self._completed[index]
                return None
            self.skipped += 1
            return rec

    def record_admitted(
        self,
        index: int,
        name: str,
        n_symbols: int,
        *,
        payload: Optional[dict] = None,
    ) -> None:
        """Phase 1 of the two-phase journal: journal an ACCEPTED request
        BEFORE it becomes visible to any flush consumer (write-ahead
        ordering — the caller must hold the request back until this
        returns).  ``payload`` must carry everything needed to re-execute
        the request after a crash (the serve broker journals tenant / kind
        / name / model + the encoded symbols).  Idempotent per index: a
        resumed run's re-queue of a journaled request does not re-admit."""
        with self._lock:
            if index in self._completed or index in self._admitted:
                return
            rec = {
                "kind": "admit",
                "index": int(index),
                "name": name,
                "n_symbols": int(n_symbols),
                "payload": payload,
            }
            # In-memory: a payload-FREE stub.  Nothing reads payloads
            # in-life (only the resume loader consumes them, from disk),
            # and keeping them resident would cost ~1.33x every queued
            # request's symbol bytes in dead base64.
            self._admitted[index] = {k: v for k, v in rec.items()
                                     if k != "payload"}
            self._append_locked(rec)

    def has_completion(self, index: int, name: str, n_symbols: int) -> bool:
        """Side-effect-free peek: does a matching completion exist?  (No
        ``skipped`` count, no mismatch discard — the broker's pre-lock
        check for skipping the journal-payload encode on replay-bound
        re-submissions.)"""
        with self._lock:
            rec = self._completed.get(index)
            return (
                rec is not None
                and rec.get("name") == name
                and int(rec.get("n_symbols", -1)) == n_symbols
            )

    def record_failed(self, index: int) -> None:
        """Terminal resolution of an admit whose request FAILED (the error
        was delivered to the client): the entry leaves the re-execution
        set — a nightly-restarted daemon must not re-run its historical
        bad requests — and the id becomes admittable again, so a client
        retrying the id (or reusing it for a new record) gets a FRESH
        write-ahead admit line with the new payload."""
        with self._lock:
            if index in self._admitted:
                self._admitted.pop(index)
                self._append_locked({"kind": "fail", "index": int(index)})

    def n_completed(self) -> int:
        with self._lock:
            return len(self._completed)

    def admitted_incomplete(self) -> list:
        """Admit records with no matching completion, in index order — the
        restart re-execution set (phase 2 never happened for these)."""
        with self._lock:
            return [
                rec for idx, rec in sorted(self._admitted.items())
                if idx not in self._completed
            ]

    def record_done(
        self,
        index: int,
        name: str,
        n_symbols: int,
        *,
        calls=None,
        conf_sum: Optional[float] = None,
        n_spans: int = 1,
    ) -> None:
        """Mark one record complete (idempotent for resumed entries)."""
        with self._lock:
            if index in self._completed:
                return
            rec = {
                "kind": "record",
                "index": int(index),
                "name": name,
                "n_symbols": int(n_symbols),
                "n_spans": int(n_spans),
                "calls": calls_to_wire(calls),
                "conf_sum": None if conf_sum is None else float(conf_sum).hex(),
            }
            self._completed[index] = rec
            # The admit entry (and its base64 payload — ~1.33x the symbol
            # bytes) is resolved: drop it, or a long-lived daemon retains
            # every request's input in memory forever.
            self._admitted.pop(index, None)
            self._append_locked(rec)

    def span_done(self, index: int, span: int) -> None:
        """Progress line for one span of a multi-span record (diagnostics
        for killed runs; resume granularity stays the record)."""
        with self._lock:
            self._append_locked(
                {"kind": "span", "index": int(index), "span": int(span)}
            )

    def _append_locked(self, rec: dict) -> None:
        # _locked suffix: callers hold self._lock (the graftsync convention).
        self._f.write(json.dumps(rec) + "\n")
        # Flush per line: a crash loses at most the line being written (the
        # loader drops a truncated tail).  No fsync — per-record durability
        # against OS crash is not worth a sync() per scaffold on network
        # filesystems; a lost page just recomputes those records.
        self._f.flush()

    # -- lifecycle -----------------------------------------------------------

    def close(self) -> None:
        # No lock: lifecycle belongs to the owning thread (the broker's
        # close path, after every flush consumer has stopped); file close
        # is idempotent.
        if not self._f.closed:
            self._f.close()

    def __enter__(self) -> "RunManifest":
        return self

    def __exit__(self, *exc) -> None:
        self.close()
