"""Resumable serving pipelines: per-record JSONL manifests.

Training has checkpoints (``utils/checkpoint.py``); until now a killed
decode of a 3 Gbase assembly restarted from symbol zero.  The manifest is
the serving-side analogue: ``decode_file``/``posterior_file`` append one
JSON line per COMPLETED record (its island calls serialized exactly, plus
the per-record confidence contribution on the posterior path), flushed as
each record lands.  A resumed run (``--resume``) validates the header
(source fingerprint, model digest, output-affecting config), skips every
completed record — reconstructing its calls from the manifest instead of
recomputing — and produces byte-identical final output, because:

- integers round-trip through JSON exactly;
- the gc/oe floats are serialized as ``float.hex()`` (bit-exact f64
  round-trip — ``%f`` re-formatting of a reconstructed value can therefore
  never differ from the original run's);
- records are the calling granularity (clean semantics call islands per
  record), so skipping whole records cannot move any call.

Crash tolerance: lines are appended + flushed per record, and the loader
ignores a truncated final line — a kill mid-write costs at most the record
being written.  A header that does not match the current run (edited
source, different model, different ``min_len``/island states) discards the
manifest with a warning and starts fresh: silently resuming across a
semantic change would be corruption, recomputing is merely slower.

Per-symbol streams (``state_path_out``, ``confidence_out``,
``mpm_path_out``) are NOT resumable — the pipeline rejects manifests for
runs that request them.
"""

from __future__ import annotations

import hashlib
import json
import logging
import os
from typing import Optional

import numpy as np

from cpgisland_tpu import obs

log = logging.getLogger(__name__)

MANIFEST_VERSION = 1


def params_digest(params) -> str:
    """Stable content digest of a model's tables (f64-normalized)."""
    h = hashlib.sha256()
    for leaf in (params.log_pi, params.log_A, params.log_B):
        h.update(np.asarray(leaf, dtype=np.float64).tobytes())
    return h.hexdigest()


def source_fingerprint(path: str) -> dict:
    st = os.stat(path)
    return {"size": st.st_size, "mtime_ns": st.st_mtime_ns}


def calls_to_wire(calls) -> Optional[dict]:
    """IslandCalls -> JSON-safe dict with bit-exact float round-trip."""
    if calls is None:
        return None
    return {
        "beg": np.asarray(calls.beg).tolist(),
        "end": np.asarray(calls.end).tolist(),
        "length": np.asarray(calls.length).tolist(),
        "gc": [float(v).hex() for v in np.asarray(calls.gc_content)],
        "oe": [float(v).hex() for v in np.asarray(calls.oe_ratio)],
        "names": (
            None if calls.names is None else [str(n) for n in calls.names]
        ),
    }


def calls_from_wire(wire: Optional[dict]):
    """Inverse of :func:`calls_to_wire`; None stays None (a record that
    contributed no IslandCalls entry)."""
    if wire is None:
        return None
    from cpgisland_tpu.ops.islands import IslandCalls

    return IslandCalls(
        beg=np.asarray(wire["beg"], np.int64),
        end=np.asarray(wire["end"], np.int64),
        length=np.asarray(wire["length"], np.int64),
        gc_content=np.asarray([float.fromhex(v) for v in wire["gc"]], np.float64),
        oe_ratio=np.asarray([float.fromhex(v) for v in wire["oe"]], np.float64),
        names=(
            None if wire["names"] is None
            else np.asarray(wire["names"], dtype=object)
        ),
    )


class RunManifest:
    """Append-only per-record completion log for one serving run.

    ``header`` must contain every field that affects the output bytes
    (mode, source path + fingerprint, model digest, min_len, island states,
    invalid-symbol policy); a resumed run whose header differs starts
    fresh.  Use as a context manager or ``close()`` in a ``finally``.
    """

    def __init__(self, path: str, *, header: dict, resume: bool) -> None:
        self.path = path
        self.header = {"kind": "run", "version": MANIFEST_VERSION, **header}
        self._completed: dict[int, dict] = {}
        self._valid_bytes = 0  # prefix of intact newline-terminated lines
        self.skipped = 0  # records served from the manifest this run
        loaded = bool(resume) and self._load()
        d = os.path.dirname(os.path.abspath(path))
        os.makedirs(d, exist_ok=True)
        if loaded:
            # Reconcile a truncated tail BEFORE appending: a kill mid-write
            # leaves a partial final line, and appending straight after it
            # would merge two lines into garbage that breaks the NEXT
            # resume's parse (losing every record after it).
            try:
                if os.path.getsize(path) != self._valid_bytes:
                    with open(path, "rb+") as f:
                        f.truncate(self._valid_bytes)
            except OSError:
                loaded = False
                self._completed.clear()
        self._f = open(path, "a" if loaded else "w", encoding="utf-8")
        if not loaded:
            self._append(self.header)
        else:
            obs.event(
                "manifest_resume", path=path,
                records_completed=len(self._completed),
            )
            log.info(
                "resuming from manifest %s: %d record(s) already complete",
                path, len(self._completed),
            )

    # -- load ----------------------------------------------------------------

    def _load(self) -> bool:
        """Parse an existing manifest; False = absent/mismatched (start
        fresh).  Tolerates a truncated final line (kill mid-append): the
        intact newline-terminated prefix is kept (``_valid_bytes``, which
        __init__ truncates to before appending — appending straight after a
        partial line would merge two lines into garbage and break the NEXT
        resume's parse)."""
        try:
            with open(self.path, encoding="utf-8") as f:
                lines = f.read().splitlines(True)
        except OSError:
            return False
        if not lines or not lines[0].endswith("\n"):
            return False  # missing or truncated header: start fresh
        try:
            head = json.loads(lines[0])
        except json.JSONDecodeError:
            log.warning("manifest %s: unreadable header; starting fresh", self.path)
            return False
        if head != self.header:
            diff = {
                k for k in set(head) | set(self.header)
                if head.get(k) != self.header.get(k)
            }
            log.warning(
                "manifest %s does not match this run (differs in %s); "
                "starting fresh — resuming across a semantic change would "
                "corrupt the output", self.path, sorted(diff),
            )
            return False
        self._valid_bytes = len(lines[0].encode("utf-8"))
        for ln in lines[1:]:
            if not ln.endswith("\n"):
                # Killed mid-append: everything before this line is intact,
                # which is the resume contract (the partial tail — even a
                # complete JSON object missing only its newline — is
                # dropped and recomputed).
                log.warning(
                    "manifest %s: discarding a truncated trailing line "
                    "(killed mid-append)", self.path,
                )
                break
            try:
                rec = json.loads(ln)
            except json.JSONDecodeError:
                log.warning(
                    "manifest %s: discarding an unparseable trailing line "
                    "(killed mid-append)", self.path,
                )
                break
            self._valid_bytes += len(ln.encode("utf-8"))
            if rec.get("kind") == "record":
                self._completed[int(rec["index"])] = rec
        return True

    # -- progress ------------------------------------------------------------

    def completed(self, index: int, name: str, n_symbols: int) -> Optional[dict]:
        """The completion record for this (index, name, size) — or None if
        it must be (re)computed.  Identity mismatches (same index, different
        record) discard the stale entry loudly."""
        rec = self._completed.get(index)
        if rec is None:
            return None
        if rec.get("name") != name or int(rec.get("n_symbols", -1)) != n_symbols:
            log.warning(
                "manifest %s: record %d is %r (%d symbols) on disk but %r "
                "(%d symbols) in the input; recomputing it",
                self.path, index, rec.get("name"), rec.get("n_symbols"),
                name, n_symbols,
            )
            del self._completed[index]
            return None
        self.skipped += 1
        return rec

    def record_done(
        self,
        index: int,
        name: str,
        n_symbols: int,
        *,
        calls=None,
        conf_sum: Optional[float] = None,
        n_spans: int = 1,
    ) -> None:
        """Mark one record complete (idempotent for resumed entries)."""
        if index in self._completed:
            return
        rec = {
            "kind": "record",
            "index": int(index),
            "name": name,
            "n_symbols": int(n_symbols),
            "n_spans": int(n_spans),
            "calls": calls_to_wire(calls),
            "conf_sum": None if conf_sum is None else float(conf_sum).hex(),
        }
        self._completed[index] = rec
        self._append(rec)

    def span_done(self, index: int, span: int) -> None:
        """Progress line for one span of a multi-span record (diagnostics
        for killed runs; resume granularity stays the record)."""
        self._append({"kind": "span", "index": int(index), "span": int(span)})

    def _append(self, rec: dict) -> None:
        self._f.write(json.dumps(rec) + "\n")
        # Flush per line: a crash loses at most the line being written (the
        # loader drops a truncated tail).  No fsync — per-record durability
        # against OS crash is not worth a sync() per scaffold on network
        # filesystems; a lost page just recomputes those records.
        self._f.flush()

    # -- lifecycle -----------------------------------------------------------

    def close(self) -> None:
        if not self._f.closed:
            self._f.close()

    def __enter__(self) -> "RunManifest":
        return self

    def __exit__(self, *exc) -> None:
        self.close()
