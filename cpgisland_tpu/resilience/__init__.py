"""Resilient serving layer: supervision, integrity, degradation, resume.

The reference delegated ALL fault tolerance to Hadoop — task retry,
speculative re-execution, and skip-bad-records came for free from MapReduce
(SURVEY.md; the Mahout ``BaumWelchDriver`` behind CpGIslandFinder.java).
This TPU stack replaced that substrate, and the training loop rebuilt its
own recovery (``train/elastic.py`` micro-batch retry, ``utils/checkpoint.py``,
``fit``'s fused->host fault fallback) — but the SERVING paths
(``pipeline.decode_file`` / ``posterior_file``, span streaming, deferred
island-call fetches) ran bare against this hardware's documented failure
modes (CLAUDE.md): phantom ~0 ms relay results, transient ~20x slowdowns,
wedged tunnel claims, remote-compile rejections.  This package is the
serving-side counterpart, four subsystems:

- :mod:`~cpgisland_tpu.resilience.policy` — the **dispatch supervisor**:
  bounded retries with exponential backoff + jitter around every blocking
  fetch on the file-serving paths, obs-ledger events per attempt.  No
  attempt is ever killed mid-execution (the never-kill rule, CLAUDE.md) —
  "timeout" here is advisory telemetry (``dispatch_slow``), never a SIGKILL.
- :mod:`~cpgisland_tpu.resilience.sentinel` — the **result-integrity
  sentinel**: bench.py's phantom-result defenses (canary fetch of a small
  derived output with a distinct per-dispatch seed fold, plausibility
  ceilings) generalized into an opt-in production guard
  (``--integrity-check``) that detects phantom/stale device results and has
  the supervisor re-dispatch.
- :mod:`~cpgisland_tpu.resilience.breaker` — the **engine degradation
  ladder**: a per-engine circuit breaker; repeated faults in a
  reduced/pallas engine trip a cooldown fallback to its parity twin
  (onehot -> pallas -> xla, device island caller -> host caller), emitting
  ``engine_degraded``/``engine_restored`` events.  Results stay exact:
  the twins are already parity-pinned (PARITY.md).
- :mod:`~cpgisland_tpu.resilience.manifest` — **resumable pipelines**: a
  per-record JSONL manifest written by ``decode_file``/``posterior_file``
  (``--resume``) so a killed or faulted run skips completed records and
  produces byte-identical final output — the serving-side analogue of
  training checkpoints.  For the serve daemon it is additionally a
  **two-phase admission journal** (admitted -> completed): a daemon killed
  mid-flush replays completed requests bit-identically AND re-executes
  admitted-but-incomplete ones on restart, so no accepted request is ever
  silently dropped.
- :mod:`~cpgisland_tpu.resilience.faultplan` — **graftfault**: a
  deterministic, seeded fault-injection harness (declarative plans armed
  around a workload; injection points pre-placed in the supervisor,
  sentinel, journal phase boundaries, and the transport reader) so every
  failover path above is exercised by CI on the virtual mesh instead of
  only by a misbehaving relay in production.

No jax import at module level (the CLI imports this before platform
selection); device work is only touched lazily inside supervised thunks.
"""

from __future__ import annotations

from cpgisland_tpu.resilience.breaker import (  # noqa: F401
    EngineBreaker,
    get_breaker,
    set_breaker,
)
from cpgisland_tpu.resilience.faultplan import (  # noqa: F401
    Fault,
    FaultPlan,
    ManualClock,
    SimulatedKill,
)
from cpgisland_tpu.resilience.manifest import RunManifest  # noqa: F401
from cpgisland_tpu.resilience.policy import (  # noqa: F401
    DispatchSupervisor,
    RetryPolicy,
    default_supervisor,
    supervise,
)
from cpgisland_tpu.resilience.sentinel import (  # noqa: F401
    IntegritySentinel,
    PhantomResult,
)


def reset() -> None:
    """Reset process-global resilience state (tests): the default
    supervisor, the global engine breaker, and any armed graftfault plan."""
    from cpgisland_tpu.resilience import breaker as breaker_mod
    from cpgisland_tpu.resilience import faultplan as faultplan_mod
    from cpgisland_tpu.resilience import policy as policy_mod

    policy_mod._DEFAULT = None
    breaker_mod._BREAKER = None
    faultplan_mod.disarm()
