"""Dispatch supervision: bounded retries around blocking serving fetches.

Every blocking dispatch on this setup crosses a relay that has been observed
failing in fault shapes a production serving path must survive (CLAUDE.md):
transient XlaRuntimeErrors (preemption, interconnect, remote-compile
rejections), transient ~20x slowdowns, and phantom ~0 ms results.  The
reference inherited retry + speculative re-execution from Hadoop's task
runner; :class:`DispatchSupervisor` is that role here, scoped to ONE
supervised unit = "(re)dispatch the device work and block on its fetch" —
jit dispatch is pure, so re-running a unit is always safe.

Rules of engagement:

- **Never kill mid-execution.**  The relay wedges its tunnel claim if a JAX
  process dies mid-TPU-execution (CLAUDE.md), so the supervisor NEVER
  enforces a hard timeout on an attempt.  Attempts that exceed
  ``slow_attempt_s`` are reported (``dispatch_slow`` event — the transient
  ~20x-slowdown telemetry) but always allowed to finish.
- **Fault-shaped errors only.**  ``RuntimeError`` (covers jaxlib's
  XlaRuntimeError: OOM, preemption, interconnect — the same set
  ``train.baum_welch.fit`` recovers from) and ``TimeoutError`` retry;
  programming errors (ValueError/TypeError, incl. IslandCapOverflow, which
  has its own dedicated retry) pass straight through, as does the obs
  recompile sentinel's assertion error.
- **Every attempt is ledgered.**  A ``dispatch_fault`` obs event per failed
  attempt (what/engine/attempt/error/will_retry), so no retry is invisible
  to the metrics stream; faults and successes also feed the engine breaker
  (:mod:`~cpgisland_tpu.resilience.breaker`) when the unit names its engine.
- **Recompute fallback.**  Deferred-fetch units (the overlapped pipeline's
  dispatch-now/fetch-later split) may hold poisoned device buffers whose
  fetch can never succeed; ``run(..., fallback=...)`` switches attempts
  after the first failure to a caller-provided serial recompute closure
  that re-derives the result from host inputs.
"""

from __future__ import annotations

import dataclasses
import logging
import random
import time
from typing import Callable, Optional

from cpgisland_tpu import obs
from cpgisland_tpu.obs.ledger import RecompileError
from cpgisland_tpu.resilience import faultplan

log = logging.getLogger(__name__)


@dataclasses.dataclass(frozen=True)
class RetryPolicy:
    """Bounded-retry policy for one supervised dispatch unit.

    Defaults are sized for the relay's observed fault profile: transient
    faults clear within seconds, so 3 retries spanning ~0.2-3.2 s of
    backoff recover them, while a persistent fault surfaces in < 5 s
    instead of hanging a multi-hour genome run.
    """

    max_retries: int = 3
    backoff_base_s: float = 0.2
    backoff_factor: float = 4.0
    backoff_max_s: float = 30.0
    # Fraction of each delay randomized (+/-): herds of retrying workers
    # must not re-slam a recovering relay in lockstep.
    jitter: float = 0.25
    # Advisory only (never-kill rule): attempts past this wall emit a
    # dispatch_slow event but always run to completion.
    slow_attempt_s: float = 300.0
    retryable: tuple = (RuntimeError, TimeoutError)
    # RecompileError is an assertion about a region, not a device fault —
    # re-running the region would just compile again.
    nonretryable: tuple = (RecompileError,)

    def delay_s(self, attempt: int, rng: random.Random) -> float:
        """Backoff before retry ``attempt`` (1-based), jittered."""
        base = min(
            self.backoff_base_s * self.backoff_factor ** (attempt - 1),
            self.backoff_max_s,
        )
        if base <= 0.0:
            return 0.0
        return base * (1.0 + self.jitter * (2.0 * rng.random() - 1.0))


class DispatchSupervisor:
    """Retry wrapper for blocking serving-path dispatch units.

    One instance per pipeline call (decode_file/posterior_file build their
    own, optionally with an :class:`IntegritySentinel` attached); the
    module-level :func:`default_supervisor` serves library entry points
    invoked directly.  Thread-safe for the pipeline's single-consumer use
    (the prefetch producer never dispatches).
    """

    def __init__(
        self,
        policy: Optional[RetryPolicy] = None,
        *,
        name: str = "serve",
        sentinel=None,
        breaker=None,
        monitor=None,
    ) -> None:
        from cpgisland_tpu.resilience import breaker as breaker_mod

        self.policy = policy if policy is not None else RetryPolicy()
        self.name = name
        self.sentinel = sentinel
        self.breaker = breaker if breaker is not None else breaker_mod.get_breaker()
        # Optional health listener (the fleet's per-device state machine):
        # record_fault(error) / record_slow(wall_s) / record_success() are
        # called alongside the breaker accounting, so the device-level view
        # sees exactly the signals the engine-level view does.
        self.monitor = monitor
        self.retries = 0  # total retries performed (tests / telemetry)
        # Deterministic per-supervisor jitter stream: reproducible runs,
        # still decorrelated across workers (seeded by object identity).
        self._rng = random.Random(id(self) & 0xFFFFFFFF)

    # graftcheck: hot-path
    def run(
        self,
        thunk: Callable[[], object],
        *,
        what: str,
        engine: Optional[str] = None,
        items: float = 0.0,
        fallback: Optional[Callable[[], object]] = None,
    ):
        """Execute ``thunk`` (dispatch + blocking fetch) under the policy.

        ``what`` labels the unit in obs events; ``engine`` (e.g.
        ``"decode.onehot"``, ``"islands.device"``) additionally feeds the
        engine breaker's fault/success accounting.  ``items`` (symbols)
        lets the sentinel apply its throughput plausibility ceiling.
        ``fallback``, when given, replaces the thunk from the second
        attempt on (see module docstring).  The thunk's own host syncs must
        route through ``obs.note_fetch`` like any hot-path fetch — the
        supervisor adds no sync of its own.
        """
        pol = self.policy
        tag = f"{self.name}:{what}"
        attempt = 0
        while True:
            fn = thunk if attempt == 0 or fallback is None else fallback
            t0 = time.perf_counter()
            try:
                # graftfault injection point: an injected fault/phantom is
                # raised HERE, inside the try, so it flows through the real
                # retry/breaker/monitor machinery like a relay fault would.
                faultplan.check("dispatch", tag=tag)
                out = fn()
                # graftfault "slow" plans pad the measured wall so the
                # dispatch_slow escalation fires without sleeping.
                dt = (time.perf_counter() - t0
                      + faultplan.wall_pad("dispatch.wall", tag=tag))
                if self.sentinel is not None:
                    # Raises PhantomResult (retryable) on a stale/phantom
                    # or implausibly fast result.
                    self.sentinel.verify(out, what=what, items=items, seconds=dt)
                if self.breaker is not None and engine is not None:
                    self.breaker.record_success(engine)
                if dt > pol.slow_attempt_s:
                    obs.event(
                        "dispatch_slow", what=what, engine=engine,
                        attempt=attempt, wall_s=round(dt, 3),
                    )
                    log.warning(
                        "%s: dispatch unit %r took %.1f s (slow-attempt "
                        "threshold %.0f s) — transient relay slowdown?",
                        self.name, what, dt, pol.slow_attempt_s,
                    )
                    # record_slow IS the slow dispatch's success
                    # notification (not success-then-slow): the monitor
                    # counts CONSECUTIVE slow dispatches, which a
                    # record_success here would reset.
                    if self.monitor is not None:
                        self.monitor.record_slow(dt)
                elif self.monitor is not None:
                    self.monitor.record_success()
                return out
            except pol.nonretryable:
                raise
            except pol.retryable as e:
                dt = time.perf_counter() - t0
                if self.breaker is not None and engine is not None:
                    self.breaker.record_fault(engine, error=e)
                if self.monitor is not None:
                    self.monitor.record_fault(e)
                attempt += 1
                will_retry = attempt <= pol.max_retries
                obs.event(
                    "dispatch_fault",
                    what=what,
                    engine=engine,
                    attempt=attempt,
                    wall_s=round(dt, 3),
                    error=f"{type(e).__name__}: {e}"[:200],
                    will_retry=will_retry,
                    recovery="recompute" if fallback is not None else "redispatch",
                )
                if not will_retry:
                    log.error(
                        "%s: dispatch unit %r failed %d times; giving up: %s",
                        self.name, what, attempt, e,
                    )
                    raise
                self.retries += 1
                delay = pol.delay_s(attempt, self._rng)
                log.warning(
                    "%s: dispatch unit %r failed (attempt %d/%d): %s — "
                    "%s in %.2f s",
                    self.name, what, attempt, pol.max_retries + 1, e,
                    "recomputing serially" if fallback is not None
                    else "re-dispatching", delay,
                )
                if delay > 0.0:
                    time.sleep(delay)


_DEFAULT: Optional[DispatchSupervisor] = None


def default_supervisor() -> DispatchSupervisor:
    """The process-wide supervisor used when a library entry point is
    called without one (pipeline calls construct their own so per-run
    sentinels/policies apply)."""
    global _DEFAULT
    if _DEFAULT is None:
        _DEFAULT = DispatchSupervisor(name="default")
    return _DEFAULT


def supervise(thunk: Callable[[], object], **kwargs):
    """``default_supervisor().run(thunk, **kwargs)`` — convenience form."""
    return default_supervisor().run(thunk, **kwargs)
