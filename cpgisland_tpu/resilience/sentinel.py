"""Result-integrity sentinel: bench.py's phantom defenses as a serving guard.

The degraded relay has served PHANTOM results — ``block_until_ready``
returning in ~0 ms without execution, even for fresh programs with distinct
inputs (CLAUDE.md r4).  bench.py defends its measurements with a layered
discipline (every timing rep fetches a small output, folds a distinct seed
into its input, and plausibility ceilings raise on absurd rates) — but
until now those defenses lived ONLY in the benchmark, while a production
decode could silently emit islands from a path that never computed.

:class:`IntegritySentinel` generalizes the same three defenses into an
opt-in per-dispatch guard (``--integrity-check``) the dispatch supervisor
invokes after every supervised unit:

- **Canary fetch with a distinct seed fold** — a tiny FRESH program per
  dispatch, data-dependent on the unit's result, whose expected output the
  host computes independently (``seed * 2 + 1``).  A phantom/stale reply
  cannot reproduce the fresh seed's fold, so the mismatch is deterministic;
  a NaN-poisoned result poisons the canary and is caught the same way.
- **Plausibility ceilings** — the unit's sym/s checked against
  :mod:`cpgisland_tpu.obs.watchdog`'s per-path ceilings (2.5x the enforced
  BASELINE.md figures, scaled by device count) and the global net.
- **Re-dispatch on detection** — a violation raises :class:`PhantomResult`
  (fault-shaped), so the supervisor re-dispatches the unit under its normal
  bounded-retry policy instead of publishing a fantasy result.

Cost when enabled: one scalar-shaped canary dispatch + fetch per supervised
unit (a relay round trip) — which is exactly why it is opt-in rather than
always on.  Off by default, zero dispatches added.
"""

from __future__ import annotations

import itertools
import logging
from typing import Optional

import numpy as np

from cpgisland_tpu import obs

log = logging.getLogger(__name__)


class PhantomResult(RuntimeError):
    """A supervised dispatch returned a result that failed integrity checks
    (stale/phantom relay reply or implausible throughput).  Fault-shaped on
    purpose: the supervisor's retry policy re-dispatches it."""


# what-prefix -> watchdog path (BASELINE.md marker family) for the
# throughput ceiling; prefixes without a marker get only the global net.
_WHAT_PATH = {"decode": "decode", "posterior": "posterior"}

_canary_seed = itertools.count(1)
_CANARY_JIT = None


def _canary_fn():
    global _CANARY_JIT
    if _CANARY_JIT is None:
        import jax
        import jax.numpy as jnp

        def _impl(probe, seed):
            p32 = probe.astype(jnp.float32)
            # Data dependence on the supervised unit's result: a phantom
            # dispatch cannot reproduce the fresh seed fold, and a
            # NaN-poisoned result poisons the canary itself.
            return jnp.where(jnp.isnan(p32), p32, seed * 2.0 + 1.0)

        _CANARY_JIT = jax.jit(_impl)
    return _CANARY_JIT


def _probe_scalar(out):
    """A 0-d element of the first non-empty array leaf of ``out`` (device
    arrays index lazily — the canary program is the one that blocks), or
    None when the result holds no checkable array."""
    import jax

    for leaf in jax.tree_util.tree_leaves(out):
        shape = getattr(leaf, "shape", None)
        if shape is None or getattr(leaf, "size", 0) == 0:
            continue
        dt = getattr(leaf, "dtype", None)
        if dt is None or dt.kind not in "fiub":
            continue
        if not getattr(leaf, "is_fully_addressable", True):
            # Multi-host global arrays: indexing would need a collective;
            # the addressable paths cover the canary's purpose.
            continue
        return leaf[(0,) * len(shape)]
    return None


class IntegritySentinel:
    """Per-dispatch phantom/stale-result detector (see module docstring).

    ``canary=False`` keeps only the throughput ceilings (no extra dispatch);
    ``factor`` is the per-path ceiling multiplier over the BASELINE.md
    figures (bench parity: 2.5).
    """

    def __init__(
        self, *, canary: bool = True, factor: Optional[float] = None
    ) -> None:
        from cpgisland_tpu.obs.watchdog import DEFAULT_CEILING_FACTOR, Watchdog

        self.canary = canary
        # mode="warn": the watchdog logs + records; the SENTINEL owns the
        # raise (as PhantomResult, so the supervisor re-dispatches).
        self.watchdog = Watchdog(
            mode="warn",
            factor=factor if factor is not None else DEFAULT_CEILING_FACTOR,
        )
        self.checks = 0
        self.violations: list[dict] = []

    # The indirection exists for tests: patching _canary_value simulates a
    # stale relay reply without needing a degraded relay.
    def _canary_value(self, probe, seed: int) -> float:
        import jax.numpy as jnp

        return float(
            obs.note_fetch(np.asarray(_canary_fn()(probe, jnp.float32(seed))))
        )

    def verify(self, out, *, what: str, items: float = 0.0, seconds: float = 0.0) -> None:
        """Check one supervised unit's result; raises :class:`PhantomResult`
        on violation, returns None otherwise."""
        self.checks += 1
        # graftfault injection point: a planted "phantom" here models the
        # relay serving a stale result that the canary catches.
        from cpgisland_tpu.resilience import faultplan

        faultplan.check("sentinel", tag=what)
        path = _WHAT_PATH.get(what.split(".", 1)[0])
        rec = self.watchdog.check(what, items, seconds, path=path)
        if rec is not None:
            self._violation(
                what,
                kind="implausible_throughput",
                detail=(
                    f"{rec['msym_per_s']} Msym/s exceeds the "
                    f"{rec['ceiling_msym_per_s']} Msym/s ceiling"
                ),
            )
        if not self.canary:
            return
        probe = _probe_scalar(out)
        if probe is None:
            return
        seed = next(_canary_seed) % (1 << 20)
        got = self._canary_value(probe, seed)
        want = float(seed * 2 + 1)
        if got != want:
            self._violation(
                what,
                kind="canary_mismatch",
                detail=(
                    f"canary expected {want}, got {got} — stale/phantom "
                    "device result (the fresh seed fold did not execute)"
                    if got == got else
                    f"canary returned NaN — the unit's result is poisoned"
                ),
            )

    def _violation(self, what: str, *, kind: str, detail: str) -> None:
        rec = {"what": what, "kind": kind, "detail": detail}
        self.violations.append(rec)
        obs.event("integrity_violation", **rec)
        log.warning(
            "integrity sentinel: %s in %r: %s — re-dispatching", kind, what,
            detail,
        )
        raise PhantomResult(f"{kind} in {what!r}: {detail}")
