from cpgisland_tpu.cli import main

raise SystemExit(main())
