"""High-level driver: file in -> trained model / island calls out.

This is the application layer of the reference (``trainModel`` and ``testModel``,
CpGIslandFinder.java:102-225 and :227-344) rebuilt over the TPU stack:

- :func:`train_file`  — encode + shard + Baum-Welch EM + reference text dump.
- :func:`decode_file` — encode + chunk + batched Viterbi + island calling,
  writing the reference's ``beg end len gc oe`` record lines.

``compat=True`` reproduces the reference end to end: headers encoded as bases,
remainder chunks dropped, 1 MiB decode chunks processed independently (islands
clipped at chunk boundaries and reset, CpGIslandFinder.java:256,262-268), the
stale-atC quirk.  ``compat=False`` is the clean path: FASTA-aware, no dropped
symbols, islands called over the stitched global path so chunk boundaries don't
clip them, optional min-length filter.
"""

from __future__ import annotations

import logging
from dataclasses import dataclass
from typing import IO, Optional, Union

import jax.numpy as jnp
import numpy as np

from cpgisland_tpu.models import presets
from cpgisland_tpu.models.hmm import HmmParams, dump_text
from cpgisland_tpu.ops import islands as islands_mod
from cpgisland_tpu.ops.islands import IslandCalls
from cpgisland_tpu.ops.viterbi import viterbi_batch
from cpgisland_tpu.train import baum_welch
from cpgisland_tpu.train.backends import EStepBackend
from cpgisland_tpu.utils import chunking, codec

log = logging.getLogger(__name__)


def train_file(
    training_path: str,
    *,
    params: Optional[HmmParams] = None,
    num_iters: int = 10,
    convergence: float = 0.005,
    backend: Union[EStepBackend, str] = "local",
    mode: str = "log",
    compat: bool = True,
    chunk_size: int = chunking.TRAIN_CHUNK,
    checkpoint_dir: Optional[str] = None,
    model_out: Optional[str] = None,
) -> baum_welch.FitResult:
    """Train the CpG HMM on a sequence file (reference ``trainModel``)."""
    if params is None:
        params = presets.durbin_cpg8()
    symbols = codec.encode_file(training_path, skip_headers=not compat)
    log.info("training input: %d symbols", symbols.size)
    chunked = chunking.frame(symbols, chunk_size, drop_remainder=compat)
    result = baum_welch.fit(
        params,
        chunked,
        num_iters=num_iters,
        convergence=convergence,
        backend=backend,
        mode=mode,
        checkpoint_dir=checkpoint_dir,
    )
    if model_out is not None:
        dump_text(result.params, model_out)
    return result


@dataclass
class DecodeResult:
    calls: IslandCalls
    n_symbols: int
    n_chunks: int


def decode_file(
    test_path: str,
    params: HmmParams,
    *,
    islands_out: Optional[Union[str, IO[str]]] = None,
    state_path_out: Optional[str] = None,
    compat: bool = True,
    chunk_size: int = chunking.DECODE_CHUNK,
    device_batch: int = 8,
    min_len: Optional[int] = None,
) -> DecodeResult:
    """Viterbi-decode a sequence file and call CpG islands (reference
    ``testModel``).

    compat mode decodes each chunk independently and resets the island caller
    per chunk (the reference's boundary-clipping behavior); clean mode stitches
    chunk paths into one global path before island calling.  (Until the
    sequence-parallel decoder, chunk boundaries still restart the DP itself in
    both modes; clean mode removes the island-call clipping.)
    """
    symbols = codec.encode_file(test_path, skip_headers=not compat)
    chunked = chunking.frame(symbols, chunk_size, drop_remainder=compat)
    chunks, lengths = chunked.chunks, chunked.lengths
    n = chunked.num_chunks

    parts: list[IslandCalls] = []
    paths_np: list[np.ndarray] = []
    for lo in range(0, n, device_batch):
        hi = min(lo + device_batch, n)
        batch_paths = viterbi_batch(
            params,
            jnp.asarray(chunks[lo:hi]),
            jnp.asarray(lengths[lo:hi]),
            return_score=False,
        )
        batch_paths = np.asarray(batch_paths)
        for i in range(hi - lo):
            L = int(lengths[lo + i])
            path = batch_paths[i][:L]
            if compat:
                parts.append(
                    islands_mod.call_islands(
                        path, chunk=lo + i, chunk_size=chunk_size, compat=True
                    )
                )
            else:
                paths_np.append(path)

    if compat:
        calls = IslandCalls.concatenate(parts)
    else:
        full = np.concatenate(paths_np) if paths_np else np.zeros(0, dtype=np.int32)
        calls = islands_mod.call_islands(full, chunk=0, compat=False, min_len=min_len)
        if state_path_out is not None:
            np.save(state_path_out, full.astype(np.int8))

    if islands_out is not None:
        own = isinstance(islands_out, str)
        f = open(islands_out, "w") if own else islands_out
        try:
            f.write(calls.format_lines())
        finally:
            if own:
                f.close()
    return DecodeResult(calls=calls, n_symbols=int(chunked.total), n_chunks=n)


def run(
    training_path: str,
    test_path: str,
    islands_out: str,
    model_out: str,
    convergence: float = 0.005,
    num_iters: int = 10,
    *,
    params: Optional[HmmParams] = None,
    backend: Union[EStepBackend, str] = "local",
    mode: str = "log",
    compat: bool = True,
    checkpoint_dir: Optional[str] = None,
    min_len: Optional[int] = None,
) -> DecodeResult:
    """The reference's full main(): train, dump model, decode, write islands
    (CpGIslandFinder.java:346-357)."""
    fit = train_file(
        training_path,
        params=params,
        num_iters=num_iters,
        convergence=convergence,
        model_out=model_out,
        backend=backend,
        mode=mode,
        compat=compat,
        checkpoint_dir=checkpoint_dir,
    )
    return decode_file(
        test_path,
        fit.params,
        islands_out=islands_out,
        compat=compat,
        min_len=min_len,
    )
