"""High-level driver: file in -> trained model / island calls out.

This is the application layer of the reference (``trainModel`` and ``testModel``,
CpGIslandFinder.java:102-225 and :227-344) rebuilt over the TPU stack:

- :func:`train_file`  — encode + shard + Baum-Welch EM + reference text dump.
- :func:`decode_file` — encode + chunk + batched Viterbi + island calling,
  writing the reference's ``beg end len gc oe`` record lines.

``compat=True`` reproduces the reference end to end: headers encoded as bases,
remainder chunks dropped, 1 MiB decode chunks processed independently (islands
clipped at chunk boundaries and reset, CpGIslandFinder.java:256,262-268), the
stale-atC quirk.  ``compat=False`` is the clean path: FASTA-aware, no dropped
symbols, per-record (chromosome) exact decode so neither 1 MiB chunk
boundaries nor record boundaries clip or merge islands, optional min-length
filter, record-name column when the file has multiple records.
"""

from __future__ import annotations

import dataclasses
import functools
import logging
import os
from dataclasses import dataclass
from typing import IO, Optional, Union

import jax
import jax.numpy as jnp
import numpy as np

from cpgisland_tpu import obs
from cpgisland_tpu import resilience
from cpgisland_tpu.models import presets
from cpgisland_tpu.models.hmm import HmmParams, dump_text
from cpgisland_tpu.ops import islands as islands_mod
from cpgisland_tpu.ops.islands import IslandCalls
from cpgisland_tpu.parallel.decode import (
    viterbi_sharded,
    viterbi_sharded_spans,
)
from cpgisland_tpu.train import baum_welch
from cpgisland_tpu.train.backends import EStepBackend
from cpgisland_tpu.utils import chunking, codec
from cpgisland_tpu.utils import profiling

log = logging.getLogger(__name__)


def _spmd_data_axis_size(backend) -> Optional[int]:
    """Data-axis size of an spmd-capable backend — the ``pad_multiple`` a
    byte-range LocalShard must be built with — or None when the backend
    cannot accept per-process LocalShard input (then multi-host train_file
    keeps the whole-file parse)."""
    from cpgisland_tpu.train.backends import SpmdBackend

    if isinstance(backend, SpmdBackend):
        return backend.mesh.shape[backend.axis]
    if backend == "spmd":
        return jax.device_count()  # get_backend('spmd') meshes all devices
    return None


def train_file(
    training_path: str,
    *,
    params: Optional[HmmParams] = None,
    num_iters: int = 10,
    convergence: float = 0.005,
    backend: Union[EStepBackend, str] = "local",
    mode: str = "rescaled",
    engine: str = "auto",
    compat: bool = True,
    chunk_size: int = chunking.TRAIN_CHUNK,
    checkpoint_dir: Optional[str] = None,
    model_out: Optional[str] = None,
    symbol_cache: Optional[str] = None,
    metrics: Optional[profiling.MetricsLogger] = None,
    fuse: Union[bool, str] = "auto",
    invalid_symbols: str = "skip",
) -> baum_welch.FitResult:
    """Train the CpG HMM on a sequence file (reference ``trainModel``).

    ``invalid_symbols``: the codec's skip/mask/fail policy for non-base,
    non-whitespace bytes (clean mode; 'skip' = reference semantics; counts
    surface as ``invalid_symbols`` obs events under mask/fail).

    ``fuse``: EM loop execution (see :func:`baum_welch.fit`) — "auto" runs
    every iteration inside one compiled program with the convergence test
    on device (one blocking round trip per training run) and falls back to
    the reference's host-loop cadence when checkpointing is requested.

    ``backend="seq2d"`` trains on whole FASTA records (one sequence per
    chromosome, EXACT statistics — no 64 Ki chunk-independence approximation)
    distributed over an automatic 2-D data x seq mesh; it requires
    ``compat=False`` since compat mode has no notion of records.  All other
    backends see the reference's chunk framing.

    ``symbol_cache``: pre-encoded symbol cache prefix (utils.codec) — repeat
    runs over the same FASTA skip the host text parse entirely (clean mode
    only; the measured end-to-end bottleneck, BASELINE.md).

    Multi-host (``jax.process_count() > 1``, after
    parallel.mesh.initialize_multihost): with an spmd backend in clean
    mode, the input is built by BYTE-RANGE SHARDED encoding
    (chunking.distributed_chunked) — each host parses only its ~1/P of the
    file and assembles only its own chunk rows, the equivalent of the
    reference's HDFS input splits (CpGIslandFinder.java:108-147).  No host
    ever holds the global batch, and ``symbol_cache`` caches per-host byte
    ranges.  Other backends (and compat mode, whose drop-remainder framing
    is host-global by definition) keep the whole-file parse.
    """
    if params is None:
        params = presets.durbin_cpg8()
    if symbol_cache is not None and compat:
        raise ValueError("symbol_cache is FASTA-aware — use compat=False (--clean)")
    _check_invalid_symbols(invalid_symbols, compat)
    with obs.span("encode", unit="sym") as _enc_span:
        chunked = _train_input(
            training_path, params, backend, compat, chunk_size, symbol_cache,
            invalid_symbols,
        )
        if _enc_span is not None:
            _enc_span.items = float(chunked.total)
    result = baum_welch.fit(
        params,
        chunked,
        num_iters=num_iters,
        convergence=convergence,
        backend=backend,
        mode=mode,
        engine=engine,
        checkpoint_dir=checkpoint_dir,
        metrics=metrics,
        fuse=fuse,
    )
    if model_out is not None:
        dump_text(result.params, model_out)
    return result


def _train_input(
    training_path: str,
    params: HmmParams,
    backend,
    compat: bool,
    chunk_size: int,
    symbol_cache: Optional[str],
    invalid_symbols: str = "skip",
):
    """Build train_file's chunked input (encode + frame/bucket/shard) —
    a Chunked, Bucketed, or LocalShard depending on backend/topology."""
    if backend == "seq2d":
        if compat:
            raise ValueError(
                "backend 'seq2d' trains per FASTA record; compat mode has no "
                "records — use compat=False (--clean)"
            )
        # Stream records into power-of-two length buckets: host peak is
        # bounded by the bucket budget (~2x the raw input overall), not the
        # O(records x max_len) dense matrix a global pad would cost (~113 GB
        # for a GRCh38 assembly).  Each bucket group later gets its own
        # dp x sp mesh split (Seq2DBackend.prepare).
        try:
            chunked = chunking.bucket_records(
                (
                    s
                    for _, s in codec.iter_fasta_records_cached(
                        training_path, symbol_cache, invalid=invalid_symbols
                    )
                ),
                pad_value=params.n_symbols,
            )
        except ValueError:
            raise ValueError(f"no sequence records in {training_path}")
        log.info(
            "training input: %d records in %d size groups, %d symbols",
            chunked.num_chunks, chunked.num_groups, chunked.total,
        )
        # The string flows through to fit() -> get_backend('seq2d'), which
        # validates mode/engine and builds the auto 2-D meshes at prepare().
    elif _spmd_data_axis_size(backend) is not None and not compat and (
        jax.process_count() > 1
    ):
        if invalid_symbols != "skip":
            raise ValueError(
                "invalid_symbols mask|fail is not supported on the "
                "byte-range sharded (multi-process spmd) encode path yet — "
                "use the default 'skip' policy there"
            )
        # Pod job: byte-range sharded encode — this host parses only its
        # ~1/P of the file and assembles only its own rows (see docstring).
        chunked = chunking.distributed_chunked(
            training_path, chunk_size,
            pad_multiple=_spmd_data_axis_size(backend),
            symbol_cache=symbol_cache,
        )
        log.info(
            "training input (byte-range sharded): process %d/%d assembled "
            "%d of %d global rows (%d local symbols)",
            jax.process_index(), jax.process_count(),
            chunked.num_chunks, chunked.global_rows, chunked.total,
        )
    else:
        symbols = codec.encode_file_cached(
            training_path, symbol_cache, skip_headers=not compat,
            invalid=invalid_symbols,
        )
        log.info("training input: %d symbols", symbols.size)
        chunked = chunking.frame(symbols, chunk_size, drop_remainder=compat)
    return chunked


def island_layout_error(params: HmmParams, island_states=None) -> Optional[str]:
    """The K=2*M island-caller pairing check, shared by decode_file and the
    CLI's parse-time validation so the two can't drift.

    The built-in caller reads base identity out of state ids, which is only
    meaningful for the reference's 2M-state X+/X- labeling
    (CpGIslandFinder.java:182-189).  Anything else would silently emit
    garbage islands — require the observation-based caller instead.  Returns
    an error message, or None when the pairing is valid.
    """
    if island_states is None and params.n_states != 2 * params.n_symbols:
        return (
            f"model has {params.n_states} states / {params.n_symbols} symbols, "
            "not the 2M-state X+/X- labeling the built-in island caller "
            "assumes — pass island_states=(...) (clean mode) to use the "
            "observation-based caller"
        )
    return None


def _check_invalid_symbols(invalid_symbols: str, compat: bool) -> None:
    """Shared validation of the codec policy flag: compat mode owes the
    reference byte-fidelity (silently skip every non-base char), so only
    clean mode may opt into mask/fail semantics."""
    from cpgisland_tpu.utils.codec import INVALID_POLICIES

    if invalid_symbols not in INVALID_POLICIES:
        raise ValueError(
            f"invalid_symbols must be one of {INVALID_POLICIES}, got "
            f"{invalid_symbols!r}"
        )
    if invalid_symbols != "skip" and compat:
        raise ValueError(
            "invalid-symbol policies other than 'skip' need clean mode "
            "(compat reproduces the reference's skip-everything encode)"
        )


def _session_for_call(
    session,
    params: HmmParams,
    *,
    name: str,
    engine: str,
    island_engine: str,
    island_cap: Optional[int],
    integrity_check: bool,
):
    """The serving-context policy shared by decode_file and posterior_file:
    an explicit session (daemon/bench) is validated against the call's
    routing kwargs and used as-is; otherwise an ephemeral Session is built
    from them — the exact state the pre-session code assembled inline."""
    from cpgisland_tpu.serve.session import Session

    if session is None:
        return Session(
            params, engine=engine, island_engine=island_engine,
            island_cap=island_cap, integrity_check=integrity_check, name=name,
        )
    session.check_call(
        params, engine=engine, island_engine=island_engine,
        island_cap=island_cap, integrity_check=integrity_check,
    )
    return session


def _open_manifest(
    mode: str,
    test_path: str,
    params: HmmParams,
    *,
    resume: bool,
    manifest_path: Optional[str],
    islands_out,
    compat: bool,
    per_symbol_outputs: tuple = (),
    config: Optional[dict] = None,
):
    """Build the run's resume manifest (or None when neither ``resume`` nor
    ``manifest_path`` asked for one) — the shared decode/posterior policy.

    Manifests are per-record, so they need clean mode, an ``islands_out``
    path to anchor the default manifest name, and no per-symbol stream
    outputs (those cannot be reconstructed record-by-record)."""
    if not resume and manifest_path is None:
        return None
    if compat:
        raise ValueError(
            "resume manifests are per-record; compat mode has no records — "
            "use compat=False (--clean)"
        )
    for flag, val in per_symbol_outputs:
        if val is not None:
            raise ValueError(
                f"resume manifests cannot reproduce per-symbol streams; "
                f"drop {flag} or run without resume/manifest"
            )
    mpath = manifest_path
    if mpath is None:
        if not isinstance(islands_out, str):
            raise ValueError(
                "resume needs islands_out as a file path (the manifest "
                "defaults to '<islands_out>.manifest.jsonl') or an explicit "
                "manifest_path"
            )
        mpath = islands_out + ".manifest.jsonl"
    from cpgisland_tpu.resilience import manifest as manifest_mod

    header = {
        "mode": mode,
        "source": os.path.abspath(test_path),
        **manifest_mod.source_fingerprint(test_path),
        "params": manifest_mod.params_digest(params),
        **(config or {}),
    }
    return manifest_mod.RunManifest(mpath, header=header, resume=resume)


@dataclass
class DecodeResult:
    calls: IslandCalls
    n_symbols: int
    n_chunks: int


# Largest sequence decoded in one sequence-parallel pass in clean mode.
# 256 Mi symbols (int32 on device plus packed backpointers) fits one v5e
# chip's HBM and covers every human chromosome; longer inputs decode
# span-wise with boundary messages threaded between spans
# (parallel.decode.viterbi_sharded_spans) — still exact, the span size only
# bounds peak device memory.
CLEAN_DECODE_SPAN = 1 << 28

# Records at or below this size batch together into one vmap decode (clean
# mode): real assemblies carry hundreds of small scaffolds beside the ~24
# chromosomes, and decoding them one dispatch at a time leaves the chip idle
# between launches.  4 Mi covers every GRCh38 non-chromosome scaffold.
SMALL_RECORD_MAX = 4 << 20


def decode_file(
    test_path: str,
    params: HmmParams,
    *,
    islands_out: Optional[Union[str, IO[str]]] = None,
    state_path_out: Optional[str] = None,
    compat: bool = True,
    chunk_size: int = chunking.DECODE_CHUNK,
    device_batch: int = 8,
    min_len: Optional[int] = None,
    span: int = CLEAN_DECODE_SPAN,
    engine: str = "auto",
    island_states=None,
    island_engine: str = "auto",
    island_cap: Optional[int] = None,
    symbol_cache: Optional[str] = None,
    metrics: Optional[profiling.MetricsLogger] = None,
    timer: Optional[profiling.PhaseTimer] = None,
    prefetch: int = 0,
    integrity_check: bool = False,
    resume: bool = False,
    manifest_path: Optional[str] = None,
    invalid_symbols: str = "skip",
    session=None,
) -> DecodeResult:
    """Viterbi-decode a sequence file and call CpG islands (reference
    ``testModel``).

    ``session`` (serve.session.Session): the long-lived serving context —
    supervisor, breaker-gated engine resolution, learned island cap,
    prepared-stream handle.  The daemon and bench pass one so repeated
    calls share warm state; when omitted an ephemeral session is built
    from the routing kwargs (identical behavior to the pre-session code).
    With an explicit session, ``params`` must be the session's own and the
    routing kwargs (``engine``/``island_engine``/``island_cap``/
    ``integrity_check``) must stay at their defaults — that config lives
    on the session.

    Resilience (the serving-side fault-tolerance layer, ``resilience/``):
    every blocking decode/island fetch runs under a dispatch supervisor
    (bounded retries with backoff on fault-shaped errors; deferred fetches
    carry a serial recompute fallback), repeated engine faults trip the
    degradation ladder to the parity twins, and ``integrity_check=True``
    adds the phantom-result sentinel (a canary fetch with a distinct seed
    fold per supervised dispatch — one extra tiny round trip each, hence
    opt-in).  ``resume=True`` (clean mode, no ``state_path_out``) replays
    completed records from a per-record JSONL manifest
    (``<islands_out>.manifest.jsonl`` unless ``manifest_path`` names one)
    and the final output is byte-identical to an uninterrupted run; the
    manifest is also WRITTEN whenever resume/manifest_path is given, so a
    killed run can resume next time.  ``invalid_symbols`` is the codec's
    skip/mask/fail policy (clean mode; 'skip' = reference semantics).

    ``prefetch`` (clean mode): depth of the double-buffered streaming
    executor.  0 (default) is the strictly serial encode -> upload ->
    compute -> fetch cadence; N >= 1 overlaps the phases — a background
    thread parses/encodes record r+1 while the device decodes record r
    (bounded queue of N records), multi-span records issue span k+1's
    async upload before blocking on span k's sweep, and with the device
    island engine record r's compact call-column fetch is deferred until
    record r+1's decode is in flight.  Island calls are bit-identical to
    the serial path (only dispatch/fetch timing changes); per-phase timer
    attribution blurs across overlapped phases by design.

    compat mode decodes 1 MiB chunks independently and resets the island
    caller per chunk (the reference's boundary behavior,
    CpGIslandFinder.java:256,262-268).  clean mode decodes each FASTA record
    exactly (sequence-parallel over all local devices) and calls islands per
    record — no DP restarts, no island clipping, no cross-chromosome islands.

    ``island_states`` (clean mode only): decode with a model whose states
    don't encode bases — e.g. presets.two_state_cpg with island_states=(0,)
    — and call islands with membership from the path but base composition
    from the observations (ops.islands.call_islands_obs).

    ``island_cap``: maximum island calls per device invocation (device
    engine only; default ops.islands_device.DEFAULT_CAP).  Batched small
    records share one cap per flush.  Overflow never aborts the run: the
    pipeline retries the (cheap, device-resident) calling pass with the cap
    raised to fit the true count, logging a warning — the default only sets
    the initial output-buffer size.

    ``island_engine``: where the island caller runs in clean mode.  "device"
    keeps the decoded path on device and reduces it there
    (ops.islands_device) so only the compact call records cross to the host —
    at genome scale the 4 B/symbol path transfer otherwise rivals the decode
    itself.  Both the 8-state labeling and observation-based
    ``island_states`` sets run on device (the latter via
    call_islands_device_obs).  "host" is the NumPy caller; "auto" picks
    device on TPU when no state-path dump is requested.  Multi-host: the
    compact call columns are gathered to every process in one collective
    (certified by the 2-process test).
    """
    if island_states is not None and compat:
        raise ValueError("island_states needs clean mode (compat=False); the "
                         "reference caller is 8-state-specific")
    if symbol_cache is not None and compat:
        raise ValueError("symbol_cache is FASTA-aware — use compat=False (--clean)")
    _check_invalid_symbols(invalid_symbols, compat)
    err = island_layout_error(params, island_states)
    if err:
        raise ValueError(err)
    session = _session_for_call(
        session, params, name="decode", engine=engine,
        island_engine=island_engine, island_cap=island_cap,
        integrity_check=integrity_check,
    )
    # The session owns the engine request: an explicit session's engine
    # must reach EVERY dispatch below (check_call forced the kwarg to its
    # 'auto' default), not just the batch lowering — raw string, not the
    # resolved name, so 'auto' keeps re-resolving against the breaker.
    engine = session.engine
    sup = session.supervisor
    manifest = _open_manifest(
        "decode", test_path, params,
        resume=resume, manifest_path=manifest_path, islands_out=islands_out,
        compat=compat,
        per_symbol_outputs=(("state_path_out", state_path_out),),
        config={
            "min_len": min_len,
            "island_states": (
                None if island_states is None else sorted(island_states)
            ),
            "invalid_symbols": invalid_symbols,
        },
    )
    use_device_islands, cap_box = session.island_policy(
        device_eligible=not compat and state_path_out is None,
        ineligible_msg=(
            "island_engine='device' implements clean-mode calling without a "
            "state-path dump (compat quirk reproduction and path dumps are "
            "host-side)"
        ),
    )
    timer = timer if timer is not None else profiling.PhaseTimer()
    # Engine + batch lowering resolved through the session (breaker-gated;
    # the flat reset-step decoder for onehot batches — see
    # Session.batch_decode_fn, the ONE copy of this choice).
    _eng = session.decode_engine()
    batch_decode = session.batch_decode_fn(_eng)

    if compat:
        with timer.phase("encode", unit="sym"):
            symbols = codec.encode_file(test_path, skip_headers=False)
        timer.phases["encode"].items += symbols.size
        chunked = chunking.frame(symbols, chunk_size, drop_remainder=True)
        chunks, lengths = chunked.chunks, chunked.lengths
        n = chunked.num_chunks
        parts: list[IslandCalls] = []
        with timer.phase("decode+islands", items=float(chunked.total), unit="sym"):
            for lo in range(0, n, device_batch):
                hi = min(lo + device_batch, n)

                def compat_unit(lo=lo, hi=hi):
                    # Dispatch + fetch as ONE supervised unit: a retry
                    # re-runs the (pure) jit dispatch, so a transient device
                    # fault costs one batch, not the file.
                    return obs.note_fetch(np.asarray(
                        batch_decode(
                            params,
                            jnp.asarray(chunks[lo:hi]),
                            jnp.asarray(lengths[lo:hi]),
                            return_score=False,
                        )
                    ))

                batch_total = lengths[lo:hi].sum()  # host array arithmetic
                batch_paths = sup.run(
                    compat_unit, what="decode.compat_batch",
                    engine=f"decode.{_eng}",
                    items=float(batch_total),
                )
                parts.extend(
                    islands_mod.call_islands(
                        batch_paths[i][: int(lengths[lo + i])],
                        chunk=lo + i,
                        chunk_size=chunk_size,
                        compat=True,
                    )
                    for i in range(hi - lo)
                )
        calls = IslandCalls.concatenate(parts)
        if metrics is not None:
            metrics.log(
                "decode",
                mode="compat",
                n_symbols=int(chunked.total),
                n_chunks=int(n),
                n_islands=len(calls),
                **timer.as_dict(),
            )
        log.info("decode phases:\n%s", timer.report())
        return _finish_decode(calls, chunked.total, n, islands_out)

    # Clean path: stream FASTA records (chromosomes) and decode each one
    # exactly — sequence-parallel over the mesh, span-wise only beyond the
    # device-memory budget — calling islands per record with per-record
    # 1-based coordinates, so an island can never span a chromosome boundary
    # (the reference concatenates the whole char stream, java:238-254).
    parts: list = []
    if state_path_out is not None:
        from cpgisland_tpu.utils.npystream import NpyStreamWriter

        path_writer = NpyStreamWriter(state_path_out, np.int8)
    else:
        path_writer = None
    n_sym = 0
    n_records = 0
    n_spans_total = 0
    # One (name, n_symbols, n_spans) entry per record; parts index == record
    # index (every record appends exactly one IslandCalls), so the manifest
    # marks completions strictly in record order as parts fill in.
    rec_meta: list = []
    mark_cursor = 0

    def mark_progress() -> None:
        nonlocal mark_cursor
        if manifest is None:
            return
        while mark_cursor < len(parts) and parts[mark_cursor] is not None:
            name_, size_, spans_ = rec_meta[mark_cursor]
            manifest.record_done(
                mark_cursor, name_, size_,
                calls=parts[mark_cursor], n_spans=spans_,
            )
            mark_cursor += 1

    # Overlapped mode (prefetch > 0) with the device island engine defers
    # each record's compact call-column fetch: the reduction is DISPATCHED
    # with the record, but the blocking host fetch waits in `deferred`
    # until the next record's decode is in flight — the relay round trip
    # then hides behind device compute.  Entries are (parts index, thunk
    # -> [IslandCalls]); settle fills the placeholders IN ORDER, so the
    # emitted records are identical to the serial path.
    defer_calls = prefetch > 0 and use_device_islands
    deferred: list = []

    def settle_deferred() -> None:
        while deferred:
            idx, thunk = deferred.pop(0)
            out = thunk()
            parts[idx : idx + len(out)] = out
        mark_progress()

    def decode_one(rec_name: str, symbols: np.ndarray) -> None:
        nonlocal n_spans_total
        n_spans = max(1, -(-symbols.size // span))
        n_spans_total += n_spans
        rec_meta[len(parts)][2] = n_spans
        if n_spans > 1:
            log.info(
                "record %r (%d symbols) exceeds the single-pass decode span "
                "(%d); decoding %d spans with boundary messages threaded "
                "between them (exact — no DP restart)",
                rec_name, symbols.size, span, n_spans,
            )

        def dispatch(overlap: bool) -> list:
            """Decode dispatch (the sharded calls supervise their own
            blocking fetches; with device islands nothing blocks here)."""
            if symbols.size == 0:
                return [np.zeros(0, dtype=np.int32)]
            if n_spans > 1:
                return viterbi_sharded_spans(
                    params, symbols, span=span, engine=engine,
                    return_device=use_device_islands,
                    prefetch=overlap, supervisor=sup,
                )
            return [
                viterbi_sharded(
                    params, symbols, engine=engine,
                    return_device=use_device_islands, supervisor=sup,
                )
            ]

        with timer.phase("decode", items=float(symbols.size), unit="sym"):
            if use_device_islands:
                if defer_calls:
                    pieces = dispatch(True)
                    full = pieces[0] if len(pieces) == 1 else jnp.concatenate(pieces)
                else:
                    def record_unit():
                        p = dispatch(False)
                        f = p[0] if len(p) == 1 else jnp.concatenate(p)
                        # Block INSIDE the supervised unit: per-phase stats
                        # attribute the decode where it happened (async
                        # dispatch would bill it to the islands phase), and
                        # a device fault surfaces HERE — where the retry
                        # re-dispatches — instead of poisoning the island
                        # call downstream.  The overlapped mode keeps the
                        # queue full instead (attribution blurs by design).
                        # graftcheck: allow(hot-path-host-sync) -- phase-attribution + fault-surfacing block (comment above); the obs ledger counts it via its block_until_ready hook
                        jax.block_until_ready(f)
                        return f

                    full = sup.run(
                        record_unit, what="decode.record_block",
                        engine=f"decode.{_eng}", items=float(symbols.size),
                    )
            else:
                pieces = dispatch(prefetch > 0)
                full = obs.note_fetch(np.concatenate(pieces))
        with timer.phase("islands", items=float(symbols.size), unit="sym"):
            if use_device_islands:
                from cpgisland_tpu.ops.islands_device import (
                    call_islands_device,
                    call_islands_device_async,
                    call_islands_device_obs,
                    call_islands_device_obs_async,
                )

                def recompute():
                    """Serial last-resort recovery for the deferred fetch:
                    the held device columns/path may be poisoned by an
                    upstream fault, so re-decode this record (blocking) and
                    re-run the island reduction from scratch."""
                    p2 = dispatch(False)
                    f2 = p2[0] if len(p2) == 1 else jnp.concatenate(p2)
                    if island_states is not None:
                        return _device_calls_retry(
                            call_islands_device_obs, f2, jnp.asarray(symbols),
                            island_states=island_states, min_len=min_len,
                            cap_box=cap_box, supervisor=sup,
                        )
                    return _device_calls_retry(
                        call_islands_device, f2, min_len=min_len,
                        cap_box=cap_box, supervisor=sup,
                    )

                if island_states is not None:
                    get = _device_calls_deferred(
                        call_islands_device_obs_async,
                        full, jnp.asarray(symbols),
                        island_states=island_states,
                        min_len=min_len, cap_box=cap_box,
                        supervisor=sup, recompute=recompute,
                    )
                else:
                    get = _device_calls_deferred(
                        call_islands_device_async, full,
                        min_len=min_len, cap_box=cap_box,
                        supervisor=sup, recompute=recompute,
                    )
                if defer_calls:
                    # "." = headerless leading sequence (see below).
                    name = rec_name or "."
                    idx = len(parts)
                    parts.append(None)
                    settle_deferred()  # previous record — our work is queued
                    deferred.append((idx, lambda: [get().with_names(name)]))
                    return
                calls = get()
            elif island_states is not None:
                calls = islands_mod.call_islands_obs(
                    full, symbols, island_states=island_states, min_len=min_len
                )
            else:
                calls = islands_mod.call_islands(full, chunk=0, compat=False, min_len=min_len)
        # "." = headerless leading sequence: keeps the name column parseable
        # (a bare "" would emit a leading space and split into 5 fields).
        parts.append(calls.with_names(rec_name or "."))
        mark_progress()
        if path_writer is not None:
            # graftcheck: allow(hot-path-host-sync) -- `full` is host already except under --clean device islands, where the path dump's one fetch is the product being written
            path_writer.write(np.asarray(full).astype(np.int8))

    def flush_small(batch: list) -> None:
        nonlocal n_spans_total
        if not batch:
            return
        if len(batch) == 1:
            decode_one(*batch[0])
            return
        n_spans_total_add, batch_parts, batch_paths = _decode_small_batch(
            params, batch, batch_decode=batch_decode, min_len=min_len,
            island_states=island_states,
            use_device_islands=use_device_islands,
            cap_box=cap_box,
            want_paths=path_writer is not None,
            timer=timer,
            defer=defer_calls,
            supervisor=sup,
            engine_label=_eng,
        )
        n_spans_total += n_spans_total_add
        if callable(batch_parts):  # deferred thunk -> per-record list
            idx = len(parts)
            parts.extend([None] * len(batch))
            settle_deferred()  # previous flush — this one is dispatched
            deferred.append((idx, batch_parts))
        else:
            parts.extend(batch_parts)
            mark_progress()
        for p in batch_paths:
            path_writer.write(p)

    # Small records (scaffolds) batch into one vmap decode per device_batch;
    # large records go through the sequence-parallel sharded decode.  Order
    # is preserved: a large record flushes the pending batch first.  The
    # finally keeps the state-path dump loadable (partial but valid) if a
    # record fails mid-file, and joins the prefetch thread deterministically.
    from cpgisland_tpu.utils.prefetch import maybe_prefetch

    rec_iter, close_prefetch = maybe_prefetch(
        codec.iter_fasta_records_cached(
            test_path, symbol_cache, invalid=invalid_symbols
        ),
        prefetch, "decode-records",
    )
    try:
        pending: list = []
        for rec_name, symbols in rec_iter:
            n_records += 1
            n_sym += symbols.size
            rec_meta.append([rec_name, int(symbols.size), 1])
            if manifest is not None:
                hit = manifest.completed(
                    n_records - 1, rec_name, int(symbols.size)
                )
                if hit is not None:
                    # Completed in a previous run: replay its calls from the
                    # manifest (bit-exact wire format) and skip all compute.
                    # Flush the pending batch first so parts stays in
                    # record order.
                    from cpgisland_tpu.resilience.manifest import calls_from_wire

                    flush_small(pending)
                    pending = []
                    spans_ = int(hit.get("n_spans", 1))
                    rec_meta[-1][2] = spans_
                    n_spans_total += spans_
                    parts.append(calls_from_wire(hit["calls"]))
                    mark_progress()
                    continue
            if symbols.size <= SMALL_RECORD_MAX:
                pending.append((rec_name, symbols))
                if len(pending) >= device_batch:
                    flush_small(pending)
                    pending = []
            else:
                flush_small(pending)
                pending = []
                decode_one(rec_name, symbols)
        flush_small(pending)
        settle_deferred()
    finally:
        close_prefetch()
        if manifest is not None:
            manifest.close()
        if path_writer is not None:
            path_writer.close()
    calls = IslandCalls.concatenate(parts)
    if n_records <= 1:
        # Single-record files keep the reference's bare 5-column format.
        calls = dataclasses.replace(calls, names=None)
    if metrics is not None:
        metrics.log(
            "decode",
            mode="clean",
            n_symbols=n_sym,
            n_records=n_records,
            n_spans=n_spans_total,
            n_islands=len(calls),
            **timer.as_dict(),
        )
    log.info("decode phases:\n%s", timer.report())
    return _finish_decode(calls, n_sym, n_spans_total, islands_out)


def _round_pow2(n: int, floor: int = 1 << 16) -> int:
    p = floor
    while p < n:
        p <<= 1
    return p


# Auto-retry never raises the cap past this: 4 Mi call slots = ~96 MB of
# device output columns.  Real genomes carry ~25-45k islands total; a count
# beyond 4 Mi per invocation means a degenerate input where unbounded
# escalation would trade a clear cap error for an opaque device OOM.
ISLAND_CAP_CEILING = 1 << 22


def _resolve_island_engine(
    island_engine: str,
    *,
    device_eligible: bool,
    ineligible_msg: str,
    island_cap: Optional[int],
    breaker=None,
):
    """(use_device_islands, cap_box) — THE island-engine policy, shared by
    decode_file, posterior_file, and the serve Session so the pipelines
    cannot diverge.  ``breaker``: the EngineBreaker gating auto-routing's
    degradation (a serve Session passes its own; default process-global).

    Works multi-host: a device path on a multi-host global mesh reduces to
    non-fully-addressable [cap] record columns, which islands_device
    gathers to every process in one collective (_cols_to_host) — certified
    by the real 2-process test (tests/test_multihost_real.py).
    """
    if island_engine not in ("auto", "host", "device"):
        raise ValueError(
            f"island_engine must be auto|host|device, got {island_engine!r}"
        )
    if island_engine == "device" and not device_eligible:
        raise ValueError(ineligible_msg)
    use_device_islands = island_engine == "device" or (
        island_engine == "auto"
        and device_eligible
        and jax.default_backend() == "tpu"
    )
    if use_device_islands and island_engine == "auto":
        # Degradation ladder: a device island caller tripped by repeated
        # dispatch faults falls back to its parity twin, the host NumPy
        # caller (calls are bit-identical, ops/islands_device.py), for the
        # breaker's cooldown window.  Auto-routing only — an EXPLICIT
        # 'device' request is honored as-is (parity runs exist to exercise
        # that specific engine; the supervisor still retries its faults).
        choice = (
            breaker if breaker is not None else resilience.get_breaker()
        ).degrade(
            "islands", "device", lambda e: "host" if e == "device" else None
        )
        use_device_islands = choice == "device"
    obs.engine_decision(
        site="island_engine",
        choice="device" if use_device_islands else "host",
        requested=island_engine,
    )
    if island_cap is None:
        from cpgisland_tpu.ops.islands_device import DEFAULT_CAP

        island_cap = DEFAULT_CAP
    if island_cap > ISLAND_CAP_CEILING:
        # The ceiling exists to prevent gigabyte-scale [cap] output buffers
        # dying in an opaque device OOM — a user-supplied starting cap must
        # not bypass it (e.g. a value thought of in bytes).
        log.warning(
            "island_cap %d exceeds the %d ceiling; clamping",
            island_cap, ISLAND_CAP_CEILING,
        )
        island_cap = ISLAND_CAP_CEILING
    # The cap_box is shared across all records/flushes of one run so a cap
    # raised by one overflow is learned for the rest (_device_calls_retry).
    return use_device_islands, [island_cap]


def _grow_cap_or_raise(e, cap_box: list) -> None:
    """The ONE overflow-cap policy (shared by the blocking retry and the
    deferred-fetch retry): grow cap_box to the next sufficient pow2, or
    re-raise when the true count exceeds the ceiling."""
    from cpgisland_tpu.analysis import memmodel
    from cpgisland_tpu.ops.islands_device import IslandCapOverflow

    if e.n > ISLAND_CAP_CEILING:
        # Terminal rejection: the true call count exceeds the ceiling —
        # report the model's predicted column footprint and the max-fit
        # cap so the failure carries actionable numbers (graftmem).
        obs.event(
            "mem_reject", site="island_cap",
            **memmodel.island_cap_report(e.n, ISLAND_CAP_CEILING),
        )
        raise IslandCapOverflow(e.n, cap_box[0]) from None
    # Clamp at the ceiling: n == ceiling exactly fits cap == n
    # slots, and the retry must not outgrow the bound the user
    # clamp enforces.
    new_cap = min(
        _round_pow2(e.n + 1, floor=2 * cap_box[0]), ISLAND_CAP_CEILING
    )
    obs.event(
        "island_cap_retry", n_calls=int(e.n), old_cap=cap_box[0],
        new_cap=new_cap,
        predicted_bytes=memmodel.island_columns_bytes(new_cap),
    )
    log.warning(
        "island calls (%d) overflowed cap=%d; retrying the on-device "
        "calling pass with cap=%d (decode not re-run)",
        e.n, cap_box[0], new_cap,
    )
    cap_box[0] = new_cap


def _device_calls_retry(
    fn, *args, cap_box: list, supervisor=None, recompute=None, **kwargs
):
    """Device island calling that SURVIVES cap overflow AND device faults.

    IslandCapOverflow carries the true surviving-call count, so the retry
    jumps straight to a sufficient (next-pow2) cap instead of aborting a
    multi-minute decode with re-run advice.  The decoded path is still
    device-resident when the overflow surfaces — only the cheap calling
    reduction re-runs (one recompile at the new static cap), never the
    decode itself.  ``cap_box`` is a one-element list: the grown cap is
    written back so later records/flushes of an island-dense file start at
    the learned size instead of re-overflowing every time.

    Fault-shaped errors (XlaRuntimeError etc.) retry under the dispatch
    supervisor; ``recompute`` (optional) is its serial fallback when the
    held device path may itself be poisoned.  Cap overflow stays OUTSIDE
    the supervisor (it is a sizing signal, not a fault — ValueError passes
    straight through).
    """
    from cpgisland_tpu.ops.islands_device import IslandCapOverflow

    sup = supervisor if supervisor is not None else resilience.default_supervisor()
    while True:
        try:
            return sup.run(
                functools.partial(fn, *args, cap=cap_box[0], **kwargs),
                what="islands.call", engine="islands.device",
                fallback=recompute,
            )
        except IslandCapOverflow as e:
            _grow_cap_or_raise(e, cap_box)


def _device_calls_deferred(
    fn_async, *args, cap_box: list, supervisor=None, recompute=None, **kwargs
):
    """Deferred twin of :func:`_device_calls_retry`.

    ``fn_async`` (islands_device.call_islands_device_async /
    ..._obs_async) dispatches the device reduction IMMEDIATELY and returns
    a fetch thunk; this wraps it so the overflow retry (re-dispatch at the
    grown cap, then fetch) happens at thunk-invocation time.  The
    overlapped pipeline calls the returned thunk only after the NEXT
    record's decode is in flight — the compact-column fetch round trip
    then hides behind device compute.  Same args/cap_box contract as the
    blocking retry; the device inputs stay referenced by the closure, so
    an overflow can still re-run only the calling reduction.

    The fetch runs under the dispatch supervisor: fault-shaped errors
    re-fetch/re-dispatch, and ``recompute`` (the caller's full serial
    re-decode + re-call closure) takes over from the second attempt —
    the held device buffers may be poisoned by an upstream fault the
    deferred cadence never blocked on.
    """
    from cpgisland_tpu.ops.islands_device import IslandCapOverflow

    sup = supervisor if supervisor is not None else resilience.default_supervisor()
    pending = fn_async(*args, cap=cap_box[0], **kwargs)

    def get():
        p = pending
        while True:
            try:
                return sup.run(
                    p, what="islands.columns", engine="islands.device",
                    fallback=recompute,
                )
            except IslandCapOverflow as e:
                _grow_cap_or_raise(e, cap_box)
                p = fn_async(*args, cap=cap_box[0], **kwargs)

    return get


def _batched_device_calls(
    params: HmmParams,
    paths,
    rows: np.ndarray,
    lengths: np.ndarray,
    batch: list,
    *,
    island_states,
    min_len,
    cap_box: list,
    deferred: bool = False,
    supervisor=None,
    recompute_paths=None,
):
    """ONE device island call over a padded [Bp, Tpad] batch of paths.

    Masked tail positions and one separator column become a non-island
    state so runs can never cross records; each emitted call's record is
    recovered from its coordinate.  The shared kernel of the batched decode
    AND batched posterior paths — only the compact call records cross to
    the host.  Returns per-record IslandCalls in batch order —
    ``deferred=True`` instead returns a zero-arg thunk producing that list:
    the device reduction is dispatched NOW, the column fetch happens when
    the thunk runs (the overlapped pipeline invokes it after the next
    batch's decode is in flight).

    ``recompute_paths`` (a blocking re-decode of the batch) is the
    supervisor's serial fallback: if the held device paths were poisoned by
    an upstream fault, the fetch retry re-derives them from host inputs and
    re-runs the blocking island call.
    """
    from cpgisland_tpu.ops.islands import N_ISLAND_STATES
    from cpgisland_tpu.ops.islands_device import (
        call_islands_device,
        call_islands_device_async,
        call_islands_device_obs,
        call_islands_device_obs_async,
    )

    Bp, Tpad = paths.shape
    stride = Tpad + 1
    # Masked tails/separators become a non-island state so runs can never
    # cross records: the background sentinel is N_ISLAND_STATES for the
    # 8-state labeling, n_states (an id no model state uses) for arbitrary
    # island_states sets.
    fill = N_ISLAND_STATES if island_states is None else params.n_states

    def _flat(paths):
        mask = jnp.arange(Tpad)[None, :] < jnp.asarray(lengths)[:, None]
        masked = jnp.where(mask, paths, fill)
        sep = jnp.full((Bp, 1), fill, masked.dtype)
        flat = jnp.concatenate([masked, sep], axis=1).reshape(-1)
        if island_states is None:
            return flat, None
        obs_dev = jnp.asarray(rows)
        obs_flat = jnp.concatenate(
            [obs_dev, jnp.zeros((Bp, 1), obs_dev.dtype)], axis=1
        ).reshape(-1)
        return flat, obs_flat

    flat, obs_flat = _flat(paths)

    recompute = None
    if recompute_paths is not None:
        def recompute():
            f2, o2 = _flat(recompute_paths())
            if island_states is not None:
                return _device_calls_retry(
                    call_islands_device_obs, f2, o2,
                    island_states=island_states, min_len=min_len,
                    cap_box=cap_box, supervisor=supervisor,
                )
            return _device_calls_retry(
                call_islands_device, f2, min_len=min_len, cap_box=cap_box,
                supervisor=supervisor,
            )

    if island_states is not None:
        get = _device_calls_deferred(
            call_islands_device_obs_async,
            flat, obs_flat, island_states=island_states,
            min_len=min_len, cap_box=cap_box,
            supervisor=supervisor, recompute=recompute,
        )
    else:
        get = _device_calls_deferred(
            call_islands_device_async, flat, min_len=min_len, cap_box=cap_box,
            supervisor=supervisor, recompute=recompute,
        )

    def finish() -> list:
        all_calls = get()
        rec_of = (all_calls.beg - 1) // stride
        parts = []
        for i, (name, _) in enumerate(batch):
            sel = rec_of == i
            parts.append(
                IslandCalls(
                    beg=all_calls.beg[sel] - i * stride,
                    end=all_calls.end[sel] - i * stride,
                    length=all_calls.length[sel],
                    gc_content=all_calls.gc_content[sel],
                    oe_ratio=all_calls.oe_ratio[sel],
                ).with_names(name or ".")
            )
        return parts

    return finish if deferred else finish()


def _decode_small_batch(
    params: HmmParams,
    batch: list,
    *,
    batch_decode,
    min_len,
    island_states,
    use_device_islands: bool,
    cap_box: list,
    want_paths: bool,
    timer: profiling.PhaseTimer,
    defer: bool = False,
    supervisor=None,
    engine_label: str = "xla",
):
    """Decode a batch of small records as vmap lanes; islands per record.

    Rows pad to a power-of-two time bucket and a fixed row count so the
    compile cache stays small across many scaffold shapes.  With device
    islands the whole padded batch flattens into ONE island call
    (_batched_device_calls).  Returns (n_spans, [IslandCalls per record],
    [paths]) — with ``defer`` (overlapped pipeline, device islands) the
    middle element is a thunk producing that list at fetch time.

    The decode dispatch + its blocking point run as one supervised unit
    (retry re-runs the pure jit dispatch); the deferred cadence instead
    hands ``_batched_device_calls`` a blocking re-decode closure as the
    fetch-time recompute fallback.
    """
    B = len(batch)
    sizes = [s.size for _, s in batch]
    Tpad = _round_pow2(max(sizes + [1]))
    Bp = _round_pow2(B, floor=8)
    rows = np.full((Bp, Tpad), chunking.PAD_SYMBOL, np.uint8)
    for i, (_, s) in enumerate(batch):
        rows[i, : s.size] = s
    lengths = np.zeros(Bp, np.int32)
    lengths[:B] = sizes
    sup = supervisor if supervisor is not None else resilience.default_supervisor()

    def decode_unit(block: bool):
        # uint8 upload (the decoders cast on device): the host->device
        # transfer is the measured end-to-end bottleneck — don't 4x it.
        paths = batch_decode(
            params, jnp.asarray(obs.note_upload(rows)), jnp.asarray(lengths),
            return_score=False,
        )
        if block:
            # Block so per-phase stats attribute the decode where it
            # happened (async dispatch would bill it to the islands
            # phase) and so a device fault surfaces inside the supervised
            # unit; the overlapped mode keeps the queue full instead.
            # graftcheck: allow(hot-path-host-sync) -- phase-attribution + fault-surfacing block (comment above); the obs ledger counts it via its block_until_ready hook
            jax.block_until_ready(paths)
        return paths

    total = float(sum(sizes))
    with timer.phase("decode", items=total, unit="sym"):
        if use_device_islands:
            if defer:
                paths = decode_unit(False)
            else:
                paths = sup.run(
                    lambda: decode_unit(True), what="decode.batch",
                    engine=f"decode.{engine_label}", items=total,
                )
        else:
            paths = sup.run(
                lambda: obs.note_fetch(np.asarray(decode_unit(False))),
                what="decode.batch", engine=f"decode.{engine_label}",
                items=total,
            )

    parts: list[IslandCalls] = []
    paths_out: list[np.ndarray] = []
    with timer.phase("islands", items=total, unit="sym"):
        if use_device_islands:
            parts = _batched_device_calls(
                params, paths, rows, lengths, batch,
                island_states=island_states, min_len=min_len, cap_box=cap_box,
                deferred=defer,
                supervisor=sup,
                recompute_paths=(lambda: decode_unit(True)) if defer else None,
            )
        else:
            for i, (name, symbols) in enumerate(batch):
                row = paths[i, : symbols.size]
                if island_states is not None:
                    calls = islands_mod.call_islands_obs(
                        row, symbols, island_states=island_states, min_len=min_len
                    )
                else:
                    calls = islands_mod.call_islands(
                        row, chunk=0, compat=False, min_len=min_len
                    )
                parts.append(calls.with_names(name or "."))
    if want_paths:
        host = obs.note_fetch(np.asarray(paths))
        paths_out = [host[i, : s.size].astype(np.int8) for i, (_, s) in enumerate(batch)]
    return B, parts, paths_out


def _decode_small_batch_stacked(
    params_list,
    batch: list,
    owners: list,
    *,
    min_len,
    island_states_list,
    use_device_list,
    cap_boxes,
    timer: profiling.PhaseTimer,
    supervisor=None,
):
    """Decode ONE small-record batch under M models in a STACKED flat
    launch set — the serve broker's mixed-model decode flush unit.

    All records (across models) ride ONE reset-step stream; every model's
    reduced chains run stacked (viterbi_onehot.decode_batch_flat_stacked),
    and record i's island calls come from its OWNING model's path
    (``owners[i]`` indexes ``params_list``).  Exactness: record i's path
    is bit-identical to ``owners[i]``'s own flat decode of this same
    padded batch AT THE SAME BLOCK SIZE — on TPU with M>=3 the stacked
    decoder clamps its block to graftmem's ``stacked_block_cap`` (VMEM),
    so vs a default-block single-model decode the comparison is modulo
    the flat decoder's pinned rounding-tie contract, like the sequential-
    flush comparison below; vs the per-model sequential flush (whose flat
    streams contain only that model's records) paths agree modulo that
    same contract (PARITY.md C10) — the reset entry constant differs,
    argmax paths only move on exact ties.

    Island calling runs per model on its records (device islands via the
    shared batched reduction, host islands via the pipelines' exact host
    callers).  Returns (B, [IslandCalls per record] in batch order).
    """
    from cpgisland_tpu.ops.viterbi_onehot import decode_batch_flat_stacked_jit

    B = len(batch)
    sizes = [s.size for _, s in batch]
    Tpad = _round_pow2(max(sizes + [1]))
    Bp = _round_pow2(B, floor=8)
    rows = np.full((Bp, Tpad), chunking.PAD_SYMBOL, np.uint8)
    for i, (_, s) in enumerate(batch):
        rows[i, : s.size] = s
    lengths = np.zeros(Bp, np.int32)
    lengths[:B] = sizes
    sup = supervisor if supervisor is not None else resilience.default_supervisor()
    any_dev = any(use_device_list)

    def decode_unit(block: bool):
        paths = decode_batch_flat_stacked_jit(
            tuple(params_list), jnp.asarray(obs.note_upload(rows)),
            jnp.asarray(lengths),
        )
        if block:
            # Phase-attribution + fault-surfacing block, the
            # _decode_small_batch contract (the obs ledger counts it via
            # its block_until_ready hook).
            jax.block_until_ready(paths)
        return paths

    total = float(sum(sizes))
    with timer.phase("decode", items=total, unit="sym"):
        if any_dev:
            paths = sup.run(
                lambda: decode_unit(True), what="decode.batch.stacked",
                engine="decode.onehot.stacked", items=total,
            )
        else:
            paths = sup.run(
                lambda: obs.note_fetch(np.asarray(decode_unit(False))),
                what="decode.batch.stacked",
                engine="decode.onehot.stacked", items=total,
            )

    parts: list = [None] * B
    with timer.phase("islands", items=total, unit="sym"):
        for m in range(len(params_list)):
            idx = [i for i in range(B) if owners[i] == m]
            if not idx:
                continue
            batch_m = [batch[i] for i in idx]
            if use_device_list[m]:
                # Pow2-pad the per-model sub-batch rows (zero-length pad
                # rows emit no calls) so varying per-flush model mixes
                # share island-reduction compiles — the same bucket
                # discipline as the whole-batch layout above.
                Bmp = _round_pow2(len(idx), floor=8)
                sel_np = np.asarray(
                    idx + [idx[0]] * (Bmp - len(idx)), np.int32
                )
                lens_m = lengths[sel_np].copy()
                lens_m[len(idx):] = 0
                calls_m = _batched_device_calls(
                    params_list[m], paths[m][jnp.asarray(sel_np)],
                    rows[sel_np], lens_m, batch_m,
                    island_states=island_states_list[m], min_len=min_len,
                    cap_box=cap_boxes[m], supervisor=sup,
                )
            else:
                pm = paths[m]
                if any_dev:
                    # ONE batched, ledger-counted fetch per model (the
                    # relay pays per round trip; per-record row fetches
                    # would be unbatched AND uncounted).
                    pm = obs.note_fetch(
                        np.asarray(pm[jnp.asarray(np.asarray(idx, np.int32))])
                    )
                else:
                    pm = np.asarray(pm)[np.asarray(idx)]
                calls_m = []
                for k, i in enumerate(idx):
                    name, symbols = batch[i]
                    row = np.asarray(pm[k][: symbols.size])
                    if island_states_list[m] is not None:
                        c = islands_mod.call_islands_obs(
                            row, symbols,
                            island_states=island_states_list[m],
                            min_len=min_len,
                        )
                    else:
                        c = islands_mod.call_islands(
                            row, chunk=0, compat=False, min_len=min_len
                        )
                    calls_m.append(c.with_names(name or "."))
            for k, i in enumerate(idx):
                parts[i] = calls_m[k]
    return B, parts


# One posterior pass materializes the alpha/beta kernel streams on device
# (~72 B/symbol at K=8), so 64 Mi spans keep the working set under ~5 GB of
# HBM.  Longer records process span-wise with boundary-message threading
# (EXACT — the span size only bounds peak device memory, like
# CLEAN_DECODE_SPAN for the hard decode).
POSTERIOR_SPAN = 1 << 26

# Records at or below this size batch into ONE chunked-layout kernel pass on
# the pallas engine (fb_pallas.batch_posterior_pallas: one record per VPU
# lane — exact, since each record fits its lane whole).  512 Ki keeps the
# padded alpha stream of a 128-lane batch ~2 GB; bigger records already fill
# >=64 lanes of the sequence-parallel path on their own.
POSTERIOR_BATCH_MAX = 1 << 19


@dataclass
class PosteriorResult:
    n_symbols: int
    n_records: int
    mean_island_confidence: float
    calls: Optional[IslandCalls] = None


def _posterior_record_unit(
    params: HmmParams,
    symbols: np.ndarray,
    island_states,
    *,
    engine: str,
    fb_eng: str,
    want_path: bool,
    return_device: bool,
    sup,
    supervised: bool = True,
    placed=None,
):
    """ONE record's posterior dispatch+fetch — the shared core of
    posterior_file's single-record path AND the serve broker's posterior
    unit, so the daemon and the batch CLI cannot diverge (same discipline
    as the decode/posterior shared-helper split).  Pads to a power-of-two
    bucket (floor 16 Ki) so varied record sizes share compiled shapes.
    ``supervised=False`` returns the raw unsupervised unit result (the
    recompute-fallback closures re-derive through it without nesting a
    second retry loop).  ``placed`` (parallel.posterior.place_record_span
    with the same pow2 bucket): an already-uploaded (arr, lens) pair —
    the compare workload places each order's stream ONCE and shares it
    across that order's members (bit-identical: _place with identical
    arguments produces identical arrays)."""
    from cpgisland_tpu.parallel.posterior import posterior_sharded

    def record_unit():
        conf, path = posterior_sharded(
            params, symbols, island_states,
            engine=engine, want_path=want_path,
            return_device=return_device,
            # Power-of-two buckets: scaffold-heavy files must not
            # compile once per distinct record size.
            pad_to=_round_pow2(symbols.size, floor=1 << 14),
            placed=placed,
            breaker=sup.breaker,
        )
        if return_device:
            # Fault-surfacing block (see decode_one): a poisoned
            # conf/path must fail INSIDE the supervised unit — where
            # the retry re-dispatches — not downstream in the device
            # accumulator or island caller.
            # graftcheck: allow(hot-path-host-sync) -- fault-surfacing + phase-attribution block (comment above); the obs ledger counts it via its block_until_ready hook
            jax.block_until_ready(path if path is not None else conf)
        return conf, path

    if not supervised:
        return record_unit()
    return sup.run(
        record_unit, what="posterior.record",
        engine=f"fb.{fb_eng}", items=float(symbols.size),
    )


def posterior_file(
    test_path: str,
    params: HmmParams,
    *,
    confidence_out: Optional[str] = None,
    mpm_path_out: Optional[str] = None,
    islands_out: Optional[Union[str, IO[str]]] = None,
    min_len: Optional[int] = None,
    island_states=None,
    span: int = POSTERIOR_SPAN,
    engine: str = "auto",
    island_engine: str = "auto",
    island_cap: Optional[int] = None,
    symbol_cache: Optional[str] = None,
    metrics: Optional[profiling.MetricsLogger] = None,
    timer: Optional[profiling.PhaseTimer] = None,
    prefetch: int = 0,
    integrity_check: bool = False,
    resume: bool = False,
    manifest_path: Optional[str] = None,
    invalid_symbols: str = "skip",
    session=None,
) -> PosteriorResult:
    """Soft decoding of a FASTA file: per-position island confidence.

    ``session``: the long-lived serving context (same contract as
    :func:`decode_file` — an explicit session owns the routing config and
    must match ``params``; omitted = ephemeral, pre-session behavior).

    Resilience: same contract as :func:`decode_file` — supervised blocking
    units with bounded retries, engine degradation to parity twins on
    repeated faults, opt-in ``integrity_check`` phantom sentinel, and
    ``resume``/``manifest_path`` per-record manifests.  Posterior manifests
    need an island-only run (``islands_out`` without ``confidence_out`` /
    ``mpm_path_out`` — per-symbol streams are not resumable); manifest
    mode processes records one at a time (no small-record batching) and
    accumulates the mean confidence from exact per-record sums recorded in
    the manifest, so a resumed run's result is identical to an
    uninterrupted manifest run.

    ``prefetch``: depth of the double-buffered streaming executor (same
    contract as decode_file) — 0 is strictly serial; N >= 1 parses/encodes
    record r+1 on a background thread while the device processes record r,
    and multi-span records issue span k+1's async upload before blocking
    on span k's transfer-total sweep.  Outputs are bit-identical to the
    serial path.

    The reference's Mahout surface exposes only hard Viterbi decoding
    (HmmEvaluator.decode, CpGIslandFinder.java:260); this is its soft
    completion — P(position is in an island | whole record) = the summed
    posterior marginal over the island states, written as one float32 per
    symbol (.npy, streamed record by record) when ``confidence_out`` is
    given.  ``mpm_path_out`` additionally writes the
    max-posterior-marginal state path (int8), the soft counterpart of
    decode_file's ``state_path_out``; ``islands_out`` calls CpG islands
    from that MPM path (clean semantics, per record, same ``beg end len gc
    oe`` format as decode_file) — the full soft counterpart of the
    reference's Viterbi -> island-caller pipeline
    (CpGIslandFinder.java:260-339), with ``min_len`` available.  At least
    one of the three outputs must be requested; an island-only run
    (``islands_out`` alone) writes NO per-symbol file and — with the
    device island engine — transfers no per-symbol array to the host
    either, so its I/O cost is the compact call records, not 4 B/symbol.

    ``island_states``: which states count as "island" (same contract as
    decode_file's flag); default = the first n_symbols states, the
    reference's 2M-state X+/X- labeling, which the model must then match.

    ``island_engine``/``island_cap``: same contract as decode_file —
    "device" reduces the MPM path to compact call records on device
    (requires ``islands_out`` without ``mpm_path_out``); "auto" picks
    device on TPU when eligible (multi-host included); cap overflow
    auto-retries.

    Clean semantics only (FASTA-aware, per-record).  Every record runs
    through the lane-parallel forward-backward machinery
    (parallel.posterior.posterior_sharded: fused Pallas kernels on TPU, the
    blockwise XLA lane path elsewhere, sequence-parallel over the mesh).
    Records longer than ``span`` process in spans with enter/exit boundary
    directions threaded between them — EXACT posteriors at any length; the
    span only bounds peak device memory.
    """
    from cpgisland_tpu.parallel.decode import _prev_real_symbol
    from cpgisland_tpu.parallel.mesh import fetch_sharded_prefix
    from cpgisland_tpu.parallel.posterior import (
        island_mask,
        place_record_span,
        posterior_sharded,
        prepare_record_span,
        transfer_total_sharded,
    )
    from cpgisland_tpu.utils.npystream import NpyStreamWriter

    def conf_to_host(conf) -> np.ndarray:
        """Host-fetch a device-resident conf array (already length-trimmed)
        under the multi-host rule: a global-mesh array spanning
        non-addressable devices gathers via process_allgather, a local one
        fetches directly — the same rule fetch_sharded_prefix applies on
        the host-return path."""
        return fetch_sharded_prefix(conf, conf.shape[0], False)

    obs_based_calls = island_states is not None  # user-named island states
    if island_states is None:
        err = island_layout_error(params, island_states)
        if err:
            raise ValueError(f"island confidence: {err}")
        island_states = tuple(range(params.n_symbols))
    island_states = tuple(sorted(island_states))
    _check_invalid_symbols(invalid_symbols, compat=False)
    timer = timer if timer is not None else profiling.PhaseTimer()
    want_conf = confidence_out is not None
    want_islands = islands_out is not None
    want_path = mpm_path_out is not None or want_islands
    if not (want_conf or want_path):
        raise ValueError(
            "posterior: nothing to do — request confidence_out, "
            "mpm_path_out, and/or islands_out"
        )
    session = _session_for_call(
        session, params, name="posterior", engine=engine,
        island_engine=island_engine, island_cap=island_cap,
        integrity_check=integrity_check,
    )
    # Session-owned engine request, raw string (see decode_file): an
    # explicit session's engine reaches every span/record dispatch below.
    engine = session.engine
    sup = session.supervisor
    manifest = _open_manifest(
        "posterior", test_path, params,
        resume=resume, manifest_path=manifest_path, islands_out=islands_out,
        compat=False,
        per_symbol_outputs=(
            ("confidence_out", confidence_out),
            ("mpm_path_out", mpm_path_out),
        ),
        config={
            "min_len": min_len,
            "island_states": sorted(island_states),
            "invalid_symbols": invalid_symbols,
        },
    )
    if manifest is not None and not want_islands:
        raise ValueError(
            "posterior resume manifests need islands_out (the island-only "
            "mode is the resumable one)"
        )
    use_device_islands, cap_box = session.island_policy(
        # The MPM path can stay device-resident only when nothing else
        # needs it on the host (the int8 dump is host-side).
        device_eligible=want_islands and mpm_path_out is None,
        ineligible_msg=(
            "island_engine='device' reduces the MPM path on device — it "
            "needs islands_out and no mpm_path_out (the path dump is "
            "host-side)"
        ),
    )
    # Small records batch into one chunked-layout kernel pass (pallas only;
    # the XLA lane path serves one record at a time).  Manifest runs keep
    # the one-record cadence: completion marks and per-record confidence
    # sums then line up with record boundaries.
    _fb_eng = session.fb_engine()
    batch_small = _fb_eng in ("pallas", "onehot") and manifest is None
    # Writers open INSIDE the try: a failure opening the second must still
    # close (finalize) the first, not leave a corrupt header slot behind.
    conf_w = None
    path_w = None
    n_sym = 0
    n_records = 0
    conf_total = 0.0

    def emit(conf, path) -> None:
        """Book host-side per-symbol outputs.  ``conf=None`` means the
        confidence stayed on device (island-only device runs) and was
        already accumulated by accum_conf_device."""
        nonlocal conf_total
        if conf is not None:
            conf = np.asarray(conf)
            # f64 accumulation: float32 partials drift ~1e-5 at multi-Gbase.
            conf_total += float(conf.sum(dtype=np.float64))
            if conf_w is not None:
                conf_w.write(conf)
        if path_w is not None and path is not None:
            path_w.write(np.asarray(path).astype(np.int8))

    conf_dev_acc = None  # device-resident f32 running sum (island-only mode)

    def accum_conf_device(conf) -> None:
        """Mean-confidence contribution of a device-resident conf array.
        The sum accumulates ON DEVICE (async dispatch, no blocking fetch per
        span/record); ONE scalar crosses to the host at end of file."""
        nonlocal conf_dev_acc
        s = jnp.sum(conf)
        conf_dev_acc = s if conf_dev_acc is None else conf_dev_acc + s

    call_parts: list[IslandCalls] = []

    def call_rec(rec_name: str, symbols: np.ndarray, path, recompute_path=None) -> None:
        """MPM-path island calls for one whole record (clean semantics).
        With the device engine ``path`` is a device array and only the
        compact call records cross to the host.  ``recompute_path`` (a
        blocking re-derivation of the MPM path) is the supervisor's serial
        fallback if the held device path turns out poisoned."""
        if not want_islands:
            return
        if use_device_islands:
            from cpgisland_tpu.ops.islands_device import (
                call_islands_device,
                call_islands_device_obs,
            )

            def _call(p, recompute=None):
                if obs_based_calls:
                    return _device_calls_retry(
                        call_islands_device_obs,
                        p, jnp.asarray(symbols), island_states=island_states,
                        min_len=min_len, cap_box=cap_box, supervisor=sup,
                        recompute=recompute,
                    )
                return _device_calls_retry(
                    call_islands_device, p, min_len=min_len, cap_box=cap_box,
                    supervisor=sup, recompute=recompute,
                )

            recompute = (
                None if recompute_path is None
                else (lambda: _call(recompute_path()))
            )
            calls = _call(path, recompute)
        elif obs_based_calls:
            calls = islands_mod.call_islands_obs(
                np.asarray(path), np.asarray(symbols),
                island_states=island_states, min_len=min_len,
            )
        else:
            calls = islands_mod.call_islands(
                np.asarray(path), chunk=0, compat=False, min_len=min_len
            )
        call_parts.append(calls.with_names(rec_name or "."))

    pending: list[tuple[str, np.ndarray]] = []

    def flush_small() -> None:
        if not pending:
            return
        batch = list(pending)
        pending.clear()
        if len(batch) == 1:
            one_record(*batch[0])
            return
        from cpgisland_tpu.ops.fb_pallas import batch_posterior_pallas

        # One kernel call per power-of-two size class: padding every record
        # to the batch maximum would inflate the walk by the size spread
        # (one ~400Ki record among 1Ki scaffolds = ~400x wasted steps).
        # Results are emitted back in FILE order regardless of class.
        by_class: dict[int, list[int]] = {}
        for i, (_, s) in enumerate(batch):
            by_class.setdefault(_round_pow2(s.size, floor=1 << 14), []).append(i)
        results: list = [None] * len(batch)
        rec_calls: list = [None] * len(batch)
        # Device-memory budget per kernel call, in PADDED symbols: the fused
        # conf path streams ~36 B/padded-symbol; want_path materializes both
        # alpha AND beta streams (~72 B), so it gets half the budget.
        budget = (1 << 26) // (2 if want_path else 1)
        for Tpad in sorted(by_class):
            group_all = by_class[Tpad]
            max_rows = max(1, budget // Tpad)
            for lo in range(0, len(group_all), max_rows):
                group = group_all[lo : lo + max_rows]
                Bp = _round_pow2(len(group), floor=8)
                rows = np.full((Bp, Tpad), chunking.PAD_SYMBOL, np.uint8)
                lens = np.zeros(Bp, np.int32)
                for g, i in enumerate(group):
                    s = batch[i][1]
                    rows[g, : s.size] = s
                    lens[g] = s.size
                total = float(sum(batch[i][1].size for i in group))

                def batch_unit(rows=rows, lens=lens):
                    conf2, path2 = batch_posterior_pallas(
                        params, jnp.asarray(rows), jnp.asarray(lens),
                        jnp.asarray(island_mask(params, island_states)),
                        want_path=want_path, onehot=_fb_eng == "onehot",
                    )
                    if use_device_islands:
                        # conf/path stay device-resident; block so the
                        # kernel time is billed to this phase AND a device
                        # fault surfaces inside the supervised unit (a
                        # retry re-dispatches; poisoned outputs must not
                        # reach the island caller / accumulator).
                        # graftcheck: allow(hot-path-host-sync) -- phase-attribution + fault-surfacing block (comment above); the obs ledger counts it via its block_until_ready hook
                        jax.block_until_ready(path2)
                    else:
                        conf2 = obs.note_fetch(np.asarray(conf2))
                        path2 = (
                            obs.note_fetch(np.asarray(path2))
                            if want_path else None
                        )
                    return conf2, path2

                with timer.phase("posterior", items=total, unit="sym"):
                    conf2, path2 = sup.run(
                        batch_unit, what="posterior.batch",
                        engine=f"fb.{_fb_eng}", items=total,
                    )
                if use_device_islands:
                    with timer.phase("islands", items=total, unit="sym"):
                        g_calls = _batched_device_calls(
                            params, path2, rows, lens,
                            [batch[i] for i in group],
                            island_states=(
                                island_states if obs_based_calls else None
                            ),
                            min_len=min_len, cap_box=cap_box,
                            supervisor=sup,
                            recompute_paths=lambda: batch_unit()[1],
                        )
                    if want_conf:
                        conf_host = obs.note_fetch(np.asarray(conf2))
                    else:
                        in_rec = (
                            jnp.arange(Tpad)[None, :]
                            < jnp.asarray(lens)[:, None]
                        )
                        accum_conf_device(jnp.where(in_rec, conf2, 0.0))
                    for g, i in enumerate(group):
                        n = batch[i][1].size
                        results[i] = (
                            conf_host[g, :n] if want_conf else None, None
                        )
                        rec_calls[i] = g_calls[g]
                else:
                    for g, i in enumerate(group):
                        n = batch[i][1].size
                        results[i] = (
                            conf2[g, :n],
                            path2[g, :n] if want_path else None,
                        )
        for i, ((name, s), (conf, path)) in enumerate(zip(batch, results)):
            emit(conf, path)
            if use_device_islands:
                call_parts.append(rec_calls[i])
            else:
                call_rec(name, s, path)

    def one_record(rec_name: str, symbols: np.ndarray) -> Optional[float]:
        """Returns the record's exact f64 confidence sum in manifest mode
        (recorded per record so a resumed run reproduces the mean), else
        None (the cheaper aggregate accumulators)."""
        nonlocal conf_total

        def unit(supervised: bool = True):
            return _posterior_record_unit(
                params, symbols, island_states, engine=engine,
                fb_eng=_fb_eng, want_path=want_path,
                return_device=use_device_islands, sup=sup,
                supervised=supervised,
            )

        with timer.phase("posterior", items=float(symbols.size), unit="sym"):
            conf, path = unit()
        rec_conf = None
        if use_device_islands:
            if want_conf:
                emit(conf_to_host(conf), None)
            elif manifest is not None:
                rec_conf = float(obs.note_fetch(np.asarray(jnp.sum(conf))))
                conf_total += rec_conf
            else:
                accum_conf_device(conf)
        elif manifest is not None:
            # graftcheck: allow(hot-path-host-sync) -- conf is host on this branch (posterior_sharded fetched it through obs.note_fetch); exact-f64 coercion only
            rec_conf = float(np.asarray(conf).sum(dtype=np.float64))
            conf_total += rec_conf
            emit(None, path)
        else:
            emit(conf, path)

        def recompute_path():
            c2, p2 = unit(supervised=False)
            return p2

        call_rec(rec_name, symbols, path, recompute_path=recompute_path)
        return rec_conf

    from cpgisland_tpu.utils.prefetch import maybe_prefetch

    rec_iter, close_prefetch = maybe_prefetch(
        codec.iter_fasta_records_cached(
            test_path, symbol_cache, invalid=invalid_symbols
        ),
        prefetch, "posterior-records",
    )
    try:
        if confidence_out is not None:
            conf_w = NpyStreamWriter(confidence_out, np.float32)
        if mpm_path_out is not None:
            path_w = NpyStreamWriter(mpm_path_out, np.int8)
        for rec_name, symbols in rec_iter:
            rec_idx = n_records
            n_records += 1
            n_sym += symbols.size
            if manifest is not None:
                hit = manifest.completed(rec_idx, rec_name, int(symbols.size))
                if hit is not None:
                    # Completed in a previous run: replay calls + the exact
                    # per-record confidence sum from the manifest.
                    from cpgisland_tpu.resilience.manifest import calls_from_wire

                    if hit.get("conf_sum") is not None:
                        conf_total += float.fromhex(hit["conf_sum"])
                    replay = calls_from_wire(hit["calls"])
                    if replay is not None:
                        call_parts.append(replay)
                    continue
            if symbols.size == 0:
                if manifest is not None:
                    manifest.record_done(
                        rec_idx, rec_name, 0, calls=None, conf_sum=0.0
                    )
                continue
            # Batch eligibility respects a user-narrowed span: a record the
            # span contract would split must take the span-threaded path.
            if batch_small and symbols.size <= min(span, POSTERIOR_BATCH_MAX):
                # graftcheck: allow(hot-path-host-sync) -- record symbols are host np arrays from the codec record reader; copy, not a device fetch
                pending.append((rec_name, np.asarray(symbols)))
                if len(pending) >= 128:
                    flush_small()
                continue
            flush_small()  # preserve record order around a large record
            n_spans = -(-symbols.size // span)
            if n_spans == 1:
                rec_conf = one_record(rec_name, symbols)
                if manifest is not None:
                    manifest.record_done(
                        rec_idx, rec_name, int(symbols.size),
                        calls=call_parts[-1] if want_islands else None,
                        conf_sum=rec_conf,
                    )
                continue
            log.info(
                "record %r (%d symbols) exceeds the posterior span (%d); "
                "processing %d spans with boundary messages threaded "
                "between them (exact — no DP restart)",
                rec_name, symbols.size, span, n_spans,
            )
            # Sweep A: each span's [K, K] transfer operator (products only).
            # pad_to=span: every span (incl. the ragged tail) shares ONE
            # compiled shape.  Each span is device-placed ONCE here and
            # reused by sweep B (popped as consumed): the upload is the
            # dominant span-path cost on any interconnect, and the two
            # sweeps would otherwise pay it twice.  Overlapped mode
            # (prefetch > 0): the totals stay device-resident through the
            # loop (return_device) so nothing blocks between spans — span
            # k+1's device_put is issued while span k's products sweep
            # runs — and the tiny [K, K] fetches all happen at the end.
            span_placed: dict = {}
            span_prep: dict = {}
            # The SESSION's PreparedStreams handle: every span's symbol-only
            # artifact (lane layout + pair stream) books against it and is
            # shared by the transfer-total and posterior sweeps below — and,
            # for a long-lived session, released by Session.close().
            rec_streams = session.streams
            with timer.phase("span-totals", items=float(symbols.size), unit="sym"):
                totals = []
                for si, lo in enumerate(range(0, symbols.size, span)):
                    piece = symbols[lo : lo + span]
                    span_placed[si] = place_record_span(
                        params, piece, pad_to=span
                    )
                    # The symbol before the span conditions the reduced
                    # onehot kernels' entry group.
                    prev = (
                        0 if lo == 0
                        else _prev_real_symbol(symbols, lo, params.n_symbols)
                    )
                    # ONE symbol-only prep (lane layout + pair stream) per
                    # placed span, shared by this transfer-total sweep and
                    # the posterior sweep below (ops.prepared; None when the
                    # mesh/engine has no prepared form — inline prep then).
                    span_prep[si] = prepare_record_span(
                        params, span_placed[si], piece.size, engine=engine,
                        first=lo == 0, prev_sym=prev, want_path=want_path,
                        streams=rec_streams, breaker=session.breaker,
                    )

                    def total_unit(si=si, piece=piece, lo=lo, prev=prev,
                                   device=prefetch > 0):
                        return transfer_total_sharded(
                            params, piece, engine=engine, first=lo == 0,
                            pad_to=span, placed=span_placed[si],
                            prev_sym=prev,
                            return_device=device,
                            prepared=span_prep[si],
                            breaker=session.breaker,
                        )

                    if prefetch > 0:
                        # Async dispatch, no blocking here — faults surface
                        # (and recover) at the supervised fetch below.
                        totals.append((total_unit, total_unit()))
                    else:
                        totals.append(sup.run(
                            total_unit, what="posterior.span_total",
                            engine=f"fb.{_fb_eng}", items=float(piece.size),
                        ))
                if prefetch > 0:
                    totals = [
                        sup.run(
                            lambda t=t: obs.note_fetch(np.asarray(t)),
                            what="posterior.span_total_fetch",
                            engine=f"fb.{_fb_eng}",
                            # Serial fallback: re-dispatch THIS span's
                            # products sweep (blocking) — the held device
                            # total may be poisoned.
                            fallback=lambda unit=unit_: unit(device=False),
                        )
                        for unit_, t in totals
                    ]
            # Host threading: entering-alpha / exiting-beta directions per
            # span (tiny [K]x[K,K] chains, f32 on normalized operators).
            pi = np.exp(np.asarray(params.log_pi, np.float64))
            B = np.exp(np.asarray(params.log_B, np.float64))
            # Emission folded in only for in-range first symbols, mirroring
            # the decode twin (viterbi_sharded_spans) — robustness only;
            # clean-mode FASTA symbols are always 0..3.
            v = (
                pi * B[:, int(symbols[0])]
                if int(symbols[0]) < params.n_symbols
                else pi
            )
            enters = [(v / v.sum()).astype(np.float32)]
            for s in range(n_spans - 1):
                v = enters[-1] @ totals[s]
                enters.append((v / v.sum()).astype(np.float32))
            exits: list = [None] * n_spans
            e = np.full(params.n_states, 1.0 / params.n_states, np.float32)
            for s in range(n_spans - 2, -1, -1):
                e = totals[s + 1] @ e
                e = (e / e.sum()).astype(np.float32)
                exits[s] = e
            # Sweep B: full posterior per span with the threaded messages.
            rec_path_parts: list = []
            rec_conf = 0.0  # exact per-record sum (manifest mode)
            for s in range(n_spans):
                lo = s * span
                piece = symbols[lo : lo + span]

                def span_unit(s=s, lo=lo, piece=piece):
                    conf, path = posterior_sharded(
                        params, piece, island_states, engine=engine,
                        enter_dir=None if s == 0 else enters[s],
                        exit_dir=exits[s], first=s == 0,
                        want_path=want_path, pad_to=span,
                        return_device=use_device_islands,
                        placed=span_placed[s],
                        prev_sym=(
                            0 if s == 0
                            else _prev_real_symbol(symbols, lo, params.n_symbols)
                        ),
                        prepared=span_prep[s],
                        breaker=session.breaker,
                    )
                    if use_device_islands:
                        # Fault-surfacing block (see one_record): poisoned
                        # outputs must fail inside the supervised unit.
                        # graftcheck: allow(hot-path-host-sync) -- fault-surfacing + phase-attribution block (comment above); the obs ledger counts it via its block_until_ready hook
                        jax.block_until_ready(path if path is not None else conf)
                    return conf, path

                with timer.phase("posterior", items=float(piece.size), unit="sym"):
                    conf, path = sup.run(
                        span_unit, what="posterior.span",
                        engine=f"fb.{_fb_eng}", items=float(piece.size),
                    )
                span_placed.pop(s, None)
                span_prep.pop(s, None)
                if use_device_islands:
                    if want_conf:
                        emit(conf_to_host(conf), None)
                    elif manifest is not None:
                        c = float(obs.note_fetch(np.asarray(jnp.sum(conf))))
                        rec_conf += c
                        conf_total += c
                    else:
                        accum_conf_device(conf)
                    if want_islands:
                        # int8 on device, like the host twin below: a
                        # multi-span record accumulates its whole path —
                        # 4x matters exactly at the long-record scale the
                        # span exists to bound (state ids are 0..K-1 < 128).
                        rec_path_parts.append(path.astype(jnp.int8))
                else:
                    if manifest is not None:
                        # graftcheck: allow(hot-path-host-sync) -- conf is host on this branch (posterior_sharded fetched it through obs.note_fetch); exact-f64 coercion only
                        c = float(np.asarray(conf).sum(dtype=np.float64))
                        rec_conf += c
                        conf_total += c
                        emit(None, path)
                    else:
                        emit(conf, path)
                    if want_islands:
                        # graftcheck: allow(hot-path-host-sync) -- `path` is host on this branch (its producer fetched through obs.note_fetch above); coercion only
                        rec_path_parts.append(np.asarray(path).astype(np.int8))
                if manifest is not None:
                    manifest.span_done(rec_idx, s)
            if want_islands:
                # Islands are called over the WHOLE record's MPM path so a
                # run crossing a span boundary is never clipped (device
                # engine: spans concatenate ON device, like decode's span
                # path, and only compact calls cross to the host).
                full_path = (
                    jnp.concatenate(rec_path_parts) if use_device_islands
                    else np.concatenate(rec_path_parts)
                )
                call_rec(rec_name, symbols, full_path)
            if manifest is not None:
                manifest.record_done(
                    rec_idx, rec_name, int(symbols.size),
                    calls=call_parts[-1] if want_islands else None,
                    conf_sum=rec_conf, n_spans=n_spans,
                )
        flush_small()
    finally:
        close_prefetch()
        if manifest is not None:
            manifest.close()
        if conf_w is not None:
            conf_w.close()
        if path_w is not None:
            path_w.close()
    if conf_dev_acc is not None:
        conf_total += float(conf_dev_acc)  # the one end-of-file scalar fetch
    mean_conf = conf_total / n_sym if n_sym else 0.0
    calls_all = None
    if want_islands:
        calls_all = IslandCalls.concatenate(call_parts)
        if n_records <= 1:
            # Single-record files keep the reference's bare 5-column format.
            calls_all = dataclasses.replace(calls_all, names=None)
        _write_calls(calls_all, islands_out)
    log.info("posterior phases:\n%s", timer.report())
    if metrics is not None:
        metrics.log(
            "posterior", n_symbols=n_sym, n_records=n_records,
            mean_island_confidence=mean_conf,
            **({"n_islands": len(calls_all)} if calls_all is not None else {}),
            **timer.as_dict(),
        )
    return PosteriorResult(
        n_symbols=n_sym, n_records=n_records, mean_island_confidence=mean_conf,
        calls=calls_all,
    )


@dataclass
class CompareResult:
    n_symbols: int
    n_records: int
    member_names: list
    baseline: str
    records: list  # [family.RecordComparison] in file order


def compare_file(
    test_path: str,
    members=None,
    *,
    out: Optional[Union[str, IO[str]]] = None,
    engine: str = "auto",
    baseline: Optional[str] = None,
    min_len: Optional[int] = None,
    threshold: Optional[float] = None,
    symbol_cache: Optional[str] = None,
    invalid_symbols: str = "skip",
    metrics: Optional[profiling.MetricsLogger] = None,
    timer: Optional[profiling.PhaseTimer] = None,
    sessions=None,
    stacked: bool = True,
) -> CompareResult:
    """Multi-model posterior comparison over a FASTA file (clean
    semantics, per record) — ``cpgisland compare``.

    Every family member is evaluated over the same record stream
    (order-2 members over the position-aligned pair recode) through the
    SAME shared record unit the posterior pipeline runs, so the per-member
    confidence tracks and island calls are bit-identical to independent
    ``posterior_file`` runs of each model; the comparison adds the
    scoring pass (record log-likelihood -> log-odds against ``baseline``)
    and the per-position winner track (family.compare_record).

    ``out`` (path or open file) writes the report: per record, one
    ``# model`` header line per member (loglik, log-odds, island count),
    followed by the winner track as reference-format island lines whose
    name column is ``<record>|<member>`` (bare ``<member>`` for
    single-record files, mirroring decode_file's name-column rule).

    ``members`` defaults to the 3-model cast (durbin8, two_state, null);
    ``sessions`` maps member names to serve Sessions (the daemon's
    per-model fault domains).  ``stacked`` (default) groups same-order
    reduced members into ONE stacked launch set per record
    (family.stacked — bit-identical results either way; False is the
    launch-level A/B arm, `cpgisland compare --no-stacked`).
    """
    from cpgisland_tpu import family

    if members is None:
        members = family.default_members()
    names = [m.name for m in members]
    kw = {} if threshold is None else {"threshold": threshold}
    # Validate the baseline name once, up front (not per record).
    b_idx = family.resolve_baseline(members, baseline)
    _check_invalid_symbols(invalid_symbols, compat=False)
    timer = timer if timer is not None else profiling.PhaseTimer()
    records: list = []
    n_sym = 0
    for rec_name, symbols in codec.iter_fasta_records_cached(
        test_path, symbol_cache, invalid=invalid_symbols
    ):
        n_sym += symbols.size
        with timer.phase("compare", items=float(symbols.size), unit="sym"):
            records.append(
                family.compare_record(
                    members, symbols, record=rec_name or ".",
                    engine=engine, baseline=members[b_idx].name,
                    min_len=min_len, sessions=sessions, stacked=stacked,
                    **kw,
                )
            )
    if out is not None:
        _write_compare(records, names, members[b_idx].name, out)
    log.info("compare phases:\n%s", timer.report())
    if metrics is not None:
        metrics.log(
            "compare", n_symbols=n_sym, n_records=len(records),
            members=names, **timer.as_dict(),
        )
    return CompareResult(
        n_symbols=n_sym, n_records=len(records), member_names=names,
        baseline=members[b_idx].name, records=records,
    )


def _write_compare(records, names, baseline: str, out) -> None:
    """The compare report writer (see compare_file's format contract)."""
    own = isinstance(out, str)
    f = open(out, "w") if own else out
    try:
        f.write(
            f"# cpgisland compare models={','.join(names)} "
            f"baseline={baseline}\n"
        )
        multi = len(records) > 1
        for rc in records:
            f.write(f"# record {rc.record} symbols {rc.n_symbols}\n")
            for m in rc.members:
                f.write(
                    f"# model {m.name} loglik {m.loglik:.6f} "
                    f"log_odds {m.log_odds:.6f} islands {len(m.calls)}\n"
                )
            wc = rc.winner_calls
            if multi and wc.names is not None:
                wc = dataclasses.replace(
                    wc,
                    names=np.array(
                        [f"{rc.record}|{n}" for n in wc.names], dtype=object
                    ),
                )
            f.write(wc.format_lines())
    finally:
        if own:
            f.close()


def _write_calls(calls: IslandCalls, islands_out: Union[str, IO[str]]) -> None:
    """Write island records (reference line format) to a path or open file —
    the ONE copy of the str-vs-IO ownership rule (decode + posterior)."""
    own = isinstance(islands_out, str)
    f = open(islands_out, "w") if own else islands_out
    try:
        f.write(calls.format_lines())
    finally:
        if own:
            f.close()


def _finish_decode(calls, n_symbols, n_chunks, islands_out) -> DecodeResult:
    if islands_out is not None:
        _write_calls(calls, islands_out)
    return DecodeResult(calls=calls, n_symbols=int(n_symbols), n_chunks=int(n_chunks))


def run(
    training_path: str,
    test_path: str,
    islands_out: str,
    model_out: str,
    convergence: float = 0.005,
    num_iters: int = 10,
    *,
    params: Optional[HmmParams] = None,
    backend: Union[EStepBackend, str] = "local",
    mode: str = "rescaled",
    compat: bool = True,
    checkpoint_dir: Optional[str] = None,
    min_len: Optional[int] = None,
    engine: str = "auto",
    island_states=None,
    symbol_cache: Optional[str] = None,
    fuse: Union[bool, str] = "auto",
    prefetch: int = 0,
) -> DecodeResult:
    """The reference's full main(): train, dump model, decode, write islands
    (CpGIslandFinder.java:346-357)."""
    fit = train_file(
        training_path,
        params=params,
        num_iters=num_iters,
        convergence=convergence,
        model_out=model_out,
        backend=backend,
        mode=mode,
        compat=compat,
        checkpoint_dir=checkpoint_dir,
        symbol_cache=symbol_cache,
        fuse=fuse,
    )
    return decode_file(
        test_path,
        fit.params,
        islands_out=islands_out,
        compat=compat,
        min_len=min_len,
        engine=engine,
        island_states=island_states,
        symbol_cache=symbol_cache,
        prefetch=prefetch,
    )
